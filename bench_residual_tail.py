"""Interleaved A/B: Pallas residual-tail kernel (BN-apply+ReLU+add in
one pass) vs XLA's own scheduling of the same tail after a real conv —
the round-5 probe VERDICT r4 #1(b) named (the 11 ms residual-add ledger
category + share of the 17.4 ms mask traffic).

Both sides run `conv1x1 -> tail` so the conv/tail fusion BOUNDARY
matches the real network (in one bare elementwise jit XLA trivially
fuses the whole tail and there is nothing to measure). Forward AND
train (value_and_grad) variants; methodology per BASELINE.md /
bench_conv_pallas.py: one process, in-jit scan with a structural
carry->weight dependency (LICM-proof), optimization_barrier after the
conv, device->host read closing every window, alternated min-of-k.

Run: python bench_residual_tail.py   (needs the TPU; run alone)
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.residual_tail_pallas import (
    _ref_formula, bn_relu_residual,
)

# (N, H, W, C) — the four residual-join shapes of batch-256 ResNet-50
SHAPES = [
    (256, 56, 56, 256),
    (256, 28, 28, 512),
    (256, 14, 14, 1024),
    (256, 7, 7, 2048),
]

REPS = 6
ITERS = 50


def _make_sides(c):
    def conv(x, w):
        return jnp.einsum("nhwc,cd->nhwd", x, w)

    def xla_side(x, res, w, mean, var, gamma, beta):
        y = jax.lax.optimization_barrier(conv(x, w))
        return _ref_formula(y, res, mean, var, gamma, beta, 1e-5)

    def pal_side(x, res, w, mean, var, gamma, beta):
        y = jax.lax.optimization_barrier(conv(x, w))
        return bn_relu_residual(y, res, mean, var, gamma, beta)

    return xla_side, pal_side


def _looped_fwd(fn):
    @jax.jit
    def run(x, res, w, args):
        def body(c, _):
            out = fn(x, res, w + c, *args)
            t = out.reshape(-1)[0].astype(jnp.float32)
            return (t * 1e-30).astype(w.dtype), None

        c, _ = jax.lax.scan(body, jnp.zeros((), w.dtype), None,
                            length=ITERS)
        return c.astype(jnp.float32)

    return run


def _looped_train(fn):
    @jax.jit
    def run(x, res, w, args):
        def loss(w_):
            out = fn(x, res, w_, *args)
            return jnp.sum(out.astype(jnp.float32) ** 2) * 1e-6

        def body(c, _):
            v, g = jax.value_and_grad(loss)(w + c)
            t = v + g.reshape(-1)[0].astype(jnp.float32)
            return (t * 1e-30).astype(w.dtype), None

        c, _ = jax.lax.scan(body, jnp.zeros((), w.dtype), None,
                            length=ITERS)
        return c.astype(jnp.float32)

    return run


def _time(run, *a):
    float(run(*a))   # compile + sync (device->host read — the axon
    #                  tunnel returns early from block_until_ready)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(run(*a))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    return best * 1e3


def main():
    rs = np.random.RandomState(0)
    results = []
    for n, h, wd, c in SHAPES:
        x = jax.device_put(jnp.asarray(
            rs.randn(n, h, wd, c) * 0.5, jnp.bfloat16))
        res = jax.device_put(jnp.asarray(
            rs.randn(n, h, wd, c) * 0.5, jnp.bfloat16))
        w = jax.device_put(jnp.asarray(
            rs.randn(c, c) * 0.05, jnp.bfloat16))
        args = tuple(jax.device_put(jnp.asarray(v, jnp.float32)) for v in
                     (rs.randn(c) * 0.1, rs.rand(c) + 0.5,
                      rs.rand(c) + 0.5, rs.randn(c) * 0.1))
        xla_side, pal_side = _make_sides(c)
        # numerics pin before timing
        a = np.asarray(jax.jit(xla_side)(x, res, w, *args),
                       np.float32)
        b = np.asarray(jax.jit(pal_side)(x, res, w, *args),
                       np.float32)
        err = float(np.abs(a - b).max())
        row = {"shape": [n, h, wd, c], "max_err": round(err, 5)}
        for nm, loop in (("fwd", _looped_fwd), ("train", _looped_train)):
            rx, rp = loop(xla_side), loop(pal_side)
            t_x = _time(rx, x, res, w, args)
            t_p = _time(rp, x, res, w, args)
            t_x = min(t_x, _time(rx, x, res, w, args))
            t_p = min(t_p, _time(rp, x, res, w, args))
            row[f"xla_{nm}_ms"] = round(t_x, 4)
            row[f"pallas_{nm}_ms"] = round(t_p, 4)
            row[f"{nm}_speedup"] = round(t_x / t_p, 3)
        results.append(row)
        print(json.dumps(row))
    for nm in ("fwd", "train"):
        tx = sum(r[f"xla_{nm}_ms"] for r in results)
        tp = sum(r[f"pallas_{nm}_ms"] for r in results)
        print(json.dumps({f"total_xla_{nm}_ms": round(tx, 3),
                          f"total_pallas_{nm}_ms": round(tp, 3),
                          f"overall_{nm}_speedup": round(tx / tp, 3)}))


if __name__ == "__main__":
    main()
