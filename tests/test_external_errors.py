"""External-errors backprop parity (reference:
MultiLayerNetwork#backpropGradient(epsilon, mgr) /
ComputationGraph#backpropGradient(INDArray...) — BackPropMLNTest's
external-errors cases: a caller-owned loss hands dL/dOutput to the
network and receives parameter gradients + input epsilon)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration, MergeVertex)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=0.1)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mse",
                               activation="identity"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestMLNExternalErrors:
    def test_matches_jax_grad_of_external_loss(self):
        # caller-owned loss L = sum(out * W); dL/dout = W, so the
        # returned gradients must equal jax.grad of the composition
        net = _net()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(5, 4)).astype(np.float32)
        W = rng.normal(size=(5, 3)).astype(np.float32)
        grads, eps = net.backpropGradient(x, W, train=False)

        fwd = net._get_forward(False, False)

        def ext_loss(pl, xx):
            return jnp.sum(fwd(pl, net.states_list, xx, None, None)
                           * W)

        want_p, want_x = jax.grad(ext_loss, argnums=(0, 1))(
            net.params_list, jnp.asarray(x))
        flat_a = jax.tree_util.tree_leaves(grads)
        flat_b = jax.tree_util.tree_leaves(want_p)
        assert len(flat_a) == len(flat_b) > 0
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(eps.jax),
                                   np.asarray(want_x), rtol=1e-5)

    def test_epsilon_shape_and_descent(self):
        # gradient-descending an EXTERNAL quadratic loss through
        # backpropGradient must reduce it (the custom-loop workflow)
        net = _net(seed=2)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(6, 4)).astype(np.float32)
        target = rng.normal(size=(6, 3)).astype(np.float32)

        def ext_loss_value():
            out = np.asarray(net.output(x).jax)
            return float(((out - target) ** 2).mean()), out

        l0, out = ext_loss_value()
        for _ in range(60):
            err = 2.0 * (out - target) / out.size
            grads, eps = net.backpropGradient(x, err, train=False)
            assert np.asarray(eps.jax).shape == x.shape
            net.params_list = jax.tree_util.tree_map(
                lambda p, g: p - 0.5 * g, net.params_list, grads)
            _, out = ext_loss_value()
        l1, _ = ext_loss_value()
        assert l1 < 0.2 * l0, (l0, l1)

    def test_shape_mismatch_raises(self):
        net = _net()
        x = np.zeros((2, 4), np.float32)
        with pytest.raises(ValueError, match="must match"):
            net.backpropGradient(x, np.zeros((2, 7), np.float32))

    def test_train_mode_runs(self):
        net = _net()
        x = np.zeros((3, 4), np.float32)
        grads, eps = net.backpropGradient(
            x, np.ones((3, 3), np.float32), train=True)
        assert np.asarray(eps.jax).shape == (3, 4)


class TestGraphExternalErrors:
    def test_two_input_graph_epsilons(self):
        conf = (ComputationGraphConfiguration.graphBuilder().seed(3)
                .addInputs("a", "b")
                .setInputTypes(InputType.feedForward(3),
                               InputType.feedForward(2))
                .addLayer("da", DenseLayer(n_out=6, activation="tanh"),
                          "a")
                .addLayer("db", DenseLayer(n_out=6, activation="tanh"),
                          "b")
                .addVertex("m", MergeVertex(), "da", "db")
                .addLayer("out", OutputLayer(n_out=2, loss="mse",
                                             activation="identity"),
                          "m")
                .setOutputs("out").build())
        g = ComputationGraph(conf)
        g.init()
        rng = np.random.default_rng(2)
        xa = rng.normal(size=(4, 3)).astype(np.float32)
        xb = rng.normal(size=(4, 2)).astype(np.float32)
        W = rng.normal(size=(4, 2)).astype(np.float32)
        grads, eps = g.backpropGradient([xa, xb], [W], train=False)
        assert set(eps) == {"a", "b"}
        assert np.asarray(eps["a"].jax).shape == xa.shape
        assert np.asarray(eps["b"].jax).shape == xb.shape

        # parity with jax.grad of the external composition
        def ext_loss(pm, inp):
            outs = g._forward_all(pm, g.states_map, inp, False, None,
                                  {})[0]
            return jnp.sum(outs["out"] * W)

        want_p, want_in = jax.grad(ext_loss, argnums=(0, 1))(
            g.params_map, {"a": jnp.asarray(xa), "b": jnp.asarray(xb)})
        for a, b in zip(jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(want_p)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(eps["a"].jax),
                                   np.asarray(want_in["a"]), rtol=1e-5)

    def test_error_count_and_shape_validation(self):
        conf = (ComputationGraphConfiguration.graphBuilder().seed(4)
                .addInputs("x")
                .setInputTypes(InputType.feedForward(3))
                .addLayer("out", OutputLayer(n_out=2, loss="mse",
                                             activation="identity"),
                          "x")
                .setOutputs("out").build())
        g = ComputationGraph(conf)
        g.init()
        x = np.zeros((2, 3), np.float32)
        with pytest.raises(ValueError, match="one external error"):
            g.backpropGradient([x], [np.zeros((2, 2)), np.zeros((2, 2))])
        with pytest.raises(ValueError, match="one input per"):
            g.backpropGradient([x, x], [np.zeros((2, 2), np.float32)])
        with pytest.raises(ValueError, match="expected"):
            g.backpropGradient([x], [np.zeros((2, 5), np.float32)])

    def test_train_mode_uses_dropout_and_rng_restores_on_error(self):
        from deeplearning4j_tpu.nn.conf import DropoutLayer
        conf = (ComputationGraphConfiguration.graphBuilder().seed(5)
                .addInputs("x")
                .setInputTypes(InputType.feedForward(4))
                .addLayer("d", DenseLayer(n_out=16, activation="tanh"),
                          "x")
                .addLayer("drop", DropoutLayer(rate=0.5), "d")
                .addLayer("out", OutputLayer(n_out=2, loss="mse",
                                             activation="identity"),
                          "drop")
                .setOutputs("out").build())
        g = ComputationGraph(conf)
        g.init()
        x = np.ones((8, 4), np.float32)
        e = np.ones((8, 2), np.float32)
        g1, _ = g.backpropGradient([x], [e], train=True)
        g2, _ = g.backpropGradient([x], [e], train=True)
        import jax as _jax
        diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
                 for a, b in zip(_jax.tree_util.tree_leaves(g1),
                                 _jax.tree_util.tree_leaves(g2))]
        assert max(diffs) > 0  # different dropout masks -> train mode real
        # a failed call must not advance the dropout stream
        key_before = g._rng_key
        with pytest.raises(ValueError):
            g.backpropGradient([x], [np.zeros((8, 9), np.float32)],
                               train=True)
        assert (np.asarray(jax.random.key_data(key_before))
                == np.asarray(jax.random.key_data(g._rng_key))).all()
