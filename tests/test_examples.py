"""Smoke-run every example (reference analog: dl4j-examples CI)."""

import importlib.util
import os
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _run(name):
    path = os.path.join(EXAMPLES, name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestExamples:
    def test_lenet_mnist(self):
        acc = _run("lenet_mnist.py").main(epochs=2)
        assert acc > 0.8  # synthetic stand-in is trivially separable

    def test_bert_finetune(self):
        acc = _run("bert_finetune.py").main(steps=40)
        assert acc > 0.7

    def test_bert_text_finetune(self):
        acc = _run("bert_text_finetune.py").main(epochs=6)
        assert acc >= 0.9

    def test_word2vec_text_cnn(self):
        p = _run("word2vec_text_cnn.py").main()
        assert p > 0.5

    def test_data_parallel(self):
        acc = _run("data_parallel_training.py").main()
        assert acc > 0.9

    def test_resnet50_training(self):
        score = _run("resnet50_training.py").main(steps=3, batch=8,
                                                  num_classes=5)
        import numpy as np
        assert np.isfinite(score)

    def test_tf_import_dynamic_rnn_example(self):
        pytest.importorskip("tensorflow")
        # non-default dims: the unit battery already imports the
        # default-shaped graph, so this run covers a different one
        # (main() owns the tolerance and raises on divergence)
        _run("tf_import_dynamic_rnn.py").main(batch=3, seq=8,
                                              d_in=4, hidden=6)

    def test_tf_import_bert_example(self):
        pytest.importorskip("tensorflow")
        pytest.importorskip("transformers")
        improved = _run("tf_import_bert.py").main(layers=1, hidden=32,
                                                  steps=10)
        assert improved

    def test_rl_async_a3c_example(self):
        ret = _run("rl_async_a3c.py").main(updates=800)
        assert ret > 0.9   # both async learners solve the 3x3 grid

    def test_timeseries_sequence_etl_example(self):
        acc = _run("timeseries_sequence_etl.py").main(epochs=20)
        assert acc > 0.9

    def test_vae_anomaly_example(self):
        flagged = _run("vae_anomaly.py").main(steps=150)
        assert flagged > 0.9  # far-out samples score below the threshold

    def test_transfer_learning_example(self):
        acc = _run("transfer_learning.py").main(epochs=8)
        assert acc > 0.9

    def test_wgan_example(self):
        d = _run("wgan.py").main(iters=120)
        assert d < 0.75
