"""Dropout family / weight noise / constraints tests (reference analogs:
TestDropout, TestWeightNoise, TestConstraints in deeplearning4j-nn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common import serde
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn.conf import (
    AlphaDropout, DenseLayer, DropConnect, Dropout, DropoutLayer,
    GaussianDropout, GaussianNoise, InputType, MaxNormConstraint,
    MinMaxNormConstraint, NeuralNetConfiguration, NonNegativeConstraint,
    OutputLayer, SpatialDropout, UnitNormConstraint, WeightNoise,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class TestDropoutFamily:
    def _x(self, shape=(4, 1000)):
        return jnp.ones(shape)

    def test_dropout_inverted_scaling(self):
        out = Dropout(rate=0.4).apply(self._x(), jax.random.key(0))
        kept = np.asarray(out) != 0
        # kept activations are scaled by 1/keep
        np.testing.assert_allclose(np.asarray(out)[kept], 1 / 0.6, rtol=1e-5)
        assert 0.5 < kept.mean() < 0.7    # ~keep probability

    def test_spatial_dropout_drops_whole_channels(self):
        x = jnp.ones((2, 8, 8, 64))
        out = np.asarray(SpatialDropout(rate=0.5).apply(x, jax.random.key(1)))
        # each (batch, channel) slice is all-zero or all-scaled
        per_chan = out.transpose(0, 3, 1, 2).reshape(2, 64, -1)
        for b in range(2):
            for c in range(64):
                sl = per_chan[b, c]
                assert np.all(sl == 0) or np.all(sl == 2.0)

    def test_gaussian_dropout_mean_preserving(self):
        out = GaussianDropout(rate=0.3).apply(self._x((8, 4000)),
                                              jax.random.key(2))
        assert abs(float(jnp.mean(out)) - 1.0) < 0.02

    def test_gaussian_noise_additive(self):
        out = GaussianNoise(stddev=0.5).apply(self._x((8, 4000)),
                                              jax.random.key(3))
        assert abs(float(jnp.mean(out)) - 1.0) < 0.02
        assert 0.45 < float(jnp.std(out)) < 0.55

    def test_alpha_dropout_preserves_selu_moments(self):
        # on SELU-distributed activations, mean/var stay ~unchanged
        x = jax.random.normal(jax.random.key(4), (64, 4000))
        out = AlphaDropout(rate=0.1).apply(x, jax.random.key(5))
        assert abs(float(jnp.mean(out))) < 0.05
        assert abs(float(jnp.var(out)) - 1.0) < 0.1

    def test_layer_level_idropout_config_and_training(self):
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(Adam(learning_rate=1e-2)).list()
                .layer(DenseLayer(n_out=16, activation="selu",
                                  dropout=AlphaDropout(rate=0.05)))
                .layer(DropoutLayer(rate=GaussianDropout(rate=0.1)))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(8)).build())
        # JSON round-trip with dropout objects
        j = conf.to_json()
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        assert MultiLayerConfiguration.from_json(j).to_json() == j
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        for _ in range(5):
            net.fit(x, y)
        assert np.isfinite(net.score())
        # inference is deterministic (no dropout)
        o1, o2 = np.asarray(net.output(x)), np.asarray(net.output(x))
        np.testing.assert_allclose(o1, o2)


class TestWeightNoise:
    def test_dropconnect_masks_weights_not_bias(self):
        dc = DropConnect(rate=0.5)
        p = {"W": jnp.ones((50, 50)), "b": jnp.ones((50,))}
        out = dc.apply(p, jax.random.key(0))
        w = np.asarray(out["W"])
        assert ((w == 0) | (w == 2.0)).all() and (w == 0).any()
        np.testing.assert_allclose(np.asarray(out["b"]), 1.0)  # untouched

    def test_weight_noise_additive(self):
        wn = WeightNoise(stddev=0.2, additive=True)
        p = {"W": jnp.zeros((100, 100))}
        out = np.asarray(wn.apply(p, jax.random.key(1))["W"])
        assert 0.15 < out.std() < 0.25 and abs(out.mean()) < 0.02

    def test_training_with_dropconnect_converges(self):
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Adam(learning_rate=1e-2)).list()
                .layer(DenseLayer(n_out=16, activation="relu",
                                  weight_noise=DropConnect(rate=0.2)))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(4)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        lab = (x[:, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[lab]
        for _ in range(60):
            net.fit(x, y)
        acc = (np.asarray(net.output(x)).argmax(-1) == lab).mean()
        assert acc > 0.8, acc


class TestConstraints:
    def test_maxnorm_unit_columns(self):
        w = jnp.full((10, 5), 3.0)  # column norm = 3*sqrt(10)
        out = np.asarray(MaxNormConstraint(max_norm=2.0)._constrain_one(w))
        norms = np.linalg.norm(out, axis=0)
        np.testing.assert_allclose(norms, 2.0, rtol=1e-5)
        # under-norm weights untouched
        w2 = jnp.full((4, 2), 0.1)
        out2 = np.asarray(MaxNormConstraint(max_norm=2.0)._constrain_one(w2))
        np.testing.assert_allclose(out2, 0.1, rtol=1e-5)

    def test_unitnorm_and_nonneg(self):
        w = jax.random.normal(jax.random.key(0), (6, 3))
        out = np.asarray(UnitNormConstraint()._constrain_one(w))
        np.testing.assert_allclose(np.linalg.norm(out, axis=0), 1.0,
                                   rtol=1e-5)
        out2 = np.asarray(NonNegativeConstraint()._constrain_one(w))
        assert (out2 >= 0).all()

    def test_minmax_norm(self):
        w = jnp.concatenate([jnp.full((9, 1), 3.0),    # norm 9
                             jnp.full((9, 1), 0.01)],  # norm .03
                            axis=1)
        out = np.asarray(MinMaxNormConstraint(
            min_norm=0.5, max_norm=2.0)._constrain_one(w))
        norms = np.linalg.norm(out, axis=0)
        np.testing.assert_allclose(norms, [2.0, 0.5], rtol=1e-4)

    def test_constraint_enforced_during_training(self):
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(learning_rate=0.5)).list()
                .layer(DenseLayer(n_out=8, activation="tanh",
                                  constraints=[MaxNormConstraint(max_norm=1.0)]))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(4)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(2)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        for _ in range(10):
            net.fit(x, y)
        w = np.asarray(net.params_list[0]["W"])
        assert np.linalg.norm(w, axis=0).max() <= 1.0 + 1e-5

    def test_serde_round_trip(self):
        for obj in [Dropout(0.3), AlphaDropout(0.2), GaussianDropout(0.1),
                    GaussianNoise(0.5), SpatialDropout(0.4),
                    DropConnect(0.5), WeightNoise(0.0, 0.1, False),
                    MaxNormConstraint(1.5), MinMaxNormConstraint(0.1, 2.0),
                    UnitNormConstraint(), NonNegativeConstraint()]:
            j = serde.to_json(obj)
            assert serde.to_json(serde.from_json(j)) == j, type(obj).__name__
