"""Dimensionality-reduction parity (reference: nd4j PCATest /
RandomProjectionTest)."""
import numpy as np
import pytest

from deeplearning4j_tpu.dimensionalityreduction import (
    PCA, RandomProjection, johnson_lindenstrauss_min_dim)


def _correlated(n=500, seed=0):
    """3-D data whose variance lives almost entirely on one axis."""
    rng = np.random.default_rng(seed)
    t = rng.normal(size=(n, 1))
    return np.hstack([3 * t + rng.normal(0, 0.05, (n, 1)),
                      -2 * t + rng.normal(0, 0.05, (n, 1)),
                      rng.normal(0, 0.05, (n, 1))]).astype(np.float32)


class TestPCA:
    def test_first_component_captures_variance(self):
        p = PCA(_correlated())
        ratios = p.eigenvalues / p.eigenvalues.sum()
        assert ratios[0] > 0.99
        # eigenvalues descending
        assert (np.diff(p.eigenvalues) <= 1e-6).all()

    def test_round_trip_reconstruction(self):
        x = _correlated()
        p = PCA(x)
        comps = p.convertToComponents(x, 1)
        assert comps.shape == (x.shape[0], 1)
        back = p.convertBackToFeatures(comps)
        # 1 component suffices: reconstruction is near-exact
        err = np.linalg.norm(back - x) / np.linalg.norm(x)
        assert err < 0.05
        # full basis reconstructs exactly
        full = p.convertBackToFeatures(p.convertToComponents(x))
        np.testing.assert_allclose(full, x, atol=1e-3)

    def test_reduced_basis_variance_fraction(self):
        x = _correlated()
        p = PCA(x)
        assert p.reducedBasis(0.95).shape == (3, 1)
        assert p.reducedBasis(1.0).shape == (3, 3)
        with pytest.raises(ValueError):
            p.reducedBasis(0.0)

    def test_estimate_variance(self):
        x = _correlated()
        p = PCA(x)
        assert p.estimateVariance(x, 1) > 0.99
        assert p.estimateVariance(x, 3) == pytest.approx(1.0, abs=1e-5)

    def test_static_pca_matches_numpy(self):
        x = _correlated(seed=2)
        reduced = PCA.pca(x, 2)
        assert reduced.shape == (x.shape[0], 2)
        # compare captured variance against numpy's own eig solution
        xc = x - x.mean(0)
        evals = np.linalg.eigvalsh(np.cov(xc.T))[::-1]
        np.testing.assert_allclose(reduced.var(0, ddof=1),
                                   evals[:2], rtol=1e-2)

    def test_factor_orthonormal(self):
        f = PCA.pca_factor(_correlated(), 3)
        np.testing.assert_allclose(f.T @ f, np.eye(3), atol=1e-4)

    def test_validation(self):
        with pytest.raises(ValueError, match="N>=2"):
            PCA(np.ones((1, 3), np.float32))


class TestRandomProjection:
    def test_jl_min_dim_formula(self):
        # classic check: 1000 points at eps=0.3 needs a few hundred dims
        k = johnson_lindenstrauss_min_dim(1000, 0.3)
        assert 600 < k < 800
        with pytest.raises(ValueError):
            johnson_lindenstrauss_min_dim(10, 1.5)

    def test_distances_approximately_preserved(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(60, 2000)).astype(np.float32)
        rp = RandomProjection(n_components=800, seed=1)
        y = rp.project(x)
        assert y.shape == (60, 800)
        d_in = np.linalg.norm(x[:20, None] - x[None, :20], axis=-1)
        d_out = np.linalg.norm(y[:20, None] - y[None, :20], axis=-1)
        iu = np.triu_indices(20, 1)
        ratio = d_out[iu] / d_in[iu]
        assert abs(ratio.mean() - 1.0) < 0.05
        assert ratio.std() < 0.1

    def test_same_space_across_calls(self):
        rng = np.random.default_rng(4)
        x = rng.normal(size=(10, 50)).astype(np.float32)
        rp = RandomProjection(n_components=8, seed=2)
        a = rp.project(x)
        b = rp.project(x)
        np.testing.assert_array_equal(a, b)

    def test_eps_mode_pins_space_across_batch_sizes(self):
        # the JL dim derives from the FIRST batch; a smaller query
        # batch must land in the SAME space, not a redrawn one
        rng = np.random.default_rng(6)
        train = rng.normal(size=(1000, 4000)).astype(np.float32)
        rp = RandomProjection(eps=0.9, seed=0)
        tr = rp.project(train)
        q = rp.project(train[:7])
        assert q.shape == (7, tr.shape[1])
        # same space: values agree up to matmul accumulation order
        np.testing.assert_allclose(q, tr[:7], rtol=1e-4, atol=1e-4)
        with pytest.raises(ValueError, match="does not match"):
            rp.project(np.zeros((3, 5), np.float32))

    def test_zero_components_rejected(self):
        x = _correlated()
        with pytest.raises(ValueError, match="n_components"):
            PCA(x).convertToComponents(x, 0)
        with pytest.raises(ValueError, match="n_components"):
            PCA.pca(x, 0)

    def test_eps_mode_and_validation(self):
        with pytest.raises(ValueError, match="exactly one"):
            RandomProjection()
        with pytest.raises(ValueError, match="exactly one"):
            RandomProjection(n_components=4, eps=0.5)
        rp = RandomProjection(eps=0.9, seed=0)
        x = np.random.default_rng(5).normal(size=(8, 200)).astype(np.float32)
        y = rp.project(x)
        assert y.shape[1] == johnson_lindenstrauss_min_dim(8, 0.9)
        # eps too tight for the input dim -> loud error
        with pytest.raises(ValueError, match="exceeds input"):
            RandomProjection(eps=0.1).project(x)
