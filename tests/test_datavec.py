"""ETL/DataVec tests (reference test model: datavec-api transform tests
+ RecordReaderDataSetIterator tests — SURVEY.md §4)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    AsyncDataSetIterator,
    ArrayDataSetIterator,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datavec import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    FileSplit,
    ImageRecordReader,
    LineRecordReader,
    NativeImageLoader,
    NumberedFileInputSplit,
    ParentPathLabelGenerator,
    Schema,
    TransformProcess,
)
from deeplearning4j_tpu.datavec.transform import Condition, ConditionOp
from deeplearning4j_tpu.datavec.schema import ColumnType


IRIS_CSV = """5.1,3.5,1.4,0.2,setosa
4.9,3.0,1.4,0.2,setosa
7.0,3.2,4.7,1.4,versicolor
6.3,3.3,6.0,2.5,virginica
5.8,2.7,5.1,1.9,virginica
"""


def iris_schema():
    return (Schema.Builder()
            .addColumnsDouble("sepal_l", "sepal_w", "petal_l", "petal_w")
            .addColumnCategorical("species",
                                  "setosa", "versicolor", "virginica")
            .build())


class TestSchema:
    def test_builder_and_queries(self):
        s = iris_schema()
        assert s.numColumns() == 5
        assert s.getColumnNames()[0] == "sepal_l"
        assert s.getIndexOfColumn("species") == 4
        assert s.getColumnMeta("species").categories == [
            "setosa", "versicolor", "virginica"]

    def test_json_roundtrip(self):
        s = iris_schema()
        assert Schema.fromJson(s.toJson()) == s

    def test_duplicate_rejected(self):
        with pytest.raises(ValueError):
            Schema.Builder().addColumnDouble("a").addColumnDouble("a").build()


class TestRecordReaders:
    def test_csv_from_string(self):
        rr = CSVRecordReader().initializeFromString(IRIS_CSV)
        assert rr.totalRecords() == 5
        first = rr.next()
        assert first == [5.1, 3.5, 1.4, 0.2, "setosa"]

    def test_csv_file_and_reset(self, tmp_path):
        p = tmp_path / "iris.csv"
        p.write_text(IRIS_CSV)
        rr = CSVRecordReader().initialize(str(p))
        n = sum(1 for _ in rr)
        rr.reset()
        assert rr.hasNext() and n == 5

    def test_csv_skip_lines(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("colA,colB\n1,2\n3,4\n")
        rr = CSVRecordReader(skip_num_lines=1).initialize(str(p))
        assert rr.allRecords() == [[1, 2], [3, 4]]

    def test_line_reader(self, tmp_path):
        p = tmp_path / "t.txt"
        p.write_text("hello\nworld\n")
        rr = LineRecordReader().initialize(str(p))
        assert rr.allRecords() == [["hello"], ["world"]]

    def test_collection_reader(self):
        rr = CollectionRecordReader([[1, 2], [3, 4]]).initialize()
        assert rr.next() == [1, 2]

    def test_file_split_extensions_and_shuffle(self, tmp_path):
        for name in ["a.csv", "b.csv", "c.txt"]:
            (tmp_path / name).write_text("1\n")
        fs = FileSplit(str(tmp_path), allowed_extensions=["csv"])
        locs = fs.locations()
        assert len(locs) == 2 and all(l.endswith(".csv") for l in locs)
        fs2 = FileSplit(str(tmp_path), seed=42)
        assert sorted(fs2.locations()) == sorted(FileSplit(str(tmp_path)).locations())

    def test_numbered_split(self):
        s = NumberedFileInputSplit("/d/f_%d.csv", 0, 2)
        assert s.locations() == ["/d/f_0.csv", "/d/f_1.csv", "/d/f_2.csv"]

    def test_csv_sequence_reader(self, tmp_path):
        for i in range(2):
            (tmp_path / f"seq_{i}.csv").write_text("1,2\n3,4\n5,6\n")
        rr = CSVSequenceRecordReader().initialize(
            NumberedFileInputSplit(str(tmp_path / "seq_%d.csv"), 0, 1))
        seq = rr.next()
        assert len(seq) == 3 and seq[0] == [1, 2]


class TestTransformProcess:
    def test_categorical_to_integer(self):
        tp = (TransformProcess.Builder(iris_schema())
              .categoricalToInteger("species")
              .build())
        rr = CSVRecordReader().initializeFromString(IRIS_CSV)
        out = tp.execute(rr.allRecords())
        assert [r[4] for r in out] == [0, 0, 1, 2, 2]
        assert tp.final_schema.getColumnMeta("species").type.name == "INTEGER"

    def test_one_hot(self):
        tp = (TransformProcess.Builder(iris_schema())
              .categoricalToOneHot("species")
              .build())
        out = tp.execute(CSVRecordReader()
                         .initializeFromString(IRIS_CSV).allRecords())
        assert tp.final_schema.numColumns() == 7
        assert out[0][4:] == [1, 0, 0]
        assert out[3][4:] == [0, 0, 1]

    def test_remove_rename_math(self):
        tp = (TransformProcess.Builder(iris_schema())
              .removeColumns("species")
              .renameColumn("sepal_l", "sl")
              .doubleMathOp("sl", "Multiply", 2.0)
              .doubleColumnsMathOp("area", "Multiply", "petal_l", "petal_w")
              .build())
        out = tp.execute(CSVRecordReader()
                         .initializeFromString(IRIS_CSV).allRecords())
        assert tp.final_schema.getColumnNames() == [
            "sl", "sepal_w", "petal_l", "petal_w", "area"]
        assert out[0][0] == pytest.approx(10.2)
        assert out[0][4] == pytest.approx(1.4 * 0.2)

    def test_filter_removes_matching(self):
        tp = (TransformProcess.Builder(iris_schema())
              .filter(ConditionOp.equal("species", "setosa"))
              .build())
        out = tp.execute(CSVRecordReader()
                         .initializeFromString(IRIS_CSV).allRecords())
        assert len(out) == 3

    def test_conditional_replace(self):
        tp = (TransformProcess.Builder(iris_schema())
              .conditionalReplaceValueTransform(
                  "sepal_l", 0.0, ConditionOp.lessThan("sepal_l", 5.5))
              .build())
        out = tp.execute(CSVRecordReader()
                         .initializeFromString(IRIS_CSV).allRecords())
        assert out[0][0] == 0.0 and out[2][0] == 7.0

    def test_normalize_and_pack(self):
        tp = (TransformProcess.Builder(iris_schema())
              .categoricalToInteger("species")
              .normalize("sepal_l", "Standardize")
              .build())
        arr = tp.executeToArray(CSVRecordReader()
                                .initializeFromString(IRIS_CSV).allRecords())
        assert arr.shape == (5, 5) and arr.dtype == np.float32
        assert abs(arr[:, 0].mean()) < 1e-6

    def test_pack_rejects_string(self):
        tp = TransformProcess.Builder(iris_schema()).build()
        with pytest.raises(TypeError):
            tp.executeToArray(CSVRecordReader()
                              .initializeFromString(IRIS_CSV).allRecords())

    def test_json_roundtrip_execution(self):
        tp = (TransformProcess.Builder(iris_schema())
              .categoricalToInteger("species")
              .doubleMathOp("sepal_w", "Add", 1.0)
              .filter(ConditionOp.greaterThan("petal_l", 5.0))
              .build())
        tp2 = TransformProcess.fromJson(tp.toJson())
        recs = CSVRecordReader().initializeFromString(IRIS_CSV).allRecords()
        assert tp.execute(recs) == tp2.execute(recs)

    def test_schema_error_surfaces(self):
        with pytest.raises(KeyError):
            (TransformProcess.Builder(iris_schema())
             .removeColumns("nope").build())
        with pytest.raises(KeyError):
            (TransformProcess.Builder(iris_schema())
             .removeAllColumnsExceptFor("sepal_l", "typo").build())

    def test_tojson_rejects_custom_steps(self):
        tp = (TransformProcess.Builder(iris_schema())
              .transform(lambda t: t).build())
        with pytest.raises(ValueError, match="custom"):
            tp.toJson()


class TestRecordReaderDataSetIterator:
    def test_classification(self):
        tp = (TransformProcess.Builder(iris_schema())
              .categoricalToInteger("species").build())
        recs = tp.execute(CSVRecordReader()
                          .initializeFromString(IRIS_CSV).allRecords())
        it = RecordReaderDataSetIterator(
            CollectionRecordReader(recs), batch_size=3,
            label_index=4, num_classes=3)
        ds = it.next()
        assert ds.features.shape == (3, 4)
        assert ds.labels.shape == (3, 3)
        assert float(np.asarray(ds.labels).sum()) == 3.0
        ds2 = it.next()
        assert ds2.features.shape == (2, 4)
        assert not it.hasNext()

    def test_regression(self):
        recs = [[1.0, 2.0, 10.0], [3.0, 4.0, 20.0]]
        it = RecordReaderDataSetIterator(
            CollectionRecordReader(recs), batch_size=2,
            label_index=2, regression=True)
        ds = it.next()
        assert ds.features.shape == (2, 2) and ds.labels.shape == (2, 1)
        assert float(np.asarray(ds.labels)[1, 0]) == 20.0

    def test_regression_with_num_classes_gives_one_channel(self, tmp_path):
        (tmp_path / "r_0.csv").write_text("1,2,0.5\n3,4,0.7\n")
        rr = CSVSequenceRecordReader().initialize(
            NumberedFileInputSplit(str(tmp_path / "r_%d.csv"), 0, 0))
        it = SequenceRecordReaderDataSetIterator(
            rr, batch_size=1, label_index=2, num_classes=3, regression=True)
        ds = it.next()
        assert ds.labels.shape == (1, 2, 1)
        assert float(np.asarray(ds.labels)[0, 1, 0]) == pytest.approx(0.7)

    def test_next_after_exhaustion_raises_stopiteration(self):
        it = RecordReaderDataSetIterator(
            CollectionRecordReader([[1.0, 2.0]]), batch_size=1,
            label_index=1, regression=True)
        it.next()
        with pytest.raises(StopIteration):
            it.next()

    def test_unknown_column_raises_in_builders(self):
        for build in [
            lambda b: b.renameColumn("nope", "x"),
            lambda b: b.categoricalToOneHot("nope"),
            lambda b: b.integerToCategorical("nope", ["a"]),
        ]:
            with pytest.raises(KeyError):
                build(TransformProcess.Builder(iris_schema())).build()

    def test_sequence_iterator_masks(self, tmp_path):
        (tmp_path / "s_0.csv").write_text("1,2,0\n3,4,1\n")
        (tmp_path / "s_1.csv").write_text("5,6,1\n")
        rr = CSVSequenceRecordReader().initialize(
            NumberedFileInputSplit(str(tmp_path / "s_%d.csv"), 0, 1))
        it = SequenceRecordReaderDataSetIterator(
            rr, batch_size=2, label_index=2, num_classes=2)
        ds = it.next()
        assert ds.features.shape == (2, 2, 2)
        assert ds.labels.shape == (2, 2, 2)
        assert np.asarray(ds.features_mask).tolist() == [[1, 1], [1, 0]]


class TestImagePipeline:
    def _make_tree(self, tmp_path):
        from PIL import Image
        rng = np.random.default_rng(0)
        for label in ["cat", "dog"]:
            d = tmp_path / label
            d.mkdir()
            for i in range(3):
                arr = rng.integers(0, 255, (12, 10, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"{i}.png")
        return tmp_path

    def test_loader_shapes(self, tmp_path):
        tree = self._make_tree(tmp_path)
        loader = NativeImageLoader(8, 8, 3)
        img = loader.asMatrix(str(tree / "cat" / "0.png"))
        assert img.shape == (8, 8, 3) and img.dtype == np.float32
        gray = NativeImageLoader(8, 8, 1).asMatrix(str(tree / "cat" / "0.png"))
        assert gray.shape == (8, 8, 1)

    def test_image_record_reader(self, tmp_path):
        tree = self._make_tree(tmp_path)
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(tree), allowed_extensions=["png"]))
        assert rr.getLabels() == ["cat", "dog"]
        x, y = rr.loadAll()
        assert x.shape == (6, 8, 8, 3)
        assert sorted(y.tolist()).count(0) == 3

    def test_image_to_dataset_iterator(self, tmp_path):
        tree = self._make_tree(tmp_path)
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(tree), allowed_extensions=["png"]))
        it = RecordReaderDataSetIterator(rr, batch_size=4, num_classes=2)
        ds = it.next()
        assert ds.features.shape == (4, 8, 8, 3)
        assert ds.labels.shape == (4, 2)

    def test_transforms(self, tmp_path):
        from deeplearning4j_tpu.datavec.image import (
            FlipImageTransform, PipelineImageTransform, ResizeImageTransform)
        rng = np.random.default_rng(0)
        img = rng.integers(0, 255, (12, 10, 3)).astype(np.float32)
        t = PipelineImageTransform(ResizeImageTransform(6, 6),
                                   FlipImageTransform(p=1.0))
        out = t(img, rng)
        assert out.shape == (6, 6, 3)


class TestAsyncIterator:
    def test_matches_sync(self):
        x = np.arange(40, dtype=np.float32).reshape(20, 2)
        y = np.zeros((20, 1), np.float32)
        sync = ArrayDataSetIterator(x, y, batch_size=6)
        async_it = AsyncDataSetIterator(
            ArrayDataSetIterator(x, y, batch_size=6), queue_size=2)
        a = [np.asarray(d.features) for d in sync]
        b = [np.asarray(d.features) for d in async_it]
        assert len(a) == len(b)
        for u, v in zip(a, b):
            np.testing.assert_array_equal(u, v)

    def test_reset_mid_epoch(self):
        x = np.arange(40, dtype=np.float32).reshape(20, 2)
        y = np.zeros((20, 1), np.float32)
        it = AsyncDataSetIterator(
            ArrayDataSetIterator(x, y, batch_size=5), queue_size=2)
        it.next()
        it.reset()
        batches = list(it)
        assert len(batches) == 4

    def test_has_next_after_exhaustion_returns_false(self):
        x = np.zeros((4, 2), np.float32)
        it = AsyncDataSetIterator(
            ArrayDataSetIterator(x, np.zeros((4, 1)), batch_size=2))
        while it.hasNext():
            it.next()
        assert not it.hasNext()
        assert not it.hasNext()  # must not block

    def test_reset_after_exhaustion(self):
        x = np.zeros((4, 2), np.float32)
        it = AsyncDataSetIterator(
            ArrayDataSetIterator(x, np.zeros((4, 1)), batch_size=2))
        assert len(list(it)) == 2
        it.reset()
        assert len(list(it)) == 2

    def test_error_propagates(self):
        class Bad(ArrayDataSetIterator):
            def next(self):
                raise RuntimeError("boom")

        it = AsyncDataSetIterator(
            Bad(np.zeros((4, 2)), np.zeros((4, 1)), batch_size=2))
        with pytest.raises(RuntimeError, match="boom"):
            while it.hasNext():
                it.next()


class TestAnalysis:
    """Reference: AnalyzeLocal.analyze/analyzeQuality."""

    def _schema(self):
        return (Schema.Builder()
                .addColumnDouble("x")
                .addColumnCategorical("cat", "a", "b")
                .addColumnString("s")
                .build())

    def test_analyze_statistics(self):
        from deeplearning4j_tpu.datavec import AnalyzeLocal
        recs = [[1.0, "a", "hi"], [2.0, "b", "worlds"], [3.0, "a", "x"],
                [-4.0, "a", "yo"]]
        da = AnalyzeLocal.analyze(self._schema(), recs)
        xa = da.getColumnAnalysis("x")
        assert xa.count == 4 and xa.min == -4.0 and xa.max == 3.0
        assert abs(xa.mean - 0.5) < 1e-9
        assert xa.count_negative == 1 and xa.count_positive == 3
        ca = da.getColumnAnalysis("cat")
        assert ca.unique_count == 2 and ca.category_counts["a"] == 3
        sa = da.getColumnAnalysis("s")
        assert sa.min_length == 1 and sa.max_length == 6
        assert "DataAnalysis" in str(da) and da.toJson()

    def test_quality(self):
        from deeplearning4j_tpu.datavec import AnalyzeLocal
        recs = [[1.0, "a", "hi"], [None, "zzz", ""], [float("nan"), "b", "y"]]
        dq = AnalyzeLocal.analyzeQuality(self._schema(), recs)
        assert dq.getColumnQuality("x").missing == 2
        assert dq.getColumnQuality("x").valid == 1
        assert dq.getColumnQuality("cat").invalid == 1
        assert dq.getColumnQuality("s").missing == 1


class TestJoinReduce:
    def test_inner_and_outer_joins(self):
        from deeplearning4j_tpu.datavec import Join, JoinType
        left_s = (Schema.Builder().addColumnInteger("id")
                  .addColumnString("name").build())
        right_s = (Schema.Builder().addColumnInteger("id")
                   .addColumnDouble("score").build())
        left = [[1, "a"], [2, "b"], [3, "c"]]
        right = [[1, 0.5], [1, 0.7], [4, 0.9]]
        inner = (Join.Builder(JoinType.INNER)
                 .setJoinColumns("id").setSchemas(left_s, right_s)
                 .build())
        out = inner.execute(left, right)
        assert out == [[1, "a", 0.5], [1, "a", 0.7]]
        assert inner.outSchema().getColumnNames() == ["id", "name", "score"]
        louter = (Join.Builder(JoinType.LEFT_OUTER)
                  .setJoinColumns("id").setSchemas(left_s, right_s)
                  .build()).execute(left, right)
        assert [1, "a", 0.5] in louter and [2, "b", None] in louter
        fouter = (Join.Builder(JoinType.FULL_OUTER)
                  .setJoinColumns("id").setSchemas(left_s, right_s)
                  .build()).execute(left, right)
        assert [4, None, 0.9] in fouter and len(fouter) == 5

    def test_reducer_group_by(self):
        from deeplearning4j_tpu.datavec import Reducer
        s = (Schema.Builder().addColumnCategorical("k", "p", "q")
             .addColumnDouble("v").addColumnInteger("n").build())
        recs = [["p", 1.0, 10], ["q", 2.0, 20], ["p", 3.0, 30]]
        red = (Reducer.Builder()
               .keyColumns("k").sumColumns("v").countColumns("n")
               .build())
        out = red.execute(s, recs)
        assert out == [["p", 4.0, 2], ["q", 2.0, 1]]
        names = red.outSchema(s).getColumnNames()
        assert names == ["k", "sum(v)", "count(n)"]

    def test_join_rejects_colliding_nonkey_columns_at_build(self):
        from deeplearning4j_tpu.datavec import Join, JoinType
        import pytest
        ls = (Schema.Builder().addColumnInteger("id")
              .addColumnString("name").build())
        rs = (Schema.Builder().addColumnInteger("id")
              .addColumnString("name").build())
        with pytest.raises(ValueError, match="both sides"):
            (Join.Builder(JoinType.INNER)
             .setJoinColumns("id").setSchemas(ls, rs).build())

    def test_outschema_does_not_alias_input_metas(self):
        from deeplearning4j_tpu.datavec import Join, JoinType
        ls = (Schema.Builder().addColumnInteger("id")
              .addColumnString("nm").build())
        rs = (Schema.Builder().addColumnInteger("id")
              .addColumnDouble("v").build())
        j = (Join.Builder(JoinType.INNER)
             .setJoinColumns("id").setSchemas(ls, rs).build())
        out = j.outSchema()
        out.getColumnMeta("id").name = "MUTATED"
        assert ls.getColumnNames()[0] == "id"


class TestAnalysisDirtyData:
    def test_analyze_survives_unparsable_numeric(self):
        from deeplearning4j_tpu.datavec import AnalyzeLocal
        s = Schema.Builder().addColumnDouble("x").build()
        da = AnalyzeLocal.analyze(s, [["abc"], [1.0], [3.0]])
        xa = da.getColumnAnalysis("x")
        assert xa.count == 2 and xa.mean == 2.0


class TestImageTransformBreadth:
    """Round-2 transform parity (reference: org/datavec/image/transform)
    — every transform runs on a synthetic image, preserves dtype/shape
    contract, and the deterministic ones are golden-checked."""

    def _img(self, h=24, w=32, c=3, seed=0):
        return np.random.default_rng(seed) \
            .integers(0, 255, (h, w, c)).astype(np.uint8)

    def test_rotate_scale_warp_shapes(self):
        from deeplearning4j_tpu.datavec.image import (
            RotateImageTransform, ScaleImageTransform, WarpImageTransform,
        )
        rng = np.random.default_rng(1)
        img = self._img()
        for t in (RotateImageTransform(30), ScaleImageTransform(0.2),
                  WarpImageTransform(3)):
            out = t(img, rng)
            assert out.shape == img.shape, type(t).__name__

    def test_color_conversions(self):
        from deeplearning4j_tpu.datavec.image import (
            ColorConversionTransform,
        )
        rng = np.random.default_rng(2)
        img = self._img()
        gray = ColorConversionTransform("gray")(img, rng)
        assert np.ptp(gray, axis=-1).max() == 0  # channels equal
        hsv = ColorConversionTransform("hsv")(img, rng)
        assert hsv.shape == img.shape
        # pure red -> hue 0, full saturation/value
        red = np.zeros((2, 2, 3), np.uint8)
        red[..., 0] = 255
        hred = ColorConversionTransform("hsv")(red, rng)
        assert hred[0, 0, 0] == 0 and hred[0, 0, 1] == 255 \
            and hred[0, 0, 2] == 255
        yuv = ColorConversionTransform("yuv")(img, rng)
        assert yuv.shape == img.shape
        with pytest.raises(ValueError):
            ColorConversionTransform("lab")

    def test_equalize_hist_flattens(self):
        from deeplearning4j_tpu.datavec.image import EqualizeHistTransform
        rng = np.random.default_rng(3)
        # low-contrast image: values clustered in [100, 120]
        img = rng.integers(100, 121, (32, 32, 1)).astype(np.uint8)
        out = EqualizeHistTransform()(img, rng)
        assert int(np.ptp(out)) > 200  # contrast stretched

    def test_random_crop_and_box(self):
        from deeplearning4j_tpu.datavec.image import (
            BoxImageTransform, RandomCropTransform,
        )
        rng = np.random.default_rng(4)
        img = self._img(24, 32)
        crop = RandomCropTransform(16, 16)(img, rng)
        assert crop.shape == (16, 16, 3)
        with pytest.raises(ValueError):
            RandomCropTransform(64, 64)(img, rng)
        boxed = BoxImageTransform(48, 48)(img, rng)
        assert boxed.shape == (48, 48, 3)
        # aspect preserved: 24x32 -> 36x48 content, vertical padding
        assert boxed[:5].sum() == 0 and boxed[-5:].sum() == 0

    def test_noise_and_pipeline(self):
        from deeplearning4j_tpu.datavec.image import (
            FlipImageTransform, NoiseImageTransform,
            PipelineImageTransform, RotateImageTransform,
        )
        rng = np.random.default_rng(5)
        img = self._img()
        out = PipelineImageTransform(
            RotateImageTransform(10), NoiseImageTransform(5.0),
            FlipImageTransform(1.0))(img, rng)
        assert out.shape == img.shape
        assert not np.array_equal(out, img)

    def test_decode_formats(self, tmp_path):
        """PIL decode breadth (reference: NativeImageLoader's format
        coverage via OpenCV): PNG, JPEG, BMP, GIF, TIFF round-trip
        through the loader at a fixed size."""
        from PIL import Image
        from deeplearning4j_tpu.datavec.image import NativeImageLoader
        src = self._img(20, 20)
        loader = NativeImageLoader(16, 16, 3)
        for ext in ("png", "jpeg", "bmp", "gif", "tiff"):
            p = str(tmp_path / f"img.{ext}")
            Image.fromarray(src).save(p)
            arr = loader.asMatrix(p)
            assert arr.shape == (16, 16, 3), ext
            assert arr.dtype == np.float32


class TestAsyncOverlap:
    def test_async_iterator_overlaps_etl_with_compute(self):
        """VERDICT r1 #9: measured proof that AsyncDataSetIterator
        overlaps host ETL with (simulated) device steps. Serial lower
        bound = n*(etl+step); overlapped ≈ n*max(etl, step) + etl.
        Asserts the measured wall time beats 80% of the serial bound —
        conservative enough for noisy CI hosts."""
        import time as _t
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        from deeplearning4j_tpu.datasets.record_reader_iterator import (
            AsyncDataSetIterator,
        )

        n_batches, etl_s, step_s = 12, 0.02, 0.02

        class SlowIterator(ArrayDataSetIterator):
            def next(self):
                _t.sleep(etl_s)         # simulated decode/augment cost
                return super().next()

        x = np.zeros((n_batches * 4, 8), np.float32)
        y = np.zeros((n_batches * 4, 2), np.float32)

        # serial: ETL then "device step", back to back
        it = SlowIterator(x, y, batch_size=4)
        t0 = _t.perf_counter()
        for _ in it:
            _t.sleep(step_s)
        serial = _t.perf_counter() - t0

        aiter = AsyncDataSetIterator(SlowIterator(x, y, batch_size=4),
                                     queue_size=4)
        t0 = _t.perf_counter()
        seen = 0
        for _ in aiter:
            _t.sleep(step_s)            # device busy; worker prefetches
            seen += 1
        overlapped = _t.perf_counter() - t0
        assert seen == n_batches
        assert overlapped < serial * 0.8, (overlapped, serial)

    def test_transforms_on_grayscale(self):
        """Review r2: PIL transforms must accept (H,W,1) arrays from
        NativeImageLoader(channels=1)."""
        from deeplearning4j_tpu.datavec.image import (
            BoxImageTransform, ColorConversionTransform,
            RotateImageTransform, ScaleImageTransform, WarpImageTransform,
        )
        rng = np.random.default_rng(6)
        img = np.random.default_rng(7).integers(
            0, 255, (20, 20, 1)).astype(np.uint8)
        for t in (RotateImageTransform(15), ScaleImageTransform(0.2),
                  WarpImageTransform(2)):
            out = t(img, rng)
            assert out.shape == img.shape, type(t).__name__
        assert BoxImageTransform(24, 24)(img, rng).shape == (24, 24, 1)
        assert ColorConversionTransform("gray")(img, rng).shape == img.shape
        with pytest.raises(ValueError, match="3 channels"):
            ColorConversionTransform("hsv")(img, rng)


class TestSequenceTransforms:
    """Sequence transform steps (reference: datavec transform/sequence/**
    — convertToSequence, OffsetSequenceTransform,
    SequenceMovingWindowReduce, SequenceDifferenceTransform, trim)."""

    def _schema(self):
        return (Schema.Builder()
                .addColumnDouble("key")
                .addColumnDouble("t")
                .addColumnDouble("x")
                .build())

    def test_convert_to_sequence_groups_and_sorts(self):
        recs = [[1, 2, 30.0], [0, 0, 1.0], [1, 0, 10.0], [0, 1, 2.0],
                [1, 1, 20.0]]
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t")
              .build())
        seqs = tp.execute(recs)
        assert len(seqs) == 2
        # first-seen key order: 1 then 0; each sorted by t
        assert [r[2] for r in seqs[0]] == [10.0, 20.0, 30.0]
        assert [r[2] for r in seqs[1]] == [1.0, 2.0]

    def test_offset_lag_trims_and_new_column(self):
        recs = [[0, t, float(10 * t)] for t in range(5)]
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t")
              .offsetSequence(["x"], 2, op="NewColumn")
              .build())
        (seq,) = tp.execute(recs)
        # 2 leading steps trimmed; new col holds x from t-2
        assert len(seq) == 3
        names = tp.final_schema.getColumnNames()
        xi, oi = names.index("x"), names.index("x_offset2")
        assert [r[xi] for r in seq] == [20.0, 30.0, 40.0]
        assert [r[oi] for r in seq] == [0.0, 10.0, 20.0]

    def test_moving_window_mean_and_difference(self):
        recs = [[0, t, v] for t, v in enumerate([1.0, 3.0, 5.0, 7.0])]
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t")
              .sequenceMovingWindowReduce("x", 2, "Mean")
              .sequenceDifference("x")
              .build())
        (seq,) = tp.execute(recs)
        names = tp.final_schema.getColumnNames()
        mi = names.index("x[mean,2]")
        xi = names.index("x")
        assert [r[mi] for r in seq] == [1.0, 2.0, 4.0, 6.0]
        assert [r[xi] for r in seq] == [0.0, 2.0, 2.0, 2.0]

    def test_trim_and_execute_sequences_direct(self):
        tp = (TransformProcess.Builder(self._schema())
              .trimSequence(1, from_start=True)
              .build())
        seqs = tp.executeSequences([[[0, 0, 1.0], [0, 1, 2.0]],
                                    [[1, 0, 3.0], [1, 1, 4.0],
                                     [1, 2, 5.0]]])
        assert [len(s) for s in seqs] == [1, 2]
        assert seqs[1][0][2] == 4.0

    def test_sequence_step_without_convert_raises(self):
        tp = (TransformProcess.Builder(self._schema())
              .sequenceDifference("x")
              .build())
        with pytest.raises(ValueError, match="convertToSequence"):
            tp.execute([[0, 0, 1.0]])
        with pytest.raises(ValueError, match="executeSequences"):
            (TransformProcess.Builder(self._schema())
             .convertToSequence("key", "t").build()
             .executeSequences([[[0, 0, 1.0]]]))

    def test_json_round_trip(self):
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t")
              .offsetSequence(["x"], 1)
              .sequenceMovingWindowReduce("x", 3, "Max")
              .trimSequence(1)
              .build())
        tp2 = TransformProcess.fromJson(tp.toJson())
        assert tp2.toJson() == tp.toJson()
        recs = [[0, t, float(t)] for t in range(4)]
        assert tp2.execute(recs) == tp.execute(recs)

    def test_sequence_step_before_convert_rejected(self):
        with pytest.raises(ValueError, match="BEFORE"):
            (TransformProcess.Builder(self._schema())
             .sequenceDifference("x")
             .convertToSequence("key", "t")
             .build()).execute([[0, 0, 1.0]])
        with pytest.raises(ValueError, match="lag"):
            TransformProcess.Builder(self._schema()) \
                .sequenceDifference("x", lag=0)

    def test_execute_to_array_rejects_grouping_chain(self):
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t").build())
        with pytest.raises(ValueError, match="execute\\(\\)"):
            tp.executeToArray([[0, 0, 1.0]])

    def test_offset_new_column_survives_full_trim(self):
        # a key with fewer rows than the offset: the sequence empties
        # but the declared new column must still exist (length 0)
        recs = [[0, 0, 1.0], [0, 1, 2.0], [1, 0, 9.0]]
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t")
              .offsetSequence(["x"], 2, op="NewColumn")
              .sequenceMovingWindowReduce("x_offset2", 2)
              .build())
        seqs = tp.execute(recs)
        assert [len(s) for s in seqs] == [0, 0]

    def test_nan_keys_rejected(self):
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t").build())
        with pytest.raises(ValueError, match="NaN"):
            tp.execute([[float("nan"), 0, 1.0]])

    def test_invalid_window_op_rejected_at_build(self):
        with pytest.raises(ValueError, match="Median"):
            TransformProcess.Builder(self._schema()) \
                .sequenceMovingWindowReduce("x", 3, "Median")

    def test_large_window_reduce_vectorized_path(self):
        # n >= w exercises the sliding_window_view path; check against
        # the naive definition
        rng = np.random.default_rng(0)
        vals = rng.normal(size=50)
        recs = [[0, t, float(v)] for t, v in enumerate(vals)]
        tp = (TransformProcess.Builder(self._schema())
              .convertToSequence("key", "t")
              .sequenceMovingWindowReduce("x", 7, "Max")
              .build())
        (seq,) = tp.execute(recs)
        names = tp.final_schema.getColumnNames()
        mi = names.index("x[max,7]")
        want = [vals[max(0, t - 6):t + 1].max() for t in range(50)]
        np.testing.assert_allclose([r[mi] for r in seq], want)


class TestTimeTransforms:
    """reference: transform/transform/time/{StringToTimeTransform,
    TimeMathOpTransform,DeriveColumnsFromTimeTransform}."""

    def _schema(self):
        return (Schema.Builder().addColumnString("ts")
                .addColumnDouble("v").build())

    def test_string_to_time_and_derive(self):
        tp = (TransformProcess.Builder(self._schema())
              .stringToTimeTransform("ts", "%Y-%m-%d %H:%M:%S")
              .deriveColumnsFromTime(
                  "ts", ("hour", "hourOfDay"), ("dow", "dayOfWeek"),
                  ("month", "monthOfYear"))
              .build())
        out = tp.execute([["2026-07-31 13:45:10", 1.0],
                          ["2026-01-01 00:00:00", 2.0]])
        # schema: ts is TIME, derived INTEGER columns appended
        fs = tp.getFinalSchema()
        assert fs.getColumnMeta("ts").type == ColumnType.TIME
        assert fs.getColumnMeta("hour").type == ColumnType.INTEGER
        names = fs.getColumnNames()
        r0 = dict(zip(names, out[0]))
        r1 = dict(zip(names, out[1]))
        # epoch check: 2026-01-01T00:00:00Z
        import datetime
        want = int(datetime.datetime(2026, 1, 1,
                                     tzinfo=datetime.timezone.utc)
                   .timestamp() * 1000)
        assert r1["ts"] == want
        assert r0["hour"] == 13 and r1["hour"] == 0
        assert r0["dow"] == 5          # 2026-07-31 is a Friday
        assert r0["month"] == 7 and r1["month"] == 1

    def test_time_math_op(self):
        tp = (TransformProcess.Builder(self._schema())
              .stringToTimeTransform("ts", "%Y-%m-%d %H:%M:%S")
              .timeMathOp("ts", "Subtract", 2, "HOURS")
              .deriveColumnsFromTime("ts", ("hour", "hourOfDay"))
              .build())
        out = tp.execute([["2026-07-31 01:00:00", 0.0]])
        row = dict(zip(tp.getFinalSchema().getColumnNames(), out[0]))
        assert row["hour"] == 23       # wrapped to the previous day

    def test_json_round_trip(self):
        tp = (TransformProcess.Builder(self._schema())
              .stringToTimeTransform("ts", "%Y-%m-%d %H:%M:%S")
              .timeMathOp("ts", "Add", 1, "DAYS")
              .deriveColumnsFromTime("ts", ("dom", "dayOfMonth"))
              .build())
        back = TransformProcess.fromJson(tp.toJson())
        a = tp.execute([["2026-02-28 12:00:00", 0.0]])
        b = back.execute([["2026-02-28 12:00:00", 0.0]])
        assert a == b
        row = dict(zip(back.getFinalSchema().getColumnNames(), b[0]))
        assert row["dom"] == 1         # Feb 28 + 1 day -> Mar 1 (2026)

    def test_validation(self):
        with pytest.raises(TypeError, match="not STRING"):
            (TransformProcess.Builder(self._schema())
             .stringToTimeTransform("v", "%Y").build())
        with pytest.raises(TypeError, match="not TIME"):
            (TransformProcess.Builder(self._schema())
             .timeMathOp("v", "Add", 1, "DAYS").build())
        with pytest.raises(ValueError, match="unknown unit"):
            (TransformProcess.Builder(self._schema())
             .stringToTimeTransform("ts", "%Y")
             .timeMathOp("ts", "Add", 1, "FORTNIGHTS").build())
        with pytest.raises(ValueError, match="unknown field"):
            (TransformProcess.Builder(self._schema())
             .stringToTimeTransform("ts", "%Y")
             .deriveColumnsFromTime("ts", ("x", "weekOfCentury"))
             .build())
        # derived name colliding with an existing column or repeated
        with pytest.raises(ValueError, match="collides"):
            (TransformProcess.Builder(self._schema())
             .stringToTimeTransform("ts", "%Y")
             .deriveColumnsFromTime("ts", ("v", "hourOfDay")).build())
        with pytest.raises(ValueError, match="collides"):
            (TransformProcess.Builder(self._schema())
             .stringToTimeTransform("ts", "%Y")
             .deriveColumnsFromTime("ts", ("h", "hourOfDay"),
                                    ("h", "dayOfWeek")).build())
        # foreign JSON cannot smuggle an invalid op past fromJson
        tp = (TransformProcess.Builder(self._schema())
              .stringToTimeTransform("ts", "%Y")
              .timeMathOp("ts", "Add", 1, "DAYS").build())
        bad = tp.toJson().replace('"Add"', '"Multiply"')
        with pytest.raises(ValueError, match="Add|Subtract"):
            TransformProcess.fromJson(bad)

    def test_string_to_time_honors_explicit_offset(self):
        # %z offsets must shift to UTC, not be reinterpreted as UTC
        schema = Schema.Builder().addColumnString("ts").build()
        tp = (TransformProcess.Builder(schema)
              .stringToTimeTransform("ts", "%Y-%m-%d %H:%M:%S %z")
              .deriveColumnsFromTime("ts", ("hour", "hourOfDay"))
              .build())
        out = tp.execute([["2026-07-31 13:00:00 +0200"],
                          ["2026-07-31 13:00:00 +0000"]])
        names = tp.getFinalSchema().getColumnNames()
        h0 = dict(zip(names, out[0]))["hour"]
        h1 = dict(zip(names, out[1]))["hour"]
        assert h0 == 11 and h1 == 13
