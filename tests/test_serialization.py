"""Checkpoint/resume tests (reference analog: ModelSerializerTest +
CheckpointListener tests; exact-resume incl. updater state is the
contract — SURVEY.md §2.24, §5)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.datasets.normalizers import (
    ImagePreProcessingScaler, NormalizerMinMaxScaler, NormalizerStandardize,
)
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import (
    CheckpointListener, CollectScoresListener, ScoreIterationListener,
)
from deeplearning4j_tpu.util import ModelSerializer


def small_net(seed=9):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).updater(Adam(learning_rate=0.01))
         .list()
         .layer(DenseLayer(n_out=8, activation="tanh"))
         .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
         .setInputType(InputType.feedForward(4))
         .build())).init()


def toy_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y_idx = (x.sum(1) > 0).astype(int)
    return x, np.eye(2, dtype=np.float32)[y_idx]


class TestModelSerializer:
    def test_save_restore_outputs_identical(self, tmp_path):
        model = small_net()
        x, y = toy_data()
        model.fit(DataSet(x, y), epochs=3)
        p = str(tmp_path / "model.zip")
        ModelSerializer.writeModel(model, p)
        restored = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_array_equal(model.output(x).toNumpy(),
                                      restored.output(x).toNumpy())
        assert restored.getIterationCount() == model.getIterationCount()

    def test_exact_resume_with_updater_state(self, tmp_path):
        """Train 3+3 with a save/load in the middle == train 6 straight.
        This is the reference's exact-resume guarantee (updaterState.bin)."""
        x, y = toy_data()
        ds = DataSet(x, y)

        m_straight = small_net()
        m_straight.fit(ds, epochs=6)

        m_half = small_net()
        m_half.fit(ds, epochs=3)
        p = str(tmp_path / "half.zip")
        ModelSerializer.writeModel(m_half, p, save_updater=True)
        m_resumed = ModelSerializer.restoreMultiLayerNetwork(p, load_updater=True)
        m_resumed.fit(ds, epochs=3)

        np.testing.assert_allclose(m_straight.params().toNumpy(),
                                   m_resumed.params().toNumpy(), atol=1e-6)

    def test_resume_without_updater_state_differs(self, tmp_path):
        """Dropping updater state must change trajectory (Adam moments)."""
        x, y = toy_data()
        ds = DataSet(x, y)
        m = small_net()
        m.fit(ds, epochs=3)
        p = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(m, p, save_updater=True)
        with_upd = ModelSerializer.restoreMultiLayerNetwork(p, load_updater=True)
        without_upd = ModelSerializer.restoreMultiLayerNetwork(p, load_updater=False)
        with_upd.fit(ds, epochs=2)
        without_upd.fit(ds, epochs=2)
        assert not np.allclose(with_upd.params().toNumpy(),
                               without_upd.params().toNumpy())

    def test_normalizer_roundtrip(self, tmp_path):
        model = small_net()
        x, y = toy_data()
        norm = NormalizerStandardize()
        norm.fit(DataSet(x, y))
        p = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(model, p, normalizer=norm)
        n2 = ModelSerializer.restoreNormalizer(p)
        np.testing.assert_allclose(norm.mean, n2.mean)
        np.testing.assert_allclose(norm.std, n2.std)


class TestNormalizers:
    def test_standardize(self):
        x = np.random.default_rng(0).normal(5, 3, (100, 4)).astype(np.float32)
        y = np.zeros((100, 1), np.float32)
        norm = NormalizerStandardize()
        norm.fit(DataSet(x, y))
        ds = norm.transform(DataSet(x, y))
        f = np.asarray(ds.features)
        np.testing.assert_allclose(f.mean(0), 0, atol=1e-4)
        np.testing.assert_allclose(f.std(0), 1, atol=1e-2)

    def test_standardize_streaming_matches_batch(self):
        x = np.random.default_rng(1).normal(2, 4, (128, 3)).astype(np.float32)
        y = np.zeros((128, 1), np.float32)
        batch = NormalizerStandardize()
        batch.fit(DataSet(x, y))
        stream = NormalizerStandardize()
        stream.fit(ArrayDataSetIterator(x, y, batch_size=32))
        np.testing.assert_allclose(batch.mean, stream.mean, rtol=1e-4)
        np.testing.assert_allclose(batch.std, stream.std, rtol=1e-3)

    def test_fit_label_and_revert_labels(self):
        # regression workflow: labels normalized for training, predictions
        # reverted to original units (reference: fitLabel/revertLabels)
        rng = np.random.default_rng(3)
        x = rng.normal(0, 1, (80, 4)).astype(np.float32)
        y = rng.normal(100, 25, (80, 2)).astype(np.float32)
        norm = NormalizerStandardize().fitLabel(True)
        norm.fit(DataSet(x, y))
        ds = norm.transform(DataSet(x, y))
        l = np.asarray(ds.labels)
        np.testing.assert_allclose(l.mean(0), 0, atol=1e-3)
        np.testing.assert_allclose(l.std(0), 1, atol=1e-2)
        back = np.asarray(norm.revertLabels(ds.labels))
        np.testing.assert_allclose(back, y, rtol=1e-4, atol=1e-3)
        # label stats survive serde
        state = norm.state_dict()
        n2 = NormalizerStandardize()
        n2.load_state_dict(state)
        np.testing.assert_allclose(np.asarray(n2.revertLabels(ds.labels)),
                                   back, rtol=1e-6)

    def test_vgg16_preprocessor(self):
        from deeplearning4j_tpu.datasets.normalizers import (
            VGG16ImagePreProcessor)
        x = np.full((2, 4, 4, 3), 150.0, np.float32)
        ds = VGG16ImagePreProcessor().transform(
            DataSet(x, np.zeros((2, 1), np.float32)))
        f = np.asarray(ds.features)
        np.testing.assert_allclose(
            f[0, 0, 0], 150.0 - VGG16ImagePreProcessor.MEANS, rtol=1e-6)

    def test_composite_preprocessor(self):
        from deeplearning4j_tpu.datasets.normalizers import (
            CompositeDataSetPreProcessor, ImagePreProcessingScaler)
        x = np.full((2, 2, 2, 1), 255.0, np.float32)
        comp = CompositeDataSetPreProcessor(
            ImagePreProcessingScaler(0.0, 1.0),
            ImagePreProcessingScaler(0.0, 1.0, max_pixel=1.0))
        f = np.asarray(comp.transform(
            DataSet(x, np.zeros((2, 1), np.float32))).features)
        np.testing.assert_allclose(f, 1.0)

    def test_composite_fits_children_on_transformed_data(self):
        # a stateful child must see the distribution the children
        # before it produce, not the raw input
        from deeplearning4j_tpu.datasets.normalizers import (
            CompositeDataSetPreProcessor, ImagePreProcessingScaler)
        rng = np.random.default_rng(4)
        x = rng.uniform(0, 255, (200, 3)).astype(np.float32)
        y = np.zeros((200, 1), np.float32)
        comp = CompositeDataSetPreProcessor(
            ImagePreProcessingScaler(0.0, 1.0), NormalizerStandardize())
        comp.fit(DataSet(x.copy(), y))
        out = np.asarray(comp.transform(DataSet(x.copy(), y)).features)
        np.testing.assert_allclose(out.mean(0), 0, atol=1e-3)
        np.testing.assert_allclose(out.std(0), 1, atol=1e-2)
        # one-shot iterator source is materialized once, not re-pulled
        comp2 = CompositeDataSetPreProcessor(
            NormalizerStandardize(), NormalizerStandardize())
        comp2.fit(iter([DataSet(x.copy(), y)]))

    def test_composite_serializer_round_trip(self, tmp_path):
        from deeplearning4j_tpu.datasets.normalizers import (
            CompositeDataSetPreProcessor, ImagePreProcessingScaler)
        rng = np.random.default_rng(5)
        x = rng.uniform(0, 255, (50, 4)).astype(np.float32)
        y = np.zeros((50, 1), np.float32)
        comp = CompositeDataSetPreProcessor(
            ImagePreProcessingScaler(0.0, 1.0), NormalizerStandardize())
        comp.fit(DataSet(x.copy(), y))
        p = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(small_net(), p, normalizer=comp)
        back = ModelSerializer.restoreNormalizer(p)
        a = np.asarray(comp.transform(DataSet(x.copy(), y)).features)
        b = np.asarray(back.transform(DataSet(x.copy(), y)).features)
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_load_state_clears_stale_label_stats(self):
        rng = np.random.default_rng(6)
        x = rng.normal(size=(30, 2)).astype(np.float32)
        y = rng.normal(100, 10, (30, 1)).astype(np.float32)
        n1 = NormalizerStandardize().fitLabel(True)
        n1.fit(DataSet(x, y))
        plain = NormalizerStandardize()
        plain.fit(DataSet(x, y))
        n1.load_state_dict(plain.state_dict())   # no label stats
        assert n1.label_mean is None
        ds = n1.transform(DataSet(x.copy(), y.copy()))
        np.testing.assert_array_equal(np.asarray(ds.labels), y)

    def test_minmax(self):
        x = np.random.default_rng(2).uniform(-5, 10, (50, 2)).astype(np.float32)
        y = np.zeros((50, 1), np.float32)
        norm = NormalizerMinMaxScaler()
        norm.fit(DataSet(x, y))
        f = np.asarray(norm.transform(DataSet(x, y)).features)
        assert f.min() >= -1e-6 and f.max() <= 1 + 1e-6

    def test_image_scaler(self):
        x = (np.arange(12).reshape(1, 12) * 20).astype(np.float32)
        ds = ImagePreProcessingScaler().transform(DataSet(x, np.zeros((1, 1))))
        assert float(np.asarray(ds.features).max()) <= 1.0


class TestListeners:
    def test_score_listener_fires(self):
        msgs = []
        model = small_net()
        model.setListeners(ScoreIterationListener(1, printer=msgs.append))
        x, y = toy_data(32)
        model.fit(DataSet(x, y), epochs=3)
        assert len(msgs) == 3

    def test_collect_scores(self):
        c = CollectScoresListener()
        model = small_net().setListeners(c)
        x, y = toy_data(32)
        model.fit(DataSet(x, y), epochs=5)
        assert len(c.scores) == 5
        assert c.scores[-1][1] <= c.scores[0][1] * 1.5  # roughly non-exploding

    def test_checkpoint_listener_keeps_last_k(self, tmp_path):
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                keep_last=2)
        model = small_net().setListeners(cl)
        x, y = toy_data(32)
        model.fit(DataSet(x, y), epochs=5)
        zips = list(tmp_path.glob("checkpoint_iter_*.zip"))
        assert len(zips) == 2
        restored = ModelSerializer.restoreMultiLayerNetwork(cl.lastCheckpoint())
        assert restored.numParams() == model.numParams()


class TestBf16Serialization:
    """npz can't natively round-trip ml_dtypes: bfloat16 loads back as
    void '|V2'. The serializer stores a uint16 view + dtype tag."""

    def test_bf16_model_roundtrip(self, tmp_path):
        import jax.numpy as jnp
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder()
             .seed(9).updater(Adam(learning_rate=0.01))
             .dataType("bfloat16")
             .list()
             .layer(DenseLayer(n_out=8, activation="tanh"))
             .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
             .setInputType(InputType.feedForward(4))
             .build())).init()
        x, y = toy_data()
        net.fit(DataSet(x, y), epochs=2)
        p = str(tmp_path / "model_bf16.zip")
        ModelSerializer.writeModel(net, p, save_updater=True)
        restored = ModelSerializer.restoreMultiLayerNetwork(p)
        for a, b in zip(net.params_list, restored.params_list):
            for k in (a or {}):
                assert b[k].dtype == jnp.bfloat16
                np.testing.assert_array_equal(
                    np.asarray(a[k], np.float32), np.asarray(b[k], np.float32))
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      restored.output(x).toNumpy())
