"""Per-mapper TF micro-graph battery (reference model:
TFGraphTestAllSameDiff — every registered mapper DRIVEN by at least one
frozen-graph golden compared against TF's own execution; SURVEY.md §4).

Exists to close the executional mapper gate
(test_zzz_mapper_execution_gate.py). Graphs are built with tf.raw_ops
so the exact node type lands in the GraphDef; every case asserts the
target op is PRESENT in the frozen graph (a battery entry that tests
the wrong op is vacuous — this check makes that loud).
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from test_tf_import import _freeze  # noqa: E402  (shared freeze helper)

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TFGraphMapper,
)

RNG = np.random.default_rng(11)
_F34 = RNG.normal(size=(3, 4)).astype(np.float32)
_P34 = (np.abs(RNG.normal(size=(3, 4))) + 0.2).astype(np.float32)
_U34 = RNG.uniform(-0.9, 0.9, (3, 4)).astype(np.float32)
_F44 = RNG.normal(size=(4, 4)).astype(np.float32)
_B234 = RNG.normal(size=(2, 3, 4)).astype(np.float32)
_B245 = RNG.normal(size=(2, 4, 5)).astype(np.float32)
_I34 = RNG.integers(0, 7, (3, 4)).astype(np.int32)
_J34 = RNG.integers(1, 7, (3, 4)).astype(np.int32)


def _graph_ops(gd):
    ops = {n.op for n in gd.node}
    for f in gd.library.function:
        ops |= {n.op for n in f.node_def}
    return ops


def _run_raw(fn, feeds_np, must_contain, rtol=1e-4, atol=1e-5):
    specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype))
             for v in feeds_np]
    gd, in_names, out_names, frozen = _freeze(fn, *specs)
    ops = _graph_ops(gd)
    for m in must_contain:
        assert m in ops, f"battery bug: {m} not in frozen graph {sorted(ops)}"
    ref = frozen(*[tf.constant(v) for v in feeds_np])
    ref = [np.asarray(r) for r in (ref if isinstance(ref, (list, tuple))
                                   else [ref])]
    sd = TFGraphMapper.importGraph(gd)
    outs = sd.output(dict(zip(in_names, feeds_np)), out_names)
    got = [np.asarray(outs[n]) for n in out_names]
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=rtol, atol=atol)


#: op -> (fn, feeds). The op name doubles as the must_contain target.
BATTERY = {
    "Add": (lambda a, b: tf.raw_ops.Add(x=a, y=b), [_F34, _P34]),
    "Any": (lambda a: tf.cast(
        tf.raw_ops.Any(input=a > 0, axis=[1], keep_dims=False),
        tf.float32), [_F34]),
    "Acosh": (lambda a: tf.math.acosh(1.5 + tf.abs(a)), [_F34]),
    "Asinh": (lambda a: tf.math.asinh(a), [_F34]),
    "Atanh": (lambda a: tf.math.atanh(a), [_U34]),
    "BatchMatMul": (lambda a, b: tf.raw_ops.BatchMatMul(x=a, y=b),
                    [_B234, _B245]),
    "BatchMatMulV3": (lambda a, b: tf.raw_ops.BatchMatMulV3(
        x=a, y=b, Tout=tf.float32), [_B234, _B245]),
    "Betainc": (lambda a, b, x: tf.math.betainc(a, b, x),
                [_P34, _P34.T.copy().T, RNG.uniform(
                    0.05, 0.95, (3, 4)).astype(np.float32)]),
    "Bincount": (lambda arr, w: tf.raw_ops.Bincount(
        arr=arr, size=tf.constant(8, tf.int32), weights=w),
        [_I34, _P34]),
    "BitwiseOr": (lambda a, b: tf.bitwise.bitwise_or(a, b),
                  [_I34, _J34]),
    "BitwiseXor": (lambda a, b: tf.bitwise.bitwise_xor(a, b),
                   [_I34, _J34]),
    "Bucketize": (lambda a: tf.cast(tf.raw_ops.Bucketize(
        input=a, boundaries=[-0.5, 0.0, 0.5]), tf.float32), [_F34]),
    "Cholesky": (lambda a: tf.linalg.cholesky(
        tf.matmul(a, a, transpose_b=True) + 4.0 * tf.eye(4)), [_F44]),
    "ClipByValue": (lambda a: tf.raw_ops.ClipByValue(
        t=a, clip_value_min=tf.constant(-0.5),
        clip_value_max=tf.constant(0.5)), [_F34]),
    "Cross": (lambda a, b: tf.linalg.cross(a, b),
              [RNG.normal(size=(5, 3)).astype(np.float32),
               RNG.normal(size=(5, 3)).astype(np.float32)]),
    "Div": (lambda a, b: tf.raw_ops.Div(x=a, y=b), [_F34, _P34]),
    "Equal": (lambda a: tf.cast(tf.raw_ops.Equal(
        x=tf.floor(a * 2.0), y=tf.constant(0.0)), tf.float32), [_F34]),
    "NotEqual": (lambda a: tf.cast(tf.raw_ops.NotEqual(
        x=tf.floor(a * 2.0), y=tf.constant(0.0)), tf.float32), [_F34]),
    "GreaterEqual": (lambda a, b: tf.cast(
        tf.raw_ops.GreaterEqual(x=a, y=b), tf.float32), [_F34, _U34]),
    "Erfinv": (lambda a: tf.math.erfinv(a), [_U34]),
    "Expm1": (lambda a: tf.math.expm1(a), [_F34]),
    "Log1p": (lambda a: tf.math.log1p(a), [_P34]),
    "Rint": (lambda a: tf.math.rint(a * 3.0), [_F34]),
    "FusedBatchNorm": (lambda x: tf.raw_ops.FusedBatchNorm(
        x=x, scale=tf.constant([0.9, 1.1, 1.3], tf.float32),
        offset=tf.constant([0.1, -0.1, 0.2], tf.float32),
        mean=tf.constant([0.05, -0.02, 0.1], tf.float32),
        variance=tf.constant([0.9, 1.2, 0.8], tf.float32),
        is_training=False)[0],
        [RNG.normal(size=(2, 5, 5, 3)).astype(np.float32)]),
    "FusedBatchNormV2": (lambda x: tf.raw_ops.FusedBatchNormV2(
        x=x, scale=tf.constant([0.9, 1.1, 1.3], tf.float32),
        offset=tf.constant([0.1, -0.1, 0.2], tf.float32),
        mean=tf.constant([0.05, -0.02, 0.1], tf.float32),
        variance=tf.constant([0.9, 1.2, 0.8], tf.float32),
        is_training=False)[0],
        [RNG.normal(size=(2, 5, 5, 3)).astype(np.float32)]),
    "Gather": (lambda a: tf.raw_ops.Gather(
        params=a, indices=tf.constant([2, 0, 1], tf.int32)), [_F34]),
    "InTopK": (lambda p: tf.cast(tf.raw_ops.InTopK(
        predictions=p, targets=tf.constant([1, 3, 0], tf.int32), k=2),
        tf.float32), [_F34]),
    "InvertPermutation": (lambda a: tf.cast(
        tf.raw_ops.InvertPermutation(
            x=tf.constant([2, 0, 3, 1], tf.int32)), tf.float32)
        + a * 0.0, [_F34[0].copy()]),
    "IsFinite": (lambda a: tf.cast(tf.math.is_finite(a), tf.float32),
                 [np.asarray([[1.0, np.inf, np.nan, -np.inf]],
                             np.float32)]),
    "IsInf": (lambda a: tf.cast(tf.math.is_inf(a), tf.float32),
              [np.asarray([[1.0, np.inf, np.nan, -np.inf]],
                          np.float32)]),
    "IsNan": (lambda a: tf.cast(tf.math.is_nan(a), tf.float32),
              [np.asarray([[1.0, np.inf, np.nan, -np.inf]],
                          np.float32)]),
    "L2Loss": (lambda a: tf.raw_ops.L2Loss(t=a), [_F34]),
    "LinSpace": (lambda a: tf.raw_ops.LinSpace(
        start=tf.constant(0.5), stop=tf.constant(2.5),
        num=tf.constant(5)) + a * 0.0,
        [np.zeros(5, np.float32)]),
    "MatrixDeterminant": (lambda a: tf.linalg.det(
        tf.matmul(a, a, transpose_b=True) + 3.0 * tf.eye(4)), [_F44]),
    "MatrixDiag": (lambda a: tf.raw_ops.MatrixDiag(diagonal=a), [_F34]),
    "MatrixDiagV2": (lambda a: tf.raw_ops.MatrixDiagV2(
        diagonal=a, k=0, num_rows=-1, num_cols=-1,
        padding_value=tf.constant(0.0)), [_F34]),
    "MatrixDiagPart": (lambda a: tf.raw_ops.MatrixDiagPart(input=a),
                       [_B234]),
    "MatrixDiagPartV2": (lambda a: tf.raw_ops.MatrixDiagPartV2(
        input=a, k=0, padding_value=tf.constant(0.0)), [_B234]),
    "MatrixSetDiag": (lambda a, d: tf.raw_ops.MatrixSetDiag(
        input=a, diagonal=d), [_B234, _F34[:2, :3].copy()]),
    "MatrixSetDiagV2": (lambda a, d: tf.raw_ops.MatrixSetDiagV2(
        input=a, diagonal=d, k=tf.constant(0, tf.int32)),
        [_B234, _F34[:2, :3].copy()]),
    "Mod": (lambda a, b: tf.raw_ops.Mod(x=a, y=b), [_F34 * 5, _P34]),
    "Polygamma": (lambda x: tf.math.polygamma(
        tf.ones_like(x), x), [_P34 * 3]),
    "Igammac": (lambda a, x: tf.math.igammac(a, x), [_P34, _P34 * 2]),
    "Reciprocal": (lambda a: tf.raw_ops.Reciprocal(x=a), [_P34]),
    "RightShift": (lambda a, b: tf.bitwise.right_shift(
        a, tf.ones_like(b)), [_I34, _J34]),
    "SegmentMax": (lambda a: tf.raw_ops.SegmentMax(
        data=a, segment_ids=tf.constant([0, 0, 1], tf.int32)), [_F34]),
    "SegmentMean": (lambda a: tf.raw_ops.SegmentMean(
        data=a, segment_ids=tf.constant([0, 0, 1], tf.int32)), [_F34]),
    "SegmentMin": (lambda a: tf.raw_ops.SegmentMin(
        data=a, segment_ids=tf.constant([0, 1, 1], tf.int32)), [_F34]),
    "SegmentProd": (lambda a: tf.raw_ops.SegmentProd(
        data=a, segment_ids=tf.constant([0, 1, 1], tf.int32)), [_F34]),
    "Select": (lambda a, b: tf.raw_ops.Select(
        condition=a > 0, x=a, y=b), [_F34, _U34]),
    "Snapshot": (lambda a: tf.raw_ops.Snapshot(input=a) * 2.0, [_F34]),
    "TruncateDiv": (lambda a, b: tf.raw_ops.TruncateDiv(x=a, y=b),
                    [_F34 * 5, _P34]),
    "TruncateMod": (lambda a, b: tf.raw_ops.TruncateMod(x=a, y=b),
                    [_F34 * 5, _P34]),
    "UnsortedSegmentMin": (lambda a: tf.raw_ops.UnsortedSegmentMin(
        data=a, segment_ids=tf.constant([1, 0, 1], tf.int32),
        num_segments=tf.constant(2, tf.int32)), [_F34]),
    "UnsortedSegmentProd": (lambda a: tf.raw_ops.UnsortedSegmentProd(
        data=a, segment_ids=tf.constant([1, 0, 1], tf.int32),
        num_segments=tf.constant(2, tf.int32)), [_F34]),
    "UnsortedSegmentSum": (lambda a: tf.raw_ops.UnsortedSegmentSum(
        data=a, segment_ids=tf.constant([1, 0, 1], tf.int32),
        num_segments=tf.constant(2, tf.int32)), [_F34]),
    "Xlog1py": (lambda a, b: tf.math.xlog1py(a, b), [_F34, _P34]),
    "TensorListGather": (lambda a: tf.raw_ops.TensorListGather(
        input_handle=tf.raw_ops.TensorListFromTensor(
            tensor=a, element_shape=tf.constant([4], tf.int32)),
        indices=tf.constant([2, 0], tf.int32),
        element_shape=tf.constant([4], tf.int32),
        element_dtype=tf.float32), [_F34]),
    "TensorListLength": (lambda a: tf.cast(tf.raw_ops.TensorListLength(
        input_handle=tf.raw_ops.TensorListFromTensor(
            tensor=a, element_shape=tf.constant([4], tf.int32))),
        tf.float32) + a * 0.0, [_F34]),
}


class TestTFMapperBattery:
    @pytest.mark.parametrize("name", sorted(BATTERY))
    def test_op(self, name):
        fn, feeds = BATTERY[name]
        _run_raw(fn, feeds, [name])


# ------------------------------------------------ functional control flow
def _conc(fn, *specs):
    return tf.function(fn).get_concrete_function(*specs)


_SPEC34 = tf.TensorSpec([3, 4], tf.float32)


class TestFunctionalControlFlowOps:
    """Plain (potentially-stateful) If/Case/PartitionedCall variants —
    the suite's other control-flow goldens only emit the Stateless*
    forms (TF2 auto-selects them for pure branches)."""

    def test_if_op(self):
        then_b = _conc(lambda t: t * 2.0, _SPEC34)
        else_b = _conc(lambda t: t - 1.0, _SPEC34)

        def f(x):
            return tf.raw_ops.If(
                cond=tf.reduce_sum(x) > 0.0, input=[x],
                Tout=[tf.float32], then_branch=then_b,
                else_branch=else_b)[0]

        _run_raw(f, [_F34], ["If"])
        _run_raw(f, [-np.abs(_F34)], ["If"])

    def test_case_op(self):
        branches = [_conc(lambda t: t * 2.0, _SPEC34),
                    _conc(lambda t: t + 10.0, _SPEC34),
                    _conc(lambda t: -t, _SPEC34)]

        def f(x):
            idx = tf.cast(tf.math.floormod(
                tf.cast(tf.reduce_sum(x) * 100.0, tf.int32), 3),
                tf.int32)
            return tf.raw_ops.Case(branch_index=idx, input=[x],
                                   Tout=[tf.float32], branches=branches)[0]

        _run_raw(f, [_F34], ["Case"])

    def test_stateless_case_op(self):
        branches = [_conc(lambda t: t * 3.0, _SPEC34),
                    _conc(lambda t: t + 5.0, _SPEC34)]

        def f(x):
            idx = tf.cast(tf.math.floormod(
                tf.cast(tf.reduce_sum(x) * 100.0, tf.int32), 2),
                tf.int32)
            return tf.raw_ops.StatelessCase(
                branch_index=idx, input=[x], Tout=[tf.float32],
                branches=branches)[0]

        _run_raw(f, [_F34], ["StatelessCase"])

    def test_partitioned_call_ops(self):
        # tf.function tracing INLINES PartitionedCall bodies during
        # freezing, so build the node in a v1 graph where raw ops land
        # verbatim (the form real SavedModel GraphDefs carry).
        tf1 = tf.compat.v1
        body = _conc(lambda t: tf.nn.relu(t) + 0.5, _SPEC34)
        for raw, opname in (
                (tf.raw_ops.PartitionedCall, "PartitionedCall"),
                (tf.raw_ops.StatefulPartitionedCall,
                 "StatefulPartitionedCall")):
            g = tf.Graph()
            with g.as_default():
                ph = tf1.placeholder(tf.float32, (3, 4), name="x")
                body.add_to_graph(g)
                out = tf.identity(
                    raw(args=[ph], Tout=[tf.float32], f=body)[0],
                    name="out")
                with tf1.Session(graph=g) as sess:
                    ref = sess.run(out, {"x:0": _F34})
                    frozen = tf1.graph_util.convert_variables_to_constants(
                        sess, g.as_graph_def(), ["out"])
            assert opname in _graph_ops(frozen), opname
            sd = TFGraphMapper.importGraph(frozen)
            got = np.asarray(sd.output({"x": _F34}, ["out"])["out"])
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


# --------------------------------------------------- TF1 TensorArray v3
class TestTensorArrayV3Battery:
    """TensorArray*V3 ops only exist in v1 control flow (TF2 emits
    TensorList*); built under disable_control_flow_v2 in a v1 Session
    graph, matching ancient frozen graphs in the wild."""

    def _frozen_v1(self, build, out_names, feeds):
        tf1 = tf.compat.v1
        tf1.disable_control_flow_v2()
        try:
            g = tf.Graph()
            with g.as_default():
                refs = build(tf1)
                with tf1.Session(graph=g) as sess:
                    sess.run(tf1.global_variables_initializer())
                    ref = sess.run(refs, feeds)
                    frozen = tf1.graph_util.convert_variables_to_constants(
                        sess, g.as_graph_def(), out_names)
        finally:
            tf1.enable_control_flow_v2()
        return frozen, ref

    def test_write_read_size_stack_in_loop(self):
        x = RNG.normal(size=(4, 2, 3)).astype(np.float32)

        def build(tf1):
            ph = tf1.placeholder(tf.float32, (4, 2, 3), name="x")
            in_ta = tf.TensorArray(tf.float32, size=4,
                                   element_shape=(2, 3)).unstack(ph)
            out_ta = tf.TensorArray(tf.float32, size=4,
                                    element_shape=(2, 3))

            def body(t, acc, ta):
                xt = in_ta.read(t)
                acc2 = acc + xt
                return t + 1, acc2, ta.write(t, acc2)

            _, acc, out_ta = tf1.while_loop(
                lambda t, acc, ta: t < 4, body,
                [0, tf.zeros((2, 3)), out_ta])
            out = tf.identity(tf.transpose(out_ta.stack(), [1, 0, 2]),
                              name="cumsum")
            # static-size TAs short-circuit .size() to a python const —
            # emit the raw node so the mapper is actually driven
            size = tf.identity(
                tf.cast(tf.raw_ops.TensorArraySizeV3(
                    handle=out_ta.handle, flow_in=out_ta.flow),
                    tf.float32), name="ta_size")
            return [out, size]

        frozen, ref = self._frozen_v1(build, ["cumsum", "ta_size"],
                                      {"x:0": x})
        ops = _graph_ops(frozen)
        for m in ("TensorArrayV3", "TensorArrayWriteV3",
                  "TensorArrayReadV3", "TensorArrayScatterV3",
                  "TensorArrayGatherV3", "TensorArraySizeV3"):
            assert m in ops, f"battery bug: {m} not in {sorted(ops)}"
        sd = TFGraphMapper.importGraph(frozen)
        res = sd.output({"x": x}, ["cumsum", "ta_size"])
        np.testing.assert_allclose(np.asarray(res["cumsum"]), ref[0],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(res["ta_size"]), ref[1])
