"""End-of-suite EXECUTIONAL op-coverage gate (reference: org/nd4j/
autodiff/validation/OpValidation — coverage accounting that tracks ops
actually exercised and fails the build otherwise, SURVEY.md §4).

The registry records every dispatched op (ops/registry.py); test
subprocesses append their sets via DL4J_TPU_OP_TRACE_FILE (conftest).
This module's zzz name puts it LAST in pytest's default alphabetical
collection, so by the time it runs the whole suite has executed. A
registered op that no test ever RAN — not merely mentioned — fails the
gate unless it carries a conscious, reasoned EXEMPT entry (the
reference's excludedOpsets role).
"""

import glob
import os

import pytest

# populate the FULL registry deterministically (a bare ops import now
# registers everything — guarded by test_op_coverage.py)
import deeplearning4j_tpu.ops  # noqa: F401
from deeplearning4j_tpu.ops.registry import executed_ops, list_ops

#: op name -> reason it is allowed to skip execution accounting. Every
#: entry is a conscious decision; an entry whose op starts executing
#: again is flagged stale below.
EXEMPT = {}


def _missing(registered, executed, exempt):
    return [op for op in registered
            if op not in executed and op not in exempt]


def test_gate_logic_catches_unexecuted_ops():
    """The gate itself must fail a registered-but-never-executed op
    (the round-3 verdict's complaint about the lexical gate: a comment
    mention must NOT count)."""
    assert _missing(["ghost_op"], set(), {}) == ["ghost_op"]
    assert _missing(["ghost_op"], {"ghost_op"}, {}) == []
    assert _missing(["ghost_op"], set(), {"ghost_op": "why"}) == []


def test_every_registered_op_executes_in_the_suite(request):
    here = os.path.dirname(os.path.abspath(__file__))
    all_mods = {os.path.basename(p)
                for p in glob.glob(os.path.join(here, "test_*.py"))}
    ran_mods = {os.path.basename(str(i.fspath))
                for i in request.session.items}
    partial = all_mods - ran_mods
    if partial:
        pytest.skip(
            f"partial run ({len(partial)} test modules not collected) "
            "— the executional gate is enforced on full-suite runs")
    executed = executed_ops()
    missing = _missing(list_ops(), executed, EXEMPT)
    assert not missing, (
        f"{len(missing)} registered ops were never EXECUTED by the "
        f"suite (reference parity: OpValidation fails the build for "
        f"untested ops); add a real test or a reasoned EXEMPT entry: "
        f"{missing}")
    stale = [op for op in EXEMPT if op in executed]
    assert not stale, (
        f"EXEMPT entries whose ops now execute — remove them: {stale}")
