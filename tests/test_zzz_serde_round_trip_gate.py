"""EXECUTIONAL config-serde gate: every @serializable-registered class
must round-trip through JSON.

Reference parity: the config system's hard contract is Jackson
round-trip on EVERY config (SURVEY.md §2.18/§5 — MultiLayerConfiguration
toJson/fromJson plus polymorphic layer/updater/schedule serializers,
exercised across the reference's layer-config test suites). The
hand-picked round-trip lists in test_layers_extra.py cover what someone
remembered to list; THIS gate iterates the live serde registry so a
newly registered config class cannot ship without a working
to_json -> from_json -> to_json identity.

Mirrors the op/mapper execution gates: enumerate the registry, build an
instance of every class (SPECIAL carries constructors for classes whose
__init__ needs arguments), and fail the build for anything that does
not round-trip. EXEMPT entries need a reason.
"""
import dataclasses

import pytest

# importing EVERY @serializable-carrying module registers the classes
# (grep '@serializable' is the source of this list; the populated-count
# floor below catches an import refactor dropping one)
import deeplearning4j_tpu.autodiff.training  # noqa: F401
import deeplearning4j_tpu.learning  # noqa: F401
import deeplearning4j_tpu.models.transformer  # noqa: F401
import deeplearning4j_tpu.nn.conf  # noqa: F401
import deeplearning4j_tpu.nn.conf.objdetect  # noqa: F401
import deeplearning4j_tpu.nn.conf.ocnn  # noqa: F401
import deeplearning4j_tpu.nn.conf.variational  # noqa: F401
import deeplearning4j_tpu.nn.graph.config  # noqa: F401
import deeplearning4j_tpu.nn.graph.vertices  # noqa: F401
import deeplearning4j_tpu.nn.transferlearning  # noqa: F401
from deeplearning4j_tpu.common import serde
from deeplearning4j_tpu.common.serde import _CLASSES

#: class name -> zero-arg factory, for classes whose __init__ requires
#: arguments. Keep entries MINIMAL — a default-constructible config is
#: the norm and keeps this gate self-maintaining.
SPECIAL = {
    "MapSchedule": lambda: _CLASSES["MapSchedule"](
        values={0: 0.1, 10: 0.01}),
}

#: class name -> reason it cannot round-trip (none expected; an entry
#: here is a conscious decision, like the op gate's EXEMPT)
EXEMPT: dict = {}


def _instances():
    for name in sorted(_CLASSES):
        if name in EXEMPT:
            continue
        yield name


@pytest.mark.parametrize("name", list(_instances()))
def test_registered_class_round_trips(name):
    cls = _CLASSES[name]
    obj = SPECIAL[name]() if name in SPECIAL else cls()
    j = serde.to_json(obj)
    back = serde.from_json(j)
    assert type(back) is cls
    assert serde.to_json(back) == j, (
        f"{name}: from_json(to_json(x)) is not identity")


def test_registry_is_populated():
    # guards against an import refactor silently emptying the gate
    assert len(_CLASSES) >= 115, sorted(_CLASSES)


def test_exempt_entries_are_still_registered():
    stale = [n for n in EXEMPT if n not in _CLASSES]
    assert not stale, f"EXEMPT entries no longer registered: {stale}"


def test_all_dataclass_fields_survive():
    # the identity gate above uses all-default instances, which cannot
    # see a field silently dropped back to its default; here EVERY
    # field of DenseLayer is set non-default and checked individually
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer, Dropout, MaxNormConstraint, WeightNoise)
    lay = DenseLayer(
        name="fc1", activation="elu", weight_init="relu",
        updater=Adam(learning_rate=0.007), l1=0.01, l2=0.02,
        dropout=Dropout(rate=0.25),
        weight_noise=WeightNoise(stddev=0.05),
        constraints=[MaxNormConstraint(max_norm=2.0)],
        n_in=7, n_out=11, has_bias=False)
    defaults = DenseLayer()
    back = serde.from_json(serde.to_json(lay))
    for f in dataclasses.fields(lay):
        # the instance genuinely differs from the default...
        assert getattr(lay, f.name) != getattr(defaults, f.name), (
            f"{f.name}: test value equals the default — set it "
            "non-default so a dropped field is detectable")
        # ...and the round-trip preserves it
        a, b = getattr(back, f.name), getattr(lay, f.name)
        assert serde.to_dict(a) == serde.to_dict(b), f.name
