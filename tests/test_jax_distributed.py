"""Real multi-process jax.distributed exercise (VERDICT r1 #7).

The reference tests its Aeron parameter server by spinning N in-process
servers over localhost (SURVEY.md §4 "distributed without a cluster");
the TPU-native equivalent is N OS processes joined through
``jax.distributed.initialize`` on a localhost coordinator, with the CPU
backend's cross-process collectives standing in for ICI. Each worker
contributes 2 virtual CPU devices; the 2 processes form one 4-device
global mesh, run a data-parallel train step where each process feeds
ONLY its local batch shard, and the result must match a single-process
run on the full batch bit-for-float (modulo reduction order)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
proc_id, nproc, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                sys.argv[3], sys.argv[4])
import jax
from deeplearning4j_tpu.distributed import DistributedBackend

DistributedBackend.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc,
    process_id=proc_id)
assert DistributedBackend.process_count() == nproc
assert DistributedBackend.process_index() == proc_id
assert len(jax.devices()) == 2 * nproc, jax.devices()

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(2 * nproc), ("data",))
dspec = NamedSharding(mesh, P("data"))
rep = NamedSharding(mesh, P())

# deterministic data: every process derives the FULL batch, then feeds
# only its local quarter rows through make_array_from_process_local_data
rs = np.random.RandomState(0)
X = rs.randn(8, 4).astype(np.float32)
Y = rs.randn(8, 2).astype(np.float32)
local_rows = slice(proc_id * 4, (proc_id + 1) * 4)
x = jax.make_array_from_process_local_data(dspec, X[local_rows], X.shape)
y = jax.make_array_from_process_local_data(dspec, Y[local_rows], Y.shape)

w = jax.device_put(jnp.zeros((4, 2)), rep)

@jax.jit
def step(w, x, y):
    def loss(w):
        return jnp.mean((x @ w - y) ** 2)
    l, g = jax.value_and_grad(loss)(w)
    return w - 0.1 * g, l

for _ in range(5):
    w, l = step(w, x, y)

out = {"loss": float(l), "w_sum": float(jnp.sum(w)),
       "w00": float(w[0, 0])}
if proc_id == 0:
    with open(os.path.join(outdir, "result.json"), "w") as f:
        json.dump(out, f)
DistributedBackend.shutdown()
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_data_parallel_matches_single_process(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = _free_port()

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",   # never touch the TPU tunnel
        "PYTHONPATH": REPO,
    })
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(2)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:               # never leak a hung worker
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{so}\n{se[-3000:]}"

    with open(tmp_path / "result.json") as f:
        got = json.load(f)

    # single-process reference on the full batch
    rs = np.random.RandomState(0)
    X = rs.randn(8, 4).astype(np.float32)
    Y = rs.randn(8, 2).astype(np.float32)
    w = np.zeros((4, 2), np.float32)
    for _ in range(5):
        r = X @ w - Y
        loss = float((r ** 2).mean())
        g = 2.0 * X.T @ r / r.size
        w = w - 0.1 * g
    assert abs(got["loss"] - loss) < 1e-5, (got, loss)
    assert abs(got["w_sum"] - float(w.sum())) < 1e-4
    assert abs(got["w00"] - float(w[0, 0])) < 1e-5


# ---------------------------------------------------------------------
# Sharded checkpoint kill-and-resume (VERDICT r2 missing #3 / SURVEY §5
# "Orbax-style checkpoint of param/opt pytrees + data-iterator state"):
# a 2-process run with row-sharded params + momentum saves per-host
# shard files mid-epoch, dies, and a NEW 2-process run restores and
# continues with exact loss continuity vs an uninterrupted run.
# ---------------------------------------------------------------------
CKPT_WORKER = r"""
import json, os, sys
proc_id, nproc, port, outdir, phase = (int(sys.argv[1]), int(sys.argv[2]),
                                       sys.argv[3], sys.argv[4], sys.argv[5])
import jax
from deeplearning4j_tpu.distributed import DistributedBackend

DistributedBackend.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc,
    process_id=proc_id)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.util import ShardedCheckpoint

mesh = Mesh(np.array(jax.devices()).reshape(2 * nproc), ("data",))
dspec = NamedSharding(mesh, P("data"))
wspec = NamedSharding(mesh, P("data"))   # w ROW-SHARDED over devices

rs = np.random.RandomState(0)
X = rs.randn(40, 8).astype(np.float32)
Y = rs.randn(40, 2).astype(np.float32)
it = ArrayDataSetIterator(X, Y, batch_size=8, shuffle=True, seed=7)

w = jax.device_put(jnp.zeros((8, 2)), wspec)
v = jax.device_put(jnp.zeros((8, 2)), wspec)

@jax.jit
def step(w, v, x, y):
    def loss(w):
        return jnp.mean((x @ w - y) ** 2)
    l, g = jax.value_and_grad(loss)(w)
    v2 = 0.9 * v + g
    return w - 0.1 * v2, v2, l

def feed(ds):
    x_np, y_np = np.asarray(ds.features), np.asarray(ds.labels)
    rows = slice(proc_id * 4, (proc_id + 1) * 4)
    x = jax.make_array_from_process_local_data(dspec, x_np[rows], x_np.shape)
    y = jax.make_array_from_process_local_data(dspec, y_np[rows], y_np.shape)
    return x, y

ckpt_dir = os.path.join(outdir, "ckpt")
losses = []
start = 0
if phase == "resume":
    template = {"w": jax.device_put(jnp.zeros((8, 2)), wspec),
                "v": jax.device_put(jnp.zeros((8, 2)), wspec)}
    tree, meta = ShardedCheckpoint.restore(ckpt_dir, template)
    w, v = tree["w"], tree["v"]
    it.set_state(meta["iterator_state"])
    start = meta["step"]

n_steps = 3 if phase == "part1" else 5
for i in range(start, n_steps):
    ds = it.next()
    x, y = feed(ds)
    w, v, l = step(w, v, x, y)
    losses.append(float(l))

if phase == "part1":
    ShardedCheckpoint.save(ckpt_dir, {"w": w, "v": v}, step=3,
                           iterator_state=it.get_state())
    # die here: the remaining 2 steps never run in this incarnation

# jnp.sum over a cross-process sharded array is a COLLECTIVE — every
# process must compute it, only proc 0 writes it
w_sum = float(jnp.sum(w))
if proc_id == 0:
    with open(os.path.join(outdir, f"losses_{phase}.json"), "w") as f:
        json.dump({"losses": losses, "w_sum": w_sum}, f)
DistributedBackend.shutdown()
"""


def _run_ckpt_phase(tmp_path, phase):
    worker = tmp_path / f"worker_{phase}.py"
    worker.write_text(CKPT_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
    })
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port),
             str(tmp_path), phase],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(2)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"{phase} worker failed:\n{so}\n{se[-3000:]}"
    with open(tmp_path / f"losses_{phase}.json") as f:
        return json.load(f)


def test_sharded_checkpoint_kill_and_resume(tmp_path):
    part1 = _run_ckpt_phase(tmp_path, "part1")
    assert len(part1["losses"]) == 3
    # per-host shard files exist (one per process), not a global blob
    ckpt = tmp_path / "ckpt"
    assert (ckpt / "shards_p0.npz").exists()
    assert (ckpt / "shards_p1.npz").exists()
    assert (ckpt / "manifest.json").exists()

    resumed = _run_ckpt_phase(tmp_path, "resume")
    assert len(resumed["losses"]) == 2

    full = _run_ckpt_phase(tmp_path, "full")
    assert len(full["losses"]) == 5

    # loss continuity: the resumed run's steps 4-5 must match the
    # uninterrupted run exactly (same params, same momentum, same
    # mid-epoch batches via the restored iterator state)
    np.testing.assert_allclose(part1["losses"], full["losses"][:3],
                               rtol=1e-6)
    np.testing.assert_allclose(resumed["losses"], full["losses"][3:],
                               rtol=1e-6)
    np.testing.assert_allclose(resumed["w_sum"], full["w_sum"],
                               rtol=1e-6)


def test_package_import_leaves_backend_uninitialized():
    """Importing the framework must NOT run any jax computation at
    module scope: multi-process workers import the package BEFORE
    calling jax.distributed.initialize(), which jax requires to happen
    before the XLA backend comes up. (Regression: a module-level
    jnp.log() constant broke both 2-process tests in this file.)"""
    code = (
        "import deeplearning4j_tpu.nn.conf, deeplearning4j_tpu.ops,\\\n"
        "    deeplearning4j_tpu.models.gpt, deeplearning4j_tpu.datasets,\\\n"
        "    deeplearning4j_tpu.graph, deeplearning4j_tpu.clustering,\\\n"
        "    deeplearning4j_tpu.dimensionalityreduction\n"
        "import jax._src.xla_bridge as xb\n"
        "assert not xb._backends, f'backend initialized: {list(xb._backends)}'\n"
        "print('CLEAN')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "CLEAN" in r.stdout, r.stderr[-2000:]


# ---------------------------------------------------------------------
# ZeRO update sharding across REAL processes: 2 workers join via
# DL4J_TPU_COORDINATOR env (maybe_init_distributed threaded through
# ShardedTrainer mesh construction), each feeds its LOCAL batch half,
# and the update-sharded result matches a single-process replicated
# run on the full batch. Skips (not fails) when the backend cannot run
# cross-process collectives (this container's CPU jaxlib — the same
# env drift that affects the tests above).
# ---------------------------------------------------------------------
ZERO_WORKER = r"""
import json, os, sys
proc_id, nproc, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                sys.argv[3], sys.argv[4])
os.environ["DL4J_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["DL4J_TPU_NUM_PROCESSES"] = str(nproc)
os.environ["DL4J_TPU_PROCESS_ID"] = str(proc_id)
import numpy as np
import jax
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn.conf import (DenseLayer, InputType,
    NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.parallel.sharded import ShardedTrainer
from deeplearning4j_tpu.datasets import DataSet

conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-2))
        .list()
        .layer(DenseLayer(n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .setInputType(InputType.feedForward(6)).build())
net = MultiLayerNetwork(conf)
# trainer BEFORE init(): mesh construction runs maybe_init_distributed,
# which must precede the first jax computation
tr = ShardedTrainer(net, mode="sharing", update_sharding="zero")
net.init()
assert jax.process_count() == nproc
assert tr.mesh.shape["data"] == 2 * nproc

rs = np.random.RandomState(0)
X = rs.randn(32, 6).astype(np.float32)
Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
rows = slice(proc_id * 16, (proc_id + 1) * 16)   # local half
try:
    for _ in range(5):
        tr.fit(DataSet(X[rows], Y[rows]))
    out = {"loss": float(net.score())}
except Exception as e:  # backend capability probe
    if "Multiprocess computations" in str(e):
        out = {"unsupported": str(e)}
    else:
        raise
if proc_id == 0:
    with open(os.path.join(outdir, "zero_result.json"), "w") as f:
        json.dump(out, f)
"""


def test_two_process_zero_update_sharding(tmp_path):
    worker = tmp_path / "zero_worker.py"
    worker.write_text(ZERO_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
    })
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(2)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{so}\n{se[-3000:]}"
    with open(tmp_path / "zero_result.json") as f:
        got = json.load(f)
    if "unsupported" in got:
        pytest.skip("backend lacks cross-process CPU collectives: "
                    + got["unsupported"][:120])

    # single-process replicated reference on the full batch
    rs = np.random.RandomState(0)
    X = rs.randn(32, 6).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer.network import (
        MultiLayerNetwork,
    )

    conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(6)).build())
    ref = MultiLayerNetwork(conf).init()
    from deeplearning4j_tpu.datasets import DataSet
    for _ in range(5):
        ref.fit(DataSet(X, Y))
    assert abs(got["loss"] - float(ref.score())) \
        / abs(float(ref.score())) < 1e-3
