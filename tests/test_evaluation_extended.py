"""Extended evaluation (ROCBinary, ROCMultiClass, EvaluationCalibration,
top-N) and the learned/recurrent attention layers.

Reference: org/nd4j/evaluation/classification/{ROCBinary,ROCMultiClass,
EvaluationCalibration}, Evaluation(topN); conf/layers/
{LearnedSelfAttentionLayer,RecurrentAttentionLayer} (SURVEY.md §2.16, §2.20).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.evaluation import (
    Evaluation, EvaluationCalibration, ROC, ROCBinary, ROCMultiClass,
)


class TestROCBinary:
    def test_perfect_and_random(self):
        rs = np.random.RandomState(0)
        y = (rs.rand(200, 3) > 0.5).astype(np.float32)
        perfect = y * 0.9 + 0.05
        roc = ROCBinary()
        roc.eval(y, perfect)
        for i in range(3):
            assert roc.calculateAUC(i) > 0.99
        rand = ROCBinary()
        rand.eval(y, rs.rand(200, 3).astype(np.float32))
        assert 0.3 < rand.calculateAverageAUC() < 0.7

    def test_batched_accumulation(self):
        rs = np.random.RandomState(1)
        y = (rs.rand(100, 2) > 0.5).astype(np.float32)
        p = np.clip(y + rs.randn(100, 2) * 0.3, 0, 1)
        whole = ROCBinary(); whole.eval(y, p)
        batched = ROCBinary()
        batched.eval(y[:50], p[:50]); batched.eval(y[50:], p[50:])
        for i in range(2):
            assert abs(whole.calculateAUC(i) - batched.calculateAUC(i)) < 1e-9


class TestROCMultiClass:
    def test_one_vs_all(self):
        rs = np.random.RandomState(2)
        cls = rs.randint(0, 4, 300)
        y = np.eye(4, dtype=np.float32)[cls]
        logits = y * 3 + rs.randn(300, 4)
        p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        roc = ROCMultiClass()
        roc.eval(y, p)
        assert roc.numClasses() == 4
        for i in range(4):
            assert roc.calculateAUC(i) > 0.85
        assert roc.calculateAverageAUC() > 0.85
        assert "AUC" in roc.stats()

    def test_matches_binary_roc_per_class(self):
        rs = np.random.RandomState(3)
        cls = rs.randint(0, 2, 100)
        y = np.eye(2, dtype=np.float32)[cls]
        p = rs.rand(100, 2).astype(np.float32)
        mc = ROCMultiClass(); mc.eval(y, p)
        r = ROC(); r.eval(y[:, 1], p[:, 1])
        assert abs(mc.calculateAUC(1) - r.calculateAUC()) < 1e-9


class TestEvaluationCalibration:
    def test_well_calibrated(self):
        rs = np.random.RandomState(4)
        p1 = rs.rand(20000)
        y1 = (rs.rand(20000) < p1).astype(np.float32)
        y = np.stack([1 - y1, y1], -1)
        p = np.stack([1 - p1, p1], -1)
        ec = EvaluationCalibration(reliability_bins=10)
        ec.eval(y, p)
        # well-calibrated → low ECE
        assert ec.expectedCalibrationError(1) < 0.03
        mean_p, frac_pos, cnt = ec.getReliabilityInfo(1)
        ok = cnt > 0
        np.testing.assert_allclose(mean_p[ok], frac_pos[ok], atol=0.08)

    def test_miscalibrated(self):
        n = 5000
        p1 = np.full(n, 0.9)
        y1 = (np.random.RandomState(5).rand(n) < 0.5).astype(np.float32)
        ec = EvaluationCalibration()
        ec.eval(np.stack([1 - y1, y1], -1), np.stack([1 - p1, p1], -1))
        assert ec.expectedCalibrationError(1) > 0.3

    def test_count_histograms(self):
        y = np.eye(3, dtype=np.float32)[[0, 1, 1, 2]]
        p = np.full((4, 3), 1 / 3, np.float32)
        p[:, 0] = 0.5
        ec = EvaluationCalibration()
        ec.eval(y, p)
        np.testing.assert_array_equal(ec.getLabelCountsEachClass(), [1, 2, 1])
        np.testing.assert_array_equal(ec.getPredictionCountsEachClass(), [4, 0, 0])
        assert ec.getResidualPlotAllClasses().sum() == 12  # 4 rows * 3 cols
        assert "ECE" in ec.stats()


class TestTopN:
    def test_top2(self):
        y = np.eye(3, dtype=np.float32)[[0, 1, 2]]
        p = np.array([[0.5, 0.4, 0.1],    # correct top1
                      [0.5, 0.4, 0.1],    # class 1 is 2nd → top2 correct
                      [0.5, 0.4, 0.1]],   # class 2 is 3rd → top2 wrong
                     np.float32)
        ev = Evaluation(top_n=2)
        ev.eval(y, p)
        assert abs(ev.accuracy() - 1 / 3) < 1e-9
        assert abs(ev.topNAccuracy() - 2 / 3) < 1e-9


class TestAttentionLayers:
    def _seq_net(self, layer, t_out=None):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration, RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
        from deeplearning4j_tpu.learning.updaters import Adam
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(layer)
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .setInputType(InputType.recurrent(6))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_learned_self_attention_shapes(self):
        from deeplearning4j_tpu.nn.conf import LearnedSelfAttentionLayer
        net = self._seq_net(LearnedSelfAttentionLayer(
            n_out=8, n_heads=2, n_queries=4))
        x = np.random.RandomState(0).randn(3, 9, 6).astype(np.float32)
        out = net.output(x).toNumpy()
        assert out.shape == (3, 4, 2)  # n_queries defines output length

    def test_learned_self_attention_trains(self):
        from deeplearning4j_tpu.nn.conf import LearnedSelfAttentionLayer
        net = self._seq_net(LearnedSelfAttentionLayer(
            n_out=8, n_heads=2, n_queries=2))
        rs = np.random.RandomState(1)
        x = rs.randn(16, 7, 6).astype(np.float32)
        lab = (x.mean((1, 2)) > 0).astype(int)
        y = np.repeat(np.eye(2, dtype=np.float32)[lab][:, None, :], 2, axis=1)
        first = None
        for _ in range(40):
            net.fit(x, y)
            first = first or net.score()
        assert net.score() < first

    def test_recurrent_attention_shapes_and_training(self):
        from deeplearning4j_tpu.nn.conf import RecurrentAttentionLayer
        net = self._seq_net(RecurrentAttentionLayer(n_out=8, n_heads=2))
        rs = np.random.RandomState(2)
        x = rs.randn(8, 5, 6).astype(np.float32)
        out = net.output(x).toNumpy()
        assert out.shape == (8, 5, 2)
        lab = (x.sum(-1) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[lab]
        first = None
        for _ in range(30):
            net.fit(x, y)
            first = first or net.score()
        assert net.score() < first

    def test_json_roundtrip(self):
        from deeplearning4j_tpu.nn.conf import (
            LearnedSelfAttentionLayer, MultiLayerConfiguration,
        )
        net = self._seq_net(LearnedSelfAttentionLayer(
            n_out=8, n_heads=2, n_queries=4))
        cfg2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert isinstance(cfg2.layers[0], LearnedSelfAttentionLayer)
        assert cfg2.layers[0].n_queries == 4
