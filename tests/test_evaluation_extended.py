"""Extended evaluation (ROCBinary, ROCMultiClass, EvaluationCalibration,
top-N) and the learned/recurrent attention layers.

Reference: org/nd4j/evaluation/classification/{ROCBinary,ROCMultiClass,
EvaluationCalibration}, Evaluation(topN); conf/layers/
{LearnedSelfAttentionLayer,RecurrentAttentionLayer} (SURVEY.md §2.16, §2.20).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.evaluation import (
    Evaluation, EvaluationCalibration, ROC, ROCBinary, ROCMultiClass,
)


class TestROCBinary:
    def test_perfect_and_random(self):
        rs = np.random.RandomState(0)
        y = (rs.rand(200, 3) > 0.5).astype(np.float32)
        perfect = y * 0.9 + 0.05
        roc = ROCBinary()
        roc.eval(y, perfect)
        for i in range(3):
            assert roc.calculateAUC(i) > 0.99
        rand = ROCBinary()
        rand.eval(y, rs.rand(200, 3).astype(np.float32))
        assert 0.3 < rand.calculateAverageAUC() < 0.7

    def test_batched_accumulation(self):
        rs = np.random.RandomState(1)
        y = (rs.rand(100, 2) > 0.5).astype(np.float32)
        p = np.clip(y + rs.randn(100, 2) * 0.3, 0, 1)
        whole = ROCBinary(); whole.eval(y, p)
        batched = ROCBinary()
        batched.eval(y[:50], p[:50]); batched.eval(y[50:], p[50:])
        for i in range(2):
            assert abs(whole.calculateAUC(i) - batched.calculateAUC(i)) < 1e-9


class TestRocCurves:
    """reference: evaluation/curves/{RocCurve,PrecisionRecallCurve} —
    the plot/export objects ROC#getRocCurve / getPrecisionRecallCurve
    return."""

    def _fitted_roc(self, seed=0, n=200):
        from deeplearning4j_tpu.evaluation import ROC
        rng = np.random.default_rng(seed)
        y = (rng.random(n) < 0.4).astype(np.float32)
        s = np.clip(0.6 * y + rng.normal(0, 0.25, n), 0, 1) \
            .astype(np.float32)   # same dtype the ROC stores
        roc = ROC()
        roc.eval(y, s)
        return roc, y, s

    def test_auc_matches_mann_whitney(self):
        # independent oracle: AUC = P(random positive outranks random
        # negative), ties counted 1/2 — must equal the trapezoid area
        # of the tie-collapsed curve
        roc, y, s = self._fitted_roc()
        pos, neg = s[y == 1], s[y == 0]
        wins = (pos[:, None] > neg[None, :]).sum() \
            + 0.5 * (pos[:, None] == neg[None, :]).sum()
        expected = wins / (len(pos) * len(neg))
        assert roc.calculateAUC() == pytest.approx(expected, abs=1e-9)
        curve = roc.getRocCurve()
        assert curve.calculateAUC() == pytest.approx(expected, abs=1e-9)
        # monotone, anchored at (0,0) and ending at (1,1)
        assert curve.getFalsePositiveRate(0) == 0.0
        assert curve.getTruePositiveRate(0) == 0.0
        assert curve.getFalsePositiveRate(curve.numPoints() - 1) \
            == pytest.approx(1.0)
        assert curve.getTruePositiveRate(curve.numPoints() - 1) \
            == pytest.approx(1.0)
        assert (np.diff(curve.fpr) >= -1e-12).all()
        assert (np.diff(curve.tpr) >= -1e-12).all()

    def test_curve_points_match_manual_thresholding(self):
        roc, y, s = self._fitted_roc(seed=1, n=60)
        curve = roc.getRocCurve()
        P, N = y.sum(), (1 - y).sum()
        for i in range(1, curve.numPoints(), 7):
            t = curve.getThreshold(i)
            pred = s >= t
            np.testing.assert_allclose(
                curve.getTruePositiveRate(i),
                (pred & (y == 1)).sum() / P, atol=1e-9)
            np.testing.assert_allclose(
                curve.getFalsePositiveRate(i),
                (pred & (y == 0)).sum() / N, atol=1e-9)

    def test_tied_scores_collapse_to_one_point(self):
        from deeplearning4j_tpu.evaluation import ROC
        roc = ROC()
        roc.eval(np.array([1, 0, 1, 0], np.float32),
                 np.array([0.7, 0.7, 0.7, 0.2], np.float32))
        curve = roc.getRocCurve()
        # thresholds: inf, 0.7, 0.2 — the three tied 0.7s are ONE point
        assert curve.numPoints() == 3

    def test_pr_curve(self):
        roc, y, s = self._fitted_roc(seed=2)
        pr = roc.getPrecisionRecallCurve()
        # recall anchored at 0, nondecreasing, ends at 1
        assert pr.getRecall(0) == 0.0
        assert (np.diff(pr.recall) >= -1e-12).all()
        assert pr.getRecall(pr.numPoints() - 1) == pytest.approx(1.0)
        # precision at a mid threshold matches manual computation
        i = pr.numPoints() // 2
        t = pr.getThreshold(i)
        pred = s >= t
        np.testing.assert_allclose(
            pr.getPrecision(i),
            ((pred) & (y == 1)).sum() / pred.sum(), atol=1e-9)

    def test_aucpr_hand_computed(self):
        # y/s chosen so the hand trapezoid is exact: points (r,p) =
        # anchor(0,1), (2/3,1), (2/3,2/3), (1,3/4), (1,3/5), (1,1/2)
        # -> area = 2/3 + 17/72 = 65/72
        from deeplearning4j_tpu.evaluation import ROC
        roc = ROC()
        roc.eval(np.array([1, 1, 0, 1, 0, 0], np.float32),
                 np.array([.9, .9, .8, .7, .6, .5], np.float32))
        assert roc.calculateAUCPR() == pytest.approx(65 / 72, abs=1e-9)
        # all scores tied: one operating point, area = its precision
        tied = ROC()
        tied.eval(np.array([1, 1, 1, 0], np.float32),
                  np.full(4, 0.7, np.float32))
        assert tied.calculateAUCPR() == pytest.approx(0.75, abs=1e-9)

    def test_empty_accumulator_is_safe(self):
        from deeplearning4j_tpu.evaluation import ROC
        roc = ROC()
        roc.eval(np.zeros(0, np.float32), np.zeros(0, np.float32))
        assert roc.calculateAUC() == 0.0
        assert roc.calculateAUCPR() == 0.0
        assert ROC().calculateAUC() == 0.0   # never eval'd at all

    def test_rocbinary_tie_order_independent(self):
        from deeplearning4j_tpu.evaluation import ROCBinary
        y = np.array([[0], [1], [1]], np.float32)
        s = np.array([[0.7], [0.7], [0.2]], np.float32)
        a = ROCBinary()
        a.eval(y, s)
        b = ROCBinary()
        b.eval(y[::-1].copy(), s[::-1].copy())
        assert a.calculateAUC(0) == pytest.approx(b.calculateAUC(0),
                                                  abs=1e-12)


class TestROCMultiClass:
    def test_one_vs_all(self):
        rs = np.random.RandomState(2)
        cls = rs.randint(0, 4, 300)
        y = np.eye(4, dtype=np.float32)[cls]
        logits = y * 3 + rs.randn(300, 4)
        p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        roc = ROCMultiClass()
        roc.eval(y, p)
        assert roc.numClasses() == 4
        for i in range(4):
            assert roc.calculateAUC(i) > 0.85
        assert roc.calculateAverageAUC() > 0.85
        assert "AUC" in roc.stats()

    def test_matches_binary_roc_per_class(self):
        rs = np.random.RandomState(3)
        cls = rs.randint(0, 2, 100)
        y = np.eye(2, dtype=np.float32)[cls]
        p = rs.rand(100, 2).astype(np.float32)
        mc = ROCMultiClass(); mc.eval(y, p)
        r = ROC(); r.eval(y[:, 1], p[:, 1])
        assert abs(mc.calculateAUC(1) - r.calculateAUC()) < 1e-9


class TestEvaluationCalibration:
    def test_well_calibrated(self):
        rs = np.random.RandomState(4)
        p1 = rs.rand(20000)
        y1 = (rs.rand(20000) < p1).astype(np.float32)
        y = np.stack([1 - y1, y1], -1)
        p = np.stack([1 - p1, p1], -1)
        ec = EvaluationCalibration(reliability_bins=10)
        ec.eval(y, p)
        # well-calibrated → low ECE
        assert ec.expectedCalibrationError(1) < 0.03
        mean_p, frac_pos, cnt = ec.getReliabilityInfo(1)
        ok = cnt > 0
        np.testing.assert_allclose(mean_p[ok], frac_pos[ok], atol=0.08)

    def test_miscalibrated(self):
        n = 5000
        p1 = np.full(n, 0.9)
        y1 = (np.random.RandomState(5).rand(n) < 0.5).astype(np.float32)
        ec = EvaluationCalibration()
        ec.eval(np.stack([1 - y1, y1], -1), np.stack([1 - p1, p1], -1))
        assert ec.expectedCalibrationError(1) > 0.3

    def test_count_histograms(self):
        y = np.eye(3, dtype=np.float32)[[0, 1, 1, 2]]
        p = np.full((4, 3), 1 / 3, np.float32)
        p[:, 0] = 0.5
        ec = EvaluationCalibration()
        ec.eval(y, p)
        np.testing.assert_array_equal(ec.getLabelCountsEachClass(), [1, 2, 1])
        np.testing.assert_array_equal(ec.getPredictionCountsEachClass(), [4, 0, 0])
        assert ec.getResidualPlotAllClasses().sum() == 12  # 4 rows * 3 cols
        assert "ECE" in ec.stats()


class TestTopN:
    def test_top2(self):
        y = np.eye(3, dtype=np.float32)[[0, 1, 2]]
        p = np.array([[0.5, 0.4, 0.1],    # correct top1
                      [0.5, 0.4, 0.1],    # class 1 is 2nd → top2 correct
                      [0.5, 0.4, 0.1]],   # class 2 is 3rd → top2 wrong
                     np.float32)
        ev = Evaluation(top_n=2)
        ev.eval(y, p)
        assert abs(ev.accuracy() - 1 / 3) < 1e-9
        assert abs(ev.topNAccuracy() - 2 / 3) < 1e-9


class TestAttentionLayers:
    def _seq_net(self, layer, t_out=None):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration, RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
        from deeplearning4j_tpu.learning.updaters import Adam
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(layer)
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .setInputType(InputType.recurrent(6))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_learned_self_attention_shapes(self):
        from deeplearning4j_tpu.nn.conf import LearnedSelfAttentionLayer
        net = self._seq_net(LearnedSelfAttentionLayer(
            n_out=8, n_heads=2, n_queries=4))
        x = np.random.RandomState(0).randn(3, 9, 6).astype(np.float32)
        out = net.output(x).toNumpy()
        assert out.shape == (3, 4, 2)  # n_queries defines output length

    def test_learned_self_attention_trains(self):
        from deeplearning4j_tpu.nn.conf import LearnedSelfAttentionLayer
        net = self._seq_net(LearnedSelfAttentionLayer(
            n_out=8, n_heads=2, n_queries=2))
        rs = np.random.RandomState(1)
        x = rs.randn(16, 7, 6).astype(np.float32)
        lab = (x.mean((1, 2)) > 0).astype(int)
        y = np.repeat(np.eye(2, dtype=np.float32)[lab][:, None, :], 2, axis=1)
        first = None
        for _ in range(40):
            net.fit(x, y)
            first = first or net.score()
        assert net.score() < first

    def test_recurrent_attention_shapes_and_training(self):
        from deeplearning4j_tpu.nn.conf import RecurrentAttentionLayer
        net = self._seq_net(RecurrentAttentionLayer(n_out=8, n_heads=2))
        rs = np.random.RandomState(2)
        x = rs.randn(8, 5, 6).astype(np.float32)
        out = net.output(x).toNumpy()
        assert out.shape == (8, 5, 2)
        lab = (x.sum(-1) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[lab]
        first = None
        for _ in range(30):
            net.fit(x, y)
            first = first or net.score()
        assert net.score() < first

    def test_json_roundtrip(self):
        from deeplearning4j_tpu.nn.conf import (
            LearnedSelfAttentionLayer, MultiLayerConfiguration,
        )
        net = self._seq_net(LearnedSelfAttentionLayer(
            n_out=8, n_heads=2, n_queries=4))
        cfg2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert isinstance(cfg2.layers[0], LearnedSelfAttentionLayer)
        assert cfg2.layers[0].n_queries == 4
