"""Unified telemetry layer (profiler/telemetry.py): metrics registry,
host spans + Chrome-trace export, recompilation detector, device-memory
watermarks, /metrics + /telemetry endpoints — plus regression tests for
the listener fixes that ride with it (PerformanceListener samples/sec,
TimeIterationListener frequency/rate, CheckpointListener atomicity,
single-transfer check_numerics).
"""

import json
import logging
import os
import time
import urllib.request
import zipfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.profiler import telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.set_enabled(True)
    yield
    telemetry.reset()


def _tiny_net(n_in=3, seed_updater=None):
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .updater(seed_updater or Sgd(1e-2)).list()
            .layer(DenseLayer(n_out=4, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _batch(n, n_in=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, n_in).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, n)]
    return x, y


# ---------------------------------------------------------------------
# MetricsRegistry
# ---------------------------------------------------------------------
class TestMetricsRegistry:
    def test_counter_inc_and_labels(self):
        reg = telemetry.MetricsRegistry()
        c = reg.counter("requests_total", "help text")
        c.inc()
        c.inc(2, route="/a")
        c.inc(3, route="/a")
        assert c.value() == 1
        assert c.value(route="/a") == 5
        assert c.total() == 6
        # idempotent get-or-create returns the same object
        assert reg.counter("requests_total") is c

    def test_gauge_last_write_wins(self):
        reg = telemetry.MetricsRegistry()
        g = reg.gauge("bytes_in_use")
        g.set(10)
        g.set(7, device="0")
        g.set(3)
        assert g.value() == 3
        assert g.value(device="0") == 7

    def test_histogram_bucket_counts_and_capture(self):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 0.5, 1.0))
        for v in (0.05, 0.3, 0.3, 0.7, 5.0):
            h.observe(v, engine="e0")
        cap = reg.capture()
        cnt, tot, buckets = cap["lat"]["series"][(("engine", "e0"),)]
        assert cnt == 5 and tot == pytest.approx(6.35)
        # non-cumulative per-bucket counts; last slot is +Inf overflow
        assert buckets == (1, 2, 1, 1)
        assert cap["lat"]["bounds"] == (0.1, 0.5, 1.0)
        text = reg.to_prometheus()
        assert 'lat_bucket{engine="e0",le="0.5"} 3' in text
        assert 'lat_bucket{engine="e0",le="+Inf"} 5' in text
        # counters/gauges capture raw values
        reg.counter("c").inc(3, k="a")
        assert reg.capture()["c"]["values"][(("k", "a"),)] == 3

    def test_remove_matching_and_engine_retire(self):
        reg = telemetry.MetricsRegistry()
        g = reg.gauge("g")
        g.set(1.0, engine="dead")
        g.set(2.0, engine="live")
        c = reg.counter("c")
        c.inc(5, engine="dead")
        h = reg.histogram("h")
        h.observe(0.1, engine="dead")
        # gauges-only removal drops the series, not the metric
        assert reg.remove_matching("engine", "dead",
                                   kinds=("gauge",)) == 1
        assert g.values() == {(("engine", "live"),): 2.0}
        assert c.value(engine="dead") == 5       # counters retained
        assert h.count(engine="dead") == 1
        # unrestricted removal sweeps every kind
        assert reg.remove_matching("engine", "dead") == 2
        assert c.value(engine="dead") == 0
        assert h.count(engine="dead") == 0

    def test_histogram_percentiles_and_bounds(self):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("lat", max_samples=64)
        for v in range(1, 101):
            h.observe(float(v))
        assert h.count() == 100
        assert h.sum() == pytest.approx(5050.0)
        p = h.percentiles()
        # reservoir is bounded: keeps the LAST 64 samples (37..100)
        assert 60 <= p["p50"] <= 75
        assert p["p99"] >= 95
        assert len(h._buf[()]) == 64

    def test_kind_conflict_raises(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_prometheus_exposition_format(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("c_total", "a counter").inc(2, site="s")
        reg.gauge("g").set(1.5)
        reg.histogram("h").observe(0.25, phase="etl")
        text = reg.to_prometheus()
        assert "# TYPE c_total counter" in text
        assert 'c_total{site="s"} 2' in text
        assert "# TYPE g gauge" in text
        assert "g 1.5" in text
        # histograms export proper cumulative _bucket{le=...} series
        # (scrapers run histogram_quantile over the same buckets the
        # in-process SLO engine windows)
        assert "# TYPE h histogram" in text
        assert 'h_bucket{phase="etl",le="0.25"} 1' in text
        assert 'h_bucket{phase="etl",le="0.1"} 0' in text
        assert 'h_bucket{phase="etl",le="+Inf"} 1' in text
        assert 'h_count{phase="etl"} 1' in text
        assert 'h_sum{phase="etl"} 0.25' in text
        # every non-comment line is "name{labels} value"
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            name, _, value = line.rpartition(" ")
            assert name and float(value) is not None

    def test_label_escaping(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("c").inc(1, k='va"l\\ue')
        text = reg.to_prometheus()
        assert 'k="va\\"l\\\\ue"' in text

    def test_label_newline_escaping(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("c").inc(1, k="a\nb")
        assert 'k="a\\nb"' in reg.to_prometheus()

    def test_help_and_type_for_every_metric(self):
        # scraper conformance: every metric family gets a # HELP and a
        # # TYPE line, even help-less ones, with HELP text escaped
        reg = telemetry.MetricsRegistry()
        reg.counter("c_total", "counts\nthings with \\slashes").inc()
        reg.gauge("g")          # no help
        reg.histogram("h", "a histogram").observe(1.0)
        text = reg.to_prometheus()
        assert "# HELP c_total counts\\nthings with \\\\slashes" in text
        assert "# HELP g" in text
        assert "# HELP h a histogram" in text
        for name, kind in (("c_total", "counter"), ("g", "gauge"),
                           ("h", "histogram")):
            assert f"# TYPE {name} {kind}" in text

    def test_nonfinite_values_render_prometheus_style(self):
        # the exposition format spells NaN / +Inf / -Inf; python's %g
        # ("nan"/"inf") is rejected by real scrapers
        reg = telemetry.MetricsRegistry()
        reg.gauge("g").set(float("nan"), k="a")
        reg.gauge("g").set(float("inf"), k="b")
        reg.gauge("g").set(float("-inf"), k="c")
        text = reg.to_prometheus()
        assert 'g{k="a"} NaN' in text
        assert 'g{k="b"} +Inf' in text
        assert 'g{k="c"} -Inf' in text
        assert "nan" not in text and "inf" not in text

    def test_spans_dropped_counter_on_wrap(self, monkeypatch):
        import collections

        monkeypatch.setattr(telemetry, "_trace_events",
                            collections.deque(maxlen=3))
        t0 = time.perf_counter()
        for i in range(5):
            telemetry.record_span(f"s{i}", t0)
        # the truncation is attributable on the export itself (the
        # export paths flush the pending count into the counter — the
        # record hot path only bumps an int under the trace lock)
        assert telemetry.chrome_trace()["otherData"][
            "spans_dropped"] == 2
        c = telemetry.MetricsRegistry.get_default().counter(
            telemetry.SPANS_DROPPED)
        assert c.total() == 2
        # flushing is not double-counting
        telemetry.flush_dropped_spans()
        assert c.total() == 2

    def test_json_dump(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("c_total").inc(3)
        reg.histogram("h").observe(1.0)
        d = reg.to_json()
        assert d["c_total"]["kind"] == "counter"
        assert d["c_total"]["values"]["total"] == 3
        assert d["h"]["values"]["total"]["count"] == 1
        json.dumps(d)  # serializable

    def test_thread_safety(self):
        import threading

        reg = telemetry.MetricsRegistry()
        c = reg.counter("n_total")

        def worker():
            for _ in range(1000):
                c.inc()

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.value() == 8000


# ---------------------------------------------------------------------
# spans + Chrome trace export
# ---------------------------------------------------------------------
class TestSpans:
    def test_nesting_recorded(self):
        with telemetry.span("outer"):
            with telemetry.span("inner"):
                time.sleep(0.001)
        evs = telemetry.chrome_trace()["traceEvents"]
        names = {e["name"]: e for e in evs}
        assert names["inner"]["args"]["parent"] == "outer"
        assert names["inner"]["args"]["depth"] == 1
        assert names["outer"]["args"]["depth"] == 0
        # inner completes first, nests inside outer's interval
        assert names["inner"]["ts"] >= names["outer"]["ts"]
        assert names["inner"]["dur"] <= names["outer"]["dur"]

    def test_chrome_trace_event_fields(self):
        with telemetry.span("s", foo="bar"):
            pass
        tr = telemetry.chrome_trace()
        assert "traceEvents" in tr
        for e in tr["traceEvents"]:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float)
            assert isinstance(e["dur"], float) and e["dur"] >= 0
            assert "pid" in e and "tid" in e and "name" in e

    def test_export_parses_as_json(self, tmp_path):
        with telemetry.span("a"):
            pass
        path = telemetry.export_chrome_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            loaded = json.load(f)
        assert loaded["traceEvents"][0]["name"] == "a"
        assert loaded["displayTimeUnit"] == "ms"

    def test_span_metric_observation(self):
        with telemetry.span("timed", metric="my_seconds", phase="x"):
            pass
        h = telemetry.MetricsRegistry.get_default().histogram("my_seconds")
        assert h.count(phase="x") == 1
        # depth/parent must NOT leak into metric labels
        assert 'depth' not in telemetry.MetricsRegistry.get_default() \
            .to_prometheus()

    def test_disabled_records_nothing(self):
        telemetry.set_enabled(False)
        with telemetry.span("ghost"):
            pass
        telemetry.record_phase("etl_wait", time.perf_counter())
        assert telemetry.chrome_trace()["traceEvents"] == []


# ---------------------------------------------------------------------
# recompilation detector
# ---------------------------------------------------------------------
class TestRecompileDetector:
    def test_stable_shapes_compile_once(self):
        net = _tiny_net()
        x, y = _batch(8)
        for _ in range(3):
            net.fit(x, y)
        c = telemetry.MetricsRegistry.get_default().counter(
            telemetry.JIT_COMPILES)
        assert c.value(site="mln_step") == 1

    def test_induced_retrace_counts_and_times(self):
        """Acceptance: fitting the same network on two distinct batch
        shapes reports >= 2 compiles with nonzero compile time."""
        net = _tiny_net()
        net.fit(*_batch(8))
        net.fit(*_batch(16))
        reg = telemetry.MetricsRegistry.get_default()
        c = reg.counter(telemetry.JIT_COMPILES)
        assert c.value(site="mln_step") >= 2
        assert reg.histogram(telemetry.JIT_COMPILE_SECONDS) \
            .sum(site="mln_step") > 0
        # compile events land in the host trace, with signatures
        evs = [e for e in telemetry.chrome_trace()["traceEvents"]
               if e["name"] == "jit_compile:mln_step"]
        assert len(evs) >= 2
        assert "signature" in evs[0]["args"]

    def test_graph_site_counted(self):
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, InputType, OutputLayer,
        )
        from deeplearning4j_tpu.nn.graph.config import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

        conf = (ComputationGraphConfiguration.graphBuilder()
                .addInputs("in")
                .setInputTypes(InputType.feedForward(3))
                .addLayer("d", DenseLayer(n_out=4, activation="relu"),
                          "in")
                .addLayer("out", OutputLayer(n_out=2,
                                             activation="softmax",
                                             loss="mcxent"), "d")
                .setOutputs("out").build())
        g = ComputationGraph(conf).init()
        g.fit(*_batch(8))
        g.fit(*_batch(12))
        c = telemetry.MetricsRegistry.get_default().counter(
            telemetry.JIT_COMPILES)
        assert c.value(site="cg_step") == 2

    def test_vjp_only_site_uses_signature_probe(self):
        """cg_ext_forward is only ever called under jax.vjp, where the
        executable cache never grows — the signature probe must count
        its compiles anyway."""
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, InputType, OutputLayer,
        )
        from deeplearning4j_tpu.nn.graph.config import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

        conf = (ComputationGraphConfiguration.graphBuilder()
                .addInputs("in")
                .setInputTypes(InputType.feedForward(3))
                .addLayer("out", OutputLayer(n_out=2,
                                             activation="softmax",
                                             loss="mcxent"), "in")
                .setOutputs("out").build())
        g = ComputationGraph(conf).init()
        for n in (4, 4, 8):
            x = np.ones((n, 3), np.float32)
            err = np.ones((n, 2), np.float32)
            g.backpropGradient([x], [err], train=False)
        c = telemetry.MetricsRegistry.get_default().counter(
            telemetry.JIT_COMPILES)
        assert c.value(site="cg_ext_forward") == 2

    def test_storm_warning(self, monkeypatch, caplog):
        monkeypatch.setenv("DL4J_TPU_RECOMPILE_STORM_THRESHOLD", "3")
        fn = telemetry.instrument_jit("storm_site",
                                      jax.jit(lambda x: x + 1))
        with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
            for n in range(1, 5):
                fn(jnp.ones(n))
        msgs = [r.message for r in caplog.records
                if "RECOMPILE STORM" in r.message]
        assert msgs and "storm_site" in msgs[0]

    def test_wrapper_passes_through_lower(self):
        """AOT cost analysis (bench_common.aot_cost_flops) must still
        reach .lower() through the instrumented wrapper."""
        fn = telemetry.instrument_jit("aot", jax.jit(lambda x: x * 2))
        compiled = fn.lower(jnp.ones(4)).compile()
        assert compiled.cost_analysis() is not None

    def test_bench_snapshot_carries_compiles(self):
        net = _tiny_net()
        net.fit(*_batch(4))
        import bench_common

        snap = bench_common.telemetry_snapshot()
        assert snap["jit_compiles_total"] >= 1
        assert snap["per_site"]["mln_step"]["compiles"] >= 1
        assert snap["per_site"]["mln_step"]["compile_seconds"] > 0


# ---------------------------------------------------------------------
# step phases + device memory
# ---------------------------------------------------------------------
class TestStepPhases:
    def test_phase_histogram_from_iterator_fit(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import (
            ListDataSetIterator,
        )

        net = _tiny_net()
        x, y = _batch(8)
        it = ListDataSetIterator([DataSet(x, y)], batch_size=8)
        net.setListeners(_NullListener())
        net.fit(it, epochs=2)
        h = telemetry.MetricsRegistry.get_default().histogram(
            telemetry.STEP_PHASE_SECONDS)
        assert h.count(phase="etl_wait") >= 2
        assert h.count(phase="device_step") >= 2
        assert h.count(phase="listener_host") >= 2
        assert h.sum(phase="device_step") > 0

    def test_device_memory_graceful_on_cpu(self):
        # CPU backend reports no memory_stats -> {} and no crash; the
        # probe result is cached so repeated calls stay cheap
        out = telemetry.sample_device_memory()
        assert out == {} or "bytes_in_use" in out

    def test_explicit_device_bypasses_cached_verdict(self):
        telemetry.sample_device_memory()   # latches False on CPU

        class FakeDevice:
            id = 3

            def memory_stats(self):
                return {"bytes_in_use": 10, "peak_bytes_in_use": 20}

        out = telemetry.sample_device_memory(FakeDevice())
        assert out["bytes_in_use"] == 10
        g = telemetry.MetricsRegistry.get_default().gauge(
            telemetry.DEVICE_PEAK_BYTES)
        assert g.value(device="3") == 20

    def test_force_samples_with_telemetry_disabled(self):
        # StatsListener's memory report must survive
        # DL4J_TPU_TELEMETRY=0: force=True still probes (gauges are
        # left untouched — telemetry is off), plain calls stay no-ops
        class FakeDevice:
            id = 7

            def memory_stats(self):
                return {"bytes_in_use": 5, "peak_bytes_in_use": 9}

        telemetry.set_enabled(False)
        try:
            assert telemetry.sample_device_memory(FakeDevice()) == {}
            out = telemetry.sample_device_memory(FakeDevice(),
                                                 force=True)
            assert out["bytes_in_use"] == 5
            g = telemetry.MetricsRegistry.get_default().gauge(
                telemetry.DEVICE_PEAK_BYTES)
            assert g.value(device="7") == 0.0   # not published
        finally:
            telemetry.set_enabled(True)

    def test_probe_exception_does_not_latch(self):
        class Flaky:
            id = 0
            calls = 0

            def memory_stats(self):
                Flaky.calls += 1
                if Flaky.calls == 1:
                    raise RuntimeError("transient init race")
                return {"bytes_in_use": 1, "peak_bytes_in_use": 2}

        d = Flaky()
        assert telemetry.sample_device_memory(d) == {}
        assert telemetry.sample_device_memory(d)["bytes_in_use"] == 1


class _NullListener:
    def iterationDone(self, model, iteration, epoch):
        pass

    def onEpochEnd(self, model):
        pass


# ---------------------------------------------------------------------
# /metrics + /telemetry endpoints
# ---------------------------------------------------------------------
class TestEndpoints:
    def test_metrics_and_telemetry(self):
        from deeplearning4j_tpu.ui.server import UIServer

        net = _tiny_net()
        net.fit(*_batch(8))
        net.fit(*_batch(16))
        ui = UIServer()   # fresh instance; do not pollute the singleton
        port = ui.start(port=0)
        try:
            base = f"http://127.0.0.1:{port}"
            resp = urllib.request.urlopen(base + "/metrics")
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
            # acceptance: valid Prometheus text with the compile counter
            # and a step-phase histogram
            assert "# TYPE dl4j_tpu_jit_compiles_total counter" in text
            assert 'dl4j_tpu_jit_compiles_total{site="mln_step"} 2' in text
            assert "dl4j_tpu_step_phase_seconds" in text
            for line in text.strip().splitlines():
                if not line.startswith("#"):
                    float(line.rpartition(" ")[2])

            tel = json.loads(urllib.request.urlopen(
                base + "/telemetry").read())
            assert tel["snapshot"]["jit_compiles_total"] >= 2
            assert tel["metrics"]["dl4j_tpu_jit_compiles_total"][
                "kind"] == "counter"
            assert tel["trace_event_count"] >= 1
            assert all("ph" in e for e in tel["trace_events"])
        finally:
            ui.stop()


# ---------------------------------------------------------------------
# listener fixes (satellites)
# ---------------------------------------------------------------------
class _FakeModel:
    def __init__(self, batch=32):
        self._last_batch_size = batch

    def score(self):
        return 0.5


class TestPerformanceListenerFix:
    def test_samples_per_sec_computed(self):
        from deeplearning4j_tpu.optimize.listeners import (
            PerformanceListener,
        )

        lines = []
        l = PerformanceListener(frequency=5, report_batch=True,
                                printer=lines.append)
        m = _FakeModel(batch=32)
        l.iterationDone(m, 1, 0)
        l.iterationDone(m, 6, 0)
        assert not np.isnan(l.samples_per_sec)
        assert l.samples_per_sec == pytest.approx(
            l.batches_per_sec * 32)
        assert "samples/sec" in lines[0]

    def test_report_batch_false_skips(self):
        from deeplearning4j_tpu.optimize.listeners import (
            PerformanceListener,
        )

        lines = []
        l = PerformanceListener(frequency=5, report_batch=False,
                                printer=lines.append)
        m = _FakeModel()
        l.iterationDone(m, 1, 0)
        l.iterationDone(m, 6, 0)
        assert np.isnan(l.samples_per_sec)
        assert "samples/sec" not in lines[0]

    def test_real_fit_populates_batch_size(self):
        from deeplearning4j_tpu.optimize.listeners import (
            PerformanceListener,
        )

        lines = []
        net = _tiny_net()
        net.setListeners(PerformanceListener(frequency=1,
                                             printer=lines.append))
        x, y = _batch(16)
        net.fit(x, y, epochs=3)
        assert net._last_batch_size == 16
        assert any("samples/sec" in s for s in lines)


class TestTimeIterationListenerFix:
    def test_frequency_honored(self):
        from deeplearning4j_tpu.optimize.listeners import (
            TimeIterationListener,
        )

        lines = []
        l = TimeIterationListener(100, printer=lines.append, frequency=2)
        m = _FakeModel()
        for i in range(1, 7):
            l.iterationDone(m, i, 0)
        # first call arms the clock; reports at iterations 2, 4, 6
        assert len(lines) == 3

    def test_rate_uses_elapsed_iterations(self):
        from deeplearning4j_tpu.optimize.listeners import (
            TimeIterationListener,
        )

        lines = []
        l = TimeIterationListener(10_000, printer=lines.append,
                                  frequency=1)
        m = _FakeModel()
        # resumed training: iteration counter starts at 5000 — ETA must
        # come from the 2 iterations we actually saw, not 5002
        l.iterationDone(m, 5000, 0)
        time.sleep(0.02)
        l.iterationDone(m, 5001, 0)
        l.iterationDone(m, 5002, 0)
        assert l._start_iter == 5000
        eta = float(lines[-1].split("ETA ")[1].rstrip("s"))
        # ~0.01s/iter * 5000 remaining ≈ 50s; the old absolute-iteration
        # rate would have claimed under a second
        assert eta > 5


class TestCheckpointListenerFix:
    def test_skips_iteration_zero_and_writes_atomically(self, tmp_path):
        from deeplearning4j_tpu.optimize.listeners import (
            CheckpointListener,
        )

        net = _tiny_net()
        l = CheckpointListener(str(tmp_path), save_every_n_iterations=5,
                               keep_last=2)
        l.iterationDone(net, 0, 0)
        assert l.lastCheckpoint() is None
        assert not list(tmp_path.iterdir())
        l.iterationDone(net, 5, 0)
        path = tmp_path / "checkpoint_iter_5.zip"
        assert path.exists()
        assert not (tmp_path / "checkpoint_iter_5.zip.tmp").exists()
        with zipfile.ZipFile(path) as zf:   # complete, readable archive
            assert "configuration.json" in zf.namelist()

    def test_failed_save_leaves_no_partial(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.optimize.listeners import (
            CheckpointListener,
        )
        from deeplearning4j_tpu.util import model_serializer

        def boom(*a, **k):
            raise RuntimeError("disk full")

        monkeypatch.setattr(model_serializer.ModelSerializer,
                            "writeModel", boom)
        l = CheckpointListener(str(tmp_path), save_every_n_iterations=1)
        with pytest.raises(RuntimeError, match="disk full"):
            l.iterationDone(_FakeModel(), 1, 0)
        assert not list(tmp_path.iterdir())   # no truncated zip, no tmp


class TestCheckNumericsFix:
    def test_single_device_get(self, monkeypatch):
        from deeplearning4j_tpu import profiler as prof

        calls = []
        orig = jax.device_get

        def counting(x):
            calls.append(x)
            return orig(x)

        monkeypatch.setattr(jax, "device_get", counting)
        tree = {"a": jnp.ones(4), "b": jnp.zeros((2, 3)),
                "c": jnp.arange(3),            # int: not fetched
                "d": [jnp.full(2, 1.5)]}
        prof.check_numerics(tree, prof.ProfilerMode.ANY_PANIC)
        assert len(calls) == 1                 # ONE transfer, all leaves
        assert len(calls[0]) == 3              # the floating leaves only

    def test_still_raises_and_reduces(self):
        from deeplearning4j_tpu import profiler as prof

        with pytest.raises(prof.NumericsException, match="NaN"):
            prof.check_numerics(
                [np.ones(3), np.asarray([np.nan])],
                prof.ProfilerMode.NAN_PANIC, "ctx")
        with pytest.raises(prof.NumericsException, match="Inf"):
            prof.check_numerics(np.asarray([np.inf]),
                                prof.ProfilerMode.INF_PANIC)
        # NAN_PANIC ignores Inf; ints ignored entirely
        prof.check_numerics(np.asarray([np.inf]),
                            prof.ProfilerMode.NAN_PANIC)
        prof.check_numerics(np.arange(5), prof.ProfilerMode.ANY_PANIC)

    def test_bfloat16_swept(self):
        from deeplearning4j_tpu import profiler as prof

        bad = jnp.asarray([np.nan], jnp.bfloat16)
        with pytest.raises(prof.NumericsException):
            prof.check_numerics(bad, prof.ProfilerMode.NAN_PANIC)


class TestTelemetryListener:
    def test_bridges_metrics(self):
        from deeplearning4j_tpu.optimize.listeners import (
            TelemetryListener,
        )

        net = _tiny_net()
        net.setListeners(TelemetryListener(frequency=1))
        x, y = _batch(8)
        net.fit(x, y, epochs=3)
        reg = telemetry.MetricsRegistry.get_default()
        assert reg.counter("dl4j_tpu_iterations_total").total() == 3
        assert reg.gauge("dl4j_tpu_score").value() == pytest.approx(
            float(net.score()))

    def test_kill_switch_skips_score_sync(self):
        from deeplearning4j_tpu.optimize.listeners import (
            TelemetryListener,
        )

        class SyncTrap:
            def score(self):
                raise AssertionError(
                    "score() must not sync when telemetry is off")

        telemetry.set_enabled(False)
        l = TelemetryListener(frequency=1)
        l.iterationDone(SyncTrap(), 1, 0)
        l.onEpochEnd(SyncTrap())
        telemetry.set_enabled(True)
        assert telemetry.MetricsRegistry.get_default().counter(
            "dl4j_tpu_iterations_total").total() == 0
