"""Extended layer family tests (reference analogs: ConvolutionLayerTest,
Convolution3DTest, LocallyConnectedLayerTest, CapsNetMNISTTest,
CNNGradientCheckTest — SURVEY.md §4's per-layer grad-check backbone)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common import serde
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn.conf import (
    CapsuleLayer, CapsuleStrengthLayer, CenterLossOutputLayer, Convolution1D,
    Convolution3D, ConvolutionLayer, Cropping1D, Cropping2D, Cropping3D,
    Deconvolution2D, DenseLayer, DepthwiseConvolution2D,
    ElementWiseMultiplicationLayer, GRU, GlobalPoolingLayer, InputType,
    LocallyConnected1D, LocallyConnected2D, LSTM, MaskLayer, MaskZeroLayer,
    NeuralNetConfiguration, OutputLayer, PReLULayer, PrimaryCapsules,
    RepeatVector, RnnOutputLayer, SpaceToBatchLayer, SpaceToDepthLayer,
    Subsampling1DLayer, Subsampling3DLayer, Upsampling1D, Upsampling3D,
    ZeroPadding1DLayer, ZeroPadding3DLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import FrozenLayerWithBackprop


def _fit_and_check(conf, x, y, steps=3):
    """Network inits, fits a few steps, loss decreases or stays finite."""
    net = MultiLayerNetwork(conf).init()
    l0 = float(net.score_on(x, y)) if hasattr(net, "score_on") else None
    for _ in range(steps):
        net.fit(x, y)
    out = net.output(x)
    assert np.all(np.isfinite(np.asarray(out)))
    return net, out


def _build(layers, input_type, updater=None):
    b = (NeuralNetConfiguration.builder().seed(7)
         .updater(updater or Adam(learning_rate=1e-3)).list())
    for l in layers:
        b = b.layer(l)
    return b.setInputType(input_type).build()


class TestConv1DFamily:
    def test_conv1d_stack_shapes_and_training(self):
        conf = _build([
            ZeroPadding1DLayer(pad=(1, 1)),
            Convolution1D(n_out=8, kernel_size=3, activation="relu"),
            Subsampling1DLayer(kernel_size=2, stride=2),
            Upsampling1D(size=2),
            Cropping1D(crop=(1, 1)),
            LocallyConnected1D(n_out=6, kernel_size=3),
            GlobalPoolingLayer(pooling_type="avg"),
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], InputType.recurrent(4, 16))
        # shape walk: 16 -pad-> 18 -conv k3-> 16 -pool-> 8 -up-> 16
        # -crop-> 14 -lc k3-> 12
        assert conf.layers[1].n_in == 4
        assert conf.layers[5].n_in == 8
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(5, 16, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.random.default_rng(1).integers(0, 3, 5)]
        net.fit(x, y)
        out = np.asarray(net.output(x))
        assert out.shape == (5, 3)
        assert np.allclose(out.sum(-1), 1, atol=1e-4)

    def test_conv1d_same_mode_preserves_length(self):
        lay = Convolution1D(n_in=4, n_out=8, kernel_size=3,
                            convolution_mode="Same")
        it = lay.output_type(InputType.recurrent(4, 16))
        assert (it.timeseries_length, it.size) == (16, 8)
        p = lay.init_params(jax.random.key(0), None, jnp.float32)
        out, _ = lay.apply(p, {}, jnp.ones((2, 16, 4)), False, None)
        assert out.shape == (2, 16, 8)


class TestConv2DExtensions:
    def test_deconv_upsamples(self):
        lay = Deconvolution2D(n_in=3, n_out=5, kernel_size=(2, 2),
                              stride=(2, 2), convolution_mode="Same")
        p = lay.init_params(jax.random.key(0), None, jnp.float32)
        out, _ = lay.apply(p, {}, jnp.ones((2, 7, 7, 3)), False, None)
        assert out.shape == (2, 14, 14, 5)
        it = lay.output_type(InputType.convolutional(7, 7, 3))
        assert (it.height, it.width, it.channels) == (14, 14, 5)

    def test_depthwise_channels_multiply(self):
        lay = DepthwiseConvolution2D(n_in=3, depth_multiplier=4,
                                     kernel_size=(3, 3),
                                     convolution_mode="Same")
        p = lay.init_params(jax.random.key(0), None, jnp.float32)
        out, _ = lay.apply(p, {}, jnp.ones((2, 8, 8, 3)), False, None)
        assert out.shape == (2, 8, 8, 12)

    def test_crop_pad_space_ops(self):
        x = jnp.arange(2 * 8 * 8 * 4, dtype=jnp.float32).reshape(2, 8, 8, 4)
        out, _ = Cropping2D(crop=(1, 2, 3, 1)).apply({}, {}, x, False, None)
        assert out.shape == (2, 5, 4, 4)
        out, _ = SpaceToDepthLayer(block_size=2).apply({}, {}, x, False, None)
        assert out.shape == (2, 4, 4, 16)
        out, _ = SpaceToBatchLayer(block_size=2).apply({}, {}, x, False, None)
        assert out.shape == (8, 4, 4, 4)

    def test_locally_connected2d_differs_from_conv(self):
        """LC2D has per-position filters — gradient check via training."""
        conf = _build([
            LocallyConnected2D(n_out=4, kernel_size=(3, 3), stride=(2, 2),
                               activation="relu"),
            DenseLayer(n_out=8, activation="relu"),
            OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], InputType.convolutional(9, 9, 2))
        net = MultiLayerNetwork(conf).init()
        # per-position weights: [outH*outW, kH*kW*C, C_out]
        assert net.params_list[0]["W"].shape == (16, 18, 4)
        x = np.random.default_rng(0).normal(size=(4, 9, 9, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
        s0 = None
        for _ in range(30):
            net.fit(x, y)
            s0 = s0 or net.score()
        assert net.score() < s0


class TestConv3DFamily:
    def test_conv3d_stack(self):
        conf = _build([
            ZeroPadding3DLayer(pad=(1, 1, 1)),
            Convolution3D(n_out=4, kernel_size=(3, 3, 3), activation="relu"),
            Subsampling3DLayer(kernel_size=(2, 2, 2), stride=(2, 2, 2)),
            Upsampling3D(size=2),
            Cropping3D(crop=(1, 1, 1, 1, 1, 1)),
            DenseLayer(n_out=8, activation="relu"),
            OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], InputType.convolutional3D(6, 6, 6, 2))
        assert conf.layers[1].n_in == 2
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(3, 6, 6, 6, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[[0, 1, 0]]
        net.fit(x, y)
        out = np.asarray(net.output(x))
        assert out.shape == (3, 2)
        assert np.all(np.isfinite(out))

    def test_conv3d_vs_reference_numpy(self):
        """Golden check: 1x1x1 kernel conv3d == channel matmul."""
        lay = Convolution3D(n_in=3, n_out=2, kernel_size=(1, 1, 1))
        p = lay.init_params(jax.random.key(3), None, jnp.float32)
        x = jax.random.normal(jax.random.key(4), (2, 4, 4, 4, 3))
        out, _ = lay.apply(p, {}, x, False, None)
        want = np.asarray(x) @ np.asarray(p["W"]).reshape(3, 2) + np.asarray(p["b"])
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


class TestMiscLayers:
    def test_gru_trains_and_steps(self):
        conf = _build([
            GRU(n_out=12),
            RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], InputType.recurrent(5, 10))
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(4, 10, 5)).astype(np.float32)
        y = np.zeros((4, 10, 3), np.float32)
        y[..., 0] = 1
        s0 = None
        for _ in range(20):
            net.fit(x, y)
            s0 = s0 or net.score()
        assert net.score() < s0
        # stateful stepping parity with full-sequence forward
        net.rnnClearPreviousState()
        step_outs = [np.asarray(net.rnnTimeStep(x[:, t:t + 1]))
                     for t in range(10)]
        full = np.asarray(net.output(x))
        np.testing.assert_allclose(np.concatenate(step_outs, 1), full,
                                   atol=1e-4)

    def test_prelu_learns_slope(self):
        lay = PReLULayer()
        p = lay.init_params(jax.random.key(0), InputType.feedForward(4),
                            jnp.float32)
        assert p["alpha"].shape == (4,)
        out, _ = lay.apply({"alpha": jnp.full((4,), 0.5)}, {},
                           jnp.array([[-2.0, -1.0, 1.0, 2.0]]), False, None)
        np.testing.assert_allclose(np.asarray(out)[0], [-1.0, -0.5, 1.0, 2.0])

    def test_elementwise_mult_and_repeat(self):
        lay = ElementWiseMultiplicationLayer()
        p = lay.init_params(jax.random.key(0), InputType.feedForward(3),
                            jnp.float32)
        out, _ = lay.apply({"W": jnp.array([1.0, 2.0, 3.0]),
                            "b": jnp.zeros(3)}, {},
                           jnp.array([[2.0, 2.0, 2.0]]), False, None)
        np.testing.assert_allclose(np.asarray(out)[0], [2.0, 4.0, 6.0])
        rep, _ = RepeatVector(n=4).apply({}, {}, jnp.ones((2, 3)), False, None)
        assert rep.shape == (2, 4, 3)

    def test_mask_zero_layer(self):
        inner = LSTM(n_in=3, n_out=5)
        lay = MaskZeroLayer(layer=inner, mask_value=0.0)
        p = lay.init_params(jax.random.key(0), None, jnp.float32)
        x = jnp.ones((2, 6, 3)).at[:, 3:].set(0.0)  # last 3 steps masked
        out, _ = lay.apply(p, {}, x, False, None)
        assert np.all(np.asarray(out)[:, 3:] == 0)
        assert np.any(np.asarray(out)[:, :3] != 0)
        # MaskLayer passes through
        m, _ = MaskLayer().apply({}, {}, x, False, None)
        np.testing.assert_allclose(np.asarray(m), np.asarray(x))

    def test_frozen_with_backprop_params_fixed(self):
        conf = _build([
            FrozenLayerWithBackprop(layer=DenseLayer(n_out=8,
                                                     activation="relu")),
            OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], InputType.feedForward(4), updater=Sgd(learning_rate=0.1))
        net = MultiLayerNetwork(conf).init()
        w0 = np.asarray(net.params_list[0]["W"]).copy()
        x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[[0, 1] * 4]
        for _ in range(5):
            net.fit(x, y)
        np.testing.assert_allclose(np.asarray(net.params_list[0]["W"]), w0)
        # output layer DID move
        assert not np.allclose(np.asarray(net.params_list[1]["W"]),
                               np.zeros_like(net.params_list[1]["W"]))

    def test_center_loss_output_layer(self):
        conf = _build([
            DenseLayer(n_out=6, activation="relu"),
            CenterLossOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent", lambda_=0.01),
        ], InputType.feedForward(4))
        net = MultiLayerNetwork(conf).init()
        assert net.params_list[1]["centers"].shape == (3, 6)
        x = np.random.default_rng(0).normal(size=(9, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.arange(9) % 3]
        s0 = None
        for _ in range(25):
            net.fit(x, y)
            s0 = s0 or net.score()
        assert net.score() < s0
        # centers moved toward features (trained via the shared updater)
        assert np.any(np.asarray(net.params_list[1]["centers"]) != 0)


class TestCapsNet:
    def test_capsnet_mnist_style(self):
        """reference: CapsNetMNISTTest — primary caps -> routing -> strength."""
        conf = _build([
            ConvolutionLayer(n_out=8, kernel_size=(5, 5), activation="relu"),
            PrimaryCapsules(capsule_dimensions=4, channels=2,
                            kernel_size=(5, 5), stride=(2, 2)),
            CapsuleLayer(capsules=3, capsule_dimensions=6, routings=2),
            CapsuleStrengthLayer(),
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], InputType.convolutional(20, 20, 1))
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(4, 20, 20, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        net.fit(x, y)
        out = np.asarray(net.output(x))
        assert out.shape == (4, 3)
        assert np.all(np.isfinite(out))

    def test_squash_norm_below_one(self):
        from deeplearning4j_tpu.nn.conf.layers_extra import _squash
        v = _squash(jnp.array([[10.0, 0.0, 0.0]]))
        assert 0.97 < float(jnp.linalg.norm(v)) < 1.0
        tiny = _squash(jnp.array([[1e-3, 0.0, 0.0]]))
        assert float(jnp.linalg.norm(tiny)) < 1e-3

    def test_capsule_routing_is_convex_combination(self):
        lay = CapsuleLayer(capsules=2, capsule_dimensions=3, routings=3)
        it = InputType.recurrent(4, 5)
        p = lay.init_params(jax.random.key(0), it, jnp.float32)
        x = jax.random.normal(jax.random.key(1), (2, 5, 4))
        out, _ = lay.apply(p, {}, x, False, None)
        assert out.shape == (2, 2, 3)
        # squashed outputs have norm < 1
        assert np.all(np.linalg.norm(np.asarray(out), axis=-1) < 1.0)


class TestSerdeRoundTrip:
    def test_all_new_layers_round_trip(self):
        layers = [
            GRU(n_in=3, n_out=4), Convolution1D(n_in=2, n_out=3),
            Subsampling1DLayer(), Upsampling1D(), Cropping1D(crop=(1, 2)),
            ZeroPadding1DLayer(), Deconvolution2D(n_in=2, n_out=3),
            DepthwiseConvolution2D(n_in=2, depth_multiplier=2),
            Cropping2D(crop=(1, 1, 2, 2)), SpaceToDepthLayer(),
            SpaceToBatchLayer(), Convolution3D(n_in=1, n_out=2),
            Subsampling3DLayer(), Upsampling3D(),
            Cropping3D(crop=(1, 1, 1, 1, 1, 1)), ZeroPadding3DLayer(),
            LocallyConnected1D(n_in=2, n_out=3),
            LocallyConnected2D(n_in=2, n_out=3), PReLULayer(n_in=4),
            ElementWiseMultiplicationLayer(n_in=3, n_out=3),
            RepeatVector(n=5), MaskLayer(),
            MaskZeroLayer(layer=LSTM(n_in=2, n_out=3), mask_value=0.0),
            CenterLossOutputLayer(n_in=4, n_out=2),
            PrimaryCapsules(n_in=2), CapsuleLayer(), CapsuleStrengthLayer(),
            FrozenLayerWithBackprop(layer=DenseLayer(n_in=2, n_out=3)),
        ]
        for lay in layers:
            j = serde.to_json(lay)
            back = serde.from_json(j)
            assert serde.to_json(back) == j, type(lay).__name__


class TestGradCheck:
    """Finite-difference gradient checks for the trickiest new layers
    (reference: CNNGradientCheckTest / GradCheckUtil epsilon method)."""

    @pytest.mark.parametrize("make_layer,shape", [
        (lambda: LocallyConnected2D(n_in=2, n_out=3, kernel_size=(2, 2)),
         (2, 4, 4, 2)),
        (lambda: CapsuleLayer(capsules=2, capsule_dimensions=3, routings=2),
         (2, 4, 3)),
        (lambda: Convolution3D(n_in=2, n_out=2, kernel_size=(2, 2, 2)),
         (2, 3, 3, 3, 2)),
        (lambda: GRU(n_in=3, n_out=4), (2, 5, 3)),
    ])
    def test_fd_gradients(self, make_layer, shape):
        lay = make_layer()
        if isinstance(lay, CapsuleLayer):
            it = InputType.recurrent(shape[-1], shape[1])
        elif isinstance(lay, GRU):
            it = InputType.recurrent(shape[-1], shape[1])
        elif len(shape) == 5:
            it = InputType.convolutional3D(*shape[1:])
        else:
            it = InputType.convolutional(*shape[1:])
        params = lay.init_params(jax.random.key(0), it, jnp.float32)
        x = jax.random.normal(jax.random.key(1), shape)

        def loss(p):
            out, _ = lay.apply(p, {}, x, False, None)
            return jnp.sum(out * out)

        g = jax.grad(loss)(params)
        flat, treedef = jax.tree_util.tree_flatten(params)
        gflat = jax.tree_util.tree_leaves(g)
        eps = 1e-3
        rng = np.random.default_rng(0)
        for arr, garr in zip(flat, gflat):
            a = np.asarray(arr, np.float64)
            ga = np.asarray(garr)
            # probe 3 random coordinates per param tensor
            for _ in range(3):
                idx = tuple(rng.integers(0, s) for s in a.shape)
                ap, am = a.copy(), a.copy()
                ap[idx] += eps
                am[idx] -= eps

                def rebuild(v):
                    newflat = [jnp.asarray(v if arr2 is arr else
                                           np.asarray(arr2, np.float64))
                               for arr2 in flat]
                    return jax.tree_util.tree_unflatten(treedef, newflat)

                fd = (float(loss(rebuild(ap))) - float(loss(rebuild(am)))) \
                    / (2 * eps)
                assert abs(fd - float(ga[idx])) < 5e-2 * max(1.0, abs(fd)), \
                    f"{type(lay).__name__} grad mismatch at {idx}"


class TestGravesBidirectionalAndEnvironment:
    def test_graves_bidirectional_lstm(self):
        from deeplearning4j_tpu.nn.conf import GravesBidirectionalLSTM
        conf = _build([
            GravesBidirectionalLSTM(n_out=6),
            RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], InputType.recurrent(3, 8))
        assert conf.layers[0].n_in == 3
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(4, 8, 3)).astype(np.float32)
        y = np.zeros((4, 8, 2), np.float32)
        y[..., 0] = 1
        net.fit(x, y)
        out = np.asarray(net.output(x))
        assert out.shape == (4, 8, 2)
        # forward/backward params both present (CONCAT doubles width)
        assert set(net.params_list[0]) == {"fw", "bw"}

    def test_environment_singleton_and_info(self):
        from deeplearning4j_tpu.common.environment import (
            Environment, Nd4jEnvironment,
        )
        env = Environment.getInstance()
        assert env is Environment.getInstance()
        env.setVerbose(True)
        assert env.isVerbose()
        env.setVerbose(False)
        env.setDebug(True)
        assert env.isVerbose() and env.isDebug()  # debug implies verbose
        env.setDebug(False)
        assert env.maxThreads() >= 1
        info = Nd4jEnvironment.getEnvironmentInformation()
        assert info["backend"] == "cpu" and info["device.count"] == 8
        assert "jax.version" in info


class TestDeconvolution3D:
    def test_same_mode_upsamples(self):
        from deeplearning4j_tpu.nn.conf import Deconvolution3D

        lay = Deconvolution3D(n_in=3, n_out=5, kernel_size=(2, 2, 2),
                              stride=(2, 2, 2), convolution_mode="Same")
        p = lay.init_params(jax.random.key(0), None, jnp.float32)
        out, _ = lay.apply(p, {}, jnp.ones((2, 4, 5, 6, 3)), False, None)
        assert out.shape == (2, 8, 10, 12, 5)
        it = lay.output_type(InputType.convolutional3D(4, 5, 6, 3))
        assert (it.depth, it.height, it.width, it.channels) == (8, 10, 12, 5)

    def test_truncate_mode_matches_torch(self):
        """Value golden vs torch conv_transpose3d: ours is zero-insert +
        correlation (DHWIO), torch is the conv gradient, so torch's
        weight maps to flip_spatial(permute(w,(2,3,4,0,1)))."""
        import torch
        from deeplearning4j_tpu.nn.conf import Deconvolution3D

        rs = np.random.RandomState(11)
        x = rs.randn(2, 3, 4, 5, 2).astype(np.float32)       # NDHWC
        wt = rs.randn(2, 4, 3, 3, 3).astype(np.float32)      # [Cin,Cout,k..]
        want = torch.nn.functional.conv_transpose3d(
            torch.tensor(x.transpose(0, 4, 1, 2, 3)), torch.tensor(wt),
            stride=(2, 1, 2), padding=1).numpy().transpose(0, 2, 3, 4, 1)

        lay = Deconvolution3D(n_in=2, n_out=4, kernel_size=(3, 3, 3),
                              stride=(2, 1, 2), padding=(1, 1, 1),
                              convolution_mode="Truncate", has_bias=False)
        w = np.flip(wt.transpose(2, 3, 4, 0, 1), (0, 1, 2)).copy()
        out, _ = lay.apply({"W": jnp.asarray(w)}, {}, jnp.asarray(x),
                           False, None)
        assert out.shape == want.shape
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                                   atol=1e-4)

    def test_trains_in_network(self):
        from deeplearning4j_tpu.nn.conf import Convolution3D, Deconvolution3D

        conf = _build([
            Convolution3D(n_out=4, kernel_size=(2, 2, 2), stride=(2, 2, 2),
                          convolution_mode="Same", activation="relu"),
            Deconvolution3D(n_out=2, kernel_size=(2, 2, 2), stride=(2, 2, 2),
                            convolution_mode="Same", activation="relu"),
            GlobalPoolingLayer(pooling_type="avg"),
            OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], InputType.convolutional3D(4, 4, 4, 1))
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(8, 4, 4, 4, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.arange(8) % 2]
        net.fit(x, y)
        s0 = net.score()
        for _ in range(20):
            net.fit(x, y)
        assert net.score() < s0


class TestLambdaLayer:
    """LambdaLayer (reference: SameDiffLambdaLayer — user-defined
    stateless computation inside the compiled step)."""

    def test_applies_function_and_trains_through_it(self):
        from deeplearning4j_tpu.nn.conf import LambdaLayer

        conf = _build([
            DenseLayer(n_out=8, activation="identity"),
            LambdaLayer(fn=lambda x: jnp.tanh(x) * 2.0),
            OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], InputType.feedForward(4))
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        x = rng.randn(32, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        net.fit(x, y)
        s0 = net.score()
        for _ in range(40):
            net.fit(x, y)
        assert net.score() < s0
        # forward value matches the function applied to layer-0 output
        acts = net.feedForward(x)
        np.testing.assert_allclose(
            np.asarray(acts[2].toNumpy()),
            np.tanh(np.asarray(acts[1].toNumpy())) * 2.0, rtol=1e-5,
            atol=1e-6)

    def test_missing_fn_raises(self):
        from deeplearning4j_tpu.nn.conf import LambdaLayer

        with pytest.raises(ValueError, match="fn"):
            LambdaLayer().apply({}, {}, jnp.ones((2, 3)), False, None)
