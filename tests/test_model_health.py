"""In-step model-health monitoring (profiler/model_health.py).

Covers the ISSUE 5 acceptance surface: per-layer grad/update stats
threaded through the jitted train step (golden-tested against an
explicit jax.grad reference), NaN provenance (chaos-injected and
param-poisoned), loss-scale awareness, the one-extra-compile /
single-transfer cost contract, off-mode bit-equality, the
StatsListener fast path, MFU, and the /trace + /telemetry endpoints.
"""

import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, LSTM, NeuralNetConfiguration, OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.profiler import HealthMonitor, model_health, telemetry


RS = np.random.RandomState(0)
X = RS.randn(16, 4).astype(np.float32)
Y = np.eye(2, dtype=np.float32)[RS.randint(0, 2, 16)]


def _mln(seed=3, layers=2):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
         .list())
    for _ in range(layers - 1):
        b = b.layer(DenseLayer(n_out=8, activation="tanh"))
    conf = (b.layer(OutputLayer(n_out=2, activation="softmax",
                                loss="mcxent"))
            .setInputType(InputType.feedForward(4)).build())
    return MultiLayerNetwork(conf).init()


def _cg(seed=3):
    conf = (ComputationGraphConfiguration.graphBuilder()
            .seed(seed).updater(Adam(1e-2))
            .addInputs("in")
            .addLayer("dense", DenseLayer(n_out=8, activation="tanh"),
                      "in")
            .addLayer("out", OutputLayer(n_out=2, activation="softmax",
                                         loss="mcxent"), "dense")
            .setOutputs("out")
            .setInputTypes(InputType.feedForward(4)).build())
    return ComputationGraph(conf).init()


def _leaves(net):
    return jax.tree_util.tree_leaves((net.params_list, net.opt_states))


class TestGradNormGolden:
    def test_grad_norms_match_explicit_jax_grad(self):
        """The in-step grad norms must equal an explicit jax.grad of
        the same loss at the same (pre-step) params — the no-second-
        backward path computes the SAME gradients, not approximations."""
        net = _mln()
        pre_params = jax.tree_util.tree_map(jnp.copy, net.params_list)
        pre_states = jax.tree_util.tree_map(jnp.copy, net.states_list)
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        net.fit(X, Y)
        got = hm.last["grad_norms"]

        ref_grads = jax.grad(
            lambda pl: net._loss(pl, pre_states, jnp.asarray(X),
                                 jnp.asarray(Y), None, None)[0])(pre_params)
        for i, g in enumerate(ref_grads):
            ref = float(jnp.sqrt(sum(
                jnp.sum(jnp.square(l.astype(jnp.float32)))
                for l in jax.tree_util.tree_leaves(g))))
            name = model_health.layer_names(net)[i]
            assert got[name] == pytest.approx(ref, rel=1e-5), name

    def test_update_ratio_matches_sgd_closed_form(self):
        """With plain SGD (no momentum), update = lr * grad, so
        update_ratio == lr * ||grad|| / ||new_param|| exactly."""
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(4)).build())
        net = MultiLayerNetwork(conf).init()
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        net.fit(X, Y)
        s = hm.last
        for name in s["grad_norms"]:
            expect = 0.1 * s["grad_norms"][name] / s["param_norms"][name]
            assert s["update_ratios"][name] == pytest.approx(
                expect, rel=1e-4)


class TestCostContract:
    def test_single_transfer_per_sampled_step(self):
        net = _mln()
        hm = HealthMonitor(frequency=2)
        net.setHealthMonitor(hm)
        for _ in range(6):
            net.fit(X, Y)
        assert hm.fetches == 3   # one device_get per sampled step

    def test_one_extra_compile_per_site_and_off_mode_reuse(self):
        reg = telemetry.MetricsRegistry.get_default()
        compiles = lambda: reg.counter(telemetry.JIT_COMPILES).value(
            site="mln_step")
        net = _mln(seed=7)
        c0 = compiles()
        net.fit(X, Y)
        assert compiles() - c0 == 1          # legacy executable
        net.setHealthMonitor(HealthMonitor(frequency=2))
        net.fit(X, Y)
        assert compiles() - c0 == 2          # exactly ONE extra compile
        net.fit(X, Y)
        assert compiles() - c0 == 2          # monitored executable cached
        net.setHealthMonitor(None)
        net.fit(X, Y)
        assert compiles() - c0 == 2          # legacy executable reused

    def test_off_mode_bit_identical_and_no_second_backward(self):
        a = _mln(seed=11)
        for _ in range(5):
            a.fit(X, Y)
        # attach-then-detach must land back on the exact legacy step
        b = _mln(seed=11)
        b.setHealthMonitor(HealthMonitor(frequency=2))
        b.setHealthMonitor(None)
        for _ in range(5):
            b.fit(X, Y)
        for la, lb in zip(_leaves(a), _leaves(b)):
            assert np.array_equal(np.asarray(la), np.asarray(lb))

    def test_monitored_run_is_numerically_equivalent(self):
        """Monitoring ON adds observers only: same loss/grads/updates
        to float tolerance (XLA may re-fuse, so bitwise equality is
        only guaranteed for monitoring OFF — docs/OBSERVABILITY.md)."""
        a = _mln(seed=13)
        b = _mln(seed=13)
        b.setHealthMonitor(HealthMonitor(frequency=3))
        for _ in range(6):
            a.fit(X, Y)
            b.fit(X, Y)
        for la, lb in zip(jax.tree_util.tree_leaves(a.params_list),
                          jax.tree_util.tree_leaves(b.params_list)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-5, atol=1e-6)


class TestNanProvenance:
    def test_poisoned_layer_is_named(self):
        net = _mln(layers=3)   # 0:Dense 1:Dense 2:Output
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        net.params_list[1]["W"] = \
            net.params_list[1]["W"].at[0, 0].set(jnp.nan)
        net.fit(X, Y)
        assert hm.last["nonfinite_first_layer"] == 1
        assert hm.last["nonfinite_layer_name"] == "1:DenseLayer"
        reg = telemetry.MetricsRegistry.get_default()
        assert reg.gauge(telemetry.NONFINITE_FIRST_LAYER).value(
            site="mln") == 1

    def test_nan_input_points_at_layer_zero(self):
        net = _mln()
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        xb = X.copy()
        xb[0, 0] = np.nan
        net.fit(xb, Y)
        assert hm.last["nonfinite_first_layer"] == 0
        assert hm.nonfinite_label() == "0:DenseLayer"

    def test_clean_run_reports_minus_one(self):
        net = _mln()
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        net.fit(X, Y)
        assert hm.last["nonfinite_first_layer"] == -1
        assert hm.last["nonfinite_layer_name"] is None
        assert hm.nonfinite_label() is None

    def test_chaos_nan_batch_labels_divergence_rollback(self):
        """End to end: chaos injects a NaN batch, the divergence guard
        rolls back, and the rollback telemetry event carries the layer
        label the HealthMonitor attributed (a NaN INPUT reads layer 0)."""
        from deeplearning4j_tpu.datasets import (
            ArrayDataSetIterator, DataSet,
        )
        from deeplearning4j_tpu.profiler.chaos import (
            ChaosConfig, installed,
        )
        from deeplearning4j_tpu.util import FaultTolerance

        reg = telemetry.MetricsRegistry.get_default()
        label_kw = {"nonfinite_layer": "0:DenseLayer"}
        before = reg.counter(telemetry.FT_ROLLBACKS).value(**label_kw)
        net = _mln(seed=17)
        hm = HealthMonitor(frequency=4)
        net.setHealthMonitor(hm)
        ft = FaultTolerance(divergence_window=8, snapshot_every=2)
        with installed(ChaosConfig(nan_steps=(3,))):
            net.fit(ArrayDataSetIterator(X, Y, 8), epochs=3,
                    fault_tolerance=ft)
        after = reg.counter(telemetry.FT_ROLLBACKS).value(**label_kw)
        assert after - before >= 1
        assert np.isfinite(net.score(DataSet(X, Y)))

    def test_handled_f16_overflow_not_misreported(self):
        """A mixed_float16 overflow the loss-scale engine handled
        (step skipped, scale halved) must read CLEAN — the raw layer
        stays visible under handled_overflow_layer for debugging."""
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Adam(1e-2)).precision("mixed_float16").list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(4)).build())
        net = MultiLayerNetwork(conf).init()
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        # the huge initial scale (2^15) overflows f16 on the first
        # step for a loss this size only when grads are large; force
        # an overflow by inflating a weight (finite, but f16-overflow
        # scale): the engine must catch it as a handled overflow
        net.params_list[0]["W"] = net.params_list[0]["W"] * 1e4
        net.fit(X, Y)
        skipped = int(np.asarray(
            net._loss_scale_state["skipped_steps"]))
        if skipped:   # engine handled it -> provenance must stay clean
            assert hm.last["handled_overflow"]
            assert hm.last["nonfinite_first_layer"] == -1
            assert hm.nonfinite_label() is None
        else:         # nothing overflowed on this backend: still clean
            assert hm.last["nonfinite_first_layer"] == -1


class TestAllStacks:
    def test_computation_graph(self):
        cg = _cg()
        hm = HealthMonitor(frequency=1)
        cg.setHealthMonitor(hm)
        cg.fit(X, Y)
        assert set(hm.last["grad_norms"]) == {"dense", "out"}
        assert hm.last["nonfinite_first_layer"] == -1
        reg = telemetry.MetricsRegistry.get_default()
        assert reg.gauge(telemetry.LAYER_GRAD_NORM).value(
            layer="dense", site="cg") > 0

    def test_sharded_trainer_sharing(self):
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        net = _mln(seed=3)
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        tr = ShardedTrainer(net, mode="sharing")
        tr.fit(X, Y)
        sharded_norms = dict(hm.last["grad_norms"])

        # mesh-reduced norms == single-device norms (GSPMD psum)
        ref = _mln(seed=3)
        hm2 = HealthMonitor(frequency=1)
        ref.setHealthMonitor(hm2)
        ref.fit(X, Y)
        for name, v in hm2.last["grad_norms"].items():
            assert sharded_norms[name] == pytest.approx(v, rel=1e-4)

    def test_sharded_toggle_caches_both_executables(self):
        """attach -> detach -> attach on a live 'sharing' trainer must
        reuse the two cached step executables, not retrace per toggle."""
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        reg = telemetry.MetricsRegistry.get_default()
        site = "parallel_sharing_step"
        compiles = lambda: reg.counter(telemetry.JIT_COMPILES).value(
            site=site)
        net = _mln(seed=29)
        tr = ShardedTrainer(net, mode="sharing")
        c0 = compiles()
        tr.fit(X, Y)                              # legacy executable
        net.setHealthMonitor(HealthMonitor(frequency=1))
        tr.fit(X, Y)                              # monitored executable
        assert compiles() - c0 == 2
        net.setHealthMonitor(None)
        tr.fit(X, Y)
        net.setHealthMonitor(HealthMonitor(frequency=1))
        tr.fit(X, Y)
        assert compiles() - c0 == 2               # both cached, no retrace

    def test_flops_capture_skips_non_step_sites(self):
        """A HealthMonitor must not tax compiles at sites MFU never
        reads (forwards, eval) with the capture trace."""
        hm = HealthMonitor(frequency=1)   # keep one provably alive
        assert model_health.flops_capture_enabled()
        assert model_health.wants_flops("mln_step")
        assert model_health.wants_flops("cg_step")
        assert not model_health.wants_flops("mln_forward")
        assert not model_health.wants_flops("cg_forward")
        del hm   # liveness gating itself is GC-timing-dependent:
        # wants_flops goes False only once the LAST monitor anywhere
        # in the process is collected, so no negative assertion here

    def test_sharded_trainer_other_modes_warn_and_skip(self, caplog):
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        net = _mln(seed=3)
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        tr = ShardedTrainer(net, mode="averaging")
        with caplog.at_level("WARNING", logger="deeplearning4j_tpu"):
            tr.fit(X, Y)
        assert any("does not support the HealthMonitor" in r.message
                   for r in caplog.records)
        assert hm.last is None   # nothing sampled, nothing crashed

    def test_reattach_to_different_model_refreshes_labels(self):
        """A monitor moved to a model with a different layer set must
        relabel, not index the new health tree with the old names."""
        hm = HealthMonitor(frequency=1)
        big = _mln(seed=3, layers=3)
        big.setHealthMonitor(hm)
        big.fit(X, Y)
        assert len(hm.last["grad_norms"]) == 3
        big.setHealthMonitor(None)

        small = _mln(seed=4, layers=2)
        small.setHealthMonitor(hm)
        small.fit(X, Y)   # stale 3-name list would IndexError here
        assert set(hm.last["grad_norms"]) == {"0:DenseLayer",
                                              "1:OutputLayer"}

    def test_stale_sample_refreshed_for_listener(self):
        """latest() serves ``last`` when the fit loop sampled this
        step, and fetches the current step itself when the monitor's
        cadence is coarser — a report never carries stale stats."""
        net = _mln()
        hm = HealthMonitor(frequency=100)   # never fires in 3 steps
        net.setHealthMonitor(hm)
        for _ in range(3):
            net.fit(X, Y)
        assert hm.last is None
        cur = hm.latest()
        assert cur is not None and cur is hm.last
        assert cur["grad_norms"]["0:DenseLayer"] > 0
        assert hm.latest() is cur   # fresh sample reused, no refetch

    def test_tbptt_segments_report(self):
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Adam(1e-2)).list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .setInputType(InputType.recurrent(4))
                .tBPTTLength(5).build())
        net = MultiLayerNetwork(conf).init()
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        rs = np.random.RandomState(1)
        xs = rs.randn(4, 12, 4).astype(np.float32)
        ys = np.eye(2, dtype=np.float32)[rs.randint(0, 2, (4, 12))]
        net.fit(xs, ys)
        assert hm.fetches == 3   # ceil(12/5) segments, frequency=1
        assert hm.last["grad_norms"]["0:LSTM"] > 0


class TestMfu:
    def test_mfu_populated_with_peak_entry(self):
        from deeplearning4j_tpu.profiler import flops as flops_mod

        kind = jax.devices()[0].device_kind
        had = kind in flops_mod.PEAK_FLOPS
        if not had:
            flops_mod.PEAK_FLOPS[kind] = {"bf16": 1e12, "f32": 1e12}
        try:
            net = _mln(seed=19)
            hm = HealthMonitor(frequency=2)
            net.setHealthMonitor(hm)
            for _ in range(6):
                net.fit(X, Y)
            # MFU needs a previous sample as the wall-clock anchor, so
            # it appears from the second sample onward
            assert hm.last.get("mfu") is not None
            assert hm.last["mfu"] > 0
            reg = telemetry.MetricsRegistry.get_default()
            assert reg.gauge(telemetry.MFU).value(site="mln") > 0
            assert model_health.site_flops("mln_step") > 0
        finally:
            if not had:
                flops_mod.PEAK_FLOPS.pop(kind, None)

    def test_mfu_omitted_without_peak_entry(self):
        from deeplearning4j_tpu.profiler import flops as flops_mod

        kind = jax.devices()[0].device_kind
        assert kind not in flops_mod.PEAK_FLOPS, \
            "test assumes the CPU backend has no PEAK_FLOPS entry"
        net = _mln(seed=23)
        hm = HealthMonitor(frequency=2)
        net.setHealthMonitor(hm)
        for _ in range(6):
            net.fit(X, Y)
        assert "mfu" not in hm.last   # warned + omitted, never wrong

    def test_mfu_numerator_exact_with_multiple_executables(self):
        """Ragged batches / shape buckets keep several executables
        with different FLOPs live at one jit site; each dispatch must
        charge its OWN executable's FLOPs (latest-compile-wins would
        make every MFU sample silently wrong)."""
        net = _mln(seed=31)
        hm = HealthMonitor(frequency=1)
        net.setHealthMonitor(hm)
        net.fit(X, Y)                 # compile + run executable A (16)
        f_a = model_health.site_flops("mln_step")
        assert f_a and f_a > 0
        d0 = model_health.dispatched_flops("mln_step")
        net.fit(X[:8], Y[:8])         # compile + run executable B (8)
        f_b = model_health.site_flops("mln_step")
        assert f_b != f_a             # genuinely different cost
        for _ in range(2):            # back on executable A
            net.fit(X, Y)
        delta = model_health.dispatched_flops("mln_step") - d0
        assert delta == pytest.approx(f_b + 2 * f_a, rel=1e-6)

    def test_bench_common_reexports_peak_flops(self):
        import bench_common

        from deeplearning4j_tpu.profiler import flops as flops_mod

        assert bench_common.PEAK_FLOPS is flops_mod.PEAK_FLOPS
        assert bench_common.peak_flops is flops_mod.peak_flops


class TestStatsListenerFastPath:
    def test_gradient_and_update_reports_without_second_backward(self):
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener
        from deeplearning4j_tpu.ui.stats import TYPE_ID

        net = _mln()
        net.setHealthMonitor(HealthMonitor(frequency=1))
        st = InMemoryStatsStorage()
        lst = StatsListener(st, session_id="mh1", worker_id="w",
                            collect_gradients=True, collect_updates=True)
        net.setListeners(lst)
        for _ in range(3):
            net.fit(X, Y)
        ups = st.getAllUpdatesAfter("mh1", TYPE_ID, "w", 0.0)
        last = ups[-1]
        assert last["gradient_stats"]["0:DenseLayer"]["l2_norm"] > 0
        assert "update_ratio" in last["update_stats"]["0:DenseLayer"]
        assert "model_health" in last
        # the fast path: no recompute closure, no host param copy
        assert lst._grads_fn is None
        assert lst._prev_params is None

    def test_explicit_histograms_fallback_still_works(self):
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener
        from deeplearning4j_tpu.ui.stats import TYPE_ID

        net = _mln()
        net.setHealthMonitor(HealthMonitor(frequency=1))
        st = InMemoryStatsStorage()
        lst = StatsListener(st, session_id="mh2", worker_id="w",
                            collect_gradients=True,
                            collect_gradient_histograms=True)
        net.setListeners(lst)
        net.fit(X, Y)
        last = st.getAllUpdatesAfter("mh2", TYPE_ID, "w", 0.0)[-1]
        assert len(last["gradient_stats"]["0_W"]["hist"]) == 20
        assert lst._grads_fn is not None   # the documented-cost opt-in

    def test_update_histograms_explicit_fallback(self):
        """collect_update_histograms=True keeps the per-leaf delta
        summaries (the dashboard's update-histogram panel) even when a
        monitor offers in-step ratios."""
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener
        from deeplearning4j_tpu.ui.stats import TYPE_ID

        net = _mln()
        net.setHealthMonitor(HealthMonitor(frequency=1))
        st = InMemoryStatsStorage()
        lst = StatsListener(st, session_id="mh5", worker_id="w",
                            collect_updates=True,
                            collect_update_histograms=True)
        net.setListeners(lst)
        for _ in range(2):
            net.fit(X, Y)
        last = st.getAllUpdatesAfter("mh5", TYPE_ID, "w", 0.0)[-1]
        assert len(last["update_stats"]["0_W"]["hist"]) == 20
        assert lst._prev_params is not None   # the documented-cost path

    def test_masked_batches_covered(self):
        """Masked batches were silently skipped by the recompute path;
        now both the fast path and the fallback report them."""
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener
        from deeplearning4j_tpu.ui.stats import TYPE_ID

        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Adam(1e-2)).list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .setInputType(InputType.recurrent(4)).build())
        rs = np.random.RandomState(1)
        xs = rs.randn(4, 6, 4).astype(np.float32)
        ys = np.eye(2, dtype=np.float32)[rs.randint(0, 2, (4, 6))]
        mask = np.ones((4, 6), np.float32)
        mask[:, 4:] = 0.0
        ds = DataSet(xs, ys, labels_mask=mask)

        # fallback (no monitor): recomputes WITH the mask now
        net = MultiLayerNetwork(conf).init()
        st = InMemoryStatsStorage()
        lst = StatsListener(st, session_id="mh3", worker_id="w",
                            collect_gradients=True)
        net.setListeners(lst)
        net.fit(ds)
        last = st.getAllUpdatesAfter("mh3", TYPE_ID, "w", 0.0)[-1]
        assert "gradient_stats" in last

        # fast path (monitor): in-step stats carry mask semantics
        net2 = MultiLayerNetwork(conf).init()
        net2.setHealthMonitor(HealthMonitor(frequency=1))
        st2 = InMemoryStatsStorage()
        lst2 = StatsListener(st2, session_id="mh4", worker_id="w",
                             collect_gradients=True)
        net2.setListeners(lst2)
        net2.fit(ds)
        last2 = st2.getAllUpdatesAfter("mh4", TYPE_ID, "w", 0.0)[-1]
        assert last2["gradient_stats"]["0:LSTM"]["l2_norm"] > 0
        assert lst2._grads_fn is None


class TestEndpoints:
    def test_trace_download_and_health_in_telemetry_json(self):
        from deeplearning4j_tpu.ui import UIServer

        net = _mln()
        net.setHealthMonitor(HealthMonitor(frequency=1))
        net.fit(X, Y)
        ui = UIServer()
        port = ui.start(0)
        try:
            base = f"http://127.0.0.1:{port}"
            resp = urllib.request.urlopen(base + "/trace")
            assert "attachment" in resp.headers["Content-Disposition"]
            trace = json.loads(resp.read())
            assert "traceEvents" in trace
            tel = json.loads(urllib.request.urlopen(
                base + "/telemetry").read())
            assert "layer_grad_norm" in tel["model_health"]
            assert "nonfinite_first_layer" in tel["model_health"]
        finally:
            ui.stop()

    def test_nonfinite_values_scrubbed_from_json(self):
        """NaN grad norms ride the JSON endpoints exactly when the
        dashboard must keep working — python's json emits bare
        NaN/Infinity tokens browsers reject, so they must be scrubbed
        to null."""
        from deeplearning4j_tpu.ui import (
            InMemoryStatsStorage, StatsListener, UIServer,
        )

        net = _mln()
        net.setHealthMonitor(HealthMonitor(frequency=1))
        st = InMemoryStatsStorage()
        net.setListeners(StatsListener(st, session_id="mhnan",
                                       worker_id="w",
                                       collect_gradients=True))
        net.params_list[0]["W"] = \
            net.params_list[0]["W"].at[0, 0].set(jnp.nan)
        net.fit(X, Y)
        ui = UIServer()
        ui.attach(st)
        port = ui.start(0)
        strict = dict(parse_constant=lambda c: (_ for _ in ()).throw(
            ValueError(f"bare {c} token in JSON")))
        try:
            base = f"http://127.0.0.1:{port}"
            body = urllib.request.urlopen(base + "/train/mhnan/model").read()
            m = json.loads(body.decode(), **strict)   # browser-strict
            stats = m["latest"]["gradient_stats"]
            assert stats["0:DenseLayer"]["l2_norm"] is None   # was NaN
            assert m["latest"]["model_health"][
                "nonfinite_layer_name"] == "0:DenseLayer"
            json.loads(urllib.request.urlopen(
                base + "/telemetry").read().decode(), **strict)
        finally:
            ui.stop()

    def test_snapshot_embeds_model_health(self):
        net = _mln()
        net.setHealthMonitor(HealthMonitor(frequency=1))
        net.fit(X, Y)
        snap = telemetry.snapshot()
        assert "model_health" in snap
        assert "layer_grad_norm" in snap["model_health"]
