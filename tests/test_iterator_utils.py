"""Utility-iterator parity (reference: nd4j KFoldIterator/ViewIterator/
SamplingDataSetIterator/CachingDataSetIterator tests + deeplearning4j
MultipleEpochsIterator/EarlyTermination/ExistingMiniBatch tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    ArrayDataSetIterator, CachingDataSetIterator, DataSet,
    EarlyTerminationDataSetIterator, ExistingMiniBatchDataSetIterator,
    KFoldIterator, MultipleEpochsIterator, SamplingDataSetIterator,
    ViewIterator)


def _ds(n=20, d=3):
    x = np.arange(n * d, dtype=np.float32).reshape(n, d)
    y = np.arange(n, dtype=np.float32)[:, None]
    return DataSet(x, y)


class TestDataSetSerde:
    def test_save_load_round_trip(self, tmp_path):
        ds = DataSet(np.ones((4, 2), np.float32),
                     np.zeros((4, 1), np.float32),
                     features_mask=np.ones((4, 2), np.float32))
        p = str(tmp_path / "d.npz")
        ds.save(p)
        back = DataSet.load(p)
        np.testing.assert_array_equal(np.asarray(back.features),
                                      np.asarray(ds.features))
        assert back.features_mask is not None
        assert back.labels_mask is None

    def test_merge(self):
        a, b = _ds(4), _ds(6)
        m = DataSet.merge([a, b])
        assert m.numExamples() == 10
        np.testing.assert_array_equal(
            np.asarray(m.features)[:4], np.asarray(a.features))

    def test_merge_mask_mismatch_raises(self):
        a = DataSet(np.ones((2, 2)), np.ones((2, 1)),
                    features_mask=np.ones((2, 2)))
        b = DataSet(np.ones((2, 2)), np.ones((2, 1)))
        with pytest.raises(ValueError, match="features_mask"):
            DataSet.merge([a, b])


class TestKFold:
    def test_folds_partition_exactly(self):
        ds = _ds(23)
        it = KFoldIterator(5, ds)
        seen_test = []
        folds = 0
        while it.hasNext():
            train = it.next()
            test = it.testFold()
            folds += 1
            assert train.numExamples() + test.numExamples() == 23
            seen_test.append(np.asarray(test.labels)[:, 0])
            # train and test are disjoint
            assert not (set(np.asarray(train.labels)[:, 0])
                        & set(seen_test[-1]))
        assert folds == 5
        # union of test folds covers every example exactly once
        allv = np.sort(np.concatenate(seen_test))
        np.testing.assert_array_equal(allv, np.arange(23))

    def test_testfold_before_next_raises(self):
        with pytest.raises(ValueError, match="next"):
            KFoldIterator(4, _ds(8)).testFold()

    def test_bad_k(self):
        with pytest.raises(ValueError):
            KFoldIterator(1, _ds(8))
        with pytest.raises(ValueError):
            KFoldIterator(9, _ds(8))


class TestViewAndSampling:
    def test_view_batches(self):
        it = ViewIterator(_ds(10), 4)
        sizes = [d.numExamples() for d in it]
        assert sizes == [4, 4, 2]
        it.reset()
        assert it.next().numExamples() == 4

    def test_sampling_draws_total(self):
        it = SamplingDataSetIterator(_ds(10), batch_size=8,
                                     total_num_samples=20, seed=1)
        sizes = [d.numExamples() for d in it]
        assert sum(sizes) == 20 and sizes == [8, 8, 4]
        # different epochs draw different samples
        first = np.asarray(next(iter(it)).labels)
        it.reset()
        second = np.asarray(it.next().labels)
        assert first.shape == second.shape
        assert (first != second).any()


class TestMaskPropagation:
    def test_view_and_sampling_keep_masks(self):
        ds = DataSet(np.ones((6, 3, 2), np.float32),
                     np.ones((6, 3, 1), np.float32),
                     features_mask=np.ones((6, 3), np.float32),
                     labels_mask=np.ones((6, 3), np.float32))
        b = ViewIterator(ds, 4).next()
        assert b.features_mask is not None and b.features_mask.shape == (4, 3)
        s = SamplingDataSetIterator(ds, 5, 5, seed=0).next()
        assert s.labels_mask is not None and s.labels_mask.shape == (5, 3)


class TestEpochAndTermination:
    def test_multiple_epochs(self):
        base = ArrayDataSetIterator(np.zeros((6, 2), np.float32),
                                    np.zeros((6, 1), np.float32), 3)
        it = MultipleEpochsIterator(3, base)
        assert sum(1 for _ in it) == 6   # 2 batches x 3 epochs

    def test_early_termination(self):
        base = ArrayDataSetIterator(np.zeros((20, 2), np.float32),
                                    np.zeros((20, 1), np.float32), 2)
        it = EarlyTerminationDataSetIterator(base, 3)
        assert sum(1 for _ in it) == 3
        it.reset()
        assert sum(1 for _ in it) == 3


class _CountingIterator(ViewIterator):
    """ViewIterator that counts underlying pulls."""

    def __init__(self, ds, bs):
        super().__init__(ds, bs)
        self.pulls = 0

    def next(self):
        self.pulls += 1
        return super().next()


class TestCaching:
    @pytest.mark.parametrize("use_dir", [False, True])
    def test_second_epoch_serves_from_cache(self, tmp_path, use_dir):
        src = _CountingIterator(_ds(12), 4)
        it = CachingDataSetIterator(
            src, cache_dir=str(tmp_path) if use_dir else None)
        first = [np.asarray(d.features).copy() for d in it]
        assert src.pulls == 3
        second = [np.asarray(d.features) for d in it]
        assert src.pulls == 3                 # cache hit, no new pulls
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)


class TestExistingMiniBatch:
    def test_reads_saved_batches_in_order(self, tmp_path):
        for i in range(3):
            DataSet(np.full((2, 2), i, np.float32),
                    np.zeros((2, 1), np.float32)).save(
                        str(tmp_path / f"dataset-{i}.npz"))
        it = ExistingMiniBatchDataSetIterator(str(tmp_path))
        vals = [float(np.asarray(d.features)[0, 0]) for d in it]
        assert vals == [0.0, 1.0, 2.0]
        assert it.batch() == 2

    def test_missing_raises(self, tmp_path):
        with pytest.raises(ValueError, match="no files"):
            ExistingMiniBatchDataSetIterator(str(tmp_path))
