"""NLP subsystem tests (reference analogs: Word2VecTests,
ParagraphVectorsTest, TokenizerFactory tests, WordVectorSerializer
tests in deeplearning4j-nlp)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, NGramTokenizerFactory, ParagraphVectors,
    VocabCache, Word2Vec, WordVectorSerializer,
)


# ----------------------------------------------------------------------
# synthetic corpus with learnable co-occurrence structure: two "topics"
# whose words only ever appear together
# ----------------------------------------------------------------------
TOPIC_A = ["cat", "dog", "pet", "fur", "tail"]
TOPIC_B = ["stock", "bond", "market", "trade", "price"]


def make_corpus(n=300, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        words = TOPIC_A if rng.random() < 0.5 else TOPIC_B
        out.append(" ".join(rng.choice(words, size=6)))
    return out


class TestTokenization:
    def test_default_tokenizer(self):
        tf = DefaultTokenizerFactory()
        toks = tf.create("the quick  brown fox").getTokens()
        assert toks == ["the", "quick", "brown", "fox"]

    def test_common_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.setTokenPreProcessor(CommonPreprocessor())
        toks = tf.create("Hello, World! 123 foo.bar").getTokens()
        assert toks == ["hello", "world", "foobar"]

    def test_ngram_tokenizer(self):
        tf = NGramTokenizerFactory(1, 2)
        toks = tf.create("a b c").getTokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestSentenceIterators:
    def test_collection_iterator_reset(self):
        it = CollectionSentenceIterator(["one", "two"])
        assert list(it) == ["one", "two"]
        assert list(it) == ["one", "two"]  # __iter__ resets

    def test_line_iterator(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("line one\nline two\nline three\n")
        it = BasicLineIterator(str(p))
        assert list(it) == ["line one", "line two", "line three"]
        it.reset()
        assert it.hasNext()
        assert it.nextSentence() == "line one"

    def test_preprocessor_applied(self):
        it = CollectionSentenceIterator(["ABC"])
        it.setPreProcessor(str.lower)
        assert list(it) == ["abc"]


class TestVocab:
    def test_build_and_query(self):
        v = VocabCache()
        for w in ["a", "a", "a", "b", "b", "c"]:
            v.addToken(w)
        v.finalize_vocab(min_word_frequency=2)
        assert v.numWords() == 2
        assert v.containsWord("a") and v.containsWord("b")
        assert not v.containsWord("c")
        assert v.indexOf("a") == 0  # most frequent first
        assert v.wordFrequency("a") == 3
        assert v.wordAtIndex(1) == "b"


class TestWord2Vec:
    def _fit(self, **kw):
        kw.setdefault("layer_size", 16)
        kw.setdefault("min_word_frequency", 1)
        kw.setdefault("window_size", 3)
        kw.setdefault("epochs", 15)
        kw.setdefault("learning_rate", 0.05)
        kw.setdefault("seed", 7)
        model = Word2Vec(**kw)
        model.fit(make_corpus())
        return model

    def test_topic_separation_skipgram(self):
        m = self._fit()
        within = m.similarity("cat", "dog")
        across = m.similarity("cat", "stock")
        assert within > across + 0.2, (within, across)

    def test_topic_separation_cbow(self):
        # CBOW cold-starts slower than skip-gram (syn1neg zeros + mean
        # context): give it more passes over the tiny corpus
        m = self._fit(use_cbow=True, epochs=50)
        within = m.similarity("market", "trade")
        across = m.similarity("market", "fur")
        assert within > across + 0.2, (within, across)

    def test_words_nearest(self):
        m = self._fit()
        near = m.wordsNearest("cat", 4)
        assert set(near) == set(TOPIC_A) - {"cat"}

    def test_vector_shape_and_vocab(self):
        m = self._fit()
        assert m.getWordVector("pet").shape == (16,)
        assert m.getWordVectorMatrix().shape == (10, 16)
        assert m.hasWord("bond")
        with pytest.raises(KeyError):
            m.getWordVector("zebra")

    def test_builder_parity_surface(self):
        m = (Word2Vec.builder()
             .layerSize(8).windowSize(2).minWordFrequency(1)
             .epochs(1).learningRate(0.05).negativeSample(3)
             .seed(1)
             .iterate(CollectionSentenceIterator(make_corpus(50)))
             .build())
        m.fit()
        assert m.getWordVectorMatrix().shape[1] == 8

    def test_min_word_frequency_filters(self):
        m = Word2Vec(layer_size=8, min_word_frequency=1000)
        with pytest.raises(ValueError, match="empty vocabulary"):
            m.fit(make_corpus(10))


class TestSerializer:
    def test_text_roundtrip(self, tmp_path):
        m = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1, seed=3)
        m.fit(make_corpus(50))
        p = str(tmp_path / "vectors.txt")
        WordVectorSerializer.writeWordVectors(m, p)
        m2 = WordVectorSerializer.readWordVectors(p)
        for w in TOPIC_A:
            np.testing.assert_allclose(m2.getWordVector(w),
                                       m.getWordVector(w), atol=1e-5)
        assert m2.wordsNearest("cat", 2) == m.wordsNearest("cat", 2)

    def test_full_model_roundtrip(self, tmp_path):
        m = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1, seed=3)
        m.fit(make_corpus(50))
        p = str(tmp_path / "model.zip")
        WordVectorSerializer.writeWord2VecModel(m, p)
        m2 = WordVectorSerializer.readWord2VecModel(p)
        np.testing.assert_allclose(m2.getWordVectorMatrix(),
                                   m.getWordVectorMatrix(), atol=1e-6)
        np.testing.assert_allclose(np.asarray(m2.syn1neg),
                                   np.asarray(m.syn1neg), atol=1e-6)
        assert m2.vocab.words() == m.vocab.words()


class TestParagraphVectors:
    def test_doc_clustering_and_inference(self):
        rng = np.random.default_rng(1)
        docs = []
        for i in range(40):
            words = TOPIC_A if i % 2 == 0 else TOPIC_B
            label = f"{'A' if i % 2 == 0 else 'B'}_{i}"
            docs.append((label, " ".join(rng.choice(words, size=8))))
        # 40 docs x 8 words = ONE batch per epoch — needs many epochs
        pv = ParagraphVectors(layer_size=16, epochs=150, seed=5,
                              learning_rate=0.05)
        pv.fit(docs)
        assert pv.getVector("A_0").shape == (16,)
        # an unseen topic-A text should land nearer A docs than B docs
        near = pv.nearestLabels("cat dog fur pet tail dog", n=6)
        a_hits = sum(1 for l in near if l.startswith("A"))
        assert a_hits >= 4, near

    def test_infer_vector_deterministic_tables(self):
        docs = [("D1", "cat dog pet"), ("D2", "stock bond market")]
        pv = ParagraphVectors(layer_size=8, epochs=5, seed=5)
        pv.fit(docs)
        v = pv.inferVector("cat pet dog")
        assert v.shape == (8,)
        assert np.isfinite(v).all()

    def test_unknown_words_give_zero_vector(self):
        pv = ParagraphVectors(layer_size=8, epochs=1, seed=5)
        pv.fit([("D1", "cat dog pet")])
        v = pv.inferVector("zebra unicorn")
        assert np.allclose(v, 0)


class TestHierarchicalSoftmax:
    """HS learning path (reference: models/embeddings/learning/impl/
    elements/ ships BOTH impls; VERDICT r4 missing #2). Device-batched
    Huffman-path steps, same harness as the NS topic tests."""

    def test_huffman_codes_prefix_free_and_optimal(self):
        import heapq
        import itertools

        from deeplearning4j_tpu.nlp.vocab import AbstractCache

        c = AbstractCache()
        freqs = {"the": 100, "cat": 40, "sat": 30, "on": 20, "mat": 8,
                 "zz": 2, "q": 1}
        for w, n in freqs.items():
            c.addToken(w, n)
        c.finalize_vocab(1)
        n_inner = c.build_huffman()
        assert n_inner == len(freqs) - 1
        codes = {vw.word: "".join(map(str, vw.codes))
                 for vw in c.vocabWords()}
        for a, b in itertools.permutations(codes.values(), 2):
            assert not b.startswith(a), (a, b)
        for vw in c.vocabWords():
            assert len(vw.codes) == len(vw.points)
            assert all(0 <= p < n_inner for p in vw.points)
        # weighted code length must equal the Huffman optimum
        got = sum(len(codes[w]) * n for w, n in freqs.items())
        h = list(freqs.values())
        heapq.heapify(h)
        opt = 0
        while len(h) > 1:
            a, b = heapq.heappop(h), heapq.heappop(h)
            opt += a + b
            heapq.heappush(h, a + b)
        assert got == opt, (got, opt)

    def test_topic_separation_skipgram_hs_only(self):
        m = Word2Vec(layer_size=16, min_word_frequency=1, window_size=3,
                     epochs=15, learning_rate=0.05, seed=7,
                     negative=0, use_hierarchic_softmax=True)
        m.fit(make_corpus())
        assert m.syn1 is not None
        within = m.similarity("cat", "dog")
        across = m.similarity("cat", "stock")
        assert within > across + 0.2, (within, across)

    def test_topic_separation_cbow_hs_only(self):
        m = Word2Vec(layer_size=16, min_word_frequency=1, window_size=3,
                     epochs=25, learning_rate=0.08, seed=7,
                     use_cbow=True, negative=0,
                     use_hierarchic_softmax=True)
        m.fit(make_corpus())
        within = m.similarity("bond", "market")
        across = m.similarity("bond", "dog")
        assert within > across + 0.2, (within, across)

    def test_hs_plus_negative_combined(self):
        # the C word2vec runs hs AND negative blocks when both are on
        m = Word2Vec(layer_size=16, min_word_frequency=1, window_size=3,
                     epochs=8, learning_rate=0.04, seed=7,
                     negative=3, use_hierarchic_softmax=True)
        m.fit(make_corpus())
        assert m.syn1 is not None and m.syn1neg is not None
        assert m.similarity("cat", "pet") > m.similarity("cat", "price")

    def test_no_objective_rejected(self):
        m = Word2Vec(negative=0, min_word_frequency=1)
        with pytest.raises(ValueError, match="useHierarchicSoftmax"):
            m.fit(make_corpus(10))

    def test_builder_flag_and_model_zip_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        m = (Word2Vec.builder().layerSize(12).minWordFrequency(1)
             .windowSize(3).epochs(5).negativeSample(0)
             .useHierarchicSoftmax(True).seed(3).build())
        m.fit(make_corpus(60))
        p = str(tmp_path / "w2v_hs.zip")
        WordVectorSerializer.writeWord2VecModel(m, p)
        m2 = WordVectorSerializer.readWord2VecModel(p)
        assert m2.use_hierarchic_softmax
        np.testing.assert_allclose(np.asarray(m2.syn1),
                                   np.asarray(m.syn1), rtol=1e-6)
        # huffman fields restored for continued training
        vw = m2.vocab.vocabWords()[0]
        assert vw.codes is not None and vw.points is not None
        np.testing.assert_allclose(
            m2.getWordVector("cat"), m.getWordVector("cat"), rtol=1e-6)


class TestInterchangeFormats:
    """word2vec C text+binary interchange formats (reference:
    WordVectorSerializer.loadGoogleModel / writeWordVectors; VERDICT r4
    missing #2 second half). The binary reader/writer are verified
    against an INDEPENDENT struct-level parser written from the public
    format spec, not against each other alone."""

    def _fit_small(self):
        m = Word2Vec(layer_size=8, min_word_frequency=1, epochs=3,
                     seed=11)
        m.fit(make_corpus(40))
        return m

    def test_binary_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        m = self._fit_small()
        p = str(tmp_path / "vecs.bin")
        WordVectorSerializer.writeWordVectors(m, p, binary=True)
        m2 = WordVectorSerializer.readWordVectors(p)   # auto-detect
        assert m2.vocab.words() == m.vocab.words()
        np.testing.assert_allclose(m2.getWordVectorMatrix(),
                                   m.getWordVectorMatrix(), rtol=1e-6)

    def test_binary_format_matches_public_spec(self, tmp_path):
        """Independent parser: header 'V D\\n', then per record
        word-bytes + 0x20 + D little-endian float32 + 0x0a."""
        import struct

        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        m = self._fit_small()
        p = str(tmp_path / "vecs.bin")
        WordVectorSerializer.writeWordVectors(m, p, binary=True)
        with open(p, "rb") as f:
            data = f.read()
        nl = data.index(b"\n")
        v, d = (int(t) for t in data[:nl].split())
        off = nl + 1
        mat = m.getWordVectorMatrix()
        for i in range(v):
            sp = data.index(b" ", off)
            word = data[off:sp].decode("utf-8")
            assert word == m.vocab.wordAtIndex(i)
            vec = struct.unpack(f"<{d}f", data[sp + 1:sp + 1 + 4 * d])
            np.testing.assert_allclose(vec, mat[i], rtol=1e-6)
            off = sp + 1 + 4 * d
            assert data[off:off + 1] == b"\n"
            off += 1
        assert off == len(data)

    def test_text_reader_reads_foreign_file(self, tmp_path):
        """A hand-written file in the interchange text format (as a
        foreign tool would produce) loads correctly."""
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        p = tmp_path / "foreign.txt"
        p.write_text("2 3\nhello 1.0 2.0 3.0\nworld -1.0 0.5 0.25\n")
        m = WordVectorSerializer.readWordVectors(str(p))
        np.testing.assert_allclose(m.getWordVector("world"),
                                   [-1.0, 0.5, 0.25])

    def test_binary_reader_reads_foreign_file(self, tmp_path):
        """A binary file built byte-by-byte from the spec (as gensim /
        word2vec.c would emit) loads correctly, incl. auto-detection."""
        import struct

        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        p = tmp_path / "foreign.bin"
        vecs = {"alpha": [0.5, -1.25], "beta": [3.0, 0.125]}
        blob = b"2 2\n"
        for w, v in vecs.items():
            blob += w.encode() + b" " + struct.pack("<2f", *v) + b"\n"
        p.write_bytes(blob)
        m = WordVectorSerializer.readWordVectors(str(p))
        for w, v in vecs.items():
            np.testing.assert_allclose(m.getWordVector(w), v)

    def test_utf8_words_in_text_format_autodetect(self, tmp_path):
        """Non-ASCII words are routine in embeddings; structural
        sniffing must not classify them as binary."""
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        p = tmp_path / "utf8.txt"
        p.write_text("2 3\ncafé 1.0 2.0 3.0\nüber -1.0 0.5 0.25\n",
                     encoding="utf-8")
        m = WordVectorSerializer.readWordVectors(str(p))
        np.testing.assert_allclose(m.getWordVector("café"),
                                   [1.0, 2.0, 3.0])

    def test_utf8_words_in_binary_format(self, tmp_path):
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

        m = self._fit_small()
        # inject a non-ascii word by renaming vocab entry 0
        vw = m.vocab.vocabWords()[0]
        old = vw.word
        m.vocab._words["café"] = m.vocab._words.pop(old)
        vw.word = "café"
        p = str(tmp_path / "u.bin")
        WordVectorSerializer.writeWordVectors(m, p, binary=True)
        m2 = WordVectorSerializer.readWordVectors(p)
        np.testing.assert_allclose(m2.getWordVector("café"),
                                   m.getWordVector("café"), rtol=1e-6)


class TestWordAnalogies:
    """reference: WordVectors#wordsNearest(positive, negative, n) /
    wordsNearestSum — the analogy arithmetic. Geometry is hand-set so
    the expected answer is exact, not corpus-dependent."""

    def _model_with_vectors(self):
        import jax.numpy as jnp
        model = Word2Vec(layer_size=2, min_word_frequency=1, epochs=1,
                         seed=0)
        model.fit(["king man woman queen day night"] * 2)
        vecs = {"king": [2.0, 2.0], "man": [2.0, 0.0],
                "woman": [0.0, 2.0], "queen": [0.3, 4.0],
                "day": [-3.0, 0.1], "night": [-3.0, -0.1]}
        mat = np.zeros((model.vocab.numWords(), 2), np.float32)
        for w, v in vecs.items():
            mat[model.vocab.indexOf(w)] = v
        model.syn0 = jnp.asarray(mat)
        return model

    def test_analogy_mean_form(self):
        m = self._model_with_vectors()
        # king - man + woman -> queen (unit-mean arithmetic)
        assert m.wordsNearest(["king", "woman"], ["man"], n=1) == ["queen"]
        # query words are excluded from results
        out = m.wordsNearest(["king", "woman"], ["man"], n=10)
        assert "king" not in out and "woman" not in out

    def test_analogy_sum_form(self):
        m = self._model_with_vectors()
        assert m.wordsNearestSum(["king", "woman"], ["man"], n=1) \
            == ["queen"]
        # single-string positives accepted, incl. the (word, n) form
        assert m.wordsNearestSum("day", n=1) == ["night"]
        assert m.wordsNearestSum("day", 1) == ["night"]

    def test_single_word_form_unchanged(self):
        m = self._model_with_vectors()
        assert m.wordsNearest("day", n=1) == ["night"]
        assert m.wordsNearest("day", n=3)[0] == "night"

    def test_unknown_word_raises(self):
        m = self._model_with_vectors()
        with pytest.raises(KeyError):
            m.wordsNearest(["king", "prince"], ["man"], n=1)
