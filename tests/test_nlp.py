"""NLP subsystem tests (reference analogs: Word2VecTests,
ParagraphVectorsTest, TokenizerFactory tests, WordVectorSerializer
tests in deeplearning4j-nlp)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, NGramTokenizerFactory, ParagraphVectors,
    VocabCache, Word2Vec, WordVectorSerializer,
)


# ----------------------------------------------------------------------
# synthetic corpus with learnable co-occurrence structure: two "topics"
# whose words only ever appear together
# ----------------------------------------------------------------------
TOPIC_A = ["cat", "dog", "pet", "fur", "tail"]
TOPIC_B = ["stock", "bond", "market", "trade", "price"]


def make_corpus(n=300, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        words = TOPIC_A if rng.random() < 0.5 else TOPIC_B
        out.append(" ".join(rng.choice(words, size=6)))
    return out


class TestTokenization:
    def test_default_tokenizer(self):
        tf = DefaultTokenizerFactory()
        toks = tf.create("the quick  brown fox").getTokens()
        assert toks == ["the", "quick", "brown", "fox"]

    def test_common_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.setTokenPreProcessor(CommonPreprocessor())
        toks = tf.create("Hello, World! 123 foo.bar").getTokens()
        assert toks == ["hello", "world", "foobar"]

    def test_ngram_tokenizer(self):
        tf = NGramTokenizerFactory(1, 2)
        toks = tf.create("a b c").getTokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestSentenceIterators:
    def test_collection_iterator_reset(self):
        it = CollectionSentenceIterator(["one", "two"])
        assert list(it) == ["one", "two"]
        assert list(it) == ["one", "two"]  # __iter__ resets

    def test_line_iterator(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("line one\nline two\nline three\n")
        it = BasicLineIterator(str(p))
        assert list(it) == ["line one", "line two", "line three"]
        it.reset()
        assert it.hasNext()
        assert it.nextSentence() == "line one"

    def test_preprocessor_applied(self):
        it = CollectionSentenceIterator(["ABC"])
        it.setPreProcessor(str.lower)
        assert list(it) == ["abc"]


class TestVocab:
    def test_build_and_query(self):
        v = VocabCache()
        for w in ["a", "a", "a", "b", "b", "c"]:
            v.addToken(w)
        v.finalize_vocab(min_word_frequency=2)
        assert v.numWords() == 2
        assert v.containsWord("a") and v.containsWord("b")
        assert not v.containsWord("c")
        assert v.indexOf("a") == 0  # most frequent first
        assert v.wordFrequency("a") == 3
        assert v.wordAtIndex(1) == "b"


class TestWord2Vec:
    def _fit(self, **kw):
        kw.setdefault("layer_size", 16)
        kw.setdefault("min_word_frequency", 1)
        kw.setdefault("window_size", 3)
        kw.setdefault("epochs", 15)
        kw.setdefault("learning_rate", 0.05)
        kw.setdefault("seed", 7)
        model = Word2Vec(**kw)
        model.fit(make_corpus())
        return model

    def test_topic_separation_skipgram(self):
        m = self._fit()
        within = m.similarity("cat", "dog")
        across = m.similarity("cat", "stock")
        assert within > across + 0.2, (within, across)

    def test_topic_separation_cbow(self):
        # CBOW cold-starts slower than skip-gram (syn1neg zeros + mean
        # context): give it more passes over the tiny corpus
        m = self._fit(use_cbow=True, epochs=50)
        within = m.similarity("market", "trade")
        across = m.similarity("market", "fur")
        assert within > across + 0.2, (within, across)

    def test_words_nearest(self):
        m = self._fit()
        near = m.wordsNearest("cat", 4)
        assert set(near) == set(TOPIC_A) - {"cat"}

    def test_vector_shape_and_vocab(self):
        m = self._fit()
        assert m.getWordVector("pet").shape == (16,)
        assert m.getWordVectorMatrix().shape == (10, 16)
        assert m.hasWord("bond")
        with pytest.raises(KeyError):
            m.getWordVector("zebra")

    def test_builder_parity_surface(self):
        m = (Word2Vec.builder()
             .layerSize(8).windowSize(2).minWordFrequency(1)
             .epochs(1).learningRate(0.05).negativeSample(3)
             .seed(1)
             .iterate(CollectionSentenceIterator(make_corpus(50)))
             .build())
        m.fit()
        assert m.getWordVectorMatrix().shape[1] == 8

    def test_min_word_frequency_filters(self):
        m = Word2Vec(layer_size=8, min_word_frequency=1000)
        with pytest.raises(ValueError, match="empty vocabulary"):
            m.fit(make_corpus(10))


class TestSerializer:
    def test_text_roundtrip(self, tmp_path):
        m = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1, seed=3)
        m.fit(make_corpus(50))
        p = str(tmp_path / "vectors.txt")
        WordVectorSerializer.writeWordVectors(m, p)
        m2 = WordVectorSerializer.readWordVectors(p)
        for w in TOPIC_A:
            np.testing.assert_allclose(m2.getWordVector(w),
                                       m.getWordVector(w), atol=1e-5)
        assert m2.wordsNearest("cat", 2) == m.wordsNearest("cat", 2)

    def test_full_model_roundtrip(self, tmp_path):
        m = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1, seed=3)
        m.fit(make_corpus(50))
        p = str(tmp_path / "model.zip")
        WordVectorSerializer.writeWord2VecModel(m, p)
        m2 = WordVectorSerializer.readWord2VecModel(p)
        np.testing.assert_allclose(m2.getWordVectorMatrix(),
                                   m.getWordVectorMatrix(), atol=1e-6)
        np.testing.assert_allclose(np.asarray(m2.syn1neg),
                                   np.asarray(m.syn1neg), atol=1e-6)
        assert m2.vocab.words() == m.vocab.words()


class TestParagraphVectors:
    def test_doc_clustering_and_inference(self):
        rng = np.random.default_rng(1)
        docs = []
        for i in range(40):
            words = TOPIC_A if i % 2 == 0 else TOPIC_B
            label = f"{'A' if i % 2 == 0 else 'B'}_{i}"
            docs.append((label, " ".join(rng.choice(words, size=8))))
        # 40 docs x 8 words = ONE batch per epoch — needs many epochs
        pv = ParagraphVectors(layer_size=16, epochs=150, seed=5,
                              learning_rate=0.05)
        pv.fit(docs)
        assert pv.getVector("A_0").shape == (16,)
        # an unseen topic-A text should land nearer A docs than B docs
        near = pv.nearestLabels("cat dog fur pet tail dog", n=6)
        a_hits = sum(1 for l in near if l.startswith("A"))
        assert a_hits >= 4, near

    def test_infer_vector_deterministic_tables(self):
        docs = [("D1", "cat dog pet"), ("D2", "stock bond market")]
        pv = ParagraphVectors(layer_size=8, epochs=5, seed=5)
        pv.fit(docs)
        v = pv.inferVector("cat pet dog")
        assert v.shape == (8,)
        assert np.isfinite(v).all()

    def test_unknown_words_give_zero_vector(self):
        pv = ParagraphVectors(layer_size=8, epochs=1, seed=5)
        pv.fit([("D1", "cat dog pet")])
        v = pv.inferVector("zebra unicorn")
        assert np.allclose(v, 0)
