"""CnnSentenceDataSetIterator tests (reference:
deeplearning4j-nlp CnnSentenceDataSetIteratorTest) — end-to-end:
Word2Vec embeddings -> sentence tensors -> Conv1D classifier."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    CnnSentenceDataSetIterator, CollectionLabeledSentenceProvider, Word2Vec,
)


def corpus():
    pets = ["cat dog pet fluffy animal", "dog cat bark purr pet",
            "fluffy cat pet animal dog", "pet dog animal bark cat"]
    fin = ["stock market price trade money", "market stock trade profit",
           "price trade stock market money", "profit money market stock"]
    sentences = (pets + fin) * 4
    labels = (["pets"] * 4 + ["finance"] * 4) * 4
    return sentences, labels


@pytest.fixture(scope="module")
def w2v():
    sentences, _ = corpus()
    return (Word2Vec.Builder().layerSize(12).windowSize(3)
            .minWordFrequency(1).epochs(8).seed(7)
            .iterate(sentences).build().fit())


class TestProvider:
    def test_collection_provider(self):
        s, l = corpus()
        p = CollectionLabeledSentenceProvider(s, l)
        assert p.totalNumSentences() == 32
        assert p.allLabels() == ["finance", "pets"]
        n = 0
        while p.hasNext():
            sent, lab = p.nextSentence()
            assert lab in ("pets", "finance")
            n += 1
        assert n == 32
        p.reset()
        assert p.hasNext()

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError, match="sentences vs"):
            CollectionLabeledSentenceProvider(["a"], ["x", "y"])


class TestCnnSentenceIterator:
    def test_tensor_shapes_and_mask(self, w2v):
        s, l = corpus()
        it = CnnSentenceDataSetIterator(
            CollectionLabeledSentenceProvider(s, l), w2v,
            batch_size=8, max_sentence_length=6)
        ds = it.next()
        assert ds.features.shape == (8, 6, 12)
        assert ds.labels.shape == (8, 2)
        assert ds.features_mask.shape == (8, 6)
        # 5-word sentences -> mask 5 ones, padded tail zero
        assert ds.features_mask[0].sum() in (4.0, 5.0)
        assert np.all(ds.features[0][int(ds.features_mask[0].sum()):] == 0)

    def test_oov_handling_modes(self, w2v):
        s = ["cat zzzunknownzzz dog"]
        it_rm = CnnSentenceDataSetIterator(
            CollectionLabeledSentenceProvider(s, ["pets"]), w2v,
            max_sentence_length=5, unknown_word_handling="RemoveWord")
        x = it_rm.loadSingleSentence(s[0])
        # OOV removed: 2 real vectors
        assert (np.abs(x[0]).sum(-1) > 0).sum() == 2
        it_unk = CnnSentenceDataSetIterator(
            CollectionLabeledSentenceProvider(s, ["pets"]), w2v,
            max_sentence_length=5, unknown_word_handling="UseUnknownVector")
        x2 = it_unk.loadSingleSentence(s[0])
        assert (np.abs(x2[0]).sum(-1) > 0).sum() == 3

    def test_end_to_end_text_cnn(self, w2v):
        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.nn.conf import (
            Convolution1D, GlobalPoolingLayer, InputType,
            NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        s, l = corpus()
        it = CnnSentenceDataSetIterator(
            CollectionLabeledSentenceProvider(s, l, rng_seed=3), w2v,
            batch_size=16, max_sentence_length=6)
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Adam(learning_rate=5e-3)).list()
                .layer(Convolution1D(n_out=16, kernel_size=3,
                                     convolution_mode="Same",
                                     activation="relu"))
                .layer(GlobalPoolingLayer(pooling_type="max"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.recurrent(12, 6)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=30)
        # classify held-out-ish sentences
        x_pet = it.loadSingleSentence("fluffy dog pet")
        x_fin = it.loadSingleSentence("stock profit market")
        p_pet = np.asarray(net.output(x_pet))[0]
        p_fin = np.asarray(net.output(x_fin))[0]
        pets_col = it.getLabels().index("pets")
        assert p_pet[pets_col] > 0.5
        assert p_fin[pets_col] < 0.5


class TestFeaturesMaskTraining:
    def test_invalid_unknown_handling_raises(self, w2v):
        s, l = corpus()
        with pytest.raises(ValueError, match="unknown_word_handling"):
            CnnSentenceDataSetIterator(
                CollectionLabeledSentenceProvider(s, l), w2v,
                unknown_word_handling="useUnknownVector")

    def test_masked_global_pooling_ignores_padding(self):
        """MLN honors features_mask: padded steps cannot win max-pool."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer
        x = jnp.asarray(np.stack([
            np.concatenate([np.full((2, 3), -1.0), np.zeros((3, 3))]),
        ]).astype(np.float32))  # [1,5,3]: real steps all -1, pad zeros
        mask = jnp.asarray([[1, 1, 0, 0, 0]], jnp.float32)
        lay = GlobalPoolingLayer(pooling_type="max")
        unmasked, _ = lay.apply({}, {}, x, False, None)
        masked, _ = lay.apply_masked({}, {}, x, mask, False, None)
        assert np.allclose(np.asarray(unmasked), 0.0)   # padding wins
        assert np.allclose(np.asarray(masked), -1.0)    # padding excluded
        # avg pooling divides by real length
        lay_avg = GlobalPoolingLayer(pooling_type="avg")
        m_avg, _ = lay_avg.apply_masked({}, {}, x, mask, False, None)
        assert np.allclose(np.asarray(m_avg), -1.0)

    def test_fit_with_features_mask_changes_training(self, w2v):
        """Same data, features_mask on/off -> different trained nets."""
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, GlobalPoolingLayer, InputType,
            NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        rng = np.random.default_rng(0)
        # padding region carries STRONG anti-signal; mask must kill it
        x = rng.normal(size=(16, 6, 4)).astype(np.float32)
        lab = (x[:, :3, 0].mean(1) > 0).astype(int)
        x[:, 3:] = -np.sign(lab)[:, None, None] * 5.0
        y = np.eye(2, dtype=np.float32)[lab]
        mask = np.ones((16, 6), np.float32)
        mask[:, 3:] = 0

        def build():
            conf = (NeuralNetConfiguration.builder().seed(4)
                    .updater(Adam(learning_rate=1e-2)).list()
                    .layer(DenseLayer(n_out=8, activation="tanh"))
                    .layer(GlobalPoolingLayer(pooling_type="avg"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .setInputType(InputType.recurrent(4, 6)).build())
            return MultiLayerNetwork(conf).init()

        net_m = build()
        ds = DataSet(x, y, features_mask=mask)
        for _ in range(30):
            net_m.fit(ds)
        net_u = build()
        for _ in range(30):
            net_u.fit(DataSet(x, y))
        out_m = np.asarray(net_m.params_list[0]["W"])
        out_u = np.asarray(net_u.params_list[0]["W"])
        assert not np.allclose(out_m, out_u)

    def test_mean_reduced_loss_mask_identity(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.loss import LossFunction, compute_loss
        rng = np.random.default_rng(1)
        labels = rng.normal(size=(2, 4, 3)).astype(np.float32)
        pred = rng.normal(size=(2, 4, 3)).astype(np.float32)
        for lf in (LossFunction.MSE, LossFunction.MAE):
            um = float(compute_loss(lf, jnp.asarray(labels),
                                    jnp.asarray(pred), "identity", None))
            am = float(compute_loss(lf, jnp.asarray(labels),
                                    jnp.asarray(pred), "identity",
                                    jnp.ones((2, 4))))
            assert abs(um - am) < 1e-5, lf
        # sparse CE identity too
        il = jnp.asarray(rng.integers(0, 3, (2, 4)))
        um = float(compute_loss(LossFunction.SPARSE_MCXENT, il,
                                jnp.asarray(pred), "softmax", None))
        am = float(compute_loss(LossFunction.SPARSE_MCXENT, il,
                                jnp.asarray(pred), "softmax",
                                jnp.ones((2, 4))))
        assert abs(um - am) < 1e-5
