"""Continuous-batching decode engine (serving/engine.py, kv_pages.py):
greedy token-parity against solo generate() with requests joining and
leaving mid-flight, AOT warm-pool zero-trace contract, int8 weight-only
decode tolerance, paged-KV allocator, HTTP front-end, telemetry."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.gpt import CausalLM
from deeplearning4j_tpu.models.transformer import tiny_config
from deeplearning4j_tpu.nn.precision import (
    dequantize_int8, int8_matmul, quantize_int8, quantized_bytes,
)
from deeplearning4j_tpu.profiler import telemetry
from deeplearning4j_tpu.serving import DecodeEngine, PagePool
from deeplearning4j_tpu.serving.kv_pages import pages_needed


VOCAB = 13


def _model():
    cfg = tiny_config(vocab=VOCAB, max_len=48, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    return CausalLM(cfg, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return _model()


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(jax.random.key(1))


def _solo(model, params, prompt, new):
    return np.asarray(model.generate(
        params, jnp.asarray(np.asarray(prompt)[None, :], jnp.int32),
        new))[0]


# ------------------------------------------------------------ kv pages
class TestPagePool:
    def test_alloc_free_roundtrip_and_utilization(self):
        pool = PagePool(2, 4, 8, 8, n_pages=9, dtype=jnp.float32)
        assert pool.capacity == 8
        a = pool.alloc(3)
        b = pool.alloc(5)
        assert sorted(a + b) == list(range(1, 9))  # null page 0 never
        assert pool.alloc(1) is None               # exhausted -> None
        assert pool.utilization() == 1.0
        pool.free(a)
        assert pool.allocated == 5
        assert pool.high_water == 8
        c = pool.alloc(3)
        assert sorted(c) == sorted(a)

    def test_double_free_and_null_page_guarded(self):
        pool = PagePool(1, 2, 4, 4, n_pages=4, dtype=jnp.float32)
        pages = pool.alloc(2)
        pool.free(pages)
        with pytest.raises(ValueError, match="double free"):
            pool.free([pages[0]])
        with pytest.raises(ValueError, match="null page"):
            pool.free([0])

    def test_pages_needed(self):
        assert pages_needed(1, 8) == 1
        assert pages_needed(8, 8) == 1
        assert pages_needed(9, 8) == 2
        assert pages_needed(48, 8) == 6


# ----------------------------------------------------- greedy parity
class TestEngineGreedyParity:
    def test_mixed_length_concurrent_requests_match_solo(self, model,
                                                         params):
        """The acceptance contract: every request decoded through the
        engine — joining/leaving mid-flight next to other requests —
        is token-identical to a solo generate() call."""
        rng = np.random.default_rng(0)
        specs = [(5, 6), (9, 3), (3, 12), (12, 1), (7, 9), (4, 4),
                 (10, 7), (6, 2), (8, 8), (5, 11)]
        prompts = [rng.integers(0, VOCAB, (t0,)).astype(np.int32)
                   for t0, _ in specs]
        with DecodeEngine(model, params, slots=3, page_size=8) as eng:
            with ThreadPoolExecutor(max_workers=8) as ex:
                handles = list(ex.map(
                    lambda pn: eng.submit(pn[0], pn[1]),
                    zip(prompts, [n for _, n in specs])))
            outs = [h.result(timeout=120) for h in handles]
            assert eng.stats()["completed"] == len(specs)
        for p, (_, new), got in zip(prompts, specs, outs):
            np.testing.assert_array_equal(got, _solo(model, params, p,
                                                     new))

    def test_staggered_join_next_to_inflight_requests(self, model,
                                                      params):
        """A request admitted while another is mid-decode must not
        perturb either (slot math is row-independent)."""
        rng = np.random.default_rng(1)
        long_p = rng.integers(0, VOCAB, (4,)).astype(np.int32)
        short_p = rng.integers(0, VOCAB, (6,)).astype(np.int32)
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            # an unreachable eos_id forces single-chunk dispatches
            # (completion is unpredictable to the scheduler), so the
            # request is observably mid-flight between bursts
            long_req = eng.submit(long_p, 14, eos_id=VOCAB)
            # wait until the long request is visibly mid-flight
            for _ in range(500):
                if len(long_req.tokens) >= 2:
                    break
                time.sleep(0.01)
            assert not long_req.done
            short_out = eng.submit(short_p, 3).result(timeout=60)
            long_out = long_req.result(timeout=60)
        np.testing.assert_array_equal(
            long_out, _solo(model, params, long_p, 14))
        np.testing.assert_array_equal(
            short_out, _solo(model, params, short_p, 3))

    def test_eos_stops_early_and_matches_solo_prefix(self, model,
                                                     params):
        p = np.asarray([1, 2, 3, 4], np.int32)
        full = _solo(model, params, p, 10)
        eos = int(full[3])     # force a stop after 4 tokens
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            req = eng.submit(p, 10, eos_id=eos)
            got = req.result(timeout=60)
            assert req.finish_reason == "eos"
        stop = int(np.flatnonzero(full == eos)[0])
        np.testing.assert_array_equal(got, full[:stop + 1])

    def test_single_token_request(self, model, params):
        p = np.asarray([2, 5, 7], np.int32)
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            got = eng.generate(p, 1)
        np.testing.assert_array_equal(got, _solo(model, params, p, 1))

    def test_streaming_yields_the_same_tokens(self, model, params):
        p = np.asarray([3, 1, 4, 1, 5], np.int32)
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            req = eng.submit(p, 6)
            streamed = list(req.stream())
        np.testing.assert_array_equal(
            np.asarray(streamed, np.int32), _solo(model, params, p, 6))

    def test_page_pool_smaller_than_traffic_queues_and_completes(
            self, model, params):
        """More concurrent requests than the KV pool can hold at once:
        the surplus queues head-of-line and completes correctly after
        evictions free pages."""
        rng = np.random.default_rng(2)
        specs = [(6, 8), (9, 5), (4, 10), (7, 7), (5, 4), (8, 6)]
        prompts = [rng.integers(0, VOCAB, (t0,)).astype(np.int32)
                   for t0, _ in specs]
        # 2 slots x 2 pages-worth of pool: at most ~2 requests resident
        with DecodeEngine(model, params, slots=2, page_size=8,
                          n_pages=1 + 4) as eng:
            handles = [eng.submit(p, n)
                       for p, (_, n) in zip(prompts, specs)]
            outs = [h.result(timeout=120) for h in handles]
            assert eng.pool.allocated == 0
        for p, (_, new), got in zip(prompts, specs, outs):
            np.testing.assert_array_equal(
                got, _solo(model, params, p, new))


# ------------------------------------------------------- AOT warm pool
class TestWarmPool:
    def _compiles(self, site):
        return telemetry.MetricsRegistry.get_default().counter(
            telemetry.JIT_COMPILES).value(site=site)

    def test_first_request_zero_trace_after_warm_start(self, model,
                                                       params):
        d0 = self._compiles("serving_decode")
        p0 = self._compiles("serving_prefill")
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            eng.generate(np.asarray([1, 2, 3], np.int32), 4)
            stats = eng.stats()
        assert self._compiles("serving_decode") == d0, \
            "decode went through the compiling jit path"
        assert self._compiles("serving_prefill") == p0, \
            "prefill went through the compiling jit path"
        # 1 prefill + the decode chunks covering 3 post-first tokens
        assert stats["warm_pool"]["hits"] >= 3
        assert stats["warm_pool"]["misses"] == 0

    def test_out_of_bucket_prompt_falls_back_and_stays_correct(
            self, model, params):
        p0 = self._compiles("serving_prefill")
        p = np.arange(11, dtype=np.int32) % VOCAB
        # buckets cover only width 8; an 11-token prompt must take the
        # compiling fallback (padded to the page-size multiple 16)
        with DecodeEngine(model, params, slots=2, page_size=8,
                          prefill_buckets=[8]) as eng:
            got = eng.generate(p, 3)
            assert eng.stats()["warm_pool"]["misses"] >= 1
        assert self._compiles("serving_prefill") > p0
        np.testing.assert_array_equal(got, _solo(model, params, p, 3))

    def test_warm_start_false_compiles_lazily_but_serves(self, model,
                                                         params):
        d0 = self._compiles("serving_decode")
        with DecodeEngine(model, params, slots=2, page_size=8,
                          warm_start=False) as eng:
            got = eng.generate(np.asarray([4, 2], np.int32), 5)
            assert eng.stats()["warm_pool"]["hits"] == 0
            assert eng.stats()["warm_pool"]["misses"] >= 2
        assert self._compiles("serving_decode") >= d0 + 1
        np.testing.assert_array_equal(
            got, _solo(model, params, np.asarray([4, 2], np.int32), 5))


# ------------------------------------------------------------ sampling
class TestSampling:
    def test_sampled_decode_deterministic_per_seed(self, model, params):
        p = np.asarray([1, 2, 3], np.int32)
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            a = eng.submit(p, 6, temperature=1.0,
                           sample_seed=7).result(60)
            b = eng.submit(p, 6, temperature=1.0,
                           sample_seed=7).result(60)
            c = eng.submit(p, 6, temperature=1.0,
                           sample_seed=8).result(60)
        np.testing.assert_array_equal(a, b)
        assert a.min() >= 0 and a.max() < VOCAB
        assert not np.array_equal(a, c) or True  # seeds may collide;
        # the hard guarantee is same-seed determinism above

    def test_mixed_greedy_and_sampled_slots_keep_greedy_exact(
            self, model, params):
        """A sampled request decoding in the neighboring slot must not
        perturb a greedy request."""
        rng = np.random.default_rng(3)
        p = rng.integers(0, VOCAB, (6,)).astype(np.int32)
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            g = eng.submit(p, 8)
            eng.submit(p, 8, temperature=1.3, sample_seed=1)
            got = g.result(timeout=60)
        np.testing.assert_array_equal(got, _solo(model, params, p, 8))


# ---------------------------------------------------------------- int8
class TestInt8Preset:
    def test_quantize_roundtrip_error_bounded(self):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(0, 0.05, (32, 48)), jnp.float32)
        wq = quantize_int8(w, axis=1)
        deq = dequantize_int8(wq)
        # symmetric rounding: per-channel error <= scale/2
        err = np.abs(np.asarray(deq - w))
        bound = np.asarray(wq["s"])[None, :] * 0.5 + 1e-7
        assert (err <= bound).all()
        assert wq["q"].dtype == jnp.int8
        # int8 storage is ~4x smaller than the f32 original
        assert quantized_bytes(wq) < quantized_bytes(w) / 3

    def test_int8_matmul_matches_dequantized(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
        w = jnp.asarray(rng.normal(0, 0.05, (32, 16)), jnp.float32)
        wq = quantize_int8(w, axis=1)
        np.testing.assert_allclose(
            np.asarray(int8_matmul(x, wq, jnp.float32)),
            np.asarray(x @ dequantize_int8(wq)), rtol=1e-5, atol=1e-5)
        # plain arrays pass through
        np.testing.assert_allclose(
            np.asarray(int8_matmul(x, w, jnp.float32)),
            np.asarray(x @ w), rtol=1e-6, atol=1e-6)

    def test_logits_tolerance_and_loss_parity_vs_reference(self, model,
                                                           params):
        """int8 weight-only decode weights must stay within the same
        quality neighborhood as a bf16 cast of the model (the serving
        preset it substitutes for)."""
        from deeplearning4j_tpu.nn.precision import cast_tree

        rng = np.random.default_rng(2)
        ids = jnp.asarray(rng.integers(0, VOCAB, (4, 16)), jnp.int32)

        def q_tree(p):
            out = {"tok_emb": dequantize_int8(quantize_int8(
                       p["tok_emb"], 0)),
                   "pos_emb": p["pos_emb"], "ln_f": p["ln_f"],
                   "layers": []}
            for lp in p["layers"]:
                nl = dict(lp)
                for k in ("wqkv", "wo", "w1", "w2"):
                    nl[k] = dequantize_int8(quantize_int8(lp[k], 1))
                out["layers"].append(nl)
            return out

        full = np.asarray(model.forward(params, ids), np.float32)
        int8 = np.asarray(model.forward(q_tree(params), ids),
                          np.float32)
        bf16 = np.asarray(model.forward(
            cast_tree(params, jnp.bfloat16), ids), np.float32)
        spread = np.abs(full).max()
        int8_err = np.abs(int8 - full).max()
        bf16_err = np.abs(bf16 - full).max()
        assert int8_err < 0.05 * spread, (int8_err, spread)
        # same neighborhood as the bf16 cast (weight-only int8 is
        # usually BETTER than casting activations+weights to bf16)
        assert int8_err < 4 * bf16_err + 1e-3, (int8_err, bf16_err)

        l_full = float(model.lm_loss(params, ids, train=False))
        l_int8 = float(model.lm_loss(q_tree(params), ids, train=False))
        assert abs(l_int8 - l_full) / abs(l_full) < 0.02

    def test_int8_engine_first_token_exact_and_decode_in_vocab(
            self, model, params):
        """Prefill stays full-precision under the int8 preset, so the
        FIRST generated token is exact; decode tokens must be valid."""
        p = np.asarray([1, 2, 3, 4, 5], np.int32)
        with DecodeEngine(model, params, slots=2, page_size=8,
                          quantization="int8") as eng:
            got = eng.generate(p, 6)
            assert eng.stats()["quantization"] == "int8"
        want = _solo(model, params, p, 6)
        assert got[0] == want[0]
        assert got.min() >= 0 and got.max() < VOCAB

    def test_unknown_quantization_rejected(self, model, params):
        with pytest.raises(ValueError, match="quantization"):
            DecodeEngine(model, params, quantization="fp4")


# ------------------------------------------------------- validation
class TestValidation:
    def test_submit_rejects_bad_requests(self, model, params):
        eng = DecodeEngine(model, params, slots=2, page_size=8,
                           warm_start=False)
        try:
            with pytest.raises(ValueError, match="empty"):
                eng.submit(np.zeros((0,), np.int32), 4)
            with pytest.raises(ValueError, match="max_new_tokens"):
                eng.submit([1, 2], 0)
            with pytest.raises(ValueError, match="max_context"):
                eng.submit(np.zeros((40,), np.int32), 20)
            # batched prompts must be rejected, not silently
            # concatenated into one sequence
            with pytest.raises(ValueError, match="ONE sequence"):
                eng.submit(np.zeros((2, 5), np.int32), 4)
            # ... but the [1, t0] convenience shape is accepted
            assert eng.submit(np.asarray([[1, 2, 3]], np.int32),
                              1).result(60).shape == (1,)
        finally:
            eng.shutdown()

    def test_request_larger_than_pool_rejected_up_front(self, model,
                                                        params):
        eng = DecodeEngine(model, params, slots=2, page_size=8,
                           n_pages=3, warm_start=False)
        try:
            with pytest.raises(ValueError, match="KV pages"):
                eng.submit(np.zeros((20,), np.int32), 10)
        finally:
            eng.shutdown()

    def test_submit_after_shutdown_raises(self, model, params):
        eng = DecodeEngine(model, params, slots=2, page_size=8,
                           warm_start=False)
        eng.start()
        eng.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            eng.submit([1, 2], 3)

    def test_shutdown_fails_pending_requests_not_strands_them(
            self, model, params):
        eng = DecodeEngine(model, params, slots=2, page_size=8).start()
        req = eng.submit(np.asarray([1, 2, 3], np.int32), 12)
        eng.shutdown()
        assert req.done
        if req.finish_reason == "error":
            with pytest.raises(RuntimeError):
                req.result(timeout=1)

    def test_engine_thread_joined_on_shutdown(self, model, params):
        with DecodeEngine(model, params, slots=2, page_size=8,
                          warm_start=False) as eng:
            eng.generate([1, 2], 2)
        assert not any(t.name == "ServingEngine" and t.is_alive()
                       for t in threading.enumerate())


# ---------------------------------------------------- front-ends
class TestGenerativeInference:
    def test_parity_and_stats(self, model, params):
        from deeplearning4j_tpu.parallel.wrapper import (
            GenerativeInference,
        )

        p = np.asarray([2, 4, 6], np.int32)
        with GenerativeInference(model, params, slots=2,
                                 page_size=8) as gi:
            out = gi.output(p, 5)
            out2 = gi.output(p[None, :], 5)     # [1, t0] also accepted
            with pytest.raises(ValueError, match="ONE sequence"):
                gi.output(np.zeros((2, 3), np.int32), 4)
            assert gi.n_requests == 2
            assert gi.n_dispatches >= 1
            assert gi.stats()["decode_steps"] >= 8
            assert gi.stats()["completed"] == 2
        np.testing.assert_array_equal(out, _solo(model, params, p, 5))
        np.testing.assert_array_equal(out2, out)


class TestHttpServing:
    def test_generate_endpoint_parity_info_stats(self, model, params):
        from deeplearning4j_tpu.remote.server import (
            JsonModelServer, JsonRemoteInference,
        )

        eng = DecodeEngine(model, params, slots=2, page_size=8)
        srv = JsonModelServer(engine=eng)
        port = srv.start()
        try:
            cli = JsonRemoteInference(f"http://127.0.0.1:{port}")
            p = np.asarray([1, 3, 5, 7], np.int32)
            got = cli.generate(p, 6)
            np.testing.assert_array_equal(
                got, _solo(model, params, p, 6))
            # concurrent HTTP clients share the engine's slots
            with ThreadPoolExecutor(max_workers=4) as ex:
                outs = list(ex.map(lambda _: cli.generate(p, 6),
                                   range(4)))
            for o in outs:
                np.testing.assert_array_equal(o, got)
            import json
            import urllib.request
            info = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/serving/info",
                timeout=10).read())
            assert info["engine"]["slots"] == 2
            stats = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/serving/stats",
                timeout=10).read())
            assert stats["completed"] == 5
        finally:
            srv.stop()
            eng.shutdown()

    def test_server_requires_model_or_engine(self):
        from deeplearning4j_tpu.remote.server import JsonModelServer

        with pytest.raises(ValueError, match="model"):
            JsonModelServer()


# ------------------------------------------------------------ telemetry
class TestServingTelemetry:
    def test_gauges_histograms_counters_populated(self, model, params):
        reg = telemetry.MetricsRegistry.get_default()
        with DecodeEngine(model, params, slots=2, page_size=8) as eng:
            eid = eng.engine_id      # fresh per engine: counts start 0
            eng.generate(np.asarray([1, 2, 3], np.int32), 5)
            eng.generate(np.asarray([4, 5], np.int32), 3)
            occ = reg.gauge(telemetry.SERVING_SLOT_OCCUPANCY).value(
                engine=eid)
            assert 0 <= occ <= 1
            # all pages freed -> utilization gauge back to 0
            assert reg.gauge(
                telemetry.SERVING_KV_PAGE_UTILIZATION).value(
                engine=eid) == 0.0
            snap = telemetry.serving_snapshot()
            for key in ("request_latency", "ttft", "slot_occupancy",
                        "queue_depth", "kv_page_utilization",
                        "tokens_total"):
                assert key in snap, key
            # per-engine label sets fold into fleet-level aggregates
            assert eid in snap["engines"]
            assert snap["aggregate"]["requests_total"] >= 2
            assert "serving" in telemetry.snapshot()
        # cumulative history survives shutdown...
        lat = reg.histogram(telemetry.SERVING_REQUEST_LATENCY)
        assert lat.count(reason="length", engine=eid) == 2
        pct = lat.percentiles(reason="length", engine=eid)
        assert pct["p50"] > 0 and pct["p99"] >= pct["p50"]
        assert reg.histogram(telemetry.SERVING_TTFT).count(
            engine=eid) == 2
        # ...but the engine's GAUGE series are retired (stale-series
        # expiry: no ghost engine frozen at its last reading) and it
        # leaves the live-engine roster while aggregates keep its
        # traffic
        snap = telemetry.serving_snapshot()
        assert eid not in snap["engines"]
        assert snap["aggregate"]["requests_total"] >= 2
        occ_series = reg.gauge(telemetry.SERVING_SLOT_OCCUPANCY).values()
        assert (("engine", eid),) not in occ_series

    def test_two_engines_are_distinguishable_series(self, model,
                                                    params):
        """The fleet-correctness contract: two engines in one process
        must NOT merge their metrics into one series."""
        reg = telemetry.MetricsRegistry.get_default()
        a = DecodeEngine(model, params, slots=2, page_size=8,
                         prefill_buckets=[8], max_chunk=2)
        b = DecodeEngine(model, params, slots=2, page_size=8,
                         prefill_buckets=[8], max_chunk=2,
                         warm_source=a)
        a.start()          # warm a first so b can adopt its programs
        assert a.engine_id != b.engine_id
        try:
            a.generate(np.asarray([1, 2], np.int32), 2)
            a.generate(np.asarray([2, 3], np.int32), 2)
            b.generate(np.asarray([1, 2], np.int32), 2)
        finally:
            a.shutdown()
            b.shutdown()
        req = reg.counter(telemetry.SERVING_REQUESTS)
        assert req.value(engine=a.engine_id) == 2
        assert req.value(engine=b.engine_id) == 1
        lat = reg.histogram(telemetry.SERVING_REQUEST_LATENCY)
        assert lat.count(reason="length", engine=a.engine_id) == 2
        assert lat.count(reason="length", engine=b.engine_id) == 1

    def test_dashboard_has_serving_card(self):
        from deeplearning4j_tpu.ui.server import _DASHBOARD_HTML

        assert "Serving (continuous-batching decode engine)" \
            in _DASHBOARD_HTML
        assert "dl4j_tpu_serving_request_latency_seconds" \
            in _DASHBOARD_HTML
