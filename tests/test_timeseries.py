"""Embedded time-series store (profiler/timeseries.py): tiered
storage + retention, PromQL-lite parsing/evaluation (rate with
counter-reset clamping, windowed histogram quantiles, aggregation),
the shared-capture sampler (one registry.capture() per tick feeds the
store AND the SLO engine), tombstones, worker metric federation
(control-dir file lease + HTTP push, SIGKILL-respawn survival), the
/v1/query(_range) HTTP surface on both servers, and the off-by-default
contract (DL4J_TPU_TSDB=0: no sampler threads, no default store)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.profiler import slo, telemetry
from deeplearning4j_tpu.profiler import timeseries as ts


def _gauge_cap(**vals):
    """Hand-built registry-capture of unlabelled gauges."""
    return {name: {"kind": "gauge", "values": {(): float(v)}}
            for name, v in vals.items()}


def _counter_cap(name, v, **labels):
    key = tuple(sorted((k, str(val)) for k, val in labels.items()))
    return {name: {"kind": "counter", "values": {key: float(v)}}}


def _hist_cap(name, count, total, buckets, bounds=(0.1, 0.5, 1.0),
              **labels):
    key = tuple(sorted((k, str(val)) for k, val in labels.items()))
    return {name: {"kind": "histogram", "bounds": tuple(bounds),
                   "series": {key: (float(count), float(total),
                                    tuple(buckets))}}}


# ================================================================ store
class TestStore:
    def test_ingest_and_select_real_capture(self):
        reg = telemetry.MetricsRegistry()
        reg.gauge("g").set(3.5, engine="e0")
        reg.counter("c").inc(7)
        db = ts.TimeSeriesDB()
        db.ingest(100.0, reg.capture())
        rows = db.select("g", [], 0.0, 200.0)
        assert len(rows) == 1
        labels, kind, _bounds, pts = rows[0]
        assert labels == {"engine": "e0"} and kind == "gauge"
        assert pts == [(100.0, 3.5)]
        assert db.series_count() == 2

    def test_last_sample_wins_within_downsample_bucket(self):
        s = ts._Series("g", (), "gauge")
        s.add(3.0, 1.0)
        s.add(7.0, 2.0)   # same 10s bucket: replaces in coarse tiers
        s.add(12.0, 3.0)
        raw = [p for p in s.tiers[0][1]]
        t10 = [p for p in s.tiers[1][1]]
        assert raw == [(3.0, 1.0), (7.0, 2.0), (12.0, 3.0)]
        assert t10 == [(7.0, 2.0), (12.0, 3.0)]

    def test_merged_tiers_back_raw_with_downsampled_tail(self):
        """Once the raw ring wraps, queries over the full span see
        raw-resolution recent history backed by the 10 s tier."""
        db = ts.TimeSeriesDB()
        for i in range(700):           # raw tier keeps 600
            db.ingest(float(i), _gauge_cap(g=i))
        (_l, _k, _b, pts), = db.select("g", [], 0.0, 699.0)
        raw_start = 100.0              # 700 - 600
        coarse = [p for p in pts if p[0] < raw_start]
        fine = [p for p in pts if p[0] >= raw_start]
        assert len(fine) == 600
        # 10 buckets of 10 s each cover t in [0, 100): last-wins
        assert [p[0] for p in coarse] == [9.0 + 10 * i
                                          for i in range(10)]
        assert pts == sorted(pts)

    def test_tombstone_excludes_at_instant_keeps_history(self):
        db = ts.TimeSeriesDB()
        db.ingest(10.0, _gauge_cap(g=1.0))
        assert db.tombstone("nope", "x") == 0
        # unlabelled gauge: tombstone by label only hits labelled ones
        reg = telemetry.MetricsRegistry()
        reg.gauge("occ").set(0.5, engine="dead")
        reg.gauge("occ").set(0.6, engine="alive")
        db.ingest(20.0, reg.capture())
        assert db.tombstone("engine", "dead", t=25.0) == 1
        # instant at/after the tombstone: the dead series is gone
        alive = db.select("occ", [], 0.0, 30.0, at=30.0)
        assert [r[0] for r in alive] == [{"engine": "alive"}]
        # history before the tombstone is still readable (at=None)
        hist = db.select("occ", [], 0.0, 30.0)
        assert {r[0]["engine"] for r in hist} == {"dead", "alive"}

    def test_reingest_clears_tombstone(self):
        db = ts.TimeSeriesDB()
        reg = telemetry.MetricsRegistry()
        reg.gauge("occ").set(0.5, engine="e")
        db.ingest(10.0, reg.capture())
        db.tombstone("engine", "e", t=11.0)
        assert db.select("occ", [], 0.0, 99.0, at=50.0) == []
        db.ingest(20.0, reg.capture())   # the id came back
        assert db.select("occ", [], 0.0, 99.0, at=50.0)

    def test_export_shape_and_bounds(self):
        db = ts.TimeSeriesDB()
        db.ingest(10.0, _gauge_cap(g=1.0))
        db.ingest(10.0, _hist_cap("h", 5, 1.5, (0, 5, 0, 0)))
        snap = db.export(window_s=60.0, now=20.0)
        assert snap["window_s"] == 60.0 and snap["now"] == 20.0
        by_name = {e["name"]: e for e in snap["series"]}
        assert by_name["g"]["points"] == [[10.0, 1.0]]
        assert by_name["h"]["bounds"] == [0.1, 0.5, 1.0]
        assert by_name["h"]["points"] == [[10.0, [5.0, 1.5,
                                                  [0.0, 5.0, 0.0,
                                                   0.0]]]]
        json.dumps(snap)                 # JSON-serializable as-is

    def test_export_truncates_oldest_registered(self):
        db = ts.TimeSeriesDB()
        for i in range(5):
            db.ingest(10.0, _gauge_cap(**{f"g{i}": float(i)}))
        snap = db.export(window_s=60.0, now=20.0, max_series=2)
        assert snap["series_truncated"] == 3
        assert [e["name"] for e in snap["series"]] == ["g3", "g4"]


# =============================================================== parser
class TestParser:
    def test_selector_with_matchers(self):
        node = ts.parse('m{a="x",b!="y",c=~"z.*",d!~"q"}')
        assert node[0] == "selector" and node[1] == "m"
        assert [(m.label, m.op, m.value) for m in node[2]] == [
            ("a", "=", "x"), ("b", "!=", "y"),
            ("c", "=~", "z.*"), ("d", "!~", "q")]

    def test_durations(self):
        assert ts.parse("rate(m[90s])")[1][2] == 90.0
        assert ts.parse("rate(m[2m])")[1][2] == 120.0
        assert ts.parse("rate(m[1h])")[1][2] == 3600.0
        assert ts.parse("rate(m[30])")[1][2] == 30.0   # bare seconds

    def test_agg_with_and_without_by(self):
        node = ts.parse("avg by (engine, host) (rate(m[30s]))")
        assert node[:3] == ("agg", "avg", ["engine", "host"])
        assert ts.parse("max (m)")[:3] == ("agg", "max", None)

    @pytest.mark.parametrize("bad", [
        "", "   ", "rate(m)", "rate(m[30s]) extra", 'm{a="x"',
        'm{a~"x"}', "histogram_quantile(1.5, m[30s])",
        'm{a=~"[unclosed"}', "rate(", "avg by () (m)",
    ])
    def test_malformed_raises_query_error(self, bad):
        with pytest.raises(ts.QueryError):
            ts.parse(bad)


# ============================================================ evaluator
class TestEval:
    def test_instant_selector_staleness_lookback(self):
        db = ts.TimeSeriesDB()
        db.ingest(1000.0, _gauge_cap(g=1.0))
        assert ts.query("g", t=1000.0 + ts.LOOKBACK_S - 1,
                        db=db) == [({}, 1.0)]
        assert ts.query("g", t=1000.0 + ts.LOOKBACK_S + 1,
                        db=db) == []

    def test_rate_golden(self):
        db = ts.TimeSeriesDB()
        for t, v in ((0.0, 0), (1.0, 5), (2.0, 10), (3.0, 15)):
            db.ingest(t, _counter_cap("c", v))
        (_l, v), = ts.query("rate(c[10s])", t=3.0, db=db)
        assert v == pytest.approx(5.0)
        (_l, v), = ts.query("increase(c[10s])", t=3.0, db=db)
        assert v == pytest.approx(15.0)

    def test_rate_needs_two_samples(self):
        db = ts.TimeSeriesDB()
        db.ingest(0.0, _counter_cap("c", 5))
        assert ts.query("rate(c[10s])", t=1.0, db=db) == []

    def test_rate_clamps_counter_reset(self):
        """0 -> 10 -> (restart) 2 -> 4: the reset contributes the
        post-restart level, never a negative delta."""
        db = ts.TimeSeriesDB()
        for t, v in ((0.0, 0), (1.0, 10), (2.0, 2), (3.0, 4)):
            db.ingest(t, _counter_cap("c", v))
        (_l, v), = ts.query("increase(c[10s])", t=3.0, db=db)
        assert v == pytest.approx(14.0)   # 10 + 2 + 2

    def test_histogram_quantile_windowed_golden(self):
        db = ts.TimeSeriesDB()
        db.ingest(0.0, _hist_cap("h", 0, 0.0, (0, 0, 0, 0)))
        db.ingest(10.0, _hist_cap("h", 10, 3.0, (0, 10, 0, 0)))
        (_l, q), = ts.query("histogram_quantile(0.5, h[30s])",
                            t=10.0, db=db)
        assert q == pytest.approx(0.3)   # midpoint of (0.1, 0.5]

    def test_histogram_reset_adds_postreset_buckets(self):
        db = ts.TimeSeriesDB()
        db.ingest(0.0, _hist_cap("h", 50, 5.0, (50, 0, 0, 0)))
        db.ingest(10.0, _hist_cap("h", 4, 2.0, (0, 4, 0, 0)))
        (_l, q), = ts.query("histogram_quantile(0.5, h[30s])",
                            t=10.0, db=db)
        assert 0.1 < q <= 0.5            # only post-reset obs count

    def test_count_sum_suffixes_and_bare_histogram_rate(self):
        db = ts.TimeSeriesDB()
        db.ingest(0.0, _hist_cap("h", 0, 0.0, (0, 0, 0, 0)))
        db.ingest(10.0, _hist_cap("h", 10, 3.0, (0, 10, 0, 0)))
        (_l, v), = ts.query("rate(h_count[30s])", t=10.0, db=db)
        assert v == pytest.approx(1.0)
        (_l, v), = ts.query("rate(h_sum[30s])", t=10.0, db=db)
        assert v == pytest.approx(0.3)
        # bare histogram name under rate(): the cumulative count
        (_l, v), = ts.query("rate(h[30s])", t=10.0, db=db)
        assert v == pytest.approx(1.0)
        # plain instant selector skips histogram series
        assert ts.query("h", t=10.0, db=db) == []

    def test_agg_by_label(self):
        db = ts.TimeSeriesDB()
        reg = telemetry.MetricsRegistry()
        g = reg.gauge("q")
        g.set(1.0, engine="a", host="h1")
        g.set(3.0, engine="b", host="h1")
        g.set(5.0, engine="c", host="h2")
        db.ingest(10.0, reg.capture())
        out = dict((lab["host"], v) for lab, v in ts.query(
            "sum by (host) (q)", t=10.0, db=db))
        assert out == {"h1": 4.0, "h2": 5.0}
        (lab, v), = ts.query("max (q)", t=10.0, db=db)
        assert lab == {} and v == 5.0

    def test_query_range_golden_and_limits(self):
        db = ts.TimeSeriesDB()
        for i in range(5):
            db.ingest(float(i), _counter_cap("c", 2 * i))
        (lab, pts), = ts.query_range("rate(c[10s])", 1.0, 4.0, 1.0,
                                     db=db)
        assert [t for t, _v in pts] == [1.0, 2.0, 3.0, 4.0]
        assert all(v == pytest.approx(2.0) for _t, v in pts[1:])
        with pytest.raises(ts.QueryError):
            ts.query_range("c", 0.0, 10.0, 0.0, db=db)
        with pytest.raises(ts.QueryError):
            ts.query_range("c", 10.0, 0.0, 1.0, db=db)
        with pytest.raises(ts.QueryError):
            ts.query_range("c", 0.0, 1e6, 0.01, db=db)

    def test_tombstoned_series_vanish_from_instants_not_ranges(self):
        db = ts.TimeSeriesDB()
        reg = telemetry.MetricsRegistry()
        reg.gauge("occ").set(0.9, engine="dead")
        db.ingest(10.0, reg.capture())
        db.ingest(20.0, reg.capture())
        db.tombstone("engine", "dead", t=25.0)
        assert ts.query('occ{engine="dead"}', t=30.0, db=db) == []
        # range evaluation BEFORE the tombstone still sees history
        rows = ts.query_range('occ{engine="dead"}', 10.0, 20.0, 5.0,
                              db=db)
        assert rows and len(rows[0][1]) == 3


# ============================================================== sampler
class TestSampler:
    def test_one_capture_per_tick_shared_with_slo_engine(self):
        """Satellite: the SLO engine attached to the sampler and the
        store itself share ONE registry.capture() per tick."""
        reg = telemetry.MetricsRegistry()
        calls = {"n": 0}
        orig = reg.capture

        def counting():
            calls["n"] += 1
            return orig()

        reg.capture = counting
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg, interval_s=60.0)
        eng = slo.SLOEngine(
            [slo.Threshold("hot", metric="g", bound=0.9, op=">",
                           for_s=0.0)],
            registry=reg, make_default=False, sampler=sampler)
        # attached engine refuses to start its own thread
        assert eng.start() is eng and eng._thread is None
        reg.gauge("g").set(1.0)
        sampler.tick_once(now_mono=100.0, now_wall=1000.0)
        assert calls["n"] == 1
        assert eng.alert_state("hot") == "firing"
        assert db.select("g", [], 0.0, 2000.0)
        sampler.tick_once(now_mono=101.0, now_wall=1001.0)
        assert calls["n"] == 2
        eng.shutdown()

    def test_sampler_thread_lifecycle(self):
        reg = telemetry.MetricsRegistry()
        reg.gauge("g").set(1.0)
        sampler = ts.Sampler(db=ts.TimeSeriesDB(), registry=reg,
                             interval_s=0.05).start()
        names = [t.name for t in threading.enumerate()]
        assert ts.Sampler.THREAD_NAME in names
        deadline = time.time() + 30
        while sampler.ticks < 2 and time.time() < deadline:
            time.sleep(0.02)
        sampler.shutdown()
        assert sampler.ticks >= 2
        assert ts.Sampler.THREAD_NAME not in [
            t.name for t in threading.enumerate() if t.is_alive()]

    def test_subscriber_exception_does_not_stop_ingest(self):
        reg = telemetry.MetricsRegistry()
        reg.gauge("g").set(1.0)
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg)

        def boom(_t, _w, _cap):
            raise RuntimeError("subscriber bug")

        sampler.subscribe(boom)
        sampler.tick_once(now_mono=1.0, now_wall=10.0)
        sampler.tick_once(now_mono=2.0, now_wall=11.0)
        (_l, _k, _b, pts), = db.select("g", [], 0.0, 99.0)
        assert len(pts) == 2


# =========================================================== federation
class TestFederation:
    def test_encode_decode_roundtrip(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("c").inc(5, engine="e0")
        reg.gauge("g").set(1.5)
        reg.histogram("h").observe(0.3, engine="e0")
        cap = reg.capture()
        wire = json.loads(json.dumps(ts.encode_capture(cap)))
        back = ts.decode_capture(wire)
        assert back == cap

    def test_decode_skips_malformed_metrics(self):
        wire = {"ok": {"kind": "gauge", "values": [[[], 2.0]]},
                "torn": {"kind": "histogram", "bounds": "nope"},
                "alien": {"kind": "widget"}}
        back = ts.decode_capture(wire)
        assert list(back) == ["ok"]
        assert back["ok"]["values"] == {(): 2.0}

    def test_ingest_remote_merges_under_worker_labels(self):
        reg = telemetry.MetricsRegistry()
        reg.gauge("g").set(1.0)
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg)
        rreg = telemetry.MetricsRegistry()
        rreg.gauge("g").set(2.0, engine="e9")
        rreg.counter("c").inc(3)
        sampler.ingest_remote(rreg.capture(), "w0", host="hostA",
                              t=1000.0)
        sampler.tick_once(now_mono=1.0, now_wall=1000.5)
        assert sampler.remote_workers() == ["w0"]
        vec = ts.query('g{worker="w0"}', t=1000.5, db=db)
        assert vec == [({"engine": "e9", "worker": "w0",
                         "host": "hostA"}, 2.0)]
        # the local series has NO worker label
        assert ts.query('g{worker=""}', t=1000.5, db=db) == \
            [({}, 1.0)]

    def test_stale_remote_expires_after_ttl(self):
        reg = telemetry.MetricsRegistry()
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg, remote_ttl_s=5.0)
        rreg = telemetry.MetricsRegistry()
        rreg.gauge("g").set(2.0)
        sampler.ingest_remote(rreg.capture(), "w0", t=1000.0)
        sampler.tick_once(now_mono=1.0, now_wall=1004.0)   # fresh
        sampler.tick_once(now_mono=2.0, now_wall=1006.0)   # expired
        (_l, _k, _b, pts), = db.select("g", [], 0.0, 9999.0)
        assert [t for t, _v in pts] == [1004.0]

    def test_ingest_push_roundtrip_and_off_mode(self):
        assert ts.default_sampler() is None
        payload = {"worker": "w0",
                   "capture": {"g": {"kind": "gauge",
                                     "values": [[[], 4.0]]}}}
        assert ts.ingest_push(payload) is False   # no sampler: off
        reg = telemetry.MetricsRegistry()
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg)
        ts.install(db, sampler)
        try:
            assert ts.ingest_push(payload) is True
            assert ts.ingest_push({"capture": {}}) is False
            sampler.tick_once(now_mono=1.0, now_wall=1000.0)
            assert ts.query('g{worker="w0"}', t=1000.0, db=db)
        finally:
            ts.install(None, None)

    @pytest.mark.slow
    def test_rate_survives_worker_sigkill_respawn(self, monkeypatch):
        """Satellite: a federated worker series keeps answering
        rate() across a SIGKILL + respawn — the respawned process's
        counter restarts from zero and the reset clamp keeps the rate
        finite and non-negative, with fresh samples resuming."""
        from deeplearning4j_tpu import control

        monkeypatch.setenv("DL4J_TPU_TSDB", "1")
        reg = telemetry.MetricsRegistry()
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg,
                             interval_s=0.1).start()
        ts.install(db, sampler)
        expr = ('rate(dl4j_tpu_worker_drill_steps_total'
                '{worker="w0"}[5s])')
        try:
            with control.WorkerSupervisor(
                    ["w0"], heartbeat_s=0.1, lease_s=10.0,
                    restart_delay_s=0.1) as sup:
                task = sup.submit_task(
                    "deeplearning4j_tpu.control.worker:spin_task", {})
                deadline = time.time() + 120

                def rate_now():
                    vec = ts.query(expr, db=db)
                    return vec[0][1] if vec else 0.0

                # the same published capture is merged at every tick
                # until the worker's next 0.5 s publish, so wait for a
                # POSITIVE rate (two distinct counter levels), not
                # just for the series to exist
                while rate_now() <= 0 and time.time() < deadline:
                    time.sleep(0.1)
                vec = ts.query(expr, db=db)
                assert vec and vec[0][0]["worker"] == "w0"
                assert vec[0][1] > 0
                sup.kill("w0")

                def respawned():
                    st = sup.workers_status()["w0"]
                    return st["restarts"] >= 1 \
                        and st["state"] == "alive"

                while not respawned() and time.time() < deadline:
                    time.sleep(0.1)
                assert respawned()
                # fresh post-respawn captures arrive (new publish t)
                t_kill = time.time()

                def fresh_pts():
                    rows = db.select(
                        "dl4j_tpu_worker_drill_steps_total", [],
                        t_kill, time.time() + 1)
                    return [p for r in rows for p in r[3]
                            if p[0] > t_kill + 0.5]

                while not fresh_pts() and time.time() < deadline:
                    time.sleep(0.1)
                assert fresh_pts()
                vec = ts.query(expr, db=db)
                assert vec and vec[0][1] >= 0.0   # reset-clamped
                sup.preempt("w0", deadline_s=30)   # clean drain
                while task.state == "running" \
                        and time.time() < deadline:
                    time.sleep(0.05)
        finally:
            sampler.shutdown()
            ts.install(None, None)


# ================================================================= HTTP
class TestHTTP:
    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return json.loads(r.read()), r.status

    def test_query_endpoints_on_ui_server(self):
        from deeplearning4j_tpu.ui.server import UIServer

        reg = telemetry.MetricsRegistry()
        db = ts.TimeSeriesDB()
        sampler = ts.Sampler(db=db, registry=reg)
        reg.counter("c").inc(5, engine="e0")
        now = time.time()
        db.ingest(now - 10, _counter_cap("c", 0, engine="e0"))
        sampler.tick_once(now_mono=1.0, now_wall=now)
        ts.install(db, sampler)
        srv = UIServer()
        port = srv.start(port=0)
        try:
            obj, code = self._get(
                port, "/v1/query?query=rate(c%5B30s%5D)")
            assert code == 200 and obj["status"] == "success"
            res = obj["data"]["result"]
            assert obj["data"]["resultType"] == "vector"
            assert res[0]["metric"] == {"engine": "e0"}
            assert float(res[0]["value"][1]) == pytest.approx(0.5)
            obj, _code = self._get(
                port, f"/v1/query_range?query=c&start={now - 10}"
                      f"&end={now}&step=5")
            assert obj["data"]["resultType"] == "matrix"
            assert obj["data"]["result"][0]["values"]
            # instant selector carries __name__ (Prometheus shape)
            obj, _code = self._get(port, "/v1/query?query=c")
            assert obj["data"]["result"][0]["metric"]["__name__"] \
                == "c"
            # malformed expression: structured 400
            try:
                self._get(port, "/v1/query?query=rate(c")
                assert False, "expected HTTP 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert json.loads(e.read())["status"] == "error"
            # federation push fallback lands in the sampler
            body = json.dumps({
                "worker": "w9",
                "capture": {"g": {"kind": "gauge",
                                  "values": [[[], 4.0]]}}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/metrics/push",
                data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read())["ok"] is True
            assert sampler.remote_workers() == ["w9"]
        finally:
            srv.stop()
            ts.install(None, None)

    def test_query_endpoints_on_remote_server(self):
        from deeplearning4j_tpu.remote.server import JsonModelServer

        db = ts.TimeSeriesDB()
        db.ingest(time.time(), _gauge_cap(g=2.5))
        ts.install(db)
        srv = JsonModelServer(model=object())
        port = srv.start()
        try:
            obj, code = self._get(port, "/v1/query?query=g")
            assert code == 200
            assert float(obj["data"]["result"][0]["value"][1]) == 2.5
            now = time.time()
            obj, _code = self._get(
                port, f"/v1/query_range?query=g&start={now - 60}"
                      f"&end={now}&step=10")
            assert obj["data"]["result"][0]["values"]
        finally:
            srv.stop()
            ts.install(None, None)

    def test_http_404_with_hint_when_store_off(self):
        assert ts.default_db() is None
        obj, code = ts.http_query("query=g")
        assert code == 404 and "DL4J_TPU_TSDB" in obj["error"]
        obj, code = ts.http_query_range(
            "query=g&start=0&end=1&step=1")
        assert code == 404

    def test_http_nonfinite_values_as_strings(self):
        db = ts.TimeSeriesDB()
        db.ingest(100.0, _gauge_cap(g=float("inf")))
        ts.install(db)
        try:
            obj, code = ts.http_query("query=g&time=100")
            assert code == 200
            assert obj["data"]["result"][0]["value"][1] == "+Inf"
        finally:
            ts.install(None, None)


# ============================================================= off mode
class TestOffByDefault:
    def test_ensure_default_is_noop_when_disabled(self):
        assert ts.enabled() is False     # suite runs with TSDB off
        assert ts.ensure_default() is None
        assert ts.default_db() is None
        assert ts.default_sampler() is None
        assert ts.Sampler.THREAD_NAME not in {
            t.name for t in threading.enumerate()}
        assert ts.metrics_history_snapshot() == {}
        assert ts.snapshot() == {}
        assert ts.tombstone_series("engine", "x") == 0

    def test_telemetry_snapshot_has_no_timeseries_when_off(self):
        snap = telemetry.snapshot()
        assert "timeseries" not in snap

    def test_retire_engine_series_tolerates_no_store(self):
        # the sys.modules-guarded hook: no default store installed
        assert ts.default_db() is None
        telemetry.retire_engine_series("ghost-engine")
