"""End-of-suite EXECUTIONAL mapper-coverage gate (reference: the
OpValidation coverage-accounting role — SURVEY.md §4 — applied to the
import layer §2.14/§2.32: `TFGraphTestAllSameDiff` + mapping-rule
coverage fails the build for unexercised mappers).

Every TF/ONNX/Keras mapper DISPATCHED on a real node during an import
records itself ("<fw>:<name>", modelimport/trace.py); test subprocesses
append their sets via DL4J_TPU_MAPPER_TRACE_FILE (conftest). The zzz
name puts this module last in pytest's alphabetical collection, so by
the time it runs the whole suite has executed. A registered mapper no
test ever DROVE — not merely mentioned — fails the gate unless it
carries a conscious, reasoned EXEMPT entry.
"""

import glob
import os

import pytest

from deeplearning4j_tpu.modelimport.keras.keras_import import (
    supported_layer_names,
)
from deeplearning4j_tpu.modelimport.onnx.onnx_import import (
    OnnxOpMappingRegistry,
)
from deeplearning4j_tpu.modelimport.tensorflow import cf_import
from deeplearning4j_tpu.modelimport.tensorflow.tf_import import (
    OpMappingRegistry,
)
from deeplearning4j_tpu.modelimport.trace import driven_mappers

#: mapper key -> reason it is allowed to skip execution accounting.
#: Every entry is a conscious decision; an entry whose mapper starts
#: being driven again is flagged stale below.
_REF_REASON = (
    "TF1 ref-dtype variant: registered as an alias of the non-Ref op "
    "in every dispatch table (WALKER_OPS, _LOOP_OPS, plan_v1_frames' "
    "op checks — same code path, driven via the non-Ref name); modern "
    "TF cannot emit Ref* nodes, so no live producer can generate a "
    "test graph. Kept for ancient-GraphDef parity.")

EXEMPT = {
    "tf:RefEnter": _REF_REASON,
    "tf:RefExit": _REF_REASON,
    "tf:RefMerge": _REF_REASON,
    "tf:RefNextIteration": _REF_REASON,
    "tf:RefSwitch": _REF_REASON,
}


def registered_mappers():
    out = [f"tf:{n}" for n in OpMappingRegistry.coverage()]
    out += [f"tf:{n}" for n in sorted(cf_import.WALKER_OPS)
            if f"tf:{n}" not in out]
    out += [f"onnx:{n}" for n in OnnxOpMappingRegistry.coverage()]
    out += ["onnx:If", "onnx:Loop"]  # walker-dispatched, not in registry
    out += [f"keras:{n}" for n in supported_layer_names()]
    return sorted(set(out))


def _missing(registered, driven, exempt):
    return [m for m in registered if m not in driven and m not in exempt]


def test_gate_logic_catches_undriven_mappers():
    assert _missing(["tf:Ghost"], set(), {}) == ["tf:Ghost"]
    assert _missing(["tf:Ghost"], {"tf:Ghost"}, {}) == []
    assert _missing(["tf:Ghost"], set(), {"tf:Ghost": "why"}) == []


def test_registry_sizes_sane():
    reg = registered_mappers()
    by_fw = {fw: sum(1 for m in reg if m.startswith(fw + ":"))
             for fw in ("tf", "onnx", "keras")}
    assert by_fw["tf"] >= 190, by_fw
    assert by_fw["onnx"] >= 120, by_fw
    assert by_fw["keras"] >= 50, by_fw


def test_every_registered_mapper_is_driven_by_the_suite(request):
    here = os.path.dirname(os.path.abspath(__file__))
    all_mods = {os.path.basename(p)
                for p in glob.glob(os.path.join(here, "test_*.py"))}
    ran_mods = {os.path.basename(str(i.fspath))
                for i in request.session.items}
    partial = all_mods - ran_mods
    if partial:
        pytest.skip(
            f"partial run ({len(partial)} test modules not collected) "
            "— the executional gate is enforced on full-suite runs")
    driven = driven_mappers()
    missing = _missing(registered_mappers(), driven, EXEMPT)
    assert not missing, (
        f"{len(missing)} registered import mappers were never DRIVEN "
        f"by the suite (reference parity: TFGraphTestAllSameDiff + "
        f"OpValidation coverage role); add an import golden or a "
        f"reasoned EXEMPT entry: {missing}")
    stale = [m for m in EXEMPT if m in driven]
    assert not stale, (
        f"EXEMPT entries whose mappers are now driven — remove them: "
        f"{stale}")
