"""RecordReaderMultiDataSetIterator (reference: deeplearning4j-data
RecordReaderMultiDataSetIterator — the builder feeding multi-input/
multi-output ComputationGraphs from named datavec readers)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import RecordReaderMultiDataSetIterator
from deeplearning4j_tpu.datavec.records import CollectionRecordReader


class _SeqReader(CollectionRecordReader):
    """Collection of sequences: record = [T][F]."""


def _flat_reader(rows):
    return CollectionRecordReader(rows)


class TestBuilderSpecs:
    def test_columns_and_one_hot(self):
        rows = [[0.1, 0.2, 0.3, 1], [0.4, 0.5, 0.6, 0],
                [0.7, 0.8, 0.9, 2], [1.0, 1.1, 1.2, 1]]
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addReader("r", _flat_reader(rows).initialize())
              .addInput("r", 0, 2)
              .addOutputOneHot("r", 3, 3)
              .build())
        mds = it.next()
        assert mds.features[0].shape == (2, 3)
        np.testing.assert_allclose(mds.features[0][0], [0.1, 0.2, 0.3])
        assert mds.labels[0].shape == (2, 3)
        np.testing.assert_array_equal(mds.labels[0][0], [0, 1, 0])
        mds2 = it.next()
        np.testing.assert_array_equal(mds2.labels[0][0], [0, 0, 1])
        assert not it.hasNext()

    def test_two_readers_lock_step(self):
        a = [[1.0, 0], [2.0, 1], [3.0, 0], [4.0, 1]]
        b = [[10.0], [20.0], [30.0], [40.0]]
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addReader("a", _flat_reader(a).initialize())
              .addReader("b", _flat_reader(b).initialize())
              .addInput("a", 0, 0)
              .addInput("b")
              .addOutputOneHot("a", 1, 2)
              .build())
        mds = it.next()
        assert mds.numFeatureArrays() == 2
        np.testing.assert_allclose(mds.features[0].ravel(), [1.0, 2.0])
        np.testing.assert_allclose(mds.features[1].ravel(), [10.0, 20.0])

    def test_unknown_reader_and_empty_specs_raise(self):
        with pytest.raises(ValueError, match="no reader named"):
            (RecordReaderMultiDataSetIterator.Builder(2)
             .addInput("missing"))
        with pytest.raises(ValueError, match="addInput"):
            (RecordReaderMultiDataSetIterator.Builder(2)
             .addReader("r", _flat_reader([[1.0]]).initialize())
             .build())

    def test_reset_supports_epochs(self):
        rows = [[1.0, 0], [2.0, 1]]
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addReader("r", _flat_reader(rows).initialize())
              .addInput("r", 0, 0).addOutputOneHot("r", 1, 2)
              .build())
        first = it.next().features[0]
        it.reset()
        np.testing.assert_array_equal(first, it.next().features[0])


class TestSequenceAlignment:
    def _ragged(self):
        s1 = [[1.0, 0], [2.0, 0], [3.0, 1]]          # T=3
        s2 = [[4.0, 1], [5.0, 0]]                    # T=2
        return _SeqReader([s1, s2]).initialize()

    def test_align_start_pads_end_with_masks(self):
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addSequenceReader("s", self._ragged())
              .addInput("s", 0, 0)
              .addOutputOneHot("s", 1, 2)
              .sequenceAlignmentMode("ALIGN_START")
              .build())
        mds = it.next()
        x = mds.features[0]
        assert x.shape == (2, 3, 1)
        np.testing.assert_allclose(x[1].ravel(), [4.0, 5.0, 0.0])
        m = mds.features_mask_arrays[0]
        np.testing.assert_array_equal(m, [[1, 1, 1], [1, 1, 0]])
        assert mds.labels[0].shape == (2, 3, 2)

    def test_align_end_pads_start(self):
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addSequenceReader("s", self._ragged())
              .addInput("s", 0, 0)
              .addOutputOneHot("s", 1, 2)
              .sequenceAlignmentMode("ALIGN_END")
              .build())
        mds = it.next()
        np.testing.assert_allclose(mds.features[0][1].ravel(),
                                   [0.0, 4.0, 5.0])
        np.testing.assert_array_equal(mds.features_mask_arrays[0][1],
                                      [0, 1, 1])

    def test_equal_length_mode_rejects_ragged(self):
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addSequenceReader("s", self._ragged())
              .addInput("s", 0, 0)
              .addOutputOneHot("s", 1, 2)
              .sequenceAlignmentMode("EQUAL_LENGTH")
              .build())
        with pytest.raises(ValueError, match="EQUAL_LENGTH"):
            it.next()

    def test_uniform_lengths_produce_no_masks(self):
        s = _SeqReader([[[1.0, 0], [2.0, 1]],
                        [[3.0, 1], [4.0, 0]]]).initialize()
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addSequenceReader("s", s)
              .addInput("s", 0, 0).addOutputOneHot("s", 1, 2)
              .build())
        mds = it.next()
        assert not mds.features_mask_arrays


class TestEndToEndGraphFit:
    def test_two_input_graph_trains(self):
        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, InputType, OutputLayer,
        )
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration, MergeVertex,
        )

        rng = np.random.RandomState(0)
        n = 64
        a = rng.randn(n, 3).astype(np.float64)
        bcol = rng.randn(n, 2).astype(np.float64)
        lab = ((a.sum(1) + bcol.sum(1)) > 0).astype(int)
        rows_a = np.hstack([a, lab[:, None]]).tolist()
        rows_b = bcol.tolist()

        it = (RecordReaderMultiDataSetIterator.Builder(16)
              .addReader("a", _flat_reader(rows_a).initialize())
              .addReader("b", _flat_reader(rows_b).initialize())
              .addInput("a", 0, 2)
              .addInput("b")
              .addOutputOneHot("a", 3, 2)
              .build())

        gb = (ComputationGraphConfiguration.graphBuilder()
              .seed(1).updater(Adam(learning_rate=0.02))
              .addInputs("ina", "inb")
              .setInputTypes(InputType.feedForward(3),
                             InputType.feedForward(2)))
        gb.addLayer("da", DenseLayer(n_out=8, activation="relu"), "ina")
        gb.addLayer("db", DenseLayer(n_out=8, activation="relu"), "inb")
        gb.addVertex("m", MergeVertex(), "da", "db")
        gb.addLayer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "m")
        net = ComputationGraph(gb.setOutputs("out").build()).init()
        net.fit(it, epochs=30)
        outs = net.output(a.astype(np.float32), bcol.astype(np.float32))
        acc = (np.asarray(outs[0].toNumpy()).argmax(1) == lab).mean()
        assert acc > 0.9, acc

    def test_single_bound_spec_rejected(self):
        b = (RecordReaderMultiDataSetIterator.Builder(2)
             .addReader("r", _flat_reader([[1.0, 2.0]]).initialize()))
        with pytest.raises(ValueError, match="BOTH col_from and col_to"):
            b.addInput("r", 1)


class TestLockStepMisalignment:
    def test_unequal_reader_lengths_raise(self):
        it = RecordReaderMultiDataSetIterator.Builder(2) \
            .addReader("a", _flat_reader(np.arange(8.).reshape(4, 2)).initialize()) \
            .addReader("b", _flat_reader(np.arange(12.).reshape(6, 2)).initialize()) \
            .addInput("a") \
            .addOutput("b") \
            .build()
        with pytest.raises(ValueError, match="lock-step"):
            for _ in it:
                pass
