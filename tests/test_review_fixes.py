"""Regression tests for the first code-review pass findings."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.learning import Sgd, StepSchedule
from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer, DenseLayer, InputType, NeuralNetConfiguration,
    OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import nn as nnops
from deeplearning4j_tpu.util import ModelSerializer
from deeplearning4j_tpu.datasets.normalizers import ImagePreProcessingScaler


def test_pool_explicit_padding_matches_shape_inference():
    layer = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), padding=(1, 2))
    it = InputType.convolutional(8, 8, 3)
    out_t = layer.output_type(it)
    x = jnp.ones((1, 8, 8, 3))
    out, _ = layer.apply({}, {}, x, False, None)
    assert out.shape == (1, out_t.height, out_t.width, 3)


def test_dilated_conv_shape_inference():
    layer = ConvolutionLayer(n_in=2, n_out=4, kernel_size=(3, 3),
                             dilation=(2, 2), convolution_mode="Truncate")
    it = InputType.convolutional(10, 10, 2)
    ot = layer.output_type(it)
    import jax

    p = layer.init_params(jax.random.key(0), it, jnp.float32)
    out, _ = layer.apply(p, {}, jnp.ones((1, 10, 10, 2)), False, None)
    assert out.shape == (1, ot.height, ot.width, 4) == (1, 6, 6, 4)


def test_sum_pooling_exact_on_same_padding():
    layer = SubsamplingLayer(pooling_type="sum", kernel_size=(3, 3),
                             stride=(1, 1), convolution_mode="Same")
    x = jnp.ones((1, 4, 4, 1))
    out, _ = layer.apply({}, {}, x, False, None)
    # corner window covers exactly 4 real pixels -> sum 4 (not 9*avg)
    assert float(out[0, 0, 0, 0]) == 4.0
    assert float(out[0, 1, 1, 0]) == 9.0


def test_epoch_schedule_counts_epochs():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    sched = StepSchedule(initial_value=1.0, decay_rate=0.5, step=1,
                         type="epoch")
    conf = (NeuralNetConfiguration.builder()
            .updater(Sgd(learning_rate=sched)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .setInputType(InputType.feedForward(4)).build())
    m = MultiLayerNetwork(conf).init()
    # 5 iterations all within epoch 0: LR must stay 1.0 throughout.
    # Compare against an iteration-typed schedule which would have decayed
    # to 1/16 by the 5th step; do this by measuring parameter movement.
    w0 = np.asarray(m.params_list[0]["W"]).copy()
    for _ in range(5):
        m.fit(DataSet(x, y))
    delta_epoch_mode = np.abs(np.asarray(m.params_list[0]["W"]) - w0).sum()

    sched_it = StepSchedule(initial_value=1.0, decay_rate=0.5, step=1,
                            type="iteration")
    conf2 = (NeuralNetConfiguration.builder()
             .updater(Sgd(learning_rate=sched_it)).list()
             .layer(DenseLayer(n_out=4, activation="tanh"))
             .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
             .setInputType(InputType.feedForward(4)).build())
    m2 = MultiLayerNetwork(conf2).init()
    for _ in range(5):
        m2.fit(DataSet(x, y))
    delta_iter_mode = np.abs(np.asarray(m2.params_list[0]["W"]) - w0).sum()
    assert delta_epoch_mode > delta_iter_mode


def test_evaluation_grows_for_int_labels():
    ev = Evaluation()
    ev.eval(np.array([0, 1]), np.array([0, 1]))
    ev.eval(np.array([3, 2]), np.array([3, 3]))  # higher class id later
    assert ev.confusionMatrix().shape == (4, 4)
    assert ev.accuracy() == 0.75


def test_manual_n_in_without_input_type():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    m = MultiLayerNetwork(conf).init()
    out = m.output(np.zeros((3, 4), np.float32))
    assert out.shape() == (3, 2)


def test_image_scaler_roundtrip(tmp_path):
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
            .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    m = MultiLayerNetwork(conf).init()
    p = str(tmp_path / "m.zip")
    ModelSerializer.writeModel(m, p, normalizer=ImagePreProcessingScaler())
    n = ModelSerializer.restoreNormalizer(p)
    assert isinstance(n, ImagePreProcessingScaler)


def test_output_train_mode_applies_dropout():
    from deeplearning4j_tpu.nn.conf import DropoutLayer

    conf = (NeuralNetConfiguration.builder().seed(3).list()
            .layer(DenseLayer(n_in=10, n_out=10, activation="identity"))
            .layer(DropoutLayer(rate=0.5))
            .layer(OutputLayer(n_in=10, n_out=10, activation="identity",
                               loss="mse"))
            .build())
    m = MultiLayerNetwork(conf).init()
    x = np.ones((4, 10), np.float32)
    o_infer = m.output(x).toNumpy()
    o_train1 = m.output(x, train=True).toNumpy()
    o_train2 = m.output(x, train=True).toNumpy()
    assert not np.allclose(o_train1, o_train2)  # stochastic in train mode
    np.testing.assert_array_equal(m.output(x).toNumpy(), o_infer)


def test_deconv_asymmetric_padding_matches_output_type():
    """ADVICE r1: asymmetric (ph != pw) Truncate deconv must agree with
    the layer's inferred output type."""
    from deeplearning4j_tpu.nn.conf.layers_extra import Deconvolution2D

    lay = Deconvolution2D(n_in=3, n_out=5, kernel_size=(3, 3),
                          stride=(2, 2), padding=(1, 0),
                          convolution_mode="Truncate")
    it = InputType.convolutional(6, 6, 3)
    out_t = lay.output_type(it)
    params = lay.init_params(__import__("jax").random.key(0), it,
                             jnp.float32)
    out, _ = lay.apply(params, {}, jnp.ones((2, 6, 6, 3)), False, None)
    assert out.shape == (2, out_t.height, out_t.width, out_t.channels)
    assert out_t.height != out_t.width  # asymmetry actually exercised


def test_masked_pooling_time_axis_mismatch_raises():
    """ADVICE r1: a strided layer between the masked input and a
    GlobalPoolingLayer must raise, not silently pool padding."""
    from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer
    from deeplearning4j_tpu.nn.conf.layers_extra import Convolution1D
    from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

    conf = (ComputationGraphConfiguration.graphBuilder()
            .addInputs("in")
            .setInputTypes(InputType.recurrent(4, 8))
            .addLayer("c", Convolution1D(
                n_out=6, kernel_size=2, stride=2), "in")
            .addLayer("pool", GlobalPoolingLayer(pooling_type="avg"), "c")
            .addLayer("out", OutputLayer(n_out=3, activation="softmax",
                                         loss="mcxent"), "pool")
            .setOutputs("out").build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 8, 4)).astype(np.float32)
    fmask = np.ones((2, 8), np.float32)
    fmask[:, 5:] = 0
    with pytest.raises(ValueError, match="changed the sequence length"):
        net.output(x, feature_masks=[fmask])


# ----------------------------------------------------------------------
# ADVICE r5 regression tests
# ----------------------------------------------------------------------
def test_normalizer_standardize_clears_stale_label_stats():
    """ADVICE r5: fitLabel(True)+fit() then fitLabel(False)+fit() must
    not keep normalizing labels with the previous fit's statistics."""
    from deeplearning4j_tpu.datasets.normalizers import (
        NormalizerStandardize,
    )

    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(5, 2, (32, 3)).astype(np.float32),
                 rng.normal(-4, 3, (32, 2)).astype(np.float32))
    norm = NormalizerStandardize().fitLabel(True)
    norm.fit(ds)
    assert norm.label_mean is not None
    norm.fitLabel(False)
    norm.fit(ds)
    assert norm.label_mean is None and norm.label_std is None
    labels = np.array(np.asarray(ds.labels))
    out = norm.transform(DataSet(np.asarray(ds.features).copy(), labels))
    np.testing.assert_array_equal(np.asarray(out.labels), labels)


def test_dataset_save_load_roundtrip_without_npz_suffix(tmp_path):
    """ADVICE r5: save(p) must write to EXACTLY p so load(p)
    round-trips on any path (np.savez silently appends '.npz')."""
    ds = DataSet(np.arange(8, dtype=np.float32).reshape(4, 2),
                 np.ones((4, 1), np.float32),
                 features_mask=None,
                 labels_mask=np.ones((4, 1), np.float32))
    for name in ("batch.bin", "batch.npz", "batch"):
        p = str(tmp_path / name)
        ds.save(p)
        import os
        assert os.path.exists(p), f"save wrote somewhere else for {name}"
        back = DataSet.load(p)
        np.testing.assert_array_equal(np.asarray(back.features),
                                      np.asarray(ds.features))
        np.testing.assert_array_equal(np.asarray(back.labels_mask),
                                      np.asarray(ds.labels_mask))


def _tiny_model():
    from deeplearning4j_tpu.learning import Sgd

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(1e-2))
            .list()
            .layer(DenseLayer(n_out=3, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(4)).build())
    return MultiLayerNetwork(conf).init()


def test_composite_normalizer_flat_roundtrip(tmp_path):
    from deeplearning4j_tpu.datasets.normalizers import (
        CompositeDataSetPreProcessor, NormalizerMinMaxScaler,
        NormalizerStandardize,
    )

    rng = np.random.default_rng(1)
    ds = DataSet(rng.normal(3, 2, (16, 4)).astype(np.float32),
                 np.ones((16, 2), np.float32))
    comp = CompositeDataSetPreProcessor(NormalizerStandardize(),
                                        NormalizerMinMaxScaler())
    comp.fit(ds)
    path = str(tmp_path / "model.zip")
    ModelSerializer.writeModel(_tiny_model(), path, normalizer=comp)
    back = ModelSerializer.restoreNormalizer(path)
    assert isinstance(back, CompositeDataSetPreProcessor)
    np.testing.assert_allclose(back.preprocessors[0].mean,
                               comp.preprocessors[0].mean, rtol=1e-6)


def test_composite_normalizer_rejects_nested_at_save(tmp_path):
    """ADVICE r5: a nested composite saved fine but crashed on restore
    (KeyError in the zero-arg registry + unrepresentable state paths)
    — now rejected at save time with the actual problem."""
    from deeplearning4j_tpu.datasets.normalizers import (
        CompositeDataSetPreProcessor, NormalizerStandardize,
    )

    rng = np.random.default_rng(2)
    ds = DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                 np.ones((8, 2), np.float32))
    inner = CompositeDataSetPreProcessor(NormalizerStandardize())
    nested = CompositeDataSetPreProcessor(inner)
    nested.fit(ds)
    with pytest.raises(ValueError, match="nested composites"):
        ModelSerializer.writeModel(_tiny_model(),
                                   str(tmp_path / "m.zip"),
                                   normalizer=nested)


def test_composite_normalizer_rejects_unknown_child(tmp_path):
    from deeplearning4j_tpu.datasets.normalizers import (
        CompositeDataSetPreProcessor, DataNormalization,
        NormalizerStandardize,
    )

    class Custom(DataNormalization):
        def fit(self, data):
            pass

        def transform(self, ds):
            return ds

        def state_dict(self):
            return {}

        def load_state_dict(self, d):
            pass

    comp = CompositeDataSetPreProcessor(NormalizerStandardize(), Custom())
    rng = np.random.default_rng(3)
    comp.fit(DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                     np.ones((8, 2), np.float32)))
    with pytest.raises(ValueError, match="not a restorable"):
        ModelSerializer.writeModel(_tiny_model(),
                                   str(tmp_path / "m.zip"),
                                   normalizer=comp)


def test_kmeans_survives_transient_distortion_increase(monkeypatch):
    """ADVICE r5: a transient distortion INCREASE (post empty-cluster
    reseed) used to satisfy `prev - distortion <= eps` and end Lloyd
    iterations early; convergence now requires a small NON-NEGATIVE
    improvement."""
    import deeplearning4j_tpu.clustering as cl

    distortions = iter([10.0, 9.0, 9.5, 5.0, 5.0 - 1e-9])
    centers = jnp.asarray(np.array([[0.0, 0.0], [4.0, 4.0]], np.float32))

    def scripted_step(x, c, distance):
        return jnp.zeros((x.shape[0],), jnp.int32), centers, \
            jnp.asarray(next(distortions))

    monkeypatch.setattr(cl, "_kmeans_step", scripted_step)
    km = cl.KMeansClustering(2, max_iterations=10,
                             min_distribution_variation_rate=1e-4)
    pts = np.array([[0, 0], [0.1, 0], [4, 4], [4, 4.1]], np.float32)
    km.applyTo(pts)
    # iterations 1..2 improve, 3 bumps UP (reseed) and must NOT
    # terminate, 4 improves, 5 converges on a tiny non-negative delta
    assert km.iterations_done == 5


def test_kmeans_still_converges_real_run():
    from deeplearning4j_tpu.clustering import KMeansClustering

    rng = np.random.default_rng(4)
    pts = np.concatenate([rng.normal(0, 0.2, (40, 2)),
                          rng.normal(5, 0.2, (40, 2))]).astype(np.float32)
    km = KMeansClustering(2, max_iterations=50)
    cs = km.applyTo(pts)
    assert km.iterations_done < 50  # converged, didn't run out
    got = sorted(c.center.mean() for c in cs.getClusters())
    assert abs(got[0] - 0.0) < 0.5 and abs(got[1] - 5.0) < 0.5
