"""ParallelInference queued dynamic batching (reference:
ParallelInference's observables queue + batched dispatch, SURVEY.md
§2.28 — VERDICT r2 weak #5: the old facade had no queue, no batching,
no concurrency test)."""

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (DenseLayer,
                                        NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.parallel.wrapper import ParallelInference


def _model():
    from deeplearning4j_tpu.nn.conf import InputType
    conf = (NeuralNetConfiguration.builder().seed(7)
            .list()
            .layer(DenseLayer(n_in=12, n_out=64, activation="relu"))
            .layer(DenseLayer(n_in=64, n_out=64, activation="relu"))
            .layer(OutputLayer(n_in=64, n_out=5, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(12))
            .build())
    from deeplearning4j_tpu.nn.multilayer.network import (
        MultiLayerNetwork,
    )
    return MultiLayerNetwork(conf).init()


@pytest.fixture(scope="module")
def net():
    return _model()


class TestParallelInference:
    def test_concurrent_clients_get_correct_results(self, net):
        pi = ParallelInference(net, workers=4, batch_limit=16,
                               nanos=20_000_000)
        rng = np.random.default_rng(0)
        reqs = [rng.normal(size=(1, 12)).astype(np.float32)
                for _ in range(48)]
        want = np.asarray(net.output(np.concatenate(reqs, 0)))
        try:
            with ThreadPoolExecutor(max_workers=16) as ex:
                got = list(ex.map(pi.output, reqs))
        finally:
            pi.shutdown()
        got = np.concatenate([np.asarray(g) for g in got], 0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # the point of the queue: 48 requests collapsed into far fewer
        # compiled calls (dynamic batching actually batched)
        assert pi.n_requests == 48
        assert pi.n_dispatches <= 12, pi.n_dispatches

    def test_multi_row_requests_and_oversized_split(self, net):
        pi = ParallelInference(net, workers=2, batch_limit=8)
        rng = np.random.default_rng(1)
        x3 = rng.normal(size=(3, 12)).astype(np.float32)
        x20 = rng.normal(size=(20, 12)).astype(np.float32)  # > limit
        try:
            out3 = np.asarray(pi.output(x3))
            out20 = np.asarray(pi.output(x20))
        finally:
            pi.shutdown()
        np.testing.assert_allclose(out3, np.asarray(net.output(x3)),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(out20, np.asarray(net.output(x20)),
                                   rtol=1e-5, atol=1e-6)

    def test_batching_beats_thread_per_request_serving(self, net):
        """The VERDICT done-criterion, measured apples-to-apples: the
        same 16 concurrent clients served through the batching queue
        vs served thread-per-request (each client calling the model
        directly — what a server without ParallelInference does). The
        queue must collapse dispatches >=8x AND win wall-clock.

        (N serial single-row calls is NOT the right CPU baseline: CPU
        matmuls are compute-bound, so a batch-16 call costs ~16x a
        row-1 call and batching's win there is dispatch overhead only;
        on the TPU the padded batch rides the same latency as one row,
        which the dispatch-count ratio captures deterministically.)"""
        rng = np.random.default_rng(2)
        reqs = [rng.normal(size=(1, 12)).astype(np.float32)
                for _ in range(256)]

        np.asarray(net.output(reqs[0]))   # warm the direct path
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=16) as ex:
            list(ex.map(lambda r: np.asarray(net.output(r)), reqs))
        per_request = time.perf_counter() - t0

        pi = ParallelInference(net, workers=4, batch_limit=16,
                               nanos=2_000_000)
        try:
            pi.output(reqs[0])            # warm the batched path
            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=16) as ex:
                list(ex.map(pi.output, reqs))
            batched = time.perf_counter() - t0
        finally:
            pi.shutdown()

        ratio = pi.n_requests / max(pi.n_dispatches, 1)
        assert ratio >= 8.0, (pi.n_requests, pi.n_dispatches)
        # observed 1.5-2.2x on the CI box; 1.1 leaves noise margin
        assert batched <= per_request / 1.1, (batched, per_request)

    def test_shutdown_rejects_new_requests(self, net):
        pi = ParallelInference(net, workers=2, batch_limit=8)
        pi.output(np.zeros((1, 12), np.float32))
        pi.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            pi.output(np.zeros((1, 12), np.float32))

    def test_batch_limit_rounds_up_to_workers(self, net):
        pi = ParallelInference(net, workers=4, batch_limit=6)
        try:
            assert pi.batch_limit == 8   # next multiple of workers
            out = pi.output(np.zeros((3, 12), np.float32))
            assert np.asarray(out).shape == (3, 5)
        finally:
            pi.shutdown()

    def test_request_latency_histogram_and_gauges_populated(self, net):
        """Serving-telemetry satellite: per-request latency rides a
        bounded histogram (p50/p99) and the dispatcher exports
        queue-depth + batch-occupancy gauges on the MetricsRegistry
        (surfaced by /telemetry)."""
        from deeplearning4j_tpu.profiler import telemetry

        reg = telemetry.MetricsRegistry.get_default()
        lat = reg.histogram(telemetry.INFERENCE_REQUEST_LATENCY)
        n0 = lat.count()
        pi = ParallelInference(net, workers=4, batch_limit=16,
                               nanos=20_000_000)
        rng = np.random.default_rng(5)
        reqs = [rng.normal(size=(1, 12)).astype(np.float32)
                for _ in range(24)]
        try:
            with ThreadPoolExecutor(max_workers=8) as ex:
                list(ex.map(pi.output, reqs))
        finally:
            pi.shutdown()
        assert lat.count() == n0 + 24
        pct = lat.percentiles()
        assert pct["p50"] > 0 and pct["p99"] >= pct["p50"]
        occ = reg.gauge(telemetry.INFERENCE_BATCH_OCCUPANCY).value()
        assert 0 < occ <= 1.0
        # the queue-depth gauge exists and holds a sane value (the
        # dispatcher sets it at every dispatch; likely 0 at idle)
        assert reg.gauge(
            telemetry.INFERENCE_QUEUE_DEPTH).value() >= 0

    def test_enqueued_requests_survive_shutdown_race(self, net):
        """Requests accepted before shutdown must be answered, not
        stranded: fire shutdown from another thread while clients are
        mid-flight and assert every future resolves."""
        pi = ParallelInference(net, workers=2, batch_limit=8,
                               nanos=5_000_000)
        rng = np.random.default_rng(3)
        reqs = [rng.normal(size=(1, 12)).astype(np.float32)
                for _ in range(24)]
        results = []
        errors = []

        def client(r):
            try:
                results.append(np.asarray(pi.output(r)))
            except RuntimeError:
                errors.append("rejected")   # post-shutdown reject is OK

        import threading
        threads = [threading.Thread(target=client, args=(r,))
                   for r in reqs]
        for t in threads[:12]:
            t.start()
        time.sleep(0.02)
        stopper = threading.Thread(target=pi.shutdown)
        stopper.start()
        for t in threads[12:]:
            t.start()
        for t in threads:
            t.join(timeout=30)
        stopper.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "stranded client"
        # every accepted request produced a result
        assert len(results) + len(errors) == 24
