"""Registered-op sweep: every op the coverage gate flags gets executed
with realistic inputs and (where a numpy analog exists) golden-checked.

Reference analog: the OpValidation per-op TestCases in
org/nd4j/autodiff/validation — this sweep is the enforcement arm of
tests/test_op_coverage.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.registry import get_op

R = np.random.default_rng(0)
X = jnp.asarray(R.normal(size=(4, 6)).astype(np.float32))
Y = jnp.asarray(R.normal(size=(4, 6)).astype(np.float32))
P = jnp.asarray(R.uniform(0.1, 0.9, (4, 6)).astype(np.float32))
IMG = jnp.asarray(R.uniform(0, 1, (2, 8, 8, 3)).astype(np.float32))
SEQ = jnp.asarray(R.normal(size=(2, 10, 4)).astype(np.float32))
VOL = jnp.asarray(R.normal(size=(2, 6, 6, 6, 3)).astype(np.float32))
INTS = jnp.asarray(R.integers(0, 255, (4, 6)), jnp.int32)
KEY = jax.random.key(0)
SPD = jnp.asarray(np.eye(4, dtype=np.float32) * 3 +
                  R.normal(size=(4, 4)).astype(np.float32) * 0.1)
SPD = (SPD + SPD.T) / 2 + 4 * jnp.eye(4)


def npx(a):
    return np.asarray(a)


# op -> (args, kwargs, golden_fn_or_None, result_checker_or_None)
CASES = {
    # unary math
    "sin": ((X,), {}, np.sin, None),
    "cosh": ((X,), {}, np.cosh, None),
    "sinh": ((X,), {}, np.sinh, None),
    "tan": ((X,), {}, np.tan, None),
    "asin": ((P,), {}, np.arcsin, None),
    "acos": ((P,), {}, np.arccos, None),
    "atan": ((X,), {}, np.arctan, None),
    "atan2": ((X, Y), {}, np.arctan2, None),
    "ceil": ((X,), {}, np.ceil, None),
    "floor": ((X,), {}, np.floor, None),
    "neg": ((X,), {}, np.negative, None),
    "log1p": ((P,), {}, np.log1p, None),
    "reciprocal": ((P,), {}, lambda a: 1.0 / a, None),
    "cube": ((X,), {}, lambda a: a ** 3, None),
    "erf": ((X,), {}, None,
            lambda o: np.all(np.abs(npx(o)) <= 1.0)),
    "pow": ((P, 2.0), {}, lambda a, p: a ** p, None),
    "pow_pairwise": ((P, P), {}, lambda a, b: a ** b, None),
    "isinf": ((jnp.array([1.0, jnp.inf, -jnp.inf]),), {},
              None, lambda o: npx(o).tolist() == [False, True, True]),
    "isnan": ((jnp.array([1.0, jnp.nan]),), {},
              None, lambda o: npx(o).tolist() == [False, True]),
    # activations
    "elu": ((X,), {}, None, lambda o: np.all(npx(o) >= -1.0)),
    "leakyrelu": ((X, 0.1), {},
                  lambda a, s: np.where(a > 0, a, s * a), None),
    "relu6": ((X * 10,), {},
              lambda a: np.clip(a, 0, 6), None),
    "hardsigmoid": ((X,), {}, None,
                    lambda o: np.all((npx(o) >= 0) & (npx(o) <= 1))),
    "hardtanh": ((X * 3,), {}, None,
                 lambda o: np.all(np.abs(npx(o)) <= 1.0)),
    "softplus": ((X,), {}, None, lambda o: np.all(npx(o) > 0)),
    "softsign": ((X,), {}, lambda a: a / (1 + np.abs(a)), None),
    "swish": ((X,), {}, lambda a: a / (1 + np.exp(-a)), None),
    "mish": ((X,), {}, None, lambda o: np.isfinite(npx(o)).all()),
    "rationaltanh": ((X,), {}, None,
                     lambda o: np.all(np.abs(npx(o)) <= 1.8)),
    "recttanh": ((X,), {}, None, lambda o: np.all(npx(o) >= 0)),
    "thresholdedrelu": ((X, 0.5), {},
                        lambda a, t: np.where(a > t, a, 0.0), None),
    # comparison / logical
    "eq": ((X, X), {}, None, lambda o: npx(o).all()),
    "neq": ((X, X + 1), {}, None, lambda o: npx(o).all()),
    "not_equals": ((X, X), {}, None, lambda o: not npx(o).any()),
    "lt": ((X, X + 1), {}, None, lambda o: npx(o).all()),
    "lte": ((X, X), {}, None, lambda o: npx(o).all()),
    "gte": ((X, X), {}, None, lambda o: npx(o).all()),
    "less": ((X, X + 1), {}, None, lambda o: npx(o).all()),
    "less_equal": ((X, X), {}, None, lambda o: npx(o).all()),
    "greater_equal": ((X, X), {}, None, lambda o: npx(o).all()),
    "is_close": ((X, X + 1e-9), {}, None, lambda o: npx(o).all()),
    "logical_and": ((X > 0, X > -1), {},
                    lambda a, b: a & b, None),
    "logical_or": ((X > 0, X > -1), {}, lambda a, b: a | b, None),
    "logical_not": ((X > 0,), {}, lambda a: ~a, None),
    "logical_xor": ((X > 0, X > -1), {}, lambda a, b: a ^ b, None),
    "select": ((X > 0, X, Y), {}, np.where, None),
    "max_pairwise": ((X, Y), {}, np.maximum, None),
    "min_pairwise": ((X, Y), {}, np.minimum, None),
    "minimum": ((X, Y), {}, np.minimum, None),
    "mod": ((INTS, jnp.asarray(7)), {}, None,
            lambda o: np.all(npx(o) < 7)),
    "floordiv": ((X, P), {}, lambda a, b: np.floor_divide(a, b), None),
    "floormod": ((X, P), {}, None, lambda o: np.isfinite(npx(o)).all()),
    # reductions
    "reduce_std": ((X,), {"dimensions": 1}, None,
                   lambda o: np.allclose(npx(o), npx(X).std(1, ddof=1),
                                         atol=1e-5)),
    "reduce_var": ((X,), {"dimensions": 1}, None,
                   lambda o: np.allclose(npx(o), npx(X).var(1, ddof=1),
                                         atol=1e-5)),
    "reduce_norm1": ((X,), {"dimensions": 1}, None,
                     lambda o: np.allclose(npx(o),
                                           np.abs(npx(X)).sum(1),
                                           atol=1e-5)),
    "reduce_norm2": ((X,), {"dimensions": 1}, None,
                     lambda o: np.allclose(
                         npx(o), np.linalg.norm(npx(X), axis=1),
                         atol=1e-5)),
    "reduce_norm_max": ((X,), {"dimensions": 1}, None,
                        lambda o: np.allclose(
                            npx(o), np.abs(npx(X)).max(1), atol=1e-6)),
    "reduce_logsumexp": ((X,), {"dimensions": 1}, None,
                         lambda o: np.allclose(
                             npx(o),
                             np.log(np.exp(npx(X)).sum(1)), atol=1e-5)),
    "reduce_any": ((X > 2,), {"dimensions": 1}, None,
                   lambda o: npx(o).dtype == bool),
    "reduce_all": ((X > -10,), {"dimensions": 1}, None,
                   lambda o: npx(o).all()),
    "variance": ((X,), {"axis": 1}, None,
                 lambda o: np.allclose(npx(o), npx(X).var(1), atol=1e-5)),
    "count_zero": ((jnp.asarray([[0.0, 1.0], [0.0, 0.0]]),), {}, None,
                   lambda o: int(npx(o)) == 3),
    "zero_fraction": ((jnp.asarray([[0.0, 1.0], [0.0, 0.0]]),), {}, None,
                      lambda o: abs(float(npx(o)) - 0.75) < 1e-6),
    "shannon_entropy": ((P,), {}, None,
                        lambda o: np.isfinite(npx(o)).all()),
    "log_entropy": ((P,), {}, None,
                    lambda o: np.isfinite(npx(o)).all()),
    "squared_norm": ((X,), {}, None,
                     lambda o: abs(float(npx(o)) -
                                   (npx(X) ** 2).sum()) < 1e-3),
    "norm_fro": ((X,), {}, None,
                 lambda o: abs(float(npx(o)) -
                               np.linalg.norm(npx(X))) < 1e-4),
    # distance
    "cosine_distance": ((X, X), {}, None,
                        lambda o: np.allclose(npx(o), 0.0, atol=1e-5)),
    "jaccard_distance": ((P, P), {}, None,
                         lambda o: np.allclose(npx(o), 0.0, atol=1e-5)),
    "dot": ((X, Y), {"axis": 1}, None,
            lambda o: np.allclose(npx(o), (npx(X) * npx(Y)).sum(1),
                                  atol=1e-5)),
    # linalg
    "batch_mmul": ((SEQ, SEQ.transpose(0, 2, 1)), {}, None,
                   lambda o: npx(o).shape == (2, 10, 10)),
    "batched_gemm": ((SEQ, SEQ.transpose(0, 2, 1)), {}, None,
                     lambda o: npx(o).shape == (2, 10, 10)),
    "kron": ((jnp.eye(2), jnp.ones((2, 2))), {},
             lambda a, b: np.kron(a, b), None),
    "eigh": ((SPD,), {}, None,
             lambda o: np.allclose(
                 npx(o[1]) @ np.diag(npx(o[0])) @ npx(o[1]).T, npx(SPD),
                 atol=1e-3)),
    "lu": ((SPD,), {}, None,
           lambda o: np.isfinite(npx(o[0])).all()),
    "lstsq": ((SPD, jnp.ones((4, 1))), {}, None,
              lambda o: np.allclose(npx(SPD @ o)[:, 0], 1.0,
                                    atol=1e-3)),
    "pinv": ((SPD,), {}, None,
             lambda o: np.allclose(npx(SPD @ o @ SPD), npx(SPD),
                                   atol=1e-3)),
    "triangular_solve": ((jnp.tril(SPD), jnp.ones((4, 1))), {}, None,
                         lambda o: np.allclose(
                             npx(jnp.tril(SPD) @ o), 1.0, atol=1e-3)),
    "log_matrix_determinant": ((SPD,), {}, None,
                               lambda o: np.allclose(
                                   float(npx(o[1])),
                                   np.linalg.slogdet(npx(SPD))[1],
                                   atol=1e-4)),
    "trace": ((SPD,), {}, None,
              lambda o: abs(float(npx(o)) - np.trace(npx(SPD))) < 1e-4),
    "matrix_trace": ((SPD,), {}, None,
                     lambda o: abs(float(npx(o)) -
                                   np.trace(npx(SPD))) < 1e-4),
    "tri": ((4,), {}, None,
            lambda o: np.allclose(npx(o), np.tri(4))),
    "triu": ((SPD,), {}, None,
             lambda o: np.allclose(npx(o), np.triu(npx(SPD)))),
    "xw_plus_b": ((X, jnp.ones((6, 3)), jnp.zeros(3)), {}, None,
                  lambda o: np.allclose(npx(o), npx(X).sum(1,
                                        keepdims=True).repeat(3, 1),
                                        atol=1e-5)),
    # shape / misc
    "fill": (((2, 3), 7.0), {}, None,
             lambda o: np.allclose(npx(o), 7.0) and npx(o).shape == (2, 3)),
    "fill_like": ((X, 3.0), {}, None,
                  lambda o: np.allclose(npx(o), 3.0)),
    "ones_like": ((X,), {}, np.ones_like, None),
    "masked_fill": ((X, X > 0, 0.0), {}, None,
                    lambda o: np.all(npx(o) <= 0)),
    "flatten_2d": ((VOL,), {}, None,
                   lambda o: npx(o).shape == (2, 6 * 6 * 6 * 3)),
    "rank_of": ((VOL,), {}, None, lambda o: int(npx(o)) == 5),
    "size_of": ((X,), {}, None, lambda o: int(npx(o)) == 24),
    "meshgrid": ((jnp.arange(3.0), jnp.arange(4.0)), {}, None,
                 lambda o: npx(o[0]).shape == (4, 3)),
    "split_v": ((X, (2, 4)), {"axis": 1}, None,
                lambda o: npx(o[0]).shape == (4, 2) and
                npx(o[1]).shape == (4, 4)),
    "unstack": ((X,), {"axis": 0}, None,
                lambda o: len(o) == 4 and npx(o[0]).shape == (6,)),
    "dynamic_update_slice": ((X, jnp.zeros((2, 2)), (1, 1)), {}, None,
                             lambda o: np.all(npx(o)[1:3, 1:3] == 0)),
    "clip_by_value": ((X, -0.5, 0.5), {}, None,
                      lambda o: np.all(np.abs(npx(o)) <= 0.5)),
    "clip_by_norm": ((X, 1.0), {}, None,
                     lambda o: np.linalg.norm(npx(o)) <= 1.0 + 1e-4),
    "standardize": ((X,), {"axis": 1}, None,
                    lambda o: np.allclose(npx(o).mean(1), 0, atol=1e-5)),
    # scatter / segment
    "scatter_update": ((X, jnp.asarray([0, 2]),
                        jnp.zeros((2, 6))), {}, None,
                       lambda o: np.all(npx(o)[[0, 2]] == 0)),
    "scatter_sub": ((X, jnp.asarray([1]), X[1:2]), {}, None,
                    lambda o: np.allclose(npx(o)[1], 0, atol=1e-6)),
    "scatter_mul": ((X, jnp.asarray([1]), jnp.zeros((1, 6))), {}, None,
                    lambda o: np.all(npx(o)[1] == 0)),
    "scatter_div": ((X, jnp.asarray([1]), jnp.full((1, 6), 2.0)), {},
                    None,
                    lambda o: np.allclose(npx(o)[1], npx(X)[1] / 2,
                                          atol=1e-6)),
    "scatter_max": ((X, jnp.asarray([1]), jnp.full((1, 6), 99.0)), {},
                    None, lambda o: np.all(npx(o)[1] == 99.0)),
    "scatter_min": ((X, jnp.asarray([1]), jnp.full((1, 6), -99.0)), {},
                    None, lambda o: np.all(npx(o)[1] == -99.0)),
    "segment_min": ((jnp.asarray([3.0, 1.0, 2.0, 5.0]),
                     jnp.asarray([0, 0, 1, 1]), 2), {}, None,
                    lambda o: npx(o).tolist() == [1.0, 2.0]),
    "unsorted_segment_sum": ((jnp.asarray([1.0, 2.0, 3.0]),
                              jnp.asarray([1, 0, 1]), 2), {}, None,
                             lambda o: npx(o).tolist() == [2.0, 4.0]),
    "unsorted_segment_mean": ((jnp.asarray([1.0, 3.0, 3.0]),
                               jnp.asarray([1, 1, 0]), 2), {}, None,
                              lambda o: npx(o).tolist() == [3.0, 2.0]),
    # bitwise
    "bitwise_not": ((INTS,), {}, None,
                    lambda o: np.array_equal(npx(o), ~npx(INTS))),
    "toggle_bits": ((INTS,), {}, None,
                    lambda o: np.array_equal(npx(o), ~npx(INTS))),
    "shift_right": ((INTS, 2), {}, None,
                    lambda o: np.array_equal(npx(o), npx(INTS) >> 2)),
    "bits_hamming_distance": ((jnp.asarray([0b1010], jnp.int32),
                               jnp.asarray([0b0110], jnp.int32)), {},
                              None, lambda o: int(npx(o).sum()) == 2),
    "bitcast": ((jnp.asarray([1.0], jnp.float32), jnp.int32), {}, None,
                lambda o: npx(o).dtype == np.int32),
    # image
    "adjust_brightness": ((IMG, 0.1), {}, None,
                          lambda o: np.allclose(npx(o), npx(IMG) + 0.1,
                                                atol=1e-5)),
    "adjust_hue": ((IMG, 0.1), {}, None,
                   lambda o: npx(o).shape == npx(IMG).shape),
    "adjust_saturation": ((IMG, 1.5), {}, None,
                          lambda o: npx(o).shape == npx(IMG).shape),
    "rgb_to_grayscale": ((IMG,), {}, None,
                         lambda o: npx(o).shape == (2, 8, 8, 1)),
    "rgb_to_yuv": ((IMG,), {}, None,
                   lambda o: npx(o).shape == npx(IMG).shape),
    "yuv_to_rgb": ((IMG,), {}, None,
                   lambda o: npx(o).shape == npx(IMG).shape),
    "image_flip_left_right": ((IMG,), {}, None,
                              lambda o: np.allclose(
                                  npx(o), npx(IMG)[:, :, ::-1])),
    "image_flip_up_down": ((IMG,), {}, None,
                           lambda o: np.allclose(
                               npx(o), npx(IMG)[:, ::-1])),
    "resize_area": ((IMG, (4, 4)), {}, None,
                    lambda o: npx(o).shape == (2, 4, 4, 3)),
    "resize_bicubic": ((IMG, (16, 16)), {}, None,
                       lambda o: npx(o).shape == (2, 16, 16, 3)),
    # conv/pool helpers
    "maxpool1d": ((SEQ, 2), {}, None,
                  lambda o: npx(o).shape == (2, 5, 4)),
    "avgpool1d": ((SEQ, 2), {}, None,
                  lambda o: npx(o).shape == (2, 5, 4)),
    "sumpool1d": ((SEQ, 2), {}, None,
                  lambda o: npx(o).shape == (2, 5, 4)),
    "pnormpool1d": ((SEQ, 2), {}, None,
                    lambda o: npx(o).shape == (2, 5, 4)),
    "sumpool2d": ((IMG,), {}, None,
                  lambda o: npx(o).shape == (2, 4, 4, 3)),
    "pnormpool2d": ((IMG,), {}, None,
                    lambda o: npx(o).shape == (2, 4, 4, 3)),
    "maxpool3d": ((VOL,), {}, None,
                  lambda o: npx(o).shape == (2, 3, 3, 3, 3)),
    "avgpool3d": ((VOL,), {}, None,
                  lambda o: npx(o).shape == (2, 3, 3, 3, 3)),
    "global_max_pool": ((IMG,), {}, None,
                        lambda o: npx(o).shape == (2, 3)),
    "upsampling2d": ((IMG, 2), {}, None,
                     lambda o: npx(o).shape == (2, 16, 16, 3)),
    "im2col": ((IMG, (2, 2)), {}, None,
               lambda o: npx(o).shape == (2, 7, 7, 12)),
    "lrn": ((IMG,), {}, None,
            lambda o: npx(o).shape == npx(IMG).shape),
    "separable_conv2d": ((IMG, jnp.ones((3, 3, 3, 1)) / 9,
                          jnp.ones((1, 1, 3, 5)) / 3), {}, None,
                         lambda o: npx(o).shape == (2, 8, 8, 5)),
    "locally_connected1d": ((SEQ, jnp.ones((9, 8, 3))), {}, None,
                            lambda o: npx(o).shape == (2, 9, 3)),
    "locally_connected2d": ((IMG, jnp.ones((49, 12, 5))), {}, None,
                            lambda o: npx(o).shape == (2, 7, 7, 5)),
    "simple_rnn_layer": ((SEQ, jnp.ones((4, 5)) * 0.1,
                          jnp.eye(5) * 0.1, jnp.zeros(5)), {}, None,
                         lambda o: npx(o[0]).shape == (2, 10, 5)),
    # loss
    "softmax_cross_entropy": ((X, jax.nn.one_hot(jnp.asarray([0, 1, 2, 3]),
                                                 6)), {}, None,
                              lambda o: np.isfinite(npx(o)).all()),
    "sigmoid_cross_entropy": ((X, P), {}, None,
                              lambda o: np.isfinite(npx(o)).all()),
    "log_loss": ((P, (P > 0.5).astype(jnp.float32)), {}, None,
                 lambda o: np.isfinite(npx(o)).all()),
    # random
    "random_normal": ((KEY, (1000,)), {}, None,
                      lambda o: abs(float(npx(o).mean())) < 0.2),
    "random_uniform": ((KEY, (1000,)), {}, None,
                       lambda o: 0 <= npx(o).min() and npx(o).max() <= 1),
    "random_bernoulli": ((KEY, (1000,)), {"p": 0.3}, None,
                         lambda o: 0.2 < npx(o).mean() < 0.4),
    "random_exponential": ((KEY, (1000,)), {}, None,
                           lambda o: npx(o).min() >= 0),
    "random_gamma": ((KEY, (100,)), {"alpha": 2.0}, None,
                     lambda o: npx(o).min() >= 0),
    "random_poisson": ((KEY, (100,)), {"lam": 3.0}, None,
                       lambda o: npx(o).min() >= 0),
    "truncated_normal": ((KEY, (1000,)), {}, None,
                         lambda o: np.abs(npx(o)).max() <= 2.0 + 1e-5),
    "dropout_mask": ((KEY, (1000,), 0.7), {}, None,
                     lambda o: 0.5 < (npx(o) > 0).mean() < 0.9),
    "adaptive_threshold": ((X,), {}, None,
                           lambda o: np.isfinite(npx(np.asarray(o,
                                                  dtype=object)
                                                  [0] if isinstance(o,
                                                  tuple) else o)).all()),
    "argamin": ((X,), {}, None,
                lambda o: npx(o).shape == () or npx(o).size >= 1),
}


def test_control_flow_ops_via_samediff():
    """if_cond / while_loop are sub-graph ops — exercised through the
    SameDiff surface that builds their serialized branch graphs."""
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    sd = SameDiff()
    x = sd.placeholder("x", shape=(2, 3))
    pred = sd.placeholder("p", shape=())
    out = sd.ifCond(pred, [x],
                    lambda sub, a: a + 1.0,
                    lambda sub, a: a - 1.0)
    got = sd.output({"x": X[:2, :3], "p": jnp.asarray(True)},
                    [out.name])[out.name]
    np.testing.assert_allclose(npx(got), npx(X)[:2, :3] + 1.0)

    sd2 = SameDiff()
    i0 = sd2.placeholder("i", shape=())
    outs = sd2.whileLoop([i0],
                         lambda sub, i: i < 5.0,
                         lambda sub, i: i + 1.0)
    final = outs[0] if isinstance(outs, (list, tuple)) else outs
    r = sd2.output({"i": jnp.asarray(0.0)}, [final.name])[final.name]
    assert float(npx(r)) == 5.0


@pytest.mark.parametrize("op_name", sorted(CASES))
def test_op(op_name):
    args, kwargs, golden, check = CASES[op_name]
    fn = get_op(op_name)
    out = fn(*args, **kwargs)
    if golden is not None:
        want = golden(*[npx(a) if hasattr(a, "shape") else a
                        for a in args])
        np.testing.assert_allclose(npx(out), want, rtol=1e-4, atol=1e-5)
    if check is not None:
        assert check(out), f"{op_name}: check failed"
    if golden is None and check is None:
        raise AssertionError(f"{op_name}: no golden and no check")


def test_importer_internal_ops():
    """Ops registered by the TF/ONNX importers + autodiff modules (their
    registration happens on importer module import; exercised directly
    here so the coverage gate stays deterministic): tf_fill,
    tf_strided_slice, onnx_reshape, onnx_flatten, onnx_slice, erfc,
    flash_attention (the Pallas/blockwise dispatcher has its own suite,
    tests/test_flash_attention.py)."""
    import deeplearning4j_tpu.modelimport.onnx.onnx_import  # noqa: F401
    import deeplearning4j_tpu.modelimport.tensorflow.tf_import  # noqa
    import deeplearning4j_tpu.autodiff.ops_math  # noqa: F401

    fill = get_op("tf_fill")
    out = fill(shape=(2, 3), value=7.0)
    assert npx(out).shape == (2, 3) and np.all(npx(out) == 7.0)

    ss = get_op("tf_strided_slice")
    out = ss(X, begin=[1, 0], end=[3, 4], strides=[1, 2])
    np.testing.assert_allclose(npx(out), npx(X)[1:3, 0:4:2])

    r = get_op("onnx_reshape")(X, jnp.asarray([6, 4]))
    assert npx(r).shape == (6, 4)
    f = get_op("onnx_flatten")(jnp.ones((2, 3, 4)), axis=1)
    assert npx(f).shape == (2, 12)
    s = get_op("onnx_slice")(X, starts=[0], ends=[2], axes=[0], steps=[1])
    assert npx(s).shape == (2, 6)

    import scipy.special as sp
    e = get_op("erfc")(X)
    np.testing.assert_allclose(npx(e), sp.erfc(npx(X)), atol=1e-5)


# ---------------------------------------------------------------------
# round-2 breadth sweep (VERDICT r1 #5): segment/scatter/linalg/image/
# random/nn-loss long tail, golden-checked against numpy/scipy where an
# analog exists
# ---------------------------------------------------------------------
SEG_IDS = jnp.asarray([0, 0, 1, 2], jnp.int32)
NDIDX = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
SQ = jnp.asarray(R.normal(size=(4, 4)).astype(np.float32))

CASES2 = {
    # unary/binary math
    "asinh": ((X,), {}, np.arcsinh, None),
    "acosh": ((1.0 + P,), {}, np.arccosh, None),
    "atanh": ((P * 0.9,), {}, np.arctanh, None),
    "expm1": ((X,), {}, np.expm1, None),
    "rint": ((X * 3,), {}, np.rint, None),
    "trunc": ((X * 3,), {}, np.trunc, None),
    "cbrt": ((P,), {}, np.cbrt, None),
    "erfinv": ((P * 0.8,), {}, None,
               lambda o: np.isfinite(npx(o)).all()),
    "lgamma": ((P + 1,), {}, None,
               lambda o: np.isfinite(npx(o)).all()),
    "digamma": ((P + 1,), {}, None,
                lambda o: np.isfinite(npx(o)).all()),
    "polygamma": ((1, P + 1), {}, None,
                  lambda o: np.isfinite(npx(o)).all()),
    "igamma": ((P + 0.5, P + 0.5), {}, None,
               lambda o: np.all((npx(o) >= 0) & (npx(o) <= 1))),
    "igammac": ((P + 0.5, P + 0.5), {}, None,
                lambda o: np.all((npx(o) >= 0) & (npx(o) <= 1))),
    "betainc": ((P + 0.5, P + 0.5, P * 0.9), {}, None,
                lambda o: np.all((npx(o) >= 0) & (npx(o) <= 1))),
    "sinc": ((X,), {}, np.sinc, None),
    "deg2rad": ((X,), {}, np.deg2rad, None),
    "rad2deg": ((X,), {}, np.rad2deg, None),
    "nan_to_num": ((jnp.array([1.0, jnp.nan, jnp.inf]),), {}, None,
                   lambda o: np.isfinite(npx(o)).all()),
    "log_cosh": ((X,), {}, lambda a: np.log(np.cosh(a)), None),
    "softmin": ((X,), {}, None,
                lambda o: np.allclose(npx(o).sum(-1), 1.0, atol=1e-5)),
    "logaddexp": ((X, Y), {}, np.logaddexp, None),
    "logaddexp2": ((X, Y), {}, np.logaddexp2, None),
    "hypot": ((X, Y), {}, np.hypot, None),
    "heaviside": ((X, jnp.float32(0.5)), {}, np.heaviside, None),
    "copysign": ((X, Y), {}, np.copysign, None),
    "fmod": ((X * 5, 2.0 + P), {}, np.fmod, None),
    "xdivy": ((jnp.array([0.0, 2.0]), jnp.array([0.0, 4.0])), {}, None,
              lambda o: npx(o).tolist() == [0.0, 0.5]),
    "xlogy": ((P, P), {}, None, lambda o: np.isfinite(npx(o)).all()),
    "xlog1py": ((P, P), {}, None, lambda o: np.isfinite(npx(o)).all()),
    "lerp": ((X, Y, 0.25), {},
             lambda a, b, w: a + w * (b - a), None),
    "addcmul": ((X, Y, P), {}, lambda x, a, b: x + a * b, None),
    "addcdiv": ((X, Y, 1.0 + P), {}, lambda x, a, b: x + a / b, None),
    "polyval": (([2.0, -1.0, 3.0], P), {},
                lambda c, x: 2 * x ** 2 - x + 3, None),
    "absolute_difference": ((X, Y), {}, lambda a, b: np.abs(a - b), None),
    "nanmean": ((jnp.array([1.0, jnp.nan, 3.0]),), {}, None,
                lambda o: abs(float(o) - 2.0) < 1e-6),
    "nansum": ((jnp.array([1.0, jnp.nan, 3.0]),), {}, None,
               lambda o: abs(float(o) - 4.0) < 1e-6),
    "nanmax": ((jnp.array([1.0, jnp.nan, 3.0]),), {}, None,
               lambda o: float(o) == 3.0),
    "nanmin": ((jnp.array([1.0, jnp.nan, 3.0]),), {}, None,
               lambda o: float(o) == 1.0),
    "percentile": ((X, 50.0), {},
                   lambda a, q: np.percentile(a, q), None),
    "median": ((X,), {}, np.median, None),
    "quantile": ((X, 0.25), {}, lambda a, q: np.quantile(a, q), None),
    "cummax": ((X,), {"axis": 1}, lambda a: np.maximum.accumulate(a, 1),
               None),
    "cummin": ((X,), {"axis": 1}, lambda a: np.minimum.accumulate(a, 1),
               None),
    "diff": ((X,), {}, lambda a: np.diff(a), None),
    "trapz": ((X,), {"dx": 0.5}, None,
              lambda o: np.isfinite(npx(o)).all()),
    # segment / scatter / indexing
    "unsorted_segment_max": ((X, SEG_IDS, 3), {}, None,
                             lambda o: npx(o).shape == (3, 6)),
    "unsorted_segment_min": ((X, SEG_IDS, 3), {}, None,
                             lambda o: npx(o).shape == (3, 6)),
    "unsorted_segment_prod": ((X, SEG_IDS, 3), {}, None,
                              lambda o: npx(o).shape == (3, 6)),
    "unsorted_segment_sqrt_n": ((X, SEG_IDS, 3), {}, None,
                                lambda o: npx(o).shape == (3, 6)),
    "scatter_nd_add": ((jnp.zeros((4, 6)), NDIDX,
                        jnp.ones((2,))), {}, None,
                       lambda o: float(npx(o).sum()) == 2.0),
    "scatter_nd_sub": ((jnp.zeros((4, 6)), NDIDX,
                        jnp.ones((2,))), {}, None,
                       lambda o: float(npx(o).sum()) == -2.0),
    "scatter_nd_update": ((jnp.zeros((4, 6)), NDIDX,
                           jnp.full((2,), 7.0)), {}, None,
                          lambda o: float(npx(o)[0, 1]) == 7.0),
    "roll": ((X, 2), {"axis": 1}, lambda a, s: np.roll(a, s, 1), None),
    "flip": ((X,), {"axis": 1}, lambda a: np.flip(a, 1), None),
    "rot90": ((X,), {}, lambda a: np.rot90(a), None),
    "bincount": ((jnp.asarray([0, 1, 1, 3], jnp.int32),),
                 {"minlength": 5}, None,
                 lambda o: npx(o).tolist() == [1, 2, 0, 1, 0]),
    "bincount_capped": ("bincount",
                        (jnp.asarray([0, 1, 1, 3], jnp.int32),),
                        {"minlength": 10, "maxlength": 3}, None,
                        lambda o: npx(o).tolist() == [1, 2, 0]),
    # 0 < minlength < maxlength: output sized to maxlength; counts in
    # [minlength, maxlength) must NOT be dropped
    "bincount_min_max": ("bincount",
                         (jnp.asarray([0, 1, 1, 3, 4], jnp.int32),),
                         {"minlength": 2, "maxlength": 5}, None,
                         lambda o: npx(o).tolist() == [1, 2, 0, 1, 1]),
    "searchsorted": ((jnp.asarray([1.0, 2.0, 4.0]),
                      jnp.asarray([0.5, 3.0])), {}, None,
                     lambda o: npx(o).tolist() == [0, 2]),
    "nth_element": ((X, 2), {}, lambda a, n: np.sort(a, -1)[..., n],
                    None),
    "histogram_fixed_width": ((P, 0.0, 1.0), {"nbins": 4}, None,
                              lambda o: int(npx(o).sum()) == P.size),
    "sequence_mask": ((jnp.asarray([1, 3], jnp.int32), 4), {}, None,
                      lambda o: npx(o).tolist() == [
                          [True, False, False, False],
                          [True, True, True, False]]),
    "batch_gather": ((SEQ, jnp.asarray([[0, 1], [2, 3]], jnp.int32)),
                     {}, None, lambda o: npx(o).shape == (2, 2, 4)),
    "dynamic_partition_masks": ((X, SEG_IDS, 3), {}, None,
                                lambda o: npx(o[0]).shape == (3, 4, 6)),
    "dynamic_stitch": (([jnp.asarray([0, 2], jnp.int32),
                         jnp.asarray([1, 3], jnp.int32)],
                        [jnp.ones((2, 6)), 2 * jnp.ones((2, 6))], 4),
                       {}, None,
                       lambda o: npx(o)[:, 0].tolist() == [1, 2, 1, 2]),
    # linalg
    "slogdet": ((SPD,), {}, None,
                lambda o: np.isfinite(float(o[1]))),
    "matrix_power": ((SQ, 3), {},
                     lambda a, n: np.linalg.matrix_power(a, n), None),
    "matrix_rank": ((SPD,), {}, None, lambda o: int(o) == 4),
    "matrix_rank_tol": ("matrix_rank",
                        (jnp.diag(jnp.asarray([100.0, 0.5])),),
                        {"tol": 1.0}, None, lambda o: int(o) == 1),
    "eigvalsh": ((SPD,), {},
                 lambda a: np.linalg.eigvalsh(a), None),
    "expm": ((SQ * 0.1,), {}, None,
             lambda o: np.isfinite(npx(o)).all()),
    "cond_number": ((SPD,), {}, None, lambda o: float(o) > 0),
    "multi_dot": (([SQ, SQ, SQ],), {},
                  lambda ms: np.linalg.multi_dot(ms), None),
    "adjoint": ((SQ,), {}, lambda a: a.T, None),
    # image
    "central_crop": ((IMG, 0.5), {}, None,
                     lambda o: npx(o).shape == (2, 4, 4, 3)),
    "central_crop_odd": ("central_crop", (IMG[:, :5, :5], 0.5), {}, None,
                         lambda o: npx(o).shape == (2, 3, 3, 3)),
    "per_image_standardization": ((IMG,), {}, None,
                                  lambda o: abs(float(npx(o).mean()))
                                  < 1e-4),
    "image_gradients": ((IMG,), {}, None,
                        lambda o: npx(o[0]).shape == IMG.shape),
    "sobel_edges": ((IMG,), {}, None,
                    lambda o: npx(o).shape == (2, 8, 8, 3, 2)),
    "pad_to_bounding_box": ((IMG, 1, 2, 12, 12), {}, None,
                            lambda o: npx(o).shape == (2, 12, 12, 3)),
    "crop_to_bounding_box": ((IMG, 1, 2, 4, 4), {}, None,
                             lambda o: npx(o).shape == (2, 4, 4, 3)),
    "adjust_gamma": ((IMG, 2.0), {}, lambda a, g: a ** 2.0, None),
    "image_translate": ((IMG, 1, -2), {}, None,
                        lambda o: npx(o).shape == IMG.shape),
    # random
    "random_laplace": ((KEY, (100,)), {}, None,
                       lambda o: np.isfinite(npx(o)).all()),
    "random_cauchy": ((KEY, (100,)), {}, None,
                      lambda o: np.isfinite(npx(o)).all()),
    "random_gumbel": ((KEY, (100,)), {}, None,
                      lambda o: np.isfinite(npx(o)).all()),
    "random_beta": ((KEY, (100,)), {"a": 2.0, "b": 3.0}, None,
                    lambda o: np.all((npx(o) >= 0) & (npx(o) <= 1))),
    "random_categorical": ((KEY, jnp.zeros((3, 5)), 7), {}, None,
                           lambda o: npx(o).shape == (3, 7)),
    "random_shuffle": ((KEY, X), {}, None,
                       lambda o: np.allclose(np.sort(npx(o), 0),
                                             np.sort(npx(X), 0))),
    "random_rademacher": ((KEY, (50,)), {}, None,
                          lambda o: set(npx(o).tolist()) <= {-1.0, 1.0}),
    # nn / norms / losses
    "celu": ((X,), {}, None, lambda o: np.all(npx(o) > -1.0001)),
    "glu": ((X,), {}, None, lambda o: npx(o).shape == (4, 3)),
    "log_sigmoid": ((X,), {}, None, lambda o: np.all(npx(o) < 0)),
    "hard_swish": ((X,), {}, None, lambda o: np.isfinite(npx(o)).all()),
    "group_norm": ((IMG, jnp.ones(3), jnp.zeros(3), 3), {}, None,
                   lambda o: npx(o).shape == IMG.shape),
    "instance_norm": ((IMG, jnp.ones(3), jnp.zeros(3)), {}, None,
                      lambda o: abs(float(npx(o).mean())) < 1e-4),
    "rms_norm": ((X, jnp.ones(6)), {}, None,
                 lambda o: np.isfinite(npx(o)).all()),
    "huber_loss": ((X, Y), {}, None, lambda o: np.all(npx(o) >= 0)),
    "hinge_loss": ((jnp.asarray([0.0, 1.0]), jnp.asarray([0.3, 2.0])),
                   {}, None,
                   lambda o: np.allclose(npx(o), [1.3, 0.0])),
    "kl_divergence": ((P / npx(P).sum(-1, keepdims=True),
                       P / npx(P).sum(-1, keepdims=True)), {}, None,
                      lambda o: np.allclose(npx(o), 0, atol=1e-5)),
    "poisson_nll_loss": ((P, X), {},
                         lambda t, l: np.exp(l) - t * l, None),
    "mean_pairwise_squared_error": (
        (jnp.zeros_like(X), X), {}, None,
        lambda o: np.allclose(
            npx(o),
            2.0 * (X.shape[1] * (npx(X) ** 2).sum(-1)
                   - npx(X).sum(-1) ** 2)
            / (X.shape[1] * (X.shape[1] - 1)), rtol=1e-5)),
    "ctc_loss": ((jax.nn.log_softmax(
        jnp.asarray(R.normal(size=(2, 12, 5)).astype(np.float32))),
        jnp.asarray([[1, 2, 3], [2, 4, 0]], jnp.int32),
        jnp.asarray([12, 12], jnp.int32),
        jnp.asarray([3, 2], jnp.int32)), {}, None,
        lambda o: np.all(npx(o) > 0)),
}


@pytest.mark.parametrize("opname", sorted(CASES2))
def test_op_case2(opname):
    case = CASES2[opname]
    if len(case) == 5:          # alias entry: (real_op, args, kw, g, c)
        real, args, kwargs, golden, checker = case
    else:
        real, (args, kwargs, golden, checker) = opname, case
    fn = get_op(real)
    out = fn(*args, **kwargs)
    if golden is not None:
        ref = golden(*[npx(a) if hasattr(a, "shape") else a
                       for a in args])
        np.testing.assert_allclose(npx(out), ref, rtol=2e-4, atol=2e-5)
    if checker is not None:
        assert checker(out), f"{opname} checker failed"


# ---------------------------------------------------------------------
# numerical gradient checks for the round-2 differentiable ops
# (reference: OpValidation/GradCheckUtil finite-difference backbone,
# SURVEY.md §4 — "every op grad-checked where differentiable")
# ---------------------------------------------------------------------
GRAD_CASES = {
    # opname -> (args builder producing differentiable first arg, kwargs)
    # --- round-3 declarable tail ---
    "l2_loss": ((X,), {}),
    "mean_squared_error": ((X, Y), {"_swap": True}),
    "smooth_l1_loss": ((X, Y), {}),
    "weighted_cross_entropy_with_logits": (
        (X, (P > 0.5).astype(jnp.float32)),
        {"pos_weight": 2.0, "_swap": True}),
    "log_poisson_loss": ((X, P), {}),
    "precise_gelu": ((X,), {}),
    "axpy": ((X, Y, P), {}),
    "total_variation": ((IMG,), {}),
    "amean": ((X,), {}),
    "asum": ((X,), {}),
    "lbeta": ((P + 0.5,), {}),
    "mergeavg": ((X, Y), {}),
    "relu_layer": ((X, jnp.asarray(R.normal(size=(6, 3))
                                   .astype(np.float32)),
                    jnp.full((3,), 0.3)), {}),
    "lstm_cell": ((X, jnp.asarray(R.normal(size=(4, 5))
                                  .astype(np.float32)),
                   jnp.asarray(R.normal(size=(4, 5)).astype(np.float32)),
                   jnp.asarray(R.normal(size=(11, 20))
                               .astype(np.float32) * 0.3),
                   jnp.zeros(20)), {}),
    "gru_cell": ((X, jnp.asarray(R.normal(size=(4, 5))
                                 .astype(np.float32)),
                  jnp.asarray(R.normal(size=(11, 15))
                              .astype(np.float32) * 0.3),
                  jnp.zeros(15)), {}),
    "sru_cell": ((X, Y,
                  jnp.asarray(R.normal(size=(6, 18))
                              .astype(np.float32) * 0.3),
                  jnp.zeros(12)), {}),
    "asinh": ((X,), {}),
    "atanh": ((P * 0.5,), {}),
    "expm1": ((X,), {}),
    "cbrt": ((P,), {}),
    "lgamma": ((P + 1.5,), {}),
    "digamma": ((P + 1.5,), {}),
    "sinc": ((P,), {}),
    "log_cosh": ((X,), {}),
    "softmin": ((X,), {}),
    "logaddexp": ((X, Y), {}),
    "hypot": ((P, P + 0.5), {}),
    "xlogy": ((P, P), {}),
    "lerp": ((X, Y, 0.3), {}),
    "addcmul": ((X, Y, P), {}),
    "cummax": ((X,), {"axis": 1}),
    "cummin": ((X,), {"axis": 1}),
    "diff": ((X,), {}),
    "huber_loss": ((X, Y), {}),
    "hinge_loss": ((jnp.asarray([0.0, 1.0, 1.0, 0.0]),
                    jnp.asarray([0.3, 2.0, -1.0, -0.4])), {}),
    # grad taken wrt the FIRST arg: put log_input first so the
    # exp(log_input) derivative path is what gets checked
    "poisson_nll_loss": ((X, P), {"_swap": True}),
    "rms_norm": ((X, jnp.ones(6) * 1.1), {}),
    "group_norm": ((IMG, jnp.ones(3), jnp.zeros(3), 3), {}),
    "instance_norm": ((IMG, jnp.ones(3), jnp.zeros(3)), {}),
    "celu": ((X,), {}),
    "log_sigmoid": ((X,), {}),
    "hard_swish": ((X + 0.1,), {}),
    "per_image_standardization": ((IMG,), {}),
    "adjust_gamma": ((IMG + 0.1, 1.7), {}),
}


@pytest.mark.parametrize("opname", sorted(GRAD_CASES))
def test_numeric_gradient(opname):
    args, kwargs = GRAD_CASES[opname]
    fn = get_op(opname)

    # copy BEFORE popping: GRAD_CASES is shared module state and a
    # repeated run of the same param must still see _swap
    kwargs = dict(kwargs)
    swap = kwargs.pop("_swap", False)

    def scalar_loss(x0):
        call = args[1:] + (x0,) if swap else (x0,) + args[1:]
        out = fn(*call, **kwargs)
        if isinstance(out, tuple):
            out = out[0]
        return jnp.sum(jnp.sin(out))   # non-trivial cotangents

    x0 = args[0]
    analytic = np.asarray(jax.grad(scalar_loss)(x0))
    eps = 1e-3
    flat = np.asarray(x0, np.float64).reshape(-1)
    # probe a few random coordinates (full FD over images is slow);
    # crc32, not hash(): hash is salted per process and would make a
    # marginal failure irreproducible
    import zlib
    rng = np.random.default_rng(zlib.crc32(opname.encode()))
    idxs = rng.choice(flat.size, size=min(6, flat.size), replace=False)
    for i in idxs:
        e = np.zeros_like(flat)
        e[i] = eps
        xp = jnp.asarray((flat + e).reshape(x0.shape), x0.dtype)
        xm = jnp.asarray((flat - e).reshape(x0.shape), x0.dtype)
        fd = (float(scalar_loss(xp)) - float(scalar_loss(xm))) / (2 * eps)
        an = analytic.reshape(-1)[i]
        assert abs(fd - an) <= 2e-2 * max(1.0, abs(fd), abs(an)), \
            (opname, i, fd, float(an))
