"""Memory workspaces, device stats, crash reporting, profiler + panics.

Reference: SURVEY.md §2.10/§2.11 (workspaces/allocator), §5 (OpProfiler
panic modes, CrashReportingUtil).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.memory import (
    CrashReportingUtil, DebugMode, MemoryWorkspace, WorkspaceConfiguration,
    assert_no_workspaces_open, device_memory_stats, getWorkspaceManager,
    host_memory_stats,
)
from deeplearning4j_tpu.profiler import (
    NumericsException, OpProfiler, ProfilerConfig, ProfilerMode,
    check_numerics,
)


class TestWorkspaces:
    def test_scoping_and_nesting(self):
        assert_no_workspaces_open()
        with MemoryWorkspace(workspace_id="outer") as outer:
            assert getWorkspaceManager().open_workspaces() == ["outer"]
            with MemoryWorkspace(workspace_id="inner"):
                assert getWorkspaceManager().open_workspaces() == \
                    ["outer", "inner"]
            outer.track(np.zeros(4))
            assert outer.tracked_count() == 1
        assert_no_workspaces_open()

    def test_leak_detection(self):
        ws = MemoryWorkspace(workspace_id="leaky")
        ws.__enter__()
        with pytest.raises(RuntimeError, match="leaky"):
            assert_no_workspaces_open()
        ws.__exit__(None, None, None)

    def test_mismatched_close(self):
        a = MemoryWorkspace(workspace_id="a")
        b = MemoryWorkspace(workspace_id="b")
        a.__enter__()
        b.__enter__()
        with pytest.raises(RuntimeError, match="mismatch"):
            a.__exit__(None, None, None)
        b.__exit__(None, None, None)
        a.__exit__(None, None, None)

    def test_config_fields(self):
        cfg = WorkspaceConfiguration(initial_size=1 << 20,
                                     debug_mode=DebugMode.VALIDATE_SCOPES)
        assert cfg.policy_allocation == "OVERALLOCATE"

    def test_memory_stats(self):
        d = device_memory_stats()
        assert "platform" in d
        h = host_memory_stats()
        assert h.get("max_rss_mb", 1) > 0


class TestCrashReporting:
    def _net(self):
        from deeplearning4j_tpu.learning.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer.network import (
            MultiLayerNetwork,
        )
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=4, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(3)).build())
        return MultiLayerNetwork(conf).init()

    def test_report_contents(self):
        net = self._net()
        report = CrashReportingUtil.generate_report(net, extra={"k": "v"})
        assert "MultiLayerNetwork" in report
        assert "DenseLayer" in report       # config json included
        assert "k: v" in report

    def test_dump_written(self, tmp_path):
        path = CrashReportingUtil.writeMemoryCrashDump(
            None, str(tmp_path / "dump.txt"))
        assert os.path.exists(path)
        assert "crash / memory report" in open(path).read()

    def test_wrap_oom(self, tmp_path):
        def boom():
            raise MemoryError("Out of memory allocating 1TB")

        guarded = CrashReportingUtil.wrap_oom(boom, dump_dir=str(tmp_path))
        with pytest.raises(MemoryError, match="crash dump written"):
            guarded()
        assert os.path.exists(tmp_path / "oom-dump.txt")

    def test_wrap_passthrough(self):
        guarded = CrashReportingUtil.wrap_oom(lambda: 42)
        assert guarded() == 42
        bad = CrashReportingUtil.wrap_oom(
            lambda: (_ for _ in ()).throw(ValueError("not oom")))
        with pytest.raises(ValueError, match="not oom"):
            bad()


class TestProfiler:
    def test_operations_mode_counts(self):
        from deeplearning4j_tpu.ops import registry
        prof = OpProfiler.getInstance()
        prof.reset()
        prof.applyConfig(ProfilerConfig(ProfilerMode.OPERATIONS))
        try:
            fn = registry.get_op("relu")
            fn(np.asarray([-1.0, 2.0], np.float32))
            fn2 = registry.get_op("exp")
            fn2(np.asarray([0.0], np.float32))
            assert prof.invocations["relu"] == 1
            assert prof.invocations["exp"] == 1
            assert "relu" in prof.printOutDashboard()
        finally:
            prof.applyConfig(ProfilerConfig(ProfilerMode.DISABLED))

    def test_check_numerics(self):
        check_numerics([np.ones(3)], ProfilerMode.ANY_PANIC)  # clean: ok
        with pytest.raises(NumericsException, match="NaN"):
            check_numerics(np.asarray([np.nan]), ProfilerMode.NAN_PANIC)
        with pytest.raises(NumericsException, match="Inf"):
            check_numerics(np.asarray([np.inf]), ProfilerMode.INF_PANIC)
        # NAN_PANIC ignores Inf
        check_numerics(np.asarray([np.inf]), ProfilerMode.NAN_PANIC)

    def test_training_panic_hook(self):
        """A diverging net (huge lr on exp-ing loss) must raise under
        NAN_PANIC instead of silently training on NaNs."""
        from deeplearning4j_tpu.learning.updaters import Sgd
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer.network import (
            MultiLayerNetwork,
        )
        conf = (NeuralNetConfiguration.builder().updater(Sgd(1e9)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(4)).build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(0)
        x = rs.randn(8, 4).astype(np.float32) * 100
        y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)]
        prof = OpProfiler.getInstance()
        prof.applyConfig(ProfilerConfig(ProfilerMode.NAN_PANIC))
        try:
            with pytest.raises(NumericsException):
                for _ in range(50):
                    net.fit(x, y)
        finally:
            prof.applyConfig(ProfilerConfig(ProfilerMode.DISABLED))
