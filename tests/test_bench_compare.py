"""bench_compare key classification: the table that decides which
direction gates a regression. Pinned because a misclassified key fails
silently — the gate still runs, it just guards the wrong direction."""

import pytest

import bench_compare


class TestClassification:
    @pytest.mark.parametrize("key,value,expect", [
        # explicitly higher-better families — pinned AHEAD of the
        # latency heuristic, so a ratio named against a latency can
        # never gate backwards
        ("bert_mfu", 0.5, "higher"),
        ("lstm_bf16_mfu", 0.4, "higher"),
        ("mixed_speedup_vs_f32", 1.2, "higher"),
        ("int8_agreement", 0.99, "higher"),
        ("decode_ms_speedup", 1.3, "higher"),   # the regression case
        ("serving_spec_acceptance", 0.7, "higher"),
        ("serving_spec_decode_speedup", 1.4, "higher"),
        ("serving_tokens_per_dispatch", 2.5, "higher"),
        # a per-dispatch ratio named against a latency window must
        # still gate higher-better (the kv "ms"-segment regression
        # case, speculative-decode edition)
        ("verify_ms_tokens_per_dispatch", 2.0, "higher"),
        # latency family: lower-better via the "ms" segment
        ("step_ms", 12.0, "lower"),
        ("gpt_decode_ms_per_step", 3.0, "lower"),
        ("serving_p99_ms", 9.0, "lower"),
        # throughput default
        ("tokens_per_sec", 1000.0, "higher"),
        ("lstm_words_per_sec", 1000.0, "higher"),
        # "ms" must match a segment, not a substring
        ("msa_rows_per_sec", 10.0, "higher"),
        # booleans are correctness gates, not magnitudes
        ("int8_tokens_identical", True, "bool"),
        # round description, never compared
        ("metric", "bench", None),
        ("vs_baseline", 1.0, None),
        ("lstm_frozen_window_ms", 5.0, None),
        ("bert_step_band_lo", 1.0, None),
        ("lstm_src", "live", None),
        ("decode_note", "x", None),
        ("some_error", "trace", None),
        ("free_text", "abc", None),             # non-numeric
    ])
    def test_pinned_table(self, key, value, expect):
        assert bench_compare._classify(key, value) == expect


class TestCompareRounds:
    def test_speedup_drop_regresses_and_ms_rise_regresses(self):
        prior = {"decode_ms_speedup": 2.0, "step_ms": 10.0,
                 "tokens_per_sec": 100.0}
        current = {"decode_ms_speedup": 1.0, "step_ms": 20.0,
                   "tokens_per_sec": 101.0}
        _report, regressions = bench_compare.compare_rounds(
            prior, current, tolerance=0.1)
        assert len(regressions) == 2
        joined = "\n".join(regressions)
        assert "decode_ms_speedup" in joined and "step_ms" in joined

    def test_spec_metrics_drop_regresses(self):
        _r, regressions = bench_compare.compare_rounds(
            {"serving_tokens_per_dispatch": 2.5,
             "serving_spec_acceptance": 0.8},
            {"serving_tokens_per_dispatch": 1.0,
             "serving_spec_acceptance": 0.4}, tolerance=0.1)
        assert len(regressions) == 2

    def test_bool_flip_fails_regardless_of_tolerance(self):
        _r, regressions = bench_compare.compare_rounds(
            {"int8_tokens_identical": True},
            {"int8_tokens_identical": False}, tolerance=10.0)
        assert regressions
