"""Mixed-precision policy engine tests (nn/precision.py): fp32 master
weights + bf16/f16 compute across MultiLayerNetwork / ComputationGraph /
ShardedTrainer, dynamic loss scaling (overflow -> skip-and-halve),
policy serde, checkpoint round-trips, and the loss-scale telemetry."""

import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.ndarray.dtypes import DataType
from deeplearning4j_tpu.nn import precision as P
from deeplearning4j_tpu.nn.conf import (
    BatchNormalization, DenseLayer, InputType, LSTM,
    NeuralNetConfiguration, OutputLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.nn.precision import PrecisionPolicy
from deeplearning4j_tpu.profiler import telemetry


def _float_dtypes(tree):
    return {str(l.dtype) for l in jax.tree_util.tree_leaves(tree)
            if hasattr(l, "dtype")
            and jnp.issubdtype(l.dtype, jnp.floating)}


def _data(n=32, fin=10, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, fin).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, n)]
    return x, y


def _mln(precision, seed=7, updater=None, bn=True):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater or Adam(1e-2)).precision(precision).list()
         .layer(DenseLayer(n_out=16, activation="relu")))
    if bn:
        b = b.layer(BatchNormalization())
    conf = (b.layer(OutputLayer(n_out=3, activation="softmax",
                                loss="mcxent"))
            .setInputType(InputType.feedForward(10)).build())
    return MultiLayerNetwork(conf).init()


OVERFLOW_X = np.full((32, 10), 1e7, np.float32)  # inf once cast to f16


# ----------------------------------------------------------------------
# policy object
# ----------------------------------------------------------------------
class TestPolicy:
    def test_presets(self):
        f32 = PrecisionPolicy.of("float32")
        assert f32.is_identity
        bf = PrecisionPolicy.of("mixed_bfloat16")
        assert (bf.param_dtype, bf.compute_dtype, bf.output_dtype) == \
            ("float32", "bfloat16", "float32")
        assert not bf.loss_scaling and not bf.is_identity
        f16 = PrecisionPolicy.of("mixed_float16")
        assert f16.compute_dtype == "float16" and f16.loss_scaling

    def test_preset_aliases(self):
        assert PrecisionPolicy.of("mixed_bf16").compute_dtype == "bfloat16"
        assert PrecisionPolicy.of("mixed_fp16").loss_scaling
        with pytest.raises(ValueError, match="Unknown precision"):
            PrecisionPolicy.of("mixed_int8")

    def test_resolve(self):
        ident = PrecisionPolicy.resolve(None, "bfloat16")
        assert ident.is_identity and ident.compute_dtype == "bfloat16"
        assert PrecisionPolicy.resolve("mixed_bfloat16", "float32") \
            .compute_dtype == "bfloat16"
        pol = PrecisionPolicy.of("mixed_float16")
        assert PrecisionPolicy.resolve(pol, "float32") is pol

    def test_layer_dtype_islands(self):
        pol = PrecisionPolicy.of("mixed_bfloat16")
        assert pol.layer_compute_dtype(DenseLayer(n_out=4), 0) == \
            jnp.dtype("bfloat16")
        assert pol.layer_compute_dtype(BatchNormalization(), 1) == \
            jnp.dtype("float32")      # normalization island
        assert pol.layer_compute_dtype(OutputLayer(n_out=2), 2) == \
            jnp.dtype("float32")      # loss head island

    def test_layer_overrides(self):
        pol = PrecisionPolicy(name="c", compute_dtype="bfloat16",
                              layer_overrides={0: "float32",
                                               "att": "float16"})
        assert pol.layer_compute_dtype(DenseLayer(n_out=4), 0) == \
            jnp.dtype("float32")
        assert pol.layer_compute_dtype(DenseLayer(n_out=4), "att") == \
            jnp.dtype("float16")
        assert pol.layer_compute_dtype(DenseLayer(n_out=4), 5) == \
            jnp.dtype("bfloat16")

    def test_conf_json_round_trip(self):
        for prec in ("mixed_bfloat16",
                     PrecisionPolicy.of("mixed_float16"),
                     PrecisionPolicy(name="c", compute_dtype="bfloat16",
                                     layer_overrides={1: "float32"})):
            conf = (NeuralNetConfiguration.builder().precision(prec)
                    .list()
                    .layer(DenseLayer(n_out=4, activation="relu"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .setInputType(InputType.feedForward(3)).build())
            c2 = MultiLayerConfiguration.from_json(conf.to_json())
            assert c2.precision == conf.precision

    def test_loss_scale_update_schedule(self):
        pol = PrecisionPolicy.of("mixed_float16")
        pol.growth_interval = 2
        st = P.init_loss_scale(pol)
        s0 = float(st["scale"])
        st = P.update_loss_scale(pol, st, jnp.asarray(False))
        assert float(st["scale"]) == s0 / 2
        assert int(st["overflows"]) == 1
        st = P.update_loss_scale(pol, st, jnp.asarray(True))
        st = P.update_loss_scale(pol, st, jnp.asarray(True))
        assert float(st["scale"]) == s0   # doubled after 2 clean steps
        # floor at min_loss_scale
        st["scale"] = jnp.asarray(1.0, jnp.float32)
        st = P.update_loss_scale(pol, st, jnp.asarray(False))
        assert float(st["scale"]) == pol.min_loss_scale
        # ceiling at max_loss_scale: growth must never reach f32 inf
        # (inf * backoff = inf would skip every step forever)
        st["scale"] = jnp.asarray(pol.max_loss_scale, jnp.float32)
        st["good_steps"] = jnp.asarray(pol.growth_interval - 1,
                                       jnp.int32)
        st = P.update_loss_scale(pol, st, jnp.asarray(True))
        assert float(st["scale"]) == pol.max_loss_scale


# ----------------------------------------------------------------------
# dtype aliases (satellite)
# ----------------------------------------------------------------------
class TestDtypeAliases:
    @pytest.mark.parametrize("alias,expect", [
        ("bf16", DataType.BFLOAT16), ("fp16", DataType.HALF),
        ("half", DataType.HALF), ("f16", DataType.HALF),
        ("f32", DataType.FLOAT), ("fp32", DataType.FLOAT),
        ("f64", DataType.DOUBLE), ("double", DataType.DOUBLE),
        ("BF16", DataType.BFLOAT16),  # case-insensitive
        ("float32", DataType.FLOAT), ("bfloat16", DataType.BFLOAT16),
    ])
    def test_alias(self, alias, expect):
        assert DataType.from_any(alias) is expect

    def test_bad_alias_still_raises(self):
        with pytest.raises((ValueError, TypeError)):
            DataType.from_any("not_a_dtype")


# ----------------------------------------------------------------------
# MultiLayerNetwork
# ----------------------------------------------------------------------
class TestMLNMixed:
    def test_bf16_masters_stay_fp32_and_loss_parity(self):
        x, y = _data()
        nets = {}
        for pol in ("float32", "mixed_bfloat16"):
            net = _mln(pol)
            for _ in range(30):
                net.fit(x, y)
            nets[pol] = net
            assert _float_dtypes(net.params_list) == {"float32"}
            assert _float_dtypes(net.opt_states) == {"float32"}
            assert np.isfinite(net.score())
        rel = abs(nets["mixed_bfloat16"].score()
                  - nets["float32"].score()) / nets["float32"].score()
        assert rel < 0.02   # acceptance: parity within 2%

    def test_identity_policy_matches_legacy_exactly(self):
        """precision=None must be bit-identical to the pre-policy code
        path (same seed, same steps)."""
        x, y = _data()
        a, b = _mln(None), _mln("float32")
        for _ in range(5):
            a.fit(x, y)
            b.fit(x, y)
        for la, lb in zip(jax.tree_util.tree_leaves(a.params_list),
                          jax.tree_util.tree_leaves(b.params_list)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_output_honors_output_dtype(self):
        x, y = _data()
        net = _mln("mixed_bfloat16")
        net.fit(x, y)
        assert net.output(x).jax.dtype == jnp.float32
        acts = net.feedForward(x)
        assert acts[-1].jax.dtype == jnp.float32
        # custom policy: bf16 outputs on request
        pol = PrecisionPolicy(name="c", compute_dtype="bfloat16",
                              output_dtype="bfloat16")
        net2 = _mln(pol)
        assert net2.output(x).jax.dtype == jnp.dtype("bfloat16")

    def test_per_layer_override_forces_fp32_compute(self):
        x, y = _data()
        pol = PrecisionPolicy(name="c", compute_dtype="bfloat16",
                              layer_overrides={0: "float32"})
        net = _mln(pol)
        net.fit(x, y)
        assert net._compute_dtypes[0] == jnp.dtype("float32")
        assert np.isfinite(net.score())

    def test_cast_count_gauge_recorded(self):
        telemetry.reset()
        _mln("mixed_bfloat16")
        g = telemetry.MetricsRegistry.get_default().gauge(
            P.PRECISION_CASTS)
        # dense W/b cast to bf16; BN + loss head stay fp32 islands
        assert g.value(site="mln") == 2

    def test_mixed_policy_with_lstm_tbptt(self):
        rs = np.random.RandomState(0)
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Adam(1e-2)).precision("mixed_bfloat16").list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                      loss="mcxent"))
                .setInputType(InputType.recurrent(5))
                .backpropType("TruncatedBPTT").tBPTTLength(4).build())
        net = MultiLayerNetwork(conf).init()
        x = rs.randn(4, 12, 5).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[
            rs.randint(0, 5, (4, 12))].astype(np.float32)
        net.fit(x, y)
        assert _float_dtypes(net.params_list) == {"float32"}
        assert np.isfinite(net.score())
        # stateful stepping under the policy
        out = net.rnnTimeStep(x[:, 0])
        assert out.jax.dtype == jnp.float32


class TestMLNLossScaling:
    def test_overflow_halves_scale_and_skips_step(self):
        x, y = _data()
        net = _mln("mixed_float16")
        net.fit(x, y)
        s0 = float(net._loss_scale_state["scale"])
        p0 = jax.device_get(net.params_list)
        o0 = jax.device_get(net.opt_states)
        net.fit(OVERFLOW_X, y)   # f16 forward overflows -> non-finite
        st = net._loss_scale_state
        assert float(st["scale"]) == s0 * 0.5
        assert int(st["overflows"]) == 1
        assert int(st["skipped_steps"]) == 1
        # the NaN step was NOT applied: params and moments held exactly
        for a, b in zip(jax.tree_util.tree_leaves(p0),
                        jax.tree_util.tree_leaves(
                            jax.device_get(net.params_list))):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(jax.tree_util.tree_leaves(o0),
                        jax.tree_util.tree_leaves(
                            jax.device_get(net.opt_states))):
            np.testing.assert_array_equal(a, b)
        # training recovers on the next clean batch
        net.fit(x, y)
        assert all(np.isfinite(l).all() for l in
                   jax.tree_util.tree_leaves(
                       jax.device_get(net.params_list)))

    def test_scale_grows_after_interval(self):
        x, y = _data()
        pol = PrecisionPolicy.of("mixed_float16")
        pol.growth_interval = 3
        net = _mln(pol)
        s0 = float(net._loss_scale_state["scale"])
        for _ in range(3):
            net.fit(x, y)
        assert float(net._loss_scale_state["scale"]) == s0 * 2

    def test_telemetry_counters_increment(self):
        telemetry.reset()
        x, y = _data()
        net = _mln("mixed_float16")
        net.fit(x, y)
        reg = telemetry.MetricsRegistry.get_default()
        assert reg.gauge(P.LOSS_SCALE).value(site="mln") > 0
        assert reg.counter(P.LOSS_SCALE_OVERFLOWS).total() == 0
        net.fit(OVERFLOW_X, y)
        assert reg.counter(P.LOSS_SCALE_OVERFLOWS).value(site="mln") == 1
        assert reg.counter(
            P.LOSS_SCALE_SKIPPED_STEPS).value(site="mln") == 1
        assert reg.gauge(P.LOSS_SCALE).value(site="mln") == \
            float(net._loss_scale_state["scale"])

    def test_f16_loss_parity_on_clean_data(self):
        x, y = _data()
        f32 = _mln("float32")
        f16 = _mln("mixed_float16")
        for _ in range(30):
            f32.fit(x, y)
            f16.fit(x, y)
        rel = abs(f16.score() - f32.score()) / f32.score()
        assert rel < 0.02
        assert int(f16._loss_scale_state["skipped_steps"]) == 0


# ----------------------------------------------------------------------
# check_numerics under half-precision (satellite)
# ----------------------------------------------------------------------
class TestCheckNumericsHalfPrecision:
    @pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
    def test_flags_injected_inf_in_half_precision_activation(self, dtype):
        from deeplearning4j_tpu.profiler import (
            NumericsException, ProfilerMode, check_numerics,
        )

        act = jnp.asarray([1.0, jnp.inf, 2.0], jnp.dtype(dtype))
        with pytest.raises(NumericsException, match="Inf"):
            check_numerics(act, ProfilerMode.INF_PANIC, "in test act")
        nan_act = jnp.asarray([1.0, jnp.nan], jnp.dtype(dtype))
        with pytest.raises(NumericsException, match="NaN"):
            check_numerics(nan_act, ProfilerMode.NAN_PANIC, "in test act")
        # clean half-precision trees pass
        check_numerics(act[:1], ProfilerMode.ANY_PANIC, "clean")

    def test_panic_message_carries_loss_scale_context(self):
        from deeplearning4j_tpu.profiler import (
            NumericsException, OpProfiler, ProfilerConfig, ProfilerMode,
        )

        x, y = _data()
        net = _mln("mixed_float16")
        net.fit(x, y)
        prof = OpProfiler.getInstance()
        old = prof.config
        prof.config = ProfilerConfig(mode=ProfilerMode.ANY_PANIC)
        try:
            with pytest.raises(NumericsException) as ei:
                net.fit(OVERFLOW_X, y)
            assert "loss_scale" in str(ei.value)
            assert "skipped" in str(ei.value)
        finally:
            prof.config = old


# ----------------------------------------------------------------------
# ComputationGraph
# ----------------------------------------------------------------------
def _cg(precision, seed=7):
    b = (ComputationGraphConfiguration.graphBuilder().seed(seed)
         .updater(Adam(1e-2)).precision(precision)
         .addInputs("in")
         .addLayer("d1", DenseLayer(n_out=16, activation="relu"), "in")
         .addLayer("bn", BatchNormalization(), "d1")
         .addLayer("out", OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"), "bn")
         .setOutputs("out")
         .setInputTypes(InputType.feedForward(10)))
    return ComputationGraph(b.build()).init()


class TestCGMixed:
    def test_bf16_masters_and_parity(self):
        x, y = _data()
        f32, bf = _cg(None), _cg("mixed_bfloat16")
        for _ in range(20):
            f32.fit(x, y)
            bf.fit(x, y)
        assert _float_dtypes(bf.params_map) == {"float32"}
        assert _float_dtypes(bf.opt_states) == {"float32"}
        rel = abs(bf.score() - f32.score()) / f32.score()
        assert rel < 0.02
        assert bf.output(x)[0].jax.dtype == jnp.float32

    def test_f16_overflow_skips_and_halves(self):
        x, y = _data()
        g = _cg("mixed_float16")
        g.fit(x, y)
        s0 = float(g._loss_scale_state["scale"])
        p0 = jax.device_get(g.params_map)
        g.fit(OVERFLOW_X, y)
        assert float(g._loss_scale_state["scale"]) == s0 * 0.5
        for a, b in zip(jax.tree_util.tree_leaves(p0),
                        jax.tree_util.tree_leaves(
                            jax.device_get(g.params_map))):
            np.testing.assert_array_equal(a, b)

    def test_graph_json_round_trip_with_policy(self):
        conf = _cg("mixed_bfloat16").conf
        c2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert c2.precision == "mixed_bfloat16"


# ----------------------------------------------------------------------
# ShardedTrainer
# ----------------------------------------------------------------------
class TestShardedMixed:
    def test_sharing_bf16_and_f16(self):
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        x, y = _data()
        for pol in ("mixed_bfloat16", "mixed_float16"):
            net = _mln(pol, bn=False)
            tr = ShardedTrainer(net)
            for _ in range(4):
                tr.fit(x, y)
            assert _float_dtypes(net.params_list) == {"float32"}
            assert np.isfinite(net.score())

    def test_sharing_f16_overflow(self):
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        x, y = _data()
        net = _mln("mixed_float16", bn=False)
        tr = ShardedTrainer(net)
        tr.fit(x, y)
        s0 = float(net._loss_scale_state["scale"])
        tr.fit(OVERFLOW_X, y)
        assert float(net._loss_scale_state["scale"]) == s0 * 0.5

    def test_loss_scaling_rejected_off_sharing(self):
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        for mode in ("averaging", "sharing_compressed"):
            with pytest.raises(ValueError, match="loss scaling"):
                ShardedTrainer(_mln("mixed_float16", bn=False),
                               mode=mode)
            # bf16 (no scaling state) is fine everywhere
            ShardedTrainer(_mln("mixed_bfloat16", bn=False), mode=mode)


# ----------------------------------------------------------------------
# serialization
# ----------------------------------------------------------------------
class TestPrecisionSerialization:
    def test_model_serializer_round_trips_policy_and_scale(self, tmp_path):
        from deeplearning4j_tpu.util import ModelSerializer

        x, y = _data()
        net = _mln("mixed_float16")
        net.fit(x, y)
        net.fit(OVERFLOW_X, y)   # scale halved once
        path = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(net, path)
        with zipfile.ZipFile(path) as zf:
            assert "lossScaleState.npz" in zf.namelist()
        m2 = ModelSerializer.restoreMultiLayerNetwork(path)
        assert m2._policy.loss_scaling
        assert float(m2._loss_scale_state["scale"]) == \
            float(net._loss_scale_state["scale"])
        assert int(m2._loss_scale_state["skipped_steps"]) == 1
        # telemetry baseline tracks the restored counters — a resumed
        # run must not replay checkpointed overflows into the process
        # counters as one spurious jump
        assert m2._ls_seen == (1, 1)
        m2.fit(x, y)   # resumes training
        assert np.isfinite(m2.score())

    def test_bf16_policy_archive_has_no_scale_member(self, tmp_path):
        from deeplearning4j_tpu.util import ModelSerializer

        x, y = _data()
        net = _mln("mixed_bfloat16")
        net.fit(x, y)
        path = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(net, path)
        with zipfile.ZipFile(path) as zf:
            assert "lossScaleState.npz" not in zf.namelist()
        m2 = ModelSerializer.restoreMultiLayerNetwork(path)
        assert m2._policy.compute_dtype == "bfloat16"
        assert _float_dtypes(m2.params_list) == {"float32"}

    def test_sharded_checkpoint_model_helpers(self, tmp_path):
        from deeplearning4j_tpu.util import restore_model, save_model

        x, y = _data()
        net = _mln("mixed_float16")
        net.fit(x, y)
        net.fit(OVERFLOW_X, y)
        save_model(str(tmp_path), net, step=2,
                   iterator_state={"i": 4})
        net2 = _mln("mixed_float16")
        meta = restore_model(str(tmp_path), net2)
        assert meta["step"] == 2
        assert meta["iterator_state"] == {"i": 4}
        assert float(net2._loss_scale_state["scale"]) == \
            float(net._loss_scale_state["scale"])
        for a, b in zip(jax.tree_util.tree_leaves(net.params_list),
                        jax.tree_util.tree_leaves(net2.params_list)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
