"""Pallas paged-attention kernel + fp8 KV cache
(ops/paged_attention_pallas.py, serving/kv_pages.py fp8 path,
nn/precision.py fp8 helpers).

The kernel runs under the Pallas INTERPRETER here (mode="interpret")
so CPU-only CI executes the same kernel body the TPU compiles —
shapes are kept tiny because interpret mode unrolls the grid at trace
time. Golden checks: kernel vs the XLA einsum pair vs a plain numpy
reference, across page counts, mid-page offsets, chunk widths,
null-page masking, and CoW-shared pages; fp8 round-trip error bounds,
frozen-at-page-start scale semantics, and engine-level greedy token
identity (xla vs interpret, including sticky-session resume and
prefix-cache hits) with pools draining to zero.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.gpt import CausalLM
from deeplearning4j_tpu.models.transformer import tiny_config
from deeplearning4j_tpu.nn import precision
from deeplearning4j_tpu.ops.paged_attention_pallas import (
    paged_attention, paged_attention_mode,
)
from deeplearning4j_tpu.serving import DecodeEngine, PagePool
from deeplearning4j_tpu.serving import kv_pages


# ------------------------------------------------------- helpers
def _mk_kv(rng, L, n_pages, H, ps, hd, fp8=False):
    k = rng.standard_normal((L, n_pages, H, ps, hd)).astype(np.float32)
    v = rng.standard_normal((L, n_pages, H, ps, hd)).astype(np.float32)
    kv = {"k": jnp.asarray(k), "v": jnp.asarray(v)}
    if fp8:
        out = {}
        for name, x in (("k", k), ("v", v)):
            am = jnp.asarray(np.abs(x).max(axis=(3, 4)))
            sc = precision.fp8_scale(am)
            out[name] = precision.quantize_fp8(
                jnp.asarray(x), sc[..., None, None])
            out[name + "_scale"] = sc
        kv = {"k": out["k"], "v": out["v"],
              "k_scale": out["k_scale"], "v_scale": out["v_scale"]}
    return kv


def _np_ref(q, kp, vp, tables, qbase):
    """Dense float32 reference over one layer's pages."""
    N, H, Q, hd = q.shape
    ps = kp.shape[2]
    out = np.zeros((N, H, Q, hd), np.float32)
    for n in range(N):
        keys = kp[tables[n]].transpose(1, 0, 2, 3).reshape(H, -1, hd)
        vals = vp[tables[n]].transpose(1, 0, 2, 3).reshape(H, -1, hd)
        for qi in range(Q):
            valid = np.arange(keys.shape[1]) <= qbase[n] + qi
            s = np.einsum("hd,htd->ht", q[n, :, qi], keys) / np.sqrt(hd)
            s = np.where(valid[None, :], s, -np.inf)
            w = np.exp(s - s.max(-1, keepdims=True))
            w /= w.sum(-1, keepdims=True)
            out[n, :, qi] = np.einsum("ht,htd->hd", w, vals)
    return out


def _both(q, kv, layer, tables, qbase):
    ker = np.asarray(paged_attention(q, kv, layer, tables, qbase,
                                     mode="interpret"))
    xla = np.asarray(paged_attention(q, kv, layer, tables, qbase,
                                     mode="xla"))
    return ker, xla


# ------------------------------------------------------- kernel golden
class TestKernelGolden:
    @pytest.mark.parametrize("P", [1, 2, 4])
    def test_decode_matches_xla_across_page_counts(self, P):
        rng = np.random.default_rng(P)
        L, H, ps, hd, N = 2, 2, 4, 8, 2
        kv = _mk_kv(rng, L, 1 + N * P, H, ps, hd)
        tables = jnp.asarray(
            1 + np.arange(N * P).reshape(N, P), jnp.int32)
        # mid-page offsets on purpose: qbase not a page multiple
        qbase = jnp.asarray([P * ps - 2, max(ps - 3, 0)], jnp.int32)
        q = jnp.asarray(rng.standard_normal((N, H, 1, hd)), jnp.float32)
        for layer in range(L):
            ker, xla = _both(q, kv, layer, tables, qbase)
            np.testing.assert_allclose(ker, xla, atol=1e-5, rtol=1e-5)

    def test_matches_dense_numpy_reference(self):
        rng = np.random.default_rng(0)
        L, H, ps, hd, N, P = 1, 2, 4, 8, 3, 3
        kv = _mk_kv(rng, L, 12, H, ps, hd)
        tables = jnp.asarray(
            1 + np.arange(N * P).reshape(N, P), jnp.int32)
        qbase = jnp.asarray([1, 5, 10], jnp.int32)   # mid-page spread
        q = jnp.asarray(rng.standard_normal((N, H, 1, hd)), jnp.float32)
        ker, xla = _both(q, kv, 0, tables, qbase)
        ref = _np_ref(np.asarray(q), np.asarray(kv["k"][0]),
                      np.asarray(kv["v"][0]), np.asarray(tables),
                      np.asarray(qbase))
        np.testing.assert_allclose(ker, ref, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(xla, ref, atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("Q", [2, 4, 8])
    def test_prefill_chunk_widths(self, Q):
        """Q > 1 is the prefix-prefill geometry: the same kernel serves
        every chunk width with the causal mask sliding per row."""
        rng = np.random.default_rng(Q)
        L, H, ps, hd, P = 1, 2, 4, 8, 3
        kv = _mk_kv(rng, L, 6, H, ps, hd)
        tables = jnp.asarray([[1, 2, 3]], jnp.int32)
        qbase = jnp.asarray([3], jnp.int32)          # mid-page start
        q = jnp.asarray(rng.standard_normal((1, H, Q, hd)), jnp.float32)
        ker, xla = _both(q, kv, 0, tables, qbase)
        ref = _np_ref(np.asarray(q), np.asarray(kv["k"][0]),
                      np.asarray(kv["v"][0]), np.asarray(tables),
                      np.asarray(qbase))
        np.testing.assert_allclose(ker, xla, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(ker, ref, atol=1e-4, rtol=1e-4)

    def test_null_page_and_tail_garbage_masked(self):
        """Unallocated table rows point at null page 0 and positions
        beyond qpos may hold arbitrary garbage — neither may leak into
        the output of either implementation."""
        rng = np.random.default_rng(3)
        L, H, ps, hd, N, P = 1, 2, 4, 8, 2, 3
        kv = _mk_kv(rng, L, 8, H, ps, hd)
        # slot 0 owns one real page (positions 0..3), rows 1..2 -> null
        # page; slot 1 owns two pages, mid-page at position 5
        tables = jnp.asarray([[1, 0, 0], [2, 3, 0]], jnp.int32)
        qbase = jnp.asarray([2, 5], jnp.int32)
        q = jnp.asarray(rng.standard_normal((N, H, 1, hd)), jnp.float32)
        clean_k, clean_v = np.asarray(kv["k"]), np.asarray(kv["v"])

        dirty_k, dirty_v = clean_k.copy(), clean_v.copy()
        dirty_k[:, 0], dirty_v[:, 0] = 1e4, -1e4     # null page garbage
        dirty_k[:, 1, :, 3:], dirty_v[:, 1, :, 3:] = 1e4, -1e4  # > qpos
        dirty_k[:, 3, :, 2:], dirty_v[:, 3, :, 2:] = -1e4, 1e4  # > qpos
        dirty = {"k": jnp.asarray(dirty_k), "v": jnp.asarray(dirty_v)}

        ker_c, xla_c = _both(q, kv, 0, tables, qbase)
        ker_d, xla_d = _both(q, dirty, 0, tables, qbase)
        np.testing.assert_allclose(ker_d, ker_c, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(xla_d, xla_c, atol=1e-5, rtol=1e-5)

    def test_cow_shared_pages(self):
        """Two slots mapping the SAME physical page (a prefix-cache
        hit before divergence) read identically to two private copies
        of it."""
        rng = np.random.default_rng(4)
        L, H, ps, hd = 1, 2, 4, 8
        kv = _mk_kv(rng, L, 8, H, ps, hd)
        # page 1 shared; pages 2/3 private seconds; page 4 = copy of 1
        shared = jnp.asarray([[1, 2], [1, 3]], jnp.int32)
        kc = np.asarray(kv["k"]).copy()
        vc = np.asarray(kv["v"]).copy()
        kc[:, 4], vc[:, 4] = kc[:, 1], vc[:, 1]
        private = jnp.asarray([[1, 2], [4, 3]], jnp.int32)
        kv2 = {"k": jnp.asarray(kc), "v": jnp.asarray(vc)}
        qbase = jnp.asarray([6, 7], jnp.int32)
        q = jnp.asarray(rng.standard_normal((2, H, 1, hd)), jnp.float32)
        ker_s, xla_s = _both(q, kv, 0, shared, qbase)
        ker_p, xla_p = _both(q, kv2, 0, private, qbase)
        np.testing.assert_allclose(ker_s, ker_p, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(xla_s, xla_p, atol=1e-5, rtol=1e-5)

    def test_bad_mode_raises(self):
        rng = np.random.default_rng(5)
        kv = _mk_kv(rng, 1, 3, 2, 4, 8)
        q = jnp.zeros((1, 2, 1, 8), jnp.float32)
        with pytest.raises(ValueError, match="paged-attention mode"):
            paged_attention(q, kv, 0, jnp.asarray([[1]], jnp.int32),
                            jnp.asarray([0], jnp.int32), mode="cuda")

    def test_env_mode_resolution(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PAGED_ATTN", "interpret")
        assert paged_attention_mode() == "interpret"
        monkeypatch.delenv("DL4J_TPU_PAGED_ATTN")
        # auto: pallas only when a TPU backend is live
        expect = ("pallas" if jax.default_backend() == "tpu"
                  else "xla")
        assert paged_attention_mode() == expect


# ------------------------------------------------------- fp8 numerics
class TestFp8:
    def test_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 16)) * 3, jnp.float32)
        am = jnp.max(jnp.abs(x), axis=-1)
        sc = precision.fp8_scale(am)
        deq = precision.dequantize_fp8(
            precision.quantize_fp8(x, sc[:, None]), sc[:, None],
            jnp.float32)
        # e4m3: 3 mantissa bits -> relative half-step 2**-4 of the
        # value, i.e. <= amax/16 absolute after scaling to +-448
        err = np.abs(np.asarray(deq) - np.asarray(x))
        bound = np.asarray(am)[:, None] / 16 + 1e-6
        assert (err <= bound).all()

    def test_scale_floor_handles_zero_pages(self):
        z = jnp.zeros((2, 8), jnp.float32)
        sc = precision.fp8_scale(jnp.max(jnp.abs(z), axis=-1))
        assert (np.asarray(sc) > 0).all()
        deq = precision.dequantize_fp8(
            precision.quantize_fp8(z, sc[:, None]), sc[:, None],
            jnp.float32)
        assert (np.asarray(deq) == 0).all()

    def test_kernel_matches_xla_on_fp8(self):
        rng = np.random.default_rng(1)
        L, H, ps, hd, N, P = 2, 2, 4, 8, 2, 2
        kv8 = _mk_kv(rng, L, 6, H, ps, hd, fp8=True)
        tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        qbase = jnp.asarray([5, 3], jnp.int32)
        q = jnp.asarray(rng.standard_normal((N, H, 1, hd)), jnp.float32)
        for layer in range(L):
            ker, xla = _both(q, kv8, layer, tables, qbase)
            np.testing.assert_allclose(ker, xla, atol=1e-5, rtol=1e-5)

    def test_fp8_close_to_float_within_quantization(self):
        rng = np.random.default_rng(2)
        L, H, ps, hd = 1, 2, 4, 8
        kvf = _mk_kv(rng, L, 6, H, ps, hd)
        kv8 = {"k": kvf["k"], "v": kvf["v"]}
        kv8 = _mk_kv(np.random.default_rng(2), L, 6, H, ps, hd,
                     fp8=True)
        tables = jnp.asarray([[1, 2]], jnp.int32)
        qbase = jnp.asarray([6], jnp.int32)
        q = jnp.asarray(rng.standard_normal((1, H, 1, hd)), jnp.float32)
        ref = np.asarray(paged_attention(q, kvf, 0, tables, qbase,
                                         mode="xla"))
        got = np.asarray(paged_attention(q, kv8, 0, tables, qbase,
                                         mode="interpret"))
        np.testing.assert_allclose(got, ref, atol=0.15)


# ------------------------------------------------- fp8 page semantics
class TestFp8Pages:
    def _pool_kv(self, L=1, H=2, ps=4, hd=8, n_pages=6):
        pool = PagePool(L, H, ps, hd, n_pages=n_pages,
                        dtype=jnp.float32, kv_dtype="fp8_e4m3")
        return pool, pool.tree()

    def test_commit_prefill_n_valid_masks_padded_tail(self):
        """Garbage past the true prompt length must not inflate a
        page's scale: scales with a huge padded tail equal scales with
        a zero tail."""
        _, kv = self._pool_kv()
        L, H, ps, hd, B = 1, 2, 4, 8, 8
        rng = np.random.default_rng(0)
        base = rng.standard_normal((L, 1, H, B, hd)).astype(np.float32)
        dirty = base.copy()
        dirty[:, :, :, 5:, :] = 1e3                    # padded tail
        clean = base.copy()
        clean[:, :, :, 5:, :] = 0.0
        row = jnp.asarray([1, 2], jnp.int32)
        out_d = kv_pages.commit_prefill(
            kv, jnp.asarray(dirty), jnp.asarray(dirty), row, ps,
            n_valid=jnp.asarray(5, jnp.int32))
        out_c = kv_pages.commit_prefill(
            kv, jnp.asarray(clean), jnp.asarray(clean), row, ps,
            n_valid=jnp.asarray(5, jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(out_d["k_scale"]), np.asarray(out_c["k_scale"]))
        # valid positions round-trip within the e4m3 bound
        deq = precision.dequantize_fp8(
            out_d["k"][0, row[0], :, :, :],
            out_d["k_scale"][0, row[0]][:, None, None], jnp.float32)
        ref = base[0, 0, :, :ps, :].transpose(0, 1, 2)
        np.testing.assert_allclose(np.asarray(deq), ref, atol=0.26)

    def test_append_token_scale_frozen_after_page_start(self):
        """offset==0 mints the page scale; later offsets reuse it even
        for outlier tokens (which clip instead of re-scaling earlier
        entries under their feet)."""
        _, kv = self._pool_kv()
        page = jnp.asarray([1], jnp.int32)
        k0 = jnp.full((1, 2, 8), 2.0, jnp.float32)
        kv = kv_pages.append_token(kv, 0, page,
                                   jnp.asarray([0], jnp.int32), k0, k0)
        minted = np.asarray(kv["k_scale"][0, 1]).copy()
        k1 = jnp.full((1, 2, 8), 400.0, jnp.float32)   # outlier
        kv = kv_pages.append_token(kv, 0, page,
                                   jnp.asarray([1], jnp.int32), k1, k1)
        np.testing.assert_array_equal(
            np.asarray(kv["k_scale"][0, 1]), minted)
        # the offset-0 entry still dequantizes to its original value
        deq = precision.dequantize_fp8(
            kv["k"][0, 1, :, 0, :], kv["k_scale"][0, 1][:, None],
            jnp.float32)
        np.testing.assert_allclose(np.asarray(deq), 2.0, atol=0.2)

    def test_append_suffix_scale_semantics(self):
        """A page whose offset-0 lane is in the suffix batch mints a
        fresh scale from the EXACT amax over every lane it receives; a
        page entered mid-way (the resume boundary) keeps its stored
        scale; untouched pages and padded lanes change nothing."""
        _, kv = self._pool_kv()
        ps, H, hd, P = 4, 2, 8, 3
        rng = np.random.default_rng(1)
        table = jnp.asarray([1, 2, 3], jnp.int32)
        # pre-commit page 2 positions 4..5 (the resumed boundary page)
        pre = jnp.full((1, H, hd), 2.0, jnp.float32)
        for off in (0, 1):
            kv = kv_pages.append_token(
                kv, 0, jnp.asarray([2], jnp.int32),
                jnp.asarray([off], jnp.int32), pre, pre)
        boundary_scale = np.asarray(kv["k_scale"][0, 2]).copy()
        # suffix covers positions 6..9: page 2 mid-way, page 3 fresh
        pos = np.arange(6, 10)
        B = 8
        ks = rng.standard_normal((B, H, hd)).astype(np.float32) * 5
        real = np.arange(B) < pos.size
        padded_pos = np.concatenate([pos, np.zeros(B - pos.size, int)])
        chunk = np.where(real, padded_pos // ps, P)
        page = np.where(real, np.asarray(table)[
            np.minimum(padded_pos // ps, P - 1)], 0)
        off = np.where(real, padded_pos % ps, 0)
        out = kv_pages.append_suffix(
            kv, 0, jnp.asarray(page, jnp.int32),
            jnp.asarray(off, jnp.int32), jnp.asarray(ks),
            jnp.asarray(ks), chunk=jnp.asarray(chunk, jnp.int32),
            real=jnp.asarray(real), table=table)
        # boundary page keeps its frozen scale; fresh page 3 mints the
        # exact amax over its two lanes (positions 8, 9)
        np.testing.assert_array_equal(
            np.asarray(out["k_scale"][0, 2]), boundary_scale)
        want = precision.fp8_scale(jnp.max(jnp.abs(
            jnp.asarray(ks[2:4])), axis=(0, 2)))
        np.testing.assert_allclose(
            np.asarray(out["k_scale"][0, 3]), np.asarray(want),
            atol=1e-6)
        # untouched page 1 still at the init scale of 1
        np.testing.assert_array_equal(
            np.asarray(out["k_scale"][0, 1]), 1.0)
        # page-3 lanes round-trip within the e4m3 bound of their amax
        deq = precision.dequantize_fp8(
            out["k"][0, 3, :, 0:2, :],
            out["k_scale"][0, 3][:, None, None], jnp.float32)
        ref = np.asarray(ks[2:4]).transpose(1, 0, 2)
        bound = np.asarray(want)[:, None, None] * 448 / 16 + 1e-6
        assert (np.abs(np.asarray(deq) - ref) <= bound).all()

    def test_copy_page_carries_scales(self):
        _, kv = self._pool_kv()
        page = jnp.asarray([1], jnp.int32)
        k0 = jnp.full((1, 2, 8), 3.0, jnp.float32)
        kv = kv_pages.append_token(kv, 0, page,
                                   jnp.asarray([0], jnp.int32), k0, k0)
        out = kv_pages.copy_page(kv, jnp.asarray(1), jnp.asarray(4))
        np.testing.assert_array_equal(
            np.asarray(out["k_scale"][:, 4]),
            np.asarray(kv["k_scale"][:, 1]))
        np.testing.assert_array_equal(
            np.asarray(out["k"][:, 4]).view(np.uint8),
            np.asarray(kv["k"][:, 1]).view(np.uint8))

    def test_pool_bytes_capacity_and_gauge(self):
        from deeplearning4j_tpu.profiler import telemetry

        bf16 = PagePool(2, 4, 8, 16, n_pages=4, dtype=jnp.bfloat16,
                        engine_id="t_bf16")
        fp8 = PagePool(2, 4, 8, 16, n_pages=4, dtype=jnp.bfloat16,
                       kv_dtype="fp8_e4m3", engine_id="t_fp8")
        ratio = bf16.bytes_per_page() / fp8.bytes_per_page()
        assert ratio >= 1.8                     # the capacity claim
        assert fp8.dtype_label == "fp8_e4m3"
        assert bf16.dtype_label == "bfloat16"
        reg = telemetry.MetricsRegistry.get_default()
        g = reg.gauge(telemetry.SERVING_KV_PAGE_BYTES)
        assert g.value(engine="t_fp8", kv_dtype="fp8_e4m3") \
            == fp8.bytes_per_page()
        assert g.value(engine="t_bf16", kv_dtype="bfloat16") \
            == bf16.bytes_per_page()

    def test_bad_kv_dtype_raises(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            PagePool(1, 2, 4, 4, n_pages=3, kv_dtype="int4")


# ------------------------------------------------- engine token identity
VOCAB = 13


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(vocab=VOCAB, max_len=48, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    return CausalLM(cfg, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(jax.random.key(1))


def _engine(model, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_context", 32)   # interpret unrolls the grid:
    kw.setdefault("max_chunk", 4)      # keep slots*H*pages tiny
    kw.setdefault("prefill_buckets", [8, 16])
    return DecodeEngine(model, params, **kw)


def _serve(eng, jobs):
    """jobs: list of (prompt, new, session_id|None) -> token arrays."""
    try:
        outs = []
        for p, n, sid in jobs:
            r = eng.submit(p, n, session_id=sid)
            outs.append(np.asarray(r.result(timeout=300)))
        drained = eng.pool.allocated if eng._sessions is None else None
        stats = eng.stats()
    finally:
        eng.shutdown()
    return outs, drained, stats


class TestEngineTokenIdentity:
    def _jobs(self, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda n: rng.integers(0, VOCAB, (n,)).astype(np.int32)
        shared = mk(10)
        return [
            (mk(6), 6, None),
            (np.concatenate([shared, mk(3)]), 5, None),
            (mk(9), 6, "conv"),                 # session open
            (np.concatenate([shared, mk(2)]), 5, None),  # prefix hit
            (mk(4), 4, "conv"),                 # session RESUME
            (mk(11), 6, None),
        ]

    def test_interpret_token_identical_to_xla(self, model, params):
        """The CI-facing identity claim: same greedy tokens from the
        kernel engine and the einsum engine, across prefix-cache hits
        and a sticky-session resume, with zero warm-pool misses."""
        jobs = self._jobs()
        a, _, sa = _serve(_engine(model, params, prefix_cache=True,
                                  session_capacity=2,
                                  attn_mode="xla"), jobs)
        b, _, sb = _serve(_engine(model, params, prefix_cache=True,
                                  session_capacity=2,
                                  attn_mode="interpret"), jobs)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert sa["warm_pool"]["misses"] == 0
        assert sb["warm_pool"]["misses"] == 0
        assert sb["attn_mode"] == "interpret"

    def test_fp8_agreement_and_drain(self, model, params):
        """fp8 is agreement-gated, not identity-gated; pools (and with
        them the scale planes) must drain to zero when no sessions pin
        pages."""
        jobs = [(p, n, None) for p, n, _ in self._jobs(1)]
        ref, d0, _ = _serve(_engine(model, params, attn_mode="xla"),
                            jobs)
        f8, d1, st = _serve(_engine(model, params,
                                    attn_mode="interpret",
                                    kv_dtype="fp8_e4m3"), jobs)
        agree = np.mean([np.array_equal(x, y)
                         for x, y in zip(ref, f8)])
        assert agree >= 0.75
        assert d0 == 0 and d1 == 0
        assert st["kv_dtype"] == "fp8_e4m3"
        assert st["kv_pages"]["page_bytes"] < 2048  # < bf16 full page

    def test_bad_engine_args_raise(self, model, params):
        with pytest.raises(ValueError, match="attn_mode"):
            DecodeEngine(model, params, slots=2, page_size=8,
                         max_context=16, attn_mode="rocm")
        with pytest.raises(ValueError, match="kv_dtype"):
            DecodeEngine(model, params, slots=2, page_size=8,
                         max_context=16, kv_dtype="fp4")
