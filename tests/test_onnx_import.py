"""ONNX import: wire-format decode + graph import into SameDiff.

Reference: samediff-import-onnx (SURVEY.md §2.14). The environment has
no `onnx` package, so fixtures are built with a minimal protobuf wire
ENCODER below (independent of the decoder under test — encoder bugs
would produce decode failures, not silent agreement). Numerical ground
truth comes from numpy/torch (CPU).
"""

import struct

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.onnx.onnx_import import (
    OnnxImport, OnnxImportError, OnnxOpMappingRegistry,
)
from deeplearning4j_tpu.modelimport.onnx.onnx_proto import decode_model


# ------------------------------------------------------- tiny pb encoder
def _varint(v: int) -> bytes:
    out = b""
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field: int, wt: int) -> bytes:
    return _varint((field << 3) | wt)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _str(field: int, s: str) -> bytes:
    return _ld(field, s.encode())


def _iv(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def tensor(name: str, arr: np.ndarray) -> bytes:
    dt = {np.dtype(np.float32): 1, np.dtype(np.int64): 7,
          np.dtype(np.int32): 6, np.dtype(np.uint8): 2,
          np.dtype(np.int8): 3}[arr.dtype]
    out = b"".join(_iv(1, d) for d in arr.shape)
    out += _iv(2, dt)
    out += _str(8, name)
    out += _ld(9, arr.tobytes())
    return out


def attr_int(name: str, v: int) -> bytes:
    return _str(1, name) + _iv(3, v) + _iv(20, 2)


def attr_float(name: str, v: float) -> bytes:
    return _str(1, name) + _tag(2, 5) + struct.pack("<f", v) + _iv(20, 1)


def attr_ints(name: str, vs) -> bytes:
    packed = b"".join(_varint(v) for v in vs)
    return _str(1, name) + _ld(8, packed) + _iv(20, 7)


def attr_tensor(name: str, t: bytes) -> bytes:
    return _str(1, name) + _ld(5, t) + _iv(20, 4)


def node(op: str, inputs, outputs, name="", attrs=()) -> bytes:
    out = b"".join(_str(1, i) for i in inputs)
    out += b"".join(_str(2, o) for o in outputs)
    out += _str(3, name or op.lower())
    out += _str(4, op)
    out += b"".join(_ld(5, a) for a in attrs)
    return out


def value_info(name: str, shape) -> bytes:
    dims = b"".join(_ld(1, _iv(1, d)) for d in shape)
    tensor_type = _iv(1, 1) + _ld(2, dims)
    return _str(1, name) + _ld(2, _ld(1, tensor_type))


def graph(nodes, initializers, inputs, outputs) -> bytes:
    out = b"".join(_ld(1, n) for n in nodes)
    out += _str(2, "g")
    out += b"".join(_ld(5, t) for t in initializers)
    out += b"".join(_ld(11, vi) for vi in inputs)
    out += b"".join(_ld(12, vi) for vi in outputs)
    return out


def model(graph_bytes: bytes, opset: int = 13) -> bytes:
    out = _iv(1, 8)                                   # ir_version
    out += _str(2, "dl4j-tpu-test")                   # producer
    out += _ld(7, graph_bytes)
    out += _ld(8, _iv(2, opset))                      # opset_import
    return out


# ---------------------------------------------------------------- fixtures
def _mlp_model(rs):
    w1 = rs.randn(4, 8).astype(np.float32)
    b1 = rs.randn(8).astype(np.float32)
    w2 = rs.randn(8, 3).astype(np.float32)
    b2 = rs.randn(3).astype(np.float32)
    g = graph(
        nodes=[
            node("Gemm", ["x", "w1", "b1"], ["h"], "fc1"),
            node("Relu", ["h"], ["hr"], "relu1"),
            node("Gemm", ["hr", "w2", "b2"], ["logits"], "fc2"),
            node("Softmax", ["logits"], ["probs"], "sm",
                 attrs=[attr_int("axis", 1)]),
        ],
        initializers=[tensor("w1", w1), tensor("b1", b1),
                      tensor("w2", w2), tensor("b2", b2)],
        inputs=[value_info("x", [2, 4])],
        outputs=[value_info("probs", [2, 3])],
    )
    return model(g), (w1, b1, w2, b2)


class TestDecoder:
    def test_model_fields(self):
        rs = np.random.RandomState(0)
        blob, _ = _mlp_model(rs)
        m = decode_model(blob)
        assert m.producer_name == "dl4j-tpu-test"
        assert m.opset_version == 13
        assert len(m.graph.nodes) == 4
        assert [n.op_type for n in m.graph.nodes] == \
            ["Gemm", "Relu", "Gemm", "Softmax"]
        assert m.graph.nodes[3].attributes["axis"] == 1
        assert m.graph.inputs[0].shape == [2, 4]

    def test_tensor_raw_roundtrip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        m = decode_model(model(graph([], [tensor("t", arr)], [], [])))
        got = m.graph.initializers[0].to_numpy()
        np.testing.assert_array_equal(got, arr)

    def test_int64_tensor(self):
        arr = np.asarray([2, -1, 7], np.int64)
        m = decode_model(model(graph([], [tensor("t", arr)], [], [])))
        np.testing.assert_array_equal(m.graph.initializers[0].to_numpy(), arr)

    def test_garbage_rejected(self):
        from deeplearning4j_tpu.modelimport.onnx.onnx_proto import (
            OnnxDecodeError,
        )
        with pytest.raises(OnnxDecodeError):
            decode_model(b"\x08\x01")  # no graph


class TestMlpImport:
    def test_matches_numpy(self):
        rs = np.random.RandomState(1)
        blob, (w1, b1, w2, b2) = _mlp_model(rs)
        sd = OnnxImport.importGraph(blob)
        x = rs.randn(2, 4).astype(np.float32)
        got = np.asarray(sd.output({"x": x}, ["probs"])["probs"])
        h = np.maximum(x @ w1 + b1, 0)
        logits = h @ w2 + b2
        e = np.exp(logits - logits.max(1, keepdims=True))
        want = e / e.sum(1, keepdims=True)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_unknown_op_error(self):
        g = graph([node("NotARealOp", ["x"], ["y"])], [],
                  [value_info("x", [1])], [value_info("y", [1])])
        with pytest.raises(OnnxImportError, match="NotARealOp"):
            OnnxImport.importGraph(model(g))

    def test_coverage_listing(self):
        cov = OnnxOpMappingRegistry.coverage()
        assert len(cov) >= 60
        for required in ("Conv", "Gemm", "MatMul", "BatchNormalization",
                         "Softmax", "Reshape", "Transpose", "MaxPool"):
            assert required in cov


class TestConvImport:
    def test_conv_pool_matches_torch(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(2)
        x = rs.randn(2, 3, 8, 8).astype(np.float32)       # NCHW
        w = rs.randn(5, 3, 3, 3).astype(np.float32)       # OIHW
        b = rs.randn(5).astype(np.float32)
        g = graph(
            nodes=[
                node("Conv", ["x", "w", "b"], ["c"], "conv",
                     attrs=[attr_ints("kernel_shape", [3, 3]),
                            attr_ints("strides", [1, 1]),
                            attr_ints("pads", [1, 1, 1, 1])]),
                node("Relu", ["c"], ["cr"], "relu"),
                node("MaxPool", ["cr"], ["p"], "pool",
                     attrs=[attr_ints("kernel_shape", [2, 2]),
                            attr_ints("strides", [2, 2])]),
                node("Flatten", ["p"], ["f"], "flat",
                     attrs=[attr_int("axis", 1)]),
            ],
            initializers=[tensor("w", w), tensor("b", b)],
            inputs=[value_info("x", [2, 3, 8, 8])],
            outputs=[value_info("f", [2, 80])],
        )
        sd = OnnxImport.importGraph(model(g))
        got = np.asarray(sd.output({"x": x}, ["f"])["f"])

        tx = torch.from_numpy(x)
        tc = torch.nn.functional.conv2d(tx, torch.from_numpy(w),
                                        torch.from_numpy(b), padding=1)
        tp = torch.nn.functional.max_pool2d(torch.relu(tc), 2, 2)
        want = tp.reshape(2, -1).numpy()
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_batchnorm_gap(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(3)
        x = rs.randn(2, 4, 6, 6).astype(np.float32)
        scale = rs.rand(4).astype(np.float32) + 0.5
        bias = rs.randn(4).astype(np.float32)
        mean = rs.randn(4).astype(np.float32)
        var = rs.rand(4).astype(np.float32) + 0.5
        g = graph(
            nodes=[
                node("BatchNormalization",
                     ["x", "scale", "bias", "mean", "var"], ["bn"], "bn",
                     attrs=[attr_float("epsilon", 1e-5)]),
                node("GlobalAveragePool", ["bn"], ["gap"], "gap"),
                node("Squeeze", ["gap"], ["out"], "sq",
                     attrs=[attr_ints("axes", [2, 3])]),
            ],
            initializers=[tensor("scale", scale), tensor("bias", bias),
                          tensor("mean", mean), tensor("var", var)],
            inputs=[value_info("x", [2, 4, 6, 6])],
            outputs=[value_info("out", [2, 4])],
        )
        sd = OnnxImport.importGraph(model(g))
        got = np.asarray(sd.output({"x": x}, ["out"])["out"])
        tb = torch.nn.functional.batch_norm(
            torch.from_numpy(x), torch.from_numpy(mean),
            torch.from_numpy(var), torch.from_numpy(scale),
            torch.from_numpy(bias), training=False, eps=1e-5)
        want = tb.mean(dim=(2, 3)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestOnnxShapeOps:
    def test_reshape_transpose_concat_slice(self):
        rs = np.random.RandomState(4)
        x = rs.randn(2, 6).astype(np.float32)
        g = graph(
            nodes=[
                node("Reshape", ["x", "shape"], ["r"], "rs"),
                node("Transpose", ["r"], ["t"], "tp",
                     attrs=[attr_ints("perm", [0, 2, 1])]),
                node("Concat", ["t", "t"], ["cc"], "cc",
                     attrs=[attr_int("axis", 2)]),
                node("Slice", ["cc"], ["s"], "sl",
                     attrs=[attr_ints("starts", [0]),
                            attr_ints("ends", [2]),
                            attr_ints("axes", [2])]),
            ],
            initializers=[tensor("shape", np.asarray([0, 2, 3], np.int64))],
            inputs=[value_info("x", [2, 6])],
            outputs=[value_info("s", [2, 3, 2])],
        )
        sd = OnnxImport.importGraph(model(g))
        got = np.asarray(sd.output({"x": x}, ["s"])["s"])
        r = x.reshape(2, 2, 3).transpose(0, 2, 1)
        want = np.concatenate([r, r], 2)[:, :, :2]
        np.testing.assert_allclose(got, want, rtol=1e-6)


def attr_str(name: str, s: str) -> bytes:
    return _str(1, name) + _ld(4, s.encode()) + _iv(20, 3)


class _SingleNodeGo:
    """Shared helper: build a one-node graph, import, compare."""

    def _go(self, op, attrs, feeds, inits, want, extra_inputs=(),
            n_out=1, rtol=1e-5, atol=1e-6):
        in_names = list(feeds) + list(extra_inputs)
        self._onames = [f"o{i}" for i in range(n_out)]
        g = graph(
            nodes=[node(op, in_names, self._onames, "n", attrs=attrs)],
            initializers=inits,
            inputs=[value_info(k, list(v.shape)) for k, v in feeds.items()],
            outputs=[value_info(o, []) for o in self._onames],
        )
        sd = OnnxImport.importGraph(model(g))
        got = sd.output(feeds, self._onames)
        for o, w in zip(self._onames, want if n_out > 1 else [want]):
            np.testing.assert_allclose(np.asarray(got[o]), w, rtol=rtol,
                                       atol=atol)


class TestOnnxBreadthRound4(_SingleNodeGo):
    """Round-4 mapper batch: the common exported-model op tail
    (reference: samediff-import-onnx's mapper set spans these)."""

    def test_split_equal_and_uneven(self):
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        self._go("Split", [attr_int("axis", 1)], {"x": x}, [],
                 [x[:, :2], x[:, 2:4], x[:, 4:]], n_out=3)
        self._go("Split", [attr_int("axis", 1)], {"x": x},
                 [tensor("sz", np.asarray([1, 5], np.int64))],
                 [x[:, :1], x[:, 1:]], extra_inputs=["sz"], n_out=2)

    def test_conv_transpose_matches_torch(self):
        import torch

        rs = np.random.RandomState(0)
        x = rs.randn(2, 3, 5, 5).astype(np.float32)
        w = (rs.randn(3, 4, 3, 3) * 0.3).astype(np.float32)
        want = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), stride=2,
            padding=1).numpy()
        self._go("ConvTranspose",
                 [attr_ints("strides", [2, 2]),
                  attr_ints("pads", [1, 1, 1, 1])],
                 {"x": x}, [tensor("w", w)], want,
                 extra_inputs=["w"], rtol=1e-4, atol=1e-5)

    def test_resize_nearest_and_linear(self):
        import torch

        rs = np.random.RandomState(1)
        x = rs.randn(1, 2, 3, 4).astype(np.float32)
        want = x.repeat(2, axis=2).repeat(3, axis=3)
        # asymmetric is always paired with nearest_mode=floor by real
        # exporters (torch); with the spec-default round_prefer_floor
        # the scale-3 axis would NOT be a plain repeat (src(2)=rpf(2/3)=1)
        self._go("Resize",
                 [attr_str("mode", "nearest"),
                  attr_str("coordinate_transformation_mode",
                           "asymmetric"),
                  attr_str("nearest_mode", "floor")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.asarray([1, 1, 2, 3], np.float32))],
                 want, extra_inputs=["roi", "sc"])
        want_lin = torch.nn.functional.interpolate(
            torch.tensor(x), size=(6, 8), mode="bilinear",
            align_corners=False).numpy()
        self._go("Resize",
                 [attr_str("mode", "linear"),
                  attr_str("coordinate_transformation_mode",
                           "half_pixel")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.zeros(0, np.float32)),
                  tensor("sizes", np.asarray([1, 2, 6, 8], np.int64))],
                 want_lin, extra_inputs=["roi", "sc", "sizes"],
                 rtol=1e-4, atol=1e-5)

    def test_resize_nearest_sizes_asymmetric_floor(self):
        """Non-integer downscale-by-sizes with asymmetric/floor (the
        torch interpolate(mode='nearest') export): src row/col must be
        floor(i*in/out), NOT half-pixel centers."""
        import torch

        rs = np.random.RandomState(3)
        x = rs.randn(1, 2, 3, 5).astype(np.float32)
        want = torch.nn.functional.interpolate(
            torch.tensor(x), size=(4, 4), mode="nearest").numpy()
        self._go("Resize",
                 [attr_str("mode", "nearest"),
                  attr_str("coordinate_transformation_mode", "asymmetric"),
                  attr_str("nearest_mode", "floor")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.zeros(0, np.float32)),
                  tensor("sizes", np.asarray([1, 2, 4, 4], np.int64))],
                 want, extra_inputs=["roi", "sc", "sizes"])

    def test_resize_nearest_half_pixel_prefer_floor(self):
        """Spec-default nearest (half_pixel + round_prefer_floor) on a
        non-integer ratio: torch's 'nearest-exact' implements the same
        coordinate map."""
        import torch

        rs = np.random.RandomState(4)
        x = rs.randn(1, 1, 3, 3).astype(np.float32)
        want = torch.nn.functional.interpolate(
            torch.tensor(x), size=(4, 5), mode="nearest-exact").numpy()
        self._go("Resize",
                 [attr_str("mode", "nearest")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.zeros(0, np.float32)),
                  tensor("sizes", np.asarray([1, 1, 4, 5], np.int64))],
                 want, extra_inputs=["roi", "sc", "sizes"])

    def test_resize_linear_align_corners(self):
        import torch

        rs = np.random.RandomState(5)
        x = rs.randn(1, 2, 3, 4).astype(np.float32)
        want = torch.nn.functional.interpolate(
            torch.tensor(x), size=(5, 7), mode="bilinear",
            align_corners=True).numpy()
        self._go("Resize",
                 [attr_str("mode", "linear"),
                  attr_str("coordinate_transformation_mode",
                           "align_corners")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.zeros(0, np.float32)),
                  tensor("sizes", np.asarray([1, 2, 5, 7], np.int64))],
                 want, extra_inputs=["roi", "sc", "sizes"],
                 rtol=1e-5, atol=1e-5)

    def test_resize_linear_downscale_no_antialias(self):
        """ONNX Resize antialias defaults to 0: a bilinear DOWNSCALE
        must not low-pass filter (jax.image's antialias default would
        diverge by O(1) here)."""
        import torch

        rs = np.random.RandomState(6)
        x = rs.randn(1, 2, 8, 8).astype(np.float32)
        want = torch.nn.functional.interpolate(
            torch.tensor(x), size=(4, 4), mode="bilinear",
            align_corners=False).numpy()
        self._go("Resize",
                 [attr_str("mode", "linear"),
                  attr_str("coordinate_transformation_mode",
                           "half_pixel")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.zeros(0, np.float32)),
                  tensor("sizes", np.asarray([1, 2, 4, 4], np.int64))],
                 want, extra_inputs=["roi", "sc", "sizes"],
                 rtol=1e-4, atol=1e-5)

    def test_resize_nearest_cross_pair_not_repeat(self):
        """half_pixel+floor at integer scale is NOT repeat-upsampling:
        in=2 scale=2 picks source rows [0,0,0,1]."""
        x = np.asarray([[[[1.0], [2.0]]]], np.float32).reshape(1, 1, 2, 1)
        want = x[:, :, [0, 0, 0, 1], :]
        self._go("Resize",
                 [attr_str("mode", "nearest"),
                  attr_str("coordinate_transformation_mode",
                           "half_pixel"),
                  attr_str("nearest_mode", "floor")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.zeros(0, np.float32)),
                  tensor("sizes", np.asarray([1, 1, 4, 1], np.int64))],
                 want, extra_inputs=["roi", "sc", "sizes"])

    def test_resize_fractional_scale_uses_scale_not_ratio(self):
        """scales=[...,2.6,...]: out=floor(3*2.6)=7, and the coordinate
        transform must divide by the PROVIDED 2.6, not by out/in=7/3
        (they pick different source pixels — torch agrees with the
        spec)."""
        import torch

        rs = np.random.RandomState(7)
        x = rs.randn(1, 1, 3, 3).astype(np.float32)
        want = torch.nn.functional.interpolate(
            torch.tensor(x), scale_factor=2.6, mode="nearest").numpy()
        self._go("Resize",
                 [attr_str("mode", "nearest"),
                  attr_str("coordinate_transformation_mode", "asymmetric"),
                  attr_str("nearest_mode", "floor")],
                 {"x": x},
                 [tensor("roi", np.zeros(0, np.float32)),
                  tensor("sc", np.asarray([1, 1, 2.6, 2.6], np.float32))],
                 want, extra_inputs=["roi", "sc"])

    def test_upsample_opset9_linear_asymmetric(self):
        """Opset-9 Upsample has no coordinate mode attr; its fixed
        semantics are ASYMMETRIC (x_src = i/scale), not half_pixel:
        2x of [0,1] must give [0, 0.5, 1, 1]."""
        x = np.asarray([[[[0.0, 1.0], [2.0, 3.0]]]], np.float32)
        # separable asymmetric lerp, hand-computed
        rows = np.stack([x[0, 0, 0], (x[0, 0, 0] + x[0, 0, 1]) / 2,
                         x[0, 0, 1], x[0, 0, 1]])
        want_hw = np.stack([rows[:, 0], (rows[:, 0] + rows[:, 1]) / 2,
                            rows[:, 1], rows[:, 1]], axis=1)
        want = want_hw[None, None]
        self._go("Upsample",
                 [attr_str("mode", "linear")],
                 {"x": x},
                 [tensor("sc", np.asarray([1, 1, 2, 2], np.float32))],
                 want, extra_inputs=["sc"])
        np.testing.assert_allclose(want[0, 0, :, 0], [0, 1, 2, 2])
        np.testing.assert_allclose(want[0, 0, 0], [0, 0.5, 1, 1])

    def test_instance_norm_matches_torch(self):
        import torch

        rs = np.random.RandomState(2)
        x = rs.randn(2, 3, 4, 5).astype(np.float32)
        g_ = rs.randn(3).astype(np.float32)
        b_ = rs.randn(3).astype(np.float32)
        want = torch.nn.functional.instance_norm(
            torch.tensor(x), weight=torch.tensor(g_),
            bias=torch.tensor(b_)).numpy()
        self._go("InstanceNormalization", [attr_float("epsilon", 1e-5)],
                 {"x": x}, [tensor("g", g_), tensor("b", b_)], want,
                 extra_inputs=["g", "b"], rtol=1e-4, atol=1e-5)

    def test_topk_largest_and_smallest(self):
        x = np.asarray([[3., 1., 4., 1., 5.], [2., 7., 1., 8., 2.]],
                       np.float32)
        k = np.asarray([2], np.int64)
        self._go("TopK", [], {"x": x}, [tensor("k", k)],
                 [np.sort(x, 1)[:, ::-1][:, :2],
                  np.argsort(-x, 1, kind="stable")[:, :2]],
                 extra_inputs=["k"], n_out=2)
        self._go("TopK", [attr_int("largest", 0)], {"x": x},
                 [tensor("k", k)],
                 [np.sort(x, 1)[:, :2],
                  np.argsort(x, 1, kind="stable")[:, :2]],
                 extra_inputs=["k"], n_out=2)

    def test_cumsum_modes(self):
        x = np.asarray([[1., 2., 3.], [4., 5., 6.]], np.float32)
        ax = np.asarray(1, np.int64)
        self._go("CumSum", [], {"x": x}, [tensor("axis", ax)],
                 np.cumsum(x, 1), extra_inputs=["axis"])
        self._go("CumSum", [attr_int("reverse", 1)], {"x": x},
                 [tensor("axis", ax)],
                 np.cumsum(x[:, ::-1], 1)[:, ::-1],
                 extra_inputs=["axis"])
        self._go("CumSum", [attr_int("exclusive", 1)], {"x": x},
                 [tensor("axis", ax)],
                 np.concatenate([np.zeros((2, 1), np.float32),
                                 np.cumsum(x, 1)[:, :-1]], 1),
                 extra_inputs=["axis"])

    def test_range_onehot_trilu(self):
        self._go("Range", [], {},
                 [tensor("s", np.asarray(1.0, np.float32)),
                  tensor("e", np.asarray(4.0, np.float32)),
                  tensor("d", np.asarray(0.5, np.float32))],
                 np.arange(1.0, 4.0, 0.5, dtype=np.float32),
                 extra_inputs=["s", "e", "d"])
        ids = np.asarray([0, 2, 1], np.int32)
        want = np.full((3, 4), 2.0, np.float32)
        for i, j in enumerate(ids):
            want[i, j] = 5.0
        self._go("OneHot", [attr_int("axis", -1)],
                 {"ids": ids},
                 [tensor("dep", np.asarray(4, np.int64)),
                  tensor("vals", np.asarray([2.0, 5.0], np.float32))],
                 want, extra_inputs=["dep", "vals"])
        x = np.arange(16, dtype=np.float32).reshape(4, 4)
        self._go("Trilu", [attr_int("upper", 1)], {"x": x},
                 [tensor("k", np.asarray(1, np.int64))],
                 np.triu(x, 1), extra_inputs=["k"])
        self._go("Trilu", [attr_int("upper", 0)], {"x": x}, [],
                 np.tril(x))

    def test_gather_scatter_family(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        nd_idx = np.asarray([[0, 1], [2, 3]], np.int64)
        self._go("GatherND", [], {"x": x}, [tensor("i", nd_idx)],
                 np.asarray([x[0, 1], x[2, 3]], np.float32),
                 extra_inputs=["i"])
        ge_idx = np.asarray([[1, 0], [2, 1], [0, 3]], np.int64)
        self._go("GatherElements", [attr_int("axis", 1)], {"x": x},
                 [tensor("i", ge_idx)],
                 np.take_along_axis(x, ge_idx, 1),
                 extra_inputs=["i"])
        upd = np.asarray([9.0, 8.0], np.float32)
        want = x.copy()
        want[0, 1], want[2, 3] = 9.0, 8.0
        self._go("ScatterND", [], {"x": x},
                 [tensor("i", nd_idx), tensor("u", upd)], want,
                 extra_inputs=["i", "u"])

    def test_reduce_composites(self):
        x = np.asarray([[1., -2., 3.], [-4., 5., -6.]], np.float32)
        self._go("ReduceL1", [attr_ints("axes", [1])], {"x": x},
                 [], np.abs(x).sum(1, keepdims=True))
        self._go("ReduceL2", [attr_ints("axes", [1])], {"x": x},
                 [], np.sqrt((x * x).sum(1, keepdims=True)))
        self._go("ReduceSumSquare", [attr_ints("axes", [1])], {"x": x},
                 [], (x * x).sum(1, keepdims=True))
        self._go("ReduceLogSumExp", [attr_ints("axes", [1])], {"x": x},
                 [], np.log(np.exp(x).sum(1, keepdims=True)),
                 rtol=1e-4)
        xp = np.abs(x) + 1.0
        self._go("ReduceLogSum", [attr_ints("axes", [1])], {"xp": xp},
                 [], np.log(xp.sum(1, keepdims=True)), rtol=1e-4)

    def test_depth_space_einsum_reverse_mean_logic(self):
        # DCR DepthToSpace per the ONNX spec formula
        rs = np.random.RandomState(3)
        x = rs.randn(1, 8, 2, 3).astype(np.float32)
        b = 2
        n, c, h, w = x.shape
        want = (x.reshape(n, b, b, c // (b * b), h, w)
                .transpose(0, 3, 4, 1, 5, 2)
                .reshape(n, c // (b * b), h * b, w * b))
        self._go("DepthToSpace", [attr_int("blocksize", 2)], {"x": x},
                 [], want)
        self._go("SpaceToDepth", [attr_int("blocksize", 2)],
                 {"y": want}, [], x)
        a_ = rs.randn(2, 3).astype(np.float32)
        b_ = rs.randn(3, 4).astype(np.float32)
        g = graph(
            nodes=[node("Einsum", ["a", "b"], ["o"], "es",
                        attrs=[attr_str("equation", "ij,jk->ik")])],
            initializers=[tensor("b", b_)],
            inputs=[value_info("a", [2, 3])],
            outputs=[value_info("o", [2, 4])])
        sd = OnnxImport.importGraph(model(g))
        np.testing.assert_allclose(
            np.asarray(sd.output({"a": a_}, ["o"])["o"]), a_ @ b_,
            rtol=1e-5, atol=1e-6)
        seq = np.arange(12, dtype=np.float32).reshape(3, 4)  # [T, N]
        lens = np.asarray([3, 1, 2, 3], np.int64)
        want_rev = seq.copy()
        for j, L in enumerate(lens):
            want_rev[:L, j] = seq[:L, j][::-1]
        self._go("ReverseSequence",
                 [attr_int("time_axis", 0), attr_int("batch_axis", 1)],
                 {"seq": seq}, [tensor("lens", lens)], want_rev,
                 extra_inputs=["lens"])
        xs = [rs.randn(2, 2).astype(np.float32) for _ in range(3)]
        g = graph(
            nodes=[node("Mean", ["m0", "m1", "m2"], ["o"], "mn")],
            initializers=[tensor("m1", xs[1]), tensor("m2", xs[2])],
            inputs=[value_info("m0", [2, 2])],
            outputs=[value_info("o", [2, 2])])
        sd = OnnxImport.importGraph(model(g))
        np.testing.assert_allclose(
            np.asarray(sd.output({"m0": xs[0]}, ["o"])["o"]),
            np.mean(xs, 0), rtol=1e-5, atol=1e-6)

    def test_hardswish_mish_argmin(self):
        import torch

        x = np.linspace(-4, 4, 9).astype(np.float32)
        self._go("HardSwish", [], {"x": x}, [],
                 torch.nn.functional.hardswish(torch.tensor(x)).numpy(),
                 rtol=1e-4, atol=1e-5)
        self._go("Mish", [], {"x": x}, [],
                 torch.nn.functional.mish(torch.tensor(x)).numpy(),
                 rtol=1e-4, atol=1e-5)
        m = np.asarray([[3., 1., 2.], [0., 5., 4.]], np.float32)
        self._go("ArgMin", [attr_int("axis", 1)], {"m": m}, [],
                 np.argmin(m, 1, keepdims=True))


class TestOnnxBreadthRound4Pt2(_SingleNodeGo):
    """Second mapper tail: activations/norms/pools/quantization/
    GridSample (reference: samediff-import-onnx covers these op
    classes)."""

    def test_celu_shrink_hardmax(self):
        import torch

        x = np.linspace(-2, 2, 12, dtype=np.float32).reshape(3, 4)
        self._go("Celu", [attr_float("alpha", 0.7)], {"x": x}, [],
                 torch.celu(torch.tensor(x), 0.7).numpy())
        lam, bias = 0.5, 0.1
        want = np.where(x < -lam, x + bias,
                        np.where(x > lam, x - bias, 0.0)).astype(np.float32)
        self._go("Shrink", [attr_float("lambd", lam),
                            attr_float("bias", bias)], {"x": x}, [], want)
        hm = np.zeros_like(x)
        hm[np.arange(3), x.argmax(1)] = 1.0
        self._go("Hardmax", [attr_int("axis", 1)], {"x": x}, [], hm)

    def test_lp_normalization(self):
        rs = np.random.RandomState(8)
        x = rs.randn(4, 6).astype(np.float32)
        self._go("LpNormalization", [attr_int("axis", 1), attr_int("p", 2)],
                 {"x": x}, [],
                 x / np.linalg.norm(x, axis=1, keepdims=True))
        self._go("LpNormalization", [attr_int("axis", 1), attr_int("p", 1)],
                 {"x": x}, [],
                 x / np.abs(x).sum(1, keepdims=True))

    def test_mvn_and_eyelike_and_det(self):
        rs = np.random.RandomState(9)
        x = rs.randn(2, 3, 4, 5).astype(np.float32)
        m = x.mean(axis=(0, 2, 3), keepdims=True)
        v = x.var(axis=(0, 2, 3), keepdims=True)
        self._go("MeanVarianceNormalization", [], {"x": x}, [],
                 (x - m) / np.sqrt(v + 1e-9), rtol=1e-4, atol=1e-5)
        e = rs.randn(3, 5).astype(np.float32)
        self._go("EyeLike", [attr_int("k", 1)], {"x": e}, [],
                 np.eye(3, 5, 1, dtype=np.float32))
        d = rs.randn(4, 3, 3).astype(np.float32)
        self._go("Det", [], {"x": d}, [], np.linalg.det(d),
                 rtol=1e-3, atol=1e-4)

    def test_bit_shift(self):
        x = np.asarray([[1, 2, 4, 255]], np.int32)
        s = np.asarray([[1, 2, 1, 3]], np.int32)
        self._go("BitShift", [attr_str("direction", "LEFT")],
                 {"x": x, "s": s}, [], x << s)
        self._go("BitShift", [attr_str("direction", "RIGHT")],
                 {"x": x, "s": s}, [], x >> s)

    def test_lp_pool_matches_torch(self):
        import torch

        rs = np.random.RandomState(10)
        x = rs.randn(2, 3, 6, 8).astype(np.float32)
        want = torch.nn.functional.lp_pool2d(
            torch.tensor(x), 2, (2, 2), stride=(2, 2)).numpy()
        self._go("LpPool", [attr_ints("kernel_shape", [2, 2]),
                            attr_ints("strides", [2, 2]),
                            attr_int("p", 2)],
                 {"x": x}, [], want, rtol=1e-4, atol=1e-5)
        glob = (np.abs(x) ** 3).sum(axis=(2, 3), keepdims=True) ** (1 / 3)
        self._go("GlobalLpPool", [attr_int("p", 3)], {"x": x}, [], glob,
                 rtol=1e-4, atol=1e-4)

    def test_grid_sample_matches_torch(self):
        import torch

        rs = np.random.RandomState(12)
        x = rs.randn(2, 3, 5, 6).astype(np.float32)
        grid = rs.uniform(-1.2, 1.2, (2, 4, 7, 2)).astype(np.float32)
        for mode in ("bilinear", "nearest"):
            for ac in (0, 1):
                want = torch.nn.functional.grid_sample(
                    torch.tensor(x), torch.tensor(grid), mode=mode,
                    padding_mode="zeros",
                    align_corners=bool(ac)).numpy()
                self._go("GridSample",
                         [attr_str("mode", mode),
                          attr_str("padding_mode", "zeros"),
                          attr_int("align_corners", ac)],
                         {"x": x, "g": grid}, [], want,
                         rtol=1e-4, atol=1e-5)

    def test_quantize_dequantize_round_trip(self):
        x = np.asarray([[0.0, 0.4, 1.0, -1.0, 3.2]], np.float32)
        scale = np.asarray(0.05, np.float32)
        zp = np.asarray(10, np.uint8)
        q = np.clip(np.round(x / 0.05) + 10, 0, 255).astype(np.uint8)
        self._go("QuantizeLinear", [], {"x": x},
                 [tensor("sc", scale), tensor("zp", zp)], q,
                 extra_inputs=["sc", "zp"])
        self._go("DequantizeLinear", [], {"q": q},
                 [tensor("sc", scale), tensor("zp", zp)],
                 (q.astype(np.float32) - 10) * 0.05,
                 extra_inputs=["sc", "zp"])

    def test_per_axis_dequantize_without_zero_point(self):
        q = np.arange(24, dtype=np.uint8).reshape(1, 3, 2, 4)
        scale = np.asarray([0.1, 0.2, 0.3], np.float32)
        want = q.astype(np.float32) * scale.reshape(1, 3, 1, 1)
        self._go("DequantizeLinear", [attr_int("axis", 1)], {"q": q},
                 [tensor("sc", scale)], want, extra_inputs=["sc"],
                 rtol=1e-6, atol=1e-6)

    def test_lp_pool_ceil_mode_rejected(self):
        x = np.zeros((1, 1, 7, 7), np.float32)
        g = graph([node("LpPool", ["x"], ["y"], "lp",
                        attrs=[attr_ints("kernel_shape", [2, 2]),
                               attr_ints("strides", [2, 2]),
                               attr_int("ceil_mode", 1)])], [],
                  [value_info("x", [1, 1, 7, 7])],
                  [value_info("y", [])])
        with pytest.raises(OnnxImportError, match="ceil_mode"):
            OnnxImport.importGraph(model(g))

    def test_eyelike_int_dtype(self):
        e = np.zeros((3, 3), np.float32)
        self._go("EyeLike", [attr_int("dtype", 7)], {"x": e}, [],
                 np.eye(3, dtype=np.int64))


class TestOpsetSensitiveDefaults(_SingleNodeGo):
    """Attribute defaults that changed across opsets must follow the
    MODEL's declared opset (reference: per-opset mapping rules in
    samediff-import-onnx)."""

    def test_hardmax_old_opset_coerces_to_2d(self):
        # opset 11, no axis attr -> default axis=1 with flatten-to-2D
        # semantics: argmax over the FLATTENED trailing dims, one hot
        # per leading row — NOT a per-last-axis hardmax.
        rs = np.random.RandomState(3)
        x = rs.randn(2, 3, 4).astype(np.float32)
        g = graph([node("Hardmax", ["x"], ["y"], "hm")], [],
                  [value_info("x", [2, 3, 4])], [value_info("y", [])])
        sd = OnnxImport.importGraph(model(g, opset=11))
        got = np.asarray(sd.output({"x": x}, ["y"])["y"])
        flat = x.reshape(2, 12)
        want = np.zeros_like(flat)
        want[np.arange(2), flat.argmax(1)] = 1.0
        np.testing.assert_allclose(got, want.reshape(2, 3, 4))

    def test_hardmax_new_opset_default_last_axis(self):
        rs = np.random.RandomState(4)
        x = rs.randn(2, 3, 4).astype(np.float32)
        want = np.zeros_like(x)
        idx = x.argmax(-1)
        for i in range(2):
            for j in range(3):
                want[i, j, idx[i, j]] = 1.0
        self._go("Hardmax", [], {"x": x}, [], want)

    def test_eyelike_unknown_dtype_enum_raises(self):
        g = graph([node("EyeLike", ["x"], ["y"], "ey",
                        attrs=[attr_int("dtype", 16)])], [],  # bf16 enum
                  [value_info("x", [3, 3])], [value_info("y", [])])
        with pytest.raises(OnnxImportError, match="dtype enum"):
            OnnxImport.importGraph(model(g))

    def test_eyelike_float16_supported(self):
        e = np.zeros((2, 4), np.float32)
        self._go("EyeLike", [attr_int("dtype", 10)], {"x": e}, [],
                 np.eye(2, 4, dtype=np.float16))

    def test_softmax_old_opset_coerce_and_custom_domain_ignored(self):
        import torch

        rs = np.random.RandomState(5)
        x = rs.randn(2, 3, 4).astype(np.float32)
        # opset 11, no axis attr -> flatten-to-2D softmax at axis=1
        g = graph([node("Softmax", ["x"], ["y"], "sm")], [],
                  [value_info("x", [2, 3, 4])], [value_info("y", [])])
        sd = OnnxImport.importGraph(model(g, opset=11))
        got = np.asarray(sd.output({"x": x}, ["y"])["y"])
        want = torch.nn.functional.softmax(
            torch.tensor(x.reshape(2, 12)), -1).numpy().reshape(2, 3, 4)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # a custom-domain opset entry with a HIGHER version must not
        # bump the core opset (domain field versions other op sets)
        m = model(g, opset=11) + _ld(8, _str(1, "com.microsoft")
                                     + _iv(2, 19))
        sd2 = OnnxImport.importGraph(m)
        got2 = np.asarray(sd2.output({"x": x}, ["y"])["y"])
        np.testing.assert_allclose(got2, want, rtol=1e-5, atol=1e-6)

    def test_logsoftmax_old_opset_explicit_last_axis_no_shape_needed(self):
        import torch

        rs = np.random.RandomState(6)
        x = rs.randn(3, 5).astype(np.float32)
        g = graph([node("LogSoftmax", ["x"], ["y"], "ls",
                        attrs=[attr_int("axis", -1)])], [],
                  [value_info("x", [3, 5])], [value_info("y", [])])
        sd = OnnxImport.importGraph(model(g, opset=9))
        got = np.asarray(sd.output({"x": x}, ["y"])["y"])
        np.testing.assert_allclose(
            got, torch.nn.functional.log_softmax(torch.tensor(x),
                                                 -1).numpy(),
            rtol=1e-5, atol=1e-6)
