"""Ring/Ulysses context parallelism vs dense attention (exact-math
check on the virtual 8-device CPU mesh — the reference has no sequence
parallelism at all, SURVEY.md §5, so the reference here is our own
single-device dense attention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from deeplearning4j_tpu.parallel.mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.ring_attention import (
    dense_attention, ring_attention, ulysses_attention,
)

B, H, T, D = 2, 8, 32, 16


def _mesh(sp=4, data=2):
    devs = np.array(jax.devices()[:sp * data]).reshape(data, sp)
    return Mesh(devs, ("data", "sp"))


def _qkv(key=0):
    ks = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, T, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, T, D), jnp.float32)
    return q, k, v


def _run_sharded(fn, mesh, q, k, v, kv_mask=None):
    spec = P(None, None, "sp", None)
    mspec = P(None, "sp")
    if kv_mask is None:
        f = shard_map(lambda a, b, c: fn(a, b, c), mesh=mesh,
                      in_specs=(spec, spec, spec), out_specs=spec,
                      check_rep=False)
        return jax.jit(f)(q, k, v)
    f = shard_map(lambda a, b, c, m: fn(a, b, c, kv_mask=m), mesh=mesh,
                  in_specs=(spec, spec, spec, mspec), out_specs=spec,
                  check_rep=False)
    return jax.jit(f)(q, k, v, kv_mask)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_matches_dense(impl):
    mesh = _mesh()
    q, k, v = _qkv()
    want = dense_attention(q, k, v)
    got = _run_sharded(impl, mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_causal_matches_dense(impl):
    mesh = _mesh()
    q, k, v = _qkv(1)

    def f(a, b, c):
        return impl(a, b, c, causal=True)

    want = dense_attention(q, k, v, causal=True)
    got = _run_sharded(f, mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_key_padding_mask(impl):
    mesh = _mesh()
    q, k, v = _qkv(2)
    mask = jnp.concatenate(
        [jnp.ones((B, T - 7)), jnp.zeros((B, 7))], axis=1)
    want = dense_attention(q, k, v, kv_mask=mask)
    got = _run_sharded(impl, mesh, q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_grads_match_dense():
    mesh = _mesh()
    q, k, v = _qkv(3)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    spec = P(None, None, "sp", None)

    def loss_ring(q, k, v):
        f = shard_map(
            lambda a, b, c: ring_attention(a, b, c), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec, check_rep=False)
        return jnp.sum(f(q, k, v) ** 2)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=3e-4, rtol=3e-4)


def test_ring_train_step_matches_unsharded():
    """Full context-parallel MLM step == unsharded step (dropout off)."""
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.models.transformer import (
        TransformerEncoder, tiny_config,
    )

    cfg = tiny_config(vocab=64, max_len=32, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    model = TransformerEncoder(cfg)
    updater = Adam(learning_rate=1e-3)
    mesh = _mesh(sp=4, data=2)

    params = model.init_params()
    rng = jax.random.key(7)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    labels = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    mask_pos = jnp.ones((4, 32), jnp.float32)

    ref_step = model.make_train_step(updater)
    p1, _, loss1 = ref_step(jax.tree_util.tree_map(jnp.copy, params),
                            updater.init_state(params), jnp.asarray(0),
                            ids, labels, mask_pos, rng)

    ring_step = model.make_ring_train_step(updater, mesh)
    with mesh:
        p2, _, loss2 = ring_step(
            jax.tree_util.tree_map(jnp.copy, params),
            updater.init_state(params), jnp.asarray(0),
            ids, labels, mask_pos, rng)

    np.testing.assert_allclose(float(loss2), float(loss1),
                               atol=1e-5, rtol=1e-5)
    fl1 = jax.tree_util.tree_leaves(p1)
    fl2 = jax.tree_util.tree_leaves(p2)
    for a, b in zip(fl1, fl2):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4)


def test_ring_train_step_pad_mask_matches_unsharded():
    """Padded batch through the ring path == unsharded dense with the
    same key-padding mask (dropout off)."""
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.models.transformer import (
        TransformerEncoder, tiny_config,
    )

    cfg = tiny_config(vocab=64, max_len=32, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    model = TransformerEncoder(cfg)
    mesh = _mesh(sp=4, data=2)
    params = model.init_params()
    rng = jax.random.key(11)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    pad = jnp.concatenate(
        [jnp.ones((4, 25)), jnp.zeros((4, 7))], axis=1)
    mask_pos = pad  # loss only on real tokens

    # unsharded reference loss with the same padding mask
    def ref_loss(p):
        hidden = model.encode(p, ids, mask=pad, train=False)
        logits = model.mlm_logits(p, hidden).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_lp = jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]
        return -jnp.sum(tok_lp * mask_pos) / jnp.sum(mask_pos)

    want = float(ref_loss(params))
    upd = Sgd(learning_rate=0.0)
    step = model.make_ring_train_step(upd, mesh)
    with mesh:
        _, _, loss = step(params, upd.init_state(params), jnp.asarray(0),
                          ids, ids, mask_pos, rng, pad_mask=pad)
    np.testing.assert_allclose(float(loss), want, atol=1e-5, rtol=1e-5)


def test_ring_seq_overflow_raises():
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.models.transformer import (
        TransformerEncoder, tiny_config,
    )

    cfg = tiny_config(vocab=64, max_len=16, d_model=32, n_layers=1,
                      n_heads=4, d_ff=64)
    model = TransformerEncoder(cfg)
    mesh = _mesh(sp=4, data=2)
    upd = Sgd(learning_rate=1e-2)
    params = model.init_params()
    ids = jnp.zeros((4, 32), jnp.int32)  # global 32 > max_len 16
    step = model.make_ring_train_step(upd, mesh)
    with pytest.raises(ValueError, match="exceeds"):
        with mesh:
            step(params, upd.init_state(params), jnp.asarray(0), ids, ids,
                 jnp.ones((4, 32), jnp.float32), jax.random.key(0))


def test_ulysses_train_step_runs():
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.models.transformer import (
        TransformerEncoder, tiny_config,
    )

    cfg = tiny_config(vocab=64, max_len=32, d_model=32, n_layers=1,
                      n_heads=4, d_ff=64)
    model = TransformerEncoder(cfg)
    updater = Sgd(learning_rate=1e-2)
    mesh = _mesh(sp=4, data=2)
    params = model.init_params()
    rng = jax.random.key(0)
    ids = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    step = model.make_ring_train_step(updater, mesh, attn="ulysses")
    with mesh:
        p, _, loss = step(params, updater.init_state(params),
                          jnp.asarray(0), ids, ids,
                          jnp.ones((4, 32), jnp.float32), rng)
    assert np.isfinite(float(loss))
