"""Class-imbalance masking under-sampler parity (reference:
UnderSamplingByMaskingPreProcessorTest in nd4j)."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.classimbalance import (
    UnderSamplingByMaskingMultiDataSetPreProcessor,
    UnderSamplingByMaskingPreProcessor)


def _imbalanced(b=64, t=120, p_minority=0.05, seed=0, onehot=False):
    rng = np.random.default_rng(seed)
    cls = (rng.random((b, t)) < p_minority).astype(np.float32)
    if onehot:
        labels = np.stack([1 - cls, cls], -1)
    else:
        labels = cls[..., None]
    feats = rng.normal(size=(b, t, 3)).astype(np.float32)
    return DataSet(feats, labels), cls


class TestUnderSampling:
    def test_unmasked_distribution_hits_target(self):
        ds, cls = _imbalanced()
        pre = UnderSamplingByMaskingPreProcessor(0.4, window_length=30,
                                                 seed=1)
        pre.preProcess(ds)
        mask = np.asarray(ds.labels_mask)
        assert mask.shape == cls.shape
        kept_minority = (cls * mask).sum()
        kept_total = mask.sum()
        frac = kept_minority / kept_total
        assert abs(frac - 0.4) < 0.08, frac

    def test_minority_never_masked(self):
        ds, cls = _imbalanced(seed=2)
        UnderSamplingByMaskingPreProcessor(0.3, 20, seed=3).preProcess(ds)
        mask = np.asarray(ds.labels_mask)
        assert (mask[cls > 0.5] == 1.0).all()

    def test_onehot_labels_equivalent(self):
        ds1, _ = _imbalanced(seed=4)
        ds2, _ = _imbalanced(seed=4, onehot=True)
        m1 = UnderSamplingByMaskingPreProcessor(0.35, 25, seed=5) \
            .adjusted_mask(np.asarray(ds1.labels))
        m2 = UnderSamplingByMaskingPreProcessor(0.35, 25, seed=5) \
            .adjusted_mask(np.asarray(ds2.labels))
        np.testing.assert_array_equal(m1, m2)

    def test_all_majority_window_masked_by_default(self):
        labels = np.zeros((2, 20, 1), np.float32)   # no minority at all
        pre = UnderSamplingByMaskingPreProcessor(0.5, 10, seed=0)
        mask = pre.adjusted_mask(labels)
        assert (mask == 0.0).all()
        keep = UnderSamplingByMaskingPreProcessor(
            0.5, 10, seed=0, mask_all_majority_windows=False)
        assert (keep.adjusted_mask(labels) == 1.0).all()

    def test_existing_mask_respected(self):
        ds, cls = _imbalanced(seed=6)
        pre_mask = np.ones(cls.shape, np.float32)
        pre_mask[:, -30:] = 0.0                      # padded tail
        ds.labels_mask = pre_mask
        UnderSamplingByMaskingPreProcessor(0.4, 30, seed=7).preProcess(ds)
        mask = np.asarray(ds.labels_mask)
        assert (mask[:, -30:] == 0.0).all()          # stays masked

    def test_validation(self):
        with pytest.raises(ValueError, match="target_minority_dist"):
            UnderSamplingByMaskingPreProcessor(0.9, 10)
        with pytest.raises(ValueError, match="window_length"):
            UnderSamplingByMaskingPreProcessor(0.3, 0)
        pre = UnderSamplingByMaskingPreProcessor(0.3, 10)
        with pytest.raises(ValueError, match="binary time"):
            pre.adjusted_mask(np.zeros((2, 5, 3), np.float32))


class TestMultiVariant:
    def test_selected_label_arrays(self):
        ds, cls = _imbalanced(seed=8)
        other = np.zeros((64, 120, 1), np.float32)
        mds = MultiDataSet(features=[np.asarray(ds.features)],
                           labels=[np.asarray(ds.labels), other])
        pre = UnderSamplingByMaskingMultiDataSetPreProcessor(
            0.4, 30, label_indices=[0], seed=9)
        pre.preProcess(mds)
        assert mds.labels_mask_arrays[0] is not None
        assert mds.labels_mask_arrays[1] is None     # untouched
        frac = (cls * mds.labels_mask_arrays[0]).sum() \
            / mds.labels_mask_arrays[0].sum()
        assert abs(frac - 0.4) < 0.08
        # mixed None/array mask lists survive batch splitting
        parts = mds.splitBatches(16)
        assert len(parts) == 4
        assert parts[0].labels_mask_arrays[0].shape == (16, 120)
        assert parts[0].labels_mask_arrays[1] is None
