"""SameDiff-equivalent engine tests (reference test strategy: SURVEY.md
§4 — OpValidation numerical gradient checks + SameDiff training/serde
round-trip tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig, VariableType
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import Adam, Sgd


class TestGraphBuild:
    def test_eval_simple(self):
        sd = SameDiff.create()
        a = sd.constant("a", jnp.asarray([1.0, 2.0, 3.0]))
        b = sd.constant("b", jnp.asarray([10.0, 20.0, 30.0]))
        c = (a + b).rename("c")
        np.testing.assert_allclose(np.asarray(c.eval()), [11.0, 22.0, 33.0])

    def test_placeholder_feed(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 3))
        w = sd.var("w", jnp.ones((3, 2)))
        y = x.mmul(w).rename("y")
        out = sd.output({"x": np.ones((4, 3), np.float32)}, ["y"])["y"]
        np.testing.assert_allclose(np.asarray(out), 3.0 * np.ones((4, 2)))

    def test_namespace_op_emission(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 4))
        h = sd.math.sigmoid(x)
        s = sd.math.reduce_sum(h, dimensions=[1])
        out = sd.outputSingle({"x": np.zeros((2, 4), np.float32)}, s)
        np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])

    def test_multi_output_op(self):
        sd = SameDiff.create()
        x = sd.constant("x", jnp.arange(6.0).reshape(2, 3))
        a, b = sd.invoke_op("split", [x], n_out=2, num_splits=2, axis=0)
        np.testing.assert_allclose(np.asarray(a.eval()), [[0, 1, 2]])
        np.testing.assert_allclose(np.asarray(b.eval()), [[3, 4, 5]])

    def test_pruning_skips_unneeded_ops(self):
        sd = SameDiff.create()
        x = sd.constant("x", jnp.ones((2, 2)))
        used = (x * 2.0).rename("used")
        _unused = sd.math.exp(x)
        needed = sd._prune(("used",))
        assert all(n.op_name != "exp" for n in needed)

    def test_variable_types(self):
        sd = SameDiff.create()
        p = sd.placeholder("p", shape=(1,))
        v = sd.var("v", jnp.zeros(3))
        c = sd.constant("c", 1.0)
        o = v + c
        assert p.vtype is VariableType.PLACEHOLDER
        assert v.vtype is VariableType.VARIABLE
        assert c.vtype is VariableType.CONSTANT
        assert o.vtype is VariableType.ARRAY
        assert sd.trainable_names() == ["v"]


class TestGradients:
    def test_grad_matches_analytic(self):
        # loss = sum((x*w)^2) -> dL/dw = 2*w*x^2 summed over batch
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None,))
        w = sd.var("w", jnp.asarray([3.0]))
        y = sd.math.reduce_sum((x * w) * (x * w)).rename("loss")
        sd.setLossVariables("loss")
        xv = np.asarray([1.0, 2.0], np.float32)
        grads = sd.calculateGradients({"x": xv}, ["w"])
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   [2.0 * 3.0 * (1.0 + 4.0)], rtol=1e-6)

    def test_numerical_gradient_check(self):
        # the reference's OpValidation/GradCheckUtil backbone: finite
        # differences vs autodiff on a small composite graph
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(2, 3))
        w = sd.var("w", jnp.asarray(np.random.RandomState(0)
                                    .randn(3, 2).astype(np.float32)))
        h = sd.math.tanh(x.mmul(w))
        loss = sd.math.reduce_sum(h * h).rename("loss")
        sd.setLossVariables("loss")
        xv = np.random.RandomState(1).randn(2, 3).astype(np.float32)
        g = np.asarray(sd.calculateGradients({"x": xv}, ["w"])["w"])

        w0 = np.asarray(sd.getVariable("w").getArr())
        eps = 1e-3
        num = np.zeros_like(w0)
        for i in range(w0.shape[0]):
            for j in range(w0.shape[1]):
                for s, sign in ((eps, 1), (-eps, -1)):
                    wp = w0.copy()
                    wp[i, j] += s
                    sd.set_array("w", wp)
                    lv = float(sd.outputSingle({"x": xv}, "loss"))
                    num[i, j] += sign * lv
                num[i, j] /= 2 * eps
        sd.set_array("w", w0)
        np.testing.assert_allclose(g, num, rtol=1e-2, atol=1e-3)

    def test_gradient_stored_on_variable(self):
        sd = SameDiff.create()
        w = sd.var("w", jnp.asarray([2.0]))
        loss = (w * w).sum().rename("loss")
        sd.setLossVariables("loss")
        sd.calculateGradients({})
        np.testing.assert_allclose(np.asarray(sd.getVariable("w").gradient()),
                                   [4.0])


class TestTraining:
    def _linreg_sd(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", jnp.zeros((2, 1)))
        b = sd.var("b", jnp.zeros((1,)))
        pred = x.mmul(w) + b
        diff = pred - y
        loss = sd.math.reduce_mean(diff * diff).rename("loss")
        sd.setLossVariables("loss")
        return sd

    def test_fit_converges(self):
        sd = self._linreg_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=Adam(learning_rate=0.1),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
        rs = np.random.RandomState(0)
        xv = rs.randn(64, 2).astype(np.float32)
        yv = (xv @ np.asarray([[2.0], [-3.0]]) + 0.5).astype(np.float32)
        hist = sd.fit(DataSet(xv, yv), epochs=150)
        assert hist.finalTrainingLoss() < 1e-2
        w = np.asarray(sd.getVariable("w").getArr()).ravel()
        np.testing.assert_allclose(w, [2.0, -3.0], atol=0.1)

    def test_history_records_losses(self):
        sd = self._linreg_sd()
        sd.setTrainingConfig(TrainingConfig(
            updater=Sgd(0.01),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
        xv = np.ones((4, 2), np.float32)
        yv = np.ones((4, 1), np.float32)
        hist = sd.fit(DataSet(xv, yv), epochs=3)
        assert len(hist.loss_curve) == 3
        assert len(hist.epoch_losses) == 3


class TestSerde:
    def test_save_load_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        w = sd.var("w", jnp.asarray([[1.0], [2.0]]))
        out = x.mmul(w).rename("out")
        path = tmp_path / "model.sdz"
        sd.save(path)

        sd2 = SameDiff.load(path)
        xv = np.asarray([[3.0, 4.0]], np.float32)
        a = np.asarray(sd.outputSingle({"x": xv}, "out"))
        b = np.asarray(sd2.outputSingle({"x": xv}, "out"))
        np.testing.assert_allclose(a, b)

    def test_exact_resume_with_updater_state(self, tmp_path):
        def make():
            sd = SameDiff.create()
            x = sd.placeholder("x", shape=(None, 2))
            y = sd.placeholder("y", shape=(None, 1))
            w = sd.var("w", jnp.zeros((2, 1)))
            loss = ((x.mmul(w) - y) * (x.mmul(w) - y)).mean().rename("loss")
            sd.setLossVariables("loss")
            sd.setTrainingConfig(TrainingConfig(
                updater=Adam(learning_rate=0.05),
                data_set_feature_mapping=["x"],
                data_set_label_mapping=["y"]))
            return sd

        rs = np.random.RandomState(0)
        xv = rs.randn(16, 2).astype(np.float32)
        yv = rs.randn(16, 1).astype(np.float32)
        ds = DataSet(xv, yv)

        # continuous 10-epoch run
        sd_full = make()
        sd_full.fit(ds, epochs=10)
        w_full = np.asarray(sd_full.getVariable("w").getArr())

        # 5 epochs, save (incl. Adam m/v + iteration), load, 5 more
        sd_a = make()
        sd_a.fit(ds, epochs=5)
        path = tmp_path / "ckpt.sdz"
        sd_a.save(path)
        sd_b = SameDiff.load(path)
        assert sd_b._iteration == sd_a._iteration
        sd_b.fit(ds, epochs=5)
        w_resumed = np.asarray(sd_b.getVariable("w").getArr())
        np.testing.assert_allclose(w_resumed, w_full, rtol=1e-5, atol=1e-6)


class TestExport:
    def test_stablehlo_lowering(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(2, 2))
        w = sd.var("w", jnp.ones((2, 2)))
        y = sd.math.relu(x.mmul(w)).rename("y")
        txt = sd.to_stablehlo({"x": np.ones((2, 2), np.float32)}, ["y"])
        assert "stablehlo" in txt or "mhlo" in txt or "func.func" in txt
        assert "dot_general" in txt


class TestValidationAndEvaluate:
    """reference: SameDiff#fit validation history + #evaluate."""

    def _classifier_sd(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
        from deeplearning4j_tpu.learning.updaters import Adam
        rng = np.random.default_rng(0)
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 4))
        w = sd.var("w", rng.normal(0, 0.3, (4, 2)).astype(np.float32))
        b = sd.var("b", np.zeros(2, np.float32))
        logits = x @ w + b
        probs = sd.nn.softmax(logits)
        y = sd.placeholder("y", shape=(None, 2))
        # CE loss
        logp = sd.nn.log_softmax(logits)
        loss = -(y * logp).sum(-1).mean()
        sd.setLossVariables(loss.name)
        sd.setTrainingConfig(TrainingConfig(
            updater=Adam(0.05), data_set_feature_mapping=["x"],
            data_set_label_mapping=["y"]))
        return sd, probs

    def test_validation_losses_tracked(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        sd, _ = self._classifier_sd()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        lab = (x[:, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[lab]
        xv = rng.normal(size=(32, 4)).astype(np.float32)
        yv = np.eye(2, dtype=np.float32)[(xv[:, 0] > 0).astype(int)]
        hist = sd.fit(DataSet(x, y), epochs=15,
                      validation_data=DataSet(xv, yv))
        assert len(hist.validation_losses) == 15
        assert hist.validation_losses[-1] < hist.validation_losses[0]
        assert np.isfinite(hist.finalValidationLoss())

    def test_evaluate_api(self):
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import DataSet
        sd, probs = self._classifier_sd()
        rng = np.random.default_rng(2)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        lab = (x[:, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[lab]
        sd.fit(DataSet(x, y), epochs=30)
        ev = sd.evaluate(ArrayDataSetIterator(x, y, 16), probs.name)
        assert ev.accuracy() > 0.9
