"""ComputationGraph tests (reference analog: ComputationGraphTestRNN,
TestComputationGraphNetwork, and zoo model instantiation tests)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    GlobalPoolingLayer, InputType, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration, ElementWiseVertex,
    MergeVertex, ScaleVertex, SubsetVertex,
)
from deeplearning4j_tpu.zoo import ResNet50


def toy(n=256, d=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, classes)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[(x @ w).argmax(-1)]
    return x, y


class TestGraphBuild:
    def test_topo_and_types(self):
        conf = (ComputationGraphConfiguration.graphBuilder()
                .addInputs("in")
                .setInputTypes(InputType.feedForward(8))
                .addLayer("d1", DenseLayer(n_out=16, activation="relu"), "in")
                .addLayer("d2", DenseLayer(n_out=16, activation="relu"), "in")
                .addVertex("merge", MergeVertex(), "d1", "d2")
                .addLayer("out", OutputLayer(n_out=3, activation="softmax",
                                             loss="mcxent"), "merge")
                .setOutputs("out")
                .build())
        # merge output is 32 wide -> out layer n_in inferred
        assert conf.nodes[-1].vertex.layer.n_in == 32

    def test_cycle_detection(self):
        b = (ComputationGraphConfiguration.graphBuilder()
             .addInputs("in").setInputTypes(InputType.feedForward(4)))
        b.addLayer("a", DenseLayer(n_out=4), "b")
        b.addLayer("b", DenseLayer(n_out=4), "a")
        b.setOutputs("b")
        with pytest.raises(ValueError, match="cycle|unknown"):
            b.build()

    def test_json_roundtrip(self):
        conf = (ComputationGraphConfiguration.graphBuilder()
                .addInputs("in")
                .setInputTypes(InputType.feedForward(8))
                .addLayer("d1", DenseLayer(n_out=4, activation="tanh"), "in")
                .addVertex("s", ScaleVertex(scale=0.5), "d1")
                .addLayer("out", OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "s")
                .setOutputs("out").build())
        back = ComputationGraphConfiguration.from_json(conf.to_json())
        assert back == conf


class TestGraphTraining:
    def test_branch_merge_learns(self):
        x, y = toy()
        conf = (ComputationGraphConfiguration.graphBuilder()
                .seed(11).updater(Adam(learning_rate=0.01))
                .addInputs("in")
                .setInputTypes(InputType.feedForward(8))
                .addLayer("d1", DenseLayer(n_out=16, activation="relu"), "in")
                .addLayer("d2", DenseLayer(n_out=16, activation="tanh"), "in")
                .addVertex("merge", MergeVertex(), "d1", "d2")
                .addLayer("out", OutputLayer(n_out=3, activation="softmax",
                                             loss="mcxent"), "merge")
                .setOutputs("out").build())
        g = ComputationGraph(conf).init()
        g.fit(ArrayDataSetIterator(x, y, batch_size=64, shuffle=True), epochs=12)
        ev = g.evaluate(ArrayDataSetIterator(x, y, batch_size=128))
        assert ev.accuracy() > 0.9, ev.stats()

    def test_residual_block(self):
        x, y = toy(d=16)
        conf = (ComputationGraphConfiguration.graphBuilder()
                .seed(2).updater(Adam(learning_rate=0.01))
                .addInputs("in")
                .setInputTypes(InputType.feedForward(16))
                .addLayer("d1", DenseLayer(n_out=16, activation="relu"), "in")
                .addVertex("res", ElementWiseVertex(op="Add"), "d1", "in")
                .addLayer("out", OutputLayer(n_out=3, activation="softmax",
                                             loss="mcxent"), "res")
                .setOutputs("out").build())
        g = ComputationGraph(conf).init()
        s0 = g.score(DataSet(x, y))
        g.fit(DataSet(x, y), epochs=20)
        assert g.score(DataSet(x, y)) < s0

    def test_multi_output(self):
        x, y = toy(d=6, classes=2)
        y2 = 1.0 - y
        conf = (ComputationGraphConfiguration.graphBuilder()
                .seed(3).updater(Adam(learning_rate=0.01))
                .addInputs("in")
                .setInputTypes(InputType.feedForward(6))
                .addLayer("trunk", DenseLayer(n_out=8, activation="relu"), "in")
                .addLayer("outA", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "trunk")
                .addLayer("outB", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "trunk")
                .setOutputs("outA", "outB").build())
        g = ComputationGraph(conf).init()
        g.fit([x], [y, y2], epochs=5)
        outs = g.output(x)
        assert len(outs) == 2
        assert outs[0].shape() == (256, 2)

    def test_subset_vertex(self):
        conf = (ComputationGraphConfiguration.graphBuilder()
                .addInputs("in")
                .setInputTypes(InputType.feedForward(10))
                .addVertex("head", SubsetVertex(frm=0, to=3), "in")
                .addLayer("out", OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "head")
                .setOutputs("out").build())
        g = ComputationGraph(conf).init()
        assert conf.nodes[-1].vertex.layer.n_in == 4
        out = g.outputSingle(np.zeros((2, 10), np.float32))
        assert out.shape() == (2, 2)


class TestResNet50:
    def test_builds_with_correct_param_count(self):
        """ResNet-50 ImageNet has ~25.6M params — structural check."""
        model = ResNet50(num_classes=1000, in_shape=(224, 224, 3)).init()
        n = model.numParams()
        assert 25_000_000 < n < 26_500_000, n

    def test_tiny_resnet_forward_and_step(self):
        # small input/classes so CPU test is fast
        model = ResNet50(num_classes=4, in_shape=(32, 32, 3),
                         updater=Adam(learning_rate=1e-3)).init()
        x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(np.float32)
        out = model.outputSingle(x)
        assert out.shape() == (2, 4)
        np.testing.assert_allclose(out.sum(1).toNumpy(), 1.0, rtol=1e-4)
        y = np.eye(4, dtype=np.float32)[[0, 1]]
        model.fit([x], [y], epochs=1)
        assert np.isfinite(model.score())
