"""Transfer learning: freeze, fine-tune, head replacement, featurize.

Reference: org/deeplearning4j/nn/transferlearning/** + FrozenLayer.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, FrozenLayer, TransferLearning,
    TransferLearningHelper,
)


def _base_net(seed=0, n_classes=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 5).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, n)]
    return x, y


class TestFrozenLayers:
    def test_frozen_params_unchanged(self):
        net = _base_net()
        x, y = _data()
        net.fit(x, y)                   # pretrain a bit
        tl = (TransferLearning.Builder(net)
              .fineTuneConfiguration(FineTuneConfiguration(updater=Sgd(0.1)))
              .setFeatureExtractor(1)   # freeze layers 0 and 1
              .build())
        assert isinstance(tl.conf.layers[0], FrozenLayer)
        assert isinstance(tl.conf.layers[1], FrozenLayer)
        frozen_before = [np.asarray(tl.params_list[i]["W"]).copy()
                         for i in (0, 1)]
        head_before = np.asarray(tl.params_list[2]["W"]).copy()
        for _ in range(5):
            tl.fit(x, y)
        for i, before in zip((0, 1), frozen_before):
            np.testing.assert_array_equal(
                np.asarray(tl.params_list[i]["W"]), before)
        assert not np.allclose(np.asarray(tl.params_list[2]["W"]),
                               head_before)

    def test_frozen_output_matches_source_features(self):
        """Frozen layers carry over the trained weights."""
        net = _base_net()
        x, y = _data()
        net.fit(x, y, epochs=3)
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(0).build())
        np.testing.assert_array_equal(
            np.asarray(tl.params_list[0]["W"]),
            np.asarray(net.params_list[0]["W"]))


class TestSurgery:
    def test_replace_output_layer(self):
        net = _base_net(n_classes=3)
        x, _ = _data()
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1)
              .removeOutputLayer()
              .addLayer(OutputLayer(n_in=8, n_out=5, activation="softmax",
                                    loss="mcxent"))
              .build())
        out = tl.output(x).toNumpy()
        assert out.shape == (32, 5)
        y5 = np.eye(5, dtype=np.float32)[np.random.RandomState(1)
                                         .randint(0, 5, 32)]
        first = None
        for _ in range(10):
            tl.fit(x, y5)
            first = first or tl.score()
        assert tl.score() < first

    def test_nout_replace(self):
        net = _base_net()
        tl = (TransferLearning.Builder(net)
              .nOutReplace(1, 12, "xavier")
              .build())
        assert tl.params_list[1]["W"].shape == (16, 12)
        assert tl.params_list[2]["W"].shape == (12, 3)
        # layer 0 kept its weights
        np.testing.assert_array_equal(np.asarray(tl.params_list[0]["W"]),
                                      np.asarray(net.params_list[0]["W"]))
        x, y = _data()
        tl.fit(x, y)
        assert np.isfinite(tl.score())

    def test_remove_everything_rejected(self):
        net = _base_net()
        with pytest.raises(ValueError):
            TransferLearning.Builder(net).removeLayersFromOutput(3).build()

    def test_requires_init(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(OutputLayer(n_in=4, n_out=2)).build())
        with pytest.raises(ValueError, match="init"):
            TransferLearning.Builder(MultiLayerNetwork(conf))


class TestHelper:
    def test_featurize_and_fit(self):
        net = _base_net()
        x, y = _data(64)
        net.fit(x, y)
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1).build())
        helper = TransferLearningHelper(tl)
        feat = helper.featurize(DataSet(x, y))
        assert feat.features.shape == (64, 8)      # layer-1 width
        before = float(tl.score(DataSet(x, y)))
        for _ in range(15):
            helper.fitFeaturized(feat)
        after = float(tl.score(DataSet(x, y)))
        assert after < before

    def test_no_frozen_rejected(self):
        net = _base_net()
        with pytest.raises(ValueError, match="frozen"):
            TransferLearningHelper(net)

    def test_json_roundtrip_frozen(self):
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        net = _base_net()
        tl = TransferLearning.Builder(net).setFeatureExtractor(0).build()
        cfg2 = MultiLayerConfiguration.from_json(tl.conf.to_json())
        assert isinstance(cfg2.layers[0], FrozenLayer)
        assert isinstance(cfg2.layers[0].layer, DenseLayer)


class TestGraphTransferLearning:
    """reference: TransferLearning.GraphBuilder tests
    (TransferLearningCompGraphTest)."""

    def _trained_graph(self):
        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.nn.conf import (DenseLayer, InputType,
                                                OutputLayer)
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )
        b = (ComputationGraphConfiguration.graphBuilder().seed(1)
             .updater(Adam(learning_rate=1e-2)).addInputs("in"))
        b.setInputTypes(InputType.feedForward(4))
        b.addLayer("fe1", DenseLayer(n_in=4, n_out=10, activation="relu"),
                   "in")
        b.addLayer("fe2", DenseLayer(n_in=10, n_out=8, activation="relu"),
                   "fe1")
        b.addLayer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                      loss="mcxent"), "fe2")
        g = ComputationGraph(b.setOutputs("out").build()).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        for _ in range(5):
            g.fit([x], [y])
        return g, x

    def test_freeze_and_replace_head(self):
        from deeplearning4j_tpu.learning import Sgd
        from deeplearning4j_tpu.nn.conf import OutputLayer
        from deeplearning4j_tpu.nn.transferlearning import (
            FineTuneConfiguration, TransferLearning,
        )
        g, x = self._trained_graph()
        fe1_w = np.asarray(g.params_map["fe1"]["W"]).copy()
        new_g = (TransferLearning.GraphBuilder(g)
                 .fineTuneConfiguration(FineTuneConfiguration(
                     updater=Sgd(learning_rate=0.1)))
                 .setFeatureExtractor("fe2")
                 .removeVertexAndConnections("out")
                 .addLayer("new_out",
                           OutputLayer(n_in=8, n_out=5,
                                       activation="softmax", loss="mcxent"),
                           "fe2")
                 .setOutputs("new_out")
                 .build())
        # transferred weights intact
        np.testing.assert_allclose(
            np.asarray(new_g.params_map["fe1"]["W"]), fe1_w)
        # new 5-class head
        out = np.asarray(new_g.outputSingle(x))
        assert out.shape == (32, 5)
        # frozen layers stay fixed through training
        rng = np.random.default_rng(1)
        y5 = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 32)]
        for _ in range(5):
            new_g.fit([x], [y5])
        np.testing.assert_allclose(
            np.asarray(new_g.params_map["fe1"]["W"]), fe1_w)
        # head trained
        assert np.isfinite(new_g.score())

    def test_nout_replace_on_graph(self):
        from deeplearning4j_tpu.nn.transferlearning import TransferLearning
        g, x = self._trained_graph()
        new_g = (TransferLearning.GraphBuilder(g)
                 .nOutReplace("out", 7)
                 .build())
        out = np.asarray(new_g.outputSingle(x))
        assert out.shape == (32, 7)
        # upstream weights preserved
        np.testing.assert_allclose(np.asarray(new_g.params_map["fe2"]["W"]),
                                   np.asarray(g.params_map["fe2"]["W"]))


class TestGraphTLReviewFixes:
    def test_keep_connections_preserves_downstream(self):
        from deeplearning4j_tpu.nn.conf import DenseLayer
        from deeplearning4j_tpu.nn.transferlearning import TransferLearning
        g, x = TestGraphTransferLearning()._trained_graph()
        new_g = (TransferLearning.GraphBuilder(g)
                 .removeVertexKeepConnections("fe2")
                 .addLayer("fe2", DenseLayer(n_in=10, n_out=8,
                                             activation="tanh"), "fe1")
                 .build())
        # downstream 'out' survived, same outputs, fresh fe2
        out = np.asarray(new_g.outputSingle(x))
        assert out.shape == (32, 3)
        assert not np.allclose(np.asarray(new_g.params_map["fe2"]["W"]),
                               np.asarray(g.params_map["fe2"]["W"]))

    def test_nout_replace_updates_downstream_nin(self):
        from deeplearning4j_tpu.nn.transferlearning import TransferLearning
        g, x = TestGraphTransferLearning()._trained_graph()
        new_g = (TransferLearning.GraphBuilder(g)
                 .nOutReplace("fe1", 20)
                 .build())
        out = np.asarray(new_g.outputSingle(x))
        assert out.shape == (32, 3)
        assert new_g.params_map["fe1"]["W"].shape == (4, 20)
        assert new_g.params_map["fe2"]["W"].shape == (20, 8)

    def test_tad_negative_dims(self):
        from deeplearning4j_tpu.ndarray import Nd4j
        a = Nd4j.arange(24).reshape(2, 3, 4)
        assert a.tensorsAlongDimension(-1) == 6
        np.testing.assert_allclose(a.tensorAlongDimension(0, -1).toNumpy(),
                                   [0, 1, 2, 3])
