"""Transfer learning: freeze, fine-tune, head replacement, featurize.

Reference: org/deeplearning4j/nn/transferlearning/** + FrozenLayer.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, FrozenLayer, TransferLearning,
    TransferLearningHelper,
)


def _base_net(seed=0, n_classes=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 5).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, n)]
    return x, y


class TestFrozenLayers:
    def test_frozen_params_unchanged(self):
        net = _base_net()
        x, y = _data()
        net.fit(x, y)                   # pretrain a bit
        tl = (TransferLearning.Builder(net)
              .fineTuneConfiguration(FineTuneConfiguration(updater=Sgd(0.1)))
              .setFeatureExtractor(1)   # freeze layers 0 and 1
              .build())
        assert isinstance(tl.conf.layers[0], FrozenLayer)
        assert isinstance(tl.conf.layers[1], FrozenLayer)
        frozen_before = [np.asarray(tl.params_list[i]["W"]).copy()
                         for i in (0, 1)]
        head_before = np.asarray(tl.params_list[2]["W"]).copy()
        for _ in range(5):
            tl.fit(x, y)
        for i, before in zip((0, 1), frozen_before):
            np.testing.assert_array_equal(
                np.asarray(tl.params_list[i]["W"]), before)
        assert not np.allclose(np.asarray(tl.params_list[2]["W"]),
                               head_before)

    def test_frozen_output_matches_source_features(self):
        """Frozen layers carry over the trained weights."""
        net = _base_net()
        x, y = _data()
        net.fit(x, y, epochs=3)
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(0).build())
        np.testing.assert_array_equal(
            np.asarray(tl.params_list[0]["W"]),
            np.asarray(net.params_list[0]["W"]))


class TestSurgery:
    def test_replace_output_layer(self):
        net = _base_net(n_classes=3)
        x, _ = _data()
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1)
              .removeOutputLayer()
              .addLayer(OutputLayer(n_in=8, n_out=5, activation="softmax",
                                    loss="mcxent"))
              .build())
        out = tl.output(x).toNumpy()
        assert out.shape == (32, 5)
        y5 = np.eye(5, dtype=np.float32)[np.random.RandomState(1)
                                         .randint(0, 5, 32)]
        first = None
        for _ in range(10):
            tl.fit(x, y5)
            first = first or tl.score()
        assert tl.score() < first

    def test_nout_replace(self):
        net = _base_net()
        tl = (TransferLearning.Builder(net)
              .nOutReplace(1, 12, "xavier")
              .build())
        assert tl.params_list[1]["W"].shape == (16, 12)
        assert tl.params_list[2]["W"].shape == (12, 3)
        # layer 0 kept its weights
        np.testing.assert_array_equal(np.asarray(tl.params_list[0]["W"]),
                                      np.asarray(net.params_list[0]["W"]))
        x, y = _data()
        tl.fit(x, y)
        assert np.isfinite(tl.score())

    def test_remove_everything_rejected(self):
        net = _base_net()
        with pytest.raises(ValueError):
            TransferLearning.Builder(net).removeLayersFromOutput(3).build()

    def test_requires_init(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(OutputLayer(n_in=4, n_out=2)).build())
        with pytest.raises(ValueError, match="init"):
            TransferLearning.Builder(MultiLayerNetwork(conf))


class TestHelper:
    def test_featurize_and_fit(self):
        net = _base_net()
        x, y = _data(64)
        net.fit(x, y)
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1).build())
        helper = TransferLearningHelper(tl)
        feat = helper.featurize(DataSet(x, y))
        assert feat.features.shape == (64, 8)      # layer-1 width
        before = float(tl.score(DataSet(x, y)))
        for _ in range(15):
            helper.fitFeaturized(feat)
        after = float(tl.score(DataSet(x, y)))
        assert after < before

    def test_no_frozen_rejected(self):
        net = _base_net()
        with pytest.raises(ValueError, match="frozen"):
            TransferLearningHelper(net)

    def test_json_roundtrip_frozen(self):
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        net = _base_net()
        tl = TransferLearning.Builder(net).setFeatureExtractor(0).build()
        cfg2 = MultiLayerConfiguration.from_json(tl.conf.to_json())
        assert isinstance(cfg2.layers[0], FrozenLayer)
        assert isinstance(cfg2.layers[0].layer, DenseLayer)
