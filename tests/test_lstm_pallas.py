"""Pallas LSTM recurrence numerics vs the scan path (interpret mode on
CPU — the kernel's TPU A/B lives in BASELINE.md)."""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.lstm_pallas import (_pick_k,
                                                pallas_lstm_recurrence)
from deeplearning4j_tpu.ops.nn import lstm_layer


class TestPallasLstm:
    def test_matches_scan_path(self):
        rng = np.random.default_rng(0)
        n, t, insz, h = 4, 12, 8, 16
        x = jnp.asarray(rng.normal(0, 0.5, (n, t, insz)), jnp.float32)
        w_ih = jnp.asarray(rng.normal(0, 0.2, (insz, 4 * h)),
                           jnp.float32)
        w_hh = jnp.asarray(rng.normal(0, 0.2, (h, 4 * h)), jnp.float32)
        b = jnp.asarray(rng.normal(0, 0.1, (4 * h,)), jnp.float32)

        ys_ref, (hT_ref, cT_ref) = lstm_layer(x, w_ih, w_hh, b)
        xp = (x.reshape(n * t, -1) @ w_ih + b) \
            .reshape(n, t, 4 * h).transpose(1, 0, 2)
        ys, hT, cT = pallas_lstm_recurrence(
            xp, w_hh, jnp.zeros((n, h)), jnp.zeros((n, h)),
            k_steps=4, interpret=True)
        np.testing.assert_allclose(np.asarray(ys.transpose(1, 0, 2)),
                                   np.asarray(ys_ref), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(hT), np.asarray(hT_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(cT), np.asarray(cT_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_pick_k_divides_and_fits(self):
        k = _pick_k(200, 256, 1024, 2)
        assert 200 % k == 0 and 2 * k * 256 * 1024 * 2 <= 6 << 20
        # rows too big for any multi-step chunk: fall back to k=1
        assert _pick_k(200, 2048, 8192, 4) == 1
