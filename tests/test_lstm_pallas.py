"""Pallas LSTM recurrence numerics vs the scan path (interpret mode on
CPU — the kernel's TPU A/B lives in BASELINE.md)."""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.lstm_pallas import (_pick_k,
                                                pallas_lstm_recurrence)
from deeplearning4j_tpu.ops.nn import lstm_layer


class TestPallasLstm:
    def test_matches_scan_path(self):
        rng = np.random.default_rng(0)
        n, t, insz, h = 4, 12, 8, 16
        x = jnp.asarray(rng.normal(0, 0.5, (n, t, insz)), jnp.float32)
        w_ih = jnp.asarray(rng.normal(0, 0.2, (insz, 4 * h)),
                           jnp.float32)
        w_hh = jnp.asarray(rng.normal(0, 0.2, (h, 4 * h)), jnp.float32)
        b = jnp.asarray(rng.normal(0, 0.1, (4 * h,)), jnp.float32)

        ys_ref, (hT_ref, cT_ref) = lstm_layer(x, w_ih, w_hh, b)
        xp = (x.reshape(n * t, -1) @ w_ih + b) \
            .reshape(n, t, 4 * h).transpose(1, 0, 2)
        ys, hT, cT = pallas_lstm_recurrence(
            xp, w_hh, jnp.zeros((n, h)), jnp.zeros((n, h)),
            k_steps=4, interpret=True)
        np.testing.assert_allclose(np.asarray(ys.transpose(1, 0, 2)),
                                   np.asarray(ys_ref), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(hT), np.asarray(hT_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(cT), np.asarray(cT_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_grad_matches_scan_path(self):
        """The custom VJP (VERDICT r3 item #6): grads through the pallas
        recurrence must match jax.grad through the lax.scan reference on
        every input — x, both weight matrices, bias, and the initial
        carry enters via zeros so it is exercised through x_proj."""
        import jax

        rng = np.random.default_rng(1)
        n, t, insz, h = 3, 8, 5, 16
        x = jnp.asarray(rng.normal(0, 0.5, (n, t, insz)), jnp.float32)
        w_ih = jnp.asarray(rng.normal(0, 0.2, (insz, 4 * h)),
                           jnp.float32)
        w_hh = jnp.asarray(rng.normal(0, 0.2, (h, 4 * h)), jnp.float32)
        b = jnp.asarray(rng.normal(0, 0.1, (4 * h,)), jnp.float32)
        # Weight the per-position loss so dys is non-uniform in time.
        wts = jnp.asarray(rng.normal(0, 1.0, (n, t, h)), jnp.float32)

        def loss(params, impl):
            x_, wih_, whh_, b_ = params
            ys, (hT, cT) = lstm_layer(x_, wih_, whh_, b_, impl=impl)
            return (jnp.sum(ys * wts) + jnp.sum(hT * hT)
                    + jnp.sum(jnp.sin(cT)))

        params = (x, w_ih, w_hh, b)
        ref_val, ref_grads = jax.value_and_grad(loss)(params, "scan")
        # interpret=None auto-selects interpret mode off-TPU, so the
        # normal lstm_layer(impl="pallas") call site differentiates
        # unchanged on the CPU test mesh.
        val, grads = jax.value_and_grad(loss)(params, "pallas")
        np.testing.assert_allclose(float(val), float(ref_val),
                                   rtol=1e-5)
        for gr, gp, name in zip(ref_grads, grads,
                                ("x", "w_ih", "w_hh", "b")):
            np.testing.assert_allclose(
                np.asarray(gp), np.asarray(gr), rtol=2e-4, atol=2e-5,
                err_msg=f"grad mismatch for {name}")

    def test_grad_initial_carry(self):
        """d/dh0 and d/dc0 flow through the custom VJP directly."""
        import jax

        rng = np.random.default_rng(2)
        n, t, h = 2, 6, 8
        xp = jnp.asarray(rng.normal(0, 0.3, (t, n, 4 * h)), jnp.float32)
        w_hh = jnp.asarray(rng.normal(0, 0.2, (h, 4 * h)), jnp.float32)
        h0 = jnp.asarray(rng.normal(0, 0.5, (n, h)), jnp.float32)
        c0 = jnp.asarray(rng.normal(0, 0.5, (n, h)), jnp.float32)

        def loss_pallas(h0_, c0_):
            ys, hT, cT = pallas_lstm_recurrence(
                xp, w_hh, h0_, c0_, k_steps=2, interpret=True)
            return jnp.sum(ys ** 2) + jnp.sum(hT) + jnp.sum(cT)

        def loss_scan(h0_, c0_):
            def step(carry, x_t):
                h, c = carry
                gates = x_t + h @ w_hh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c2 = (jax.nn.sigmoid(f) * c
                      + jax.nn.sigmoid(i) * jnp.tanh(g))
                h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
                return (h2, c2), h2

            (hT, cT), ys = jax.lax.scan(step, (h0_, c0_), xp)
            return jnp.sum(ys ** 2) + jnp.sum(hT) + jnp.sum(cT)

        gp = jax.grad(loss_pallas, argnums=(0, 1))(h0, c0)
        gr = jax.grad(loss_scan, argnums=(0, 1))(h0, c0)
        np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gr[0]),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gr[1]),
                                   rtol=2e-4, atol=2e-5)

    def test_pick_k_divides_and_fits(self):
        k = _pick_k(200, 256, 1024, 2)
        assert 200 % k == 0 and 2 * k * 256 * 1024 * 2 <= 6 << 20
        # rows too big for any multi-step chunk: fall back to k=1
        assert _pick_k(200, 2048, 8192, 4) == 1
