"""Seq2seq encoder-decoder tests (reference: the dl4j-examples
AdditionRNN recipe; vertices LastTimeStepVertex /
DuplicateToTimeSeriesVertex / ReverseTimeSeriesVertex / Stack/Unstack)."""

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.models.seq2seq import Seq2SeqLSTM
from deeplearning4j_tpu.nn.graph import (
    DuplicateToTimeSeriesVertex, LastTimeStepVertex,
    ReverseTimeSeriesVertex, StackVertex, UnstackVertex,
)


class TestRnnVertices:
    def test_last_time_step_plain_and_masked(self):
        x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        v = LastTimeStepVertex()
        out, _ = v.apply(None, None, [x], False, None)
        np.testing.assert_allclose(out, np.asarray(x)[:, -1])
        mask = jnp.asarray([[1.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
        out, _ = v.apply(None, None, [x, mask], False, None)
        np.testing.assert_allclose(out[0], np.asarray(x)[0, 1])
        np.testing.assert_allclose(out[1], np.asarray(x)[1, 0])

    def test_duplicate_to_timeseries(self):
        feat = jnp.asarray([[1.0, 2.0]])
        ref = jnp.zeros((1, 5, 3))
        out, _ = DuplicateToTimeSeriesVertex().apply(
            None, None, [feat, ref], False, None)
        assert out.shape == (1, 5, 2)
        np.testing.assert_allclose(out[0, 4], [1.0, 2.0])

    def test_reverse_and_stack_unstack(self):
        x = jnp.asarray(np.arange(6, dtype=np.float32).reshape(1, 3, 2))
        rev, _ = ReverseTimeSeriesVertex().apply(None, None, [x], False,
                                                 None)
        np.testing.assert_allclose(rev[0, 0], np.asarray(x)[0, 2])
        a = jnp.ones((2, 4))
        b = jnp.zeros((2, 4))
        st, _ = StackVertex().apply(None, None, [a, b], False, None)
        assert st.shape == (4, 4)
        back, _ = UnstackVertex(from_index=1, stack_size=2).apply(
            None, None, [st], False, None)
        np.testing.assert_allclose(back, b)


class TestSeq2Seq:
    def _reversal_data(self, n=64, t=6, k=8, seed=0):
        """Task: output = input sequence reversed (one-hot alphabet k).
        Decoder input is the shifted target (teacher forcing)."""
        rs = np.random.RandomState(seed)
        src = rs.randint(0, k, (n, t))
        tgt = src[:, ::-1]
        enc = np.eye(k, dtype=np.float32)[src]
        dec_out = np.eye(k, dtype=np.float32)[tgt]
        dec_in = np.zeros_like(dec_out)
        dec_in[:, 1:] = dec_out[:, :-1]  # <go> = zeros, then shifted
        return enc, dec_in, dec_out

    def test_learns_reversal(self):
        k, t = 8, 6
        enc, dec_in, dec_out = self._reversal_data(t=t, k=k)
        net = Seq2SeqLSTM(in_features=k, out_features=k, hidden=64,
                          t_in=t, t_out=t).init()
        first = last = None
        for i in range(60):
            net.fit([enc, dec_in], [dec_out])
            if i == 0:
                first = net.score()
        last = net.score()
        assert last < first * 0.5, (first, last)
        pred = net.output(enc, dec_in)[0].toNumpy()
        acc = (pred.argmax(-1) == dec_out.argmax(-1)).mean()
        assert acc > 0.6, acc

    def test_config_json_roundtrip(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraphConfiguration,
        )
        conf = Seq2SeqLSTM(in_features=5, out_features=7, hidden=16,
                           t_in=4, t_out=4).conf()
        js = conf.to_json()
        rt = ComputationGraphConfiguration.from_json(js)
        assert rt.to_json() == js


class TestReviewRegressions:
    def test_last_step_gap_mask_uses_last_nonzero(self):
        x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 3, 4))
        mask = jnp.asarray([[1.0, 0.0, 1.0]])  # interior gap
        out, _ = LastTimeStepVertex().apply(None, None, [x, mask],
                                            False, None)
        np.testing.assert_allclose(out[0], np.asarray(x)[0, 2])

    def test_unstack_validates(self):
        import pytest
        x = jnp.ones((10, 4))
        with pytest.raises(ValueError, match="divisible"):
            UnstackVertex(from_index=0, stack_size=3).apply(
                None, None, [x], False, None)
        with pytest.raises(ValueError, match="from_index"):
            UnstackVertex(from_index=2, stack_size=2).apply(
                None, None, [jnp.ones((4, 4))], False, None)
