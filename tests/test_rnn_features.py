"""RNN completeness: Bidirectional, rnnTimeStep stateful stepping, tBPTT.

Reference behaviors mirrored (SURVEY.md §5 long-context):
- conf/layers/recurrent/Bidirectional.java (CONCAT/ADD/MUL/AVERAGE)
- MultiLayerNetwork#rnnTimeStep / rnnClearPreviousState
- MultiLayerNetwork#doTruncatedBPTT (segment updates, carried state)
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    Bidirectional, InputType, LSTM, MultiLayerConfiguration,
    NeuralNetConfiguration, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers import SimpleRnn
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.learning.updaters import Sgd


def _rnn_net(layer, n_out=3, seed=7, **list_kwargs):
    lb = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
          .list()
          .layer(layer)
          .layer(RnnOutputLayer(n_in=None, n_out=n_out, activation="softmax",
                                loss="mcxent"))
          .setInputType(InputType.recurrent(4)))
    for k, v in list_kwargs.items():
        getattr(lb, k)(v)
    net = MultiLayerNetwork(lb.build()).init()
    return net


class TestBidirectional:
    def test_concat_shape(self):
        net = _rnn_net(Bidirectional(layer=LSTM(n_out=5)))
        x = np.random.RandomState(0).randn(2, 6, 4).astype(np.float32)
        out = net.output(x).toNumpy()
        assert out.shape == (2, 6, 3)
        # concat doubles the hidden width feeding the output layer
        assert net.params_list[1]["W"].shape[0] == 10

    @pytest.mark.parametrize("mode", ["ADD", "MUL", "AVERAGE"])
    def test_elementwise_modes(self, mode):
        net = _rnn_net(Bidirectional(layer=LSTM(n_out=5), mode=mode))
        assert net.params_list[1]["W"].shape[0] == 5
        x = np.random.RandomState(0).randn(2, 6, 4).astype(np.float32)
        out = net.output(x).toNumpy()
        assert out.shape == (2, 6, 3)
        assert np.isfinite(out).all()

    def test_forward_direction_matches_unidirectional(self):
        """The fw half of a CONCAT bidirectional equals the plain LSTM
        run with the same params."""
        import jax.numpy as jnp
        bi = Bidirectional(layer=LSTM(n_in=4, n_out=5, weight_init="xavier"))
        import jax
        params = bi.init_params(jax.random.key(0), InputType.recurrent(4),
                                jnp.float32)
        x = jnp.asarray(np.random.RandomState(1).randn(2, 6, 4),
                        jnp.float32)
        y_bi, _ = bi.apply(params, {}, x, False, None)
        y_uni, _ = bi.layer.apply(params["fw"], {}, x, False, None)
        np.testing.assert_allclose(np.asarray(y_bi[..., :5]),
                                   np.asarray(y_uni), rtol=1e-5, atol=1e-6)

    def test_json_roundtrip(self):
        net = _rnn_net(Bidirectional(layer=LSTM(n_out=5), mode="ADD"))
        js = net.conf.to_json()
        cfg2 = MultiLayerConfiguration.from_json(js)
        assert isinstance(cfg2.layers[0], Bidirectional)
        assert cfg2.layers[0].mode == "ADD"
        assert cfg2.layers[0].layer.n_out == 5

    def test_rnn_time_step_rejected(self):
        net = _rnn_net(Bidirectional(layer=LSTM(n_out=5)))
        x = np.zeros((2, 4), np.float32)
        with pytest.raises(NotImplementedError):
            net.rnnTimeStep(x)


class TestRnnTimeStep:
    @pytest.mark.parametrize("layer", [LSTM(n_out=5), SimpleRnn(n_out=5)])
    def test_stepwise_matches_full_sequence(self, layer):
        net = _rnn_net(layer)
        x = np.random.RandomState(3).randn(2, 5, 4).astype(np.float32)
        full = net.output(x).toNumpy()              # [2, 5, 3]
        steps = [net.rnnTimeStep(x[:, t]).toNumpy() for t in range(5)]
        np.testing.assert_allclose(np.stack(steps, axis=1), full,
                                   rtol=1e-5, atol=1e-6)

    def test_3d_chunked_stepping(self):
        net = _rnn_net(LSTM(n_out=5))
        x = np.random.RandomState(4).randn(2, 6, 4).astype(np.float32)
        full = net.output(x).toNumpy()
        a = net.rnnTimeStep(x[:, :2]).toNumpy()
        b = net.rnnTimeStep(x[:, 2:]).toNumpy()
        np.testing.assert_allclose(np.concatenate([a, b], axis=1), full,
                                   rtol=1e-5, atol=1e-6)

    def test_clear_resets_state(self):
        net = _rnn_net(LSTM(n_out=5))
        x = np.random.RandomState(5).randn(2, 4).astype(np.float32)
        first = net.rnnTimeStep(x).toNumpy()
        second = net.rnnTimeStep(x).toNumpy()       # state carried → differs
        assert not np.allclose(first, second)
        net.rnnClearPreviousState()
        again = net.rnnTimeStep(x).toNumpy()
        np.testing.assert_allclose(again, first, rtol=1e-6)

    def test_get_set_state(self):
        net = _rnn_net(LSTM(n_out=5))
        x = np.random.RandomState(6).randn(2, 4).astype(np.float32)
        net.rnnTimeStep(x)
        st = net.rnnGetPreviousState(0)
        assert st is not None and len(st) == 2     # (h, c)
        out_before = net.rnnTimeStep(x).toNumpy()
        net.rnnSetPreviousState(0, st)
        out_after = net.rnnTimeStep(x).toNumpy()
        np.testing.assert_allclose(out_after, out_before, rtol=1e-6)


class TestTbptt:
    def _data(self, n=4, t=12, f=4, c=3, seed=0):
        rs = np.random.RandomState(seed)
        x = rs.randn(n, t, f).astype(np.float32)
        y = np.eye(c, dtype=np.float32)[rs.randint(0, c, size=(n, t))]
        return x, y

    def test_segment_iteration_count(self):
        net = _rnn_net(LSTM(n_out=5), tBPTTLength=4)
        assert net.conf.tbptt_fwd_length == 4
        x, y = self._data(t=12)
        net.fit(x, y)
        # 12 steps / 4 per segment = 3 updater applications
        assert net.getIterationCount() == 3

    def test_learning_happens(self):
        net = _rnn_net(LSTM(n_out=8), tBPTTLength=4)
        x, y = self._data(t=8)
        losses = []
        for _ in range(15):
            net.fit(x, y)
            losses.append(net.score())
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_partial_last_segment(self):
        net = _rnn_net(LSTM(n_out=5), tBPTTLength=5)
        x, y = self._data(t=12)           # 5 + 5 + 2
        net.fit(x, y)
        assert net.getIterationCount() == 3

    def test_matches_standard_bptt_when_t_below_k(self):
        """T <= k must take the standard (untruncated) path."""
        net = _rnn_net(LSTM(n_out=5), tBPTTLength=16)
        x, y = self._data(t=8)
        net.fit(x, y)
        assert net.getIterationCount() == 1

    def test_builder_backprop_type(self):
        lb = (NeuralNetConfiguration.builder().list()
              .layer(LSTM(n_in=4, n_out=5))
              .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                    loss="mcxent"))
              .setInputType(InputType.recurrent(4))
              .backpropType("TruncatedBPTT").tBPTTForwardLength(6)
              .tBPTTBackwardLength(6))
        cfg = lb.build()
        assert cfg.tbptt_fwd_length == 6
        assert cfg.tbptt_back_length == 6

    def test_backprop_type_standard_wins(self):
        """Explicit Standard disables tBPTT even with a length set."""
        net = _rnn_net(LSTM(n_out=5), tBPTTLength=4,
                       backpropType="Standard")
        assert net.conf.tbptt_fwd_length == 0

    def test_truncated_default_length(self):
        """TruncatedBPTT without a length uses the reference default 20."""
        net = _rnn_net(LSTM(n_out=5), backpropType="TruncatedBPTT")
        assert net.conf.tbptt_fwd_length == 20

    def test_bidirectional_rejected(self):
        net = _rnn_net(Bidirectional(layer=LSTM(n_out=5)), tBPTTLength=4)
        x, y = self._data(t=12)
        with pytest.raises(ValueError, match="Bidirectional"):
            net.fit(x, y)

    def test_batch_size_change_rejected(self):
        net = _rnn_net(LSTM(n_out=5))
        net.rnnTimeStep(np.zeros((4, 4), np.float32))
        with pytest.raises(ValueError, match="batch size"):
            net.rnnTimeStep(np.zeros((2, 4), np.float32))
        net.rnnClearPreviousState()
        net.rnnTimeStep(np.zeros((2, 4), np.float32))  # fine after clear


class TestGraphRnnTimeStep:
    """ComputationGraph#rnnTimeStep parity (reference: stateful graph
    inference with recurrent layer vertices keeping carries)."""

    def _net(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )

        b = (ComputationGraphConfiguration.graphBuilder()
             .seed(9).addInputs("in")
             .setInputTypes(InputType.recurrent(4)))
        b.addLayer("lstm", LSTM(n_out=5), "in")
        b.addLayer("out", RnnOutputLayer(n_out=3, activation="identity",
                                         loss="mse"), "lstm")
        return ComputationGraph(b.setOutputs("out").build()).init()

    def test_stepwise_matches_full_sequence(self):
        net = self._net()
        x = np.random.RandomState(3).randn(2, 5, 4).astype(np.float32)
        full = net.output(x)[0].toNumpy()
        steps = [net.rnnTimeStep(x[:, t])[0].toNumpy() for t in range(5)]
        np.testing.assert_allclose(np.stack(steps, axis=1), full,
                                   rtol=1e-5, atol=1e-6)

    def test_clear_and_batch_mismatch(self):
        net = self._net()
        x = np.random.RandomState(5).randn(2, 4).astype(np.float32)
        first = net.rnnTimeStep(x)[0].toNumpy()
        second = net.rnnTimeStep(x)[0].toNumpy()
        assert not np.allclose(first, second)
        with pytest.raises(ValueError, match="batch size changed"):
            net.rnnTimeStep(np.zeros((3, 4), np.float32))
        net.rnnClearPreviousState()
        again = net.rnnTimeStep(x)[0].toNumpy()
        np.testing.assert_allclose(again, first, rtol=1e-6)
        assert net.rnnGetPreviousState("lstm") is not None
