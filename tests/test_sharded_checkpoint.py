"""ShardedCheckpoint units on the 8-device CPU mesh (the 2-process
kill-and-resume e2e lives in test_jax_distributed.py). Reference role:
SURVEY.md §5 "Orbax-style checkpoint of param/opt pytrees +
data-iterator state"."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.util import ShardedCheckpoint


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))


def _tree(mesh):
    rs = np.random.RandomState(0)
    return {
        "layer": {
            "w": jax.device_put(
                jnp.asarray(rs.randn(8, 6).astype(np.float32)),
                NamedSharding(mesh, P("data", "model"))),
            "b": jax.device_put(
                jnp.asarray(rs.randn(6).astype(np.float32)),
                NamedSharding(mesh, P())),       # fully replicated
        },
        "opt": [jax.device_put(
            jnp.asarray(rs.randn(8, 6).astype(np.float32)),
            NamedSharding(mesh, P("data", None)))],
    }


class TestShardedCheckpoint:
    def test_roundtrip_preserves_values_and_shardings(self, mesh,
                                                      tmp_path):
        tree = _tree(mesh)
        ShardedCheckpoint.save(str(tmp_path), tree, step=7,
                               iterator_state={"i": 16, "epoch": 2})
        template = jax.tree_util.tree_map(jnp.zeros_like, tree)
        out, meta = ShardedCheckpoint.restore(str(tmp_path), template)
        assert meta["step"] == 7
        assert meta["iterator_state"] == {"i": 16, "epoch": 2}
        for (pa, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(tree),
                jax.tree_util.tree_leaves_with_path(out)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       err_msg=str(pa))
            assert a.sharding == b.sharding, pa

    def test_replicated_leaf_stored_once(self, mesh, tmp_path):
        tree = _tree(mesh)
        ShardedCheckpoint.save(str(tmp_path), tree)
        shards = np.load(str(tmp_path / "shards_p0.npz"))
        b_keys = [k for k in shards.files if k.startswith("layer/b")]
        assert b_keys == ["layer/b@@rep"]       # one copy, not 8
        w_keys = [k for k in shards.files if k.startswith("layer/w")]
        assert len(w_keys) == 8                 # one per device shard

    def test_shape_mismatch_rejected(self, mesh, tmp_path):
        ShardedCheckpoint.save(str(tmp_path), _tree(mesh))
        bad = _tree(mesh)
        bad["layer"]["b"] = jnp.zeros(5)
        with pytest.raises(ValueError, match="shape"):
            ShardedCheckpoint.restore(str(tmp_path), bad)

    def test_missing_path_rejected(self, mesh, tmp_path):
        ShardedCheckpoint.save(str(tmp_path), _tree(mesh))
        bad = _tree(mesh)
        bad["extra"] = jnp.zeros(3)
        with pytest.raises(KeyError, match="extra"):
            ShardedCheckpoint.restore(str(tmp_path), bad)

    def test_torn_checkpoint_detected(self, mesh, tmp_path):
        """A crash between hosts' writes leaves shard files from a
        different step than the manifest — restore must be a loud
        error, never silently mixed parameter state."""
        import json
        tree = _tree(mesh)
        ShardedCheckpoint.save(str(tmp_path), tree, step=5)
        mpath = tmp_path / "manifest.json"
        manifest = json.loads(mpath.read_text())
        manifest["step"] = 6      # manifest advanced; shards did not
        mpath.write_text(json.dumps(manifest))
        with pytest.raises(ValueError, match="torn"):
            ShardedCheckpoint.restore(str(tmp_path), _tree(mesh))

    def test_exists(self, mesh, tmp_path):
        assert not ShardedCheckpoint.exists(str(tmp_path))
        ShardedCheckpoint.save(str(tmp_path), _tree(mesh))
        assert ShardedCheckpoint.exists(str(tmp_path))


class TestIteratorState:
    def test_mid_epoch_resume_reproduces_batches(self):
        rs = np.random.RandomState(1)
        X, Y = rs.randn(32, 4).astype(np.float32), \
            rs.randn(32, 1).astype(np.float32)
        it = ArrayDataSetIterator(X, Y, batch_size=8, shuffle=True,
                                  seed=5)
        it.next()
        it.next()
        state = it.get_state()
        want = [np.asarray(it.next().features) for _ in range(2)]

        it2 = ArrayDataSetIterator(X, Y, batch_size=8, shuffle=True,
                                   seed=5)
        it2.set_state(state)
        got = [np.asarray(it2.next().features) for _ in range(2)]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)

    def test_state_survives_epoch_boundary(self):
        X = np.arange(16, dtype=np.float32).reshape(16, 1)
        it = ArrayDataSetIterator(X, X, batch_size=8, shuffle=True,
                                  seed=3)
        it.next()
        it.next()
        it.reset()          # epoch 1
        it.next()
        state = it.get_state()
        want = np.asarray(it.next().features)
        it2 = ArrayDataSetIterator(X, X, batch_size=8, shuffle=True,
                                   seed=3)
        it2.set_state(state)
        np.testing.assert_array_equal(np.asarray(it2.next().features),
                                      want)

    def test_base_iterator_raises(self):
        from deeplearning4j_tpu.datasets.iterator import DataSetIterator
        with pytest.raises(NotImplementedError):
            DataSetIterator().get_state()
