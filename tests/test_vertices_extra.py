"""Extended graph vertex tests (reference analogs: graph vertex tests in
deeplearning4j-nn ComputationGraphTestRNN / TestGraphNodes)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning import Adam, AdamW, Sgd
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration,
    DotProductAttentionVertex, FrozenVertex, L2Vertex, LayerVertex,
    PoolHelperVertex, ReshapeVertex, ShiftVertex,
)


class TestSimpleVertices:
    def test_shift_reshape_poolhelper(self):
        sv = ShiftVertex(shift=2.5)
        out, _ = sv.apply({}, {}, [jnp.zeros((2, 3))], False, None)
        np.testing.assert_allclose(np.asarray(out), 2.5)

        rv = ReshapeVertex(shape=[4, 4, 2])
        out, _ = rv.apply({}, {}, [jnp.arange(64.0).reshape(2, 32)], False,
                          None)
        assert out.shape == (2, 4, 4, 2)
        it = rv.output_type([InputType.feedForward(32)])
        assert (it.height, it.width, it.channels) == (4, 4, 2)

        ph = PoolHelperVertex()
        out, _ = ph.apply({}, {}, [jnp.ones((2, 5, 5, 3))], False, None)
        assert out.shape == (2, 4, 4, 3)

    def test_l2_vertex_distance(self):
        a = jnp.array([[1.0, 0.0], [0.0, 0.0]])
        b = jnp.array([[0.0, 0.0], [3.0, 4.0]])
        out, _ = L2Vertex().apply({}, {}, [a, b], False, None)
        np.testing.assert_allclose(np.asarray(out)[:, 0], [1.0, 5.0],
                                   atol=1e-5)

    def test_attention_vertex(self):
        n, t, s, d = 2, 3, 4, 8
        q = jax.random.normal(jax.random.key(0), (n, t, d))
        k = jax.random.normal(jax.random.key(1), (n, s, d))
        v = jax.random.normal(jax.random.key(2), (n, s, d))
        out, _ = DotProductAttentionVertex().apply({}, {}, [q, k, v],
                                                   False, None)
        assert out.shape == (n, t, d)
        # mask: only first source position attended -> output == v[:, :1]
        mask = jnp.zeros((n, s)).at[:, 0].set(1.0)
        out_m, _ = DotProductAttentionVertex().apply({}, {}, [q, k, v, mask],
                                                     False, None)
        want = jnp.broadcast_to(v[:, :1, :], (n, t, d))
        np.testing.assert_allclose(np.asarray(out_m), np.asarray(want),
                                   atol=1e-5)


class TestFrozenVertexTraining:
    def test_frozen_vertex_params_fixed_in_graph(self):
        b = (ComputationGraphConfiguration.graphBuilder().seed(1)
             .updater(AdamW(learning_rate=0.05, weight_decay=0.01))
             .addInputs("in"))
        b.setInputTypes(InputType.feedForward(4))
        b.addVertex("frozen",
                    FrozenVertex(vertex=LayerVertex(
                        layer=DenseLayer(n_in=4, n_out=8,
                                         activation="relu"))), "in")
        b.addLayer("out", OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"), "frozen")
        g = ComputationGraph(b.setOutputs("out").build()).init()
        w0 = np.asarray(g.params_map["frozen"]["W"]).copy()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        for _ in range(5):
            g.fit([x], [y])
        np.testing.assert_allclose(np.asarray(g.params_map["frozen"]["W"]),
                                   w0)
        # downstream layer trained
        assert np.isfinite(g.score())


class TestAttentionGraphTraining:
    def test_attention_seq_classifier_learns(self):
        """q/k/v projections as layers + attention vertex, end-to-end."""
        b = (ComputationGraphConfiguration.graphBuilder().seed(3)
             .updater(Adam(learning_rate=5e-3))
             .addInputs("seq"))
        b.setInputTypes(InputType.recurrent(6, 8))
        b.addLayer("q", DenseLayer(n_in=6, n_out=12), "seq")
        b.addLayer("k", DenseLayer(n_in=6, n_out=12), "seq")
        b.addLayer("v", DenseLayer(n_in=6, n_out=12), "seq")
        b.addVertex("att", DotProductAttentionVertex(), "q", "k", "v")
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer
        b.addLayer("pool", GlobalPoolingLayer(pooling_type="avg"), "att")
        b.addLayer("out", OutputLayer(n_in=12, n_out=2,
                                      activation="softmax", loss="mcxent"),
                   "pool")
        g = ComputationGraph(b.setOutputs("out").build()).init()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(32, 8, 6)).astype(np.float32)
        lab = (x[:, :, 0].mean(1) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[lab]
        s0 = None
        for _ in range(30):
            g.fit([x], [y])
            s0 = s0 or g.score()
        assert g.score() < s0
