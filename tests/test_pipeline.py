"""Pipeline parallelism tests on the virtual 8-device CPU mesh.

GPipe is mathematically a no-op: pipelined loss/gradients must equal the
unpipelined model's (the schedule only reorders compute). The reference
has no pipeline parallelism at all (SURVEY.md §2 parallelism list) —
this is a TPU-first extension, tested with the same
distributed-without-cluster philosophy as the reference's Aeron tests.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.models.transformer import (
    TransformerEncoder, tiny_config,
)
from deeplearning4j_tpu.parallel.pipeline import PipelinedTransformer


def _mesh(data=2, pipe=4):
    devs = np.asarray(jax.devices()[:data * pipe]).reshape(data, pipe)
    return Mesh(devs, ("data", "pipe"))


def _batch(cfg, n=8, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, cfg.vocab_size, (n, cfg.max_len)).astype(np.int32)
    labels = rs.randint(0, cfg.vocab_size, (n, cfg.max_len)).astype(np.int32)
    mask = (rs.rand(n, cfg.max_len) < 0.15).astype(np.float32)
    mask[:, 0] = 1.0  # ensure nonzero count per row
    return jnp.asarray(ids), jnp.asarray(labels), jnp.asarray(mask)


class TestPipelineEquivalence:
    def test_eval_loss_matches_unpipelined(self):
        cfg = tiny_config(vocab=97, max_len=16, d_model=32, n_layers=4,
                          d_ff=64)
        enc = TransformerEncoder(cfg)
        params = enc.init_params()
        ids, labels, mask = _batch(cfg)
        ref = float(enc.mlm_loss(params, ids, labels, mask, train=False))
        mesh = _mesh(data=2, pipe=4)
        pp = PipelinedTransformer(enc, n_stages=4)
        sp = pp.shard_params(params, mesh)
        got = float(pp.eval_loss(sp, ids, labels, mask, mesh, n_micro=2))
        assert abs(got - ref) / abs(ref) < 1e-5, (got, ref)

    def test_stack_unstack_roundtrip(self):
        cfg = tiny_config(n_layers=4)
        enc = TransformerEncoder(cfg)
        params = enc.init_params()
        pp = PipelinedTransformer(enc, n_stages=2)
        rt = pp.unstack_params(pp.stack_params(params))
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(rt)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_layers_indivisible_raises(self):
        enc = TransformerEncoder(tiny_config(n_layers=3))
        with pytest.raises(ValueError, match="divisible"):
            PipelinedTransformer(enc, n_stages=2)


class TestPipelineTraining:
    def test_train_step_matches_single_device(self):
        """One pipelined train step == one unsharded train step (same
        updater, same data): GPipe must not change the math."""
        cfg = tiny_config(vocab=53, max_len=8, d_model=16, n_layers=4,
                          d_ff=32)
        cfg.dropout = 0.0
        enc = TransformerEncoder(cfg)
        params = enc.init_params()
        ids, labels, mask = _batch(cfg, n=8)
        rng = jax.random.key(7)

        # SGD, not Adam: at step 0 Adam's update is ~sign(g)*lr, which
        # amplifies float-reassociation noise on near-zero grads into
        # full-size update flips — SGD keeps update proportional to grad
        # so the tolerance is meaningful.
        from deeplearning4j_tpu.learning.updaters import Sgd
        ref_step = enc.make_train_step(Sgd(0.5))
        ref_params, _, ref_loss = ref_step(
            jax.tree_util.tree_map(jnp.copy, params),
            Sgd(0.5).init_state(params), jnp.asarray(0),
            ids, labels, mask, rng)

        mesh = _mesh(data=2, pipe=4)
        pp = PipelinedTransformer(enc, n_stages=4)
        sp = pp.shard_params(params, mesh)
        opt = Sgd(0.5).init_state(sp)
        step = pp.make_train_step(Sgd(0.5), mesh, n_micro=2)
        new_sp, _, loss = step(sp, opt, jnp.asarray(0), ids, labels,
                               mask, rng)
        assert abs(float(loss) - float(ref_loss)) / abs(float(ref_loss)) \
            < 1e-5
        got = pp.unstack_params(new_sp)
        for (pa, a), (pb, b) in zip(
                jax.tree_util.tree_leaves_with_path(ref_params),
                jax.tree_util.tree_leaves_with_path(got)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=str(pa))

    def test_loss_decreases(self):
        cfg = tiny_config(vocab=31, max_len=8, d_model=16, n_layers=2,
                          d_ff=32)
        enc = TransformerEncoder(cfg)
        mesh = _mesh(data=2, pipe=2)
        pp = PipelinedTransformer(enc, n_stages=2)
        sp = pp.shard_params(enc.init_params(), mesh)
        upd = Adam(5e-3)
        opt = upd.init_state(sp)
        step = pp.make_train_step(upd, mesh, n_micro=2)
        ids, labels, mask = _batch(cfg, n=8, seed=3)
        losses = []
        for i in range(16):
            sp, opt, loss = step(sp, opt, jnp.asarray(i), ids, labels,
                                 mask, jax.random.key(i))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses
