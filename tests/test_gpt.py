"""Causal LM recipe (models/gpt.py): KV-cache decode pinned against
the recompute-everything forward, training convergence on a periodic
language, scan-based generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.models.gpt import CausalLM
from deeplearning4j_tpu.models.transformer import tiny_config


def _model(vocab=11, max_len=32):
    cfg = tiny_config(vocab=vocab, max_len=max_len, d_model=32,
                      n_layers=2, n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    return CausalLM(cfg, compute_dtype=jnp.float32)


class TestKvCacheCorrectness:
    def test_generate_matches_full_forward_greedy(self):
        m = _model()
        params = m.init_params(jax.random.key(1))
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, 11, (3, 5)), jnp.int32)
        out = np.asarray(m.generate(params, prompt, max_new_tokens=6))
        # oracle: recompute the full prefix each step, argmax last pos
        seq = np.asarray(prompt)
        want = []
        for _ in range(6):
            logits = np.asarray(m.forward(params, jnp.asarray(seq)))
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            want.append(nxt)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, np.stack(want, axis=1))

    def test_prompt_overflow_raises(self):
        m = _model(max_len=8)
        params = m.init_params()
        with pytest.raises(ValueError, match="max_len"):
            m.generate(params, jnp.zeros((1, 5), jnp.int32),
                       max_new_tokens=4)


class TestTraining:
    def test_learns_periodic_language_and_continues_it(self):
        period = 7
        m = _model(vocab=period + 1, max_len=32)
        params = m.init_params(jax.random.key(0))
        step = m.make_train_step(Adam(learning_rate=3e-3))
        opt = Adam(learning_rate=3e-3).init_state(params)
        rng = np.random.default_rng(1)
        # sequences are the cyclic language t -> (t+1) % period with a
        # random phase per row
        def batch(n=32, t=24):
            phase = rng.integers(0, period, n)
            return jnp.asarray(
                (phase[:, None] + np.arange(t)) % period, jnp.int32)

        losses = []
        for i in range(150):
            params, opt, loss = step(params, opt, jnp.asarray(i),
                                     batch(), jax.random.key(i))
            losses.append(float(loss))
        assert losses[-1] < 0.1, losses[-1]
        assert losses[-1] < losses[0] / 5

        prompt = jnp.asarray([[2, 3, 4], [5, 6, 0]], jnp.int32)
        cont = np.asarray(m.generate(params, prompt, max_new_tokens=5))
        np.testing.assert_array_equal(
            cont, [[5, 6, 0, 1, 2], [1, 2, 3, 4, 5]])

    def test_gen_cache_is_lru_not_fifo(self):
        """Serving regression: with 9 shapes alternating against 2 hot
        ones, the hot programs must stay compiled. The old FIFO
        eviction (pop oldest-INSERTED) dropped the hottest program
        precisely because it was compiled first."""
        m = _model()
        params = m.init_params(jax.random.key(0))

        def gen(t0):
            m.generate(params, jnp.zeros((1, t0), jnp.int32),
                       max_new_tokens=1)

        gen(3)
        gen(4)
        hot = {k: v for k, v in m._gen_cache.items()}
        assert len(hot) == 2
        for t0 in range(5, 12):      # 7 cold shapes -> 9 total
            gen(t0)
            gen(3)                   # hot shapes stay in rotation
            gen(4)
        assert len(m._gen_cache) <= 8
        for key, fn in hot.items():
            assert m._gen_cache.get(key) is fn, \
                f"hot program {key} was evicted/recompiled (FIFO " \
                "eviction regression)"

    def test_sampled_generation_shape_and_vocab(self):
        m = _model()
        params = m.init_params()
        out = np.asarray(m.generate(
            params, jnp.zeros((2, 3), jnp.int32), max_new_tokens=4,
            temperature=1.0, rng=jax.random.key(3)))
        assert out.shape == (2, 4)
        assert out.min() >= 0 and out.max() < 11
