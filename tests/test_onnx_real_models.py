"""REAL exported-model ONNX goldens (reference: samediff-import-onnx
run against actual producer artifacts, SURVEY.md §2.14). The models
are exported by torch.onnx.export itself — the attr conventions under
test are the real exporter's, not hand-built protos (VERDICT r2
missing #5). torchvision is absent in this image, so ResNet-18 is
built faithfully to torchvision.models.resnet18's architecture inline.
"""

import os
import tempfile

import numpy as np
import pytest
import torch
import torch.nn as nn

from deeplearning4j_tpu.modelimport.onnx.onnx_import import OnnxImport


@pytest.fixture(autouse=True)
def _patch_export(monkeypatch):
    """torch.onnx.export's TorchScript path only needs the `onnx`
    package to splice onnxscript custom functions into the C++-built
    proto; none of these models use onnxscript, so the hook becomes
    identity (the proto bytes come from the C++ exporter either way)."""
    from torch.onnx._internal.torchscript_exporter import (
        onnx_proto_utils,
    )

    monkeypatch.setattr(onnx_proto_utils, "_add_onnxscript_fn",
                        lambda model_bytes, custom_opsets: model_bytes)


def _export(model, args, **kw):
    model.eval()
    path = os.path.join(tempfile.mkdtemp(), "model.onnx")
    with torch.no_grad():
        torch.onnx.export(model, args, path, dynamo=False, **kw)
    return path


def _golden(model, x, rtol=1e-4, atol=1e-4, **export_kw):
    path = _export(model, (x,), **export_kw)
    with torch.no_grad():
        ref = model(x).numpy()
    sd = OnnxImport.importGraph(path)
    phs = [v.name for v in sd.variables()
           if v.vtype.value == "PLACEHOLDER"]
    assert len(phs) == 1, phs
    # ONNX graph output name = last node's output
    out_name = sd._ops[-1].outputs[0]
    got = np.asarray(sd.output({phs[0]: x.numpy()},
                               [out_name])[out_name])
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)
    return sd


# ------------------------------------------------ torchvision resnet18
class BasicBlock(nn.Module):
    """torchvision.models.resnet.BasicBlock, verbatim architecture."""

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            idn = self.downsample(x)
        return self.relu(out + idn)


class ResNet18(nn.Module):
    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        layers = []
        cin = 64
        for cout, stride in [(64, 1), (64, 1), (128, 2), (128, 1),
                             (256, 2), (256, 1), (512, 2), (512, 1)]:
            layers.append(BasicBlock(cin, cout, stride))
            cin = cout
        self.layers = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2d((1, 1))
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layers(x)
        x = torch.flatten(self.avgpool(x), 1)
        return self.fc(x)


class SmallTransformer(nn.Module):
    """Real torch TransformerEncoder + classifier head — the exporter
    emits the genuine attention/LayerNorm/GELU op patterns."""

    def __init__(self, vocab=50, d=32, heads=4, layers=2, seq=12):
        super().__init__()
        self.emb = nn.Embedding(vocab, d)
        self.pos = nn.Parameter(torch.randn(1, seq, d) * 0.02)
        enc_layer = nn.TransformerEncoderLayer(
            d, heads, dim_feedforward=64, batch_first=True,
            activation="gelu", dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer, layers)
        self.head = nn.Linear(d, 5)

    def forward(self, ids):
        h = self.encoder(self.emb(ids) + self.pos)
        return self.head(h[:, 0])


class TestRealExportedModels:
    def test_resnet18_golden(self):
        torch.manual_seed(0)
        model = ResNet18(num_classes=10)
        # randomize BN stats so inference BN actually transforms
        for mod in model.modules():
            if isinstance(mod, nn.BatchNorm2d):
                mod.running_mean.uniform_(-0.2, 0.2)
                mod.running_var.uniform_(0.6, 1.4)
        x = torch.randn(2, 3, 64, 64)
        sd = _golden(model, x, rtol=2e-4, atol=2e-4)
        # structural sanity: the residual adds survived import
        assert sum(1 for op in sd._ops if op.op_name == "add") >= 8

    def test_small_transformer_golden(self):
        torch.manual_seed(1)
        model = SmallTransformer()
        ids = torch.randint(0, 50, (3, 12))
        # the fused aten::_transformer_encoder_layer_fwd fast path has
        # no ONNX lowering; force the decomposed (exportable) path
        try:
            torch.backends.mha.set_fastpath_enabled(False)
            path = _export(model, (ids,))
        finally:
            torch.backends.mha.set_fastpath_enabled(True)
        with torch.no_grad():
            ref = model(ids).numpy()
        sd = OnnxImport.importGraph(path)
        phs = [v.name for v in sd.variables()
               if v.vtype.value == "PLACEHOLDER"]
        out_name = sd._ops[-1].outputs[0]
        got = np.asarray(sd.output({phs[0]: ids.numpy()},
                                   [out_name])[out_name])
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_mobilenet_style_depthwise_golden(self):
        """Depthwise-separable stack (MobileNet's defining block) via
        the real exporter's grouped-Conv encoding."""
        torch.manual_seed(2)
        model = nn.Sequential(
            nn.Conv2d(3, 16, 3, 2, 1, bias=False),
            nn.BatchNorm2d(16), nn.ReLU6(),
            nn.Conv2d(16, 16, 3, 1, 1, groups=16, bias=False),  # dw
            nn.BatchNorm2d(16), nn.ReLU6(),
            nn.Conv2d(16, 32, 1, bias=False),                   # pw
            nn.BatchNorm2d(32), nn.ReLU6(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(),
            nn.Linear(32, 7))
        for mod in model.modules():
            if isinstance(mod, nn.BatchNorm2d):
                mod.running_mean.uniform_(-0.2, 0.2)
                mod.running_var.uniform_(0.6, 1.4)
        x = torch.randn(2, 3, 32, 32)
        _golden(model, x, rtol=2e-4, atol=2e-4)


    def test_decoder_upsampling_golden(self):
        """Generator/decoder-style stack through the REAL exporter:
        ConvTranspose2d (the r4 mapper incl. kernel flip), Upsample
        (Resize nearest, asymmetric/floor), InstanceNorm2d, HardSwish,
        Mish — the image-generation op tail."""
        torch.manual_seed(3)
        model = nn.Sequential(
            nn.Conv2d(3, 8, 3, 2, 1),
            nn.InstanceNorm2d(8, affine=True),
            nn.Hardswish(),
            nn.ConvTranspose2d(8, 6, 4, 2, 1),
            nn.Mish(),
            nn.Upsample(scale_factor=2, mode="nearest"),
            nn.Conv2d(6, 3, 3, 1, 1))
        with torch.no_grad():
            for m in model.modules():
                if isinstance(m, nn.InstanceNorm2d):
                    m.weight.uniform_(0.5, 1.5)
                    m.bias.uniform_(-0.3, 0.3)
        x = torch.randn(2, 3, 16, 16)
        _golden(model, x, rtol=2e-4, atol=2e-4)


class TestRecurrentOperators:
    """ONNX LSTM/GRU/RNN operators as torch.onnx.export actually emits
    them (time-major X, packed iofc/zrh gate blocks, Expand-ed initial
    states) — golden vs torch (reference: samediff-import-onnx onto
    nd4j lstmLayer). Exercises lstm_seq / gru_seq backing ops."""

    def _golden_rnn(self, mod, x, rtol=2e-4, atol=2e-4):
        mod.eval()
        path = _export(mod, (x,))
        with torch.no_grad():
            ref, _ = mod(x)
        sd = OnnxImport.importGraph(path)
        phs = [v.name for v in sd.variables()
               if v.vtype.value == "PLACEHOLDER"]
        out_name = sd._ops[-1].outputs[0]
        got = np.asarray(sd.output({phs[0]: x.numpy()},
                                   [out_name])[out_name])
        np.testing.assert_allclose(got, ref.numpy(), rtol=rtol,
                                   atol=atol)

    def test_lstm_forward(self):
        torch.manual_seed(3)
        self._golden_rnn(nn.LSTM(5, 7, batch_first=True),
                         torch.randn(2, 6, 5))

    def test_lstm_bidirectional(self):
        torch.manual_seed(4)
        self._golden_rnn(
            nn.LSTM(5, 7, batch_first=True, bidirectional=True),
            torch.randn(2, 6, 5))

    def test_gru_forward(self):
        torch.manual_seed(5)
        self._golden_rnn(nn.GRU(5, 7, batch_first=True),
                         torch.randn(2, 6, 5))

    def test_gru_bidirectional(self):
        torch.manual_seed(6)
        self._golden_rnn(
            nn.GRU(5, 7, batch_first=True, bidirectional=True),
            torch.randn(2, 6, 5))

    def test_rnn_tanh_forward(self):
        torch.manual_seed(7)
        self._golden_rnn(nn.RNN(5, 7, batch_first=True),
                         torch.randn(2, 6, 5))

    def test_lstm_classifier_end_to_end(self):
        """A realistic exported model: LSTM backbone + dense head."""
        torch.manual_seed(8)

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lstm = nn.LSTM(6, 12, batch_first=True)
                self.head = nn.Linear(12, 4)

            def forward(self, x):
                y, _ = self.lstm(x)
                return self.head(y[:, -1])

        m = M()
        x = torch.randn(3, 10, 6)
        _golden(m, x, rtol=2e-4, atol=2e-4)


class ScriptedIf(nn.Module):
    def forward(self, x):
        if bool(x.sum() > 0.0):
            return x * 2.0
        else:
            return x - 1.0


class ScriptedWhile(nn.Module):
    def forward(self, x):
        i = 0
        acc = x
        while i < 5:
            acc = acc * 0.8 + 1.0
            i = i + 1
        return acc


class ScriptedCondWhile(nn.Module):
    def forward(self, x):
        acc = x
        while bool(acc.sum() < 100.0):
            acc = acc + acc.abs() + 0.5
        return acc


class ScriptedCondWhileWeighted(nn.Module):
    """Cond-driven while whose carried float state is seeded through a
    weight — promoting the weight makes gradients flow INTO the loop."""

    def __init__(self):
        super().__init__()
        self.w = nn.Parameter(torch.full((3,), 0.5))

    def forward(self, x):
        acc = x * self.w
        while bool(acc.sum() < 100.0):
            acc = acc + acc.abs() + 0.5
        return acc


class ScriptedLoopIf(nn.Module):
    def forward(self, x):
        acc = x
        i = 0
        while i < 3:
            if bool(acc.mean() > 0.0):
                acc = acc * 0.5
            else:
                acc = acc + 1.0
            i = i + 1
        return acc


class TestOnnxControlFlow:
    """ONNX If/Loop operators as torch.jit.script + export actually
    emits them (If branches capture outer tensors by name; Loop
    carries (i, cond, state) with INT64_MAX trip counts for
    cond-driven whiles) — the reference executes these through
    AbstractSession; here they compile into if_cond/while_loop."""

    @staticmethod
    def _import_scripted(mod, x):
        """script -> export -> import; returns (sd, model, phs, outs)."""
        m = torch.jit.script(mod)
        m.eval()
        path = _export(m, (x,))
        model = OnnxImport._as_model(path)
        sd = OnnxImport.importGraph(path)
        phs = [v.name for v in sd.variables()
               if v.vtype.value == "PLACEHOLDER"]
        outs = [o.name for o in model.graph.outputs]
        return sd, model, phs, outs

    def _golden_scripted(self, mod, x, rtol=1e-5, atol=1e-6):
        with torch.no_grad():
            ref = mod(x).numpy()
        sd, model, phs, outs = self._import_scripted(mod, x)
        got = np.asarray(sd.output({phs[0]: x.numpy()},
                                   outs)[outs[0]])
        np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)
        return model

    def test_if_taken_and_not_taken(self):
        torch.manual_seed(0)
        m = self._golden_scripted(ScriptedIf(),
                                  torch.abs(torch.randn(2, 3)))
        assert any(n.op_type == "If" for n in m.graph.nodes)
        self._golden_scripted(ScriptedIf(),
                              -torch.abs(torch.randn(2, 3)))

    def test_counted_while(self):
        torch.manual_seed(1)
        m = self._golden_scripted(ScriptedWhile(), torch.randn(2, 3))
        assert any(n.op_type == "Loop" for n in m.graph.nodes)

    def test_condition_driven_while(self):
        torch.manual_seed(2)
        self._golden_scripted(ScriptedCondWhile(),
                              torch.abs(torch.randn(2, 3)))

    def test_if_nested_in_loop(self):
        torch.manual_seed(3)
        self._golden_scripted(ScriptedLoopIf(), torch.randn(2, 3))
        self._golden_scripted(ScriptedLoopIf(), -torch.randn(2, 3).abs())

    def test_counted_while_is_trainable(self):
        """The torch `while i < N` export (Loop with INT64_MAX trip
        count + carried cond recomputed in the body) derives a static
        bound and trains: gradients through the imported loop match
        torch autograd (round-3 verdict's missing #1)."""
        import jax
        import jax.numpy as jnp

        torch.manual_seed(4)
        x = torch.randn(2, 3)
        sd, model, phs, outs = self._import_scripted(ScriptedWhile(), x)
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] == 5

        xt = x.clone().requires_grad_(True)
        (ScriptedWhile()(xt) ** 2).sum().backward()
        ref_gx = xt.grad.numpy()

        fn = sd._build_fn((outs[0],))
        arrays = dict(sd._arrays)
        gx = jax.grad(
            lambda xv: jnp.sum(fn(arrays, {phs[0]: xv})[outs[0]] ** 2)
        )(jnp.asarray(x.numpy()))
        np.testing.assert_allclose(np.asarray(gx), ref_gx,
                                   rtol=1e-4, atol=1e-5)

    def test_condition_driven_while_stays_inference_only(self):
        """A genuinely dynamic loop (data-dependent termination) keeps
        the lax.while_loop lowering; the grad path fails with the
        framework's loud inference-only message, not a raw JAX error."""
        torch.manual_seed(5)
        x = torch.abs(torch.randn(2, 3))
        sd, model, phs, outs = self._import_scripted(
            ScriptedCondWhileWeighted(), x)
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] is None
        # forward still matches torch (inference works)
        with torch.no_grad():
            ref = ScriptedCondWhileWeighted()(x).numpy()
        got = np.asarray(sd.output({phs[0]: x.numpy()}, outs)[outs[0]])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        # promote the float weight captured INTO the loop body: grads
        # must flow into the loop's carried float state -> documented
        # inference-only error (not raw JAX's transpose failure)
        loss = sd._op("reduce_sum", [outs[0]])
        sd.setLossVariables(loss.name)
        w_name = next(
            v.name for v in sd.variables()
            if v.vtype.value == "CONSTANT"
            and np.asarray(sd.getVariable(v.name).getArr()).shape
            == (3,))
        sd.convertConstantsToVariables(w_name)
        with pytest.raises(ValueError, match="inference-only"):
            sd.calculateGradients({phs[0]: x.numpy()})

    def test_loop_if_nested_trainable(self):
        """Counter-bounded loop with an If inside: grads flow through
        the masked scan + lax.cond composition and match torch."""
        import jax
        import jax.numpy as jnp

        torch.manual_seed(6)
        x = torch.randn(2, 3)
        sd, model, phs, outs = self._import_scripted(ScriptedLoopIf(), x)
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] == 3

        xt = x.clone().requires_grad_(True)
        (ScriptedLoopIf()(xt) ** 2).sum().backward()
        ref_gx = xt.grad.numpy()

        fn = sd._build_fn((outs[0],))
        arrays = dict(sd._arrays)
        gx = jax.grad(
            lambda xv: jnp.sum(fn(arrays, {phs[0]: xv})[outs[0]] ** 2)
        )(jnp.asarray(x.numpy()))
        np.testing.assert_allclose(np.asarray(gx), ref_gx,
                                   rtol=1e-4, atol=1e-5)

    def test_control_flow_survives_serde(self, tmp_path):
        """Nested If-in-Loop save/load round trip: the sub-graph dicts
        (branches, bodies, captures) must serialize with the graph."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        torch.manual_seed(9)
        mod = ScriptedLoopIf()
        x = torch.randn(2, 3)
        with torch.no_grad():
            ref = mod(x).numpy()
        sd, model, phs, outs = self._import_scripted(mod, x)
        p = str(tmp_path / "cf.sdnb")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got = np.asarray(sd2.output({phs[0]: x.numpy()},
                                    outs)[outs[0]])
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
