"""TF frozen-graph import tests (reference model: TFGraphTestAllSameDiff
— run frozen TF graphs through import+exec and compare against TF's own
outputs; SURVEY.md §4 golden tests)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tensorflow import (OpMappingRegistry,
                                                       TFGraphMapper)
from deeplearning4j_tpu.modelimport.tensorflow.tf_import import TFImportError


def _freeze(fn, *specs):
    """tf.function → frozen GraphDef with variables folded to consts."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    return gd, in_names, out_names, frozen


def _run_both(fn, feeds_np, rtol=1e-4, atol=1e-5):
    specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype)) for v in feeds_np]
    gd, in_names, out_names, frozen = _freeze(fn, *specs)
    ref = frozen(*[tf.constant(v) for v in feeds_np])
    ref = [np.asarray(r) for r in (ref if isinstance(ref, (list, tuple))
                                   else [ref])]
    sd = TFGraphMapper.importGraph(gd)
    feeds = dict(zip(in_names, feeds_np))
    outs = sd.output(feeds, out_names)
    got = [np.asarray(outs[n]) for n in out_names]
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=rtol, atol=atol)
    return sd


class TestBasicGraphs:
    def test_mlp(self):
        w1 = tf.Variable(np.random.default_rng(0).normal(
            size=(6, 8)).astype(np.float32))
        b1 = tf.Variable(np.zeros(8, np.float32))
        w2 = tf.Variable(np.random.default_rng(1).normal(
            size=(8, 3)).astype(np.float32))

        def mlp(x):
            h = tf.nn.relu(tf.matmul(x, w1) + b1)
            return tf.nn.softmax(tf.matmul(h, w2))

        x = np.random.default_rng(2).normal(size=(4, 6)).astype(np.float32)
        _run_both(mlp, [x])

    def test_math_reductions_shapes(self):
        def f(x):
            y = tf.reshape(x, [-1, 6])
            z = tf.transpose(y, [1, 0])
            m = tf.reduce_mean(z, axis=1, keepdims=True)
            v = tf.reduce_sum(tf.square(z - m), axis=[1])
            return tf.sqrt(v + 1e-6)

        x = np.random.default_rng(3).normal(size=(4, 3, 2)) \
            .astype(np.float32)
        _run_both(f, [x])

    def test_concat_split_pad_slice(self):
        def f(x):
            a, b = tf.split(x, 2, axis=1)
            c = tf.concat([b, a], axis=1)
            p = tf.pad(c, [[0, 0], [1, 1]])
            return tf.strided_slice(p, [0, 1], [4, 7], [1, 1])

        x = np.random.default_rng(4).normal(size=(4, 6)).astype(np.float32)
        _run_both(f, [x])

    def test_conv_pool(self):
        k = tf.Variable(np.random.default_rng(5).normal(
            size=(3, 3, 2, 4)).astype(np.float32) * 0.3)

        def f(x):
            h = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
            h = tf.nn.relu(h)
            return tf.nn.max_pool2d(h, 2, 2, "VALID")

        x = np.random.default_rng(6).normal(size=(2, 8, 8, 2)) \
            .astype(np.float32)
        _run_both(f, [x], rtol=1e-3, atol=1e-4)

    def test_explicit_padding_conv(self):
        """TF EXPLICIT (per-edge asymmetric) conv padding — previously
        a loud-error corner (VERDICT r3 missing #3)."""
        k = tf.constant(np.random.default_rng(20).normal(
            size=(3, 3, 2, 4)).astype(np.float32) * 0.3)
        kd = tf.constant(np.random.default_rng(21).normal(
            size=(2, 2, 2, 1)).astype(np.float32) * 0.3)

        def f(x):
            h = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1],
                             padding=[[0, 0], [1, 2], [0, 3], [0, 0]])
            g = tf.nn.depthwise_conv2d(
                x, kd, strides=[1, 1, 1, 1],
                padding=[[0, 0], [2, 0], [1, 1], [0, 0]])
            # dilated depthwise: the 'dilations' attr must be honored,
            # not silently dropped
            d = tf.nn.depthwise_conv2d(
                x, kd, strides=[1, 1, 1, 1], padding="SAME",
                dilations=[2, 2])
            return h, g, d

        x = np.random.default_rng(22).normal(size=(2, 6, 6, 2)) \
            .astype(np.float32)
        _run_both(f, [x], rtol=1e-3, atol=1e-4)

    def test_bincount_binary_output(self):
        def f(x):
            counts = tf.raw_ops.DenseBincount(
                input=x, size=8, weights=tf.zeros([0], tf.int32),
                binary_output=False)
            present = tf.raw_ops.DenseBincount(
                input=x, size=8, weights=tf.zeros([0], tf.int32),
                binary_output=True)
            return counts, present

        x = np.asarray([0, 2, 2, 5, 5, 5, 9], np.int32)
        _run_both(f, [x])

    def test_nchw_conv_stack_golden(self):
        """NCHW graphs (VERDICT r3 item #9): the importer wraps each
        NCHW node in an NCHW->NHWC->NCHW transpose sandwich. TF's CPU
        kernels can't EXECUTE NCHW convs, but freezing only traces —
        so the golden freezes the NCHW graph and uses the executed
        NHWC twin (same weights) as the oracle."""
        rng = np.random.default_rng(9)
        k = tf.constant(rng.normal(size=(3, 3, 2, 4))
                        .astype(np.float32) * 0.3)
        kd = tf.constant(rng.normal(size=(3, 3, 4, 1))
                         .astype(np.float32) * 0.3)
        bias = tf.constant(rng.normal(size=(4,)).astype(np.float32))
        gamma = tf.constant(rng.normal(size=(4,)).astype(np.float32))
        beta = tf.constant(rng.normal(size=(4,)).astype(np.float32))
        mean = tf.constant(rng.normal(size=(4,)).astype(np.float32))
        var = tf.constant(rng.uniform(0.5, 2.0, (4,))
                          .astype(np.float32))

        def f_nchw(x):
            h = tf.nn.conv2d(x, k, strides=[1, 1, 2, 2], padding="SAME",
                             data_format="NCHW")
            h = tf.nn.bias_add(h, bias, data_format="NC..")
            h, _, _ = tf.raw_ops.FusedBatchNormV3(
                x=h, scale=gamma, offset=beta, mean=mean, variance=var,
                is_training=False, data_format="NCHW")[:3]
            h = tf.nn.relu(h)
            h = tf.nn.max_pool2d(h, 2, 2, "VALID", data_format="NCHW")
            h = tf.nn.depthwise_conv2d(
                h, kd, strides=[1, 1, 1, 1], padding="SAME",
                data_format="NCHW")
            return tf.nn.avg_pool2d(h, 2, 1, "VALID",
                                    data_format="NCHW")

        def f_nhwc(x):
            h = tf.nn.conv2d(x, k, strides=[1, 2, 2, 1], padding="SAME")
            h = tf.nn.bias_add(h, bias)
            h, _, _ = tf.raw_ops.FusedBatchNormV3(
                x=h, scale=gamma, offset=beta, mean=mean, variance=var,
                is_training=False, data_format="NHWC")[:3]
            h = tf.nn.relu(h)
            h = tf.nn.max_pool2d(h, 2, 2, "VALID")
            h = tf.nn.depthwise_conv2d(h, kd, strides=[1, 1, 1, 1],
                                       padding="SAME")
            return tf.nn.avg_pool2d(h, 2, 1, "VALID")

        x = rng.normal(size=(2, 2, 12, 12)).astype(np.float32)  # NCHW
        gd, in_names, out_names, _ = _freeze(
            f_nchw, tf.TensorSpec(x.shape, tf.float32))
        ref = np.transpose(
            np.asarray(f_nhwc(tf.constant(np.transpose(x, (0, 2, 3, 1))))),
            (0, 3, 1, 2))
        sd = TFGraphMapper.importGraph(gd)
        outs = sd.output(dict(zip(in_names, [x])), out_names)
        np.testing.assert_allclose(np.asarray(outs[out_names[0]]), ref,
                                   rtol=1e-3, atol=1e-4)

    def test_gather_onehot_argmax_cast(self):
        table = tf.Variable(np.random.default_rng(7).normal(
            size=(10, 4)).astype(np.float32))

        def f(ids):
            e = tf.gather(table, ids)
            a = tf.argmax(e, axis=-1)
            oh = tf.one_hot(a, 4)
            return tf.cast(oh, tf.float32) + e

        ids = np.random.default_rng(8).integers(0, 10, (3, 5)) \
            .astype(np.int32)
        _run_both(f, [ids])

    def test_attention_block(self):
        """The BERT-ish op set: batched matmul, softmax, transpose,
        reshape, layer-norm decomposition."""
        rng = np.random.default_rng(9)
        d, h = 8, 2
        wq = tf.Variable(rng.normal(size=(d, d)).astype(np.float32) * 0.3)
        wk = tf.Variable(rng.normal(size=(d, d)).astype(np.float32) * 0.3)
        wv = tf.Variable(rng.normal(size=(d, d)).astype(np.float32) * 0.3)
        g = tf.Variable(np.ones(d, np.float32))
        b = tf.Variable(np.zeros(d, np.float32))

        def f(x):
            n, t = tf.shape(x)[0], tf.shape(x)[1]
            q = tf.reshape(x @ wq, [-1, 4, h, d // h])
            kk = tf.reshape(x @ wk, [-1, 4, h, d // h])
            v = tf.reshape(x @ wv, [-1, 4, h, d // h])
            q = tf.transpose(q, [0, 2, 1, 3])
            kk = tf.transpose(kk, [0, 2, 1, 3])
            v = tf.transpose(v, [0, 2, 1, 3])
            att = tf.nn.softmax(
                tf.matmul(q, kk, transpose_b=True) / np.sqrt(d // h))
            o = tf.transpose(tf.matmul(att, v), [0, 2, 1, 3])
            o = tf.reshape(o, [-1, 4, d])
            # layer norm decomposed
            mu = tf.reduce_mean(o, axis=-1, keepdims=True)
            var = tf.reduce_mean(tf.math.squared_difference(o, mu),
                                 axis=-1, keepdims=True)
            return (o - mu) * tf.math.rsqrt(var + 1e-6) * g + b

        x = rng.normal(size=(2, 4, d)).astype(np.float32)
        _run_both(f, [x], rtol=1e-3, atol=1e-4)

    def test_keras_cnn_frozen(self):
        keras = tf.keras
        m = keras.Sequential([
            keras.layers.Input((8, 8, 1)),
            keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
            keras.layers.BatchNormalization(),
            keras.layers.MaxPooling2D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(3, activation="softmax"),
        ])
        x = np.random.default_rng(10).normal(size=(2, 8, 8, 1)) \
            .astype(np.float32)
        _run_both(lambda t: m(t, training=False), [x],
                  rtol=1e-3, atol=1e-4)


class TestImportSemantics:
    def test_fine_tune_imported_graph(self):
        w = tf.Variable(np.random.default_rng(0).normal(
            size=(4, 2)).astype(np.float32))

        def f(x):
            return tf.matmul(x, w)

        gd, in_names, out_names, _ = _freeze(
            f, tf.TensorSpec([None, 4], tf.float32))
        sd = TFGraphMapper.importGraph(gd)
        # promote the frozen weight const to a trainable variable
        consts = [v.name for v in sd.variables()
                  if v.vtype.value == "CONSTANT"
                  and sd._arrays[v.name].ndim == 2]
        assert len(consts) == 1
        sd.convertConstantsToVariables(consts[0])

        import jax.numpy as jnp
        out = sd.getVariable(out_names[0])
        y = sd.placeholder("y_target", shape=(None, 2))
        loss = ((out - y) * (out - y)).mean()
        sd.setLossVariables(loss.name)
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.learning.updaters import Sgd
        sd.setTrainingConfig(TrainingConfig(
            updater=Sgd(0.1), data_set_feature_mapping=[in_names[0]],
            data_set_label_mapping=["y_target"]))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.default_rng(1)
        xs = rng.normal(size=(32, 4)).astype(np.float32)
        ys = np.zeros((32, 2), np.float32)
        hist = sd.fit(DataSet(xs, ys), epochs=30)
        assert hist.loss_curve[-1] < hist.loss_curve[0] * 0.1

    def test_promote_after_fit_resets_updater_state(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.learning.updaters import Adam

        sd = SameDiff()
        x = sd.placeholder("x", shape=(None, 3))
        w = sd.var("w", np.zeros((3, 2), np.float32))
        c = sd.constant("c", np.ones((2,), np.float32))
        out = x @ w + c
        y = sd.placeholder("y", shape=(None, 2))
        loss = ((out - y) * (out - y)).mean()
        sd.setLossVariables(loss.name)
        sd.setTrainingConfig(TrainingConfig(
            updater=Adam(0.01), data_set_feature_mapping=["x"],
            data_set_label_mapping=["y"]))
        ds = DataSet(np.ones((4, 3), np.float32), np.ones((4, 2), np.float32))
        sd.fit(ds, epochs=1)
        sd.convertConstantsToVariables("c")
        sd.fit(ds, epochs=1)  # must not crash on stale updater slots
        assert "c" in sd.trainable_names()

    def test_promotion_is_atomic(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd = SameDiff()
        sd.constant("c", np.ones(2))
        sd.placeholder("p", shape=(2,))
        with pytest.raises(ValueError):
            sd.convertConstantsToVariables("c", "p")
        assert sd.getVariable("c").vtype.value == "CONSTANT"

    def test_unknown_op_fails_loudly(self):
        def f(x):
            # MatrixSquareRoot has no mapper (Betainc, the previous
            # example, gained one in round 3)
            return tf.raw_ops.MatrixSquareRoot(input=x)

        gd, *_ = _freeze(f, tf.TensorSpec([3, 3], tf.float32))
        with pytest.raises(TFImportError, match="no mapper"):
            TFGraphMapper.importGraph(gd)

    def test_coverage_listing(self):
        cov = OpMappingRegistry.coverage()
        assert len(cov) > 80
        for op in ["MatMul", "Conv2D", "FusedBatchNormV3", "Softmax",
                   "StridedSlice", "GatherV2"]:
            assert op in cov


class TestReviewRegressions:
    """Regressions for import-mapper bugs found in code review."""

    def test_strided_slice_last_element(self):
        # x[-1] / x[:, -1]: shrink_axis with begin=-1 must take the last
        # element, not an empty slice
        def f(x):
            return x[-1] + x[:, -1][0]

        x = np.random.default_rng(5).normal(size=(3, 4)).astype(np.float32)
        _run_both(f, [x])

    def test_padv2_constant_values(self):
        def f(x):
            return tf.pad(x, [[1, 1], [0, 2]], constant_values=-3.5)

        x = np.random.default_rng(6).normal(size=(2, 3)).astype(np.float32)
        _run_both(f, [x])

    def test_one_hot_on_off_axis(self):
        def f(x):
            idx = tf.cast(tf.argmax(x, axis=-1), tf.int32)
            a = tf.one_hot(idx, 5, on_value=2.0, off_value=-1.0)
            b = tf.one_hot(idx, 5, axis=0)
            return a + tf.transpose(b)

        x = np.random.default_rng(7).normal(size=(4, 5)).astype(np.float32)
        _run_both(f, [x])

    def test_addn_single_input(self):
        def f(x):
            return tf.raw_ops.AddN(inputs=[x])

        x = np.random.default_rng(8).normal(size=(3, 2)).astype(np.float32)
        _run_both(f, [x])

    def test_explicit_padding_conv_matches_tf(self):
        # was a loud-rejection regression test; EXPLICIT per-edge conv
        # padding is now SUPPORTED (round-4 mapper), so the regression
        # to guard is golden parity, not the error message
        w = np.random.default_rng(9).normal(size=(3, 3, 1, 2)) \
            .astype(np.float32)

        def f(x):
            return tf.raw_ops.Conv2D(
                input=x, filter=tf.constant(w), strides=[1, 1, 1, 1],
                padding="EXPLICIT",
                explicit_paddings=[0, 0, 1, 1, 1, 1, 0, 0])

        x = np.random.default_rng(10).normal(size=(1, 5, 5, 1)) \
            .astype(np.float32)
        _run_both(f, [x])


class TestBertMiniEndToEnd:
    """The SURVEY §3.4 headline path: a COMPLETE (mini) BERT encoder —
    token+position embeddings, N transformer blocks (MHA + LayerNorm +
    GELU FFN + residuals), MLM logits head — frozen in TF, imported
    node-by-node into SameDiff, golden-compared against TF, then
    FINE-TUNED as one jit-compiled step (reference:
    samediff-import-tensorflow + SameDiff.fit)."""

    def _build_bert(self, rng, vocab=50, max_len=16, d=16, heads=2,
                    layers=2, ff=32):
        W = lambda *s, scale=0.3: tf.Variable(
            rng.normal(size=s).astype(np.float32) * scale)
        p = {
            "tok": W(vocab, d), "pos": W(max_len, d),
        }
        for i in range(layers):
            p[f"l{i}"] = {
                "wq": W(d, d), "wk": W(d, d), "wv": W(d, d), "wo": W(d, d),
                "g1": tf.Variable(np.ones(d, np.float32)),
                "b1": tf.Variable(np.zeros(d, np.float32)),
                "w_ff1": W(d, ff), "b_ff1": tf.Variable(np.zeros(ff, np.float32)),
                "w_ff2": W(ff, d), "b_ff2": tf.Variable(np.zeros(d, np.float32)),
                "g2": tf.Variable(np.ones(d, np.float32)),
                "b2": tf.Variable(np.zeros(d, np.float32)),
            }
        dh = d // heads

        def ln(x, g, b):
            mu = tf.reduce_mean(x, axis=-1, keepdims=True)
            var = tf.reduce_mean(tf.math.squared_difference(x, mu),
                                 axis=-1, keepdims=True)
            return (x - mu) * tf.math.rsqrt(var + 1e-6) * g + b

        def model(ids):
            h = (tf.gather(p["tok"], ids)
                 + tf.gather(p["pos"], tf.range(max_len)))
            for i in range(layers):
                lp = p[f"l{i}"]
                q = tf.reshape(h @ lp["wq"], [-1, max_len, heads, dh])
                k = tf.reshape(h @ lp["wk"], [-1, max_len, heads, dh])
                v = tf.reshape(h @ lp["wv"], [-1, max_len, heads, dh])
                q = tf.transpose(q, [0, 2, 1, 3])
                k = tf.transpose(k, [0, 2, 1, 3])
                v = tf.transpose(v, [0, 2, 1, 3])
                att = tf.nn.softmax(
                    tf.matmul(q, k, transpose_b=True) / np.sqrt(dh))
                o = tf.transpose(tf.matmul(att, v), [0, 2, 1, 3])
                o = tf.reshape(o, [-1, max_len, d]) @ lp["wo"]
                h = ln(h + o, lp["g1"], lp["b1"])
                ffn = tf.nn.gelu(h @ lp["w_ff1"] + lp["b_ff1"]) \
                    @ lp["w_ff2"] + lp["b_ff2"]
                h = ln(h + ffn, lp["g2"], lp["b2"])
            # MLM logits: tied embedding projection
            return tf.matmul(h, p["tok"], transpose_b=True)

        return model

    def test_golden_and_finetune(self):
        rng = np.random.default_rng(0)
        vocab, max_len = 50, 16
        model = self._build_bert(rng, vocab=vocab, max_len=max_len)
        ids = rng.integers(0, vocab, (4, max_len)).astype(np.int32)

        gd, in_names, out_names, frozen = _freeze(
            model, tf.TensorSpec([None, max_len], tf.int32))
        ref = frozen(tf.constant(ids))
        ref = np.asarray(ref[0] if isinstance(ref, (list, tuple)) else ref)
        sd = TFGraphMapper.importGraph(gd)
        got = np.asarray(sd.output({in_names[0]: ids},
                                   out_names)[out_names[0]])
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-4)

        # ---- fine-tune the imported graph (reference: BERT path) ----
        for v in sd.variables():
            if v.vtype.value == "CONSTANT" and \
                    sd._arrays[v.name].ndim == 2 and \
                    sd._arrays[v.name].dtype.kind == "f":
                sd.convertConstantsToVariables(v.name)
        assert sd.trainable_names(), "no trainables promoted"

        out = sd.getVariable(out_names[0])
        y = sd.placeholder("y_ids", shape=(None, max_len))
        # per-token CE against target ids via one-hot (mean)
        import jax.numpy as jnp
        oh = sd.math.one_hot(y, depth=vocab)  # depth is a static attr
        logp = sd.nn.log_softmax(out)
        loss = -(oh * logp).sum(-1).mean()
        sd.setLossVariables(loss.name)

        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.learning.updaters import Adam
        sd.setTrainingConfig(TrainingConfig(
            updater=Adam(1e-2), data_set_feature_mapping=[in_names[0]],
            data_set_label_mapping=["y_ids"]))
        targets = rng.integers(0, vocab, (4, max_len)).astype(np.int32)
        hist = sd.fit(DataSet(ids, targets), epochs=25)
        assert hist.loss_curve[-1] < hist.loss_curve[0] * 0.7, \
            hist.loss_curve[:3] + hist.loss_curve[-3:]


class TestRealBertBaseImport:
    """VERDICT r1 #4: import a REAL full-size BERT-base frozen GraphDef
    (HuggingFace TFBertForMaskedLM, randomly initialized locally — no
    egress), not a hand-built mini. Exercises the true node set
    (~3000 nodes: dynamic-shape subgraphs Shape->StridedSlice->Pack/
    Prod->Reshape with literal -1 + dynamic batch, Einsum-free Keras
    path, Erfc gelu, Assert/string-const dropping) through
    ImportGraph-equivalent mapping (SURVEY.md §3.4)."""

    @staticmethod
    def _freeze_hf_bert(cfg, seq):
        transformers = pytest.importorskip("transformers")
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        m = transformers.TFBertForMaskedLM(cfg)

        @tf.function
        def f(ids, mask, tt):
            return m(input_ids=ids, attention_mask=mask,
                     token_type_ids=tt, training=False).logits

        spec = [tf.TensorSpec([None, seq], tf.int32)] * 3
        frozen = convert_variables_to_constants_v2(
            f.get_concrete_function(*spec))
        gd = frozen.graph.as_graph_def()
        ins = [t.name.split(":")[0] for t in frozen.inputs]
        out = frozen.outputs[0].name.split(":")[0]
        return gd, ins, out, frozen

    def test_full_bert_base_golden(self):
        from transformers import BertConfig

        cfg = BertConfig()  # true bert-base: 12L/768H/12A, vocab 30522
        seq = 128
        gd, ins, out, frozen = self._freeze_hf_bert(cfg, seq)
        assert len(gd.node) > 2500  # real node set, not a mini
        sd = TFGraphMapper.importGraph(gd)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (2, seq)).astype(np.int32)
        mask = np.ones((2, seq), np.int32)
        tt = np.zeros((2, seq), np.int32)
        ref = np.asarray(frozen(tf.constant(ids), tf.constant(mask),
                                tf.constant(tt))[0])
        got = np.asarray(sd.output(dict(zip(ins, [ids, mask, tt])),
                                   [out])[out])
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-4)

    def test_real_node_set_finetune(self):
        """Same real HF node structure at a small width: promote the
        frozen weights to variables and run the whole-graph-jit train
        loop (reference: SameDiff.fit on imported BERT)."""
        from transformers import BertConfig

        cfg = BertConfig(num_hidden_layers=2, hidden_size=32,
                         num_attention_heads=2, intermediate_size=64,
                         vocab_size=100, max_position_embeddings=32)
        seq = 16
        gd, ins, out, _ = self._freeze_hf_bert(cfg, seq)
        sd = TFGraphMapper.importGraph(gd)

        for v in list(sd.variables()):
            if v.vtype.value == "CONSTANT" and v.name in sd._arrays and \
                    sd._arrays[v.name].ndim >= 2 and \
                    np.asarray(sd._arrays[v.name]).dtype.kind == "f":
                sd.convertConstantsToVariables(v.name)
        assert sd.trainable_names()

        rng = np.random.default_rng(1)
        ids = rng.integers(0, 100, (4, seq)).astype(np.int32)
        y = sd.placeholder("y_ids", shape=(None, seq))
        oh = sd.math.one_hot(y, depth=100)
        logp = sd.nn.log_softmax(sd.getVariable(out))
        loss = -(oh * logp).sum(-1).mean()
        sd.setLossVariables(loss.name)

        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.learning.updaters import Adam
        from deeplearning4j_tpu.datasets.multi_dataset import MultiDataSet

        sd.setTrainingConfig(TrainingConfig(
            updater=Adam(1e-2),
            data_set_feature_mapping=list(ins),
            data_set_label_mapping=["y_ids"]))
        targets = rng.integers(0, 100, (4, seq)).astype(np.int32)
        mds = MultiDataSet(
            [ids, np.ones((4, seq), np.int32),
             np.zeros((4, seq), np.int32)], [targets])
        hist = sd.fit(mds, epochs=20)
        assert hist.loss_curve[-1] < hist.loss_curve[0] * 0.7


# ---------------------------------------------------------------------
# Golden battery (reference: TFGraphTestAllSameDiff — hundreds of
# frozen graphs imported and compared node-by-node against stored TF
# outputs, SURVEY.md §4; here the TF outputs are computed live).
# ---------------------------------------------------------------------
_RNG = np.random.default_rng(42)
_F44 = _RNG.normal(size=(4, 4)).astype(np.float32)
_F34 = _RNG.normal(size=(3, 4)).astype(np.float32)
_P44 = _RNG.uniform(0.2, 2.0, (4, 4)).astype(np.float32)
_I4 = np.asarray([2, 0, 3, 1], np.int32)
_IMG = _RNG.normal(size=(2, 8, 8, 3)).astype(np.float32)

BATTERY = {
    "abs_neg_sign": (lambda a: tf.abs(a) + tf.sign(a) - tf.negative(a),
                     [_F44]),
    "exp_log_sqrt": (lambda a: tf.exp(tf.math.log(a)) + tf.sqrt(a),
                     [_P44]),
    "rsqrt_square": (lambda a: tf.math.rsqrt(a) * tf.square(a), [_P44]),
    "floor_ceil_round": (lambda a: tf.floor(a) + tf.math.ceil(a)
                         + tf.round(a), [_F44 * 3]),
    "pow_maximum_minimum": (lambda a, b: tf.pow(a, 2.0)
                            + tf.maximum(a, b) - tf.minimum(a, b),
                            [_P44, _P44.T.copy()]),
    "floordiv_mod": (lambda a, b: tf.math.floordiv(a, b)
                     + tf.math.mod(a, b), [_F44 * 5, _P44]),
    "trig": (lambda a: tf.sin(a) + tf.cos(a) + tf.tan(a * 0.3), [_F44]),
    "hyperbolic": (lambda a: tf.sinh(a) + tf.cosh(a) + tf.tanh(a),
                   [_F44 * 0.5]),
    "erf_gelu_chain": (lambda a: tf.nn.gelu(a) + tf.math.erf(a), [_F44]),
    "sigmoid_softplus_softsign": (
        lambda a: tf.sigmoid(a) + tf.math.softplus(a)
        + tf.math.softsign(a), [_F44]),
    "elu_selu_relu6": (lambda a: tf.nn.elu(a) + tf.nn.selu(a)
                       + tf.nn.relu6(a), [_F44 * 2]),
    "leaky_softmax_logsoftmax": (
        lambda a: tf.nn.leaky_relu(a, 0.3)
        + tf.nn.softmax(a) + tf.nn.log_softmax(a), [_F44]),
    "reduce_family": (
        lambda a: tf.reduce_sum(a, 1) + tf.reduce_mean(a, 1)
        + tf.reduce_max(a, 1) + tf.reduce_min(a, 1)
        + tf.reduce_prod(a * 0.5, 1), [_P44]),
    "argmax_cast": (lambda a: tf.cast(tf.argmax(a, axis=1), tf.float32),
                    [_F44]),
    "comparisons_where": (
        lambda a, b: tf.where(tf.greater(a, b), a, b)
        + tf.cast(tf.less_equal(a, b), tf.float32), [_F44, _F44.T.copy()]),
    "logical_ops": (
        lambda a, b: tf.cast(
            tf.logical_and(a > 0, b > 0) | tf.logical_not(a > 0),
            tf.float32), [_F44, _F44.T.copy()]),
    "concat_split_stack": (
        lambda a, b: tf.stack(tf.split(tf.concat([a, b], 1), 2, axis=1),
                              axis=0), [_F34, _F34]),
    "unstack_tile": (
        lambda a: tf.tile(tf.unstack(a, axis=0)[1][None], [2, 1]),
        [_F34]),
    "pad_padv2": (
        lambda a: tf.pad(a, [[1, 0], [0, 2]])
        + tf.pad(a, [[1, 0], [0, 2]], constant_values=0.0), [_F34]),
    "slice_strided": (
        lambda a: tf.slice(a, [1, 0], [2, 3]) + a[1:3, :3], [_F44]),
    "strided_negative_step": (lambda a: a[::-1, 1:], [_F44]),
    "transpose_expand_squeeze": (
        lambda a: tf.squeeze(tf.expand_dims(tf.transpose(a), 0), 0),
        [_F34]),
    "reshape_flatten": (
        lambda a: tf.reshape(a, [-1]) , [_F34]),
    "gather_onehot": (
        lambda a, i: tf.gather(a, i)
        + tf.one_hot(i, 4, dtype=tf.float32), [_F44, _I4]),
    "matmul_transposed": (
        lambda a, b: tf.matmul(a, b, transpose_b=True), [_F34, _F34]),
    "batch_matmul": (
        lambda a: tf.matmul(tf.stack([a, a]),
                            tf.stack([tf.transpose(a),
                                      tf.transpose(a)])), [_F34]),
    "bias_add": (lambda a: tf.nn.bias_add(a, tf.constant(
        [1.0, 2.0, 3.0, 4.0])), [_F44]),
    "addn": (lambda a, b: tf.add_n([a, b, a]), [_F44, _F44]),
    "squared_difference_div": (
        lambda a, b: tf.math.squared_difference(a, b)
        + tf.math.divide(a, b), [_F44, _P44]),
    "range_fill": (
        lambda a: a + tf.fill([4, 4], 2.0)
        + tf.cast(tf.range(0, 4, 1), tf.float32)[None], [_F44]),
    "conv_relu_pool": (
        lambda x: tf.nn.max_pool2d(
            tf.nn.relu(tf.nn.conv2d(
                x, tf.constant(_RNG.normal(size=(3, 3, 3, 4))
                               .astype(np.float32) * 0.2),
                strides=1, padding="SAME")), 2, 2, "VALID"), [_IMG]),
    "depthwise_avgpool": (
        lambda x: tf.nn.avg_pool2d(
            tf.nn.depthwise_conv2d(
                x, tf.constant(_RNG.normal(size=(3, 3, 3, 2))
                               .astype(np.float32) * 0.2),
                strides=[1, 1, 1, 1], padding="SAME"), 2, 2, "VALID"),
        [_IMG]),
    "stop_gradient_identity": (
        lambda a: tf.stop_gradient(a) + tf.identity(a), [_F44]),
    "clipping": (lambda a: tf.clip_by_value(a, -0.5, 0.5), [_F44]),
    "select_v2_broadcast": (
        lambda a: tf.where(a > 0, a, tf.zeros_like(a)), [_F44]),
    "sci_funcs": (
        lambda a: tf.math.lgamma(a) + tf.math.digamma(a)
        + tf.math.igamma(a, a) + tf.math.zeta(a + 2.0, a), [_P44 + 1.0]),
    "atan_family": (
        lambda a, b: tf.atan2(a, b) + tf.asin(a * 0.3)
        + tf.acos(b * 0.3) + tf.atan(a), [_F44, _P44]),
    "xlog_clip": (
        lambda a, b: tf.math.xlogy(a, b) + tf.math.xdivy(a, b)
        + tf.clip_by_value(a, -0.5, 0.5)
        + tf.math.divide_no_nan(a, b - b), [_P44, _P44]),
    "cumulative": (
        lambda a: tf.cumsum(a, axis=1) + tf.math.cumprod(
            a * 0.5, axis=0, exclusive=True, reverse=True), [_P44]),
    "topk_intopk": (
        lambda a: tf.cast(tf.nn.in_top_k(
            tf.constant([0, 2, 1, 3]), a, 2), tf.float32)
        + tf.reduce_sum(tf.math.top_k(a, k=3).values, -1), [_F44]),
    "reverse_ops": (
        lambda a: tf.reverse(a, [1])
        + tf.reverse_sequence(a, tf.constant([2, 4, 1, 3]),
                              seq_axis=1), [_F44]),
    "space_depth_roundtrip": (
        lambda x: tf.nn.depth_to_space(
            tf.nn.space_to_depth(x, 2), 2) + x, [_IMG]),
    "space_batch_nd": (
        lambda x: tf.batch_to_space(
            tf.space_to_batch(x, [2, 2], [[0, 0], [0, 0]]),
            [2, 2], [[0, 0], [0, 0]]), [_IMG]),
    "segment_ops": (
        lambda a: tf.math.segment_sum(a, tf.constant([0, 0, 1, 1]))
        + tf.math.unsorted_segment_max(
            a, tf.constant([1, 0, 1, 0]), 2), [_P44]),
    "linalg_band_inverse": (
        lambda a: tf.linalg.band_part(a, 1, 1)
        + tf.linalg.inv(a @ tf.transpose(a)
                        + 4.0 * tf.eye(4)), [_F44]),
    "diag_ops": (
        lambda a: tf.linalg.tensor_diag(a[0])
        + tf.linalg.tensor_diag_part(a), [_F44]),
    # (tf.math.bincount is NOT in the battery: DenseBincount's size
    # operand is max(values)+1 — a data-dependent output shape no
    # static-shape importer can honor; the mapper handles const-size
    # graphs only)
    "bitwise_ops": (
        lambda i: tf.bitwise.bitwise_and(i, 3)
        + tf.bitwise.left_shift(i, 1)
        + tf.bitwise.invert(i), [_I4]),
    "matrix_diag_eye": (
        lambda a: tf.matmul(a, tf.eye(4))
        + tf.linalg.diag(tf.linalg.diag_part(a)), [_F44]),
    "matrix_set_diag": (
        lambda a: tf.linalg.set_diag(a, tf.ones([4])), [_F44]),
}


class TestTFGoldenBattery:
    @pytest.mark.parametrize("name", sorted(BATTERY))
    def test_graph(self, name):
        fn, feeds = BATTERY[name]
        _run_both(fn, feeds, rtol=2e-4, atol=2e-5)


class TestImportedGraphSerde:
    """Imported graph -> SameDiff save/load round-trip (reference:
    SameDiff.save of an imported TF model incl. training state)."""

    def test_import_save_load_resume(self, tmp_path):
        w = tf.Variable(np.random.default_rng(3).normal(
            size=(6, 4)).astype(np.float32) * 0.4)

        def f(x):
            return tf.nn.log_softmax(tf.matmul(x, w))

        x = np.random.default_rng(4).normal(size=(5, 6)).astype(np.float32)
        gd, ins, outs, frozen = _freeze(
            f, tf.TensorSpec([None, 6], tf.float32))
        sd = TFGraphMapper.importGraph(gd)
        sd.convertConstantsToVariables(
            *[v.name for v in sd.variables()
              if v.vtype.value == "CONSTANT"
              and np.asarray(v.getArr()).ndim == 2])

        y = sd.placeholder("y", shape=(None,))
        oh = sd.math.one_hot(y, depth=4)
        loss = -(oh * sd.getVariable(outs[0])).sum(-1).mean()
        sd.setLossVariables(loss.name)
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.learning.updaters import Adam
        sd.setTrainingConfig(TrainingConfig(
            updater=Adam(5e-2), data_set_feature_mapping=[ins[0]],
            data_set_label_mapping=["y"]))
        labels = np.random.default_rng(5).integers(0, 4, 5) \
            .astype(np.int32)
        sd.fit(DataSet(x, labels), epochs=3)

        p = str(tmp_path / "imported.sdnb")
        sd.save(p, save_updater_state=True)
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd2 = SameDiff.load(p)

        # identical outputs after round-trip
        o1 = np.asarray(sd.output({ins[0]: x}, [outs[0]])[outs[0]])
        o2 = np.asarray(sd2.output({ins[0]: x}, [outs[0]])[outs[0]])
        np.testing.assert_allclose(o1, o2, rtol=1e-6, atol=1e-7)

        # training RESUMES with preserved updater state: losses keep
        # descending in both original and restored copies identically
        h1 = sd.fit(DataSet(x, labels), epochs=2)
        h2 = sd2.fit(DataSet(x, labels), epochs=2)
        np.testing.assert_allclose(h1.loss_curve, h2.loss_curve,
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------
# Control-flow golden graphs (reference: AbstractSession executes
# If/While/Enter/Exit/Merge at runtime, SURVEY.md §3.4; here the frames
# import into while_loop/if_cond ops and the WHOLE loop compiles into
# the one XLA executable). Each graph is checked in BOTH frozen forms:
# lower_control_flow=True (TF1 Switch/Merge/Enter/Exit/NextIteration
# frames) and =False (functional While/If + TensorList ops).
# ---------------------------------------------------------------------
def _while_counter_fn(a):
    return tf.while_loop(
        lambda i, acc: i < 5,
        lambda i, acc: (i + 1, acc + a * tf.cast(i, tf.float32)),
        [tf.constant(0), tf.zeros_like(a)])[1]


def _cond_fn(a):
    return tf.cond(tf.reduce_sum(a) > 0,
                   lambda: a * 2.0 + 1.0, lambda: a - 1.0)


def _nested_while_fn(a):
    def outer_body(i, acc):
        inner = tf.while_loop(
            lambda j, s: j < 3,
            lambda j, s: (j + 1, s + a * tf.cast(i + j, tf.float32)),
            [tf.constant(0), tf.zeros_like(a)])[1]
        return i + 1, acc + inner
    return tf.while_loop(lambda i, acc: i < 2, outer_body,
                         [tf.constant(0), tf.zeros_like(a)])[1]


def _case_fn(a):
    idx = tf.cast(tf.reduce_sum(a) > 0, tf.int32) + \
        tf.cast(tf.reduce_max(a) > 2.0, tf.int32)
    return tf.switch_case(idx, [lambda: a + 1.0, lambda: a * 2.0,
                                lambda: a - 3.0])


def _nested_case_fn(a):
    def outer0():
        return tf.switch_case(
            tf.cast(tf.reduce_max(a) > 1.0, tf.int32),
            [lambda: a + 10.0, lambda: a + 20.0])
    idx = tf.cast(tf.reduce_sum(a) > 0, tf.int32)
    return tf.switch_case(idx, [outer0, lambda: a * 5.0])


def _tensorarray_fn(a):
    ta = tf.TensorArray(tf.float32, size=4, element_shape=(4,))
    def body(i, ta):
        return i + 1, ta.write(i, a[:, i] * tf.cast(i + 1, tf.float32))
    _, ta = tf.while_loop(lambda i, ta: i < 4, body, [0, ta])
    return ta.stack()


def _run_both_cf(fn, feeds_np, lower, rtol=1e-4, atol=1e-5):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype))
             for v in feeds_np]
    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(
        conc, lower_control_flow=lower)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    ref = frozen(*[tf.constant(v) for v in feeds_np])
    ref = [np.asarray(r) for r in (ref if isinstance(ref, (list, tuple))
                                   else [ref])]
    sd = TFGraphMapper.importGraph(gd)
    outs = sd.output(dict(zip(in_names, feeds_np)), out_names)
    for n, r in zip(out_names, ref):
        np.testing.assert_allclose(np.asarray(outs[n]), r,
                                   rtol=rtol, atol=atol)
    return gd


CF_BATTERY = {
    "while_counter": (_while_counter_fn, [_F44]),
    "cond_taken": (_cond_fn, [np.abs(_F44)]),
    "cond_not_taken": (_cond_fn, [-np.abs(_F44)]),
    "nested_while": (_nested_while_fn, [_F44]),
    "while_tensorarray": (_tensorarray_fn, [_F44]),
    "switch_case": (_case_fn, [_F44]),
    "switch_case_branch2": (_case_fn, [np.abs(_F44) + 2.0]),
    "nested_case": (_nested_case_fn, [-np.abs(_F44)]),
    "nested_case_inner1": (_nested_case_fn, [-np.abs(_F44) * 0.1]),
}


class TestControlFlowGolden:
    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1_frames", "functional"])
    @pytest.mark.parametrize("name", sorted(CF_BATTERY))
    def test_graph(self, name, lower):
        fn, feeds = CF_BATTERY[name]
        _run_both_cf(fn, feeds, lower)

    def test_v1_frames_form_actually_contains_frames(self):
        """Guard the test premise: the lowered freeze really emits the
        TF1 frame ops the reference's AbstractSession handles."""
        gd = _run_both_cf(*CF_BATTERY["while_counter"], lower=True)
        ops = {n.op for n in gd.node}
        assert {"Enter", "Exit", "Merge", "Switch", "NextIteration",
                "LoopCond"} <= ops

    def test_functional_form_keeps_functions(self):
        gd = _run_both_cf(*CF_BATTERY["while_counter"], lower=False)
        assert any(n.op in ("While", "StatelessWhile") for n in gd.node)
        assert len(gd.library.function) >= 2

    def test_v1_session_graph_dynamic_rnn_style(self):
        """A raw tf.compat.v1 Graph + Session golden: time-major GRU
        recurrence driven by TensorArray read/write inside a while
        frame, frozen with the v1 graph_util path (the exact shape of
        a legacy frozen dynamic_rnn checkpoint)."""
        tf1 = tf.compat.v1
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 6, 5)).astype(np.float32)
        g = tf.Graph()
        with g.as_default():
            ph = tf1.placeholder(tf.float32, (2, 6, 5), name="x")
            Wz = tf1.get_variable(
                "Wz", (12, 7),
                initializer=tf1.initializers.glorot_uniform(seed=1))
            Wh = tf1.get_variable(
                "Wh", (12, 7),
                initializer=tf1.initializers.glorot_uniform(seed=2))
            xs = tf.transpose(ph, [1, 0, 2])
            in_ta = tf.TensorArray(tf.float32, size=6,
                                   element_shape=(2, 5)).unstack(xs)
            out_ta = tf.TensorArray(tf.float32, size=6,
                                    element_shape=(2, 7))

            def body(t, h, ta):
                xt = in_ta.read(t)
                cat = tf.concat([xt, h], 1)
                z = tf.sigmoid(tf.matmul(cat, Wz))
                hc = tf.tanh(tf.matmul(cat, Wh))
                h2 = (1.0 - z) * h + z * hc
                return t + 1, h2, ta.write(t, h2)

            _, hT, out_ta = tf1.while_loop(
                lambda t, h, ta: t < 6, body,
                [0, tf.zeros((2, 7)), out_ta])
            out = tf.identity(tf.transpose(out_ta.stack(), [1, 0, 2]),
                              name="rnn_out")
            hT = tf.identity(hT, name="h_final")
            with tf1.Session(graph=g) as sess:
                sess.run(tf1.global_variables_initializer())
                ref, ref_h = sess.run([out, hT], {ph: x})
                frozen = tf1.graph_util.convert_variables_to_constants(
                    sess, g.as_graph_def(), ["rnn_out", "h_final"])
        sd = TFGraphMapper.importGraph(frozen)
        res = sd.output({"x": x}, ["rnn_out", "h_final"])
        np.testing.assert_allclose(np.asarray(res["rnn_out"]), ref,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(res["h_final"]), ref_h,
                                   rtol=1e-4, atol=1e-5)

    def test_imported_dynamic_rnn_is_trainable(self):
        """Gradients flow THROUGH an imported TF1 while frame: the
        counter-bounded loop lowers to a differentiable masked scan
        (reference: createGradFunction covers control-flow internal ops
        under TrainingSession, SURVEY.md §2.12/§3.4 — round-3 verdict's
        missing #1). Reference grads come from an independent JAX
        implementation of the same recurrence."""
        import jax
        import jax.numpy as jnp

        tf1 = tf.compat.v1
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 6, 5)).astype(np.float32)
        g = tf.Graph()
        with g.as_default():
            ph = tf1.placeholder(tf.float32, (2, 6, 5), name="x")
            Wz = tf1.get_variable(
                "Wz", (12, 7),
                initializer=tf1.initializers.glorot_uniform(seed=1))
            Wh = tf1.get_variable(
                "Wh", (12, 7),
                initializer=tf1.initializers.glorot_uniform(seed=2))
            xs = tf.transpose(ph, [1, 0, 2])
            in_ta = tf.TensorArray(tf.float32, size=6,
                                   element_shape=(2, 5)).unstack(xs)
            out_ta = tf.TensorArray(tf.float32, size=6,
                                    element_shape=(2, 7))

            def body(t, h, ta):
                xt = in_ta.read(t)
                cat = tf.concat([xt, h], 1)
                z = tf.sigmoid(tf.matmul(cat, Wz))
                hc = tf.tanh(tf.matmul(cat, Wh))
                h2 = (1.0 - z) * h + z * hc
                return t + 1, h2, ta.write(t, h2)

            _, hT, out_ta = tf1.while_loop(
                lambda t, h, ta: t < 6, body,
                [0, tf.zeros((2, 7)), out_ta])
            out = tf.identity(tf.transpose(out_ta.stack(), [1, 0, 2]),
                              name="rnn_out")
            with tf1.Session(graph=g) as sess:
                sess.run(tf1.global_variables_initializer())
                wz_val, wh_val = sess.run([Wz, Wh])
                frozen = tf1.graph_util.convert_variables_to_constants(
                    sess, g.as_graph_def(), ["rnn_out"])

        def ref_loss(params, xv):
            wz, wh = params

            def step(h, xt):
                cat = jnp.concatenate([xt, h], 1)
                z = jax.nn.sigmoid(cat @ wz)
                hc = jnp.tanh(cat @ wh)
                h2 = (1 - z) * h + z * hc
                return h2, h2

            _, ys = jax.lax.scan(step, jnp.zeros((2, 7)),
                                 jnp.transpose(xv, (1, 0, 2)))
            y = jnp.transpose(ys, (1, 0, 2))
            return jnp.sum(y * y)

        ref_gz, ref_gh = jax.grad(ref_loss)(
            (jnp.asarray(wz_val), jnp.asarray(wh_val)), jnp.asarray(x))

        sd = TFGraphMapper.importGraph(frozen)
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] == 6
        sd.convertConstantsToVariables("Wz", "Wh")
        y = sd.getVariable("rnn_out")
        loss = sd._op("reduce_sum",
                      [sd._op("mul", [y.name, y.name]).name])
        sd.setLossVariables(loss.name)
        grads = sd.calculateGradients({"x": x}, ["Wz", "Wh"])
        np.testing.assert_allclose(np.asarray(grads["Wz"]),
                                   np.asarray(ref_gz),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(grads["Wh"]),
                                   np.asarray(ref_gh),
                                   rtol=1e-3, atol=1e-3)

        # and the whole fine-tune path descends
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.learning.updaters import Sgd

        sd.setTrainingConfig(TrainingConfig(
            updater=Sgd(1e-3), data_set_feature_mapping=["x"],
            minimize=True))
        hist = sd.fit(DataSet(x, None), epochs=3)
        assert hist.loss_curve[-1] < hist.loss_curve[0]

    def test_dynamic_shape_bound_does_not_fake_a_trip_count(self):
        """A loop bound derived from a DYNAMIC placeholder dim flows
        through partial eval as a provenance sentinel — it must NOT be
        mistaken for a constant (which would stamp a bogus
        max_trip_count and silently truncate the loop); the import
        falls back to lax.while_loop and stays shape-polymorphic."""
        def fn(a):
            n = tf.shape(a)[0]
            return tf.while_loop(
                lambda i, acc: i < n,
                lambda i, acc: (i + 1, acc + tf.reduce_sum(a) * 0.1),
                [tf.constant(0), tf.constant(0.0)])[1]

        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        spec = [tf.TensorSpec([None, 3], tf.float32)]
        conc = tf.function(fn).get_concrete_function(*spec)
        frozen = convert_variables_to_constants_v2(
            conc, lower_control_flow=True)
        gd = frozen.graph.as_graph_def()
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        sd = TFGraphMapper.importGraph(gd)
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] is None
        for b in (2, 5):
            x = np.ones((b, 3), np.float32)
            got = float(sd.output({in_name: x}, [out_name])[out_name])
            ref = frozen(tf.constant(x))
            ref = float(np.asarray(ref[0] if isinstance(ref, list)
                                   else ref))
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_unreconstructible_frame_fails_loudly(self):
        """A lone Enter without Merge/Switch structure must raise a
        clear TFImportError, not import garbage."""
        from tensorflow.core.framework import graph_pb2
        gd = graph_pb2.GraphDef()
        n = gd.node.add()
        n.name, n.op = "x", "Placeholder"
        n.attr["dtype"].type = 1
        e = gd.node.add()
        e.name, e.op = "enter", "Enter"
        e.input.append("x")
        e.attr["frame_name"].s = b"broken_frame"
        with pytest.raises(TFImportError):
            TFGraphMapper.importGraph(gd)


class TestRound4TailMappers:
    """Round-4 pt2 TF mappers: Einsum, MirrorPad, Roll,
    TensorScatterUpdate/Add, PreventGradient, sparse softmax CE."""

    def test_einsum(self):
        def f(a, b):
            # both forms verified numerically: 2-operand contraction
            # and single-operand reduction (broadcast into the sum)
            return tf.einsum("ij,jk->ik", a, b) \
                + tf.einsum("ij->j", a)[None, :3]

        rs = np.random.default_rng(20)
        a = rs.normal(size=(2, 4)).astype(np.float32)
        b = rs.normal(size=(4, 3)).astype(np.float32)
        _run_both(f, [a, b])

    def test_mirror_pad_both_modes(self):
        def f(x):
            r = tf.raw_ops.MirrorPad(input=x, paddings=[[1, 2], [2, 1]],
                                     mode="REFLECT")
            s = tf.raw_ops.MirrorPad(input=x, paddings=[[1, 1], [0, 2]],
                                     mode="SYMMETRIC")
            return r[:4, :4] + s[:4, :4]

        x = np.random.default_rng(21).normal(size=(4, 4)) \
            .astype(np.float32)
        _run_both(f, [x])

    def test_roll_and_tensor_scatter(self):
        def f(x):
            r = tf.roll(x, shift=[1, -2], axis=[0, 1])
            idx = tf.constant([[0], [2]])
            upd = tf.ones((2, 4), tf.float32)
            u = tf.tensor_scatter_nd_update(x, idx, upd)
            a = tf.tensor_scatter_nd_add(x, idx, upd)
            return r + u + a

        x = np.random.default_rng(22).normal(size=(3, 4)) \
            .astype(np.float32)
        _run_both(f, [x])

    def test_prevent_gradient_is_identity_forward(self):
        def f(x):
            return tf.raw_ops.PreventGradient(input=x) * 2.0

        x = np.random.default_rng(23).normal(size=(2, 3)) \
            .astype(np.float32)
        _run_both(f, [x])

    def test_sparse_softmax_cross_entropy(self):
        def f(x):
            labels = tf.constant([0, 2], tf.int32)
            return tf.nn.sparse_softmax_cross_entropy_with_logits(
                labels=labels, logits=x)

        x = np.random.default_rng(24).normal(size=(2, 3)) \
            .astype(np.float32)
        _run_both(f, [x])
