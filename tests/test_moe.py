"""MoE + expert parallelism tests (8-device CPU mesh).

The reference has no MoE (SURVEY.md §2: data parallelism only). Checks:
router invariants (capacity, gate normalization, aux loss), exact
equivalence of a 1-expert MoE with the dense FFN, training convergence,
and sharded-vs-unsharded step equivalence (EP over the 'model' axis)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.models.moe import capacity, moe_ffn, router_dispatch
from deeplearning4j_tpu.models.transformer import (
    TransformerEncoder, tiny_config,
)


def _moe_cfg(**kw):
    cfg = tiny_config(vocab=47, max_len=8, d_model=16, n_layers=2,
                      d_ff=32)
    cfg.n_experts = kw.pop("n_experts", 4)
    cfg.expert_top_k = kw.pop("top_k", 2)
    cfg.capacity_factor = kw.pop("capacity_factor", 2.0)
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


class TestRouter:
    def test_capacity_respected(self):
        rs = np.random.RandomState(0)
        probs = jax.nn.softmax(jnp.asarray(rs.rand(64, 4) * 5), -1)
        cap = 4  # deliberately tight: 64 tokens * top1 / 4 experts = 16
        combine, aux = router_dispatch(probs, top_k=1, cap=cap)
        # no expert slot double-booked, no expert over capacity
        per_slot = np.asarray(jnp.sum((combine > 0), axis=0))  # [E, C]
        assert per_slot.max() <= 1
        assert np.asarray(jnp.sum(combine > 0, axis=(0, 2))).max() <= cap
        assert np.isfinite(float(aux))

    def test_gates_normalized_top2(self):
        rs = np.random.RandomState(1)
        probs = jax.nn.softmax(jnp.asarray(rs.rand(32, 4)), -1)
        cap = capacity(32, 4, 4.0, 2)  # generous: nothing dropped
        combine, _ = router_dispatch(probs, top_k=2, cap=cap)
        sums = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(sums, 1.0, rtol=1e-5)

    def test_aux_is_one_for_uniform_router(self):
        probs = jnp.full((64, 8), 1.0 / 8)
        _, aux = router_dispatch(probs, top_k=1, cap=64)
        # E * sum_e (1/E * 1/E) * E... = 1 for a perfectly uniform router
        assert abs(float(aux) - 1.0) < 1e-5


class TestMoEFFN:
    def test_single_expert_equals_dense(self):
        rs = np.random.RandomState(2)
        d, f, s = 8, 16, 12
        x = jnp.asarray(rs.randn(s, d).astype(np.float32))
        w1 = jnp.asarray(rs.randn(d, f).astype(np.float32) * 0.1)
        w2 = jnp.asarray(rs.randn(f, d).astype(np.float32) * 0.1)
        y, aux = moe_ffn(
            x, jnp.zeros((d, 1)), w1[None], jnp.zeros((1, f)),
            w2[None], jnp.zeros((1, d)), top_k=1, capacity_factor=1.0)
        ref = jax.nn.gelu(x @ w1) @ w2
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_overflow_tokens_dropped_to_zero(self):
        rs = np.random.RandomState(3)
        d = 4
        x = jnp.asarray(rs.randn(16, d).astype(np.float32))
        wr = jnp.asarray(rs.randn(d, 2).astype(np.float32))
        y, _ = moe_ffn(
            x, wr, jnp.ones((2, d, d)) * 0.1, jnp.zeros((2, d)),
            jnp.ones((2, d, d)) * 0.1, jnp.zeros((2, d)),
            top_k=1, capacity_factor=0.25)
        nz = np.asarray(jnp.any(y != 0, axis=-1))
        cap = capacity(16, 2, 0.25, 1)
        # at most cap tokens kept per expert; with 16 tokens over 2
        # experts of capacity 2 most are dropped, and dropped tokens'
        # outputs are exactly zero (the residual carries them)
        assert 0 < nz.sum() <= 2 * cap
        assert (~nz).sum() >= 16 - 2 * cap

class TestMoETraining:
    def test_loss_decreases(self):
        cfg = _moe_cfg()
        enc = TransformerEncoder(cfg)
        params = enc.init_params()
        from deeplearning4j_tpu.learning.updaters import Adam
        upd = Adam(5e-3)
        opt = upd.init_state(params)
        step = enc.make_train_step(upd)
        rs = np.random.RandomState(5)
        ids = jnp.asarray(rs.randint(0, 47, (8, 8)).astype(np.int32))
        mask = jnp.ones((8, 8), jnp.float32)
        losses = []
        for i in range(16):
            params, opt, loss = step(params, opt, jnp.asarray(i), ids,
                                     ids, mask, jax.random.key(i))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_sharded_step_matches_unsharded(self):
        cfg = _moe_cfg(capacity_factor=4.0)
        cfg.dropout = 0.0
        enc = TransformerEncoder(cfg)
        params = enc.init_params()
        from deeplearning4j_tpu.learning.updaters import Sgd
        rs = np.random.RandomState(6)
        ids = jnp.asarray(rs.randint(0, 47, (8, 8)).astype(np.int32))
        mask = jnp.ones((8, 8), jnp.float32)
        rng = jax.random.key(0)

        ref_step = enc.make_train_step(Sgd(0.2))
        _, _, ref_loss = ref_step(
            jax.tree_util.tree_map(jnp.copy, params),
            Sgd(0.2).init_state(params), jnp.asarray(0), ids, ids, mask,
            rng)

        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4),
                    ("data", "model"))
        sp = enc.shard_params(params, mesh)
        step = enc.make_train_step(Sgd(0.2), mesh)
        with mesh:
            _, _, loss = step(sp, Sgd(0.2).init_state(sp), jnp.asarray(0),
                              ids, ids, mask, rng)
        assert abs(float(loss) - float(ref_loss)) / abs(float(ref_loss)) \
            < 1e-4, (float(loss), float(ref_loss))

    def test_ring_moe_composes_and_learns(self):
        """VERDICT r1 #6: MoE under the SP/ring engine — shard-local
        routing with the balance loss pmean'd over (data, sp)."""
        cfg = _moe_cfg()
        cfg.dropout = 0.0
        enc = TransformerEncoder(cfg)
        mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2),
                    ("data", "sp"))
        from deeplearning4j_tpu.learning.updaters import Adam
        step = enc.make_ring_train_step(Adam(5e-3), mesh)
        params = enc.init_params()
        opt = Adam(5e-3).init_state(params)
        rs = np.random.RandomState(9)
        ids = jnp.asarray(rs.randint(0, 47, (8, 8)).astype(np.int32))
        mask = jnp.ones((8, 8), jnp.float32)
        losses = []
        with mesh:
            for i in range(12):
                params, opt, loss = step(params, opt, jnp.asarray(i),
                                         ids, ids, mask,
                                         jax.random.key(i))
                losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, losses

    def test_pipeline_moe_composes_and_learns(self):
        """VERDICT r1 #6: MoE under the PP engine — per-stage aux sums
        accumulated only on real (non-fill/drain) ticks."""
        from deeplearning4j_tpu.parallel.pipeline import (
            PipelinedTransformer,
        )
        cfg = _moe_cfg()
        cfg.dropout = 0.0
        enc = TransformerEncoder(cfg)
        mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2),
                    ("data", "pipe"))
        pp = PipelinedTransformer(enc, n_stages=2)
        from deeplearning4j_tpu.learning.updaters import Adam
        params = pp.shard_params(enc.init_params(), mesh)
        opt = Adam(5e-3).init_state(params)
        step = pp.make_train_step(Adam(5e-3), mesh, n_micro=2)
        rs = np.random.RandomState(10)
        ids = jnp.asarray(rs.randint(0, 47, (16, 8)).astype(np.int32))
        mask = jnp.ones((16, 8), jnp.float32)
        losses = []
        with mesh:
            for i in range(12):
                params, opt, loss = step(params, opt, jnp.asarray(i),
                                         ids, ids, mask,
                                         jax.random.key(i))
                losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, losses


class TestReviewRegressions:
    def test_top1_router_receives_task_gradient(self):
        """Switch-style top-1 keeps the RAW gate: normalizing would make
        the gate identically 1 and zero the router's task gradient."""
        rs = np.random.RandomState(7)
        d, f = 8, 16
        x = jnp.asarray(rs.randn(12, d).astype(np.float32))
        wr = jnp.asarray(rs.randn(d, 4).astype(np.float32))
        we1 = jnp.asarray(rs.randn(4, d, f).astype(np.float32) * 0.1)
        we2 = jnp.asarray(rs.randn(4, f, d).astype(np.float32) * 0.1)

        def out_sum(wr_):
            y, _ = moe_ffn(x, wr_, we1, jnp.zeros((4, f)), we2,
                           jnp.zeros((4, d)), top_k=1,
                           capacity_factor=4.0)
            return jnp.sum(y * y)

        g = jax.grad(out_sum)(wr)
        assert float(jnp.max(jnp.abs(g))) > 0.0

    def test_grouped_dispatch(self):
        """Per-group dispatch (GShard): capacity applies within each
        group, and an indivisible group size raises clearly."""
        rs = np.random.RandomState(8)
        d = 8
        x = jnp.asarray(rs.randn(32, d).astype(np.float32))
        wr = jnp.asarray(rs.randn(d, 2).astype(np.float32))
        y, aux = moe_ffn(
            x, wr, jnp.ones((2, d, d)) * 0.1, jnp.zeros((2, d)),
            jnp.ones((2, d, d)) * 0.1, jnp.zeros((2, d)),
            top_k=1, capacity_factor=1.0, group_size=8)
        assert y.shape == (32, d) and np.isfinite(float(aux))
        with pytest.raises(ValueError, match="divisible"):
            moe_ffn(x, wr, jnp.ones((2, d, d)), jnp.zeros((2, d)),
                    jnp.ones((2, d, d)), jnp.zeros((2, d)),
                    top_k=1, capacity_factor=1.0, group_size=5)
