"""deeplearning4j-graph parity: Graph/walks/GraphHuffman/DeepWalk.

Reference tests (eclipse monorepo deeplearning4j/deeplearning4j-graph/
src/test/java/org/deeplearning4j/graph/):
- TestGraph.java — construction + degree + random-walk mechanics,
  disconnected-vertex handling.
- TestGraphHuffman.java — code validity: prefix-free, high-degree
  vertices get the short codes.
- TestDeepWalk.java — fit on a structured graph, similarity sanity,
  vector serde round-trip.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.graph import (
    DeepWalk, Graph, GraphHuffman, GraphLoader, NoEdgeHandling,
    RandomWalkIterator, WeightedRandomWalkIterator,
    generate_random_walks, loadGraphVectors, writeGraphVectors)


def _two_cliques(k=6, bridges=1):
    """Two k-cliques joined by `bridges` edges — communities 0..k-1 and
    k..2k-1."""
    g = Graph(2 * k)
    for base in (0, k):
        for i in range(k):
            for j in range(i + 1, k):
                g.addEdge(base + i, base + j)
    for b in range(bridges):
        g.addEdge(b, k + b)
    return g


class TestGraph:
    def test_construction_and_degree(self):
        g = Graph(4)
        g.addEdge(0, 1)
        g.addEdge(1, 2)
        g.addEdge(2, 3)
        assert g.numVertices() == 4
        assert g.numEdges() == 3
        assert g.getVertexDegree(1) == 2          # undirected
        assert sorted(g.getConnectedVertexIndices(1)) == [0, 2]

    def test_directed_edge(self):
        g = Graph(3)
        g.addEdge(0, 1, directed=True)
        assert g.getConnectedVertexIndices(0) == [1]
        assert g.getConnectedVertexIndices(1) == []

    def test_bad_edges_raise(self):
        g = Graph(2)
        with pytest.raises(ValueError):
            g.addEdge(0, 5)
        with pytest.raises(ValueError):
            g.addEdge(0, 1, weight=0.0)

    def test_edge_list_loaders(self, tmp_path):
        p = tmp_path / "edges.csv"
        p.write_text("# comment\n0,1\n1,2\n")
        g = GraphLoader.loadUndirectedGraphEdgeListFile(str(p), 3)
        assert g.numEdges() == 2
        pw = tmp_path / "weighted.csv"
        pw.write_text("0,1,0.5\n1,2,2.0\n")
        gw = GraphLoader.loadWeightedEdgeListFile(str(pw), 3)
        assert gw.numEdges() == 2
        with pytest.raises(ValueError):
            GraphLoader.loadWeightedEdgeListFile(str(p), 3)  # no weight


class TestRandomWalks:
    def test_walk_shape_and_validity(self):
        g = _two_cliques()
        walks = generate_random_walks(g, walk_length=10, seed=0)
        assert walks.shape == (12, 11)
        assert (walks[:, 0] == np.arange(12)).all()
        # every step follows an edge
        for w in walks:
            for a, b in zip(w[:-1], w[1:]):
                assert b in g.getConnectedVertexIndices(a)

    def test_self_loop_on_disconnected(self):
        g = Graph(3)
        g.addEdge(0, 1)                          # vertex 2 isolated
        walks = generate_random_walks(
            g, walk_length=5, seed=0,
            no_edge_handling=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)
        assert (walks[2] == 2).all()

    def test_exception_on_disconnected(self):
        g = Graph(3)
        g.addEdge(0, 1)
        with pytest.raises(ValueError, match="no outgoing"):
            generate_random_walks(
                g, walk_length=5,
                no_edge_handling=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)

    def test_dead_end_mid_walk_raises(self):
        g = Graph(3)
        g.addEdge(0, 1, directed=True)           # 1 is a sink
        with pytest.raises(ValueError, match="disconnected vertex"):
            generate_random_walks(
                g, walk_length=4, starts=[0],
                no_edge_handling=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)

    def test_weighted_walks_follow_weights(self):
        # hub 0 with a 99x-heavier edge to 1 than to 2
        g = Graph(3)
        g.addEdge(0, 1, weight=99.0)
        g.addEdge(0, 2, weight=1.0)
        walks = generate_random_walks(
            g, walk_length=1, starts=np.zeros(4000, np.int64),
            weighted=True, seed=1)
        frac_to_1 = (walks[:, 1] == 1).mean()
        assert frac_to_1 > 0.95

    def test_bad_starts_raise(self):
        g = _two_cliques()
        with pytest.raises(ValueError, match="out of range"):
            generate_random_walks(g, 4, starts=[-1])
        with pytest.raises(ValueError, match="out of range"):
            generate_random_walks(g, 4, starts=[99])

    def test_reset_yields_fresh_walks(self):
        g = _two_cliques()
        it = RandomWalkIterator(g, walk_length=12, seed=5)
        first = np.array([it.next() for _ in range(g.numVertices())])
        it.reset()
        second = np.array([it.next() for _ in range(g.numVertices())])
        assert (first[:, 0] == second[:, 0]).all()   # same starts
        assert (first != second).any()               # fresh randomness

    def test_iterator_facades(self):
        g = _two_cliques()
        it = RandomWalkIterator(g, walk_length=4, seed=3)
        seen = 0
        while it.hasNext():
            w = it.next()
            assert len(w) == 5
            seen += 1
        assert seen == g.numVertices()
        wit = WeightedRandomWalkIterator(g, walk_length=4, seed=3)
        assert len(wit.next()) == 5


class TestGraphHuffman:
    def test_codes_prefix_free_and_degree_ordered(self):
        # star: hub 0 degree 8, leaves degree 1
        g = Graph(9)
        for i in range(1, 9):
            g.addEdge(0, i)
        h = GraphHuffman(g)
        assert h.n_inner == 8
        # hub gets the (strictly) shortest code
        hub_len = h.getCodeLength(0)
        leaf_lens = [h.getCodeLength(i) for i in range(1, 9)]
        assert hub_len <= min(leaf_lens)
        # prefix-free over all vertex codes
        codes = []
        for vw in h.cache.vocabWords():
            codes.append("".join(map(str, vw.codes)))
        for i, a in enumerate(codes):
            for j, b in enumerate(codes):
                if i != j:
                    assert not b.startswith(a)

    def test_vertex_row_mapping_is_bijective(self):
        g = _two_cliques()
        h = GraphHuffman(g)
        assert sorted(h.vertex_to_row.tolist()) == list(range(12))
        assert (h.row_to_vertex[h.vertex_to_row]
                == np.arange(12)).all()


class TestDeepWalk:
    def test_fit_separates_communities(self):
        g = _two_cliques(k=6, bridges=1)
        dw = (DeepWalk.Builder().vectorSize(32).windowSize(3)
              .learningRate(0.15).seed(7).batchSize(1024).build())
        dw.fit(g, walk_length=20, walks_per_vertex=10, epochs=5)
        intra, inter = [], []
        for a in range(1, 6):          # skip bridge vertex 0
            intra.append(dw.similarity(1, a) if a != 1 else 1.0)
            inter.append(dw.similarity(1, 6 + a))
        assert np.mean(intra) > np.mean(inter) + 0.2
        # nearest neighbours of a clique member are its clique
        near = dw.verticesNearest(2, top=4)
        assert sum(1 for v in near if v < 6) >= 3

    def test_vector_shapes_and_api(self):
        g = _two_cliques()
        dw = DeepWalk(vector_size=16, seed=1)
        dw.fit(g, walk_length=8, walks_per_vertex=2)
        assert dw.numVertices() == 12
        assert dw.getVertexVector(3).shape == (16,)
        assert dw.getVectorMatrix().shape == (12, 16)
        assert dw.similarity(4, 4) == pytest.approx(1.0, abs=1e-5)

    def test_unfitted_raises(self):
        dw = DeepWalk()
        with pytest.raises(ValueError, match="not initialized"):
            dw.getVertexVector(0)

    def test_serde_round_trip(self, tmp_path):
        g = _two_cliques()
        dw = DeepWalk(vector_size=8, seed=2)
        dw.fit(g, walk_length=6)
        path = str(tmp_path / "gv.txt")
        writeGraphVectors(dw, path)
        loaded = loadGraphVectors(path)
        assert loaded.numVertices() == 12
        np.testing.assert_allclose(
            loaded.getVertexVector(5), dw.getVertexVector(5),
            rtol=1e-5)
        assert loaded.verticesNearest(1, 3) == dw.verticesNearest(1, 3)
