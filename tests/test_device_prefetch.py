"""Device input pipeline tests: async prefetch ordering/reset/shutdown
(no leaked threads), pad-to-bucket loss equivalence, on-device batch
passthrough in all three fit loops, recompile-count bounds, and the
transfer-overlap / queue-depth telemetry contract."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    ArrayDataSetIterator, BatchShapePolicy, DataSet,
    DevicePrefetchIterator, DevicePrefetchMultiIterator,
    ListDataSetIterator, MultiDataSet, ListMultiDataSetIterator,
    MultiDataSetIterator,
)
from deeplearning4j_tpu.datasets.record_reader_iterator import (
    AsyncDataSetIterator,
)
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.nn.conf import (
    LSTM, DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.profiler import telemetry


def _reg():
    return telemetry.MetricsRegistry.get_default()


def _lstm_net(seed=7, n_in=4, hidden=6, n_out=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(1e-2)).list()
            .layer(LSTM(n_out=hidden))
            .layer(RnnOutputLayer(n_out=n_out, activation="softmax",
                                  loss="mcxent"))
            .setInputType(InputType.recurrent(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _ff_net(seed=3, loss="mse", activation="identity"):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(1e-2)).list()
            .layer(DenseLayer(n_out=6, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation=activation, loss=loss))
            .setInputType(InputType.feedForward(4)).build())
    return MultiLayerNetwork(conf).init()


def _ragged_sets(lengths, batch=8, last_n=3, n_in=4, n_out=5, seed=1):
    rng = np.random.default_rng(seed)
    eye = np.eye(n_out, dtype=np.float32)
    sets = []
    for i, t in enumerate(lengths):
        n = batch if i < len(lengths) - 1 else last_n
        sets.append(DataSet(
            rng.normal(size=(n, t, n_in)).astype(np.float32),
            eye[rng.integers(0, n_out, (n, t))]))
    return sets


def _threads():
    return {t for t in threading.enumerate() if t.is_alive()}


# ----------------------------------------------------------------------
# prefetch mechanics: ordering, reset, shutdown, error propagation
# ----------------------------------------------------------------------
class TestPrefetchMechanics:
    def test_ordering_matches_sync_iteration(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(20, 4)).astype(np.float32)
        y = rng.normal(size=(20, 2)).astype(np.float32)
        raw = [np.asarray(ds.features)
               for ds in ArrayDataSetIterator(x, y, 4)]
        pf = DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=2)
        # workers start lazily: fit loops reset() before consuming, and
        # an eager start would discard the first prefetched batches
        assert pf._thread is None
        pf.reset()   # pre-consumption reset must not spin anything up
        assert pf._thread is None
        got = [np.asarray(ds.features) for ds in pf]
        pf.shutdown()
        assert len(got) == len(raw) == 5
        for a, b in zip(raw, got):
            np.testing.assert_array_equal(a, b)

    def test_reset_mid_epoch_and_multi_epoch(self):
        x = np.arange(24, dtype=np.float32).reshape(12, 2)
        y = np.zeros((12, 1), np.float32)
        pf = DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=2)
        assert pf.hasNext()
        first = np.asarray(pf.next().features)
        pf.reset()  # mid-epoch restart
        epochs = [[np.asarray(ds.features) for ds in pf]
                  for _ in range(2)]  # __iter__ resets each time
        pf.shutdown()
        np.testing.assert_array_equal(epochs[0][0], first)
        assert len(epochs[0]) == len(epochs[1]) == 3
        for a, b in zip(epochs[0], epochs[1]):
            np.testing.assert_array_equal(a, b)

    def test_shutdown_leaves_no_threads(self):
        before = _threads()
        x = np.zeros((16, 4), np.float32)
        y = np.zeros((16, 2), np.float32)
        pf = DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=2)
        next(iter(pf))  # partially consumed epoch, workers mid-flight
        pf.shutdown()
        leaked = _threads() - before
        assert not leaked, f"leaked threads: {leaked}"
        # shutdown is idempotent and reset() reopens
        pf.shutdown()
        pf.reset()
        assert len(list(pf)) == 4
        pf.shutdown()
        assert not (_threads() - before)

    def test_context_manager_shuts_down(self):
        before = _threads()
        x = np.zeros((8, 4), np.float32)
        y = np.zeros((8, 2), np.float32)
        with DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=1) as pf:
            assert len(list(pf)) == 2
        assert not (_threads() - before)

    def test_async_iterator_shutdown_joins_worker(self):
        before = _threads()
        x = np.zeros((16, 4), np.float32)
        y = np.zeros((16, 2), np.float32)
        it = AsyncDataSetIterator(ArrayDataSetIterator(x, y, 4),
                                  queue_size=2)
        it.next()  # abandon mid-epoch
        it.shutdown()
        assert not (_threads() - before)
        it.reset()  # reopens
        assert len(list(it)) == 4
        it.shutdown()
        assert not (_threads() - before)

    def test_slow_consumer_never_loses_final_batches(self):
        """Regression: the ETL worker's sentinel put used to DROP a
        live queued batch whenever the consumer stalled >0.1s at epoch
        end (exactly what a jit compile does) — only a requested
        stop/reset may discard batches."""
        import time

        x = np.arange(24, dtype=np.float32).reshape(12, 2)
        y = np.zeros((12, 1), np.float32)
        it = AsyncDataSetIterator(ArrayDataSetIterator(x, y, 4),
                                  queue_size=1)
        got = 0
        while it.hasNext():
            time.sleep(0.25)  # stall past the old sentinel-put timeout
            it.next()
            got += 1
        it.shutdown()
        assert got == 3

    def test_worker_error_reraises_on_consumer(self):
        class Exploding(ArrayDataSetIterator):
            def next(self):
                if self._i >= 4:
                    raise RuntimeError("decode failed")
                return super().next()

        x = np.zeros((12, 4), np.float32)
        y = np.zeros((12, 2), np.float32)
        pf = DevicePrefetchIterator(Exploding(x, y, 4), depth=2)
        with pytest.raises(RuntimeError, match="decode failed"):
            list(pf)
        pf.shutdown()

    def test_depth_zero_sync_fallback_no_threads(self):
        before = _threads()
        x = np.ones((10, 4), np.float32)
        y = np.ones((10, 2), np.float32)
        pf = DevicePrefetchIterator(
            ArrayDataSetIterator(x, y, 4), depth=0,
            policy=BatchShapePolicy("pad_last", batch_size=4))
        batches = list(pf)
        assert _threads() == before  # fully synchronous
        assert len(batches) == 3
        for b in batches:
            assert isinstance(b.features, jax.Array)
            assert b.features.shape[0] == 4  # partial batch padded

    def test_multi_iterator_dispatch(self):
        mds = [MultiDataSet([np.ones((4, 3), np.float32)],
                            [np.ones((4, 2), np.float32)])
               for _ in range(3)]
        pf = DevicePrefetchIterator(ListMultiDataSetIterator(mds),
                                    depth=1)
        assert isinstance(pf, DevicePrefetchMultiIterator)
        assert isinstance(pf, MultiDataSetIterator)
        got = list(pf)
        pf.shutdown()
        assert len(got) == 3
        assert isinstance(got[0], MultiDataSet)
        assert isinstance(got[0].features[0], jax.Array)


# ----------------------------------------------------------------------
# shape policy: padding + bucketing semantics and loss equivalence
# ----------------------------------------------------------------------
class TestBatchShapePolicy:
    def test_bucket_pads_to_pow2_and_batch(self):
        pol = BatchShapePolicy("bucket", batch_size=8)
        ds = DataSet(np.ones((3, 13, 4), np.float32),
                     np.ones((3, 13, 5), np.float32))
        out = pol.apply(ds)
        assert np.asarray(out.features).shape == (8, 16, 4)
        assert np.asarray(out.labels).shape == (8, 16, 5)
        fm = np.asarray(out.features_mask)
        lm = np.asarray(out.labels_mask)
        assert fm.shape == lm.shape == (8, 16)
        # real region: fm 1, lm scaled by 8/3; padding: fm time-pad 0,
        # lm 0 everywhere outside the real region
        assert np.all(fm[:3, :13] == 1.0) and np.all(fm[:3, 13:] == 0.0)
        np.testing.assert_allclose(lm[:3, :13], 8.0 / 3.0, rtol=1e-6)
        assert np.all(lm[3:] == 0.0) and np.all(lm[:3, 13:] == 0.0)

    def test_exact_mode_is_identity(self):
        ds = DataSet(np.ones((3, 5, 4), np.float32),
                     np.ones((3, 5, 5), np.float32))
        assert BatchShapePolicy("exact").apply(ds) is ds

    def test_existing_ragged_mask_is_extended_and_scaled(self):
        fm = np.zeros((3, 13), np.float32)
        fm[0, :13] = 1.0
        fm[1, :7] = 1.0
        fm[2, :2] = 1.0
        ds = DataSet(np.ones((3, 13, 4), np.float32),
                     np.ones((3, 13, 5), np.float32), fm)
        out = BatchShapePolicy("bucket", batch_size=4).apply(ds)
        lm = np.asarray(out.labels_mask)
        np.testing.assert_allclose(lm[:3, :13], fm * (4.0 / 3.0),
                                   rtol=1e-6)
        assert np.all(lm[3:] == 0.0)

    def test_pad_last_loss_equivalence_mse(self):
        rng = np.random.default_rng(5)
        net = _ff_net()
        x = rng.normal(size=(5, 4)).astype(np.float32)
        y = rng.normal(size=(5, 2)).astype(np.float32)
        out = BatchShapePolicy("pad_last", batch_size=8).apply(
            DataSet(x, y))
        l0, _ = net._loss(net.params_list, net.states_list,
                          jnp.asarray(x), jnp.asarray(y), None, None)
        l1, _ = net._loss(net.params_list, net.states_list,
                          jnp.asarray(np.asarray(out.features)),
                          jnp.asarray(np.asarray(out.labels)),
                          jnp.asarray(np.asarray(out.labels_mask)),
                          None)
        assert abs(float(l0) - float(l1)) < 1e-6

    def test_bucket_loss_equivalence_masked_rnn(self):
        """Padded (batch AND time) masked loss == unpadded loss to
        ~1e-6 — the padding must be invisible to training."""
        rng = np.random.default_rng(6)
        net = _lstm_net()
        x = rng.normal(size=(3, 5, 4)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, (3, 5))]
        out = BatchShapePolicy("bucket", batch_size=8).apply(
            DataSet(x, y))
        l0, _ = net._loss(net.params_list, net.states_list,
                          jnp.asarray(x), jnp.asarray(y), None, None)
        l1, _ = net._loss(net.params_list, net.states_list,
                          jnp.asarray(np.asarray(out.features)),
                          jnp.asarray(np.asarray(out.labels)),
                          jnp.asarray(np.asarray(out.labels_mask)),
                          None,
                          jnp.asarray(np.asarray(out.features_mask)))
        assert abs(float(l0) - float(l1)) < 1e-5

    def test_per_example_mask_on_sequence_labels(self):
        """A per-example [N,1] labels mask on [N,T,C] labels must
        broadcast to per-timestep (used to IndexError on time pad)."""
        ds = DataSet(np.ones((3, 10, 5), np.float32),
                     np.ones((3, 10, 2), np.float32),
                     labels_mask=np.asarray([[1.0], [0.5], [2.0]],
                                            np.float32))
        out = BatchShapePolicy("bucket", batch_size=4).apply(ds)
        lm = np.asarray(out.labels_mask)
        assert lm.shape == (4, 16)
        np.testing.assert_allclose(lm[1, :10], 0.5 * 4.0 / 3.0,
                                   rtol=1e-6)
        assert np.all(lm[:, 10:] == 0.0) and np.all(lm[3:] == 0.0)

    def test_caller_policy_not_mutated(self):
        """Filling batch_size from the iterator must not write back
        into a caller-owned (possibly shared) policy object."""
        pol = BatchShapePolicy("pad_last")
        x = np.ones((10, 4), np.float32)
        y = np.ones((10, 2), np.float32)
        pf = DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=0, policy=pol)
        out = list(pf)
        assert pol.batch_size is None
        assert pf.batch() == 4
        assert np.asarray(out[-1].features).shape[0] == 4

    def test_multi_dataset_padding(self):
        mds = MultiDataSet(
            [np.ones((3, 4), np.float32), np.ones((3, 6, 2), np.float32)],
            [np.ones((3, 2), np.float32)])
        out = BatchShapePolicy("bucket", batch_size=8).apply(mds)
        assert np.asarray(out.features[0]).shape == (8, 4)
        assert np.asarray(out.features[1]).shape == (8, 8, 2)
        lm = np.asarray(out.labels_mask_arrays[0])
        np.testing.assert_allclose(lm[:3], 8.0 / 3.0, rtol=1e-6)
        assert np.all(lm[3:] == 0.0)

    def test_padded_examples_counter(self):
        before = _reg().counter(telemetry.PREFETCH_PADDED_EXAMPLES).total()
        BatchShapePolicy("pad_last", batch_size=8).apply(
            DataSet(np.ones((3, 4), np.float32),
                    np.ones((3, 2), np.float32)))
        after = _reg().counter(telemetry.PREFETCH_PADDED_EXAMPLES).total()
        assert after - before == 5


# ----------------------------------------------------------------------
# fit-loop integration: passthrough + recompile bounds + telemetry
# ----------------------------------------------------------------------
class TestFitIntegration:
    def test_mln_on_device_passthrough_and_fit(self):
        net = _ff_net()
        rng = np.random.default_rng(2)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = rng.normal(size=(16, 2)).astype(np.float32)
        c0 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(site="mln")
        with DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=2, dtype=net._dtype) as pf:
            net.fit(pf, epochs=1)
        c1 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(site="mln")
        assert c1 - c0 == 4
        assert np.isfinite(net.score())

    def test_cg_on_device_passthrough_and_fit(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )

        conf = (ComputationGraphConfiguration.graphBuilder()
                .addInputs("in")
                .setInputTypes(InputType.feedForward(4))
                .addLayer("d", DenseLayer(n_out=6, activation="tanh"),
                          "in")
                .addLayer("out", OutputLayer(n_out=2,
                                             activation="softmax",
                                             loss="mcxent"), "d")
                .setOutputs("out").build())
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(3)
        x = rng.normal(size=(12, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 12)]
        c0 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(site="cg")
        with DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=1, dtype=net._dtype) as pf:
            net.fit(pf, epochs=1)
        c1 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(site="cg")
        assert c1 - c0 == 3
        assert np.isfinite(net.score())

    def test_sharded_on_device_passthrough(self):
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

        net = _ff_net(loss="mcxent", activation="softmax")
        mesh = build_mesh(num_data=8)
        tr = ShardedTrainer(net, mesh=mesh)
        rng = np.random.default_rng(4)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        c0 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(
            site="sharded")
        with DevicePrefetchIterator(
                ArrayDataSetIterator(x, y, 16), depth=2, mesh=mesh,
                dtype=net._dtype,
                policy=BatchShapePolicy("pad_last", batch_size=16)) as pf:
            tr.fit(pf, epochs=1)
        c1 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(
            site="sharded")
        assert c1 - c0 == 2
        assert np.isfinite(net.score())

    def test_parallel_wrapper_prefetch_buffer(self):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        before = _threads()
        net = _ff_net(loss="mcxent", activation="softmax")
        pw = (ParallelWrapper.Builder(net).workers(8)
              .prefetchBuffer(2).build())
        assert pw.prefetch_buffer == 2
        rng = np.random.default_rng(8)
        # 40 examples / batch 16 -> partial final batch of 8, padded
        # to 16 by the default pad_last policy so it shards evenly
        x = rng.normal(size=(40, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 40)]
        c0 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(
            site="sharded")
        pw.fit(ArrayDataSetIterator(x, y, 16), epochs=1)
        c1 = _reg().counter(telemetry.ON_DEVICE_BATCHES).value(
            site="sharded")
        assert c1 - c0 == 3
        assert not (_threads() - before)  # fit() shut the pipeline down

    def test_bucketed_ragged_stream_compiles_per_bucket(self):
        """Acceptance: a ragged LSTM stream (varying T + partial final
        batch) through the bucket policy compiles at most one
        executable per shape bucket — not one per distinct shape."""
        net = _lstm_net()
        lengths = [5, 9, 13, 3]  # buckets: 8, 16
        sets = _ragged_sets(lengths)
        c0 = _reg().counter(telemetry.JIT_COMPILES).value(site="mln_step")
        with DevicePrefetchIterator(
                ListDataSetIterator(sets, batch_size=8), depth=2,
                policy=BatchShapePolicy("bucket", batch_size=8)) as pf:
            net.fit(pf, epochs=2)
        c1 = _reg().counter(telemetry.JIT_COMPILES).value(site="mln_step")
        n_buckets = len({max(8, 1 << (t - 1).bit_length())
                         for t in lengths})
        assert n_buckets == 2
        assert c1 - c0 <= n_buckets
        # contrast: the raw stream compiles one executable per
        # distinct (T, n) shape — the storm bucketing kills
        net2 = _lstm_net()
        c2 = _reg().counter(telemetry.JIT_COMPILES).value(site="mln_step")
        net2.fit(ListDataSetIterator(sets, batch_size=8), epochs=1)
        c3 = _reg().counter(telemetry.JIT_COMPILES).value(site="mln_step")
        assert c3 - c2 == len(lengths)

    def test_bucket_hit_miss_counters(self):
        sets = _ragged_sets([5, 9, 6, 13], last_n=8)
        pol = BatchShapePolicy("bucket", batch_size=8)
        h0 = _reg().counter(telemetry.BUCKET_HITS).total()
        m0 = _reg().counter(telemetry.BUCKET_MISSES).total()
        for ds in sets:
            pol.apply(ds)
        assert _reg().counter(telemetry.BUCKET_MISSES).total() - m0 == 2
        assert _reg().counter(telemetry.BUCKET_HITS).total() - h0 == 2

    def test_transfer_overlap_and_queue_depth_telemetry(self):
        """Acceptance: with depth>=1 the transfer of batch N+1 is
        issued before batch N is consumed — every consumed batch shows
        a positive transfer-overlap sample, and the queue-depth gauge
        reports the device-side buffer."""
        net = _ff_net()
        rng = np.random.default_rng(9)
        x = rng.normal(size=(24, 4)).astype(np.float32)
        y = rng.normal(size=(24, 2)).astype(np.float32)
        hist = _reg().histogram(telemetry.TRANSFER_OVERLAP_MS)
        n0 = hist.count()
        with DevicePrefetchIterator(ArrayDataSetIterator(x, y, 4),
                                    depth=2, dtype=net._dtype) as pf:
            net.fit(pf, epochs=1)
        assert hist.count() - n0 == 6  # one overlap sample per batch
        assert hist.percentiles()["p50"] >= 0.0
        # the gauge exists and its last value is a valid queue size
        depth = _reg().gauge(telemetry.PREFETCH_QUEUE_DEPTH).value()
        assert 0 <= depth <= 2