"""GloVe / FastText / t-SNE tests (reference analogs: GloveTest,
FastTextTest, Test BarnesHutTsne in deeplearning4j-nlp)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import BarnesHutTsne, FastText, Glove


def tiny_corpus():
    """Two topic clusters so embedding geometry is checkable."""
    return [
        "cat dog cat dog pet animal cat dog",
        "dog cat pet animal dog cat",
        "cat pet dog animal pet cat dog",
        "stock market trade price stock market",
        "market stock price trade market stock",
        "trade price stock market trade stock",
    ] * 6


class TestGlove:
    def test_fit_loss_decreases_and_similarity(self):
        g = Glove(layer_size=16, window_size=4, epochs=30,
                  learning_rate=0.1, batch_size=64, seed=7)
        g.fit(tiny_corpus())
        assert g.loss_history[-1] < g.loss_history[0]
        assert g.hasWord("cat") and g.hasWord("stock")
        assert g.getWordVector("cat").shape == (16,)
        # within-topic similarity beats cross-topic
        within = g.similarity("cat", "dog")
        across = g.similarity("cat", "stock")
        assert within > across

    def test_words_nearest(self):
        g = Glove(layer_size=12, epochs=25, seed=3,
                  batch_size=32).fit(tiny_corpus())
        near = g.wordsNearest("market", n=3)
        assert "stock" in near or "trade" in near or "price" in near


class TestFastText:
    def test_fit_and_oov_vectors(self):
        ft = FastText(layer_size=16, window_size=3, epochs=8,
                      batch_size=128, buckets=2000, seed=5)
        ft.fit(tiny_corpus())
        assert ft.loss_history[-1] < ft.loss_history[0]
        v = ft.getWordVector("cat")
        assert v.shape == (16,) and np.any(v != 0)
        # OOV: built purely from shared char n-grams
        oov = ft.getWordVector("cats")
        assert oov.shape == (16,) and np.any(oov != 0)
        # OOV overlapping "cat" n-grams should be closer to cat than an
        # unrelated OOV string
        def cos(a, b):
            return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
        assert cos(oov, ft.getWordVector("cat")) > \
            cos(ft.getWordVector("zxqwvu"), ft.getWordVector("cat"))

    def test_similarity_topics(self):
        ft = FastText(layer_size=16, epochs=8, batch_size=128,
                      buckets=2000, seed=11).fit(tiny_corpus())
        assert ft.similarity("stock", "market") > ft.similarity("stock", "dog")


class TestTsne:
    def test_clusters_stay_separated(self):
        rng = np.random.default_rng(0)
        a = rng.normal(0, 0.3, (30, 10)) + 5.0
        b = rng.normal(0, 0.3, (30, 10)) - 5.0
        x = np.vstack([a, b]).astype(np.float32)
        ts = BarnesHutTsne(n_components=2, perplexity=10, n_iter=300,
                           learning_rate=100.0, seed=1)
        y = ts.fit_transform(x)
        assert y.shape == (60, 2)
        assert np.all(np.isfinite(y))
        # KL decreased over optimization
        assert ts.kl_history[-1] < ts.kl_history[0]
        # cluster centroids separate farther than intra-cluster spread
        ca, cb = y[:30].mean(0), y[30:].mean(0)
        spread = max(y[:30].std(), y[30:].std())
        assert np.linalg.norm(ca - cb) > 2 * spread

    def test_plot_api(self):
        x = np.random.default_rng(2).normal(size=(20, 5)).astype(np.float32)
        ts = BarnesHutTsne(n_iter=50, perplexity=5)
        out = ts.plot(x, n_dims=3)
        assert out.shape == (20, 3)
        assert ts.getData() is out


class TestVectorizers:
    """Reference: bagofwords/vectorizer/{BagOfWordsVectorizer,
    TfidfVectorizer} (deeplearning4j-nlp)."""

    CORPUS = ["the cat sat on the mat",
              "the dog sat on the log",
              "cats and dogs and cats"]

    def test_bow_counts_and_vocab_filtering(self):
        from deeplearning4j_tpu.nlp import BagOfWordsVectorizer

        v = BagOfWordsVectorizer(min_word_frequency=2,
                                 stop_words=["the", "and"])
        v.fit(self.CORPUS)
        # survivors: sat(2) on(2) cats(2); cat/mat/dog/log/dogs fall
        # below min_word_frequency; the/and stopped
        assert sorted(v.vocab.words()) == ["cats", "on", "sat"]
        row = v.transform("cats on cats on cats zzz")
        assert row[v.vocab.indexOf("cats")] == 3.0
        assert row[v.vocab.indexOf("on")] == 2.0
        assert row[v.vocab.indexOf("sat")] == 0.0

    def test_tfidf_matches_reference_formula(self):
        import numpy as np

        from deeplearning4j_tpu.nlp import TfidfVectorizer

        v = TfidfVectorizer()
        v.fit(self.CORPUS)
        # 'sat' appears in 2 of 3 docs; reference formula:
        # idf = log10(1 + N/(1+df)), tf = raw count in the query doc
        row = v.transform("sat sat cat")
        want_sat = 2.0 * np.log10(1.0 + 3.0 / 3.0)
        want_cat = 1.0 * np.log10(1.0 + 3.0 / 2.0)
        np.testing.assert_allclose(row[v.vocab.indexOf("sat")],
                                   want_sat, rtol=1e-6)
        np.testing.assert_allclose(row[v.vocab.indexOf("cat")],
                                   want_cat, rtol=1e-6)

    def test_vectorize_dataset_and_training(self):
        """End to end: tf-idf rows feed the compiled classifier path
        (the reference's vectorizer -> DataSet -> fit pipeline)."""
        import numpy as np

        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.nlp import TfidfVectorizer
        from deeplearning4j_tpu.nn.conf import (DenseLayer, InputType,
                                                NeuralNetConfiguration,
                                                OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        pos = ["good great fine excellent good",
               "great good wonderful fine",
               "excellent wonderful great day"]
        neg = ["bad awful poor terrible bad",
               "awful bad dreadful poor",
               "terrible dreadful poor day"]
        v = TfidfVectorizer()
        v.fit(pos + neg)
        ds = v.vectorize(pos[0], 0, 2)
        assert ds.getFeatures().shape() == (1, v.vocab_size)
        x = v.transform_batch(pos + neg)
        y = np.repeat(np.eye(2, dtype=np.float32), 3, axis=0)
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(Adam(1e-1)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(v.vocab_size))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(60):
            net.fit(x, y)
        pred = np.asarray(net.output(x)).argmax(1)
        assert (pred == y.argmax(1)).all()
