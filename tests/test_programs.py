"""Roofline program registry + managed device profiling
(profiler/programs.py, the peak tables in profiler/flops.py, the
trace shims in profiler/__init__, the SLO engine's page-capture hook,
and the flight recorder's programs.json dump member)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.profiler as profiler
from deeplearning4j_tpu.profiler import flops as flops_mod
from deeplearning4j_tpu.profiler import (
    flight_recorder, programs, slo, telemetry,
)


@pytest.fixture(autouse=True)
def _clean_programs():
    programs.set_enabled(False)
    programs.reset()
    yield
    programs.set_enabled(False)
    programs.reset()


class _FakeProfiler:
    """Stand-in for jax.profiler: records start/stop calls and drops a
    file into the trace dir so capture bundles have content. Mirrors
    the real contract (second start raises RuntimeError)."""

    def __init__(self):
        self.starts = 0
        self.stops = 0
        self._active = False

    def install(self, monkeypatch):
        monkeypatch.setattr(jax.profiler, "start_trace", self.start)
        monkeypatch.setattr(jax.profiler, "stop_trace", self.stop)
        return self

    def start(self, log_dir):
        if self._active:
            raise RuntimeError("profiler already started")
        self._active = True
        self.starts += 1
        os.makedirs(log_dir, exist_ok=True)
        with open(os.path.join(log_dir, "trace.bin"), "wb") as f:
            f.write(b"\x00fake-xplane")

    def stop(self):
        self._active = False
        self.stops += 1


def _register_square(reg, site="t_site", n=64, seconds=(0.01,)):
    """Register one real compiled executable + dispatches."""
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((n, n), jnp.float32)
    sig = f"float32[{n}, {n}]"
    reg.register(site, sig, f.lower(x).compile(),
                 source="jit", compile_seconds=0.5)
    for s in seconds:
        reg.record_dispatch(site, sig, s)
    return sig


# ------------------------------------------------------------ peaks
class TestPeakTables:
    def test_known_device_reads_table(self, monkeypatch):
        kind = jax.devices()[0].device_kind
        monkeypatch.setitem(flops_mod.PEAK_FLOPS, kind,
                            {"bf16": 2e12, "f32": 1e12})
        monkeypatch.setitem(flops_mod.PEAK_HBM_GBPS, kind, 3.0)
        assert flops_mod.peak_flops("bf16") == 2e12
        assert flops_mod.peak_flops("float32") == 1e12
        assert flops_mod.peak_hbm_gbps() == 3.0

    def test_unknown_device_none_with_one_warning(self, monkeypatch,
                                                  caplog):
        kind = jax.devices()[0].device_kind
        assert kind not in flops_mod.PEAK_FLOPS     # CPU smoke premise
        assert kind not in flops_mod.PEAK_HBM_GBPS
        monkeypatch.setattr(flops_mod, "_warned_unknown_peak", set())
        monkeypatch.setattr(flops_mod, "_warned_unknown_hbm", set())
        with caplog.at_level("WARNING", logger="deeplearning4j_tpu"):
            assert flops_mod.peak_flops("bf16") is None
            assert flops_mod.peak_hbm_gbps() is None
            first = [r for r in caplog.records
                     if "peak" in r.getMessage().lower()]
            assert flops_mod.peak_flops("bf16") is None   # warn-once
            assert flops_mod.peak_hbm_gbps() is None
        again = [r for r in caplog.records
                 if "peak" in r.getMessage().lower()]
        assert len(first) == 2 and len(again) == 2


# --------------------------------------------------------- verdicts
class TestRooflineVerdict:
    def test_no_cost_numbers_is_unknown(self):
        assert programs.roofline_verdict(None, None) == "unknown"
        assert programs.roofline_verdict(0, 1e6) == "unknown"
        assert programs.roofline_verdict(1e6, 0) == "unknown"

    def test_tiny_program_is_dispatch_bound(self):
        # roofline time ~5ns on nominal v5e peaks: launch overhead wins
        assert programs.roofline_verdict(1e6, 1e4) == "dispatch_bound"

    def test_low_ai_is_memory_bound(self):
        # AI=10 against a ~240 flops/byte nominal ridge
        assert programs.roofline_verdict(1e13, 1e12) == "memory_bound"

    def test_high_ai_is_compute_bound(self):
        assert programs.roofline_verdict(1e14, 1e11) == "compute_bound"

    def test_measured_dispatch_needs_real_peaks(self):
        # nominal mode must IGNORE measured wall time: CPU dispatch
        # seconds against a TPU roofline would mislabel everything
        assert programs.roofline_verdict(
            1e13, 1e12, avg_dispatch_s=999.0) == "memory_bound"
        # with real peaks, 60s measured vs a 1s roofline model is
        # launch/host overhead
        assert programs.roofline_verdict(
            1e13, 1e12, avg_dispatch_s=60.0,
            peak_fl=1e13, peak_bw_gbps=1000.0) == "dispatch_bound"
        assert programs.roofline_verdict(
            1e13, 1e12, avg_dispatch_s=5.0,
            peak_fl=1e13, peak_bw_gbps=1000.0) == "compute_bound"


# --------------------------------------------------------- registry
class TestProgramRegistry:
    def test_register_extracts_cost_and_memory(self):
        reg = programs.ProgramRegistry()
        sig = _register_square(reg)
        reg.record_dispatch("t_site", sig, None)     # untimed (compile)
        reg.record_dispatch("t_site", "nope", 9.9)   # unknown: dropped
        reg.record_dispatch("t_site", None, 9.9)
        snap = reg.snapshot()
        (row,) = snap["programs"]
        assert row["site"] == "t_site" and row["signature"] == sig
        assert row["flops"] > 0 and row["bytes_accessed"] > 0
        assert row["arithmetic_intensity"] == pytest.approx(
            row["flops"] / row["bytes_accessed"])
        assert row["dispatches"] == 2            # timed + untimed
        assert row["dispatch_seconds"] == pytest.approx(0.01)
        assert row["compile_seconds"] == 0.5
        assert len(row["hlo_digest"]) == 16
        assert set(row["memory"]) == {
            "temp_bytes", "argument_bytes", "output_bytes",
            "generated_code_bytes"}
        assert row["verdict"] in programs.VERDICTS
        # untimed dispatches must not fabricate achieved rates from
        # a partial denominator
        assert row["achieved_flops_per_s"] == pytest.approx(
            row["flops"] / 0.01)
        site = snap["sites"]["t_site"]
        assert site["dispatches"] == 2
        assert site["flops"] == pytest.approx(row["flops"] * 2)
        assert site["verdict"] == row["verdict"]

    def test_recompile_keeps_dispatch_history(self):
        reg = programs.ProgramRegistry()
        sig = _register_square(reg, seconds=(0.01, 0.02))
        _register_square(reg, seconds=())            # refresh, same key
        (row,) = reg.snapshot()["programs"]
        assert row["dispatches"] == 2
        assert row["dispatch_seconds"] == pytest.approx(0.03)

    def test_top_n_truncates_programs_not_sites(self):
        reg = programs.ProgramRegistry()
        for i, s in enumerate(("a", "b", "c")):
            _register_square(reg, site=s, n=8, seconds=(0.01 * (i + 1),))
        snap = reg.snapshot(top_n=1)
        assert len(snap["programs"]) == 1
        assert snap["programs"][0]["site"] == "c"    # most device time
        assert set(snap["sites"]) == {"a", "b", "c"}

    def test_module_snapshot_empty_until_registered(self):
        assert programs.snapshot() == {}
        programs.set_enabled(True)
        _register_square(programs.get_default(), n=8)
        assert programs.snapshot()["sites"].keys() == {"t_site"}

    def test_off_mode_record_dispatch_is_noop(self):
        assert not programs.enabled()
        programs.record_dispatch("t_site", "sig", 1.0)  # must not raise
        assert programs.snapshot() == {}

    def test_instrument_jit_populates_registry(self):
        programs.set_enabled(True)
        telemetry.set_enabled(True)
        wrapped = telemetry.instrument_jit(
            "prog_test_site", jax.jit(lambda x: x * 2 + 1))
        x = jnp.ones((16,), jnp.float32)
        for _ in range(3):
            wrapped(x)
        snap = programs.get_default().snapshot()
        site = snap["sites"].get("prog_test_site")
        assert site is not None
        # compile-call wall time is compile, not execution: only the
        # post-compile dispatches are counted
        assert site["dispatches"] == 3
        (row,) = [r for r in snap["programs"]
                  if r["site"] == "prog_test_site"]
        assert row["signature"] == "float32[16]"
        assert row["compile_seconds"] > 0


# ------------------------------------------------------- trace shims
class TestTraceShims:
    def test_double_start_is_idempotent_with_warning(self, monkeypatch,
                                                     tmp_path):
        fake = _FakeProfiler().install(monkeypatch)
        assert profiler.start_trace(str(tmp_path)) is True
        # the old code called jax.profiler.start_trace again here and
        # got RuntimeError from inside XLA
        assert profiler.start_trace(str(tmp_path)) is False
        assert fake.starts == 1
        assert profiler.stop_trace() is True
        assert profiler.stop_trace() is False
        assert fake.stops == 1

    def test_trace_ctx_does_not_stop_an_outer_trace(self, monkeypatch,
                                                    tmp_path):
        fake = _FakeProfiler().install(monkeypatch)
        assert profiler.start_trace(str(tmp_path / "outer")) is True
        with profiler.trace(str(tmp_path / "inner")):   # start refused
            pass
        assert fake.stops == 0                # inner exit: no stop
        assert profiler.stop_trace() is True  # outer still active
        assert fake.stops == 1

    def test_trace_ctx_stops_on_body_exception(self, monkeypatch,
                                               tmp_path):
        fake = _FakeProfiler().install(monkeypatch)
        with pytest.raises(ValueError):
            with profiler.trace(str(tmp_path)):
                raise ValueError("boom")
        assert (fake.starts, fake.stops) == (1, 1)
        assert programs.profile_session().active() is None

    def test_failed_start_leaves_slot_free(self, monkeypatch, tmp_path):
        def refuse(log_dir):
            raise RuntimeError("backend refused")

        monkeypatch.setattr(jax.profiler, "start_trace", refuse)
        with pytest.raises(RuntimeError):
            profiler.start_trace(str(tmp_path))
        assert programs.profile_session().active() is None


# ---------------------------------------------------------- captures
class TestProfileSession:
    def test_capture_roundtrips_digest_valid(self, monkeypatch,
                                             tmp_path):
        _FakeProfiler().install(monkeypatch)
        programs.set_enabled(True)
        _register_square(programs.get_default(), n=8)
        sess = programs.ProfileSession(directory=str(tmp_path))
        path = sess.capture(0.0, trigger="unit")
        assert path and os.path.basename(path).startswith("profile-")
        cap = programs.load_capture(path)
        assert cap["valid"] is True
        assert cap["manifest"]["trigger"] == "unit"
        assert "trace/trace.bin" in cap["manifest"]["digests"]
        assert cap["programs"]["sites"].keys() == {"t_site"}
        assert sess.last_bundle == path

    def test_tampered_bundle_is_invalid(self, monkeypatch, tmp_path):
        _FakeProfiler().install(monkeypatch)
        sess = programs.ProfileSession(directory=str(tmp_path))
        path = sess.capture(0.0, trigger="unit")
        with open(os.path.join(path, "programs.json"), "a") as f:
            f.write(" ")
        assert programs.load_capture(path)["valid"] is False

    def test_capture_refused_while_manual_trace_active(
            self, monkeypatch, tmp_path):
        fake = _FakeProfiler().install(monkeypatch)
        sess = programs.ProfileSession(directory=str(tmp_path))
        assert sess.start_manual(str(tmp_path / "t"))
        assert sess.capture(0.0, trigger="unit") is None
        assert fake.starts == 1               # no second start attempt
        assert sess.stop_manual()

    def test_capture_failure_never_raises_and_frees_slot(
            self, monkeypatch, tmp_path):
        def boom(log_dir):
            raise RuntimeError("no backend")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        sess = programs.ProfileSession(directory=str(tmp_path))
        assert sess.capture(0.0, trigger="unit") is None
        assert sess.active() is None
        assert sess.capture("bogus", trigger="unit") is None

    def test_pruning_keeps_newest(self, monkeypatch, tmp_path):
        _FakeProfiler().install(monkeypatch)
        monkeypatch.setattr(programs.ProfileSession, "KEEP_CAPTURES", 2)
        sess = programs.ProfileSession(directory=str(tmp_path))
        paths = [sess.capture(0.0, trigger=f"t{i}") for i in range(3)]
        assert all(paths)
        left = programs.list_captures(str(tmp_path))
        assert len(left) == 2
        assert paths[-1] in left

    def test_rate_limit_spans_automated_but_not_manual(
            self, monkeypatch, tmp_path):
        _FakeProfiler().install(monkeypatch)
        sess = programs.ProfileSession(directory=str(tmp_path))
        # a forced manual capture must NOT start the automated window
        assert sess.capture(0.0, trigger="manual")
        first = sess.maybe_capture(trigger="slo:a", duration_s=0.0,
                                   min_interval_s=3600.0)
        assert first is not None
        # ...but an automated capture does rate-limit the next one
        assert sess.maybe_capture(trigger="slo:b", duration_s=0.0,
                                  min_interval_s=3600.0) is None

    def test_capture_emits_flight_event_and_counter(self, monkeypatch,
                                                    tmp_path):
        _FakeProfiler().install(monkeypatch)
        reg = telemetry.MetricsRegistry.get_default()
        m = reg.peek(telemetry.PROFILE_CAPTURES)
        key = '{trigger="prog-unit-ev"}'
        before = (m._json().get(key, 0.0) if m is not None else 0.0)
        sess = programs.ProfileSession(directory=str(tmp_path))
        path = sess.capture(0.0, trigger="prog-unit-ev")
        evs = [e for e in flight_recorder.get_default().events()
               if e["kind"] == "profile_capture"
               and e.get("trigger") == "prog-unit-ev"]
        assert evs and evs[-1]["bundle"] == path
        after = reg.peek(telemetry.PROFILE_CAPTURES)._json()[key]
        assert after == before + 1.0


# -------------------------------------------------------------- http
class TestHttpHandlers:
    def test_programs_endpoint_shape_and_validation(self):
        programs.set_enabled(True)
        _register_square(programs.get_default(), n=8)
        out, status = programs.http_programs("")
        assert status == 200 and len(out["programs"]) == 1
        out, status = programs.http_programs("n=abc")
        assert status == 400
        for s in ("a", "b"):
            _register_square(programs.get_default(), site=s, n=8)
        out, status = programs.http_programs("n=1")
        assert status == 200 and len(out["programs"]) == 1

    def test_profile_endpoint_validation(self, monkeypatch, tmp_path):
        assert programs.http_profile("nope")[1] == 400
        assert programs.http_profile({"duration_s": "x"})[1] == 400
        assert programs.http_profile({"duration_s": 1e9})[1] == 400
        _FakeProfiler().install(monkeypatch)
        sess = programs.profile_session()
        assert sess.start_manual(str(tmp_path / "t"))
        assert programs.http_profile({})[1] == 409
        assert sess.stop_manual()
        out, status = programs.http_profile(
            {"duration_s": 0.0, "directory": str(tmp_path)})
        assert status == 200
        assert programs.load_capture(out["bundle"])["valid"]


# ----------------------------------------------------- slo page hook
class TestSLOProfileHook:
    def _fire(self, tmp_path, **engkw):
        reg = telemetry.MetricsRegistry()
        eng = slo.SLOEngine(
            [slo.Threshold("hot", metric="g", bound=1.0, op=">",
                           severity="page", group_by=())],
            registry=reg, make_default=False,
            flight_dir=str(tmp_path / "fl"),
            profile_dir=str(tmp_path / "pr"),
            profile_duration_s=0.0, **engkw)
        reg.gauge("g").set(5.0)
        eng.tick(now=0.0)
        (a,) = [a for a in eng.alerts() if a.state == "firing"]
        return eng, a

    def test_auto_mode_rides_the_registry_opt_in(self, monkeypatch,
                                                 tmp_path):
        _FakeProfiler().install(monkeypatch)
        _eng, a = self._fire(tmp_path)        # programs disabled: no
        assert a.profile_bundle is None       # capture, incident still
        assert a.incident_dump is not None    # written
        assert "profile_bundle" in a.to_dict()

    def test_page_alert_captures_and_stamps_incident(self, monkeypatch,
                                                     tmp_path):
        _FakeProfiler().install(monkeypatch)
        programs.set_enabled(True)
        _eng, a = self._fire(tmp_path)
        assert a.profile_bundle is not None
        assert programs.load_capture(a.profile_bundle)["valid"]
        assert a.to_dict()["profile_bundle"] == a.profile_bundle
        dump = flight_recorder.load_dump(a.incident_dump)
        assert dump["valid"]
        assert dump["manifest"]["context"]["profile_bundle"] \
            == a.profile_bundle

    def test_profile_on_page_false_disables(self, monkeypatch,
                                            tmp_path):
        _FakeProfiler().install(monkeypatch)
        programs.set_enabled(True)
        _eng, a = self._fire(tmp_path, profile_on_page=False)
        assert a.profile_bundle is None

    def test_refire_inside_min_interval_is_rate_limited(
            self, monkeypatch, tmp_path):
        _FakeProfiler().install(monkeypatch)
        programs.set_enabled(True)
        eng, a = self._fire(tmp_path,
                            profile_min_interval_s=3600.0)
        assert a.profile_bundle is not None
        eng.registry.peek("g").set(0.0)
        eng.tick(now=1.0)
        assert eng.alert_state("hot") == "resolved"
        eng.registry.peek("g").set(5.0)
        eng.tick(now=2.0)
        (b,) = [x for x in eng.alerts() if x.state == "firing"]
        assert b.profile_bundle is None       # inside the window


# -------------------------------------------- flight dump + snapshot
class TestObservabilityEmbeds:
    def test_incident_dump_carries_programs_member(self, tmp_path):
        programs.set_enabled(True)
        _register_square(programs.get_default(), n=8)
        fr = flight_recorder.FlightRecorder(directory=str(tmp_path),
                                            enabled=True)
        fr.record("unit", note="x")
        path = fr.incident("prog_unit")
        dump = flight_recorder.load_dump(path)
        assert dump["valid"]
        assert "programs.json" in dump["manifest"]["digests"]
        assert dump["programs"]["sites"].keys() == {"t_site"}

    def test_telemetry_snapshot_embeds_registry(self):
        telemetry.set_enabled(True)
        programs.set_enabled(True)
        _register_square(programs.get_default(), n=8)
        snap = telemetry.snapshot()
        assert snap["programs"]["sites"].keys() == {"t_site"}

    def test_off_mode_snapshot_has_no_programs_key(self):
        telemetry.set_enabled(True)
        assert "programs" not in telemetry.snapshot()
