"""Per-mapper Keras import battery (reference model:
KerasModelEndToEndTest — import saved models, compare predictions to
the originals'; SURVEY.md §4). Exists to close the executional mapper
gate (test_zzz_mapper_execution_gate.py): each case saves a tiny live
Keras model containing the target layer(s) and compares imported
inference output against keras.predict.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = tf.keras

from test_keras_import import _compare  # noqa: E402

from deeplearning4j_tpu.modelimport.keras import KerasModelImport


def _roundtrip(tmp_path, layers, x, **kw):
    m = keras.Sequential(layers)
    p = str(tmp_path / "m.h5")
    m.save(p)
    net = KerasModelImport.importKerasSequentialModelAndWeights(p)
    _compare(m, net, x, **kw)
    return net


RNG = np.random.default_rng(21)


class TestStochasticLayersInferenceIdentity:
    """Dropout-family layers are identity at inference; the mapper must
    produce nets whose output() matches keras.predict exactly."""

    def test_dropout_family(self, tmp_path):
        x = RNG.normal(size=(4, 10)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((10,)),
            keras.layers.Dense(8, activation="relu"),
            keras.layers.Dropout(0.4),
            keras.layers.GaussianDropout(0.3),
            keras.layers.GaussianNoise(0.2),
            keras.layers.AlphaDropout(0.1),
            keras.layers.Dense(3, activation="softmax"),
        ], x)

    def test_spatial_dropout_1d_2d_3d(self, tmp_path):
        x1 = RNG.normal(size=(2, 6, 5)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((6, 5)),
            keras.layers.SpatialDropout1D(0.3),
            keras.layers.Conv1D(4, 3, activation="relu"),
            keras.layers.GlobalAveragePooling1D(),
            keras.layers.Dense(2, activation="softmax"),
        ], x1)
        x2 = RNG.normal(size=(2, 8, 8, 3)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((8, 8, 3)),
            keras.layers.SpatialDropout2D(0.3),
            keras.layers.Conv2D(4, 3, activation="relu"),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ], x2)
        x3 = RNG.normal(size=(2, 4, 4, 4, 2)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((4, 4, 4, 2)),
            keras.layers.SpatialDropout3D(0.3),
            keras.layers.Conv3D(3, 2, activation="relu"),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ], x3)


class TestActivationAndMaskLayers:
    def test_activation_softmax_thresholded(self, tmp_path):
        x = RNG.normal(size=(4, 10)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((10,)),
            keras.layers.Dense(8),
            keras.layers.Activation("tanh"),
            keras.layers.Dense(6),
            keras.layers.ThresholdedReLU(theta=0.4),
            keras.layers.Dense(5),
            keras.layers.Softmax(),
        ], x)

    def test_masking_layer(self, tmp_path):
        # Masking passes values through; downstream layers here do not
        # consume the mask, so keras output == unmasked compute and the
        # imported MaskLayer pass-through must match exactly. (Keras's
        # RNN state-SKIPPING under masks is a different semantic the
        # framework covers via setLayerMaskArrays — tested in the
        # masking-parity suite, not an import concern.)
        x = RNG.normal(size=(3, 5, 4)).astype(np.float32)
        x[:, 3:, :] = 0.0
        _roundtrip(tmp_path, [
            keras.layers.Input((5, 4)),
            keras.layers.Masking(mask_value=0.0),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ], x)


class TestPoolingPaddingUpsampling:
    def test_average_pooling_1d_2d_3d(self, tmp_path):
        x1 = RNG.normal(size=(2, 8, 3)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((8, 3)),
            keras.layers.AveragePooling1D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ], x1)
        x2 = RNG.normal(size=(2, 8, 8, 3)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((8, 8, 3)),
            keras.layers.AveragePooling2D(2),
            keras.layers.GlobalMaxPooling2D(),
            keras.layers.Dense(2, activation="softmax"),
        ], x2)
        x3 = RNG.normal(size=(2, 6, 6, 6, 2)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((6, 6, 6, 2)),
            keras.layers.AveragePooling3D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ], x3)

    def test_zero_padding_cropping_upsampling_3d(self, tmp_path):
        x = RNG.normal(size=(2, 4, 4, 4, 2)).astype(np.float32)
        _roundtrip(tmp_path, [
            keras.layers.Input((4, 4, 4, 2)),
            keras.layers.ZeroPadding3D(1),
            keras.layers.Cropping3D(((1, 0), (0, 1), (1, 1))),
            keras.layers.UpSampling3D(2),
            keras.layers.Conv3D(3, 2, activation="relu"),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ], x)
