"""SLO engine (profiler/slo.py): windowed evaluation, rule types,
alert lifecycle (pending -> firing -> resolved, flap suppression,
counter-reset clamp, empty windows), burn-rate math, action hooks,
the built-in rule pack, HTTP surfaces, the control plane's
alert-driven serve scale-up, and bench_compare's round diff."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.profiler import (
    flight_recorder, slo, telemetry,
)


def _engine(rules, **kw):
    kw.setdefault("registry", telemetry.MetricsRegistry())
    kw.setdefault("make_default", False)
    return slo.SLOEngine(rules, **kw)


# ---------------------------------------------------------------- math
class TestHistogramQuantile:
    def test_interpolates_inside_bucket(self):
        # 10 samples all in (0.1, 0.5]: p50 at the bucket midpoint
        q = slo.histogram_quantile((0.1, 0.5, 1.0), (0, 10, 0, 0), 0.5)
        assert q == pytest.approx(0.3)

    def test_empty_window_is_none(self):
        assert slo.histogram_quantile((0.1, 0.5), (0, 0, 0), 0.99) \
            is None

    def test_inf_bucket_clamps_to_top_bound(self):
        q = slo.histogram_quantile((0.1, 0.5), (0, 0, 10), 0.9)
        assert q == 0.5

    def test_matches_bucket_boundaries(self):
        # 50 fast + 50 slow: p99 lands in the slow bucket
        q = slo.histogram_quantile((0.1, 1.0), (50, 0, 50), 0.99)
        assert q == 1.0
        q50 = slo.histogram_quantile((0.1, 1.0), (50, 50, 0), 0.25)
        assert 0 < q50 <= 0.1


# ------------------------------------------------------------ threshold
class TestThresholdLifecycle:
    def test_pending_firing_resolved(self):
        eng = _engine([slo.Threshold("hot", metric="g", bound=0.9,
                                     op=">", for_s=2.0)])
        g = eng.registry.gauge("g")
        g.set(0.5)
        eng.tick(now=0.0)
        assert eng.alerts() == []
        g.set(0.95)
        eng.tick(now=1.0)
        assert eng.alert_state("hot") == "pending"
        eng.tick(now=2.0)           # 1s pending: for_s not served
        assert eng.alert_state("hot") == "pending"
        eng.tick(now=3.5)
        assert eng.alert_state("hot") == "firing"
        g.set(0.1)
        eng.tick(now=4.0)
        assert eng.alert_state("hot") == "resolved"
        # re-breach: the same alert object re-enters the lifecycle
        g.set(0.99)
        eng.tick(now=5.0)
        assert eng.alert_state("hot") == "pending"

    def test_flapping_never_fires(self):
        """A pending alert whose condition clears before for_s is
        SUPPRESSED: no firing transition, ever."""
        eng = _engine([slo.Threshold("flap", metric="g", bound=1.0,
                                     op=">", for_s=10.0)])
        g = eng.registry.gauge("g")
        for i in range(5):          # breach for 2s, clear for 2s, ...
            g.set(2.0)
            eng.tick(now=i * 4.0)
            eng.tick(now=i * 4.0 + 2.0 - 0.01)
            g.set(0.0)
            eng.tick(now=i * 4.0 + 2.0)
        c = eng.registry.counter(telemetry.ALERTS_TOTAL)
        assert c.value(rule="flap", state="firing") == 0
        assert c.value(rule="flap", state="pending") == 5
        assert c.value(rule="flap", state="suppressed") == 5
        assert eng.alert_state("flap") == "inactive"

    def test_for_s_zero_fires_immediately(self):
        eng = _engine([slo.Threshold("now", metric="g", bound=1.0,
                                     op=">")])
        eng.registry.gauge("g").set(5.0)
        eng.tick(now=0.0)
        assert eng.alert_state("now") == "firing"

    def test_below_bound_op(self):
        eng = _engine([slo.Threshold("low", metric="g", bound=0.1,
                                     op="<")])
        g = eng.registry.gauge("g")
        g.set(0.5)
        eng.tick(now=0.0)
        assert eng.alert_state("low") == "inactive"
        g.set(0.01)
        eng.tick(now=1.0)
        assert eng.alert_state("low") == "firing"

    def test_per_labelset_dedup(self):
        """Each label set is its own alert; a condition that stays
        breached keeps ONE firing alert (no re-fire per tick)."""
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">")])
        g = eng.registry.gauge("g")
        g.set(2.0, engine="a")
        g.set(0.5, engine="b")
        for i in range(5):
            eng.tick(now=float(i))
        firing = eng.alerts(states=("firing",))
        assert len(firing) == 1
        assert firing[0].labels == {"engine": "a"}
        c = eng.registry.counter(telemetry.ALERTS_TOTAL)
        assert c.value(rule="hot", state="firing") == 1

    def test_vanished_series_resolves(self):
        """Stale-series expiry composes with alerting: when a dead
        engine's gauge series is removed, its firing alert resolves
        instead of firing forever."""
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">")])
        g = eng.registry.gauge("g")
        g.set(2.0, engine="dead")
        eng.tick(now=0.0)
        assert eng.alert_state("hot", engine="dead") == "firing"
        eng.registry.remove_matching("engine", "dead")
        eng.tick(now=1.0)
        assert eng.alert_state("hot", engine="dead") == "resolved"
        # within RESOLVED_RETENTION the resolved entry stays visible
        # (drills and operators poll alert_state right after recovery)
        eng.tick(now=2.0)
        assert eng.alert_state("hot", engine="dead") == "resolved"
        # still dark past retention: pruned (engine-id churn must not
        # grow the alert table forever); the record lives in history
        eng.tick(now=1.0 + slo.SLOEngine.RESOLVED_RETENTION + 1.0)
        assert eng.alert_state("hot", engine="dead") == "inactive"
        assert not eng.alerts()
        hist = eng.alerts_json()["history"]
        assert [h["to"] for h in hist
                if h["rule"] == "hot"] == ["firing", "resolved"]

    def test_quantile_threshold_windowed(self):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 0.5, 1.0))
        eng = _engine([slo.Threshold(
            "p99", metric="lat", quantile=0.99, window_s=10.0,
            bound=0.5, op=">", group_by=())], registry=reg)
        eng.tick(now=0.0)
        for _ in range(100):
            h.observe(0.05)
        eng.tick(now=10.0)
        assert eng.alert_state("p99") == "inactive"
        for _ in range(30):          # 30% now slow: p99 over 0.5s
            h.observe(2.0)
        eng.tick(now=20.0)
        assert eng.alert_state("p99") == "firing"

    def test_empty_window_evaluates_nothing(self):
        """Zero samples in the window: the rule does NOT evaluate —
        no alert appears, and quantiles never read the stale
        reservoir."""
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 0.5))
        h.observe(9.9)               # old slow sample, pre-history
        eng = _engine([slo.Threshold(
            "p99", metric="lat", quantile=0.99, window_s=5.0,
            bound=0.5, op=">", group_by=())], registry=reg)
        eng.tick(now=0.0)
        eng.tick(now=5.0)            # window delta = 0 samples
        eng.tick(now=10.0)
        assert eng.alerts() == []


# ----------------------------------------------------------------- rate
class TestRateRule:
    def test_rate_over_window(self):
        eng = _engine([slo.Rate("r", metric="c", bound=1.0,
                                window_s=10.0, group_by=())])
        c = eng.registry.counter("c")
        eng.tick(now=0.0)
        c.inc(5)
        eng.tick(now=10.0)           # 0.5/s: under bound
        assert eng.alert_state("r") == "inactive"
        c.inc(50)
        eng.tick(now=20.0)           # 5/s
        assert eng.alert_state("r") == "firing"

    def test_counter_reset_clamps_at_zero(self):
        """An engine restart zeroes its counters; the windowed rate
        must clamp at 0, never go negative (and the alert must
        resolve, not wedge)."""
        eng = _engine([slo.Rate("r", metric="c", bound=1.0,
                                window_s=10.0, group_by=())])
        c = eng.registry.counter("c")
        c.inc(100)
        eng.tick(now=0.0)
        c.inc(100)
        eng.tick(now=10.0)
        assert eng.alert_state("r") == "firing"
        # restart: the series starts over at a LOWER value
        with c._lock:
            c._values.clear()
        c.inc(1)
        eng.tick(now=20.0)
        a = [x for x in eng.alerts() if x.rule == "r"][0]
        assert a.state == "resolved"
        assert a.value == 0.0        # clamped, not -19.9/s

    def test_not_enough_history_never_fires(self):
        eng = _engine([slo.Rate("r", metric="c", bound=0.0,
                                window_s=60.0, group_by=())])
        c = eng.registry.counter("c")
        c.inc(100)
        eng.tick(now=0.0)
        c.inc(100)
        eng.tick(now=5.0)            # only 5s of history for a 60s rule
        assert eng.alerts() == []


# ------------------------------------------------------------ burn rate
class TestBurnRate:
    def _hist_engine(self, **kw):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 0.5, 1.0))
        kw.setdefault("factor", 4.0)
        eng = _engine([slo.BurnRate(
            "burn", histogram="lat", target_s=0.5, objective=0.99,
            fast_window_s=5.0, slow_window_s=10.0, group_by=(),
            **kw)], registry=reg)
        return eng, h

    def test_fires_when_both_windows_burn(self):
        eng, h = self._hist_engine()
        eng.tick(now=0.0)
        for _ in range(20):
            h.observe(2.0)           # 100% over target: burn = 100x
        eng.tick(now=5.0)
        for _ in range(20):
            h.observe(2.0)
        eng.tick(now=10.0)
        assert eng.alert_state("burn") == "firing"
        a = eng.alerts(states=("firing",))[0]
        assert a.value > 4.0

    def test_fast_window_recovery_resolves(self):
        """min(fast, slow): the fast window un-pages promptly after
        recovery even while the slow window still burns."""
        eng, h = self._hist_engine()
        eng.tick(now=0.0)
        for _ in range(20):
            h.observe(2.0)
        eng.tick(now=5.0)
        for _ in range(20):
            h.observe(2.0)
        eng.tick(now=10.0)
        assert eng.alert_state("burn") == "firing"
        for _ in range(200):
            h.observe(0.05)          # healthy traffic floods fast win
        eng.tick(now=15.0)
        assert eng.alert_state("burn") == "resolved"

    def test_slow_healthy_history_prevents_spike_page(self):
        """A short spike that the slow window dilutes below factor
        never fires — the multi-window guard against paging on one
        bad burst."""
        eng, h = self._hist_engine(factor=30.0)
        eng.tick(now=0.0)
        for _ in range(960):
            h.observe(0.05)          # long healthy history
        eng.tick(now=5.0)
        for _ in range(10):
            h.observe(2.0)           # brief spike (1% of slow window)
        eng.tick(now=10.0)
        assert eng.alert_state("burn") in ("inactive",)

    def test_counter_mode_error_ratio(self):
        reg = telemetry.MetricsRegistry()
        errs = reg.counter("errs")
        total = reg.counter("total")
        eng = _engine([slo.BurnRate(
            "errors", numerator="errs", denominator="total",
            objective=0.999, fast_window_s=5.0, slow_window_s=10.0,
            factor=4.0, group_by=())], registry=reg)
        eng.tick(now=0.0)
        total.inc(100)
        errs.inc(2)                  # 2% vs 0.1% budget: burn 20x
        eng.tick(now=5.0)
        total.inc(100)
        errs.inc(2)
        eng.tick(now=10.0)
        assert eng.alert_state("errors") == "firing"

    def test_counter_mode_empty_denominator_is_no_data(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("errs")
        reg.counter("total").inc(0)
        eng = _engine([slo.BurnRate(
            "errors", numerator="errs", denominator="total",
            objective=0.999, fast_window_s=5.0, slow_window_s=10.0,
            group_by=())], registry=reg)
        for i in range(4):
            eng.tick(now=i * 5.0)
        assert eng.alerts() == []

    def test_where_selector_filters_series(self):
        reg = telemetry.MetricsRegistry()
        lat = reg.histogram("lat", buckets=(0.1,))
        eng = _engine([slo.BurnRate(
            "errors", numerator=("lat", {"reason": "error"}),
            denominator="lat", objective=0.99, fast_window_s=5.0,
            slow_window_s=10.0, factor=4.0, group_by=())],
            registry=reg)
        eng.tick(now=0.0)
        for _ in range(45):
            lat.observe(0.05, reason="length")
        for _ in range(5):
            lat.observe(0.05, reason="error")    # 10% errors
        eng.tick(now=5.0)
        for _ in range(10):
            lat.observe(0.05, reason="error")
        eng.tick(now=10.0)
        assert eng.alert_state("errors") == "firing"


# ------------------------------------------------- transitions + sinks
class TestAlertSinks:
    def test_flight_events_and_metrics_on_every_transition(self):
        flight_recorder.reset()
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">", for_s=1.0)])
        g = eng.registry.gauge("g")
        g.set(2.0)
        eng.tick(now=0.0)
        eng.tick(now=1.5)
        g.set(0.0)
        eng.tick(now=2.0)
        states = [e["state"] for e in flight_recorder.get_default()
                  .events() if e["kind"] == "alert"]
        assert states == ["pending", "firing", "resolved"]
        c = eng.registry.counter(telemetry.ALERTS_TOTAL)
        for state in ("pending", "firing", "resolved"):
            assert c.value(rule="hot", state=state) == 1
        # the active gauge tracked the lifecycle and ended at 0
        act = eng.registry.gauge(telemetry.ALERTS_ACTIVE)
        assert act.value(state="firing") == 0
        assert act.value(state="pending") == 0

    def test_page_severity_dumps_digest_valid_incident(self, tmp_path):
        flight_recorder.reset()
        eng = _engine([slo.Threshold("p99_melt", metric="g",
                                     bound=1.0, op=">",
                                     severity="page")],
                      flight_dir=str(tmp_path))
        eng.registry.gauge("g").set(9.0)
        eng.tick(now=0.0)
        a = eng.alerts(states=("firing",))[0]
        assert a.incident_dump is not None
        dump = flight_recorder.load_dump(a.incident_dump)
        assert dump["valid"]
        assert dump["manifest"]["reason"] == "slo_page"
        assert dump["manifest"]["context"]["rule"] == "p99_melt"
        # the dump's last event is the incident itself
        assert dump["events"][-1]["kind"] == "slo_page"

    def test_on_alert_subscription_and_bad_subscriber(self):
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">")])
        seen = []
        eng.on_alert(lambda a: seen.append((a.rule, a.state)))
        eng.on_alert(lambda a: 1 / 0)   # must not break evaluation
        g = eng.registry.gauge("g")
        g.set(2.0)
        eng.tick(now=0.0)
        g.set(0.0)
        eng.tick(now=1.0)
        assert seen == [("hot", "firing"), ("hot", "resolved")]

    def test_on_alert_pending_opt_in(self):
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">", for_s=5.0)])
        seen = []
        eng.on_alert(lambda a: seen.append(a.state),
                     states=("pending", "firing"))
        eng.registry.gauge("g").set(2.0)
        eng.tick(now=0.0)
        assert seen == ["pending"]
        with pytest.raises(ValueError, match="unknown alert states"):
            eng.on_alert(lambda a: None, states=("exploded",))

    def test_webhook_posts_firing_and_resolved(self):
        from http.server import (
            BaseHTTPRequestHandler, ThreadingHTTPServer,
        )

        got = []

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                got.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/hook"
            eng = _engine([slo.Threshold("hot", metric="g",
                                         bound=1.0, op=">")],
                          webhook_url=url)
            g = eng.registry.gauge("g")
            g.set(2.0)
            eng.tick(now=0.0)
            g.set(0.0)
            eng.tick(now=1.0)
        finally:
            srv.shutdown()
            srv.server_close()
        assert [p["state"] for p in got] == ["firing", "resolved"]
        assert got[0]["rule"] == "hot"


# ------------------------------------------------------ engine plumbing
class TestEnginePlumbing:
    def test_evaluator_thread_name_and_clean_shutdown(self):
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">")], interval_s=0.01)
        eng.registry.gauge("g").set(5.0)
        with eng:
            deadline = time.monotonic() + 5
            while eng.ticks == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.ticks > 0
            assert any(t.name == "SLOEvaluator"
                       for t in threading.enumerate())
            deadline = time.monotonic() + 5
            while not eng.alerts(states=("firing",)) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.alert_state("hot") == "firing"
        assert not any(t.name == "SLOEvaluator" and t.is_alive()
                       for t in threading.enumerate())

    def test_shutdown_zeroes_active_alerts_gauge(self):
        """A dead engine must not leave dl4j_tpu_alerts_active frozen
        at its last pending/firing counts (the stale-series
        discipline, applied to the engine's own gauges)."""
        reg = telemetry.MetricsRegistry()
        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0)],
                      registry=reg)
        reg.gauge("g").set(5.0)
        eng.tick(now=0.0)
        assert reg.gauge(telemetry.ALERTS_ACTIVE).value(
            state="firing") == 1
        eng.shutdown()
        assert reg.gauge(telemetry.ALERTS_ACTIVE).value(
            state="firing") == 0
        assert reg.gauge(telemetry.ALERTS_ACTIVE).value(
            state="pending") == 0

    def test_duplicate_rule_name_rejected(self):
        eng = _engine([slo.Threshold("x", metric="g", bound=1)])
        with pytest.raises(ValueError, match="duplicate"):
            eng.add_rule(slo.Rate("x", metric="c", bound=1,
                                  window_s=5))

    def test_rule_validation(self):
        with pytest.raises(ValueError, match="severity"):
            slo.Threshold("x", metric="g", bound=1, severity="chaos")
        with pytest.raises(ValueError, match="objective"):
            slo.BurnRate("x", objective=1.5, fast_window_s=1,
                         slow_window_s=2, numerator="a",
                         denominator="b")
        with pytest.raises(ValueError, match="histogram mode"):
            slo.BurnRate("x", objective=0.99, fast_window_s=1,
                         slow_window_s=2, histogram="h")
        with pytest.raises(ValueError, match="window_s"):
            slo.Threshold("x", metric="h", bound=1, quantile=0.99)

    def test_builtin_packs(self):
        rules = slo.default_rules(p99_target_s=0.2, mfu_floor=0.1)
        names = {r.name for r in rules}
        assert {"serving_p99_burn", "serving_ttft_p99",
                "serving_error_rate", "serving_429_burn",
                "serving_kv_utilization", "serving_queue_pressure",
                "train_mfu_drop", "train_watchdog_stalls",
                "train_divergence_rollbacks",
                "train_prefetch_starvation"} <= names
        qp = next(r for r in rules
                  if r.name == "serving_queue_pressure")
        assert qp.action == "scale_serve"
        burn = next(r for r in rules if r.name == "serving_p99_burn")
        assert burn.severity == "page" and burn.target_s == 0.2
        with pytest.raises(TypeError, match="unknown"):
            slo.default_rules(nope=1)

    def test_alerts_json_and_snapshot(self):
        eng = _engine(slo.default_rules())
        eng.registry.gauge(
            telemetry.SERVING_KV_PAGE_UTILIZATION).set(0.99)
        for i in range(30):
            eng.tick(now=float(i))
        out = eng.alerts_json()
        assert out["ticks"] == 30
        assert len(out["rules"]) == 10
        firing = [a for a in out["alerts"] if a["state"] == "firing"]
        assert firing and firing[0]["rule"] == "serving_kv_utilization"
        snap = eng.snapshot()
        assert snap["rules"] == 10 and snap["firing"]

    def test_default_engine_registration(self):
        assert slo.default_engine() is None
        eng = _engine([], make_default=True)
        try:
            assert slo.default_engine() is eng
            assert telemetry.snapshot().get("alerts") is not None
        finally:
            eng.shutdown()
        assert slo.default_engine() is None
        assert slo.alerts_snapshot() == {}


# ------------------------------------------- shared-capture sampler
class TestSamplerBackedEngine:
    def test_tsdb_backed_engine_fires_identically(self):
        """An engine riding the TSDB sampler's shared capture walks
        the exact same pending -> firing -> resolved lifecycle as one
        ticking its own registry directly — same rules, same registry,
        same fake clock, state compared at every step."""
        from deeplearning4j_tpu.profiler import timeseries as ts

        reg = telemetry.MetricsRegistry()

        def rules():
            return [slo.Threshold("hot", metric="g", bound=0.9,
                                  op=">", for_s=2.0)]

        direct = _engine(rules(), registry=reg)
        sampler = ts.Sampler(db=ts.TimeSeriesDB(), registry=reg,
                             interval_s=60.0)
        backed = _engine(rules(), registry=reg, sampler=sampler)
        script = [0.5, 0.95, 0.95, 0.95, 0.95, 0.5, 0.5, 0.95, 0.5]
        seen = []
        for i, v in enumerate(script):
            t = float(i)
            reg.gauge("g").set(v)
            direct.tick(now=t)
            sampler.tick_once(now_mono=t, now_wall=1000.0 + t)
            seen.append((direct.alert_state("hot"),
                         backed.alert_state("hot")))
        assert [a for a, _b in seen] == [b for _a, b in seen]
        assert "firing" in [a for a, _b in seen]
        assert direct.alerts_json()["alerts"] == \
            backed.alerts_json()["alerts"]
        backed.shutdown()
        direct.shutdown()
        # shutdown detached the subscription: further ticks are
        # invisible to the dead engine
        before = backed.ticks
        sampler.tick_once(now_mono=99.0, now_wall=1099.0)
        assert backed.ticks == before

    def test_attach_refuses_while_thread_alive(self):
        from deeplearning4j_tpu.profiler import timeseries as ts

        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0)],
                      interval_s=0.01)
        sampler = ts.Sampler(db=ts.TimeSeriesDB(),
                             registry=eng.registry)
        with eng:
            deadline = time.monotonic() + 5
            while eng.ticks == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            eng.attach_sampler(sampler)     # refused: no double-tick
            assert eng._sampler is None
        assert sampler._subs == []


# ------------------------------------------------------------- HTTP
class TestAlertsHTTP:
    def test_http_alerts_404_without_engine(self):
        obj, code = slo.http_alerts()
        assert code == 404 and "no SLO engine" in obj["error"]

    def test_v1_alerts_on_ui_server(self):
        from deeplearning4j_tpu.ui.server import UIServer

        eng = _engine([slo.Threshold("hot", metric="g", bound=1.0,
                                     op=">")], make_default=True)
        eng.registry.gauge("g").set(2.0)
        eng.tick(now=0.0)
        ui = UIServer()
        port = ui.start(port=0)
        try:
            out = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/alerts",
                timeout=10).read())
            assert out["alerts"][0]["rule"] == "hot"
            assert out["alerts"][0]["state"] == "firing"
            assert out["rules"][0]["kind"] == "threshold"
        finally:
            ui.stop()
            eng.shutdown()
        # 404 with a hint once the engine is gone
        ui2 = UIServer()
        port = ui2.start(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/alerts", timeout=10)
            assert ei.value.code == 404
        finally:
            ui2.stop()

    def test_dashboard_has_alerts_card(self):
        from deeplearning4j_tpu.ui.server import _DASHBOARD_HTML

        assert "Alerts (SLO engine)" in _DASHBOARD_HTML


# --------------------------------------------- control-plane actions
class TestSchedulerIntegration:
    def _tiny_fleet_job(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu import control
        from deeplearning4j_tpu.models.gpt import CausalLM
        from deeplearning4j_tpu.models.transformer import tiny_config
        from deeplearning4j_tpu.serving import ServingFleet

        cfg = tiny_config(vocab=13, max_len=32, d_model=16,
                          n_layers=1, n_heads=2, d_ff=32)
        cfg.dropout = 0.0
        m = CausalLM(cfg, compute_dtype=jnp.float32)
        params = m.init_params(jax.random.key(0))

        def build(ctx):
            return ServingFleet(m, params, devices=ctx.devices,
                                slots=2, page_size=8,
                                prefill_buckets=[8], max_chunk=2)

        devs = jax.devices()[:2]
        return control, devs, control.ServeJob(
            build, chips=2, min_chips=1, tenant="t")

    @pytest.mark.slow
    def test_queue_pressure_alert_restarts_drained_replica(self):
        """End to end: drain a replica (rebalance hand-back), then a
        FIRING serving_queue_pressure alert makes the scheduler
        restart it — the ROADMAP's 'scale serve replicas on sustained
        queue pressure instead of one-shot rebalance'."""
        control, devs, job = self._tiny_fleet_job()
        slo_eng = _engine(
            [slo.Threshold("serving_queue_pressure",
                           metric=telemetry.SERVING_FLEET_PRESSURE,
                           bound=1.0, op=">", for_s=0.0,
                           action="scale_serve")],
            registry=telemetry.MetricsRegistry.get_default())
        sched = control.JobScheduler(
            devices=devs,
            workers={"w0": devs[:1], "w1": devs[1:]},
            slo=slo_eng, make_default=False)
        try:
            sched.start()
            sched.submit(job)
            sched.wait(job.job_id, timeout=60, states=("running",))
            deadline = time.monotonic() + 30
            while job.fleet is None and time.monotonic() < deadline:
                time.sleep(0.02)
            fl = job.fleet
            assert fl is not None
            fl.drain_replica(1)
            assert fl.alive_replicas() == 1
            # the drained chip went back to the pool
            deadline = time.monotonic() + 10
            while sched.devices.free == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sched.devices.free == 1
            # sustained pressure: publish the gauge breached and tick
            telemetry.MetricsRegistry.get_default().gauge(
                telemetry.SERVING_FLEET_PRESSURE).set(
                3.0, fleet=fl.fleet_id)
            slo_eng.tick()
            deadline = time.monotonic() + 30
            while fl.alive_replicas() < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert fl.alive_replicas() == 2
            assert sched.devices.free == 0       # chip re-acquired
            reg = telemetry.MetricsRegistry.get_default()
            assert reg.counter(telemetry.JOBS_RESTARTS).value(
                job=job.job_id, reason="queue_pressure_alert") == 1
            # the restarted replica still serves
            out = fl.generate(np.asarray([1, 2, 3], np.int32), 3)
            assert len(out) == 3
        finally:
            sched.shutdown()
            slo_eng.shutdown()

    def test_rebalance_vetoed_while_pressure_alert_active(self):
        """Hysteresis: with an SLO engine attached, _maybe_rebalance
        must not drain a replica from a fleet whose queue-pressure
        alert is pending/firing, even if an instantaneous poll would
        read idle."""
        from deeplearning4j_tpu import control

        slo_eng = _engine([slo.Threshold(
            "serving_queue_pressure",
            metric=telemetry.SERVING_FLEET_PRESSURE, bound=1.0,
            op=">", for_s=100.0, action="scale_serve")])
        sched = control.JobScheduler(devices=["c0"], slo=slo_eng,
                                     make_default=False)
        try:
            class _FakeEngine:
                _device = None
                slots = 2

                def queue_depth(self):
                    return 0

            class _FakeReplica:
                alive = True
                draining = False
                engine = _FakeEngine()

                def __init__(self, index):
                    self.index = index

            class _FakeFleet:
                fleet_id = "fleet-test"
                # two replicas: one above min_chips (clamped to 1), so
                # exactly one is drainable
                _replicas = [_FakeReplica(0), _FakeReplica(1)]

                def queue_pressure(self):
                    return 0.0       # instantaneous poll says idle

                def drain_replica(self, idx):
                    raise AssertionError("drained despite alert")

                def cancel_pending(self):
                    pass

                def shutdown(self, timeout=None):
                    pass

            job = control.ServeJob(lambda ctx: None, chips=1,
                                   min_chips=0)
            job.state = "running"
            job.fleet = _FakeFleet()
            sched._jobs[job.job_id] = job
            starved = control.TrainJob(lambda ctx: None, chips=1)
            starved._pending_since = time.monotonic() - 100
            # alert pending on this fleet: veto
            slo_eng.registry.gauge(
                telemetry.SERVING_FLEET_PRESSURE).set(
                5.0, fleet="fleet-test")
            slo_eng.tick(now=0.0)
            assert slo_eng.alert_state(
                "serving_queue_pressure",
                fleet="fleet-test") == "pending"
            sched._maybe_rebalance(starved)     # must not drain
            # alert cleared: the drain goes ahead
            drained = []
            job.fleet.drain_replica = lambda idx: drained.append(idx)
            slo_eng.registry.gauge(
                telemetry.SERVING_FLEET_PRESSURE).set(
                0.0, fleet="fleet-test")
            slo_eng.tick(now=1.0)
            sched._maybe_rebalance(starved)
            deadline = time.monotonic() + 5
            while not drained and time.monotonic() < deadline:
                time.sleep(0.02)
            assert drained == [1]      # the victim is the LAST alive
        finally:
            sched.shutdown()
            slo_eng.shutdown()


    def test_direct_pressure_poll_survives_slo_attach(self):
        """Attaching an SLO engine must ADD hysteresis, not silently
        drop the pre-SLO protection: with no queue-pressure data in
        the engine (alert inactive), a fleet whose direct
        queue_pressure() poll reads busy still keeps its replicas."""
        from deeplearning4j_tpu import control

        slo_eng = _engine([slo.Threshold(
            "serving_queue_pressure",
            metric=telemetry.SERVING_FLEET_PRESSURE, bound=1.0,
            op=">", for_s=100.0, action="scale_serve")])
        sched = control.JobScheduler(devices=["c0"], slo=slo_eng,
                                     make_default=False)
        try:
            class _FakeEngine:
                _device = None
                slots = 2

                def queue_depth(self):
                    return 9

            class _FakeReplica:
                alive = True
                draining = False
                engine = _FakeEngine()

                def __init__(self, index):
                    self.index = index

            class _FakeFleet:
                fleet_id = "fleet-busy"
                _replicas = [_FakeReplica(0), _FakeReplica(1)]

                def queue_pressure(self):
                    return 4.0       # direct poll says BUSY

                def drain_replica(self, idx):
                    raise AssertionError(
                        "drained a busy fleet: SLO attach dropped "
                        "the direct pressure poll")

                def cancel_pending(self):
                    pass

                def shutdown(self, timeout=None):
                    pass

            job = control.ServeJob(lambda ctx: None, chips=1,
                                   min_chips=0)
            job.state = "running"
            job.fleet = _FakeFleet()
            sched._jobs[job.job_id] = job
            starved = control.TrainJob(lambda ctx: None, chips=1)
            starved._pending_since = time.monotonic() - 100
            # the engine has never seen SERVING_FLEET_PRESSURE data:
            # the alert is inactive, only the direct poll protects
            slo_eng.tick(now=0.0)
            assert slo_eng.alert_state(
                "serving_queue_pressure",
                fleet="fleet-busy") == "inactive"
            sched._maybe_rebalance(starved)     # must not drain
        finally:
            sched.shutdown()
            slo_eng.shutdown()

    def test_reconcile_retries_firing_scale_serve_alert(self):
        """The firing transition is edge-triggered and deduplicated —
        a scale-up skipped on the transition (fleet not built yet,
        chip briefly held elsewhere) must be re-attempted by the
        supervision loop while the alert STAYS firing."""
        from deeplearning4j_tpu import control

        slo_eng = _engine([slo.Threshold(
            "serving_queue_pressure",
            metric=telemetry.SERVING_FLEET_PRESSURE, bound=1.0,
            op=">", for_s=0.0, action="scale_serve")])
        sched = control.JobScheduler(devices=["c0"], slo=slo_eng,
                                     make_default=False)
        attempts = []
        try:
            sched._on_slo_alert = lambda a: attempts.append(a.rule)
            slo_eng.registry.gauge(
                telemetry.SERVING_FLEET_PRESSURE).set(
                5.0, fleet="fleet-x")
            # edge delivery goes to the bound method subscribed at
            # attach (no ServeJob -> no-op); the reconcile pass below
            # resolves the instance-attr stub instead
            slo_eng.tick(now=0.0)
            assert slo_eng.alert_state(
                "serving_queue_pressure", fleet="fleet-x") == "firing"
            sched._last_slo_reconcile = 0.0
            sched._reconcile_slo()
            assert attempts.count("serving_queue_pressure") >= 1
            # throttled: an immediate second pass is a no-op
            n = len(attempts)
            sched._reconcile_slo()
            assert len(attempts) == n
        finally:
            sched.shutdown()
            slo_eng.shutdown()


# ----------------------------------------------------- bench compare
class TestBenchCompare:
    def test_regression_detected_and_tolerance(self):
        import bench_compare as bc

        prior = {"metric": "bert", "value": 100.0, "unit": "t/s",
                 "resnet50_mfu": 0.25, "gpt_decode_ms_per_step": 10.0,
                 "serving_prefix_token_identical": True,
                 "vs_baseline": 1.0, "lstm_hidden": 256,
                 "vs_frozen_band_lo": 1.05}
        current = dict(prior, value=85.0,
                       gpt_decode_ms_per_step=12.0)
        report, regs = bc.compare_rounds(prior, current,
                                         tolerance=0.1)
        assert len(regs) == 2        # throughput -15%, ms +20%
        assert any("value" in r for r in regs)
        assert any("ms_per_step" in r for r in regs)
        # within tolerance: clean
        _, regs = bc.compare_rounds(prior, dict(prior, value=95.0),
                                    tolerance=0.1)
        assert regs == []
        # skipped keys never regress
        _, regs = bc.compare_rounds(
            prior, dict(prior, vs_baseline=0.1, lstm_hidden=1,
                        vs_frozen_band_lo=0.0), tolerance=0.1)
        assert regs == []

    def test_zero_prior_never_hides_a_regression(self):
        import bench_compare as bc

        # a lower-better metric recorded 0 in the prior round: any
        # move off zero is an infinite relative change, not "+0.0%"
        _, regs = bc.compare_rounds({"a_ms": 0.0}, {"a_ms": 99.0},
                                    tolerance=0.1)
        assert len(regs) == 1
        # higher-better appearing from zero is an improvement
        _, regs = bc.compare_rounds({"tput": 0.0}, {"tput": 50.0},
                                    tolerance=0.1)
        assert regs == []
        # zero -> zero is clean
        _, regs = bc.compare_rounds({"a_ms": 0.0}, {"a_ms": 0.0},
                                    tolerance=0.1)
        assert regs == []

    def test_bool_gate_flip_fails_regardless_of_tolerance(self):
        import bench_compare as bc

        prior = {"serving_prefix_token_identical": True}
        _, regs = bc.compare_rounds(
            prior, {"serving_prefix_token_identical": False},
            tolerance=10.0)
        assert len(regs) == 1

    def test_load_round_formats(self, tmp_path):
        import bench_compare as bc

        line = {"metric": "x", "value": 5.0}
        p1 = tmp_path / "round.json"
        p1.write_text(json.dumps({"n": 3, "parsed": line,
                                  "tail": "..."}))
        assert bc.load_round(str(p1)) == line
        p2 = tmp_path / "bare.json"
        p2.write_text(json.dumps(line))
        assert bc.load_round(str(p2)) == line
        p3 = tmp_path / "stdout.txt"
        p3.write_text("WARNING: noise\n" + json.dumps(line) + "\n")
        assert bc.load_round(str(p3)) == line
        p4 = tmp_path / "empty.txt"
        p4.write_text("no json here")
        with pytest.raises(ValueError, match="no aggregate line"):
            bc.load_round(str(p4))
