"""Elastic control plane (control/scheduler.py): gang scheduling over
one device fleet, health verdicts (worker death / stall / divergence),
checkpoint-and-migrate onto a reduced topology, retry budgets with
backoff, serving jobs with replica restart and capacity hand-back —
plus the satellites: engine/fleet request cancel, the chaos hang
injector, and the idempotent HTTP generate."""

import json
import tempfile
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import control
from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.models.gpt import CausalLM
from deeplearning4j_tpu.models.transformer import tiny_config
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.profiler import chaos, flight_recorder, telemetry
from deeplearning4j_tpu.remote.server import (
    JsonModelServer, JsonRemoteInference,
)
from deeplearning4j_tpu.serving import DecodeEngine, ServingFleet
from deeplearning4j_tpu.util.resilience import FaultTolerance

DEVS = jax.devices()
VOCAB = 17


def small_net(seed=9):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater(Adam(learning_rate=0.01)).list()
         .layer(DenseLayer(n_out=8, activation="tanh"))
         .layer(OutputLayer(n_out=2, activation="softmax",
                            loss="mcxent"))
         .setInputType(InputType.feedForward(4)).build())).init()


def toy_data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return x, y


X, Y = toy_data()


def data_iter():
    return ArrayDataSetIterator(X, Y, 8, shuffle=True, seed=5)


class SlowIter(ArrayDataSetIterator):
    """Stateful iterator with a per-batch delay, so a drill can land
    mid-fit deterministically."""

    def __init__(self, *a, delay=0.03, **kw):
        super().__init__(*a, **kw)
        self._delay = delay

    def next(self):
        time.sleep(self._delay)
        return super().next()


def make_sched(**kw):
    kw.setdefault("devices", DEVS[:4])
    kw.setdefault("workers", {"w0": DEVS[:2], "w1": DEVS[2:4]})
    kw.setdefault("rebalance", False)
    return control.JobScheduler(**kw)


def _gpt_model():
    cfg = tiny_config(vocab=VOCAB, max_len=64, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    return CausalLM(cfg, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def gpt():
    m = _gpt_model()
    return m, m.init_params(jax.random.key(1))


def _engine(model, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", [8, 16, 40])
    kw.setdefault("max_chunk", 4)
    return DecodeEngine(model, params, **kw)


# ======================================================================
# device fleet
# ======================================================================
class TestDeviceFleet:
    def test_gang_all_or_nothing(self):
        fl = control.DeviceFleet(devices=DEVS[:3],
                                 workers={"w": DEVS[:3]})
        assert fl.acquire(4, "j") is None
        got = fl.acquire(3, "j")
        assert len(got) == 3 and fl.free == 0
        fl.release(got)
        assert fl.free == 3

    def test_release_is_idempotent_per_device(self):
        fl = control.DeviceFleet(devices=DEVS[:2],
                                 workers={"w": DEVS[:2]})
        got = fl.acquire(2, "j")
        fl.release(got)
        fl.release(got)      # double hand-back must not inflate
        assert fl.free == 2 and fl.total == 2

    def test_lose_and_restore_worker(self):
        fl = control.DeviceFleet(
            devices=DEVS[:4],
            workers={"a": DEVS[:2], "b": DEVS[2:4]})
        lost = fl.lose_worker("b")
        assert len(lost) == 2 and fl.free == 2 and fl.lost == 2
        assert fl.acquire(3, "j") is None     # gang can't span the dead
        assert fl.is_lost(DEVS[2])
        fl.restore_worker("b")
        assert fl.free == 4 and fl.lost == 0


# ======================================================================
# scheduler core
# ======================================================================
class TestScheduler:
    def test_train_job_completes_and_releases_devices(self):
        holder = {}

        def run(ctx):
            net = small_net()
            holder["net"] = net
            net.fit(data_iter(), epochs=2,
                    fault_tolerance=ctx.fault_tolerance)
            return float(net._score)

        with make_sched() as s:
            job = s.submit(control.TrainJob(run, name="ok", chips=1))
            s.wait(job.job_id, timeout=120)
            assert job.state == "completed", job.status()
            assert holder["net"].getIterationCount() == 12
            assert job.devices == [] and s.devices.free == 4
            assert job.result == pytest.approx(
                float(holder["net"]._score))
        kinds = [e["kind"] for e in flight_recorder.get_default().events()]
        assert "job_submit" in kinds and "job_finished" in kinds

    def test_retry_budget_with_backoff_then_success(self):
        attempts = []

        def run(ctx):
            attempts.append(ctx.attempt)
            if ctx.attempt == 1:
                raise RuntimeError("flaky infra")
            net = small_net()
            net.fit(data_iter(), epochs=1,
                    fault_tolerance=ctx.fault_tolerance)

        with make_sched() as s:
            job = s.submit(control.TrainJob(
                run, chips=1, max_retries=2, backoff_s=0.05))
            s.wait(job.job_id, timeout=120)
            assert job.state == "completed"
            assert attempts == [1, 2]
            assert job.retries_used == 1

    def test_retry_budget_exhausted_fails(self):
        def run(ctx):
            raise RuntimeError("always broken")

        with make_sched() as s:
            job = s.submit(control.TrainJob(
                run, chips=1, max_retries=1, backoff_s=0.01))
            s.wait(job.job_id, timeout=60)
            assert job.state == "failed"
            assert "retry budget exhausted" in job.error
            assert s.devices.free == 4

    def test_cancel_pending_job(self):
        ev = threading.Event()

        def hog(ctx):
            ev.wait(20)

        def never(ctx):            # pragma: no cover - must not run
            raise AssertionError("cancelled job ran")

        with make_sched() as s:
            h = s.submit(control.TrainJob(hog, chips=4))
            s.wait(h.job_id, timeout=30, states=("running",))
            j = s.submit(control.TrainJob(never, chips=4))
            time.sleep(0.1)
            assert j.state == "pending"
            s.cancel(j.job_id)
            assert j.state == "cancelled"
            ev.set()
            s.wait(h.job_id, timeout=30)

    def test_gang_scheduling_two_jobs_share_fleet(self):
        """A 2-chip job and a 1-chip job run concurrently on disjoint
        device grants."""
        grants = {}
        ev = threading.Event()

        def run(name):
            def _r(ctx):
                grants[name] = list(ctx.devices)
                ev.wait(30)
            return _r

        with make_sched() as s:
            a = s.submit(control.TrainJob(run("a"), chips=2))
            b = s.submit(control.TrainJob(run("b"), chips=1))
            s.wait(a.job_id, timeout=30, states=("running",))
            s.wait(b.job_id, timeout=30, states=("running",))
            assert len(grants["a"]) == 2 and len(grants["b"]) == 1
            assert not set(grants["a"]) & set(grants["b"])
            ev.set()
            s.wait(a.job_id, timeout=30)
            s.wait(b.job_id, timeout=30)


# ======================================================================
# the migration drill (kill a worker mid-fit)
# ======================================================================
class TestMigration:
    def test_worker_kill_migrates_and_finishes_bit_identical(
            self, tmp_path):
        """SIGKILL-equivalent worker death mid-fit: the job recovers
        its newest periodic bundle, reschedules onto the surviving
        worker, finishes at the exact total step count, and the final
        loss is BIT-identical to an uninterrupted run (PR 4's resume
        guarantee, now driven by the scheduler)."""
        nets = []

        def run(ctx):
            net = small_net(seed=3)
            nets.append(net)
            it = (SlowIter(X, Y, 8, shuffle=True, seed=5)
                  if ctx.attempt == 1 else data_iter())
            net.fit(it, epochs=3,
                    fault_tolerance=ctx.fault_tolerance)
            return float(net._score)

        with make_sched() as s:
            job = s.submit(control.TrainJob(
                run, chips=1, checkpoint_dir=str(tmp_path / "ck"),
                checkpoint_every=3, backoff_s=0.05))
            s.wait(job.job_id, timeout=60, states=("running",))
            deadline = time.time() + 60
            while (not nets or nets[0].getIterationCount() < 5) \
                    and time.time() < deadline:
                assert job.state not in control.TERMINAL, job.status()
                time.sleep(0.01)
            worker = ("w0" if job.devices[0] in DEVS[:2] else "w1")
            s.kill_worker(worker)
            s.wait(job.job_id, timeout=120)
            assert job.state == "completed", job.status()
            assert job.retries_used == 1 and job.attempts == 2
            # rescheduled on the SURVIVING worker's devices
            survivors = DEVS[2:4] if worker == "w0" else DEVS[:2]
            # exact total step count
            assert nets[-1].getIterationCount() == 18
        # bit-identical to an uninterrupted run (same seed/data)
        ref = small_net(seed=3)
        ref.fit(data_iter(), epochs=3)
        assert float(ref._score) == job.result
        # the death is an incident dump; resume + migration visible
        kinds = [e["kind"]
                 for e in flight_recorder.get_default().events()]
        assert "job_worker_lost" in kinds or any(
            i["reason"] == "job_worker_lost"
            for i in flight_recorder.get_default().incidents)
        assert "auto_resume" in kinds

    def test_stall_verdict_preempts_and_migrates(self, tmp_path):
        """Chaos hang injector: a step stalls past the watchdog
        deadline; the scheduler's stall verdict preempts (checkpoint at
        the next boundary — the post-hang step) and reschedules; the
        job still finishes at the exact step count."""
        nets = []

        def run(ctx):
            net = small_net(seed=4)
            nets.append(net)
            net.fit(data_iter(), epochs=2,
                    fault_tolerance=ctx.fault_tolerance)
            return float(net._score)

        cfg = chaos.ChaosConfig(hang_step=3, hang_seconds=1.0)
        with chaos.installed(cfg):
            with make_sched() as s:
                job = s.submit(control.TrainJob(
                    run, chips=1,
                    checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every=100,   # preemption bundle only
                    step_deadline=0.25, stall_grace_s=30.0,
                    backoff_s=0.05))
                s.wait(job.job_id, timeout=120)
                assert job.state == "completed", job.status()
                assert job.attempts == 2
                assert job.migrations >= 1
                assert job.retries_used == 0   # scheduler's fault, free
                assert nets[-1].getIterationCount() == 12
        kinds = [e["kind"]
                 for e in flight_recorder.get_default().events()]
        assert "job_stalled" in kinds
        ref = small_net(seed=4)
        ref.fit(data_iter(), epochs=2)
        assert float(ref._score) == job.result

    def test_divergence_abort_is_terminal(self, tmp_path):
        """DivergenceError = the guard already spent its budget: the
        scheduler fails the job instead of retry-looping a run that
        will re-diverge."""
        def run(ctx):
            net = small_net()
            bad = np.full_like(X, np.nan)
            ft = ctx.fault_tolerance
            ft.divergence_window = 4
            ft.max_rollbacks = 0
            net.fit(ArrayDataSetIterator(bad, Y, 8), epochs=1,
                    fault_tolerance=ft)

        with make_sched() as s:
            job = s.submit(control.TrainJob(
                run, chips=1, checkpoint_dir=str(tmp_path / "ck"),
                max_retries=3, backoff_s=0.01))
            s.wait(job.job_id, timeout=120)
            assert job.state == "failed"
            assert "divergence" in job.error
            assert job.retries_used == 0


# ======================================================================
# serving jobs
# ======================================================================
class TestServeJob:
    @pytest.mark.slow
    def test_serve_job_serves_drains_and_hands_back_capacity(
            self, gpt):
        model, params = gpt

        def build(ctx):
            return ServingFleet(model, params, devices=ctx.devices,
                                slots=2, page_size=8,
                                prefill_buckets=[8, 16, 40],
                                max_chunk=4)

        rng = np.random.default_rng(7)
        with make_sched(devices=DEVS[:2],
                        workers={"w0": DEVS[:2]}) as s:
            job = s.submit(control.ServeJob(build, replicas=1))
            s.wait(job.job_id, timeout=120, states=("running",))
            deadline = time.time() + 60
            while job.fleet is None and time.time() < deadline:
                time.sleep(0.02)
            assert job.fleet is not None
            prompt = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            out = job.generate(prompt, 5, timeout=60)
            assert out.shape == (5,)
            assert s.devices.free == 1     # 1 of 2 chips in use
            s.drain(job.job_id)
            s.wait(job.job_id, timeout=60)
            assert job.state == "drained"
            assert s.devices.free == 2     # capacity handed back

    @pytest.mark.slow
    def test_rebalance_drains_idle_replica_for_starved_train(
            self, gpt):
        """Train-vs-serve rebalancing: a train job starving for a chip
        claims a replica from an idle serving fleet — the drain hands
        the chip back through the capacity listener and the train job
        runs."""
        model, params = gpt

        def build(ctx):
            return ServingFleet(model, params, devices=ctx.devices,
                                slots=2, page_size=8,
                                prefill_buckets=[8, 16, 40],
                                max_chunk=4)

        ran = threading.Event()

        def run(ctx):
            ran.set()

        with make_sched(devices=DEVS[:2], workers={"w0": DEVS[:2]},
                        rebalance=True,
                        rebalance_after_s=0.3) as s:
            serve = s.submit(control.ServeJob(build, replicas=2))
            s.wait(serve.job_id, timeout=120, states=("running",))
            deadline = time.time() + 60
            while serve.fleet is None and time.time() < deadline:
                time.sleep(0.02)
            assert s.devices.free == 0
            train = s.submit(control.TrainJob(run, chips=1))
            s.wait(train.job_id, timeout=120)
            assert train.state == "completed"
            assert ran.is_set()
            assert serve.fleet.alive_replicas() == 1
            kinds = [e["kind"] for e in
                     flight_recorder.get_default().events()]
            assert "job_rebalance" in kinds
            s.cancel(serve.job_id)
            s.wait(serve.job_id, timeout=60)

    @pytest.mark.slow
    def test_replica_death_on_healthy_chip_restarts(self, gpt):
        model, params = gpt

        def build(ctx):
            return ServingFleet(model, params, replicas=2, slots=2,
                                page_size=8,
                                prefill_buckets=[8, 16, 40],
                                max_chunk=4)

        rng = np.random.default_rng(8)
        with make_sched(devices=DEVS[:2],
                        workers={"w0": DEVS[:2]}) as s:
            job = s.submit(control.ServeJob(build, replicas=2))
            s.wait(job.job_id, timeout=120, states=("running",))
            deadline = time.time() + 60
            while job.fleet is None and time.time() < deadline:
                time.sleep(0.02)
            fleet = job.fleet
            fleet.kill_replica(1)
            deadline = time.time() + 60
            while fleet.alive_replicas() < 2 \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert fleet.alive_replicas() == 2   # scheduler restarted
            prompt = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            assert job.generate(prompt, 4, timeout=60).shape == (4,)
            s.cancel(job.job_id)
            s.wait(job.job_id, timeout=60)


# ======================================================================
# satellites: cancel / abort
# ======================================================================
class TestCancel:
    def test_engine_cancel_mid_decode_frees_slot_and_pages(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(9)
        with _engine(model, params) as eng:
            prompt = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            req = eng.submit(prompt, 48)
            it = req.stream()
            got = [next(it), next(it)]       # decoding is live
            assert req.cancel()
            rest = list(it)                  # stream ends cleanly
            assert req.done
            assert req.finish_reason == "cancelled"
            assert req._error is None
            toks = req.result(10)            # partial tokens, no raise
            assert 2 <= len(toks) < 48
            assert list(toks[:2]) == got
            deadline = time.time() + 10
            while eng.pool.allocated and time.time() < deadline:
                time.sleep(0.01)
            assert eng.pool.allocated == 0   # pages drained to rc0
            assert not req.cancel()          # already done

    def test_engine_cancel_queued_request_never_runs(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(10)
        with _engine(model, params, slots=1, max_queue=8) as eng:
            blocker = eng.submit(
                rng.integers(0, VOCAB, (6,)).astype(np.int32), 40)
            queued = eng.submit(
                rng.integers(0, VOCAB, (6,)).astype(np.int32), 8)
            assert queued.cancel()
            queued._done.wait(10)
            assert queued.finish_reason == "cancelled"
            assert queued.tokens == []
            blocker.result(60)               # unaffected neighbor
            assert len(blocker.tokens) == 40

    def test_cancel_closes_trace_with_reason(self, gpt):
        from deeplearning4j_tpu.profiler import tracing

        model, params = gpt
        rng = np.random.default_rng(11)
        prev = tracing.enabled()
        tracing.set_enabled(True)
        try:
            with _engine(model, params) as eng:
                req = eng.submit(
                    rng.integers(0, VOCAB, (6,)).astype(np.int32), 48)
                next(req.stream())
                req.cancel()
                req._done.wait(10)
                tl = tracing.timeline(str(req.request_id))
                assert tl is not None
                assert tl["finish_reason"] == "cancelled"
                fin = [e for e in tl["events"]
                       if e["name"] == "finish"]
                assert fin and fin[0]["reason"] == "cancelled"
        finally:
            tracing.set_enabled(prev)

    @pytest.mark.slow
    def test_fleet_request_cancel_and_cancel_pending(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(12)
        fl = ServingFleet(model, params, replicas=1, slots=2,
                          page_size=8, prefill_buckets=[8, 16, 40],
                          max_chunk=4)
        fl.start()
        try:
            prompt = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            freq = fl.submit(prompt, 48)
            it = freq.stream()
            next(it)
            assert freq.cancel()
            list(it)
            assert freq.finish_reason == "cancelled"
            assert freq._error is None
            # cancel_pending sweeps whatever is live
            more = [fl.submit(
                rng.integers(0, VOCAB, (5,)).astype(np.int32), 30)
                for _ in range(3)]
            n = fl.cancel_pending()
            assert n >= 1
            for m in more:
                m._done.wait(30)
                assert m.done
            eng = fl._replicas[0].engine
            deadline = time.time() + 10
            while eng.pool.allocated and time.time() < deadline:
                time.sleep(0.01)
            assert eng.pool.allocated == 0
        finally:
            fl.shutdown()


# ======================================================================
# satellites: chaos hang injector + idempotent HTTP generate
# ======================================================================
class TestChaosHang:
    def test_hang_replica_stalls_then_recovers(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(13)
        with _engine(model, params) as eng:
            eng.generate(
                rng.integers(0, VOCAB, (6,)).astype(np.int32), 2,
                timeout=60)
            chaos.hang_replica(eng, seconds=0.4)
            t0 = time.perf_counter()
            out = eng.generate(
                rng.integers(0, VOCAB, (6,)).astype(np.int32), 3,
                timeout=60)
            assert out.shape == (3,)
            assert time.perf_counter() - t0 >= 0.35
        kinds = [e["kind"]
                 for e in flight_recorder.get_default().events()]
        assert "chaos_hang" in kinds

    def test_compile_grace_extends_first_step_only(self):
        """The first step of every attempt pays the jit compile; the
        scheduler's stall verdict must not read it as a stall. The
        grace applies to step 0 of a run and nothing else — warm steps
        keep the tight deadline."""
        from deeplearning4j_tpu.util.resilience import FaultTolerance

        ft = FaultTolerance(step_deadline=0.25, compile_grace_s=120.0)
        assert ft._watchdog(step=0).deadline == 120.25
        assert ft._watchdog(step=1).deadline == 0.25
        assert ft._watchdog(step=7).deadline == 0.25
        # default stays 0: standalone fits keep the historical
        # fire-on-compile behavior the tracing drills depend on
        bare = FaultTolerance(step_deadline=0.02)
        assert bare._watchdog(step=0).deadline == 0.02
        # TrainJob's auto-built policy arms the grace
        job = control.TrainJob(lambda ctx: None, step_deadline=0.25)
        assert job.fault_tolerance.compile_grace_s == 120.0

    def test_train_hang_env_knobs_parse(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_CHAOS", "1")
        monkeypatch.setenv("DL4J_TPU_CHAOS_HANG_STEP", "5")
        monkeypatch.setenv("DL4J_TPU_CHAOS_HANG_SECONDS", "0.5")
        monkeypatch.setenv("DL4J_TPU_CHAOS_KILL_AT", "9")
        cfg = chaos.ChaosConfig.from_env()
        assert cfg.hang_step == 5
        assert cfg.hang_seconds == 0.5
        assert cfg.kill_at_step == 9


class TestIdempotency:
    @pytest.mark.slow
    def test_replayed_post_returns_original_request(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(14)
        with _engine(model, params) as eng:
            srv = JsonModelServer(engine=eng)
            payload = {
                "prompt_ids": rng.integers(
                    0, VOCAB, (6,)).astype(np.int32).tolist(),
                "max_new_tokens": 5,
                "idempotency_key": "k-123",
            }
            a = srv.generate(dict(payload))
            b = srv.generate(dict(payload))   # the replayed POST
            assert b["request_id"] == a["request_id"]
            assert b["tokens"] == a["tokens"]
            assert b.get("replayed") is True
            assert "replayed" not in a
            # a DIFFERENT key is a fresh request
            c = srv.generate(dict(payload, idempotency_key="k-456"))
            assert c["request_id"] != a["request_id"]

    @pytest.mark.slow
    def test_client_threads_key_through_http_retries(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(15)
        with _engine(model, params) as eng:
            srv = JsonModelServer(engine=eng)
            port = srv.start()
            try:
                cli = JsonRemoteInference(
                    f"http://127.0.0.1:{port}", timeout=60)
                prompt = rng.integers(0, VOCAB, (6,)).astype(np.int32)
                out = cli.generate_full(prompt, 4)
                assert len(out["tokens"]) == 4
                # the client minted a key; a manual replay of the same
                # key joins the original request
                with srv._idem_lock:
                    key = next(reversed(srv._idem))
                body = json.dumps({
                    "prompt_ids": prompt.tolist(),
                    "max_new_tokens": 4,
                    "idempotency_key": key}).encode()
                r = urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/serving/generate",
                    data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=60)
                replay = json.loads(r.read())
                assert replay["replayed"] is True
                assert replay["request_id"] == out["request_id"]
                assert replay["tokens"] == out["tokens"]
            finally:
                srv.stop()


# ======================================================================
# /v1/jobs HTTP surface + telemetry embedding
# ======================================================================
class TestJobsHTTP:
    def test_jobs_endpoints_on_ui_server(self):
        from deeplearning4j_tpu.ui.server import UIServer

        ev = threading.Event()

        def hold(ctx):
            ev.wait(30)

        with make_sched() as s:
            s.register_factory(
                "hold", lambda **kw: control.TrainJob(hold, **kw))
            ui = UIServer()
            port = ui.start(port=0)
            try:
                base = f"http://127.0.0.1:{port}"
                # submit through HTTP via the registered factory
                body = json.dumps({"factory": "hold",
                                   "params": {"chips": 1,
                                              "tenant": "t9"}}).encode()
                r = urllib.request.urlopen(urllib.request.Request(
                    base + "/v1/jobs", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=10)
                sub = json.loads(r.read())
                jid = sub["job_id"]
                assert sub["tenant"] == "t9"
                listing = json.loads(urllib.request.urlopen(
                    base + "/v1/jobs", timeout=10).read())
                assert any(j["job_id"] == jid
                           for j in listing["jobs"])
                assert listing["devices"]["total"] == 4
                one = json.loads(urllib.request.urlopen(
                    base + f"/v1/jobs/{jid}", timeout=10).read())
                assert one["kind"] == "train"
                # cancel over HTTP
                r = urllib.request.urlopen(urllib.request.Request(
                    base + f"/v1/jobs/{jid}/cancel", data=b"{}",
                    headers={"Content-Type": "application/json"}),
                    timeout=10)
                ev.set()
                s.wait(jid, timeout=30)
                assert s.job(jid).state in ("cancelled", "completed")
            finally:
                ev.set()
                ui.stop()

    def test_jobs_http_404_without_scheduler(self):
        from deeplearning4j_tpu.ui.server import UIServer

        assert control.default_scheduler() is None
        ui = UIServer()
        port = ui.start(port=0)
        try:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/jobs", timeout=10)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            ui.stop()

    def test_snapshot_embeds_jobs(self):
        def run(ctx):
            pass

        with make_sched() as s:
            job = s.submit(control.TrainJob(run, chips=1,
                                            tenant="acme"))
            s.wait(job.job_id, timeout=30)
            snap = telemetry.snapshot()
            assert "jobs" in snap
            rows = snap["jobs"]["jobs"]
            assert any(r["job_id"] == job.job_id
                       and r["tenant"] == "acme" for r in rows)
        assert control.default_scheduler() is None


# ======================================================================
# periodic checkpoints (resilience satellite the scheduler rides on)
# ======================================================================
class TestPeriodicCheckpoints:
    def test_periodic_bundles_written_and_pruned(self, tmp_path):
        from deeplearning4j_tpu.util import resilience

        net = small_net()
        ck = str(tmp_path / "ck")
        ft = FaultTolerance(checkpoint_dir=ck, auto_resume=False,
                            checkpoint_every=4, keep_last=2)
        before = telemetry.MetricsRegistry.get_default().counter(
            telemetry.FT_PERIODIC_CHECKPOINTS).total()
        net.fit(data_iter(), epochs=2, fault_tolerance=ft)
        after = telemetry.MetricsRegistry.get_default().counter(
            telemetry.FT_PERIODIC_CHECKPOINTS).total()
        assert after - before == 3      # 12 steps / every 4
        bundles = resilience._list_bundles(ck)
        assert len(bundles) == 2        # keep_last pruning
        path = resilience.latest_valid_bundle(ck)
        assert path is not None
        with open(f"{path}/resume.json") as f:
            meta = json.load(f)
        assert meta["periodic"] is True
        assert meta["iterator_state"] is not None

    def test_inject_fault_dies_without_checkpoint_then_resumes(
            self, tmp_path):
        from deeplearning4j_tpu.util import resilience

        ck = str(tmp_path / "ck")
        net = small_net(seed=6)
        ft = FaultTolerance(checkpoint_dir=ck, checkpoint_every=3)
        it = SlowIter(X, Y, 8, shuffle=True, seed=5, delay=0.02)

        def late_kill():
            while net.getIterationCount() < 5:
                time.sleep(0.005)
            ft.inject_fault(control.DeviceLostError("host gone"))

        killer = threading.Thread(target=late_kill, daemon=True)
        killer.start()
        with pytest.raises(control.DeviceLostError):
            net.fit(it, epochs=2, fault_tolerance=ft)
        killer.join(10)
        # no checkpoint at death: newest bundle is a periodic one at a
        # multiple of 3, strictly before the death step
        path = resilience.latest_valid_bundle(ck)
        assert path is not None
        with open(f"{path}/resume.json") as f:
            assert json.load(f)["periodic"] is True
        # resume on a FRESH model finishes bit-identical
        net2 = small_net(seed=6)
        net2.fit(data_iter(), epochs=2, auto_resume=ck,
                 fault_tolerance=FaultTolerance(checkpoint_dir=ck))
        ref = small_net(seed=6)
        ref.fit(data_iter(), epochs=2)
        assert net2.getIterationCount() == ref.getIterationCount()
        for a, b in zip(jax.tree_util.tree_leaves(net2.params_list),
                        jax.tree_util.tree_leaves(ref.params_list)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))


# ======================================================================
# phase 3: elasticity (manual scale through the fleet endpoint)
# ======================================================================
class TestElasticity:
    def test_fleet_endpoints_error_conventions(self):
        with make_sched() as s:
            obj, code = control.http_fleet_get("/v1/fleet")
            assert code == 200 and obj == {"fleets": []}
            obj, code = control.http_fleet_get("/v1/fleet/nope")
            assert code == 404
            obj, code = control.http_fleet_post("/v1/fleet/scale", {})
            assert code == 400 and "target" in obj["error"]
            obj, code = control.http_fleet_post("/v1/fleet/scale",
                                                {"target": 0})
            assert code == 400
            obj, code = control.http_fleet_post("/v1/fleet/scale",
                                                {"target": 2})
            assert code == 404          # no running serve job
            obj, code = control.http_fleet_post("/v1/fleet/other", {})
            assert code == 404

    @pytest.mark.slow
    def test_http_scale_grows_and_shrinks_fleet(self, gpt):
        """Operator scaling end to end: POST /v1/fleet/scale grows a
        live fleet onto a freshly acquired chip (replica registered,
        chip accounted), serves token-identically, then shrinks back
        — replica drained, chip returned to the pool. Manual scale is
        PINNED: the auto scale-down pass must not undo it."""
        model, params = gpt

        def build(ctx):
            return ServingFleet(model, params, devices=ctx.devices,
                                slots=2, page_size=8,
                                prefill_buckets=[8, 16, 40],
                                max_chunk=4)

        rng = np.random.default_rng(31)
        with make_sched(devices=DEVS[:2], workers={"w0": DEVS[:2]},
                        scale_down_hold_s=0.01) as s:
            job = s.submit(control.ServeJob(build, replicas=1))
            s.wait(job.job_id, timeout=120, states=("running",))
            deadline = time.time() + 60
            while job.fleet is None and time.time() < deadline:
                time.sleep(0.02)
            assert s.devices.free == 1
            obj, code = control.http_fleet_post(
                "/v1/fleet/scale", {"target": 2})
            assert code == 200, obj
            assert obj["replicas"] == 2 and obj["manual"] == 1
            assert s.devices.free == 0       # second chip in use
            assert job.fleet.alive_replicas() == 2
            prompt = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            out = job.generate(prompt, 5, timeout=60)
            assert out.shape == (5,)
            # manual replicas survive the auto scale-down pass
            time.sleep(0.2)
            s._maybe_scale_down()
            assert job.fleet.alive_replicas() == 2
            # a third replica has no chip to land on: clean 400, no
            # half-built replica, no leaked pending_scale
            obj, code = control.http_fleet_post(
                "/v1/fleet/scale", {"target": 3})
            assert code == 400
            assert job.fleet.alive_replicas() == 2
            assert job.fleet.stats()["pending_scale"] == 0
            # shrink back: drain hands the chip to the pool
            obj, code = control.http_fleet_post(
                "/v1/fleet/scale", {"target": 1})
            assert code == 200, obj
            assert job.fleet.alive_replicas() == 1
            deadline = time.time() + 30
            while s.devices.free < 1 and time.time() < deadline:
                time.sleep(0.02)
            assert s.devices.free == 1
            kinds = [e["kind"] for e in
                     flight_recorder.get_default().events()]
            assert "job_scale_up" in kinds
            assert "job_scale_down" in kinds
            assert "fleet_replica_added" in kinds
            assert "fleet_replica_removed" in kinds
            s.cancel(job.job_id)
            s.wait(job.job_id, timeout=60)
