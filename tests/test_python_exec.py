"""python4j-equivalent: scoped execution, variable marshalling,
PythonTransform (SURVEY.md §2.40)."""

import numpy as np
import pytest

from deeplearning4j_tpu.ndarray.factory import Nd4j
from deeplearning4j_tpu.python_exec import (
    PythonContextManager, PythonExecutioner, PythonTransform,
    PythonVariables,
)


@pytest.fixture(autouse=True)
def fresh_contexts():
    PythonContextManager.reset()
    yield
    PythonContextManager.reset()


class TestExecutioner:
    def test_basic_exec(self):
        ins = PythonVariables().add("a", 2).add("b", 3)
        outs = PythonVariables().add("c")
        PythonExecutioner.exec("c = a * b + 1", ins, outs)
        assert outs.getValue("c") == 7

    def test_ndarray_marshalling(self):
        x = Nd4j.create(np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32))
        ins = PythonVariables().addNDArray("x", x)
        outs = PythonVariables().add("y")
        PythonExecutioner.exec("y = (x * 2).sum(axis=0)", ins, outs)
        np.testing.assert_allclose(
            outs.getNDArrayValue("y").toNumpy(), [8.0, 12.0])

    def test_missing_output_raises(self):
        outs = PythonVariables().add("never_set")
        with pytest.raises(KeyError, match="never_set"):
            PythonExecutioner.exec("pass", None, outs)

    def test_context_isolation(self):
        PythonExecutioner.exec("secret = 41", context="ctx_a")
        with pytest.raises(NameError):
            PythonExecutioner.exec("print(secret)", context="ctx_b")
        outs = PythonVariables().add("v")
        PythonExecutioner.exec("v = secret + 1", outputs=outs,
                               context="ctx_a")
        assert outs.getValue("v") == 42

    def test_context_persistence(self):
        PythonContextManager.setContext("persistent")
        PythonExecutioner.exec("counter = 0")
        PythonExecutioner.exec("counter += 1")
        PythonExecutioner.exec("counter += 1")
        outs = PythonVariables().add("counter")
        PythonExecutioner.exec("", outputs=outs)
        assert outs.getValue("counter") == 2

    def test_delete_context(self):
        PythonContextManager.setContext("tmp")
        PythonExecutioner.exec("x = 1")
        PythonContextManager.deleteContext("tmp")
        assert PythonContextManager.currentContext() == "main"
        with pytest.raises(ValueError):
            PythonContextManager.deleteContext("main")


class TestPythonTransform:
    def test_columnar_transform(self):
        t = PythonTransform(
            code="z = x * 2 + y",
            input_columns=["x", "y"], output_columns=["z"])
        table = {"x": np.asarray([1.0, 2.0, 3.0]),
                 "y": np.asarray([10.0, 20.0, 30.0])}
        out = t.apply_columnar(table)
        np.testing.assert_allclose(out["z"], [12.0, 24.0, 36.0])
        np.testing.assert_allclose(out["x"], table["x"])  # inputs kept
