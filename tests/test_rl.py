"""RL subsystem: DQN solves small MDPs, A2C improves, policies behave.

Reference: rl4j QLearningDiscreteDense / A3CDiscreteDense / policies
(SURVEY.md §2.41).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.rl import (
    A2CConfiguration, A2CDiscreteDense, A3CConfiguration, A3CDiscreteDense,
    AsyncNStepQLConfiguration, AsyncNStepQLearningDiscrete, CorridorMDP,
    DQNPolicy, EpsGreedy, ExpReplay, GridWorldMDP, HistoryMDP,
    HistoryProcessor, HistoryProcessorConfiguration, MDP, QLConfiguration,
    QLearningDiscreteDense, SlowMDP, Transition,
)


class TestEnvs:
    def test_corridor_optimal(self):
        env = CorridorMDP(length=5)
        env.reset()
        total, done = 0.0, False
        while not done:
            _, r, done, _ = env.step(1)
            total += r
        assert total == pytest.approx(1.0 - 0.01 * 3)

    def test_gridworld_goal(self):
        env = GridWorldMDP(n=3)
        env.reset()
        for a in [1, 1, 3, 3]:
            obs, r, done, _ = env.step(a)
        assert done and r == 1.0


class TestReplay:
    def test_circular_and_sample(self):
        rp = ExpReplay(4, 2)
        for i in range(6):
            rp.store(Transition(np.full(2, i, np.float32), i % 2, float(i),
                                np.zeros(2, np.float32), False))
        assert len(rp) == 4
        obs, act, rew, nobs, done = rp.sample(8)
        assert obs.shape == (8, 2)
        assert rew.min() >= 2.0  # oldest two evicted


class TestEpsGreedy:
    def test_anneal(self):
        pol = EpsGreedy(DQNPolicy(lambda o: np.zeros((1, 2))), 2,
                        eps_start=1.0, eps_min=0.1, anneal_steps=10)
        assert pol.epsilon == 1.0
        for _ in range(10):
            pol.next_action(np.zeros(2, np.float32))
        assert pol.epsilon == pytest.approx(0.1)


class TestDQN:
    def test_solves_corridor(self):
        conf = QLConfiguration(
            seed=3, max_step=3000, exp_replay_size=2000, batch_size=32,
            target_dqn_update_freq=50, update_start=64, gamma=0.95,
            epsilon_nb_step=1500, min_epsilon=0.05, hidden=(32,),
            learning_rate=3e-3)
        ql = QLearningDiscreteDense(CorridorMDP(length=6), conf)
        ql.train()
        # greedy policy must walk straight to the goal
        ret = ql.getPolicy().play(CorridorMDP(length=6))
        assert ret > 0.9   # optimal = 1 - 0.01*4 = 0.96

    def test_double_dqn_flag(self):
        for dd in (True, False):
            conf = QLConfiguration(seed=0, max_step=200, update_start=32,
                                   double_dqn=dd, hidden=(16,))
            ql = QLearningDiscreteDense(CorridorMDP(length=4), conf)
            ql.train()
            q = ql.q_values(np.eye(4, dtype=np.float32))
            assert q.shape == (4, 2) and np.isfinite(q).all()


class TestA2C:
    def test_improves_on_corridor(self):
        conf = A2CConfiguration(seed=1, n_step=8, n_envs=8,
                                learning_rate=3e-3, hidden=(32,))
        a2c = A2CDiscreteDense(lambda: CorridorMDP(length=6), conf)
        a2c.train(updates=150)
        rewards = a2c.episode_rewards
        assert len(rewards) > 10
        early = np.mean(rewards[:10])
        late = np.mean(rewards[-10:])
        assert late > early
        # greedy policy should reach the goal
        ret = a2c.getPolicy(greedy=True).play(CorridorMDP(length=6))
        assert ret > 0.5


class TestA3C:
    """Async actor-learner (reference: A3CDiscreteDense + AsyncGlobal —
    rl4j's headline feature, VERDICT r3 item #7)."""

    def test_converges_on_corridor(self):
        conf = A3CConfiguration(seed=1, n_step=8, n_workers=3,
                                learning_rate=3e-3, hidden=(32,))
        a3c = A3CDiscreteDense(lambda: CorridorMDP(length=6), conf)
        a3c.train(updates=400)
        rewards = a3c.episode_rewards
        assert len(rewards) > 10
        assert np.mean(rewards[-10:]) > np.mean(rewards[:10])
        ret = a3c.getPolicy(greedy=True).play(CorridorMDP(length=6))
        assert ret > 0.5

    def test_multi_actor_beats_single_wall_clock(self):
        """The point of async: with env-step latency dominating (the
        gym-round-trip regime), N workers overlap the waiting. Same
        total update budget, 2ms per env step; 4 workers must cut
        wall-clock vs 1 by well more than noise (ideal ~4x; assert
        >=1.6x to stay robust on a loaded CI host)."""

        def run(n_workers):
            conf = A3CConfiguration(seed=0, n_step=4, n_workers=n_workers,
                                    hidden=(16,))
            a3c = A3CDiscreteDense(
                lambda: SlowMDP(CorridorMDP(length=4), 0.002), conf)
            a3c.train(updates=60)
            return a3c.train_seconds

        run(1)  # warm the jit caches so timing compares env overlap only
        t1 = run(1)
        t4 = run(4)
        assert t4 < t1 / 1.6, (t1, t4)


class TestAsyncNStepQ:
    """rl4j's second async learner (AsyncNStepQLearningDiscrete)."""

    def test_converges_on_corridor(self):
        """Async updates make the trajectory nondeterministic (thread
        interleaving decides which stale gradient lands first), so
        train in rounds until the greedy policy solves the corridor —
        bounded, and failure still means genuinely not converging."""
        conf = AsyncNStepQLConfiguration(
            seed=4, n_step=5, n_workers=3, learning_rate=3e-3,
            target_update=25, anneal_updates=400, hidden=(32,))
        ql = AsyncNStepQLearningDiscrete(lambda: CorridorMDP(length=6),
                                         conf)
        ret = -1.0
        for _round in range(3):
            ql.train(updates=600)
            ret = ql.getPolicy().play(CorridorMDP(length=6))
            if ret > 0.9:
                break
        assert ret > 0.9   # optimal = 0.96: greedy walks to the goal

    def test_target_net_lags_then_syncs(self):
        conf = AsyncNStepQLConfiguration(seed=0, n_step=4, n_workers=1,
                                         target_update=10, hidden=(16,))
        ql = AsyncNStepQLearningDiscrete(lambda: CorridorMDP(length=4),
                                         conf)
        ql.train(updates=10)  # exactly one sync boundary
        a = np.concatenate([np.ravel(p["W"]) for p in ql._target])
        b = np.concatenate([np.ravel(p["W"]) for p in ql._params])
        np.testing.assert_allclose(a, b)


class _PixelCorridor(MDP):
    """CorridorMDP rendered as a 16x16 image (pos column lit)."""

    def __init__(self, length=4):
        self._inner = CorridorMDP(length=length, max_steps=40)
        self.length = length

    @property
    def obs_size(self):
        return 256

    @property
    def n_actions(self):
        return 2

    def _render(self, onehot):
        img = np.zeros((16, 16), np.float32)
        img[:, int(np.argmax(onehot)) * 2] = 255.0
        return img

    def reset(self):
        return self._render(self._inner.reset())

    def step(self, a):
        o, r, d, i = self._inner.step(a)
        return self._render(o), r, d, i


class TestHistoryProcessor:
    def test_grayscale_and_area_rescale_exact(self):
        conf = HistoryProcessorConfiguration(
            history_length=2, rescaled_width=4, rescaled_height=4,
            skip_frame=1, normalize=False)
        hp = HistoryProcessor(conf)
        rgb = np.zeros((8, 8, 3), np.float32)
        rgb[..., 0] = 100.0  # pure red
        hp.record(rgb)
        h = hp.get_history()
        assert h.shape == (2, 4, 4)
        np.testing.assert_allclose(h[0], 0.0)     # zero-padded warmup
        np.testing.assert_allclose(h[1], 29.9)    # 0.299 * 100, area-avg
        # non-integer factor: 9x9 -> 4x4 crops to 8x8 then averages
        hp.record(np.full((9, 9), 8.0, np.float32))
        np.testing.assert_allclose(hp.get_history()[1], 8.0)
        # (H,W,1) gym-style grayscale and RGBA both accepted
        hp.record(np.full((4, 4, 1), 5.0, np.float32))
        np.testing.assert_allclose(hp.get_history()[1], 5.0)
        hp.record(np.concatenate([rgb[:4, :4], np.full((4, 4, 1), 9.0,
                                                       np.float32)], -1))
        np.testing.assert_allclose(hp.get_history()[1], 29.9)
        with pytest.raises(ValueError, match="channels"):
            hp.record(np.zeros((4, 4, 2), np.float32))

    def test_stack_order_oldest_first(self):
        conf = HistoryProcessorConfiguration(
            history_length=3, rescaled_width=2, rescaled_height=2,
            normalize=False)
        hp = HistoryProcessor(conf)
        for v in (1.0, 2.0, 3.0, 4.0):
            hp.record(np.full((2, 2), v, np.float32))
        h = hp.get_history()
        np.testing.assert_allclose(h[:, 0, 0], [2.0, 3.0, 4.0])

    def test_history_mdp_skip_and_reward_sum(self):
        conf = HistoryProcessorConfiguration(
            history_length=2, rescaled_width=8, rescaled_height=8,
            skip_frame=2)
        env = HistoryMDP(_PixelCorridor(length=6), conf)
        obs = env.reset()
        assert obs.shape == (2 * 8 * 8,)
        _, r, done, _ = env.step(1)   # two inner steps, rewards summed
        assert r == pytest.approx(-0.02) and not done
        assert env._inner._inner._pos == 2

    def test_dqn_trains_on_pixel_history(self):
        conf = QLConfiguration(
            seed=5, max_step=1500, exp_replay_size=1500, batch_size=32,
            target_dqn_update_freq=50, update_start=64, gamma=0.95,
            epsilon_nb_step=800, min_epsilon=0.05, hidden=(64,),
            learning_rate=3e-3)
        hconf = HistoryProcessorConfiguration(
            history_length=2, rescaled_width=8, rescaled_height=8,
            skip_frame=1)
        ql = QLearningDiscreteDense(
            HistoryMDP(_PixelCorridor(length=4), hconf), conf)
        ql.train()
        ret = ql.getPolicy().play(HistoryMDP(_PixelCorridor(length=4),
                                             hconf))
        assert ret > 0.9


class TestPolicySerde:
    """DQNPolicy save/load (reference: DQNPolicy#save / .load)."""

    def test_round_trip_preserves_q_values_and_policy(self, tmp_path):
        from deeplearning4j_tpu.rl import (
            GridWorldMDP, QLConfiguration, QLearningDiscreteDense,
        )
        from deeplearning4j_tpu.rl.policy import DQNPolicy

        mdp = GridWorldMDP(n=3)
        learner = QLearningDiscreteDense(mdp, QLConfiguration(
            max_step=300, epsilon_nb_step=200, target_dqn_update_freq=50))
        learner.train(300)
        p = str(tmp_path / "dqn.npz")
        learner.getPolicy().save(p)

        restored = DQNPolicy.load(p, GridWorldMDP(n=3))
        obs = np.eye(9, dtype=np.float32)[:5]
        np.testing.assert_allclose(learner.q_values(obs),
                                   restored._learner.q_values(obs),
                                   rtol=1e-6)
        for o in obs:
            assert learner.getPolicy().next_action(o) \
                == restored.next_action(o)

    def test_shape_mismatch_rejected(self, tmp_path):
        from deeplearning4j_tpu.rl import (
            GridWorldMDP, QLConfiguration, QLearningDiscreteDense,
        )

        mdp = GridWorldMDP(n=3)
        learner = QLearningDiscreteDense(mdp, QLConfiguration(max_step=10))
        p = str(tmp_path / "dqn.npz")
        learner.save(p)
        with pytest.raises(ValueError, match="obs_size"):
            QLearningDiscreteDense.load(p, GridWorldMDP(n=4))

    def test_bare_policy_save_raises(self):
        from deeplearning4j_tpu.rl.policy import DQNPolicy

        pol = DQNPolicy(lambda o: np.zeros((1, 2)))
        with pytest.raises(ValueError, match="learner"):
            pol.save("/tmp/nope.npz")
