"""RL subsystem: DQN solves small MDPs, A2C improves, policies behave.

Reference: rl4j QLearningDiscreteDense / A3CDiscreteDense / policies
(SURVEY.md §2.41).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.rl import (
    A2CConfiguration, A2CDiscreteDense, A3CConfiguration, A3CDiscreteDense,
    CorridorMDP, DQNPolicy, EpsGreedy, ExpReplay, GridWorldMDP,
    QLConfiguration, QLearningDiscreteDense, SlowMDP, Transition,
)


class TestEnvs:
    def test_corridor_optimal(self):
        env = CorridorMDP(length=5)
        env.reset()
        total, done = 0.0, False
        while not done:
            _, r, done, _ = env.step(1)
            total += r
        assert total == pytest.approx(1.0 - 0.01 * 3)

    def test_gridworld_goal(self):
        env = GridWorldMDP(n=3)
        env.reset()
        for a in [1, 1, 3, 3]:
            obs, r, done, _ = env.step(a)
        assert done and r == 1.0


class TestReplay:
    def test_circular_and_sample(self):
        rp = ExpReplay(4, 2)
        for i in range(6):
            rp.store(Transition(np.full(2, i, np.float32), i % 2, float(i),
                                np.zeros(2, np.float32), False))
        assert len(rp) == 4
        obs, act, rew, nobs, done = rp.sample(8)
        assert obs.shape == (8, 2)
        assert rew.min() >= 2.0  # oldest two evicted


class TestEpsGreedy:
    def test_anneal(self):
        pol = EpsGreedy(DQNPolicy(lambda o: np.zeros((1, 2))), 2,
                        eps_start=1.0, eps_min=0.1, anneal_steps=10)
        assert pol.epsilon == 1.0
        for _ in range(10):
            pol.next_action(np.zeros(2, np.float32))
        assert pol.epsilon == pytest.approx(0.1)


class TestDQN:
    def test_solves_corridor(self):
        conf = QLConfiguration(
            seed=3, max_step=3000, exp_replay_size=2000, batch_size=32,
            target_dqn_update_freq=50, update_start=64, gamma=0.95,
            epsilon_nb_step=1500, min_epsilon=0.05, hidden=(32,),
            learning_rate=3e-3)
        ql = QLearningDiscreteDense(CorridorMDP(length=6), conf)
        ql.train()
        # greedy policy must walk straight to the goal
        ret = ql.getPolicy().play(CorridorMDP(length=6))
        assert ret > 0.9   # optimal = 1 - 0.01*4 = 0.96

    def test_double_dqn_flag(self):
        for dd in (True, False):
            conf = QLConfiguration(seed=0, max_step=200, update_start=32,
                                   double_dqn=dd, hidden=(16,))
            ql = QLearningDiscreteDense(CorridorMDP(length=4), conf)
            ql.train()
            q = ql.q_values(np.eye(4, dtype=np.float32))
            assert q.shape == (4, 2) and np.isfinite(q).all()


class TestA2C:
    def test_improves_on_corridor(self):
        conf = A2CConfiguration(seed=1, n_step=8, n_envs=8,
                                learning_rate=3e-3, hidden=(32,))
        a2c = A2CDiscreteDense(lambda: CorridorMDP(length=6), conf)
        a2c.train(updates=150)
        rewards = a2c.episode_rewards
        assert len(rewards) > 10
        early = np.mean(rewards[:10])
        late = np.mean(rewards[-10:])
        assert late > early
        # greedy policy should reach the goal
        ret = a2c.getPolicy(greedy=True).play(CorridorMDP(length=6))
        assert ret > 0.5


class TestA3C:
    """Async actor-learner (reference: A3CDiscreteDense + AsyncGlobal —
    rl4j's headline feature, VERDICT r3 item #7)."""

    def test_converges_on_corridor(self):
        conf = A3CConfiguration(seed=1, n_step=8, n_workers=3,
                                learning_rate=3e-3, hidden=(32,))
        a3c = A3CDiscreteDense(lambda: CorridorMDP(length=6), conf)
        a3c.train(updates=400)
        rewards = a3c.episode_rewards
        assert len(rewards) > 10
        assert np.mean(rewards[-10:]) > np.mean(rewards[:10])
        ret = a3c.getPolicy(greedy=True).play(CorridorMDP(length=6))
        assert ret > 0.5

    def test_multi_actor_beats_single_wall_clock(self):
        """The point of async: with env-step latency dominating (the
        gym-round-trip regime), N workers overlap the waiting. Same
        total update budget, 2ms per env step; 4 workers must cut
        wall-clock vs 1 by well more than noise (ideal ~4x; assert
        >=1.6x to stay robust on a loaded CI host)."""

        def run(n_workers):
            conf = A3CConfiguration(seed=0, n_step=4, n_workers=n_workers,
                                    hidden=(16,))
            a3c = A3CDiscreteDense(
                lambda: SlowMDP(CorridorMDP(length=4), 0.002), conf)
            a3c.train(updates=60)
            return a3c.train_seconds

        run(1)  # warm the jit caches so timing compares env overlap only
        t1 = run(1)
        t4 = run(4)
        assert t4 < t1 / 1.6, (t1, t4)
