"""SameDiff control flow + gradient-check validation tests
(reference model: AbstractSession If/While tests and
OpValidation/GradCheckUtil suites — SURVEY.md §4)."""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import (GradCheckUtil, OpValidation,
                                         SameDiff, TrainingConfig)
from deeplearning4j_tpu.autodiff import TestCase as OpTestCase
from deeplearning4j_tpu.learning.updaters import Sgd


class TestIfCond:
    def test_branch_selection(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(None,))
        pred = sd.placeholder("p", shape=())
        out = sd.ifCond(pred, [x],
                        lambda sub, a: a * 2.0,
                        lambda sub, a: a + 100.0)
        r_true = sd.output({"x": jnp.ones(3), "p": jnp.asarray(True)},
                           [out.name])[out.name]
        r_false = sd.output({"x": jnp.ones(3), "p": jnp.asarray(False)},
                            [out.name])[out.name]
        np.testing.assert_allclose(np.asarray(r_true), 2.0)
        np.testing.assert_allclose(np.asarray(r_false), 101.0)

    def test_multi_output_branches(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(2,))
        pred = sd.placeholder("p", shape=())
        a, b = sd.ifCond(pred, [x],
                         lambda sub, v: [v + 1.0, v * 3.0],
                         lambda sub, v: [v - 1.0, v / 2.0])
        outs = sd.output({"x": jnp.full((2,), 4.0), "p": jnp.asarray(False)},
                         [a.name, b.name])
        np.testing.assert_allclose(np.asarray(outs[a.name]), 3.0)
        np.testing.assert_allclose(np.asarray(outs[b.name]), 2.0)

    def test_branch_arity_mismatch(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(2,))
        pred = sd.placeholder("p", shape=())
        with pytest.raises(ValueError, match="arity"):
            sd.ifCond(pred, [x],
                      lambda sub, v: [v, v],
                      lambda sub, v: v)

    def test_grad_through_cond(self):
        sd = SameDiff()
        w = sd.var("w", jnp.asarray([2.0, 3.0]))
        pred = sd.placeholder("p", shape=())
        out = sd.ifCond(pred, [w],
                        lambda sub, v: (v * v).sum(),
                        lambda sub, v: v.sum())
        sd.setLossVariables(out.name)
        g = sd.calculateGradients({"p": jnp.asarray(True)})
        np.testing.assert_allclose(np.asarray(g["w"]), [4.0, 6.0])
        g2 = sd.calculateGradients({"p": jnp.asarray(False)})
        np.testing.assert_allclose(np.asarray(g2["w"]), [1.0, 1.0])


class TestWhileLoop:
    def test_countdown_sum(self):
        # while i < 5: acc += i; i += 1  → acc = 0+1+2+3+4 = 10
        sd = SameDiff()
        i0 = sd.placeholder("i0", shape=())
        acc0 = sd.placeholder("acc0", shape=())
        i_f, acc_f = sd.whileLoop(
            [i0, acc0],
            cond_fn=lambda sub, i, acc: i < 5.0,
            body_fn=lambda sub, i, acc: [i + 1.0, acc + i])
        outs = sd.output({"i0": jnp.asarray(0.0), "acc0": jnp.asarray(0.0)},
                         [i_f.name, acc_f.name])
        assert float(outs[i_f.name]) == 5.0
        assert float(outs[acc_f.name]) == 10.0

    def test_vector_state(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(3,))
        n = sd.placeholder("n", shape=())
        n_f, x_f = sd.whileLoop(
            [n, x],
            cond_fn=lambda sub, k, v: k > 0.0,
            body_fn=lambda sub, k, v: [k - 1.0, v * 2.0])
        outs = sd.output({"x": jnp.ones(3), "n": jnp.asarray(3.0)},
                         [x_f.name])
        np.testing.assert_allclose(np.asarray(outs[x_f.name]), 8.0)

    def test_body_arity_checked(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=())
        with pytest.raises(ValueError, match="body"):
            sd.whileLoop([x],
                         cond_fn=lambda sub, v: v > 0.0,
                         body_fn=lambda sub, v: [v, v])

    def test_serde_roundtrip_control_flow(self, tmp_path):
        sd = SameDiff()
        i0 = sd.placeholder("i0", shape=())
        acc0 = sd.placeholder("acc0", shape=())
        _, acc_f = sd.whileLoop(
            [i0, acc0],
            cond_fn=lambda sub, i, acc: i < 4.0,
            body_fn=lambda sub, i, acc: [i + 1.0, acc + i * i])
        acc_f.rename("result")
        p = str(tmp_path / "cf.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = sd2.output({"i0": jnp.asarray(0.0), "acc0": jnp.asarray(0.0)},
                         ["result"])["result"]
        assert float(out) == 0 + 1 + 4 + 9


class TestGradCheckUtil:
    def test_passes_on_correct_graph(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4, 3))
        w = sd.var("w", np.random.default_rng(0).normal(size=(3, 2)) * 0.5)
        b = sd.var("b", np.zeros(2))
        out = sd.nn.sigmoid(x @ w + b)
        loss = (out * out).mean()
        sd.setLossVariables(loss.name)
        feeds = {"x": np.random.default_rng(1).normal(size=(4, 3))}
        assert GradCheckUtil.checkGradients(sd, feeds, eps=1e-2,
                                            max_rel_error=0.08)

    def test_catches_wrong_gradient(self):
        # stop_gradient makes the analytic grad 0 while numeric isn't
        sd = SameDiff()
        w = sd.var("w", jnp.asarray([1.0, 2.0]))
        out = sd.math.stop_gradient(w * w).sum() \
            if hasattr(sd.math, "stop_gradient") else None
        if out is None:
            pytest.skip("no stop_gradient op registered")
        sd.setLossVariables(out.name)
        assert not GradCheckUtil.checkGradients(
            sd, {}, eps=1e-2, print_failures=False)


class TestOpValidation:
    def test_forward_and_grad(self):
        rng = np.random.default_rng(0)
        OpValidation.validate(OpTestCase(
            "matmul",
            args=[rng.normal(size=(3, 4)).astype(np.float32),
                  rng.normal(size=(4, 2)).astype(np.float32)],
            expected=lambda a, b: a @ b,
            grad_eps=1e-2, grad_rtol=0.08))

    def test_attrs_and_reduction(self):
        rng = np.random.default_rng(0)
        OpValidation.validate(OpTestCase(
            "reduce_mean",
            args=[rng.normal(size=(3, 4)).astype(np.float32)],
            attrs={"dimensions": [1]},
            expected=lambda a: a.mean(axis=1),
            grad_eps=1e-2, grad_rtol=0.08))

    def test_forward_mismatch_raises(self):
        with pytest.raises(AssertionError):
            OpValidation.validate(OpTestCase(
                "add", args=[np.ones(2, np.float32), np.ones(2, np.float32)],
                expected=lambda a, b: a * 5,
                grad_check=False))

    def test_coverage_report(self):
        rep = OpValidation.coverage_report()
        assert rep["total"] > 50
        assert "matmul" in rep["validated"]
