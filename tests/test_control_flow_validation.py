"""SameDiff control flow + gradient-check validation tests
(reference model: AbstractSession If/While tests and
OpValidation/GradCheckUtil suites — SURVEY.md §4)."""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import (GradCheckUtil, OpValidation,
                                         SameDiff, TrainingConfig)
from deeplearning4j_tpu.autodiff import TestCase as OpTestCase
from deeplearning4j_tpu.learning.updaters import Sgd


class TestIfCond:
    def test_branch_selection(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(None,))
        pred = sd.placeholder("p", shape=())
        out = sd.ifCond(pred, [x],
                        lambda sub, a: a * 2.0,
                        lambda sub, a: a + 100.0)
        r_true = sd.output({"x": jnp.ones(3), "p": jnp.asarray(True)},
                           [out.name])[out.name]
        r_false = sd.output({"x": jnp.ones(3), "p": jnp.asarray(False)},
                            [out.name])[out.name]
        np.testing.assert_allclose(np.asarray(r_true), 2.0)
        np.testing.assert_allclose(np.asarray(r_false), 101.0)

    def test_multi_output_branches(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(2,))
        pred = sd.placeholder("p", shape=())
        a, b = sd.ifCond(pred, [x],
                         lambda sub, v: [v + 1.0, v * 3.0],
                         lambda sub, v: [v - 1.0, v / 2.0])
        outs = sd.output({"x": jnp.full((2,), 4.0), "p": jnp.asarray(False)},
                         [a.name, b.name])
        np.testing.assert_allclose(np.asarray(outs[a.name]), 3.0)
        np.testing.assert_allclose(np.asarray(outs[b.name]), 2.0)

    def test_branch_arity_mismatch(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(2,))
        pred = sd.placeholder("p", shape=())
        with pytest.raises(ValueError, match="arity"):
            sd.ifCond(pred, [x],
                      lambda sub, v: [v, v],
                      lambda sub, v: v)

    def test_grad_through_cond(self):
        sd = SameDiff()
        w = sd.var("w", jnp.asarray([2.0, 3.0]))
        pred = sd.placeholder("p", shape=())
        out = sd.ifCond(pred, [w],
                        lambda sub, v: (v * v).sum(),
                        lambda sub, v: v.sum())
        sd.setLossVariables(out.name)
        g = sd.calculateGradients({"p": jnp.asarray(True)})
        np.testing.assert_allclose(np.asarray(g["w"]), [4.0, 6.0])
        g2 = sd.calculateGradients({"p": jnp.asarray(False)})
        np.testing.assert_allclose(np.asarray(g2["w"]), [1.0, 1.0])


class TestWhileLoop:
    def test_countdown_sum(self):
        # while i < 5: acc += i; i += 1  → acc = 0+1+2+3+4 = 10
        sd = SameDiff()
        i0 = sd.placeholder("i0", shape=())
        acc0 = sd.placeholder("acc0", shape=())
        i_f, acc_f = sd.whileLoop(
            [i0, acc0],
            cond_fn=lambda sub, i, acc: i < 5.0,
            body_fn=lambda sub, i, acc: [i + 1.0, acc + i])
        outs = sd.output({"i0": jnp.asarray(0.0), "acc0": jnp.asarray(0.0)},
                         [i_f.name, acc_f.name])
        assert float(outs[i_f.name]) == 5.0
        assert float(outs[acc_f.name]) == 10.0

    def test_vector_state(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(3,))
        n = sd.placeholder("n", shape=())
        n_f, x_f = sd.whileLoop(
            [n, x],
            cond_fn=lambda sub, k, v: k > 0.0,
            body_fn=lambda sub, k, v: [k - 1.0, v * 2.0])
        outs = sd.output({"x": jnp.ones(3), "n": jnp.asarray(3.0)},
                         [x_f.name])
        np.testing.assert_allclose(np.asarray(outs[x_f.name]), 8.0)

    def test_body_arity_checked(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=())
        with pytest.raises(ValueError, match="body"):
            sd.whileLoop([x],
                         cond_fn=lambda sub, v: v > 0.0,
                         body_fn=lambda sub, v: [v, v])

    def test_serde_roundtrip_control_flow(self, tmp_path):
        sd = SameDiff()
        i0 = sd.placeholder("i0", shape=())
        acc0 = sd.placeholder("acc0", shape=())
        _, acc_f = sd.whileLoop(
            [i0, acc0],
            cond_fn=lambda sub, i, acc: i < 4.0,
            body_fn=lambda sub, i, acc: [i + 1.0, acc + i * i])
        acc_f.rename("result")
        p = str(tmp_path / "cf.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = sd2.output({"i0": jnp.asarray(0.0), "acc0": jnp.asarray(0.0)},
                         ["result"])["result"]
        assert float(out) == 0 + 1 + 4 + 9


class TestTrainableLoops:
    """Statically-bounded while loops lower to a differentiable masked
    lax.scan; genuinely dynamic loops stay lax.while_loop and must fail
    LOUDLY at grad time (reference: TrainingSession differentiates
    through Enter/Exit/Merge frames uniformly — SURVEY.md §2.12/§3.4;
    XLA makes static bounds the price of the backward pass)."""

    def _counted_loop(self):
        sd = SameDiff()
        x = sd.var("x", np.asarray([2.0, 3.0], np.float32))
        i0 = sd.constant("i0", np.int32(0))
        outs = sd.whileLoop(
            [i0, x],
            cond_fn=lambda sub, i, a: sub._op(
                "lt", [i.name, sub.constant("n", np.int32(3)).name]),
            body_fn=lambda sub, i, a: (
                sub._op("add", [i.name,
                                sub.constant("one", np.int32(1)).name]),
                sub._op("mul", [a.name,
                                sub.constant("two",
                                             np.float32(2.0)).name])))
        return sd, outs

    def test_counted_loop_derives_static_trip(self):
        sd, _ = self._counted_loop()
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] == 3

    def test_grad_flows_through_counted_loop(self):
        sd, outs = self._counted_loop()
        loss = sd._op("reduce_sum", [outs[1].name])
        sd.setLossVariables(loss.name)
        g = sd.calculateGradients({}, ["x"])
        # d/dx sum(x * 2^3) = 8
        np.testing.assert_allclose(np.asarray(g["x"]), 8.0)
        np.testing.assert_allclose(np.asarray(outs[1].eval()),
                                   [16.0, 24.0])

    def test_masked_scan_matches_while_semantics(self):
        # bound derived from an lte + step-2 counter; forward value must
        # equal the plain while result (early conjuncts honoured)
        sd = SameDiff()
        x = sd.var("x", np.float32(1.0))
        i0 = sd.constant("i0", np.int32(0))
        outs = sd.whileLoop(
            [i0, x],
            cond_fn=lambda sub, i, a: sub._op(
                "lte", [i.name, sub.constant("n", np.int32(5)).name]),
            body_fn=lambda sub, i, a: (
                sub._op("add", [i.name,
                                sub.constant("two", np.int32(2)).name]),
                sub._op("add", [a.name,
                                sub.constant("one",
                                             np.float32(1.0)).name])))
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        # i = 0,2,4 pass (<=5), i=6 fails -> 3 iterations
        assert node.attrs["max_trip_count"] == 3
        assert float(outs[1].eval()) == 4.0

    def test_dynamic_loop_grad_fails_loudly(self):
        sd = SameDiff()
        x = sd.var("x", np.asarray([1.5], np.float32))
        outs = sd.whileLoop(
            [x],
            cond_fn=lambda sub, a: sub._op(
                "lt", [sub._op("reduce_sum", [a.name]).name,
                       sub.constant("b", np.float32(100.0)).name]),
            body_fn=lambda sub, a: (
                sub._op("mul", [a.name,
                                sub.constant("two",
                                             np.float32(2.0)).name]),))
        outs = outs if isinstance(outs, tuple) else (outs,)
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        assert node.attrs["max_trip_count"] is None
        # forward still runs (inference-only loop)
        np.testing.assert_allclose(np.asarray(outs[0].eval()), [192.0])
        loss = sd._op("reduce_sum", [outs[0].name])
        sd.setLossVariables(loss.name)
        with pytest.raises(ValueError, match="inference-only"):
            sd.calculateGradients({}, ["x"])

    def test_integer_state_dynamic_loop_grads_fine(self):
        # a dynamic loop whose carried state is ALL integer receives
        # only symbolic-zero tangents: jax.grad handles it, and the
        # error path (rewrap of JAX's transpose failure) must NOT
        # false-positive on it even though it sits on the wrt path
        sd = SameDiff()
        w = sd.var("w", np.float32(1.5))
        seed = sd._op("cast", [sd._op("mul", [w.name, sd.constant(
            "zero", np.float32(0.0)).name]).name], dtype="int32")
        outs = sd.whileLoop(
            [seed],
            cond_fn=lambda sub, i: sub._op(
                "lt", [i.name, sub.constant("n", np.int32(3)).name]),
            body_fn=lambda sub, i: (
                sub._op("add", [i.name,
                                sub.constant("one",
                                             np.int32(1)).name]),))
        outs = outs if isinstance(outs, tuple) else (outs,)
        # seed is w-dependent (not a constant) -> no static derivation
        assert next(n for n in sd._ops if n.op_name == "while_loop") \
            .attrs["max_trip_count"] is None
        stepsf = sd._op("cast", [outs[0].name], dtype="float32")
        loss = sd._op("reduce_sum",
                      [sd._op("mul", [w.name, stepsf.name]).name])
        sd.setLossVariables(loss.name)
        g = sd.calculateGradients({}, ["w"])  # must NOT raise
        np.testing.assert_allclose(np.asarray(g["w"]), 3.0)

    def test_dynamic_loop_off_grad_path_is_fine(self):
        # a dynamic loop fed only by constants receives no tangents:
        # grads wrt other variables must still compute (the guard is
        # scoped to the wrt-dependent subgraph)
        sd = SameDiff()
        x = sd.var("x", np.asarray([1.0, 2.0], np.float32))
        c = sd.constant("c", np.float32(1.5))
        outs = sd.whileLoop(
            [c],
            cond_fn=lambda sub, a: sub._op(
                "lt", [a.name, sub.constant("b", np.float32(50.0)).name]),
            body_fn=lambda sub, a: (
                sub._op("mul", [a.name,
                                sub.constant("two",
                                             np.float32(2.0)).name]),))
        outs = outs if isinstance(outs, tuple) else (outs,)
        assert next(n for n in sd._ops if n.op_name == "while_loop") \
            .attrs["max_trip_count"] is None
        scaled = sd._op("mul", [outs[0].name, "x"])
        loss = sd._op("reduce_sum", [scaled.name])
        sd.setLossVariables(loss.name)
        g = sd.calculateGradients({}, ["x"])  # must NOT raise
        np.testing.assert_allclose(np.asarray(g["x"]), 96.0)

    def test_fit_gets_the_loud_error_too(self):
        from deeplearning4j_tpu.autodiff import TrainingConfig
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.learning.updaters import Sgd

        sd = SameDiff()
        x0 = sd.placeholder("x0", shape=(2,))
        w = sd.var("w", np.asarray([1.0, 1.0], np.float32))
        seeded = sd._op("mul", [x0.name, "w"])
        outs = sd.whileLoop(
            [seeded],
            cond_fn=lambda sub, a: sub._op(
                "lt", [sub._op("reduce_sum", [a.name]).name,
                       sub.constant("b", np.float32(40.0)).name]),
            body_fn=lambda sub, a: (
                sub._op("mul", [a.name,
                                sub.constant("two",
                                             np.float32(2.0)).name]),))
        outs = outs if isinstance(outs, tuple) else (outs,)
        loss = sd._op("reduce_sum", [outs[0].name])
        sd.setLossVariables(loss.name)
        sd.setTrainingConfig(TrainingConfig(
            updater=Sgd(0.01), data_set_feature_mapping=["x0"]))
        with pytest.raises(ValueError, match="inference-only"):
            sd.fit(DataSet(np.ones(2, np.float32), None), epochs=1)

    def test_tighter_conjunct_wins(self):
        # two derivable conjuncts: the analysis takes the MINIMUM bound
        sd = SameDiff()
        x = sd.var("x", np.float32(2.0))
        i0 = sd.constant("i0", np.int32(0))
        k0 = sd.constant("k0", np.float32(0.0))

        def cond(sub, i, k, a):
            lt = sub._op("lt", [i.name,
                                sub.constant("n", np.int32(5)).name])
            lt2 = sub._op("lt", [k.name,
                                 sub.constant("m",
                                              np.float32(3.0)).name])
            return sub._op("logical_and", [lt.name, lt2.name])

        def body(sub, i, k, a):
            return (
                sub._op("add", [i.name,
                                sub.constant("one", np.int32(1)).name]),
                sub._op("add", [k.name,
                                sub.constant("one_k",
                                             np.float32(1.0)).name]),
                sub._op("mul", [a.name, sub.constant(
                    "two", np.float32(2.0)).name]))

        outs = sd.whileLoop([i0, k0, x], cond_fn=cond, body_fn=body)
        assert next(n for n in sd._ops if n.op_name == "while_loop") \
            .attrs["max_trip_count"] == 3
        assert float(outs[2].eval()) == 16.0

    def test_dead_iterations_do_not_poison_grads(self):
        # derivable bound 5, but a NON-derivable data conjunct (carried
        # product, multiplicative update) exits after 3 true trips. The
        # 2 dead scan steps would compute 1/(3-k) = 1/0; the lax.cond
        # lowering must never execute them, keeping grads finite (a
        # where-mask lowering yields 0*inf = NaN in the backward pass).
        sd = SameDiff()
        x = sd.var("x", np.float32(2.0))
        i0 = sd.constant("i0", np.int32(0))
        k0 = sd.constant("k0", np.float32(0.0))
        p0 = sd.constant("p0", np.float32(1.0))

        def cond(sub, i, k, p, a):
            lt = sub._op("lt", [i.name,
                                sub.constant("n", np.int32(5)).name])
            gt = sub._op("gt", [p.name,
                                sub.constant("eps",
                                             np.float32(0.005)).name])
            return sub._op("logical_and", [lt.name, gt.name])

        def body(sub, i, k, p, a):
            den = sub._op("sub", [sub.constant(
                "three", np.float32(3.0)).name, k.name])
            inv = sub._op("div", [sub.constant(
                "one_f", np.float32(1.0)).name, den.name])
            return (
                sub._op("add", [i.name,
                                sub.constant("one", np.int32(1)).name]),
                sub._op("add", [k.name,
                                sub.constant("one_k",
                                             np.float32(1.0)).name]),
                sub._op("mul", [p.name,
                                sub.constant("tenth",
                                             np.float32(0.1)).name]),
                sub._op("mul", [a.name, inv.name]))

        outs = sd.whileLoop([i0, k0, p0, x], cond_fn=cond, body_fn=body)
        # only the i<5 conjunct derives (p's update is multiplicative)
        assert next(n for n in sd._ops if n.op_name == "while_loop") \
            .attrs["max_trip_count"] == 5
        # true trips: p = 1, .1, .01 pass (>0.005), .001 fails -> 3
        # iterations with den = 3, 2, 1; a = x/6. Dead step 4 would
        # divide by zero.
        val = float(outs[3].eval())
        np.testing.assert_allclose(val, 2.0 / 6.0, rtol=1e-6)
        loss = sd._op("reduce_sum", [outs[3].name])
        sd.setLossVariables(loss.name)
        g = sd.calculateGradients({}, ["x"])
        assert np.isfinite(np.asarray(g["x"])).all()
        np.testing.assert_allclose(np.asarray(g["x"]), 1.0 / 6.0,
                                   rtol=1e-6)

    def test_decreasing_counter_derives(self):
        sd = SameDiff()
        x = sd.var("x", np.float32(0.0))
        i0 = sd.constant("i0", np.int32(10))
        outs = sd.whileLoop(
            [i0, x],
            cond_fn=lambda sub, i, a: sub._op(
                "gt", [i.name, sub.constant("n", np.int32(4)).name]),
            body_fn=lambda sub, i, a: (
                sub._op("sub", [i.name,
                                sub.constant("two", np.int32(2)).name]),
                sub._op("add", [a.name,
                                sub.constant("one",
                                             np.float32(1.0)).name])))
        node = next(n for n in sd._ops if n.op_name == "while_loop")
        # i = 10,8,6 pass (>4), i=4 fails -> 3 iterations
        assert node.attrs["max_trip_count"] == 3
        assert float(outs[1].eval()) == 3.0
        loss = sd._op("reduce_sum", [outs[1].name])
        sd.setLossVariables(loss.name)
        sd.calculateGradients({}, ["x"])  # differentiable


class TestGradCheckUtil:
    def test_passes_on_correct_graph(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4, 3))
        w = sd.var("w", np.random.default_rng(0).normal(size=(3, 2)) * 0.5)
        b = sd.var("b", np.zeros(2))
        out = sd.nn.sigmoid(x @ w + b)
        loss = (out * out).mean()
        sd.setLossVariables(loss.name)
        feeds = {"x": np.random.default_rng(1).normal(size=(4, 3))}
        assert GradCheckUtil.checkGradients(sd, feeds, eps=1e-2,
                                            max_rel_error=0.08)

    def test_catches_wrong_gradient(self):
        # stop_gradient makes the analytic grad 0 while numeric isn't
        sd = SameDiff()
        w = sd.var("w", jnp.asarray([1.0, 2.0]))
        out = sd.math.stop_gradient(w * w).sum() \
            if hasattr(sd.math, "stop_gradient") else None
        if out is None:
            pytest.skip("no stop_gradient op registered")
        sd.setLossVariables(out.name)
        assert not GradCheckUtil.checkGradients(
            sd, {}, eps=1e-2, print_failures=False)


class TestOpValidation:
    def test_forward_and_grad(self):
        rng = np.random.default_rng(0)
        OpValidation.validate(OpTestCase(
            "matmul",
            args=[rng.normal(size=(3, 4)).astype(np.float32),
                  rng.normal(size=(4, 2)).astype(np.float32)],
            expected=lambda a, b: a @ b,
            grad_eps=1e-2, grad_rtol=0.08))

    def test_attrs_and_reduction(self):
        rng = np.random.default_rng(0)
        OpValidation.validate(OpTestCase(
            "reduce_mean",
            args=[rng.normal(size=(3, 4)).astype(np.float32)],
            attrs={"dimensions": [1]},
            expected=lambda a: a.mean(axis=1),
            grad_eps=1e-2, grad_rtol=0.08))

    def test_forward_mismatch_raises(self):
        with pytest.raises(AssertionError):
            OpValidation.validate(OpTestCase(
                "add", args=[np.ones(2, np.float32), np.ones(2, np.float32)],
                expected=lambda a, b: a * 5,
                grad_check=False))

    def test_coverage_report(self):
        rep = OpValidation.coverage_report()
        assert rep["total"] > 50
        assert "matmul" in rep["validated"]
