"""Dense TensorArray ops + dynamic StridedSlice + call_graph units
(reference: the TensorArray declarable ops AbstractSession evaluates,
SURVEY.md §3.4 — here a TA is a dense array carried as loop state)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.registry import get_op


class TestTensorArrayOps:
    def test_tensorarray_reserve(self):
        ta = get_op("tensorarray_reserve")(size=4, elem_shape=(2, 3),
                                           dtype="float32")
        assert ta.shape == (4, 2, 3) and ta.dtype == jnp.float32
        assert float(jnp.abs(ta).sum()) == 0.0

    def test_tensorarray_write_read_roundtrip(self):
        ta = get_op("tensorarray_reserve")(size=3, elem_shape=(2,))
        v = jnp.asarray([1.5, -2.0])
        ta = get_op("tensorarray_write")(ta, 1, v)
        got = jnp.take(ta, 1, axis=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(v))
        assert float(jnp.abs(ta[0]).sum()) == 0.0

    def test_tensorarray_write_traced_index_in_loop(self):
        """The point of the dense representation: writes with a traced
        loop counter compile into lax.while_loop."""
        def step(i, ta):
            return i + 1, get_op("tensorarray_write")(
                ta, i, jnp.full((2,), i, jnp.float32))

        def run():
            ta = get_op("tensorarray_reserve")(size=4, elem_shape=(2,))
            _, ta = jax.lax.while_loop(lambda s: s[0] < 4,
                                       lambda s: step(*s), (0, ta))
            return ta

        out = np.asarray(jax.jit(run)())
        np.testing.assert_allclose(out[:, 0], [0, 1, 2, 3])

    def test_tensorarray_scatter_defines_shape(self):
        # dummy 1-D reserve (unknown element shape) + full scatter:
        # the value defines the real shape
        ta = get_op("tensorarray_reserve")(size=3, elem_shape=())
        v = jnp.arange(6, dtype=jnp.float32).reshape(3, 2)
        out = get_op("tensorarray_scatter")(ta, jnp.arange(3), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(v))

    def test_tensorarray_scatter_partial(self):
        ta = get_op("tensorarray_reserve")(size=4, elem_shape=(2,))
        v = jnp.ones((2, 2))
        out = get_op("tensorarray_scatter")(ta, jnp.asarray([3, 1]), v)
        np.testing.assert_allclose(np.asarray(out).sum(axis=1),
                                   [0, 2, 0, 2])

    def test_tensorarray_size(self):
        ta = get_op("tensorarray_reserve")(size=5, elem_shape=(2,))
        assert int(get_op("tensorarray_size")(ta)) == 5


class TestDynamicStridedSlice:
    def test_tf_strided_slice_dyn_shrink(self):
        """a[:, i] with traced i — the dynamic_rnn per-step read."""
        x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)

        def f(i):
            begin_t = jnp.stack([jnp.asarray(0), i])
            return get_op("tf_strided_slice_dyn")(
                x, begin_t, begin=[0, None], end=[0, None],
                begin_mask=1, end_mask=1, shrink_axis_mask=2)

        out = jax.jit(f)(jnp.asarray(2))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(x)[:, 2])

    def test_tf_strided_slice_dyn_negative_index(self):
        x = jnp.arange(5, dtype=jnp.float32)
        out = get_op("tf_strided_slice_dyn")(
            x, jnp.asarray([-1]), begin=[None], end=[None],
            begin_mask=0, end_mask=0, shrink_axis_mask=1)
        assert float(out) == 4.0

    def test_tf_strided_slice_dyn_mixed_static(self):
        x = jnp.arange(24, dtype=jnp.float32).reshape(4, 6)
        out = get_op("tf_strided_slice_dyn")(
            x, jnp.asarray([1, 2]), begin=[1, None], end=[3, None],
            begin_mask=0, end_mask=0, shrink_axis_mask=2)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(x)[1:3, 2])


class TestCallGraph:
    def test_call_graph_inlines_subgraph(self):
        from deeplearning4j_tpu.autodiff.control_flow import (
            subgraph_to_dict,
        )
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sub = SameDiff()
        a = sub.placeholder("sg_in_0")
        b = sub.placeholder("sg_in_1")
        out = a * b + a
        g = subgraph_to_dict(sub, [out.name], 2)
        x = jnp.asarray([1.0, 2.0])
        y = jnp.asarray([3.0, 4.0])
        res = get_op("call_graph")(x, y, graph=g)
        np.testing.assert_allclose(np.asarray(res), [4.0, 10.0])
