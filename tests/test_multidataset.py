"""MultiDataSet / MultiDataSetIterator tests (reference analog:
MultiDataSetTest, ComputationGraph multi-input fit tests)."""

import numpy as np

from deeplearning4j_tpu.datasets import (
    ArrayMultiDataSetIterator, ListMultiDataSetIterator, MultiDataSet,
    MultiDataSetIteratorAdapter, ArrayDataSetIterator,
)
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration, MergeVertex,
)


def _two_input_graph():
    b = (ComputationGraphConfiguration.graphBuilder().seed(0)
         .updater(Adam(learning_rate=5e-3)).addInputs("a", "b"))
    b.setInputTypes(InputType.feedForward(3), InputType.feedForward(3))
    b.addLayer("da", DenseLayer(n_in=3, n_out=8, activation="relu"), "a")
    b.addLayer("db", DenseLayer(n_in=3, n_out=8, activation="relu"), "b")
    b.addVertex("m", MergeVertex(), "da", "db")
    b.addLayer("out", OutputLayer(n_in=16, n_out=2, activation="softmax",
                                  loss="mcxent"), "m")
    return ComputationGraph(b.setOutputs("out").build()).init()


def _data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    xa = rng.normal(size=(n, 3)).astype(np.float32)
    xb = rng.normal(size=(n, 3)).astype(np.float32)
    lab = ((xa[:, 0] + xb[:, 0]) > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[lab]
    return xa, xb, y, lab


class TestMultiDataSet:
    def test_accessors_and_split(self):
        xa, xb, y, _ = _data()
        mds = MultiDataSet([xa, xb], [y])
        assert mds.numFeatureArrays() == 2
        assert mds.numLabelsArrays() == 1
        assert mds.numExamples() == 48
        parts = mds.splitBatches(20)
        assert [p.numExamples() for p in parts] == [20, 20, 8]
        np.testing.assert_allclose(parts[1].getFeatures(0), xa[20:40])

    def test_graph_fit_with_multidataset(self):
        xa, xb, y, lab = _data()
        g = _two_input_graph()
        mds = MultiDataSet([xa, xb], [y])
        s0 = None
        for _ in range(40):
            g.fit(mds)
            s0 = s0 or g.score()
        assert g.score() < s0
        pred = np.asarray(g.outputSingle(xa, xb)).argmax(-1)
        assert (pred == lab).mean() > 0.85

    def test_graph_fit_with_iterator(self):
        xa, xb, y, _ = _data()
        g = _two_input_graph()
        it = ArrayMultiDataSetIterator([xa, xb], [y], batch_size=16)
        g.fit(it, epochs=5)
        assert np.isfinite(g.score())
        # list iterator path too
        parts = MultiDataSet([xa, xb], [y]).splitBatches(16)
        g.fit(ListMultiDataSetIterator(parts), epochs=2)
        assert np.isfinite(g.score())

    def test_adapter_wraps_datasetiterator(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        base = ArrayDataSetIterator(x, y, 8)
        adapter = MultiDataSetIteratorAdapter(base)
        batches = list(adapter)
        assert len(batches) == 4
        assert batches[0].numFeatureArrays() == 1
        assert batches[0].getFeatures(0).shape == (8, 4)


class TestMaskAndResetGuards:
    def test_masks_preserved_by_adapter_and_split(self):
        from deeplearning4j_tpu.datasets import DataSet
        x = np.ones((8, 5, 3), np.float32)
        y = np.ones((8, 5, 2), np.float32)
        lm = np.ones((8, 5), np.float32)
        mds = MultiDataSet.fromDataSet(DataSet(x, y, labels_mask=lm))
        assert len(mds.labels_mask_arrays) == 1
        parts = mds.splitBatches(3)
        assert parts[0].labels_mask_arrays[0].shape == (3, 5)
        # masked data must NOT silently train on the graph path
        import pytest as _pytest
        g = _two_input_graph()  # wrong input count is irrelevant: guard first
        with _pytest.raises(NotImplementedError, match="mask"):
            g.fit(mds)

    def test_dataset_with_mask_raises_on_graph(self):
        import pytest as _pytest
        from deeplearning4j_tpu.datasets import DataSet
        g = _two_input_graph()
        ds = DataSet(np.ones((4, 3), np.float32),
                     np.ones((4, 2), np.float32),
                     labels_mask=np.ones((4,), np.float32))
        with _pytest.raises(NotImplementedError, match="mask"):
            g.fit(ds)

    def test_nonresettable_multi_epoch_raises(self):
        import pytest as _pytest

        class OneShot(ListMultiDataSetIterator):
            def resetSupported(self):
                return False

        xa, xb, y, _ = _data(16)
        parts = MultiDataSet([xa, xb], [y]).splitBatches(8)
        g = _two_input_graph()
        with _pytest.raises(ValueError, match="resettable"):
            g.fit(OneShot(parts), epochs=3)
        g.fit(OneShot(parts), epochs=1)  # single epoch is fine
