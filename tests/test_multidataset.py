"""MultiDataSet / MultiDataSetIterator tests (reference analog:
MultiDataSetTest, ComputationGraph multi-input fit tests)."""

import numpy as np

from deeplearning4j_tpu.datasets import (
    ArrayMultiDataSetIterator, ListMultiDataSetIterator, MultiDataSet,
    MultiDataSetIteratorAdapter, ArrayDataSetIterator,
)
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration, MergeVertex,
)


def _two_input_graph():
    b = (ComputationGraphConfiguration.graphBuilder().seed(0)
         .updater(Adam(learning_rate=5e-3)).addInputs("a", "b"))
    b.setInputTypes(InputType.feedForward(3), InputType.feedForward(3))
    b.addLayer("da", DenseLayer(n_in=3, n_out=8, activation="relu"), "a")
    b.addLayer("db", DenseLayer(n_in=3, n_out=8, activation="relu"), "b")
    b.addVertex("m", MergeVertex(), "da", "db")
    b.addLayer("out", OutputLayer(n_in=16, n_out=2, activation="softmax",
                                  loss="mcxent"), "m")
    return ComputationGraph(b.setOutputs("out").build()).init()


def _data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    xa = rng.normal(size=(n, 3)).astype(np.float32)
    xb = rng.normal(size=(n, 3)).astype(np.float32)
    lab = ((xa[:, 0] + xb[:, 0]) > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[lab]
    return xa, xb, y, lab


class TestMultiDataSet:
    def test_accessors_and_split(self):
        xa, xb, y, _ = _data()
        mds = MultiDataSet([xa, xb], [y])
        assert mds.numFeatureArrays() == 2
        assert mds.numLabelsArrays() == 1
        assert mds.numExamples() == 48
        parts = mds.splitBatches(20)
        assert [p.numExamples() for p in parts] == [20, 20, 8]
        np.testing.assert_allclose(parts[1].getFeatures(0), xa[20:40])

    def test_graph_fit_with_multidataset(self):
        xa, xb, y, lab = _data()
        g = _two_input_graph()
        mds = MultiDataSet([xa, xb], [y])
        s0 = None
        for _ in range(40):
            g.fit(mds)
            s0 = s0 or g.score()
        assert g.score() < s0
        pred = np.asarray(g.outputSingle(xa, xb)).argmax(-1)
        assert (pred == lab).mean() > 0.85

    def test_graph_fit_with_iterator(self):
        xa, xb, y, _ = _data()
        g = _two_input_graph()
        it = ArrayMultiDataSetIterator([xa, xb], [y], batch_size=16)
        g.fit(it, epochs=5)
        assert np.isfinite(g.score())
        # list iterator path too
        parts = MultiDataSet([xa, xb], [y]).splitBatches(16)
        g.fit(ListMultiDataSetIterator(parts), epochs=2)
        assert np.isfinite(g.score())

    def test_adapter_wraps_datasetiterator(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        base = ArrayDataSetIterator(x, y, 8)
        adapter = MultiDataSetIteratorAdapter(base)
        batches = list(adapter)
        assert len(batches) == 4
        assert batches[0].numFeatureArrays() == 1
        assert batches[0].getFeatures(0).shape == (8, 4)


class TestMaskAndResetGuards:
    def test_masks_preserved_by_adapter_and_split(self):
        from deeplearning4j_tpu.datasets import DataSet
        x = np.ones((8, 5, 3), np.float32)
        y = np.ones((8, 5, 2), np.float32)
        lm = np.ones((8, 5), np.float32)
        mds = MultiDataSet.fromDataSet(DataSet(x, y, labels_mask=lm))
        assert len(mds.labels_mask_arrays) == 1
        parts = mds.splitBatches(3)
        assert parts[0].labels_mask_arrays[0].shape == (3, 5)

    def test_features_mask_applied_on_graph(self):
        """Graph fit honors features masks: padded steps (which carry a
        strong anti-signal here) are zeroed before the forward."""
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer, \
            DenseLayer as DL
        b = (ComputationGraphConfiguration.graphBuilder().seed(4)
             .updater(Adam(learning_rate=1e-2)).addInputs("seq"))
        b.setInputTypes(InputType.recurrent(4, 6))
        b.addLayer("d", DL(n_in=4, n_out=8, activation="tanh"), "seq")
        b.addLayer("pool", GlobalPoolingLayer(pooling_type="avg"), "d")
        b.addLayer("out", OutputLayer(n_in=8, n_out=2,
                                      activation="softmax", loss="mcxent"),
                   "pool")
        conf = b.setOutputs("out").build()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 6, 4)).astype(np.float32)
        lab = (x[:, :3, 0].mean(1) > 0).astype(int)
        x[:, 3:] = -np.sign(lab)[:, None, None] * 5.0
        y = np.eye(2, dtype=np.float32)[lab]
        fm = np.ones((16, 6), np.float32)
        fm[:, 3:] = 0
        g_m = ComputationGraph(conf).init()
        mds = MultiDataSet([x], [y], features_mask_arrays=[fm])
        for _ in range(30):
            g_m.fit(mds)
        g_u = ComputationGraph(conf).init()
        for _ in range(30):
            g_u.fit(MultiDataSet([x], [y]))
        assert not np.allclose(np.asarray(g_m.params_map["d"]["W"]),
                               np.asarray(g_u.params_map["d"]["W"]))

    def test_label_mask_applied_in_graph_loss(self):
        """Label masks flow to the output layer's loss: masking out the
        second half of a sequence must change the loss."""
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer, LSTM, \
            RnnOutputLayer
        b = (ComputationGraphConfiguration.graphBuilder().seed(2)
             .updater(Adam(learning_rate=1e-3)).addInputs("seq"))
        b.setInputTypes(InputType.recurrent(3, 6))
        b.addLayer("rnn", LSTM(n_in=3, n_out=5), "seq")
        b.addLayer("out", RnnOutputLayer(n_in=5, n_out=2,
                                         activation="softmax",
                                         loss="mcxent"), "rnn")
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 6, 3)).astype(np.float32)
        y = np.zeros((4, 6, 2), np.float32)
        y[..., 0] = 1
        # corrupt the second half's labels; mask them out
        y_bad = y.copy()
        y_bad[:, 3:, 0] = 0
        y_bad[:, 3:, 1] = 1
        mask = np.ones((4, 6), np.float32)
        mask[:, 3:] = 0

        g1 = ComputationGraph(b.setOutputs("out").build()).init()
        mds = MultiDataSet([x], [y_bad], labels_mask_arrays=[mask])
        g1.fit(mds)
        masked_loss = g1.score()
        # same graph, same data, NO mask -> corrupted labels contribute
        g2 = ComputationGraph(g1.conf).init()
        g2.fit(MultiDataSet([x], [y_bad]))
        unmasked_loss = g2.score()
        assert abs(masked_loss - unmasked_loss) > 1e-3

    def test_nonresettable_multi_epoch_raises(self):
        import pytest as _pytest

        class OneShot(ListMultiDataSetIterator):
            def resetSupported(self):
                return False

        xa, xb, y, _ = _data(16)
        parts = MultiDataSet([xa, xb], [y]).splitBatches(8)
        g = _two_input_graph()
        with _pytest.raises(ValueError, match="resettable"):
            g.fit(OneShot(parts), epochs=3)
        g.fit(OneShot(parts), epochs=1)  # single epoch is fine


class TestMaskSemantics:
    """compute_loss mask shapes + normalization (reference:
    ILossFunction mask/minibatch score semantics)."""

    def _ce(self, labels, logits, mask):
        import jax.numpy as jnp
        from deeplearning4j_tpu.loss import LossFunction, compute_loss
        return float(compute_loss(LossFunction.MCXENT,
                                  jnp.asarray(labels), jnp.asarray(logits),
                                  "softmax", None if mask is None
                                  else jnp.asarray(mask)))

    def test_all_ones_mask_is_identity(self):
        rng = np.random.default_rng(0)
        labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 6))]
        logits = rng.normal(size=(4, 6, 2)).astype(np.float32)
        unmasked = self._ce(labels, logits, None)
        masked = self._ce(labels, logits, np.ones((4, 6), np.float32))
        assert abs(unmasked - masked) < 1e-5

    def test_mask_shapes_accepted(self):
        rng = np.random.default_rng(1)
        labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 6))]
        logits = rng.normal(size=(4, 6, 2)).astype(np.float32)
        base = self._ce(labels, logits, np.ones((4, 6), np.float32))
        # [N,T,1] same as [N,T]
        assert abs(self._ce(labels, logits,
                            np.ones((4, 6, 1), np.float32)) - base) < 1e-5
        # [N,1] per-example weights: all-ones == unmasked
        assert abs(self._ce(labels, logits,
                            np.ones((4, 1), np.float32)) - base) < 1e-5
        # [N] per-example on 2D labels
        l2d = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        z2d = rng.normal(size=(4, 3)).astype(np.float32)
        assert abs(self._ce(l2d, z2d, np.ones(4, np.float32)) -
                   self._ce(l2d, z2d, None)) < 1e-5

    def test_masked_timesteps_contribute_zero(self):
        rng = np.random.default_rng(2)
        labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 6))]
        logits = rng.normal(size=(4, 6, 2)).astype(np.float32)
        m = np.ones((4, 6), np.float32)
        m[:, 3:] = 0
        masked = self._ce(labels, logits, m)
        # equals CE computed on the first half only (same N divisor)
        half = self._ce(labels[:, :3], logits[:, :3], None)
        assert abs(masked - half) < 1e-5

    def test_graph_mask_count_mismatch_raises(self):
        import pytest as _pytest
        g = _two_input_graph()
        xa, xb, y, _ = _data(8)
        mds = MultiDataSet([xa, xb], [y])
        with _pytest.raises(ValueError, match="label masks"):
            g._fit_batch([xa, xb], [y], [None, np.ones(8)])

    def test_panic_env_wiring(self):
        import subprocess, sys, os
        code = (
            "import os\n"
            "os.environ['JAX_PLATFORMS']='cpu'\n"
            "os.environ['PALLAS_AXON_POOL_IPS']=''\n"
            "from deeplearning4j_tpu.profiler import OpProfiler, ProfilerMode\n"
            "assert OpProfiler.getInstance().config.mode is ProfilerMode.NAN_PANIC\n"
            "print('WIRED')\n")
        env = dict(os.environ, DL4J_TPU_PANIC="nan", JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert "WIRED" in r.stdout, r.stderr[-500:]


class TestMaskedInference:
    def test_output_honors_features_mask(self):
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer, \
            DenseLayer as DL
        b = (ComputationGraphConfiguration.graphBuilder().seed(9)
             .updater(Adam(learning_rate=1e-2)).addInputs("seq"))
        b.setInputTypes(InputType.recurrent(3, 4))
        b.addLayer("d", DL(n_in=3, n_out=6, activation="tanh"), "seq")
        b.addLayer("pool", GlobalPoolingLayer(pooling_type="avg"), "d")
        b.addLayer("out", OutputLayer(n_in=6, n_out=2,
                                      activation="softmax", loss="mcxent"),
                   "pool")
        g = ComputationGraph(b.setOutputs("out").build()).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 4, 3)).astype(np.float32)
        fm = np.array([[1, 1, 0, 0]] * 4, np.float32)
        o_masked = np.asarray(g.outputSingle(x, feature_masks=[fm]))
        o_plain = np.asarray(g.outputSingle(x))
        assert not np.allclose(o_masked, o_plain)
        # recompute manually: mean over first 2 steps == masked avg
        d_w = g.params_map["d"]
        h = np.tanh(x @ np.asarray(d_w["W"]) + np.asarray(d_w["b"]))
        pooled = h[:, :2].mean(1)
        ow = g.params_map["out"]
        logits = pooled @ np.asarray(ow["W"]) + np.asarray(ow["b"])
        want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        np.testing.assert_allclose(o_masked, want, atol=1e-4)

    def test_bad_fmask_shape_raises(self):
        import pytest as _pytest
        g = _two_input_graph()
        xa, xb, y, _ = _data(8)
        with _pytest.raises(NotImplementedError, match="features mask"):
            g._fit_batch([xa, xb], [y], None,
                         [np.ones((8,), np.float32), None])

    def test_mln_output_mask_consistency(self):
        from deeplearning4j_tpu.nn.conf import (
            GlobalPoolingLayer, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(2)
                .updater(Adam(learning_rate=1e-2)).list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(GlobalPoolingLayer(pooling_type="max"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.recurrent(3, 4)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(1).normal(size=(4, 4, 3)).astype(np.float32)
        fm = np.array([[1, 1, 0, 0]] * 4, np.float32)
        o_m = np.asarray(net.output(x, features_mask=fm))
        o_p = np.asarray(net.output(x))
        assert not np.allclose(o_m, o_p)


class TestMaskBranchIsolation:
    def test_unmasked_branch_pooling_not_masked(self):
        """Masked pooling must only fire on the masked input's branch."""
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer, \
            DenseLayer as DL
        b = (ComputationGraphConfiguration.graphBuilder().seed(0)
             .updater(Adam(learning_rate=1e-3)).addInputs("a", "b"))
        b.setInputTypes(InputType.recurrent(3, 4), InputType.recurrent(3, 4))
        b.addLayer("pa", GlobalPoolingLayer(pooling_type="avg"), "a")
        b.addLayer("pb", GlobalPoolingLayer(pooling_type="avg"), "b")
        b.addVertex("m", MergeVertex(), "pa", "pb")
        b.addLayer("out", OutputLayer(n_in=6, n_out=2,
                                      activation="softmax", loss="mcxent"),
                   "m")
        g = ComputationGraph(b.setOutputs("out").build()).init()
        xa = np.ones((2, 4, 3), np.float32)
        xb = np.ones((2, 4, 3), np.float32) * 2.0
        fm = np.array([[1, 1, 0, 0]] * 2, np.float32)  # mask only input a
        # run the training-path forward via one fit step and check the
        # pooled activations through the jitted loss by comparing to an
        # unmasked-b expectation: b's avg over ALL 4 steps stays 2.0
        import jax
        outs, _ = g._forward_all(
            g.params_map, g.states_map,
            {"a": jax.numpy.asarray(xa), "b": jax.numpy.asarray(xb)},
            False, None, {"a": jax.numpy.asarray(fm)})
        np.testing.assert_allclose(np.asarray(outs["pa"]), 1.0, atol=1e-6)
        # b unmasked: avg over 4 steps of constant 2.0 -> exactly 2.0;
        # a bug applying a's mask to b would still give 2.0 here, so
        # ALSO check a zero-suffixed b would differ:
        xb2 = xb.copy()
        xb2[:, 2:] = 0
        outs2, _ = g._forward_all(
            g.params_map, g.states_map,
            {"a": jax.numpy.asarray(xa), "b": jax.numpy.asarray(xb2)},
            False, None, {"a": jax.numpy.asarray(fm)})
        # unmasked avg over 4 steps = 1.0; masked-with-a's-mask would be 2.0
        np.testing.assert_allclose(np.asarray(outs2["pb"]), 1.0, atol=1e-6)

    def test_reference_interval_overload(self):
        from deeplearning4j_tpu.ndarray import Nd4j, NDArrayIndex
        a = Nd4j.arange(10)
        # reference 3-arg form: (begin, stride, end)
        got = a.get(NDArrayIndex.interval(0, 2, 10))
        np.testing.assert_allclose(got.toNumpy(), [0, 2, 4, 6, 8])
        # put without an index raises
        import pytest as _pytest
        with _pytest.raises(TypeError, match="put"):
            a.put(5.0)
