"""BertWordPieceTokenizer golden vs HuggingFace `tokenizers` + the
BertIterator text->fine-tune path (reference: BertWordPieceTokenizer +
BertIterator feeding SameDiff BERT fine-tuning, SURVEY.md §2.35)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import BertIterator, BertWordPieceTokenizer

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "quick", "brown", "fox", "jump", "##s", "##ed", "over",
         "lazy", "dog", "##gy", "un", "##aff", "##able", "run", "##ning",
         "hello", "world", ",", ".", "!", "?", "'", "te", "##st",
         "cafe", "12", "##3", "a", "b", "c", "中", "国"]

SENTENCES = [
    "The quick brown fox jumps over the lazy dog.",
    "Hello, world!",
    "unaffable",
    "running tests",
    "Café 123",            # accents + digits
    "totallyunknownword here",  # -> [UNK]
    "hello 中国 world",          # CJK chars split
    "a b c a b c",
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("wp") / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return str(p)


@pytest.fixture(scope="module")
def wp(vocab_file):
    return BertWordPieceTokenizer(vocab_file)


class TestWordPieceGolden:
    def test_matches_hf_tokenizers(self, wp, vocab_file):
        hf_tok = pytest.importorskip("tokenizers")
        from tokenizers import BertWordPieceTokenizer as HFWordPiece

        hf = HFWordPiece(vocab_file, lowercase=True)
        del hf_tok
        for s in SENTENCES:
            ours, _ = wp.encode(s)
            theirs = hf.encode(s).ids
            assert ours == list(theirs), (s, ours, theirs)

    def test_pair_encoding_matches_hf(self, wp, vocab_file):
        pytest.importorskip("tokenizers")
        from tokenizers import BertWordPieceTokenizer as HFWordPiece

        hf = HFWordPiece(vocab_file, lowercase=True)
        ids, segs = wp.encode("the quick fox", "hello world!")
        enc = hf.encode("the quick fox", "hello world!")
        assert ids == list(enc.ids)
        assert segs == list(enc.type_ids)

    def test_greedy_longest_match(self, wp):
        assert wp.tokenize("unaffable") == ["un", "##aff", "##able"]
        assert wp.tokenize("jumps") == ["jump", "##s"]
        assert wp.tokenize("doggy") == ["dog", "##gy"]

    def test_unknown_word(self, wp):
        assert wp.tokenize("zzzzz") == ["[UNK]"]

    def test_truncation_budget(self, wp):
        ids, _ = wp.encode(" ".join(["the"] * 50), max_len=16)
        assert len(ids) == 16
        assert ids[0] == VOCAB.index("[CLS]")
        assert ids[-1] == VOCAB.index("[SEP]")

    def test_decode_roundtrip(self, wp):
        ids, _ = wp.encode("unaffable doggy")
        assert wp.decode(ids) == "unaffable doggy"


class TestBertIterator:
    def test_seq_classification_batches(self, wp):
        data = [("the quick fox", 0), ("lazy doggy", 1),
                ("hello world", 1)]
        it = (BertIterator.builder().tokenizer(wp)
              .lengthHandling("FIXED_LENGTH", 12).minibatchSize(2)
              .sentenceProvider(data)
              .task(BertIterator.SEQ_CLASSIFICATION).build())
        batches = list(it)
        assert [b["ids"].shape[0] for b in batches] == [2, 1]
        b0 = batches[0]
        assert b0["ids"].shape == (2, 12)
        assert b0["mask"].dtype == np.float32
        assert b0["labels"].tolist() == [0, 1]
        # padding is masked out
        row_len = int(b0["mask"][0].sum())
        assert (b0["ids"][0, row_len:] == 0).all()

    def test_unsupervised_mlm_masking(self, wp):
        data = ["the quick brown fox jumps over the lazy dog"] * 8
        it = BertIterator(wp, data, length=16, batch_size=8,
                          task=BertIterator.UNSUPERVISED,
                          mask_prob=0.5, seed=1)
        b = next(iter(it))
        pos = b["mlm_positions"]
        assert pos.sum() > 0
        # masked positions never touch CLS/SEP/PAD
        cls_id, sep_id = VOCAB.index("[CLS]"), VOCAB.index("[SEP]")
        orig = b["mlm_labels"]
        assert not ((pos > 0) & ((orig == cls_id) | (orig == sep_id)
                                 | (orig == 0))).any()
        # ~80% of picked positions became [MASK]
        mask_id = VOCAB.index("[MASK]")
        frac = ((b["ids"] == mask_id) & (pos > 0)).sum() / pos.sum()
        assert 0.5 < frac <= 1.0

    def test_text_to_finetune_end_to_end(self, wp):
        """Raw text -> BertIterator -> BertClassifier fine-tune: the
        full reference capability (BertIterator + SameDiff BERT)."""
        import jax

        from deeplearning4j_tpu.learning.updaters import Adam
        from deeplearning4j_tpu.models.bert_classifier import (
            BertSequenceClassifier,
        )
        from deeplearning4j_tpu.models.transformer import tiny_config

        data = [("the quick brown fox", 0), ("lazy doggy runs", 1),
                ("quick quick fox fox", 0), ("lazy lazy dog dog", 1)] * 4
        it = BertIterator(wp, data, length=12, batch_size=8, seed=0)

        cfg = tiny_config(vocab=len(VOCAB), max_len=12, d_model=32,
                          n_layers=2, n_heads=4, d_ff=64)
        model = BertSequenceClassifier(cfg, n_classes=2)
        params = model.init_params()
        updater = Adam(learning_rate=5e-3)
        opt = updater.init_state(params)
        step = model.make_train_step(updater)

        losses = []
        rng = jax.random.key(0)
        for epoch in range(6):
            ep = []
            for b in it:
                params, opt, loss = step(
                    params, opt, np.int32(epoch), b["ids"],
                    b["labels"], b["mask"], rng)
                ep.append(float(loss))
            losses.append(sum(ep) / len(ep))
        assert losses[-1] < losses[0] * 0.5, losses

        preds = model.predict(params, batches_ids := next(
            iter(it))["ids"], mask=None)
        assert preds.shape[0] == batches_ids.shape[0]
