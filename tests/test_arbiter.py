"""Hyperparameter search (reference: arbiter — spaces, grid/random
generators, LocalOptimizationRunner, termination. SURVEY.md §2.41)."""

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace, DiscreteParameterSpace, FixedValue,
    GeneticSearchCandidateGenerator, GridSearchCandidateGenerator,
    IntegerParameterSpace, LocalOptimizationRunner,
    MaxCandidatesCondition, MaxTimeCondition, OptimizationConfiguration,
    RandomSearchGenerator,
)


class TestSpaces:
    def test_continuous_bounds(self):
        s = ContinuousParameterSpace(0.1, 0.9)
        vals = [s.sample(u) for u in np.linspace(0, 0.999, 50)]
        assert min(vals) >= 0.1 and max(vals) <= 0.9

    def test_log_scale(self):
        s = ContinuousParameterSpace(1e-4, 1e-1, log_scale=True)
        assert s.sample(0.0) == pytest.approx(1e-4)
        assert s.sample(1.0) == pytest.approx(1e-1)
        # midpoint in log space is the geometric mean
        assert s.sample(0.5) == pytest.approx(np.sqrt(1e-4 * 1e-1), rel=1e-6)

    def test_integer_grid(self):
        s = IntegerParameterSpace(2, 5)
        assert s.grid_values(10) == [2, 3, 4, 5]
        assert all(2 <= s.sample(u) <= 5 for u in np.linspace(0, 0.999, 20))

    def test_discrete_and_fixed(self):
        d = DiscreteParameterSpace(["a", "b", "c"])
        assert d.grid_values(99) == ["a", "b", "c"]
        assert d.sample(0.99) == "c"
        assert FixedValue(7).sample(0.3) == 7


class TestGenerators:
    def test_grid_cartesian(self):
        gen = GridSearchCandidateGenerator(
            {"x": DiscreteParameterSpace([1, 2]),
             "y": DiscreteParameterSpace(["p", "q"])})
        combos = list(gen.candidates())
        assert len(combos) == 4
        assert {"x": 1, "y": "p"} in combos

    def test_grid_random_order_same_set(self):
        space = {"x": IntegerParameterSpace(0, 5)}
        a = list(GridSearchCandidateGenerator(space, 10).candidates())
        b = list(GridSearchCandidateGenerator(
            space, 10, mode="RandomOrder", seed=1).candidates())
        assert sorted(c["x"] for c in a) == sorted(c["x"] for c in b)

    def test_random_reproducible(self):
        space = {"lr": ContinuousParameterSpace(0, 1)}
        g1 = RandomSearchGenerator(space, seed=5, max_candidates=5)
        g2 = RandomSearchGenerator(space, seed=5, max_candidates=5)
        assert [c["lr"] for c in g1.candidates()] == \
               [c["lr"] for c in g2.candidates()]


class TestGeneticSearch:
    """Reference: GeneticSearchCandidateGenerator — score feedback via
    the runner's report() hook drives selection in genotype space."""

    SPACE = {"x": ContinuousParameterSpace(0.0, 1.0),
             "y": ContinuousParameterSpace(0.0, 1.0)}

    @staticmethod
    def _score(c):
        return (c["x"] - 0.7) ** 2 + (c["y"] - 0.3) ** 2

    def _best(self, gen, n):
        runner = LocalOptimizationRunner(OptimizationConfiguration(
            candidate_generator=gen, score_function=self._score,
            termination_conditions=[MaxCandidatesCondition(n)]))
        runner.execute()
        return runner.bestResult().score

    def test_beats_random_on_quadratic(self):
        budget = 120
        genetic = self._best(GeneticSearchCandidateGenerator(
            self.SPACE, population_size=12, seed=3), budget)
        random = self._best(RandomSearchGenerator(self.SPACE, seed=3),
                            budget)
        assert genetic < random
        assert genetic < 1e-3   # converged near (0.7, 0.3)

    def test_maximize_mode_inherited_from_config(self):
        # the generator's direction defaults to None and inherits the
        # config's — setting it in one place cannot silently breed from
        # the worst candidates
        gen = GeneticSearchCandidateGenerator(
            self.SPACE, population_size=10, seed=1)
        runner = LocalOptimizationRunner(OptimizationConfiguration(
            candidate_generator=gen,
            score_function=lambda c: -self._score(c),
            termination_conditions=[MaxCandidatesCondition(100)],
            minimize=False))
        runner.execute()
        assert gen.minimize is False
        # selection must have pushed toward (0.7, 0.3); random-only at
        # this budget typically sits an order of magnitude further out
        assert runner.bestResult().score > -1e-2

    def test_conflicting_direction_raises(self):
        gen = GeneticSearchCandidateGenerator(self.SPACE, minimize=True)
        runner = LocalOptimizationRunner(OptimizationConfiguration(
            candidate_generator=gen, score_function=self._score,
            termination_conditions=[MaxCandidatesCondition(5)],
            minimize=False))
        with pytest.raises(ValueError, match="conflicts"):
            runner.execute()

    def test_prewarmed_generator_resumes(self):
        """A generator handed to a SECOND runner keeps its population:
        the runner reports against the generator's own indices, so
        feedback still lands after the counters diverge."""
        gen = GeneticSearchCandidateGenerator(self.SPACE,
                                              population_size=8, seed=2)
        mk = lambda n: OptimizationConfiguration(
            candidate_generator=gen, score_function=self._score,
            termination_conditions=[MaxCandidatesCondition(n)])
        LocalOptimizationRunner(mk(40)).execute()
        pool_before = len(gen._scored)
        r2 = LocalOptimizationRunner(mk(40))
        r2.execute()
        assert pool_before > 0 and len(gen._scored) > 0
        assert not gen._pending          # every report landed
        assert r2.bestResult().score < 1e-2

    def test_failed_candidates_leave_gene_pool(self):
        gen = GeneticSearchCandidateGenerator(self.SPACE,
                                              population_size=4, seed=0)
        calls = {"n": 0}

        def flaky(c):
            calls["n"] += 1
            if calls["n"] % 3 == 0:
                raise RuntimeError("boom")
            return self._score(c)

        runner = LocalOptimizationRunner(OptimizationConfiguration(
            candidate_generator=gen, score_function=flaky,
            termination_conditions=[MaxCandidatesCondition(30)]))
        runner.execute()
        assert runner.numCandidatesFailed() == 10
        assert runner.numCandidatesCompleted() == 30
        # every report landed; failed genomes never entered the pool
        # (the pool is culled to population_size during breeding)
        assert not gen._pending
        assert 0 < len(gen._scored)


class TestRunner:
    def test_finds_minimum(self):
        space = {"x": ContinuousParameterSpace(-2.0, 2.0)}
        conf = OptimizationConfiguration(
            candidate_generator=RandomSearchGenerator(space, seed=0),
            score_function=lambda c: (c["x"] - 0.7) ** 2,
            termination_conditions=[MaxCandidatesCondition(60)])
        runner = LocalOptimizationRunner(conf)
        runner.execute()
        best = runner.bestResult()
        assert runner.numCandidatesCompleted() == 60
        assert abs(best.candidate["x"] - 0.7) < 0.2

    def test_failures_recorded(self):
        def score(c):
            if c["x"] > 0.5:
                raise RuntimeError("boom")
            return c["x"]
        conf = OptimizationConfiguration(
            candidate_generator=RandomSearchGenerator(
                {"x": ContinuousParameterSpace(0, 1)}, seed=1),
            score_function=score,
            termination_conditions=[MaxCandidatesCondition(20)])
        runner = LocalOptimizationRunner(conf)
        runner.execute()
        assert runner.numCandidatesFailed() > 0
        assert runner.bestResult().score <= 0.5

    def test_time_termination(self):
        import time
        conf = OptimizationConfiguration(
            candidate_generator=RandomSearchGenerator(
                {"x": ContinuousParameterSpace(0, 1)}, seed=2),
            score_function=lambda c: time.sleep(0.05) or c["x"],
            termination_conditions=[MaxTimeCondition(0.2)])
        runner = LocalOptimizationRunner(conf)
        runner.execute()
        assert 1 <= runner.numCandidatesCompleted() <= 10

    def test_model_search_end_to_end(self):
        """Search lr/width on a tiny real training task."""
        from deeplearning4j_tpu.learning.updaters import Adam
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork

        rs = np.random.RandomState(0)
        x = rs.randn(64, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]

        def score(cand):
            conf = (NeuralNetConfiguration.builder().seed(7)
                    .updater(Adam(cand["lr"])).list()
                    .layer(DenseLayer(n_out=cand["width"],
                                      activation="relu"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .setInputType(InputType.feedForward(4)).build())
            net = MultiLayerNetwork(conf).init()
            for _ in range(12):
                net.fit(x, y)
            return net.score()

        conf = OptimizationConfiguration(
            candidate_generator=GridSearchCandidateGenerator(
                {"lr": DiscreteParameterSpace([1e-4, 1e-2]),
                 "width": DiscreteParameterSpace([4, 16])}),
            score_function=score,
            termination_conditions=[MaxCandidatesCondition(4)])
        runner = LocalOptimizationRunner(conf)
        runner.execute()
        best = runner.bestResult()
        assert best is not None
        # the larger lr must beat 1e-4 after 12 iters
        assert best.candidate["lr"] == pytest.approx(1e-2)
