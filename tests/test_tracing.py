"""Per-request distributed tracing + black-box flight recorder
(profiler/tracing.py, profiler/flight_recorder.py).

Covers: trace contexts and the per-request timeline registry, the
serving engine's submit -> queue_wait -> prefill -> decode_burst ->
finish thread-through, the HTTP timeline endpoints, multi-host span
aggregation, the flight-recorder ring + atomic digest-verified
incident dumps, the JSONL loader, and the three incident triggers
(chaos NaN rollback, watchdog stall, SIGTERM preemption) — each dump's
LAST event must be the incident itself, at the failing step.
"""

import json
import os
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.profiler import (
    chaos, flight_recorder, telemetry, tracing,
)
from deeplearning4j_tpu.util import FaultTolerance


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    tracing.reset()
    flight_recorder.reset()
    was = tracing.enabled()
    yield
    tracing.set_enabled(was)
    tracing.reset()
    flight_recorder.reset()
    telemetry.reset()


def make_net(seed: int = 11):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(4)).build())
    return MultiLayerNetwork(conf).init()


def fit_data():
    rs = np.random.RandomState(0)
    x = rs.randn(32, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)]
    return x, y


# =====================================================================
# trace contexts + registries
# =====================================================================
class TestTraceContext:
    def test_disabled_is_none(self):
        tracing.set_enabled(False)
        assert tracing.new_trace("serving_request", request_id=1) is None
        # finishing a None trace is a no-op, not an error
        tracing.finish_trace(None, reason="length")

    def test_events_land_in_chrome_trace_with_identity(self):
        tracing.set_enabled(True)
        ctx = tracing.new_trace("serving_request", request_id=42)
        t0 = time.perf_counter()
        ctx.event("prefill", t0, t0 + 0.001, bucket=16)
        evs = telemetry.chrome_trace()["traceEvents"]
        ev = next(e for e in evs if e["name"] == "prefill")
        assert ev["args"]["trace"] == ctx.trace_id
        assert ev["args"]["request"] == 42
        assert ev["args"]["host"] == tracing.host_id()
        assert ev["args"]["bucket"] == 16

    def test_timeline_live_then_finished(self):
        tracing.set_enabled(True)
        ctx = tracing.new_trace("serving_request", request_id=7)
        with ctx.span("queue_wait"):
            pass
        live = tracing.timeline(7)
        assert live["finish_reason"] is None
        assert [e["name"] for e in live["events"]] == ["queue_wait"]
        tracing.finish_trace(ctx, reason="eos")
        done = tracing.timeline(7)
        assert done["finish_reason"] == "eos"
        assert not any(s["request_id"] == 7
                       for s in tracing.live_summaries())
        assert tracing.timeline("nonexistent") is None

    def test_recent_registry_bounded(self):
        tracing.set_enabled(True)
        for i in range(tracing._RECENT_MAX + 10):
            tracing.finish_trace(
                tracing.new_trace("serving_request", request_id=i),
                reason="length")
        assert tracing.timeline(0) is None          # evicted
        assert tracing.timeline(tracing._RECENT_MAX + 9) is not None

    def test_summary_phase_totals(self):
        tracing.set_enabled(True)
        ctx = tracing.new_trace("serving_request", request_id=3)
        t0 = time.perf_counter()
        ctx.event("queue_wait", t0, t0 + 0.002)
        ctx.event("decode_burst", t0, t0 + 0.004, tokens=4)
        ctx.event("decode_burst", t0, t0 + 0.006, tokens=2)
        tracing.finish_trace(ctx, reason="length")
        s = tracing.recent_summaries()[0]
        assert s["queue_ms"] == pytest.approx(2.0, abs=0.5)
        assert s["decode_ms"] == pytest.approx(10.0, abs=1.0)
        assert s["spans"]["decode_burst"]["count"] == 2

    def test_train_step_trace(self):
        tracing.set_enabled(True)
        t0 = time.perf_counter()
        for i in range(3):
            tracing.record_train_step("mln", i + 1, t0)
        tl = tracing.timeline("train:mln")
        assert [e["iteration"] for e in tl["events"]] == [1, 2, 3]
        assert tl["kind"] == "train"

    def test_train_trace_survives_request_flood(self):
        # a flood of live request traces evicts oldest-first from the
        # bounded live registry; the never-finishing train context is
        # re-inserted newest every step, so it must survive
        tracing.set_enabled(True)
        t0 = time.perf_counter()
        tracing.record_train_step("mln", 1, t0)
        for i in range(tracing._LIVE_MAX + 5):
            tracing.new_trace("serving_request", request_id=10_000 + i)
        tracing.record_train_step("mln", 2, t0)
        assert tracing.timeline("train:mln") is not None


class TestHostAggregation:
    def test_local_spans_aggregate(self):
        with telemetry.span("device_step"):
            time.sleep(0.001)
        with telemetry.span("device_step"):
            pass
        hs = tracing.host_spans()
        assert hs["host"] == tracing.host_id()
        assert hs["spans"]["device_step"]["count"] == 2
        assert hs["spans"]["device_step"]["total_ms"] > 0

    def test_ingest_and_aggregate(self):
        tracing.ingest_host_spans(
            {"host": 5, "spans": {"device_step":
                                  {"count": 9, "total_ms": 123.0}}})
        agg = tracing.aggregate_hosts()
        assert "5" in agg and str(tracing.host_id()) in agg
        assert agg["5"]["spans"]["device_step"]["total_ms"] == 123.0
        # a straggler-host push makes the snapshot non-empty even with
        # local tracing off
        tracing.set_enabled(False)
        assert "5" in tracing.snapshot()["hosts"]

    def test_ingest_rejects_hostless(self):
        with pytest.raises(ValueError):
            tracing.ingest_host_spans({"spans": {}})

    def test_push_spans_http_roundtrip(self):
        from deeplearning4j_tpu.ui.server import UIServer

        ui = UIServer()
        port = ui.start(port=0)
        try:
            with telemetry.span("device_step"):
                pass
            tracing.push_spans(f"http://127.0.0.1:{port}", host=9)
            tel = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/telemetry", timeout=10).read())
            hosts = tel["snapshot"]["tracing"]["hosts"]
            assert "9" in hosts
            assert hosts["9"]["spans"]["device_step"]["count"] >= 1
        finally:
            ui.stop()


# =====================================================================
# flight recorder: ring + dumps + loader
# =====================================================================
class TestFlightRecorder:
    def test_ring_wraps_and_seq_is_monotonic(self):
        r = flight_recorder.FlightRecorder(capacity=4, enabled=True)
        for i in range(10):
            r.record("train_step", iteration=i)
        evs = r.events()
        assert len(evs) == 4
        assert [e["iteration"] for e in evs] == [6, 7, 8, 9]
        assert [e["seq"] for e in evs] == [7, 8, 9, 10]

    def test_disabled_records_nothing(self, tmp_path):
        r = flight_recorder.FlightRecorder(enabled=False,
                                           directory=str(tmp_path))
        r.record("train_step", iteration=1)
        assert r.events() == []
        assert r.incident("boom") is None
        assert list(tmp_path.iterdir()) == []

    def test_incident_dump_round_trips(self, tmp_path):
        r = flight_recorder.FlightRecorder(capacity=8, enabled=True,
                                           directory=str(tmp_path))
        r.record("train_step", iteration=1, dispatch_ms=0.5)
        r.record("serving_admit", request_id=0, slot=2)
        path = r.incident("forced", note="test")
        out = flight_recorder.load_dump(path)
        assert out["valid"]
        assert out["manifest"]["reason"] == "forced"
        assert out["manifest"]["event_count"] == 3
        kinds = [e["kind"] for e in out["events"]]
        assert kinds == ["train_step", "serving_admit", "forced"]
        assert out["events"][-1]["note"] == "test"
        assert out["events"][-1]["seq"] == out["manifest"]["last_seq"]
        assert "traceEvents" in out["trace"]
        assert set(out["requests"]) == {"live", "recent"}
        assert flight_recorder.list_dumps(str(tmp_path)) == [path]
        # counter labelled by reason
        assert telemetry.MetricsRegistry.get_default().counter(
            telemetry.INCIDENT_DUMPS).value(reason="forced") == 1
        # request timelines must survive sanitization as STRUCTURE,
        # not repr strings (the events sit 4-5 levels deep)
        tracing.set_enabled(True)
        ctx = tracing.new_trace("serving_request", request_id=11)
        with ctx.span("prefill", bucket=16):
            pass
        out2 = flight_recorder.load_dump(r.incident("forced2"))
        live = {t["request_id"]: t for t in out2["requests"]["live"]}
        ev = live[11]["events"][0]
        assert isinstance(ev, dict) and ev["name"] == "prefill"
        assert ev["bucket"] == 16

    def test_tampered_dump_is_invalid(self, tmp_path):
        r = flight_recorder.FlightRecorder(enabled=True,
                                           directory=str(tmp_path))
        r.record("train_step", iteration=1)
        path = r.incident("forced")
        with open(os.path.join(path, "events.jsonl"), "a") as f:
            f.write('{"seq": 999, "kind": "forged"}\n')
        assert not flight_recorder.load_dump(path)["valid"]

    def test_sanitize_non_finite_and_arrays(self, tmp_path):
        r = flight_recorder.FlightRecorder(enabled=True,
                                           directory=str(tmp_path))
        r.record("train_loss", loss=float("nan"),
                 spike=float("inf"), norm=np.float32(2.5),
                 n=np.int64(3))
        out = flight_recorder.load_dump(r.incident("forced"))
        assert out["valid"]
        ev = out["events"][0]
        assert ev["loss"] == "nan" and ev["spike"] == "inf"
        assert ev["norm"] == 2.5 and ev["n"] == 3

    def test_incident_terminal_event_is_atomic_with_snapshot(self,
                                                             tmp_path):
        """Events recorded AFTER the incident snapshot must not appear
        in the dump — the last dumped event is always the incident."""
        import threading

        r = flight_recorder.FlightRecorder(enabled=True,
                                           directory=str(tmp_path))
        stop = threading.Event()

        def noisy():
            i = 0
            while not stop.is_set():
                r.record("serving_burst", i=i)
                i += 1

        t = threading.Thread(target=noisy, daemon=True)
        t.start()
        try:
            for _ in range(5):
                out = flight_recorder.load_dump(r.incident("probe"))
                assert out["valid"]
                assert out["events"][-1]["kind"] == "probe"
        finally:
            stop.set()
            t.join()

    def test_configure_default_instance(self, tmp_path):
        flight_recorder.configure(directory=str(tmp_path), capacity=6)
        for i in range(9):
            flight_recorder.record("x", i=i)
        r = flight_recorder.get_default()
        assert len(r.events()) == 6
        path = flight_recorder.incident("forced")
        assert path.startswith(str(tmp_path))
        snap = flight_recorder.snapshot()
        assert snap["last_incident"] == path
        assert snap["incidents"][0]["reason"] == "forced"

    def test_excepthook_dumps(self, tmp_path):
        import sys

        flight_recorder.configure(directory=str(tmp_path))
        flight_recorder.record("train_step", iteration=1)
        prev = sys.excepthook
        try:
            flight_recorder.install_excepthook()
            try:
                raise RuntimeError("synthetic crash")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
        finally:
            sys.excepthook = prev
            flight_recorder._hook_installed = False
        dumps = flight_recorder.list_dumps(str(tmp_path))
        assert len(dumps) == 1
        out = flight_recorder.load_dump(dumps[0])
        assert out["events"][-1]["kind"] == "unhandled_exception"
        assert "synthetic crash" in out["events"][-1]["error"]


# =====================================================================
# incident triggers end to end (chaos-injected)
# =====================================================================
class TestIncidentTriggers:
    def test_nan_rollback_dumps_with_failing_step_last(self, tmp_path):
        x, y = fit_data()
        net = make_net()
        ft = FaultTolerance(checkpoint_dir=str(tmp_path),
                            divergence_window=4, snapshot_every=1)
        with chaos.installed(chaos.ChaosConfig(nan_steps=(2,))):
            net.fit(ArrayDataSetIterator(x, y, 8), epochs=2,
                    fault_tolerance=ft)
        dumps = flight_recorder.list_dumps(
            os.path.join(str(tmp_path), "incidents"))
        assert len(dumps) == 1
        out = flight_recorder.load_dump(dumps[0])
        assert out["valid"]
        last = out["events"][-1]
        assert last["kind"] == "divergence_rollback"
        # NaN batch at ordinal 2 fails the 3rd step -> iteration 3
        assert last["iteration"] == 3
        assert last["why"].startswith("non-finite loss")
        # the black box holds the path INTO the incident: per-step
        # events and the non-finite loss itself (stringified NaN)
        assert any(e["kind"] == "train_step" for e in out["events"])
        bad = [e for e in out["events"] if e["kind"] == "train_loss"
               and e["iteration"] == 3]
        assert bad and bad[-1]["loss"] == "nan"

    def test_watchdog_stall_dumps(self, tmp_path):
        x, y = fit_data()
        net = make_net()
        # 20ms deadline: the first step's jit compile always exceeds it
        ft = FaultTolerance(checkpoint_dir=str(tmp_path),
                            divergence_window=0, step_deadline=0.02)
        net.fit(ArrayDataSetIterator(x, y, 8), epochs=1,
                fault_tolerance=ft)
        deadline = time.time() + 10
        dumps = []
        while not dumps and time.time() < deadline:
            dumps = flight_recorder.list_dumps(
                os.path.join(str(tmp_path), "incidents"))
            time.sleep(0.05)
        assert dumps, "watchdog stall produced no incident dump"
        # the first stall is the first step (jit compile >> deadline);
        # slow CI machines may stall later steps too — find step 0
        stalls = []
        for p in dumps:
            out = flight_recorder.load_dump(p)
            assert out["valid"]
            stalls.extend(e for e in out["events"]
                          if e["kind"] == "watchdog_stall")
        assert any(e["step"] == 0 for e in stalls), stalls
        assert all(e["context"] == "train_step" for e in stalls)
        assert telemetry.MetricsRegistry.get_default().counter(
            telemetry.WATCHDOG_STALLS).total() >= 1

    def test_sigterm_preemption_dumps(self, tmp_path):
        from deeplearning4j_tpu.util.resilience import (
            latest_valid_bundle,
        )

        x, y = fit_data()
        net = make_net()
        ft = FaultTolerance(checkpoint_dir=str(tmp_path),
                            divergence_window=0)
        with chaos.installed(chaos.ChaosConfig(preempt_at_step=3)):
            net.fit(ArrayDataSetIterator(x, y, 8), epochs=3,
                    fault_tolerance=ft)
        assert latest_valid_bundle(str(tmp_path)) is not None
        dumps = flight_recorder.list_dumps(
            os.path.join(str(tmp_path), "incidents"))
        assert len(dumps) == 1
        out = flight_recorder.load_dump(dumps[0])
        assert out["valid"]
        last = out["events"][-1]
        assert last["kind"] == "preemption_checkpoint"
        assert last["iteration"] == 3       # preempted after step 3
        assert "bundle-" in last["bundle"]

    def test_flight_dir_knob_overrides(self, tmp_path):
        ft = FaultTolerance(checkpoint_dir="/ckpt",
                            flight_dir=str(tmp_path / "fl"))
        assert ft.incident_dir() == str(tmp_path / "fl")
        assert FaultTolerance(checkpoint_dir="/ckpt").incident_dir() \
            == os.path.join("/ckpt", "incidents")
        assert FaultTolerance().incident_dir() is None


# =====================================================================
# serving engine thread-through + HTTP endpoints
# =====================================================================
@pytest.fixture(scope="module")
def gpt():
    from deeplearning4j_tpu.models.gpt import CausalLM
    from deeplearning4j_tpu.models.transformer import tiny_config

    cfg = tiny_config(vocab=17, max_len=48, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    m = CausalLM(cfg, compute_dtype=jnp.float32)
    return m, m.init_params(jax.random.key(1))


class TestServingTracing:
    def test_request_timeline_spans(self, gpt):
        from deeplearning4j_tpu.serving import DecodeEngine

        tracing.set_enabled(True)
        m, params = gpt
        with DecodeEngine(m, params, slots=2, page_size=8) as eng:
            reqs = [eng.submit(np.arange(1, 4 + i, dtype=np.int32),
                               3 + i) for i in range(3)]
            for r in reqs:
                r.result(timeout=60)
        for r in reqs:
            assert r.trace_id is not None
            tl = tracing.timeline(r.request_id)
            names = [e["name"] for e in tl["events"]]
            assert names[0] == "queue_wait"
            assert names[1] == "prefill"
            assert "decode_burst" in names
            assert names[-1] == "finish"
            assert tl["finish_reason"] == "length"
            assert tl["attrs"]["prompt_tokens"] == r.prompt.size
            decoded = sum(e.get("tokens", 0) for e in tl["events"]
                          if e["name"] == "decode_burst")
            # bursts decode every slot lane; this request EMITTED
            # max_new_tokens - 1 of them after the prefill-sampled first
            assert decoded >= r.max_new_tokens - 1
        # scheduler decisions landed in the black box
        kinds = {e["kind"] for e in flight_recorder.get_default().events()}
        assert {"serving_submit", "serving_admit", "serving_burst",
                "serving_evict"} <= kinds

    def test_stats_and_responses_carry_request_id(self, gpt):
        from deeplearning4j_tpu.remote.server import JsonModelServer
        from deeplearning4j_tpu.serving import DecodeEngine

        tracing.set_enabled(True)
        m, params = gpt
        with DecodeEngine(m, params, slots=2, page_size=8) as eng:
            srv = JsonModelServer(engine=eng)
            port = srv.start()
            try:
                body = json.dumps({"prompt_ids": [1, 2, 3],
                                   "max_new_tokens": 4}).encode()
                rq = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/serving/generate",
                    data=body,
                    headers={"Content-Type": "application/json"})
                out = json.loads(
                    urllib.request.urlopen(rq, timeout=60).read())
                assert out["finish_reason"] == "length"
                rid = out["request_id"]
                assert isinstance(rid, int)
                assert out["trace_id"]
                # stats join: request_id + finish reason
                st = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/serving/stats",
                    timeout=10).read())
                rec = st["recent_requests"][0]
                assert rec["request_id"] == rid
                assert rec["finish_reason"] == "length"
                assert rec["latency_ms"] > 0
                # one request's timeline over HTTP
                tl = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/serving/requests/{rid}",
                    timeout=10).read())
                assert tl["request_id"] == rid
                assert {e["name"] for e in tl["events"]} >= \
                    {"queue_wait", "prefill", "finish"}
                # the listing includes it too
                lst = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/serving/requests",
                    timeout=10).read())
                assert any(s["request_id"] == rid
                           for s in lst["recent"])
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}"
                        "/v1/serving/requests/424242", timeout=10)
                assert ei.value.code == 404
            finally:
                srv.stop()

    def test_request_ids_unique_across_engines(self, gpt):
        # the trace registries and HTTP lookups key on request_id —
        # two engines in one process must not both mint id N
        from deeplearning4j_tpu.serving import DecodeEngine

        tracing.set_enabled(True)
        m, params = gpt
        with DecodeEngine(m, params, slots=2, page_size=8) as a, \
                DecodeEngine(m, params, slots=2, page_size=8) as b:
            ra = a.submit(np.arange(1, 5, dtype=np.int32), 2)
            rb = b.submit(np.arange(1, 5, dtype=np.int32), 2)
            ra.result(60)
            rb.result(60)
        assert ra.request_id != rb.request_id
        assert tracing.timeline(ra.request_id)["trace_id"] == ra.trace_id
        assert tracing.timeline(rb.request_id)["trace_id"] == rb.trace_id

    def test_tracing_off_is_token_identical_and_unlisted(self, gpt):
        from deeplearning4j_tpu.serving import DecodeEngine

        m, params = gpt
        prompts = [np.arange(2, 9, dtype=np.int32),
                   np.arange(1, 5, dtype=np.int32)]

        def run():
            with DecodeEngine(m, params, slots=2, page_size=8) as eng:
                rs = [eng.submit(p, 5) for p in prompts]
                return [r.result(timeout=60) for r in rs], rs

        tracing.set_enabled(False)
        off, off_reqs = run()
        assert all(r.trace_id is None for r in off_reqs)
        assert all(tracing.timeline(r.request_id) is None
                   for r in off_reqs)
        tracing.set_enabled(True)
        on, _ = run()
        for a, b in zip(off, on):
            assert np.array_equal(a, b)
