"""Serving fleet (serving/fleet.py): replicated decode engines behind
one KV-aware router with disaggregated prefill — token-identity vs a
solo engine (N=1 and N=2, lane on and off), session affinity,
kill-a-replica failover with exact replay, drain/restart elastic
resize, shared AOT warm pools, capacity 429s, and the HTTP front-end's
routing fields."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.gpt import CausalLM
from deeplearning4j_tpu.models.transformer import tiny_config
from deeplearning4j_tpu.profiler import (
    chaos, flight_recorder, telemetry, tracing,
)
from deeplearning4j_tpu.serving import (
    CapacityRejected, DecodeEngine, ServingFleet,
)

VOCAB = 17


def _model():
    cfg = tiny_config(vocab=VOCAB, max_len=64, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    return CausalLM(cfg, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return _model()


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(jax.random.key(1))


def _solo(model, params, prompt, new):
    return np.asarray(model.generate(
        params, jnp.asarray(np.asarray(prompt)[None, :], jnp.int32),
        new))[0]


def _fleet(model, params, **kw):
    """Fleet with a slimmed AOT surface (3 prefill buckets, short
    chunk ladder) so each test's startup stays ~1s — the full bucket
    ladder is the CI fleet smoke gate's job."""
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", [8, 16, 40])
    kw.setdefault("max_chunk", 4)
    return ServingFleet(model, params, **kw)


def _mixed_specs(n, rng, long_every=3):
    specs = []
    for i in range(n):
        t0 = (int(rng.integers(20, 40)) if long_every and
              i % long_every == 0 else int(rng.integers(3, 12)))
        specs.append((rng.integers(0, VOCAB, (t0,)).astype(np.int32),
                      int(rng.integers(2, 10))))
    return specs


# ----------------------------------------------------- token identity
class TestFleetParity:
    @pytest.mark.slow
    def test_single_replica_no_disagg_identical_to_solo(self, model,
                                                        params):
        """Acceptance: a fleet of N=1 with disaggregation off is
        greedy token-identical to a solo engine (and to generate())."""
        rng = np.random.default_rng(0)
        specs = _mixed_specs(4, rng, long_every=0)
        with _fleet(model, params, replicas=1) as fl:
            outs = [fl.submit(p, n).result(120) for p, n in specs]
        for (p, n), got in zip(specs, outs):
            np.testing.assert_array_equal(got,
                                          _solo(model, params, p, n))

    @pytest.mark.slow
    def test_two_replicas_with_lane_identical_to_solo(self, model,
                                                      params):
        """Concurrent mixed-length traffic over 2 replicas + the
        disaggregated prefill lane stays token-identical: the lane's
        prefill is the same forward at the same bucket padding, and
        the adopt scatter commits the same bytes."""
        rng = np.random.default_rng(1)
        specs = _mixed_specs(12, rng)
        with _fleet(model, params, replicas=2, prefill_threshold=16,
                    prefix_cache=True) as fl:
            with ThreadPoolExecutor(max_workers=8) as ex:
                hs = list(ex.map(lambda pn: fl.submit(pn[0], pn[1]),
                                 specs))
            outs = [h.result(timeout=300) for h in hs]
            lane = fl._lane.stats()
        assert lane["prefills"] >= 1, "no prompt took the lane"
        for (p, n), got in zip(specs, outs):
            np.testing.assert_array_equal(got,
                                          _solo(model, params, p, n))

    def test_shared_aot_zero_compiles_for_second_replica(self, model,
                                                         params):
        reg = telemetry.MetricsRegistry.get_default()
        compiles = reg.counter(telemetry.JIT_COMPILES)

        def site_total():
            return sum(compiles.value(site=s) for s in
                       ("serving_decode", "serving_prefill",
                        "serving_adopt", "serving_lane_prefill",
                        "serving_prefix_prefill", "serving_cow_copy"))

        fl = _fleet(model, params, replicas=2, prefill_threshold=16)
        fl.start()
        try:
            before = site_total()
            st = fl.stats()
            # replica 1 adopted replica 0's executables wholesale
            assert st["replicas"][1]["warm_pool"]["adopted"] > 0
            assert st["replicas"][0]["warm_pool"]["adopted"] == 0
            rng = np.random.default_rng(2)
            hs = [fl.submit(rng.integers(0, VOCAB, (t0,)).astype(
                np.int32), 3) for t0 in (5, 25, 9, 30)]
            for h in hs:
                h.result(120)
            assert site_total() == before, \
                "post-startup request paid a serving-site compile"
        finally:
            fl.shutdown()


# -------------------------------------------------- routing + affinity
class TestRouting:
    def test_session_affinity_routes_back_warm(self, model, params):
        rng = np.random.default_rng(3)
        with _fleet(model, params, replicas=2, prefix_cache=True,
                    session_capacity=4) as fl:
            t1 = rng.integers(0, VOCAB, (9,)).astype(np.int32)
            r1 = fl.submit(t1, 5, session_id="conv")
            o1 = r1.result(60)
            t2 = np.concatenate(
                [t1, o1, rng.integers(0, VOCAB, (3,)).astype(np.int32)])
            r2 = fl.submit(t2, 5, session_id="conv")
            o2 = r2.result(60)
            assert r2.routing["reason"] == "affinity"
            assert r2.routing["replica"] == r1.routing["replica"]
            assert r2.cache_hit_tokens == t1.size + o1.size - 1
            np.testing.assert_array_equal(
                o2, _solo(model, params, t2, 5))

    def test_prefix_locality_prefers_warm_replica(self, model, params):
        """The KV-aware score: a prompt whose prefix pages live on
        replica k routes to k (hit hint beats raw free capacity)."""
        rng = np.random.default_rng(4)
        sys_p = rng.integers(0, VOCAB, (24,)).astype(np.int32)
        with _fleet(model, params, replicas=2,
                    prefix_cache=True) as fl:
            first = fl.submit(np.concatenate(
                [sys_p, rng.integers(0, VOCAB, (4,)).astype(np.int32)]),
                4)
            first.result(60)
            warm_rep = first.routing["replica"]
            hits = 0
            for _ in range(4):
                r = fl.submit(np.concatenate(
                    [sys_p,
                     rng.integers(0, VOCAB, (4,)).astype(np.int32)]), 4)
                r.result(60)
                hits += (r.routing["replica"] == warm_rep
                         and r.cache_hit_tokens >= 16)
            assert hits == 4, f"only {hits}/4 warm-routed"

# --------------------------------------------------- failure + resize
class TestFailover:
    def test_kill_replica_replays_exactly_and_sessions_readmit_cold(
            self, model, params):
        rng = np.random.default_rng(5)
        fl = _fleet(model, params, replicas=2, prefix_cache=True,
                    session_capacity=4)
        fl.start()
        try:
            t1 = rng.integers(0, VOCAB, (8,)).astype(np.int32)
            s1 = fl.submit(t1, 4, session_id="conv")
            s1.result(60)
            doomed = s1.routing["replica"]
            idx = next(i for i, r in enumerate(fl._replicas)
                       if r.engine.engine_id == doomed)
            # a long request pinned to the doomed replica via affinity,
            # plus bystanders spread across the fleet
            long_p = rng.integers(0, VOCAB, (4,)).astype(np.int32)
            victim = fl.submit(long_p, 56, session_id="conv2")
            others = [fl.submit(
                rng.integers(0, VOCAB, (6,)).astype(np.int32), 8)
                for _ in range(4)]
            deadline = time.time() + 30
            while not victim.tokens and time.time() < deadline:
                time.sleep(0.0002)
            assert victim.tokens, "victim never started"
            # stall the doomed scheduler before the kill: a fully warm
            # compile cache can otherwise finish the victim between
            # the progress poll and the kill, leaving nothing in
            # flight to re-route (at most the pass already executing
            # slips through the stall)
            chaos.hang_replica(fl._replicas[idx].engine, 2.0)
            fl.kill_replica(idx)
            got = victim.result(timeout=120)
            np.testing.assert_array_equal(
                got, _solo(model, params, long_p, 56))
            for o in others:
                o.result(timeout=120)
            assert fl.alive_replicas() == 1
            # flight recorder saw the death and the re-route
            kinds = [e["kind"]
                     for e in flight_recorder.get_default().events()]
            assert "fleet_replica_dead" in kinds
            assert "fleet_reroute" in kinds
            # the session pinned on the dead replica re-admits cold
            o1 = np.asarray(s1.tokens, np.int32)
            t2 = np.concatenate(
                [t1, o1, rng.integers(0, VOCAB, (2,)).astype(np.int32)])
            r2 = fl.submit(t2, 4, session_id="conv")
            o2 = r2.result(60)
            assert r2.routing["replica"] != doomed
            np.testing.assert_array_equal(
                o2, _solo(model, params, t2, 4))
        finally:
            fl.shutdown()
        survivors = [r for r in fl._replicas if r.engine.pool]
        for r in survivors:
            assert r.engine.pool.allocated == 0

    @pytest.mark.slow
    def test_drain_then_restart_replica(self, model, params):
        rng = np.random.default_rng(6)
        with _fleet(model, params, replicas=2) as fl:
            fl.generate(rng.integers(0, VOCAB, (5,)).astype(np.int32),
                        3)
            assert fl.drain_replica(1)
            assert fl.alive_replicas() == 1
            p = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            np.testing.assert_array_equal(
                fl.generate(p, 4), _solo(model, params, p, 4))
            fl.restart_replica(1)
            assert fl.alive_replicas() == 2
            # restarted replica adopts a live donor's warm pool
            assert fl._replicas[1].engine._warm.adopted > 0
            p2 = rng.integers(0, VOCAB, (7,)).astype(np.int32)
            np.testing.assert_array_equal(
                fl.generate(p2, 4), _solo(model, params, p2, 4))

    @pytest.mark.slow
    def test_drain_with_inflight_session_pinned_requests(
            self, model, params):
        """Elastic-resize coverage the kill path doesn't give:
        draining a replica that holds session-PINNED pages while a
        session request is still decoding there. The in-flight request
        must finish (drain waits), the drained pool must reach 0
        allocated pages (pins released with the shutdown), and the
        session's next turn must cold-restart cleanly on the survivor
        with token-identical output."""
        rng = np.random.default_rng(11)
        with _fleet(model, params, replicas=2, prefix_cache=True,
                    session_capacity=4) as fl:
            t1 = rng.integers(0, VOCAB, (9,)).astype(np.int32)
            r1 = fl.submit(t1, 4, session_id="pin")
            o1 = r1.result(60)
            target = r1.routing["replica"]
            idx = next(i for i, r in enumerate(fl._replicas)
                       if r.engine.engine_id == target)
            eng = fl._replicas[idx].engine
            assert eng._sessions.stats()["sessions"] == 1
            # turn 2 of the same session decodes ON the pinned replica
            # (affinity) while the drain starts — it re-pins mid-drain
            t2 = np.concatenate(
                [t1, o1, rng.integers(0, VOCAB, (2,)).astype(np.int32)])
            r2 = fl.submit(t2, 24, session_id="pin")
            deadline = time.time() + 30
            while len(r2.tokens) < 2 and time.time() < deadline:
                time.sleep(0.005)
            assert r2.routing["replica"] == target
            assert fl.drain_replica(idx, timeout=120)
            # the in-flight session request FINISHED during the drain
            o2 = r2.result(10)
            np.testing.assert_array_equal(
                o2, _solo(model, params, t2, 24))
            # pins released, pool fully drained on the dead replica
            assert eng.pool.allocated == 0
            assert eng.pool.shared_pages() == 0
            # next turn cold-restarts on the survivor, token-identical
            t3 = np.concatenate(
                [t2, o2, rng.integers(0, VOCAB, (2,)).astype(np.int32)])
            r3 = fl.submit(t3, 4, session_id="pin")
            o3 = r3.result(60)
            assert r3.routing["replica"] != target
            assert r3.cache_hit_tokens == 0        # cold re-admit
            np.testing.assert_array_equal(
                o3, _solo(model, params, t3, 4))
            # ...and RE-pins on the survivor: turn 4 is warm again
            t4 = np.concatenate(
                [t3, o3, rng.integers(0, VOCAB, (2,)).astype(np.int32)])
            r4 = fl.submit(t4, 4, session_id="pin")
            o4 = r4.result(60)
            assert r4.routing["replica"] == r3.routing["replica"]
            assert r4.cache_hit_tokens > 0
            np.testing.assert_array_equal(
                o4, _solo(model, params, t4, 4))


# ------------------------------------------------------ capacity 429s
class TestCapacity:
    def test_engine_full_queue_raises_structured_reject(self, model,
                                                        params):
        eng = DecodeEngine(model, params, slots=1, page_size=8,
                           max_queue=1, warm_start=False)
        eng.start()
        try:
            held = [eng.submit(np.asarray([1, 2], np.int32), 30,
                               eos_id=VOCAB)]
            deadline = time.time() + 30
            while not eng._active.any() and time.time() < deadline:
                time.sleep(0.002)
            held.append(eng.submit(np.asarray([1, 2], np.int32), 4))
            with pytest.raises(CapacityRejected) as ei:
                for _ in range(4):   # queue depth 1: must trip now
                    held.append(
                        eng.submit(np.asarray([1, 2], np.int32), 4))
            assert ei.value.retry_after_s > 0
            reg = telemetry.MetricsRegistry.get_default()
            assert reg.counter(telemetry.SERVING_REJECTS).value(
                engine=eng.engine_id) >= 1
        finally:
            eng.shutdown()

    def test_fleet_full_queue_raises_structured_reject(self, model,
                                                       params):
        fl = ServingFleet(model, params, replicas=1, slots=1,
                          page_size=8, max_queue=1, warm_start=False)
        # never started: the router drains nothing, so the 2nd+3rd
        # submissions must overflow the fleet queue deterministically
        fl._router = threading.Thread(target=lambda: None)  # inert
        try:
            fl.submit(np.asarray([1, 2], np.int32), 4)
            with pytest.raises(CapacityRejected) as ei:
                fl.submit(np.asarray([1, 2], np.int32), 4)
                fl.submit(np.asarray([1, 2], np.int32), 4)
            assert ei.value.retry_after_s > 0
        finally:
            fl._stop.set()
            for r in fl._replicas:
                r.engine.shutdown()

    @pytest.mark.slow
    def test_http_429_and_client_backoff_retry(self, model, params):
        """HTTP front-end answers the reject with a structured 429 +
        Retry-After; JsonRemoteInference retries with backoff and
        succeeds once capacity frees."""
        import json
        import urllib.error
        import urllib.request

        from deeplearning4j_tpu.remote.server import (
            JsonModelServer, JsonRemoteInference,
        )

        eng = DecodeEngine(model, params, slots=1, page_size=8,
                           max_queue=1, prefill_buckets=[8],
                           max_chunk=2)
        srv = JsonModelServer(engine=eng)
        port = srv.start()
        eng.start()
        try:
            blocker = eng.submit(np.asarray([1, 2], np.int32), 40,
                                 eos_id=VOCAB)
            deadline = time.time() + 30
            while not eng._active.any() and time.time() < deadline:
                time.sleep(0.002)
            filler = eng.submit(np.asarray([3, 4], np.int32), 2)
            # raw request: structured 429 with Retry-After header
            body = json.dumps({"prompt_ids": [1, 2],
                               "max_new_tokens": 2}).encode()
            got429 = None
            for _ in range(6):
                try:
                    urllib.request.urlopen(urllib.request.Request(
                        f"http://127.0.0.1:{port}/v1/serving/generate",
                        data=body,
                        headers={"Content-Type": "application/json"}),
                        timeout=30).read()
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        got429 = e
                        break
                    raise
            assert got429 is not None, "queue never filled to a 429"
            assert float(got429.headers["Retry-After"]) > 0
            payload = json.loads(got429.read())
            assert payload["retry_after_s"] > 0
            # retrying client: blocker/filler drain within its backoff
            # budget, so generate() succeeds instead of raising
            cli = JsonRemoteInference(f"http://127.0.0.1:{port}",
                                      retries=8, max_backoff_s=0.5)
            out = cli.generate(np.asarray([5, 6], np.int32), 3)
            np.testing.assert_array_equal(
                out, _solo(model, params,
                           np.asarray([5, 6], np.int32), 3))
            blocker.result(120)
            filler.result(120)
        finally:
            srv.stop()
            eng.shutdown()


# ------------------------------------------------------ HTTP fleet
class TestHttpFleet:
    @pytest.mark.slow
    def test_server_over_fleet_routing_fields_and_stats(self, model,
                                                        params):
        import json
        import urllib.request

        from deeplearning4j_tpu.remote.server import (
            JsonModelServer, JsonRemoteInference,
        )

        was = tracing.enabled()
        tracing.set_enabled(True)
        fl = _fleet(model, params, replicas=2, prefill_threshold=16)
        srv = JsonModelServer(engine=fl)
        port = srv.start()
        try:
            cli = JsonRemoteInference(f"http://127.0.0.1:{port}")
            p = np.arange(24, dtype=np.int32) % VOCAB   # lane-long
            out = cli.generate_full(p, 4)
            np.testing.assert_array_equal(
                np.asarray(out["tokens"], np.int32),
                _solo(model, params, p, 4))
            assert out["engine"] is not None
            assert out["routing"]["replica"] == out["engine"]
            assert out["routing"]["lane"] is True
            assert out["routing"]["attempts"] == 1
            # per-replica tags visible in the request traces
            tl = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/serving/requests/"
                f"{out['request_id']}", timeout=10).read())
            assert tl["attrs"]["engine"] == out["engine"]
            summaries = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/serving/requests",
                timeout=10).read())
            mine = next(s for s in summaries["recent"]
                        if s["request_id"] == out["request_id"])
            assert mine["engine"] == out["engine"]
            assert mine["lane_prefill_ms"] >= 0
            st = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/serving/stats",
                timeout=10).read())
            assert st["fleet"] and st["alive_replicas"] == 2
            info = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/serving/info",
                timeout=10).read())
            assert info["engine"]["slots"] == 4
        finally:
            srv.stop()
            fl.shutdown()
            tracing.set_enabled(was)
            tracing.reset()


# --------------------------------------------- GenerativeInference
class TestGenerativeInferenceFleet:
    @pytest.mark.slow
    def test_wrapper_builds_fleet_and_serves(self, model, params):
        from deeplearning4j_tpu.parallel.wrapper import (
            GenerativeInference,
        )

        p = np.asarray([2, 4, 6], np.int32)
        with GenerativeInference(model, params, replicas=2, slots=2,
                                 page_size=8) as gi:
            from deeplearning4j_tpu.serving.fleet import ServingFleet
            assert isinstance(gi.engine, ServingFleet)
            out = gi.output(p, 5)
            assert gi.n_requests == 1
            assert gi.n_dispatches >= 1
            assert gi.stats()["alive_replicas"] == 2
        np.testing.assert_array_equal(out, _solo(model, params, p, 5))


# -------------------------------------------------- fleet telemetry
class TestFleetTelemetry:
    @pytest.mark.slow
    def test_fleet_counters_and_snapshot(self, model, params):
        reg = telemetry.MetricsRegistry.get_default()
        with _fleet(model, params, replicas=2,
                    prefill_threshold=16) as fl:
            eids = [r.engine.engine_id for r in fl._replicas]
            rng = np.random.default_rng(7)
            for t0 in (5, 25, 7, 30):
                fl.generate(
                    rng.integers(0, VOCAB, (t0,)).astype(np.int32), 3)
            assert reg.gauge(
                telemetry.SERVING_FLEET_REPLICAS).value() == 2
            routed = reg.counter(telemetry.SERVING_FLEET_ROUTED)
            assert sum(routed.value(reason="score", engine=e)
                       for e in eids) >= 4
            assert reg.counter(
                telemetry.SERVING_LANE_PREFILLS).total() >= 2
            st = fl.stats()
            assert st["fleet"] and len(st["replicas"]) == 2
            assert st["alive_replicas"] == 2
            assert st["slots"] == 4
            for k in ("page_size", "max_context", "quantization",
                      "prefill_buckets"):
                assert k in st, k
            assert st["router"]["routed"].get("score", 0) >= 4
            assert st["prefill_lane"]["threshold"] == 16
            ps = fl.prefix_stats()
            assert ps["fleet"] and len(ps["replicas"]) == 2
        snap = telemetry.serving_snapshot()
        for key in ("fleet_routed", "fleet_live_replicas",
                    "lane_prefills", "handoff_seconds"):
            assert key in snap, key

    @pytest.mark.slow
    def test_killed_replica_series_retired(self, model, params):
        """Stale-series regression (PR 14): a killed replica's
        engine-labelled GAUGE series must disappear (no ghost engine
        frozen at its last reading for serving_snapshot(), /metrics,
        or SLO rules to evaluate) while the fleet's cumulative
        aggregates keep its history."""
        reg = telemetry.MetricsRegistry.get_default()
        rng = np.random.default_rng(3)
        with _fleet(model, params, replicas=2) as fl:
            for _ in range(4):
                fl.generate(rng.integers(0, VOCAB, (6,)).astype(
                    np.int32), 3)
            agg_before = telemetry.serving_snapshot()[
                "aggregate"]["requests_total"]
            victim = fl._replicas[0]
            vid = victim.engine.engine_id
            # the victim served traffic: its gauges exist pre-kill
            assert any(dict(k).get("engine") == vid for k in reg.gauge(
                telemetry.SERVING_KV_PAGE_UTILIZATION).values())
            fl.kill_replica(0)
            deadline = time.monotonic() + 10
            while (victim.alive or victim.needs_cleanup) \
                    and time.monotonic() < deadline:
                time.sleep(0.02)     # router health pass cleans up
            assert not victim.alive and not victim.needs_cleanup
            # every gauge series of the dead engine is gone...
            for name in (telemetry.SERVING_KV_PAGE_UTILIZATION,
                         telemetry.SERVING_QUEUE_DEPTH,
                         telemetry.SERVING_SLOT_OCCUPANCY):
                m = reg.peek(name)
                if m is not None:
                    assert not any(
                        dict(k).get("engine") == vid
                        for k in m.values()), name
            # ...and /metrics stops exposing it
            assert f'engine="{vid}"' not in "\n".join(
                line for line in reg.to_prometheus().splitlines()
                if line.startswith(("dl4j_tpu_serving_kv",
                                    "dl4j_tpu_serving_queue_depth",
                                    "dl4j_tpu_serving_slot")))
            snap = telemetry.serving_snapshot()
            assert vid not in snap["engines"]
            # fleet aggregates stay correct: the dead engine's served
            # requests still count
            assert snap["aggregate"]["requests_total"] == agg_before
            # the survivor still serves and its series stay live
            sid = fl._replicas[1].engine.engine_id
            fl.generate(rng.integers(0, VOCAB, (6,)).astype(
                np.int32), 3)
            assert sid in telemetry.serving_snapshot()["engines"]

    @pytest.mark.slow
    def test_fleet_pressure_gauge_published_and_retired(self, model,
                                                        params):
        reg = telemetry.MetricsRegistry.get_default()
        with _fleet(model, params, replicas=1) as fl:
            fl.generate(np.asarray([1, 2, 3], np.int32), 3)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                series = reg.gauge(
                    telemetry.SERVING_FLEET_PRESSURE).values()
                if (("fleet", fl.fleet_id),) in series:
                    break
                time.sleep(0.05)
            assert (("fleet", fl.fleet_id),) in reg.gauge(
                telemetry.SERVING_FLEET_PRESSURE).values()
            fid = fl.fleet_id
        # shutdown retires the fleet's pressure series
        assert (("fleet", fid),) not in reg.gauge(
            telemetry.SERVING_FLEET_PRESSURE).values()


# -------------------------------------------------- runtime elasticity
class TestElasticScale:
    """Phase-3 elasticity: replicas added/removed at RUNTIME on stable
    ids, with warm-pool adoption and token identity preserved."""

    @pytest.mark.slow
    def test_add_replica_adopts_warm_and_stays_token_identical(
            self, model, params):
        """Growing a live 1-replica fleet: the new replica adopts the
        donor's AOT warm pool (same device), registers atomically, and
        traffic across the grown fleet stays token-identical to solo —
        with ZERO post-adopt warm-pool misses."""
        rng = np.random.default_rng(21)
        reg = telemetry.MetricsRegistry.get_default()
        with _fleet(model, params, replicas=1) as fl:
            fl.generate(rng.integers(0, VOCAB, (5,)).astype(np.int32),
                        3)
            rid = fl.add_replica()
            assert rid == 1 and fl.alive_replicas() == 2
            st = fl.stats()
            assert st["pending_scale"] == 0
            assert [r["id"] for r in st["replicas"]] == [0, 1]
            new_eng = fl._by_rid[rid].engine
            assert new_eng._warm.adopted > 0     # same-device adopt
            specs = _mixed_specs(8, rng)
            with ThreadPoolExecutor(max_workers=6) as ex:
                hs = list(ex.map(lambda pn: fl.submit(pn[0], pn[1]),
                                 specs))
            outs = [h.result(timeout=300) for h in hs]
            for (p, n), got in zip(specs, outs):
                np.testing.assert_array_equal(
                    got, _solo(model, params, p, n))
            # the acceptance bar: nothing compiled on the new
            # replica's hot path after adoption
            assert new_eng.stats()["warm_pool"]["misses"] == 0
            assert new_eng.n_dispatches > 0      # it actually served
            # size gauge reflects the grown fleet
            assert reg.gauge(telemetry.SERVING_FLEET_SIZE).values()[
                (("fleet", fl.fleet_id),)] == 2

    @pytest.mark.slow
    def test_remove_replica_with_pinned_sessions(self, model, params):
        """Satellite: scale-down while sessions are PINNED to the
        doomed replica. remove_replica drains it (in-flight requests
        finish), its pool empties, the session's next turn
        cold-restarts on a survivor and RE-pins warm — token output
        never diverges from solo."""
        rng = np.random.default_rng(22)
        with _fleet(model, params, replicas=2, prefix_cache=True,
                    session_capacity=4) as fl:
            t1 = rng.integers(0, VOCAB, (9,)).astype(np.int32)
            r1 = fl.submit(t1, 4, session_id="pin")
            o1 = r1.result(60)
            target = r1.routing["replica"]
            doomed = next(r for r in fl._replicas
                          if r.engine.engine_id == target)
            eng = doomed.engine
            assert eng._sessions.stats()["sessions"] == 1
            assert fl.remove_replica(doomed.rid, timeout=120)
            # identity retired: the old id is gone, not renumbered
            with pytest.raises(IndexError):
                fl.drain_replica(doomed.rid)
            st = fl.stats()
            assert doomed.rid not in [r["id"] for r in st["replicas"]]
            assert eng.pool.allocated == 0       # pins released
            # next session turn cold-restarts on the survivor...
            t2 = np.concatenate(
                [t1, o1, rng.integers(0, VOCAB, (2,)).astype(np.int32)])
            r2 = fl.submit(t2, 4, session_id="pin")
            o2 = r2.result(60)
            assert r2.routing["replica"] != target
            assert r2.cache_hit_tokens == 0
            np.testing.assert_array_equal(
                o2, _solo(model, params, t2, 4))
            # ...and re-pins warm for the turn after
            t3 = np.concatenate(
                [t2, o2, rng.integers(0, VOCAB, (2,)).astype(np.int32)])
            r3 = fl.submit(t3, 4, session_id="pin")
            o3 = r3.result(60)
            assert r3.routing["replica"] == r2.routing["replica"]
            assert r3.cache_hit_tokens > 0
            np.testing.assert_array_equal(
                o3, _solo(model, params, t3, 4))

    def test_remove_replica_tombstones_tsdb_series(self, model,
                                                   params):
        """Satellite: scale-down retires the dead engine's gauge
        series in the time-series store too (telemetry.
        retire_engine_series -> timeseries.tombstone_series) —
        instant queries stop answering for the removed replica while
        its pre-death history stays readable, and the survivor's
        series is untouched."""
        from deeplearning4j_tpu.profiler import timeseries as ts

        was = telemetry.enabled()
        telemetry.set_enabled(True)
        db = ts.TimeSeriesDB()
        ts.install(db)
        reg = telemetry.MetricsRegistry.get_default()
        try:
            with _fleet(model, params, replicas=2) as fl:
                eids = [r.engine.engine_id for r in fl._replicas]
                g = reg.gauge(telemetry.SERVING_SLOT_OCCUPANCY)
                for e in eids:
                    g.set(0.5, engine=e)
                t0 = time.time()
                db.ingest(t0, reg.capture())
                dead, alive = eids[0], eids[1]
                assert fl.remove_replica(fl._replicas[0].rid)
                now = time.time()
                occ = "dl4j_tpu_serving_slot_occupancy"
                assert ts.query(f'{occ}{{engine="{dead}"}}',
                                t=now, db=db) == []
                assert ts.query(f'{occ}{{engine="{alive}"}}',
                                t=now, db=db) == \
                    [({"engine": alive}, 0.5)]
                # pre-death history is still there (range reads with
                # no instant don't drop tombstoned series)
                hist = db.select(occ, [], t0 - 1, now + 1)
                assert {r[0]["engine"] for r in hist} == set(eids)
        finally:
            ts.install(None, None)
            telemetry.set_enabled(was)

    def test_rid_stability_and_last_replica_guard(self, model,
                                                  params):
        """Replica ids are STABLE handles, not list positions: after
        removing id 0, id 1 still addresses the same engine; the next
        add mints id 2; and the last live replica refuses removal."""
        rng = np.random.default_rng(23)
        with _fleet(model, params, replicas=2) as fl:
            keep_eng = fl._by_rid[1].engine
            assert fl.remove_replica(0)
            assert fl.alive_replicas() == 1
            assert fl._by_rid[1].engine is keep_eng
            with pytest.raises(ValueError):
                fl.remove_replica(1)             # last live replica
            rid = fl.add_replica()
            assert rid == 2
            assert [r["id"] for r in fl.stats()["replicas"]] == [1, 2]
            # stable-id drain/restart still address the right engine
            assert fl.drain_replica(2)
            fl.restart_replica(2)
            assert fl.alive_replicas() == 2
            p = rng.integers(0, VOCAB, (6,)).astype(np.int32)
            np.testing.assert_array_equal(
                fl.generate(p, 4), _solo(model, params, p, 4))
