"""Op-registry completeness checks (reference: OpRegistrator holds the
full declarable-op set, SURVEY.md §2.2).

The EXECUTIONAL coverage gate — every registered op must actually run
during the suite — lives in test_zzz_op_execution_gate.py (last in
collection order). This module guards the registry itself: a bare
``import deeplearning4j_tpu.ops`` must register the FULL op set (the
round-3 verdict found importer-owned stragglers), and the README's
headline op count must match reality.
"""

import os
import re

import deeplearning4j_tpu.ops  # noqa: F401
from deeplearning4j_tpu.ops.registry import list_ops

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bare_ops_import_registers_the_full_set():
    """Importing the importers/flash-attention modules must add ZERO
    new ops over a bare `deeplearning4j_tpu.ops` import."""
    base = set(list_ops())
    import deeplearning4j_tpu.modelimport.onnx.onnx_import  # noqa: F401
    import deeplearning4j_tpu.modelimport.tensorflow.tf_import  # noqa: F401,E501
    import deeplearning4j_tpu.modelimport.tensorflow.cf_import  # noqa: F401,E501
    full = set(list_ops())
    assert full == base, (
        f"importer modules register ops a bare import misses: "
        f"{sorted(full - base)} — move them into ops/")


def test_registry_is_at_least_reference_scale():
    # the reference registers ~500 declarable ops (SURVEY.md §2.6)
    assert len(list_ops()) >= 500


def test_readme_op_count_matches_registry():
    """The op count is a headline claim (README/PARITY); it must not
    drift from the actual registry (round-3 verdict weak #6)."""
    n = len(list_ops())
    for doc in ("README.md", "PARITY.md"):
        text = open(os.path.join(REPO, doc)).read()
        claims = [int(m) for m in
                  re.findall(r"(\d{3})\+? registered ops", text)]
        for c in claims:
            assert c == n, (
                f"{doc} claims {c} registered ops; registry has {n}")
