"""Op-coverage accounting gate (reference: org/nd4j/autodiff/validation/
OpValidation — "coverage accounting that fails the build if an op has
no test", SURVEY.md §4).

Every registered op name must be referenced somewhere in the test
corpus (as a word token — a direct call, a registry lookup string, or a
SameDiff namespace emission). Newly registered ops without any test
reference fail this gate, exactly like the reference's
OpValidation#logCoverageInformation build failure.
"""

import os
import re

import pytest

# populate the FULL registry deterministically — some ops register on
# import of the autodiff/importer modules, and the gate must not depend
# on which other test files ran first in the session
import deeplearning4j_tpu.ops  # noqa: F401
import deeplearning4j_tpu.autodiff.ops_math  # noqa: F401
import deeplearning4j_tpu.autodiff.control_flow  # noqa: F401
import deeplearning4j_tpu.ops.flash_attention  # noqa: F401
import deeplearning4j_tpu.modelimport.onnx.onnx_import  # noqa: F401
import deeplearning4j_tpu.modelimport.tensorflow.tf_import  # noqa: F401
from deeplearning4j_tpu.ops.registry import list_ops

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))

#: ops intentionally exempt from per-op test accounting: thin jnp/lax
#: aliases exercised transitively (each entry is a conscious decision,
#: like the reference's excludedOpsets)
EXEMPT = set()


def _test_corpus() -> str:
    chunks = []
    for fn in os.listdir(TESTS_DIR):
        if fn.endswith(".py") and fn != os.path.basename(__file__):
            with open(os.path.join(TESTS_DIR, fn)) as f:
                chunks.append(f.read())
    # framework internals count as indirect coverage only through their
    # own tests, so ONLY the tests dir is scanned
    return "\n".join(chunks)


def test_every_registered_op_is_referenced_in_tests():
    corpus = _test_corpus()
    words = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", corpus))
    missing = [op for op in list_ops()
               if op not in words and op not in EXEMPT]
    assert not missing, (
        f"{len(missing)} registered ops have no test reference "
        f"(reference parity: OpValidation coverage gate): {missing}")
