"""Fused BN+ReLU backward (ops/nn.py batch_norm_relu_train): grads
pinned against XLA autodiff of the unfused batch_norm_train + relu
composition, plus end-to-end layer-path equivalence under the
FUSED_BN_RELU_BWD toggle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.ops.nn as nnops


@pytest.fixture
def data():
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(8, 5, 5, 6)).astype(np.float32)) * 2 + 1.5
    g = jnp.asarray(rng.normal(size=(6,)).astype(np.float32)) + 1.0
    b = jnp.asarray(rng.normal(size=(6,)).astype(np.float32))
    gy = jnp.asarray(rng.normal(size=(8, 5, 5, 6)).astype(np.float32))
    return x, g, b, gy


class TestFusedBnRelu:
    def test_forward_matches_unfused(self, data):
        x, g, b, _ = data
        y0, m0, v0 = nnops.batch_norm_train(x, g, b)
        y1, m1, v1 = nnops.batch_norm_relu_train(x, g, b)
        np.testing.assert_allclose(np.maximum(y0, 0), y1, atol=1e-6)
        np.testing.assert_allclose(m0, m1, atol=1e-6)
        np.testing.assert_allclose(v0, v1, atol=1e-6)

    def test_grads_match_autodiff(self, data):
        x, g, b, gy = data

        def ref(x, g, b):
            y, _, _ = nnops.batch_norm_train(x, g, b)
            return jnp.sum(jnp.maximum(y, 0) * gy)

        def fused(x, g, b):
            y, _, _ = nnops.batch_norm_relu_train(x, g, b)
            return jnp.sum(y * gy)

        gr = jax.grad(ref, argnums=(0, 1, 2))(x, g, b)
        gf = jax.grad(fused, argnums=(0, 1, 2))(x, g, b)
        for a, c in zip(gr, gf):
            np.testing.assert_allclose(a, c, rtol=2e-5, atol=2e-5)

    def test_dense_axes(self, data):
        _, g, b, _ = data
        rng = np.random.default_rng(3)
        x2 = jnp.asarray(rng.normal(size=(32, 6)).astype(np.float32))
        gy2 = jnp.asarray(rng.normal(size=(32, 6)).astype(np.float32))

        def ref(x):
            y, _, _ = nnops.batch_norm_train(x, g, b)
            return jnp.sum(jnp.maximum(y, 0) * gy2)

        def fused(x):
            y, _, _ = nnops.batch_norm_relu_train(x, g, b)
            return jnp.sum(y * gy2)

        np.testing.assert_allclose(jax.grad(ref)(x2), jax.grad(fused)(x2),
                                   rtol=2e-5, atol=2e-5)

    def test_stats_outputs_are_stop_gradient(self, data):
        x, g, b, _ = data

        def stats_loss(x):
            _, m, v = nnops.batch_norm_relu_train(x, g, b)
            return jnp.sum(m) + jnp.sum(v)

        np.testing.assert_allclose(jax.grad(stats_loss)(x),
                                   jnp.zeros_like(x), atol=0)

    def test_layer_toggle_equivalence(self):
        """One BN(relu) training step via MultiLayerNetwork under both
        toggle values converges to the same loss."""
        from deeplearning4j_tpu.nn.conf import (
            BatchNormalization, DenseLayer, InputType,
            NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 12)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]

        losses = {}
        prev = nnops.FUSED_BN_RELU_BWD
        try:
            for fused in (False, True):
                nnops.FUSED_BN_RELU_BWD = fused
                conf = (NeuralNetConfiguration.builder().seed(5)
                        .list()
                        .layer(DenseLayer(n_out=16, activation="identity"))
                        .layer(BatchNormalization(activation="relu"))
                        .layer(OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"))
                        .setInputType(InputType.feedForward(12)).build())
                net = MultiLayerNetwork(conf).init()
                for _ in range(5):
                    net.fit(x, y)
                losses[fused] = net.score()
        finally:
            nnops.FUSED_BN_RELU_BWD = prev
        assert abs(losses[False] - losses[True]) < 1e-4 * max(
            1.0, abs(losses[False]))
