"""ZeRO-style cross-replica weight-update sharding
(parallel/zero.py + ShardedTrainer update_sharding='zero' +
ops/fused_update_pallas.py; arXiv:2004.13336):

- loss-trajectory parity with the replicated sharing step (f32 exact,
  mixed policies within the precision-smoke tolerance)
- 1/N per-device master/opt byte gauges
- fused Adam+unscale+clip kernel golden test vs the composed
  updaters reference at step 300 (XLA fallback + Pallas interpreter)
- CG sharing-mode mask threading (the PR 2 mask gap)
- mixed per-layer updaters (multi-group flat layout)
"""

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.learning.updaters import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.nn.conf import (
    DenseLayer, InputType, LSTM, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
from deeplearning4j_tpu.ops.fused_update_pallas import (
    fused_master_update, fused_update_mode,
)
from deeplearning4j_tpu.ops.registry import get_op
from deeplearning4j_tpu.parallel.mesh import (
    build_mesh, maybe_init_distributed,
)
from deeplearning4j_tpu.parallel.sharded import ShardedTrainer
from deeplearning4j_tpu.profiler import telemetry


def small_net(updater=None, precision=None, per_layer_updater=None,
              seed=11):
    b = NeuralNetConfiguration.builder().seed(seed).updater(
        updater or Adam(1e-2))
    if precision:
        b = b.precision(precision)
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="tanh",
                              updater=per_layer_updater))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.feedForward(6)).build())
    return MultiLayerNetwork(conf).init()


def toy_data(n=32, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return x, y


X, Y = toy_data()
MESH = None


def mesh8():
    global MESH
    if MESH is None:
        MESH = build_mesh(num_data=8)
    return MESH


def fit_pair(steps=8, **kw):
    """(replicated_model, zero_model) after identical fits."""
    a = small_net(**kw)
    ta = ShardedTrainer(a, mesh=mesh8(), mode="sharing")
    b = small_net(**kw)
    tb = ShardedTrainer(b, mesh=mesh8(), mode="sharing",
                        update_sharding="zero")
    ds = DataSet(X, Y)
    for _ in range(steps):
        ta.fit(ds)
        tb.fit(ds)
    return a, b


class TestZeroParity:
    def test_adam_f32_matches_replicated(self):
        a, b = fit_pair()
        la, lb = float(a.score()), float(b.score())
        assert abs(la - lb) / abs(la) < 1e-5, (la, lb)
        # canonical trees synced at fit exit: params AND Adam moments
        for u, v in zip(
                jax.tree_util.tree_leaves((a.params_list, a.opt_states)),
                jax.tree_util.tree_leaves((b.params_list, b.opt_states))):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                       rtol=5e-4, atol=1e-6)

    def test_generic_updater_path(self):
        """Non-Adam updaters take the generic flat-updater path."""
        a, b = fit_pair(updater=Nesterovs(0.05))
        la, lb = float(a.score()), float(b.score())
        assert abs(la - lb) / abs(la) < 1e-5, (la, lb)

    def test_mixed_per_layer_updaters_multi_group(self):
        """A per-layer updater override splits the flat layout into
        multiple groups (fused Adam + generic Sgd) — parity holds."""
        a, b = fit_pair(per_layer_updater=Sgd(0.05))
        la, lb = float(a.score()), float(b.score())
        assert abs(la - lb) / abs(la) < 1e-4, (la, lb)
        tb_layout = None  # layout introspection via a fresh trainer
        net = small_net(per_layer_updater=Sgd(0.05))
        tr = ShardedTrainer(net, mesh=mesh8(), mode="sharing",
                            update_sharding="zero")
        tr.fit(DataSet(X, Y))
        tb_layout = tr._zero_layout
        assert len(tb_layout.groups) == 2
        assert sorted(g.fused for g in tb_layout.groups) == [False, True]

    def test_mixed_bfloat16_policy(self):
        a, b = fit_pair(precision="mixed_bfloat16")
        la, lb = float(a.score()), float(b.score())
        assert np.isfinite(lb)
        assert abs(la - lb) / abs(la) < 0.02, (la, lb)

    def test_mixed_float16_loss_scaling(self):
        """Dynamic loss scaling threads through the zero step: scale
        state advances and masters stay fp32."""
        net = small_net(precision="mixed_float16")
        tr = ShardedTrainer(net, mesh=mesh8(), mode="sharing",
                            update_sharding="zero")
        ds = DataSet(X, Y)
        for _ in range(6):
            tr.fit(ds)
        assert np.isfinite(float(net.score()))
        assert float(np.asarray(
            net._loss_scale_state["scale"])) > 0
        for gid, flat in tr._zero["masters"].items():
            assert flat.dtype == jnp.float32
        tr._finish()
        for leaf in jax.tree_util.tree_leaves(net.params_list):
            assert leaf.dtype == jnp.float32

    def test_interpret_kernel_end_to_end(self, monkeypatch):
        """The Pallas kernel (interpreter) + shard_map path trains with
        the same trajectory as the XLA fallback."""
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATE", "interpret")
        assert fused_update_mode() == "interpret"
        a, b = fit_pair(steps=3)
        la, lb = float(a.score()), float(b.score())
        assert abs(la - lb) / abs(la) < 1e-5, (la, lb)

    def test_double_model_takes_generic_path(self):
        """f64 masters must NOT route through the fused kernel (its
        moment buffers are f32 — silent accumulator truncation); the
        generic flat-updater path keeps f64 end to end. Needs real
        x64 (the suite pins jax_enable_x64=False, under which 'double'
        params are f32 and fusing them is correct)."""
        jax.config.update("jax_enable_x64", True)
        try:
            conf = (NeuralNetConfiguration.builder().seed(11)
                    .dataType("double").updater(Adam(1e-2)).list()
                    .layer(DenseLayer(n_out=8, activation="tanh"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .setInputType(InputType.feedForward(6)).build())
            net = MultiLayerNetwork(conf).init()
            tr = ShardedTrainer(net, mesh=mesh8(), mode="sharing",
                                update_sharding="zero")
            tr.fit(DataSet(X.astype(np.float64),
                           Y.astype(np.float64)))
            assert all(not g.fused for g in tr._zero_layout.groups)
            for flat in tr._zero["masters"].values():
                assert flat.dtype == jnp.float64
            tr._finish()
            assert np.isfinite(float(net.score()))
            for leaf in jax.tree_util.tree_leaves(net.params_list):
                assert leaf.dtype == jnp.float64
        finally:
            jax.config.update("jax_enable_x64", False)

    def test_paramless_layers_pass_through(self):
        """Leafless layers (subsampling/pooling) have no flat-layout
        group; their empty param/opt subtrees must survive assembly
        (placement, the traced step, and the _finish gather)."""
        from deeplearning4j_tpu.nn.conf import (
            ConvolutionLayer, SubsamplingLayer,
        )

        def conv_net():
            conf = (NeuralNetConfiguration.builder().seed(13)
                    .updater(Adam(1e-2)).list()
                    .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                            activation="relu"))
                    .layer(SubsamplingLayer(kernel_size=(2, 2),
                                            stride=(2, 2)))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .setInputType(InputType.convolutional(8, 8, 1))
                    .build())
            return MultiLayerNetwork(conf).init()

        rs = np.random.RandomState(2)
        xi = rs.randn(16, 8, 8, 1).astype(np.float32)
        yi = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)]
        a = conv_net()
        ShardedTrainer(a, mesh=mesh8(), mode="sharing").fit(
            DataSet(xi, yi))
        b = conv_net()
        tb = ShardedTrainer(b, mesh=mesh8(), mode="sharing",
                            update_sharding="zero")
        for _ in range(3):
            tb.fit(DataSet(xi, yi))
        la, lb = float(a.score()), float(b.score())
        assert np.isfinite(lb)
        # the paramless layer's subtrees survive the canonical sync
        assert jax.tree_util.tree_structure(b.params_list) \
            == jax.tree_util.tree_structure(a.params_list)
        assert jax.tree_util.tree_structure(b.opt_states) \
            == jax.tree_util.tree_structure(a.opt_states)

    def test_update_sharding_validation(self):
        net = small_net()
        with pytest.raises(ValueError, match="sharing"):
            ShardedTrainer(net, mesh=mesh8(), mode="averaging",
                           update_sharding="zero")
        with pytest.raises(ValueError, match="update_sharding"):
            ShardedTrainer(net, mesh=mesh8(), update_sharding="bogus")


class TestZeroMemoryGauges:
    def test_per_device_bytes_drop_to_one_nth(self):
        net = small_net()
        tr = ShardedTrainer(net, mesh=mesh8(), mode="sharing",
                            update_sharding="zero")
        tr.fit(DataSet(X, Y))
        rep_net = small_net()
        rep = ShardedTrainer(rep_net, mesh=mesh8(), mode="sharing")
        rep.fit(DataSet(X, Y))
        reg = telemetry.MetricsRegistry.get_default()
        mg = reg.gauge(telemetry.MASTER_PARAM_BYTES)
        og = reg.gauge(telemetry.OPT_STATE_BYTES)
        m_rep = mg.value(mode="replicated", site="sharded")
        m_z = mg.value(mode="update_sharded", site="sharded")
        o_rep = og.value(mode="replicated", site="sharded")
        o_z = og.value(mode="update_sharded", site="sharded")
        assert m_rep > 0 and o_rep > 0
        # 1/8 plus shard-alignment padding: must be well under 1/4
        assert 0 < m_z < m_rep / 4, (m_z, m_rep)
        assert 0 < o_z < o_rep / 4, (o_z, o_rep)
        # masters really live sharded P('data') on the mesh
        flat = next(iter(tr._zero["masters"].values()))
        assert flat.addressable_shards[0].data.shape[0] \
            == flat.shape[0] // 8
        snap = telemetry.snapshot()
        assert "state_bytes" in snap
        assert "master_param_bytes" in snap["state_bytes"]


class TestFusedKernelGolden:
    def _golden(self, mode):
        """Kernel vs composed reference (unscale -> global-norm clip ->
        updaters.Adam.apply -> p - u) at step 300 — where a
        half-precision bias-correction power would have decayed
        (the _step_float contract)."""
        rs = np.random.RandomState(3)
        n = 2000
        master = jnp.asarray(rs.randn(n), jnp.float32)
        m = jnp.asarray(rs.randn(n) * 0.01, jnp.float32)
        v = jnp.asarray(np.abs(rs.randn(n)) * 1e-4, jnp.float32)
        grad = jnp.asarray(rs.randn(n) * 2 ** 12, jnp.float32)
        upd = Adam(3e-4)
        step = jnp.asarray(300)
        inv_scale = jnp.asarray(2.0 ** -12)
        clip = 0.5
        g_u = grad * inv_scale
        norm = jnp.sqrt(jnp.sum(g_u ** 2))
        g_c = g_u * jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
        updates, ns = upd.apply({"m": m, "v": v}, g_c, step)
        ref = (master - updates, ns["m"], ns["v"])
        got = get_op("fused_adam_master_update")(
            master, m, v, grad, step, upd, inv_scale=inv_scale,
            clip_norm=clip, mode=mode)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_xla_fallback_matches_reference(self):
        self._golden("xla")

    def test_pallas_interpreter_matches_reference(self):
        self._golden("interpret")

    def test_rejects_non_adam(self):
        with pytest.raises(TypeError, match="Adam"):
            fused_master_update(jnp.zeros(8), jnp.zeros(8), jnp.zeros(8),
                                jnp.zeros(8), 0, Nesterovs(0.1))


class TestGraphMasks:
    """PR 2 mask-gap fix: sharing-mode ShardedTrainer threads DataSet
    masks through ComputationGraph models instead of warn+ignore."""

    def _rnn_cg(self, seed=3):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )
        b = (ComputationGraphConfiguration.graphBuilder()
             .seed(seed).updater(Adam(1e-2))
             .addInputs("in")
             .setInputTypes(InputType.recurrent(5)))
        b.addLayer("lstm", LSTM(n_out=8), "in")
        b.addLayer("out", OutputLayer(n_out=4, activation="softmax",
                                      loss="mcxent"), "lstm")
        return ComputationGraph(b.setOutputs("out").build()).init()

    def _masked_ds(self):
        rs = np.random.RandomState(1)
        n, t = 16, 6
        x = rs.randn(n, t, 5).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, (n, t))]
        fm = (rs.rand(n, t) > 0.3).astype(np.float32)
        fm[:, 0] = 1.0
        return DataSet(x, y, labels_mask=fm, features_mask=fm)

    @pytest.mark.parametrize("us", [None, "zero"])
    def test_masked_loss_parity_with_single_device(self, us, caplog):
        ds = self._masked_ds()
        ref = self._rnn_cg()
        for _ in range(3):
            ref.fit(ds)
        dp = self._rnn_cg()
        tr = ShardedTrainer(dp, mesh=mesh8(), mode="sharing",
                            update_sharding=us)
        with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
            for _ in range(3):
                tr.fit(ds)
        assert not [r for r in caplog.records
                    if "ignores DataSet mask" in r.getMessage()]
        la, lb = float(ref.score()), float(dp.score())
        assert abs(la - lb) / abs(la) < 1e-4, (la, lb)

    def test_non_sharing_modes_still_warn(self, caplog):
        ds = self._masked_ds()
        dp = self._rnn_cg()
        tr = ShardedTrainer(dp, mesh=mesh8(), mode="averaging")
        with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
            tr.fit(ds)
        assert [r for r in caplog.records
                if "ignores DataSet mask" in r.getMessage()]


class TestDistributedInit:
    def test_no_env_is_noop(self):
        assert maybe_init_distributed(env={}) is False
        assert maybe_init_distributed(
            env={"DL4J_TPU_COORDINATOR": "x:1",
                 "DL4J_TPU_NUM_PROCESSES": "1"}) is False

    def test_bad_env_is_noop(self):
        assert maybe_init_distributed(
            env={"DL4J_TPU_COORDINATOR": "x:1",
                 "DL4J_TPU_NUM_PROCESSES": "two"}) is False
