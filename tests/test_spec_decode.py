"""Speculative decoding (serving/spec_decode.py + the engine's verify
path): rejection-sampling acceptance golden vs a dense per-slot numpy
reference, distribution preservation at temperature > 0, greedy
engine-level token identity vs spec-off across mid-flight joins,
sessions resume (with forced rejected-token rewind), prefix-cache CoW
sharers, per-seed determinism, warm-pool zero-miss / zero-compile
contracts, and off-mode inertness (spec_decode=None builds nothing)."""

import hashlib
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.gpt import CausalLM
from deeplearning4j_tpu.models.transformer import tiny_config
from deeplearning4j_tpu.profiler import telemetry
from deeplearning4j_tpu.serving import (
    DecodeEngine, NGramDraft, SpecConfig,
)
from deeplearning4j_tpu.serving.spec_decode import accept_tokens

VOCAB = 13
PS = 8


def _model():
    cfg = tiny_config(vocab=VOCAB, max_len=64, d_model=32, n_layers=2,
                      n_heads=4, d_ff=64)
    cfg.dropout = 0.0
    return CausalLM(cfg, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return _model()


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(jax.random.key(1))


def _solo(model, params, prompt, new):
    return np.asarray(model.generate(
        params, jnp.asarray(np.asarray(prompt)[None, :], jnp.int32),
        new))[0]


def _engine(model, params, spec=4, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("page_size", PS)
    kw.setdefault("max_chunk", 4)
    kw.setdefault("prefill_buckets", [8, 16, 32])
    return DecodeEngine(model, params, spec_decode=spec, **kw)


# ------------------------------------------------------- n-gram draft
class TestNGramDraft:
    def test_proposes_continuation_of_trailing_ngram(self):
        d = NGramDraft(match_len=3)
        h = np.asarray([1, 2, 3, 9, 1, 2, 3], np.int32)
        # trailing [1,2,3] occurred at position 0; what followed is
        # [9, 1, 2]
        np.testing.assert_array_equal(d.propose(h, 3), [9, 1, 2])

    def test_prefers_longest_match_and_most_recent_occurrence(self):
        d = NGramDraft(match_len=2)
        # [5, 6] occurs twice before the tail; the LATER one (followed
        # by 8) must win over the earlier (followed by 7)
        h = np.asarray([5, 6, 7, 5, 6, 8, 5, 6], np.int32)
        np.testing.assert_array_equal(d.propose(h, 1), [8])

    def test_fallback_repeats_last_token(self):
        d = NGramDraft(match_len=3)
        h = np.asarray([3, 4, 5], np.int32)   # no repeated n-gram
        np.testing.assert_array_equal(d.propose(h, 4), [5, 5, 5, 5])

    def test_short_continuation_padded_to_k(self):
        d = NGramDraft(match_len=2)
        h = np.asarray([1, 2, 3, 1, 2], np.int32)
        # match at 0, continuation [3, 1, 2] then padded with 2
        np.testing.assert_array_equal(d.propose(h, 5), [3, 1, 2, 2, 2])

    def test_always_returns_exactly_k_int32(self):
        d = NGramDraft()
        for k in (1, 3, 8):
            out = d.propose(np.asarray([0, 1, 0, 1, 0], np.int32), k)
            assert out.shape == (k,) and out.dtype == np.int32

    def test_match_len_validated(self):
        with pytest.raises(ValueError, match="match_len"):
            NGramDraft(match_len=0)


# -------------------------------------------------------- SpecConfig
class TestSpecConfig:
    def test_resolve_forms(self):
        assert SpecConfig.resolve(None) is None
        assert SpecConfig.resolve(False) is None
        assert SpecConfig.resolve(True).k == 4
        assert SpecConfig.resolve(6).k == 6
        assert SpecConfig.resolve("ngram").draft == "ngram"
        c = SpecConfig.resolve({"k": 2, "match_len": 1})
        assert c.k == 2 and c.match_len == 1
        cfg = SpecConfig(k=3)
        assert SpecConfig.resolve(cfg) is cfg

    def test_resolve_rejects_unknowns(self):
        with pytest.raises(ValueError, match="unknown spec_decode"):
            SpecConfig.resolve("medusa")
        with pytest.raises(ValueError, match="k must be >= 1"):
            SpecConfig.resolve(0)
        with pytest.raises(ValueError):
            SpecConfig.resolve(3.5)

    def test_make_draft_custom_object(self):
        class Custom:
            def propose(self, history, k):
                return np.zeros((k,), np.int32)

        d = Custom()
        assert SpecConfig(k=2, draft=d).make_draft() is d
        with pytest.raises(ValueError, match="propose"):
            SpecConfig(k=2, draft=object()).make_draft()


# ---------------------------------------------- acceptance math golden
def _ref_accept(logits, drafts, n_draft, keydata, temps):
    """Dense per-slot python reference of accept_tokens: the same
    jax.random primitives applied one slot / one position at a time,
    with the acceptance loop written as the textbook sequential
    algorithm. The fixed-shape vectorized version must agree exactly."""
    S, W, V = logits.shape
    K = W - 1
    outs, naccs, carries = [], [], []
    for s in range(S):
        kk = jax.random.wrap_key_data(jnp.asarray(keydata[s]))
        nk = jax.random.split(kk, 2 * K + 2)
        carries.append(np.asarray(jax.random.key_data(nk[0])))
        lg = np.asarray(logits[s], np.float32)
        t = float(temps[s])
        if t > 0:
            scaled = lg / t
            p = np.asarray(jax.nn.softmax(jnp.asarray(scaled[:K]),
                                          axis=-1))
            m = 0
            while m < n_draft[s]:
                u = float(jax.random.uniform(nk[1 + m]))
                if u < p[m, drafts[s, m]]:
                    m += 1
                else:
                    break
            if m < n_draft[s]:
                resid = scaled[m].copy()
                resid[drafts[s, m]] = -np.inf
                corr = int(jax.random.categorical(
                    nk[K + 1 + m], jnp.asarray(resid)))
            else:
                corr = int(jax.random.categorical(
                    nk[2 * K + 1], jnp.asarray(scaled[int(n_draft[s])])))
        else:
            greedy = lg.argmax(-1)
            m = 0
            while m < n_draft[s] and drafts[s, m] == greedy[m]:
                m += 1
            corr = int(greedy[m])
        outs.append(list(drafts[s, :m]) + [corr])
        naccs.append(m + 1)
    return outs, naccs, np.stack(carries)


class TestAcceptTokens:
    def _case(self, seed, S=5, K=4, V=VOCAB, temps=None):
        rng = np.random.default_rng(seed)
        logits = rng.normal(0, 2, (S, K + 1, V)).astype(np.float32)
        drafts = rng.integers(0, V, (S, K)).astype(np.int32)
        n_draft = rng.integers(0, K + 1, (S,)).astype(np.int32)
        n_draft[0] = K            # always cover the all-real case
        keydata = np.stack([
            np.asarray(jax.random.key_data(jax.random.key(seed * 100
                                                          + s)))
            for s in range(S)])
        if temps is None:
            temps = np.zeros((S,), np.float32)
        return logits, drafts, n_draft, keydata, temps

    def test_greedy_matches_sequential_reference(self):
        for seed in range(4):
            lg, dr, nd, kd, tm = self._case(seed)
            out, nacc, new_kd = jax.tree_util.tree_map(
                np.asarray, accept_tokens(jnp.asarray(lg),
                                          jnp.asarray(dr),
                                          jnp.asarray(nd),
                                          jnp.asarray(kd),
                                          jnp.asarray(tm)))
            ref_out, ref_n, ref_kd = _ref_accept(lg, dr, nd, kd, tm)
            np.testing.assert_array_equal(nacc, ref_n)
            np.testing.assert_array_equal(new_kd, ref_kd)
            for s in range(lg.shape[0]):
                np.testing.assert_array_equal(out[s, :nacc[s]],
                                              ref_out[s])

    def test_greedy_is_longest_prefix_plus_argmax_correction(self):
        """Constructed case: drafts agree with the target argmax for
        exactly m positions -> emit those m + the argmax at m."""
        V, K = 7, 3
        logits = np.full((1, K + 1, V), -5.0, np.float32)
        argmaxes = [2, 5, 1, 6]
        for i, a in enumerate(argmaxes):
            logits[0, i, a] = 5.0
        drafts = np.asarray([[2, 5, 3]], np.int32)   # mismatch at i=2
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))[None]
        out, nacc, _ = accept_tokens(
            jnp.asarray(logits), jnp.asarray(drafts),
            jnp.asarray([K], jnp.int32), jnp.asarray(kd),
            jnp.zeros((1,), jnp.float32))
        assert int(nacc[0]) == 3
        np.testing.assert_array_equal(np.asarray(out)[0, :3], [2, 5, 1])

    def test_sampled_matches_sequential_reference(self):
        for seed in range(4):
            lg, dr, nd, kd, _ = self._case(seed)
            tm = np.full((lg.shape[0],), 0.7, np.float32)
            tm[0] = 0.0           # mixed greedy/sampled roster
            out, nacc, new_kd = jax.tree_util.tree_map(
                np.asarray, accept_tokens(jnp.asarray(lg),
                                          jnp.asarray(dr),
                                          jnp.asarray(nd),
                                          jnp.asarray(kd),
                                          jnp.asarray(tm)))
            ref_out, ref_n, ref_kd = _ref_accept(lg, dr, nd, kd, tm)
            np.testing.assert_array_equal(nacc, ref_n)
            np.testing.assert_array_equal(new_kd, ref_kd)
            for s in range(lg.shape[0]):
                np.testing.assert_array_equal(out[s, :nacc[s]],
                                              ref_out[s])

    def test_nacc_bounds_and_zero_draft_slots(self):
        lg, dr, nd, kd, tm = self._case(9)
        nd[:] = [4, 0, 2, 0, 1]
        out, nacc, _ = accept_tokens(
            jnp.asarray(lg), jnp.asarray(dr), jnp.asarray(nd),
            jnp.asarray(kd), jnp.asarray(tm))
        nacc = np.asarray(nacc)
        assert ((nacc >= 1) & (nacc <= nd + 1)).all()
        # n_draft = 0 lanes are op-for-op a plain greedy step
        assert nacc[1] == 1 and nacc[3] == 1
        assert int(np.asarray(out)[1, 0]) == int(lg[1, 0].argmax())

    def test_key_advance_independent_of_acceptance(self):
        """The carry key must advance identically no matter what was
        drafted or accepted — replays stay deterministic per seed."""
        lg, dr, nd, kd, _ = self._case(3)
        tm = np.full((lg.shape[0],), 0.9, np.float32)
        _, _, kd_a = accept_tokens(
            jnp.asarray(lg), jnp.asarray(dr), jnp.asarray(nd),
            jnp.asarray(kd), jnp.asarray(tm))
        rng = np.random.default_rng(99)
        other = rng.integers(0, VOCAB, dr.shape).astype(np.int32)
        _, _, kd_b = accept_tokens(
            jnp.asarray(-lg), jnp.asarray(other),
            jnp.asarray(np.zeros_like(nd)), jnp.asarray(kd),
            jnp.asarray(tm))
        np.testing.assert_array_equal(np.asarray(kd_a),
                                      np.asarray(kd_b))

    def test_first_token_marginal_is_target_distribution(self):
        """Rejection sampling with a deterministic draft preserves the
        target law: over many keys, the FIRST emitted token's empirical
        distribution matches softmax(logits / T) even though the draft
        always proposes the same token."""
        V, K, N, T = 5, 1, 4000, 0.8
        rng = np.random.default_rng(0)
        row = rng.normal(0, 1, (V,)).astype(np.float32)
        logits = np.tile(row, (N, K + 1, 1))
        drafts = np.full((N, K), 3, np.int32)   # fixed draft token
        kd = np.asarray(jax.vmap(jax.random.key_data)(
            jax.vmap(jax.random.key)(jnp.arange(N))))
        out, _, _ = accept_tokens(
            jnp.asarray(logits), jnp.asarray(drafts),
            jnp.full((N,), K, jnp.int32), jnp.asarray(kd),
            jnp.full((N,), T, jnp.float32))
        first = np.asarray(out)[:, 0]
        want = np.asarray(jax.nn.softmax(jnp.asarray(row) / T))
        got = np.bincount(first, minlength=V) / N
        np.testing.assert_allclose(got, want, atol=0.05)


# -------------------------------------------------- engine: greedy id
class _WrongDraft:
    """Adversarial draft proposing guaranteed-mismatching tokens
    (argmax + 1 mod V of nothing — just a constant stream shifted off
    the history), so every dispatch exercises the rejected-token KV
    rewind path."""

    def propose(self, history, k):
        h = np.asarray(history, np.int32)
        return ((h[-1] + 5 + np.arange(k)) % VOCAB).astype(np.int32)


class TestEngineSpecGreedyIdentity:
    def test_mixed_length_concurrent_requests_match_solo(self, model,
                                                         params):
        """The tentpole acceptance contract: spec-on greedy decoding is
        token-identical to solo generate() for every request, with
        requests joining and leaving mid-flight."""
        rng = np.random.default_rng(0)
        specs = [(5, 6), (9, 3), (3, 12), (12, 1), (7, 9), (4, 4),
                 (10, 7), (6, 2)]
        prompts = [rng.integers(0, VOCAB, (t0,)).astype(np.int32)
                   for t0, _ in specs]
        with _engine(model, params, spec=4) as eng:
            with ThreadPoolExecutor(max_workers=8) as ex:
                handles = list(ex.map(
                    lambda pn: eng.submit(pn[0], pn[1]),
                    zip(prompts, [n for _, n in specs])))
            outs = [h.result(timeout=120) for h in handles]
            st = eng.stats()
            assert st["completed"] == len(specs)
            assert st["spec"]["proposed"] > 0
            assert st["spec"]["verify_dispatches"] > 0
            assert st["warm_pool"]["misses"] == 0
        assert eng.pool.allocated == 0
        for p, (_, new), got in zip(prompts, specs, outs):
            np.testing.assert_array_equal(
                got, _solo(model, params, p, new))

    def test_staggered_join_next_to_inflight_request(self, model,
                                                     params):
        rng = np.random.default_rng(1)
        long_p = rng.integers(0, VOCAB, (4,)).astype(np.int32)
        short_p = rng.integers(0, VOCAB, (6,)).astype(np.int32)
        with _engine(model, params, spec=2, slots=2) as eng:
            long_req = eng.submit(long_p, 14, eos_id=VOCAB)
            for _ in range(500):
                if len(long_req.tokens) >= 2:
                    break
                time.sleep(0.01)
            assert not long_req.done
            short_out = eng.submit(short_p, 3).result(timeout=60)
            long_out = long_req.result(timeout=60)
        np.testing.assert_array_equal(
            long_out, _solo(model, params, long_p, 14))
        np.testing.assert_array_equal(
            short_out, _solo(model, params, short_p, 3))

    def test_all_rejected_drafts_still_token_identical(self, model,
                                                       params):
        """An adversarial always-wrong draft forces a rejection (and a
        KV position rewind) on EVERY verify dispatch; output identity
        proves rejected lanes leave no trace in the cache."""
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, VOCAB, (n,)).astype(np.int32)
                   for n in (5, 9, 13)]
        cfg = SpecConfig(k=3, draft=_WrongDraft())
        with _engine(model, params, spec=cfg, slots=2) as eng:
            outs = [eng.submit(p, 10).result(timeout=120)
                    for p in prompts]
            st = eng.stats()["spec"]
            assert st["proposed"] > 0
            assert st["acceptance"] == 0.0   # every draft rejected
        for p, got in zip(prompts, outs):
            np.testing.assert_array_equal(
                got, _solo(model, params, p, 10))

    def test_session_resume_after_rejected_rewinds(self, model,
                                                   params):
        """A session's pinned pages were written THROUGH the verify
        path (including rejected, rewound positions past each turn's
        end); the resumed turn must still be token-identical."""
        rng = np.random.default_rng(3)
        p = rng.integers(0, VOCAB, (9,)).astype(np.int32)
        cfg = SpecConfig(k=3, draft=_WrongDraft())
        with _engine(model, params, spec=cfg, slots=2,
                     prefix_cache=True, session_capacity=2) as eng:
            o1 = eng.submit(p, 6, session_id="s").result(timeout=120)
            t2 = np.concatenate([p, o1])
            r2 = eng.submit(t2, 6, session_id="s")
            o2 = r2.result(timeout=120)
            assert r2.cache_hit_tokens == t2.size - 1
        np.testing.assert_array_equal(o1, _solo(model, params, p, 6))
        np.testing.assert_array_equal(o2, _solo(model, params, t2, 6))

    def test_prefix_cache_cow_sharers_token_identical(self, model,
                                                      params):
        """Two requests sharing cached prefix pages read-only while
        the verify program appends their divergent suffixes: CoW must
        isolate them exactly as on the plain path."""
        rng = np.random.default_rng(4)
        sys_p = rng.integers(0, VOCAB, (16,)).astype(np.int32)
        pa = np.concatenate([sys_p, rng.integers(0, VOCAB, (4,))
                             .astype(np.int32)])
        pb = np.concatenate([sys_p, rng.integers(0, VOCAB, (6,))
                             .astype(np.int32)])
        with _engine(model, params, spec=4, slots=2,
                     prefix_cache=True) as eng:
            eng.submit(sys_p, 1).result(120)    # populate the cache
            with ThreadPoolExecutor(max_workers=2) as ex:
                ha = ex.submit(lambda: eng.submit(pa, 10).result(120))
                hb = ex.submit(lambda: eng.submit(pb, 10).result(120))
                out_a, out_b = ha.result(), hb.result()
        np.testing.assert_array_equal(out_a,
                                      _solo(model, params, pa, 10))
        np.testing.assert_array_equal(out_b,
                                      _solo(model, params, pb, 10))

    def test_per_request_opt_out_rides_along(self, model, params):
        """spec_decode=False requests share the roster with drafting
        neighbors as plain lanes: identical output, zero spec stats."""
        rng = np.random.default_rng(5)
        pa = rng.integers(0, VOCAB, (6,)).astype(np.int32)
        pb = rng.integers(0, VOCAB, (8,)).astype(np.int32)
        with _engine(model, params, spec=4, slots=2) as eng:
            with ThreadPoolExecutor(max_workers=2) as ex:
                ha = ex.submit(lambda: eng.submit(pa, 9))
                hb = ex.submit(lambda: eng.submit(pb, 9,
                                                  spec_decode=False))
                ra, rb = ha.result(), hb.result()
            out_a = ra.result(timeout=120)
            out_b = rb.result(timeout=120)
            assert rb.spec_proposed == 0 and rb.spec_accepted == 0
        np.testing.assert_array_equal(out_a,
                                      _solo(model, params, pa, 9))
        np.testing.assert_array_equal(out_b,
                                      _solo(model, params, pb, 9))

    def test_fp8_kv_with_spec_completes_and_drains(self, model,
                                                   params):
        """fp8 pages + the verify path's segment-max scale minting:
        requests complete with the right token counts and every page
        refcount returns to zero (numeric identity is not the fp8
        contract — quantization moves logits by design)."""
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, VOCAB, (n,)).astype(np.int32)
                   for n in (5, 11)]
        with _engine(model, params, spec=3, slots=2,
                     kv_dtype="fp8_e4m3") as eng:
            outs = [eng.submit(p, 8).result(timeout=120)
                    for p in prompts]
            assert eng.stats()["spec"]["proposed"] > 0
        assert all(o.size == 8 for o in outs)
        assert eng.pool.allocated == 0


# ------------------------------------------- determinism + telemetry
class TestSpecDeterminismAndTelemetry:
    def test_sampling_deterministic_per_seed(self, model, params):
        """Same seeds, fresh engines: identical sampled outputs AND
        identical acceptance counters (the n-gram draft and the fixed
        key schedule are both deterministic)."""
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, VOCAB, (n,)).astype(np.int32)
                   for n in (6, 10)]

        def run():
            with _engine(model, params, spec=4, slots=2) as eng:
                outs = [eng.submit(p, 12, temperature=0.8,
                                   sample_seed=40 + i).result(120)
                        for i, p in enumerate(prompts)]
                st = eng.stats()["spec"]
            return outs, (st["proposed"], st["accepted"])

        outs_a, st_a = run()
        outs_b, st_b = run()
        assert st_a == st_b
        for a, b in zip(outs_a, outs_b):
            np.testing.assert_array_equal(a, b)

    def test_zero_post_start_compiles_and_counters_advance(
            self, model, params):
        """The verify program is AOT-warmed: warm traffic pays zero
        compiles at every serving site, and the spec telemetry
        counters advance."""
        reg = telemetry.MetricsRegistry.get_default()
        compiles = lambda s: reg.counter(
            telemetry.JIT_COMPILES).value(site=s)
        rng = np.random.default_rng(8)
        p = rng.integers(0, VOCAB, (7,)).astype(np.int32)
        with _engine(model, params, spec=4, slots=2) as eng:
            c0 = {s: compiles(s) for s in
                  ("serving_verify", "serving_decode",
                   "serving_prefill")}
            eng.submit(p, 10).result(timeout=120)
            st = eng.stats()
            assert st["warm_pool"]["misses"] == 0
            assert st["spec"]["proposed"] > 0
            assert st["spec"]["tokens_per_dispatch"] >= 1.0
        for s, v in c0.items():
            assert compiles(s) == v, f"{s} paid a compile post-startup"

    def test_request_level_spec_stats_populated(self, model, params):
        p = (np.arange(9) % VOCAB).astype(np.int32)
        with _engine(model, params, spec=4, slots=2) as eng:
            r = eng.submit(p, 8)
            r.result(timeout=120)
        assert r.spec_proposed > 0
        assert 0 <= r.spec_accepted <= r.spec_proposed


# ------------------------------------------------------ off-mode inert
class TestSpecOffMode:
    def test_off_engine_builds_no_spec_machinery(self, model, params):
        eng = DecodeEngine(model, params, slots=2, page_size=PS,
                           max_chunk=4, prefill_buckets=[8, 16])
        assert eng._spec is None
        assert not hasattr(eng, "_verify_jit")
        with eng:
            p = (np.arange(6) % VOCAB).astype(np.int32)
            eng.submit(p, 5).result(timeout=120)
            assert not any(k[0] == "verify" for k in eng._warm._exec)
            assert "spec" not in eng.stats()

    def test_spec_on_leaves_plain_programs_byte_identical(self, model,
                                                          params):
        """Turning speculation on must not change the plain decode /
        prefill executables at all — same warm-pool keys plus exactly
        the ("verify", k) addition, and HLO-digest-identical programs
        for every shared key."""
        def digests(eng):
            return {k: hashlib.sha256(
                ex.as_text().encode()).hexdigest()
                for k, ex in eng._warm._exec.items()}

        kw = dict(slots=2, page_size=PS, max_chunk=4,
                  prefill_buckets=[8, 16])
        off = DecodeEngine(model, params, **kw)
        on = DecodeEngine(model, params, spec_decode=4, **kw)
        with off, on:
            d_off, d_on = digests(off), digests(on)
        extra = set(d_on) - set(d_off)
        assert extra == {("verify", 4)}
        for k in d_off:
            assert d_on[k] == d_off[k], \
                f"{k} recompiled differently with spec on"
