"""ONNX recurrent-operator corner coverage: GRU linear_before_reset=0,
LSTM cell clip / input_forget coupling / peepholes / non-default
activations, sequence_lens < T (incl. bidirectional reverse-prefix
semantics), and layout=1 batch-major tensors.

Reference model: the reference maps these through nd4j's flexible
lstmLayer (samediff-import-onnx, SURVEY.md §2.14). No onnxruntime in
this image and torch cannot express most of these configs, so the
goldens are hand-built protos (tiny encoder from test_onnx_import)
checked against an INDEPENDENT plain-loop numpy implementation of the
ONNX spec equations.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.onnx.onnx_import import (
    OnnxImport, OnnxImportError,
)
from tests.test_onnx_import import (
    _iv, _ld, _str, attr_float, attr_int, attr_ints, graph, model,
    node, tensor, value_info,
)


# ------------------------------------------------ encoder additions
def attr_str(name: str, v: str) -> bytes:
    return _str(1, name) + _ld(4, v.encode()) + _iv(20, 3)


def attr_strs(name: str, vs) -> bytes:
    return _str(1, name) + b"".join(_ld(9, v.encode()) for v in vs) \
        + _iv(20, 8)


def attr_floats(name: str, vs) -> bytes:
    import struct
    return _str(1, name) + _ld(
        7, b"".join(struct.pack("<f", float(v)) for v in vs)) \
        + _iv(20, 6)


def attr_graph(name: str, g: bytes) -> bytes:
    return _str(1, name) + _ld(6, g) + _iv(20, 5)


def tensor_any(name: str, arr: np.ndarray) -> bytes:
    """tensor() plus bool support (dtype 9)."""
    if arr.dtype == np.bool_:
        out = b"".join(_iv(1, d) for d in arr.shape)
        out += _iv(2, 9)
        out += _str(8, name)
        out += _ld(9, arr.tobytes())
        return out
    return tensor(name, arr)


# ------------------------------------------- numpy spec reference
def _act(spec):
    name, alpha, beta = spec
    n = name.lower()
    if n == "sigmoid":
        return lambda v: 1.0 / (1.0 + np.exp(-v))
    if n == "tanh":
        return np.tanh
    if n == "relu":
        return lambda v: np.maximum(v, 0.0)
    if n == "leakyrelu":
        a = 0.01 if alpha is None else alpha
        return lambda v: np.where(v >= 0, v, a * v)
    if n == "hardsigmoid":
        a = 0.2 if alpha is None else alpha
        c = 0.5 if beta is None else beta
        return lambda v: np.clip(a * v + c, 0.0, 1.0)
    if n == "affine":
        a = 1.0 if alpha is None else alpha
        c = 0.0 if beta is None else beta
        return lambda v: a * v + c
    raise ValueError(name)


def _clip(v, c):
    return np.clip(v, -c, c) if c else v


def ref_lstm(x, W, R, B, P=None, h0=None, c0=None, lens=None,
             direction="forward", clip=0.0, input_forget=False,
             acts=None):
    """Plain-loop ONNX LSTM: x [T,N,in]; W [dirs,4H,in] iofc;
    R [dirs,4H,H]; B [dirs,8H]; P [dirs,3H] (p_i,p_o,p_f).
    Returns Y [T,dirs,N,H], Yh [dirs,N,H], Yc [dirs,N,H]."""
    T, N, _ = x.shape
    dirs = W.shape[0]
    H = R.shape[2]
    Y = np.zeros((T, dirs, N, H), np.float64)
    Yh = np.zeros((dirs, N, H), np.float64)
    Yc = np.zeros((dirs, N, H), np.float64)
    for d in range(dirs):
        f_a, g_a, h_a = [
            _act(s) for s in (acts[d] if acts else
                              [("sigmoid", None, None),
                               ("tanh", None, None),
                               ("tanh", None, None)])]
        Wi, Wo, Wf, Wc = np.split(W[d], 4)
        Ri, Ro, Rf, Rc = np.split(R[d], 4)
        wb = np.split(B[d][:4 * H], 4)
        rb = np.split(B[d][4 * H:], 4)
        pi = P[d][:H] if P is not None else 0.0
        po = P[d][H:2 * H] if P is not None else 0.0
        pf = P[d][2 * H:] if P is not None else 0.0
        rev = (direction == "reverse") or d == 1
        for n_ in range(N):
            ln = int(lens[n_]) if lens is not None else T
            h = (h0[d, n_] if h0 is not None else np.zeros(H)).copy()
            c = (c0[d, n_] if c0 is not None else np.zeros(H)).copy()
            order = range(ln - 1, -1, -1) if rev else range(ln)
            for t in order:
                xt = x[t, n_]
                it = f_a(_clip(xt @ Wi.T + h @ Ri.T + pi * c
                               + wb[0] + rb[0], clip))
                if input_forget:
                    ft = 1.0 - it
                else:
                    ft = f_a(_clip(xt @ Wf.T + h @ Rf.T + pf * c
                                   + wb[2] + rb[2], clip))
                ct = g_a(_clip(xt @ Wc.T + h @ Rc.T
                               + wb[3] + rb[3], clip))
                c = ft * c + it * ct
                ot = f_a(_clip(xt @ Wo.T + h @ Ro.T + po * c
                               + wb[1] + rb[1], clip))
                h = ot * h_a(c)
                Y[t, d, n_] = h
            Yh[d, n_] = h
            Yc[d, n_] = c
    return Y, Yh, Yc


def ref_gru(x, W, R, B, h0=None, lens=None, direction="forward",
            clip=0.0, linear_before_reset=0, acts=None):
    """Plain-loop ONNX GRU: W [dirs,3H,in] zrh; B [dirs,6H].
    Returns Y [T,dirs,N,H], Yh [dirs,N,H]."""
    T, N, _ = x.shape
    dirs = W.shape[0]
    H = R.shape[2]
    Y = np.zeros((T, dirs, N, H), np.float64)
    Yh = np.zeros((dirs, N, H), np.float64)
    for d in range(dirs):
        f_a, g_a = [
            _act(s) for s in (acts[d] if acts else
                              [("sigmoid", None, None),
                               ("tanh", None, None)])]
        Wz, Wr, Wh = np.split(W[d], 3)
        Rz, Rr, Rh = np.split(R[d], 3)
        wbz, wbr, wbh = np.split(B[d][:3 * H], 3)
        rbz, rbr, rbh = np.split(B[d][3 * H:], 3)
        rev = (direction == "reverse") or d == 1
        for n_ in range(N):
            ln = int(lens[n_]) if lens is not None else T
            h = (h0[d, n_] if h0 is not None else np.zeros(H)).copy()
            order = range(ln - 1, -1, -1) if rev else range(ln)
            for t in order:
                xt = x[t, n_]
                zt = f_a(_clip(xt @ Wz.T + h @ Rz.T + wbz + rbz, clip))
                rt = f_a(_clip(xt @ Wr.T + h @ Rr.T + wbr + rbr, clip))
                if linear_before_reset:
                    ht = g_a(_clip(xt @ Wh.T + rt * (h @ Rh.T + rbh)
                                   + wbh, clip))
                else:
                    ht = g_a(_clip(xt @ Wh.T + (rt * h) @ Rh.T
                                   + rbh + wbh, clip))
                h = (1.0 - zt) * ht + zt * h
                Y[t, d, n_] = h
            Yh[d, n_] = h
    return Y, Yh


def ref_rnn(x, W, R, B, h0=None, lens=None, direction="forward",
            clip=0.0, acts=None):
    T, N, _ = x.shape
    dirs = W.shape[0]
    H = R.shape[2]
    Y = np.zeros((T, dirs, N, H), np.float64)
    Yh = np.zeros((dirs, N, H), np.float64)
    for d in range(dirs):
        f_a = _act(acts[d][0] if acts else ("tanh", None, None))
        rev = (direction == "reverse") or d == 1
        for n_ in range(N):
            ln = int(lens[n_]) if lens is not None else T
            h = (h0[d, n_] if h0 is not None else np.zeros(H)).copy()
            order = range(ln - 1, -1, -1) if rev else range(ln)
            for t in order:
                h = f_a(_clip(x[t, n_] @ W[d].T + h @ R[d].T
                              + B[d][:H] + B[d][H:], clip))
                Y[t, d, n_] = h
            Yh[d, n_] = h
    return Y, Yh


# ------------------------------------------------- model builders
def _build_rnn_model(op, T, N, I, H, dirs, W, R, B, attrs,
                     lens=None, h0=None, c0=None, P=None,
                     n_out=2, layout=0):
    inits = [tensor("W", W.astype(np.float32)),
             tensor("R", R.astype(np.float32)),
             tensor("B", B.astype(np.float32))]
    ins = ["x", "W", "R", "B"]
    if lens is not None:
        inits.append(tensor("lens", lens.astype(np.int32)))
        ins.append("lens")
    else:
        ins.append("")
    if h0 is not None:
        inits.append(tensor("h0", h0.astype(np.float32)))
        ins.append("h0")
    elif c0 is not None or P is not None:
        ins.append("")
    if c0 is not None:
        inits.append(tensor("c0", c0.astype(np.float32)))
        ins.append("c0")
    elif P is not None and op == "LSTM":
        ins.append("")
    if P is not None:
        inits.append(tensor("P", P.astype(np.float32)))
        ins.append("P")
    while ins and ins[-1] == "":
        ins.pop()
    outs = [f"y{k}" for k in range(n_out)]
    x_shape = [N, T, I] if layout else [T, N, I]
    g = graph([node(op, ins, outs, attrs=attrs)], inits,
              [value_info("x", x_shape)],
              [value_info(o, [1]) for o in outs])
    return model(g, opset=14)


def _run(model_bytes, x):
    sd = OnnxImport.importGraph(OnnxImport._as_model(model_bytes))
    phs = [v.name for v in sd.variables()
           if v.vtype.value == "PLACEHOLDER"]
    outs = [od for od in sd._ops]
    names = [f"y{k}" for k in range(8) if sd.hasVariable(f"y{k}")]
    res = sd.output({phs[0]: x.astype(np.float32)}, names)
    return sd, [np.asarray(res[n]) for n in names]


def _mk(rs, *shape):
    return rs.normal(0, 0.4, shape)


RTOL, ATOL = 2e-4, 2e-5


class TestGruResetBefore:
    def test_linear_before_reset_0(self):
        rs = np.random.RandomState(0)
        T, N, I, H = 5, 3, 4, 6
        W, R, B = _mk(rs, 1, 3 * H, I), _mk(rs, 1, 3 * H, H), \
            _mk(rs, 1, 6 * H)
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("GRU", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_int("linear_before_reset", 0)])
        _, got = _run(m, x)
        Y, Yh = ref_gru(x, W, R, B, linear_before_reset=0)
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)

    def test_both_forms_differ(self):
        # premise guard: the two forms must actually disagree on this
        # data, otherwise the lbr=0 test proves nothing
        rs = np.random.RandomState(1)
        T, N, I, H = 4, 2, 3, 5
        W, R, B = _mk(rs, 1, 3 * H, I), _mk(rs, 1, 3 * H, H), \
            _mk(rs, 1, 6 * H)
        x = _mk(rs, T, N, I)
        y0, _ = ref_gru(x, W, R, B, linear_before_reset=0)
        y1, _ = ref_gru(x, W, R, B, linear_before_reset=1)
        assert np.abs(y0 - y1).max() > 1e-4

    def test_linear_before_reset_0_bidirectional(self):
        rs = np.random.RandomState(2)
        T, N, I, H = 5, 2, 3, 4
        W, R, B = _mk(rs, 2, 3 * H, I), _mk(rs, 2, 3 * H, H), \
            _mk(rs, 2, 6 * H)
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("GRU", T, N, I, H, 2, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_int("linear_before_reset", 0),
                              attr_str("direction", "bidirectional")])
        _, got = _run(m, x)
        Y, Yh = ref_gru(x, W, R, B, direction="bidirectional",
                        linear_before_reset=0)
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)


class TestLstmCorners:
    def _wrb(self, rs, dirs, I, H):
        return _mk(rs, dirs, 4 * H, I), _mk(rs, dirs, 4 * H, H), \
            _mk(rs, dirs, 8 * H)

    def test_cell_clip(self):
        rs = np.random.RandomState(3)
        T, N, I, H = 5, 2, 4, 3
        W, R, B = self._wrb(rs, 1, I, H)
        x = _mk(rs, T, N, I) * 3.0   # large inputs so the clip BITES
        m = _build_rnn_model("LSTM", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_float("clip", 0.4)], n_out=3)
        _, got = _run(m, x)
        Y, Yh, Yc = ref_lstm(x, W, R, B, clip=0.4)
        Y_noclip, _, _ = ref_lstm(x, W, R, B)
        assert np.abs(Y - Y_noclip).max() > 1e-3  # premise guard
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[2], Yc, rtol=RTOL, atol=ATOL)

    def test_input_forget_coupling(self):
        rs = np.random.RandomState(4)
        T, N, I, H = 4, 2, 3, 5
        W, R, B = self._wrb(rs, 1, I, H)
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("LSTM", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_int("input_forget", 1)], n_out=3)
        _, got = _run(m, x)
        Y, Yh, Yc = ref_lstm(x, W, R, B, input_forget=True)
        Y_plain, _, _ = ref_lstm(x, W, R, B)
        assert np.abs(Y - Y_plain).max() > 1e-3
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)

    def test_peepholes(self):
        rs = np.random.RandomState(5)
        T, N, I, H = 4, 2, 3, 4
        W, R, B = self._wrb(rs, 1, I, H)
        P = _mk(rs, 1, 3 * H)
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("LSTM", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H)], P=P,
                             n_out=3)
        _, got = _run(m, x)
        Y, Yh, Yc = ref_lstm(x, W, R, B, P=P)
        Y_plain, _, _ = ref_lstm(x, W, R, B)
        assert np.abs(Y - Y_plain).max() > 1e-3
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[2], Yc, rtol=RTOL, atol=ATOL)

    def test_nondefault_activations(self):
        rs = np.random.RandomState(6)
        T, N, I, H = 4, 2, 3, 4
        W, R, B = self._wrb(rs, 1, I, H)
        x = _mk(rs, T, N, I)
        acts = [[("hardsigmoid", 0.25, 0.55), ("relu", None, None),
                 ("tanh", None, None)]]
        m = _build_rnn_model(
            "LSTM", T, N, I, H, 1, W, R, B,
            [attr_int("hidden_size", H),
             attr_strs("activations", ["HardSigmoid", "Relu", "Tanh"]),
             attr_floats("activation_alpha", [0.25, 0.0, 0.0]),
             attr_floats("activation_beta", [0.55, 0.0, 0.0])],
            n_out=3)
        _, got = _run(m, x)
        Y, Yh, Yc = ref_lstm(x, W, R, B, acts=acts)
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)

    def test_layout_1_batch_major(self):
        rs = np.random.RandomState(7)
        T, N, I, H = 5, 3, 4, 2
        W, R, B = self._wrb(rs, 2, I, H)
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("LSTM", T, N, I, H, 2, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_int("layout", 1),
                              attr_str("direction", "bidirectional")],
                             n_out=3, layout=1)
        _, got = _run(m, x.transpose(1, 0, 2))  # feed [N,T,I]
        Y, Yh, Yc = ref_lstm(x, W, R, B, direction="bidirectional")
        # layout=1: Y [N,T,dirs,H]; states [N,dirs,H]
        np.testing.assert_allclose(got[0], Y.transpose(2, 0, 1, 3),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh.transpose(1, 0, 2),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[2], Yc.transpose(1, 0, 2),
                                   rtol=RTOL, atol=ATOL)


class TestSequenceLens:
    def test_lstm_ragged_forward(self):
        rs = np.random.RandomState(8)
        T, N, I, H = 6, 3, 4, 5
        W = _mk(rs, 1, 4 * H, I)
        R = _mk(rs, 1, 4 * H, H)
        B = _mk(rs, 1, 8 * H)
        lens = np.array([6, 3, 1])
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("LSTM", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H)], lens=lens,
                             n_out=3)
        _, got = _run(m, x)
        Y, Yh, Yc = ref_lstm(x, W, R, B, lens=lens)
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[2], Yc, rtol=RTOL, atol=ATOL)
        # rows past each length are exactly zero
        assert np.all(got[0][3:, 0, 1] == 0)
        assert np.all(got[0][1:, 0, 2] == 0)

    def test_lstm_ragged_bidirectional(self):
        """Reverse direction must run over each element's OWN prefix
        reversed (reverse_sequence semantics), not the padded tail."""
        rs = np.random.RandomState(9)
        T, N, I, H = 5, 3, 3, 4
        W = _mk(rs, 2, 4 * H, I)
        R = _mk(rs, 2, 4 * H, H)
        B = _mk(rs, 2, 8 * H)
        lens = np.array([5, 2, 4])
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("LSTM", T, N, I, H, 2, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_str("direction", "bidirectional")],
                             lens=lens, n_out=3)
        _, got = _run(m, x)
        Y, Yh, Yc = ref_lstm(x, W, R, B, lens=lens,
                             direction="bidirectional")
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[2], Yc, rtol=RTOL, atol=ATOL)

    def test_gru_ragged_with_state(self):
        rs = np.random.RandomState(10)
        T, N, I, H = 5, 2, 3, 4
        W, R, B = _mk(rs, 1, 3 * H, I), _mk(rs, 1, 3 * H, H), \
            _mk(rs, 1, 6 * H)
        h0 = _mk(rs, 1, N, H)
        lens = np.array([4, 2])
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("GRU", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_int("linear_before_reset", 1)],
                             lens=lens, h0=h0)
        _, got = _run(m, x)
        Y, Yh = ref_gru(x, W, R, B, h0=h0, lens=lens,
                        linear_before_reset=1)
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)

    def test_rnn_relu_ragged_reverse(self):
        rs = np.random.RandomState(11)
        T, N, I, H = 5, 2, 3, 4
        W, R, B = _mk(rs, 1, H, I), _mk(rs, 1, H, H), _mk(rs, 1, 2 * H)
        lens = np.array([3, 5])
        x = _mk(rs, T, N, I)
        m = _build_rnn_model("RNN", T, N, I, H, 1, W, R, B,
                             [attr_int("hidden_size", H),
                              attr_str("direction", "reverse"),
                              attr_strs("activations", ["Relu"])],
                             lens=lens)
        _, got = _run(m, x)
        Y, Yh = ref_rnn(x, W, R, B, lens=lens, direction="reverse",
                        acts=[[("relu", None, None)]])
        np.testing.assert_allclose(got[0], Y, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got[1], Yh, rtol=RTOL, atol=ATOL)


class TestLoopScanOutputs:
    """ONNX Loop scan outputs via the dense-buffer pattern (the same
    dense-TA design the TF importer uses): per-iteration values stack
    into a [trips, *elem] buffer carried as loop state."""

    def _model(self, M=3):
        one = tensor("one", np.full((2,), 0.5, np.float32))
        body_nodes = [
            node("Identity", ["cond_in"], ["cond_out"]),
            node("Add", ["c_in", "one"], ["c_out"]),
            node("Mul", ["c_out", "c_out"], ["scan_val"]),
        ]
        body = graph(body_nodes, [one],
                     [value_info("iter", []), value_info("cond_in", []),
                      value_info("c_in", [2])],
                     [value_info("cond_out", []),
                      value_info("c_out", [2]),
                      value_info("scan_val", [2])])
        inits = [tensor("M", np.array(M, np.int64)),
                 tensor_any("cond0", np.array(True))]
        g = graph([node("Loop", ["M", "cond0", "x"],
                        ["final", "stacked"],
                        attrs=[attr_graph("body", body)])],
                  inits, [value_info("x", [2])],
                  [value_info("final", [2]),
                   value_info("stacked", [M, 2])])
        return model(g, opset=14)

    def test_forward_matches_numpy(self):
        x = np.array([1.0, -2.0], np.float32)
        sd = OnnxImport.importGraph(OnnxImport._as_model(self._model()))
        res = sd.output({"x": x}, ["final", "stacked"])
        c = x.astype(np.float64)
        rows = []
        for _ in range(3):
            c = c + 0.5
            rows.append(c * c)
        np.testing.assert_allclose(np.asarray(res["final"]), c,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(res["stacked"]),
                                   np.stack(rows), rtol=1e-6)

    def test_grad_flows_through_scan_output(self):
        import jax
        import jax.numpy as jnp

        sd = OnnxImport.importGraph(OnnxImport._as_model(self._model()))
        fn = sd._build_fn(("stacked",))
        arrays = dict(sd._arrays)
        x = np.array([1.0, -2.0], np.float32)

        def loss(xv):
            return jnp.sum(fn(arrays, {"x": xv})["stacked"])

        g = jax.grad(loss)(jnp.asarray(x))
        # d/dx sum_k (x + 0.5k)^2 = sum_k 2(x + 0.5k)
        exp = sum(2.0 * (x + 0.5 * k) for k in (1, 2, 3))
        np.testing.assert_allclose(np.asarray(g), exp, rtol=1e-5)

    def test_scan_output_on_dynamic_loop_is_loud(self):
        """Scan outputs without a derivable bound must fail with a
        clear message, not import garbage."""
        one = tensor("one", np.full((2,), 0.5, np.float32))
        body_nodes = [
            node("Identity", ["cond_in"], ["cond_out"]),
            node("Add", ["c_in", "one"], ["c_out"]),
            node("Mul", ["c_out", "c_out"], ["scan_val"]),
        ]
        body = graph(body_nodes, [one],
                     [value_info("iter", []), value_info("cond_in", []),
                      value_info("c_in", [2])],
                     [value_info("cond_out", []),
                      value_info("c_out", [2]),
                      value_info("scan_val", [2])])
        # M is a graph INPUT (runtime value), so no static bound
        g2 = graph([node("Loop", ["m", "cond0", "x"],
                         ["final", "stacked"],
                         attrs=[attr_graph("body", body)])],
                   [tensor_any("cond0", np.array(True))],
                   [value_info("x", [2]), value_info("m", [])],
                   [value_info("final", [2]),
                    value_info("stacked", [3, 2])])
        with pytest.raises(OnnxImportError,
                           match="statically bounded"):
            OnnxImport.importGraph(
                OnnxImport._as_model(model(g2, opset=14)))
