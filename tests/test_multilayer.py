"""MultiLayerNetwork end-to-end tests — the LeNet-5 MNIST slice from
SURVEY.md §7.3 (reference analog: MultiLayerTest, ConvolutionLayerTest,
gradient-check suites)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.learning import Adam, Nesterovs, NoOp, Sgd
from deeplearning4j_tpu.nn.conf import (
    BatchNormalization, ConvolutionLayer, DenseLayer, DropoutLayer,
    InputType, MultiLayerConfiguration, NeuralNetConfiguration, OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def make_blob_images(n=256, hw=28, seed=0):
    """Synthetic MNIST-stand-in: class = quadrant containing the bright
    blob (4 classes). No network egress in this environment, so MNIST
    itself can't be downloaded; the learning task is equivalent in
    structure (28x28x1 -> 4-way softmax)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 0.1, (n, hw, hw, 1)).astype(np.float32)
    labels = rng.integers(0, 4, n)
    half = hw // 2
    for i, c in enumerate(labels):
        r0 = 0 if c in (0, 1) else half
        c0 = 0 if c in (0, 2) else half
        x[i, r0 + 4:r0 + half - 4, c0 + 4:c0 + half - 4, 0] += 1.0
    y = np.eye(4, dtype=np.float32)[labels]
    return x, y


def lenet_conf(n_classes=4, updater=None):
    return (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(updater or Adam(learning_rate=1e-3))
            .weightInit("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5), stride=(1, 1),
                                    activation="relu", convolution_mode="Same"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=16, kernel_size=(5, 5),
                                    activation="relu", convolution_mode="Same"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="mcxent"))
            .setInputType(InputType.convolutional(28, 28, 1))
            .build())


class TestConfig:
    def test_shape_inference(self):
        conf = lenet_conf()
        # conv1 n_in from channels; dense n_in from flattened conv output
        assert conf.layers[0].n_in == 1
        assert conf.layers[2].n_in == 8
        assert conf.layers[4].n_in == 7 * 7 * 16
        assert conf.layers[5].n_in == 32
        assert conf.preprocessors.get(4) == "flatten"

    def test_json_roundtrip(self):
        conf = lenet_conf()
        j = conf.to_json()
        back = MultiLayerConfiguration.from_json(j)
        assert back == conf
        # and the rebuilt config trains identically (same init)
        m1 = MultiLayerNetwork(conf).init()
        m2 = MultiLayerNetwork(back).init()
        assert float(jnp.sum(m1.params_list[0]["W"])) == \
               float(jnp.sum(m2.params_list[0]["W"]))

    def test_global_defaults_inherited(self):
        conf = (NeuralNetConfiguration.builder().l2(1e-4).weightInit("relu")
                .list()
                .layer(DenseLayer(n_in=4, n_out=3, activation="relu"))
                .layer(OutputLayer(n_in=3, n_out=2, loss="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        assert conf.layers[0].l2 == 1e-4
        assert conf.layers[0].weight_init == "relu"


class TestMlpTraining:
    def _toy(self, n=512, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(n, 10)).astype(np.float32)
        w = rng.normal(size=(10, 3)).astype(np.float32)
        y_idx = (x @ w).argmax(-1)
        return x, np.eye(3, dtype=np.float32)[y_idx]

    def test_mlp_learns(self):
        x, y = self._toy()
        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Adam(learning_rate=0.01))
                .list()
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .setInputType(InputType.feedForward(10))
                .build())
        model = MultiLayerNetwork(conf).init()
        it = ArrayDataSetIterator(x, y, batch_size=64, shuffle=True)
        first = None
        model.fit(it, epochs=15)
        ev = model.evaluate(ArrayDataSetIterator(x, y, batch_size=128))
        assert ev.accuracy() > 0.9, ev.stats()
        assert model.score() < 0.5

    def test_score_decreases(self):
        x, y = self._toy(n=128)
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Sgd(learning_rate=0.1))
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .setInputType(InputType.feedForward(10))
                .build())
        model = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y)
        s0 = model.score(ds)
        model.fit(ds, epochs=30)
        assert model.score(ds) < s0 * 0.8

    def test_params_roundtrip(self):
        conf = lenet_conf()
        model = MultiLayerNetwork(conf).init()
        flat = model.params()
        assert flat.length() == model.numParams()
        model2 = MultiLayerNetwork(conf).init()
        model2.setParams(flat)
        x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)).astype(np.float32)
        np.testing.assert_allclose(model.output(x).toNumpy(),
                                   model2.output(x).toNumpy(), atol=1e-6)

    def test_frozen_layer_noop_updater(self):
        x, y = self._toy(n=64)
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Sgd(learning_rate=0.5))
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh", updater=NoOp()))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .setInputType(InputType.feedForward(10))
                .build())
        model = MultiLayerNetwork(conf).init()
        w0 = np.asarray(model.params_list[0]["W"])
        model.fit(DataSet(x, y), epochs=3)
        np.testing.assert_array_equal(w0, np.asarray(model.params_list[0]["W"]))
        # output layer DID move
        assert model.score() > 0

    def test_gradient_check_mlp(self):
        """Finite-difference gradient check through the full network
        (the reference's GradientCheckUtil mechanism, SURVEY.md §4)."""
        x, y = self._toy(n=8)
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Sgd(learning_rate=0.1))
                .list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .setInputType(InputType.feedForward(10))
                .build())
        model = MultiLayerNetwork(conf).init()
        grads, score = model.computeGradientAndScore(x, y)
        eps = 1e-3
        w = model.params_list[0]["W"]
        for idx in [(0, 0), (3, 2), (9, 4)]:
            model.params_list[0]["W"] = w.at[idx].add(eps)
            sp = model.score(DataSet(x, y))
            model.params_list[0]["W"] = w.at[idx].add(-eps)
            sm = model.score(DataSet(x, y))
            model.params_list[0]["W"] = w
            fd = (sp - sm) / (2 * eps)
            assert abs(fd - float(grads[0]["W"][idx])) < 1e-2


class TestLeNetEndToEnd:
    def test_lenet_trains_on_images(self):
        x, y = make_blob_images(n=256)
        conf = lenet_conf()
        model = MultiLayerNetwork(conf).init()
        it = ArrayDataSetIterator(x, y, batch_size=64, shuffle=True)
        model.fit(it, epochs=8)
        ev = model.evaluate(ArrayDataSetIterator(x, y, batch_size=128))
        assert ev.accuracy() > 0.9, ev.stats()

    def test_summary(self):
        model = MultiLayerNetwork(lenet_conf()).init()
        s = model.summary()
        assert "ConvolutionLayer" in s and "Total params" in s

    def test_output_shape(self):
        model = MultiLayerNetwork(lenet_conf()).init()
        out = model.output(np.zeros((3, 28, 28, 1), np.float32))
        assert out.shape() == (3, 4)
        np.testing.assert_allclose(out.sum(1).toNumpy(), np.ones(3), rtol=1e-5)

    def test_batchnorm_dropout_net(self):
        x, y = make_blob_images(n=128)
        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Adam(learning_rate=1e-3))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        convolution_mode="Same", activation="identity"))
                .layer(BatchNormalization(activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(DropoutLayer(rate=0.3))
                .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
                .setInputType(InputType.convolutional(28, 28, 1))
                .build())
        model = MultiLayerNetwork(conf).init()
        st0 = np.asarray(model.states_list[1]["mean"]).copy()
        model.fit(DataSet(x, y), epochs=2)
        # BN running stats must have moved (functional state threading)
        assert not np.allclose(st0, np.asarray(model.states_list[1]["mean"]))
        # inference deterministic despite dropout layer
        x0 = x[:4]
        o1 = model.output(x0).toNumpy()
        o2 = model.output(x0).toNumpy()
        np.testing.assert_array_equal(o1, o2)


class TestEvaluateROCApis:
    """evaluateROC / evaluateROCMultiClass (reference:
    MultiLayerNetwork#evaluateROC[MultiClass], ComputationGraph dito)."""

    def _binary(self, n=256):
        rng = np.random.RandomState(3)
        x = rng.randn(n, 6).astype(np.float32)
        y_idx = (x.sum(1) > 0).astype(int)
        return x, np.eye(2, dtype=np.float32)[y_idx]

    def test_mln_roc_auc(self):
        x, y = self._binary()
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(learning_rate=0.02))
                .list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .setInputType(InputType.feedForward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        model.fit(ArrayDataSetIterator(x, y, batch_size=64), epochs=10)
        roc = model.evaluateROC(ArrayDataSetIterator(x, y, batch_size=128))
        assert roc.calculateAUC() > 0.9
        mc = model.evaluateROCMultiClass(
            ArrayDataSetIterator(x, y, batch_size=128))
        assert mc.calculateAUC(1) > 0.9

    def test_graph_roc_auc(self):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration,
        )

        x, y = self._binary()
        b = (ComputationGraphConfiguration.graphBuilder()
             .seed(1).updater(Adam(learning_rate=0.02))
             .addInputs("in").setInputTypes(InputType.feedForward(6)))
        b.addLayer("d", DenseLayer(n_out=16, activation="relu"), "in")
        b.addLayer("out", OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"), "d")
        net = ComputationGraph(b.setOutputs("out").build()).init()
        net.fit(ArrayDataSetIterator(x, y, batch_size=64), epochs=10)
        roc = net.evaluateROC(ArrayDataSetIterator(x, y, batch_size=128))
        assert roc.calculateAUC() > 0.9
        mc = net.evaluateROCMultiClass(
            ArrayDataSetIterator(x, y, batch_size=128))
        assert mc.calculateAUC(0) > 0.9
